"""Unit coverage for the resilience subsystem (docs/resilience.md):
Deadline, RetryPolicy/retryable, CircuitBreaker, AdmissionController, and
the typed sandbox-error taxonomy. Time-dependent pieces run on ManualClock;
anything that really sleeps uses sub-100ms budgets."""

import asyncio
import time

import pytest

from bee_code_interpreter_tpu.resilience import (
    AdmissionController,
    AdmissionRejected,
    BreakerOpenError,
    BreakerState,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    ResilientCodeExecutor,
    RetryPolicy,
    SandboxError,
    SandboxFatalError,
    SandboxTransientError,
    classify_http_status,
    retryable,
)
from bee_code_interpreter_tpu.services.code_executor import Result
from bee_code_interpreter_tpu.utils.metrics import Registry
from tests.chaos import ManualClock


# ----------------------------------------------------------------- deadline


def test_deadline_remaining_shrinks_with_clock():
    clock = ManualClock()
    d = Deadline.after(10.0, clock=clock)
    assert d.remaining() == pytest.approx(10.0)
    clock.advance(4.0)
    assert d.remaining() == pytest.approx(6.0)
    assert not d.expired
    clock.advance(7.0)
    assert d.remaining() == 0.0  # clamped, never negative
    assert d.expired
    with pytest.raises(DeadlineExceeded):
        d.check("unit test")


def test_deadline_clamp_caps_operation_timeouts():
    clock = ManualClock()
    d = Deadline.after(5.0, clock=clock)
    assert d.clamp(60.0) == pytest.approx(5.0)  # op budget > deadline
    assert d.clamp(2.0) == pytest.approx(2.0)  # op budget < deadline
    assert d.clamp(None) == pytest.approx(5.0)  # no op budget: the deadline


async def test_deadline_run_bounds_and_cancels():
    d = Deadline.after(0.05)
    cancelled = asyncio.Event()

    async def hang():
        try:
            await asyncio.sleep(10)
        except asyncio.CancelledError:
            cancelled.set()
            raise

    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        await d.run(hang(), what="hang")
    assert time.monotonic() - t0 < 1.0
    assert cancelled.is_set()  # the hung work was cancelled, not leaked


async def test_deadline_run_passes_result_and_errors_through():
    async def ok():
        return 42

    async def boom():
        raise ValueError("boom")

    d = Deadline.after(5.0)
    assert await d.run(ok()) == 42
    with pytest.raises(ValueError):
        await d.run(boom())


# ------------------------------------------------------------------- errors


def test_error_taxonomy():
    # RuntimeError subclassing keeps legacy `except RuntimeError` sites alive.
    assert issubclass(SandboxTransientError, RuntimeError)
    assert issubclass(SandboxFatalError, RuntimeError)
    assert issubclass(SandboxTransientError, SandboxError)
    assert isinstance(classify_http_status(503, "x"), SandboxTransientError)
    assert isinstance(classify_http_status(500, "x"), SandboxTransientError)
    assert isinstance(classify_http_status(404, "x"), SandboxFatalError)
    assert isinstance(classify_http_status(400, "x"), SandboxFatalError)
    # DeadlineExceeded / BreakerOpenError are NOT RuntimeErrors: retry
    # policies keyed on RuntimeError must never re-attempt them.
    assert not issubclass(DeadlineExceeded, RuntimeError)
    assert not issubclass(BreakerOpenError, RuntimeError)


# -------------------------------------------------------------------- retry


class _Flaky:
    """Host object for the retryable decorator."""

    def __init__(self, failures, policy):
        self._failures = failures
        self._policy = policy
        self.calls = 0
        self.backoffs = []

    def _on_retry_backoff(self, op, attempt, sleep_s, exc):
        self.backoffs.append((op, attempt, sleep_s))

    @retryable("_policy", op="unit")
    async def work(self, deadline=None):
        self.calls += 1
        if self.calls <= self._failures:
            raise SandboxTransientError(f"flake #{self.calls}")
        return "done"


async def test_retry_succeeds_after_transient_failures_with_schedule():
    policy = RetryPolicy(
        attempts=3, wait_min_s=0.01, wait_max_s=0.04, retry_on=(SandboxTransientError,)
    )
    flaky = _Flaky(failures=2, policy=policy)
    assert await flaky.work() == "done"
    assert flaky.calls == 3
    # exponential: wait_min * 2**(attempt-1), capped at wait_max
    assert [s for _, _, s in flaky.backoffs] == [pytest.approx(0.01), pytest.approx(0.02)]


async def test_retry_exhausts_attempts_and_reraises():
    policy = RetryPolicy(
        attempts=2, wait_min_s=0.01, wait_max_s=0.01, retry_on=(SandboxTransientError,)
    )
    flaky = _Flaky(failures=10, policy=policy)
    with pytest.raises(SandboxTransientError):
        await flaky.work()
    assert flaky.calls == 2


async def test_retry_does_not_retry_non_matching_errors():
    policy = RetryPolicy(
        attempts=3, wait_min_s=0.01, wait_max_s=0.01, retry_on=(SandboxTransientError,)
    )

    class Fatal(_Flaky):
        @retryable("_policy", op="unit")
        async def work(self, deadline=None):
            self.calls += 1
            raise SandboxFatalError("HTTP 400")

    fatal = Fatal(failures=0, policy=policy)
    with pytest.raises(SandboxFatalError):
        await fatal.work()
    assert fatal.calls == 1


async def test_retry_stops_when_deadline_cannot_cover_backoff():
    policy = RetryPolicy(
        attempts=5, wait_min_s=1.0, wait_max_s=1.0, retry_on=(SandboxTransientError,)
    )
    flaky = _Flaky(failures=10, policy=policy)
    t0 = time.monotonic()
    with pytest.raises(SandboxTransientError):
        await flaky.work(deadline=Deadline.after(0.05))
    # no budget for the 1s backoff: re-raised immediately, single attempt
    assert flaky.calls == 1
    assert time.monotonic() - t0 < 0.5


def test_retry_preserves_wrapped_for_bypass():
    assert _Flaky.work.__wrapped__.__name__ == "work"


# ------------------------------------------------------------------ breaker


def _breaker(clock, **kwargs):
    defaults = dict(
        window=4, failure_rate_threshold=0.5, min_calls=2, cooldown_s=30.0,
        half_open_max_calls=1, clock=clock,
    )
    defaults.update(kwargs)
    return CircuitBreaker("unit", **defaults)


def test_breaker_full_lifecycle():
    clock = ManualClock()
    transitions = []
    b = _breaker(clock, on_transition=lambda name, s: transitions.append(s))
    assert b.state is BreakerState.CLOSED

    # One failure of one call: below min_calls, stays closed.
    b.before_call(); b.record_failure()
    assert b.state is BreakerState.CLOSED

    # Second failure: rate 2/2 >= 0.5 with min_calls=2 -> OPEN.
    b.before_call(); b.record_failure()
    assert b.state is BreakerState.OPEN
    with pytest.raises(BreakerOpenError) as exc:
        b.before_call()
    assert exc.value.retry_after_s == pytest.approx(30.0)

    # Cooldown elapses: HALF_OPEN, one probe allowed.
    clock.advance(31.0)
    assert b.state is BreakerState.HALF_OPEN
    b.before_call()  # the probe slot
    with pytest.raises(BreakerOpenError):
        b.before_call()  # second concurrent probe rejected
    b.record_success()
    assert b.state is BreakerState.CLOSED
    assert transitions == [
        BreakerState.OPEN, BreakerState.HALF_OPEN, BreakerState.CLOSED,
    ]


def test_breaker_half_open_failure_reopens():
    clock = ManualClock()
    b = _breaker(clock)
    b.before_call(); b.record_failure()
    b.before_call(); b.record_failure()
    clock.advance(31.0)
    b.before_call()  # half-open probe
    b.record_failure()
    assert b.state is BreakerState.OPEN
    with pytest.raises(BreakerOpenError):
        b.before_call()
    # and the cooldown restarted from the probe failure
    clock.advance(29.0)
    with pytest.raises(BreakerOpenError):
        b.before_call()
    clock.advance(2.0)
    b.before_call()  # half-open again


def test_breaker_successes_keep_it_closed():
    clock = ManualClock()
    b = _breaker(clock)
    for _ in range(10):
        b.before_call(); b.record_success()
    b.before_call(); b.record_failure()  # 1 failure in window of 4: 0.25 < 0.5
    assert b.state is BreakerState.CLOSED


async def test_breaker_guard_classifies_with_is_failure():
    clock = ManualClock()
    b = _breaker(
        clock, is_failure=lambda e: not isinstance(e, SandboxFatalError)
    )
    # 4xx answers are breaker-successes: the backend is responsive.
    for _ in range(5):
        with pytest.raises(SandboxFatalError):
            async with b.guard():
                raise SandboxFatalError("HTTP 400")
    assert b.state is BreakerState.CLOSED
    # transient failures trip it
    for _ in range(2):
        with pytest.raises(SandboxTransientError):
            async with b.guard():
                raise SandboxTransientError("HTTP 503")
    assert b.state is BreakerState.OPEN


async def test_breaker_guard_deadline_exceeded_is_neutral():
    # A blown *request* deadline is the client's budget running out, not a
    # backend verdict: impatient clients must not trip the breaker.
    clock = ManualClock()
    b = _breaker(clock)
    for _ in range(5):
        with pytest.raises(DeadlineExceeded):
            async with b.guard():
                raise DeadlineExceeded("pod group spawn")
    assert b.state is BreakerState.CLOSED


async def test_breaker_guard_cancellation_is_neutral():
    # A client disconnect (CancelledError) says nothing about backend health:
    # no failure recorded, and a half-open probe slot is released.
    clock = ManualClock()
    b = _breaker(clock)
    for _ in range(3):
        with pytest.raises(asyncio.CancelledError):
            async with b.guard():
                raise asyncio.CancelledError()
    assert b.state is BreakerState.CLOSED
    # even paired with real outcomes, the cancels never entered the window:
    # [T, T, F] is 1/3 < 0.5 -> still closed
    b.before_call(); b.record_success()
    b.before_call(); b.record_success()
    b.before_call(); b.record_failure()
    assert b.state is BreakerState.CLOSED

    # half-open: a cancelled probe frees the slot for the next probe
    b.before_call(); b.record_failure()  # [T,T,F,F] -> 2/4 >= 0.5: OPEN
    assert b.state is BreakerState.OPEN
    clock.advance(31.0)
    with pytest.raises(asyncio.CancelledError):
        async with b.guard():
            raise asyncio.CancelledError()
    b.before_call()  # slot available again, not BreakerOpenError
    b.record_success()
    assert b.state is BreakerState.CLOSED


# ---------------------------------------------------------------- admission


async def test_admission_fast_path_and_release():
    a = AdmissionController(max_in_flight=2, max_queue=0)
    async with a.admit():
        assert a.in_flight == 1
        async with a.admit():
            assert a.in_flight == 2
    assert a.in_flight == 0


async def test_admission_sheds_when_queue_full():
    a = AdmissionController(max_in_flight=1, max_queue=0, retry_after_s=7.0)
    async with a.admit():
        with pytest.raises(AdmissionRejected) as exc:
            async with a.admit():
                pass
    assert exc.value.reason == "queue_full"
    assert exc.value.retry_after_s == pytest.approx(7.0)


async def test_admission_queues_then_grants_fifo():
    a = AdmissionController(max_in_flight=1, max_queue=4)
    order = []

    release = asyncio.Event()

    async def holder():
        async with a.admit():
            order.append("holder")
            await release.wait()

    async def waiter(tag):
        async with a.admit():
            order.append(tag)

    h = asyncio.create_task(holder())
    await asyncio.sleep(0.01)
    w1 = asyncio.create_task(waiter("w1"))
    w2 = asyncio.create_task(waiter("w2"))
    await asyncio.sleep(0.01)
    assert a.queue_depth == 2
    release.set()
    await asyncio.gather(h, w1, w2)
    assert order == ["holder", "w1", "w2"]  # FIFO handoff
    assert a.in_flight == 0 and a.queue_depth == 0


async def test_admission_waiter_sheds_at_deadline_never_hangs():
    a = AdmissionController(max_in_flight=1, max_queue=4)
    release = asyncio.Event()

    async def holder():
        async with a.admit():
            await release.wait()

    h = asyncio.create_task(holder())
    await asyncio.sleep(0.01)
    t0 = time.monotonic()
    with pytest.raises(AdmissionRejected) as exc:
        async with a.admit(Deadline.after(0.05)):
            pass
    assert exc.value.reason == "queue_timeout"
    assert time.monotonic() - t0 < 1.0
    release.set()
    await h
    assert a.in_flight == 0 and a.queue_depth == 0


async def test_admission_cancelled_waiter_frees_its_queue_slot():
    # A queued client that disconnects must not keep consuming a queue slot
    # (it would shed healthy traffic as queue_full long after it left).
    a = AdmissionController(max_in_flight=1, max_queue=1)
    release = asyncio.Event()

    async def holder():
        async with a.admit():
            await release.wait()

    h = asyncio.create_task(holder())
    await asyncio.sleep(0.01)

    async def waiter():
        async with a.admit():
            pass

    w = asyncio.create_task(waiter())
    await asyncio.sleep(0.01)
    assert a.queue_depth == 1
    w.cancel()
    with pytest.raises(asyncio.CancelledError):
        await w
    assert a.queue_depth == 0  # the dead future was withdrawn

    # the freed slot is usable: a new waiter queues instead of being shed
    w2 = asyncio.create_task(waiter())
    await asyncio.sleep(0.01)
    assert a.queue_depth == 1
    release.set()
    await asyncio.gather(h, w2)
    assert a.in_flight == 0 and a.queue_depth == 0


async def test_admission_never_exceeds_max_in_flight_under_burst():
    a = AdmissionController(max_in_flight=3, max_queue=64)
    peak = 0
    active = 0

    async def job():
        nonlocal peak, active
        async with a.admit(Deadline.after(5.0)):
            active += 1
            peak = max(peak, active)
            await asyncio.sleep(0.001)
            active -= 1

    await asyncio.gather(*(job() for _ in range(20)))
    assert peak <= 3
    assert a.in_flight == 0 and a.queue_depth == 0


async def test_admission_metrics_exported():
    reg = Registry()
    a = AdmissionController(max_in_flight=1, max_queue=0, metrics=reg)
    async with a.admit():
        with pytest.raises(AdmissionRejected):
            async with a.admit():
                pass
        text = reg.expose()
        assert 'bci_admission_shed_total{reason="queue_full"} 1' in text
        assert "bci_admission_in_flight 1" in text
    assert "bci_admission_in_flight 0" in reg.expose()


# -------------------------------------------------- resilient executor unit


class _StubExecutor:
    def __init__(self, error=None):
        self.error = error
        self.calls = 0

    async def execute(self, source_code, files=None, env=None, timeout_s=None,
                      deadline=None):
        self.calls += 1
        if self.error is not None:
            raise self.error
        return Result(stdout="stub\n", stderr="", exit_code=0, files={})


async def test_resilient_executor_falls_back_on_open_breaker():
    reg = Registry()
    primary = _StubExecutor(error=BreakerOpenError("k8s-spawn", 30.0))
    fallback = _StubExecutor()
    r = ResilientCodeExecutor(primary, fallback=fallback, metrics=reg)
    result = await r.execute("print(1)")
    assert result.stdout == "stub\n"
    assert primary.calls == 1 and fallback.calls == 1
    assert "bci_executor_fallback_total 1" in reg.expose()


async def test_resilient_executor_no_fallback_for_data_plane_breaker():
    # The http breaker can open mid-request, AFTER user code already ran on
    # the pod — falling back would execute side-effectful code twice.
    primary = _StubExecutor(error=BreakerOpenError("k8s-http", 30.0))
    fallback = _StubExecutor()
    r = ResilientCodeExecutor(primary, fallback=fallback)
    with pytest.raises(BreakerOpenError):
        await r.execute("print(1)")
    assert fallback.calls == 0


async def test_resilient_executor_reraises_without_fallback():
    primary = _StubExecutor(error=BreakerOpenError("k8s-spawn", 30.0))
    r = ResilientCodeExecutor(primary)
    with pytest.raises(BreakerOpenError):
        await r.execute("print(1)")


async def test_resilient_executor_enforces_deadline_hard_bound():
    class Slow:
        async def execute(self, source_code, files=None, env=None,
                          timeout_s=None, deadline=None):
            await asyncio.sleep(10)

    r = ResilientCodeExecutor(Slow())
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        await r.execute("print(1)", deadline=Deadline.after(0.05))
    assert time.monotonic() - t0 < 1.0
