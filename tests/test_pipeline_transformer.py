"""Pipeline-parallel transformer forward/training vs the standard forward."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.parallel import make_mesh


def f32_tiny():
    return dataclasses.replace(T.TransformerConfig.tiny(), dtype=jnp.float32)


def test_pipelined_forward_matches_standard():
    config = f32_tiny()  # n_layers=2 -> pp=2
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    params = T.init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, config.vocab_size)

    want = T.forward(params, tokens, config)  # mesh=None single-shard path
    got = T.forward_pipelined(params, tokens, config, mesh, n_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-4)


def test_pipelined_forward_composes_with_dp():
    config = dataclasses.replace(f32_tiny(), n_layers=4)
    mesh = make_mesh({"dp": 2, "pp": 4}, devices=jax.devices()[:8])
    params = T.init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, config.vocab_size)

    want = T.forward(params, tokens, config)
    got = T.forward_pipelined(params, tokens, config, mesh, n_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-4)


def test_pipelined_training_decreases_loss():
    # Full pipeline-parallel training: grad through the GPipe schedule, AdamW
    # update, loss decreases — the dp x pp counterpart of the dp x ep x tp
    # MoE training test.
    import optax

    config = f32_tiny()
    mesh = make_mesh({"dp": 2, "pp": 2}, devices=jax.devices()[:4])
    params = T.init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, config.vocab_size)
    batch = {"tokens": tokens[:, :-1], "targets": tokens[:, 1:]}

    def loss_fn(params):
        logits = T.forward_pipelined(
            params, batch["tokens"], config, mesh, n_microbatches=2
        )
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        target = jnp.take_along_axis(
            logits, batch["targets"][..., None], axis=-1
        )[..., 0]
        return (logz - target).mean()

    optimizer = optax.adamw(1e-2)
    opt_state = optimizer.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return jax.tree.map(lambda p, u: p + u, params, updates), opt_state, loss

    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def ample_moe():
    # drop-free capacity: every token keeps both top-2 routes, so routing is
    # identical whether tokens compete within a microbatch or the full batch
    return dataclasses.replace(
        T.TransformerConfig.tiny_moe(), dtype=jnp.float32,
        moe_capacity_factor=8.0,
    )


def test_pipelined_moe_matches_microbatched_oracle():
    # The MoE aux loss rides the pipeline carry (masked to non-bubble ticks,
    # averaged over microbatches). Oracle: the standard forward applied to
    # each microbatch separately — identical routing pools by construction.
    config = ample_moe()
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    params = T.init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, config.vocab_size)

    got, aux = T.forward_pipelined(
        params, tokens, config, mesh, n_microbatches=2, return_aux=True
    )
    mb_logits, mb_aux = [], []
    for mb in jnp.split(tokens, 2, axis=0):
        lg, ax = T.forward(params, mb, config, return_aux=True)
        mb_logits.append(lg)
        mb_aux.append(ax)
    want = jnp.concatenate(mb_logits, axis=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(
        float(aux), float(np.mean([float(a) for a in mb_aux])), rtol=1e-5
    )
    assert float(aux) > 0.0  # a dropped aux loss would read exactly 0


def test_pipelined_moe_drop_free_matches_full_forward():
    # With ample capacity the pipelined logits equal the full-batch forward
    # too (routing is per-token when nothing is dropped).
    config = ample_moe()
    mesh = make_mesh({"dp": 2, "pp": 2}, devices=jax.devices()[:4])
    params = T.init_params(config, jax.random.PRNGKey(2))
    tokens = jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0, config.vocab_size)

    want = T.forward(params, tokens, config)
    got, _ = T.forward_pipelined(
        params, tokens, config, mesh, n_microbatches=2, return_aux=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-4)


def test_pipelined_moe_requires_return_aux():
    # Silently dropping the load-balancing loss would train experts toward
    # collapse — the path fails loudly instead (review r3).
    import pytest

    config = ample_moe()
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    params = T.init_params(config, jax.random.PRNGKey(0))
    tokens = jnp.zeros((4, 16), dtype=jnp.int32)
    with pytest.raises(ValueError, match="return_aux=True"):
        T.forward_pipelined(params, tokens, config, mesh, n_microbatches=2)


def test_pipelined_moe_training_decreases_loss():
    # Pipeline-parallel MoE training with the aux loss in the objective:
    # grads flow through the pipeline carry and the routing einsums.
    import optax

    config = ample_moe()
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    params = T.init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0, config.vocab_size)
    batch = {"tokens": tokens[:, :-1], "targets": tokens[:, 1:]}

    def loss_fn(params):
        logits, aux = T.forward_pipelined(
            params, batch["tokens"], config, mesh, n_microbatches=2,
            return_aux=True,
        )
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        target = jnp.take_along_axis(
            logits, batch["targets"][..., None], axis=-1
        )[..., 0]
        return (logz - target).mean() + config.moe_aux_weight * aux

    optimizer = optax.adamw(1e-2)
    opt_state = optimizer.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return jax.tree.map(lambda p, u: p + u, params, updates), opt_state, loss

    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
