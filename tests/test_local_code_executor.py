from bee_code_interpreter_tpu.services.local_code_executor import LocalCodeExecutor


async def test_execute_basic(local_executor: LocalCodeExecutor):
    result = await local_executor.execute("print(21 * 2)")
    # health-check contract (reference health_check.py:25-53)
    assert result.stdout == "42\n"
    assert result.exit_code == 0


async def test_file_roundtrip_across_executions(local_executor: LocalCodeExecutor):
    # The session-continuity mechanism: file map out of one execution feeds the
    # next (reference test_http.py:47-85; SURVEY.md §5 checkpoint/resume).
    r1 = await local_executor.execute("open('data.txt', 'w').write('persisted state')")
    assert set(r1.files) == {"/workspace/data.txt"}
    r2 = await local_executor.execute(
        "print(open('data.txt').read())", files=r1.files
    )
    assert r2.stdout == "persisted state\n"
    assert r2.exit_code == 0
    # unchanged restored file is not re-reported
    assert r2.files == {}


async def test_workspace_isolated_between_executions(local_executor: LocalCodeExecutor):
    await local_executor.execute("open('leak.txt', 'w').write('x')")
    r = await local_executor.execute("import os; print(os.path.exists('leak.txt'))")
    assert r.stdout == "False\n"


async def test_env_forwarded(local_executor: LocalCodeExecutor):
    r = await local_executor.execute(
        "import os; print(os.environ['FOO'])", env={"FOO": "bar"}
    )
    assert r.stdout == "bar\n"


async def test_binary_file_roundtrip(local_executor: LocalCodeExecutor):
    r1 = await local_executor.execute(
        "open('blob.bin','wb').write(bytes(range(256)))"
    )
    r2 = await local_executor.execute(
        "data = open('blob.bin','rb').read()\nprint(len(data), data[:4].hex())",
        files=r1.files,
    )
    assert r2.stdout == "256 00010203\n"
