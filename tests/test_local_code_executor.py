from bee_code_interpreter_tpu.services.local_code_executor import LocalCodeExecutor


async def test_execute_basic(local_executor: LocalCodeExecutor):
    result = await local_executor.execute("print(21 * 2)")
    # health-check contract (reference health_check.py:25-53)
    assert result.stdout == "42\n"
    assert result.exit_code == 0


async def test_file_roundtrip_across_executions(local_executor: LocalCodeExecutor):
    # The session-continuity mechanism: file map out of one execution feeds the
    # next (reference test_http.py:47-85; SURVEY.md §5 checkpoint/resume).
    r1 = await local_executor.execute("open('data.txt', 'w').write('persisted state')")
    assert set(r1.files) == {"/workspace/data.txt"}
    r2 = await local_executor.execute(
        "print(open('data.txt').read())", files=r1.files
    )
    assert r2.stdout == "persisted state\n"
    assert r2.exit_code == 0
    # unchanged restored file is not re-reported
    assert r2.files == {}


async def test_workspace_isolated_between_executions(local_executor: LocalCodeExecutor):
    await local_executor.execute("open('leak.txt', 'w').write('x')")
    r = await local_executor.execute("import os; print(os.path.exists('leak.txt'))")
    assert r.stdout == "False\n"


async def test_env_forwarded(local_executor: LocalCodeExecutor):
    r = await local_executor.execute(
        "import os; print(os.environ['FOO'])", env={"FOO": "bar"}
    )
    assert r.stdout == "bar\n"


async def test_binary_file_roundtrip(local_executor: LocalCodeExecutor):
    r1 = await local_executor.execute(
        "open('blob.bin','wb').write(bytes(range(256)))"
    )
    r2 = await local_executor.execute(
        "data = open('blob.bin','rb').read()\nprint(len(data), data[:4].hex())",
        files=r1.files,
    )
    assert r2.stdout == "256 00010203\n"


async def test_mnist_dp_8chip_example_end_to_end(storage, tmp_path):
    # BASELINE.md north-star #2: the 8-chip data-parallel MNIST training job
    # submitted through the execution path completes end-to-end. Runs the
    # actual example payload on 8 virtual CPU devices (SURVEY.md §4's
    # simulated multi-chip strategy); on a real pod the same payload lands on
    # the slice's physical chips. Uses the runtime shim (as the executor image
    # does) so the sandbox can import the bundled model library.
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    source = (repo / "examples" / "mnist-dp-8chip.py").read_text()
    executor = LocalCodeExecutor(
        storage=storage,
        workspace_root=tmp_path / "workspaces",
        disable_dep_install=True,
        execution_timeout_s=120.0,
        shim_dir=repo / "bee_code_interpreter_tpu" / "runtime" / "shim",
    )
    r = await executor.execute(
        source,
        env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        },
    )
    assert r.exit_code == 0, r.stderr
    assert "trained data-parallel over 8 device(s)" in r.stdout
    # loss decreased over the 20 steps
    losses = [
        float(line.rsplit(" ", 1)[1])
        for line in r.stdout.splitlines()
        if line.startswith("step ")
    ]
    assert losses[-1] < losses[0], r.stdout


async def test_per_request_timeout(local_executor: LocalCodeExecutor):
    # A request may shorten the deadline below the service default...
    r = await local_executor.execute(
        "import time\ntime.sleep(30)", timeout_s=0.5
    )
    assert r.exit_code == -1
    assert r.stderr == "Execution timed out"


async def test_per_request_timeout_clamped_to_service_bound(storage, tmp_path):
    # ...but can never extend past it.
    executor = LocalCodeExecutor(
        storage=storage,
        workspace_root=tmp_path / "workspaces",
        disable_dep_install=True,
        execution_timeout_s=0.5,
    )
    r = await executor.execute("import time\ntime.sleep(30)", timeout_s=9999)
    assert r.exit_code == -1
    assert r.stderr == "Execution timed out"
