"""Slow capacity sweep (ISSUE 18, docs/capacity.md): the full knee search
against a REAL two-replica fleet behind the real router — the same probe
``bench.py capacity`` publishes, held as a test so the harness's verdicts
stay anchored to the production edge, not just the stub service the
seconds-scale tier-1 smoke uses (tests/test_loadgen.py).

Marked ``slow``: a bisection is minutes of wall-clock probes by design.
"""

import httpx
import pytest
from aiohttp import web

from bee_code_interpreter_tpu.fleet import FleetRouter, create_router_app
from bee_code_interpreter_tpu.loadgen import (
    CapacityReporter,
    OpenLoopGenerator,
    TrafficMix,
    find_knee,
)
from tests.fakes import ReplicaStack, free_port

pytestmark = pytest.mark.slow


async def test_knee_search_against_a_real_fleet(tmp_path):
    shared_root = tmp_path / "shared-objects"
    stacks = [
        await ReplicaStack(
            f"r{i}", tmp_path, shared_root, autoscale_window_s=10.0
        ).start()
        for i in range(2)
    ]
    router = FleetRouter(
        [(s.name, s.base_url) for s in stacks],
        refresh_interval_s=0.5,
        dead_after_s=3.0,
    )
    runner = web.AppRunner(create_router_app(router))
    await runner.setup()
    port = free_port()
    await web.TCPSite(runner, "127.0.0.1", port).start()
    await router.refresh_once()
    router.start()
    url = f"http://127.0.0.1:{port}"
    client = httpx.AsyncClient(timeout=30.0)
    try:
        response = await client.post(f"{url}/v1/sessions", json={})
        assert response.status_code == 200, response.text
        session_id = response.json()["session_id"]
        generator = OpenLoopGenerator(
            client,
            url,
            mix=TrafficMix(
                kinds=(("execute", 8.0), ("session", 1.0), ("stream", 1.0))
            ),
            session_ids=[session_id],
        )
        reporter = CapacityReporter(client, url, router=router)
        knee, probes = await find_knee(
            generator,
            lo_rps=1.0,
            hi_rps=40.0,
            duration_s=3.0,
            p99_ms=2000.0,
            reporter=reporter,
            iterations=5,
            settle_s=0.5,
            drain_timeout_s=20.0,
        )
        # The fleet sustains SOMETHING and saturates somewhere: a real
        # knee, bracketed — and every probe carries the federated plane's
        # account of itself.
        assert knee >= 1.0, probes
        assert len(probes) >= 2
        assert any(not p["sustained"] for p in probes) or knee == 40.0
        for probe in probes:
            assert probe["recommendation"] is not None, probe
            assert probe["recommendation"]["target_replicas"] >= 1
        # The p99-vs-load curve bends the right way: the fastest sustained
        # probe is no slower than the slowest unsustained one.
        sustained = [
            p["result"]["latency_ms"]["p99"] for p in probes if p["sustained"]
        ]
        unsustained = [
            p["result"]["latency_ms"]["p99"]
            for p in probes
            if not p["sustained"]
        ]
        if sustained and unsustained:
            assert min(sustained) <= max(unsustained)
        # The router-stage breakdown exists for the same traffic the knee
        # was measured on.
        assert reporter.stage_p50_ms(), "router traces empty after a sweep"
    finally:
        await client.aclose()
        await runner.cleanup()
        await router.stop()
        for stack in stacks:
            await stack.stop()
