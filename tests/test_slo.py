"""SLO engine (observability/slo.py): objective parsing, sliding-window math
under a manual clock (every number hand-computed), multi-window burn-rate
alerting, and the /v1/slo + debug-bundle surfaces on both transports."""

import pytest

from bee_code_interpreter_tpu.observability import (
    SloEngine,
    parse_objectives,
)
from bee_code_interpreter_tpu.observability.slo import WINDOWS
from bee_code_interpreter_tpu.utils.metrics import Registry
from tests.chaos import ManualClock

# ------------------------------------------------------------- parsing


def test_parse_objectives_availability_and_latency():
    objectives = parse_objectives(99.5, "2000:99")
    assert [(o.name, o.kind) for o in objectives] == [
        ("availability", "availability"),
        ("latency_2000ms", "latency"),
    ]
    assert objectives[0].target == pytest.approx(0.995)
    assert objectives[0].error_budget == pytest.approx(0.005)
    assert objectives[1].target == pytest.approx(0.99)
    assert objectives[1].threshold_ms == 2000.0


def test_parse_objectives_latency_list_and_empty():
    objectives = parse_objectives(None, "500:95, 2000:99")
    assert [o.name for o in objectives] == ["latency_500ms", "latency_2000ms"]
    assert parse_objectives(None, None) == []
    assert parse_objectives(None, "") == []


@pytest.mark.parametrize(
    "availability,latency",
    [
        (0, None),
        (100, None),
        (101.5, None),
        (None, "banana"),
        (None, "2000"),
        (None, "2000:"),
        (None, ":99"),
        (None, "2000:101"),
        (None, "-5:99"),
    ],
)
def test_parse_objectives_rejects_malformed(availability, latency):
    with pytest.raises(ValueError):
        parse_objectives(availability, latency)


# ------------------------------------------------------- window math


def availability_engine(clock, target_percent=99.0, **kwargs):
    return SloEngine(
        parse_objectives(target_percent, None), clock=clock, **kwargs
    )


def test_availability_burn_rate_hand_computed():
    clock = ManualClock(start=5.0)
    engine = availability_engine(clock)  # budget = 0.01
    (objective,) = engine.objectives
    for i in range(100):
        engine.record(ok=i >= 2, duration_s=0.01)  # 2 bad of 100

    # bad_ratio = 2/100 = 0.02; burn = 0.02 / 0.01 = 2.0, in EVERY window
    for window in WINDOWS:
        assert engine.burn_rate(objective, window) == pytest.approx(2.0)
    # budget remaining over 6h: 1 - 0.02/0.01 = -1 (overspent reads negative)
    assert engine.error_budget_remaining(objective) == pytest.approx(-1.0)

    snap = engine.snapshot()
    (obj,) = snap["objectives"]
    assert obj["windows"]["5m"] == {
        "total": 100,
        "bad": 2,
        "bad_ratio": pytest.approx(0.02),
        "burn_rate": pytest.approx(2.0),
    }


def test_sliding_window_forgets_old_buckets():
    clock = ManualClock(start=5.0)
    engine = availability_engine(clock)  # bucket_s=10: events land in idx 0
    (objective,) = engine.objectives
    for _ in range(10):
        engine.record(ok=False, duration_s=0.01)

    # bucket [0,10) stays in the 5m window until now - 300 >= 10
    clock.advance(300.0)  # now=305: still (barely) inside
    assert engine.burn_rate(objective, "5m") == pytest.approx(100.0)
    clock.advance(15.0)  # now=320: outside 5m, inside 1h
    assert engine.burn_rate(objective, "5m") == 0.0
    assert engine.burn_rate(objective, "1h") == pytest.approx(100.0)
    clock.advance(WINDOWS["6h"])  # beyond every window
    assert engine.burn_rate(objective, "6h") == 0.0
    assert engine.error_budget_remaining(objective) == pytest.approx(1.0)


def test_latency_objective_counts_successes_only():
    clock = ManualClock(start=5.0)
    engine = SloEngine(
        parse_objectives(None, "100:95"), clock=clock
    )  # budget = 0.05
    (objective,) = engine.objectives
    for i in range(20):
        engine.record(ok=True, duration_s=0.15 if i < 2 else 0.05)
    for _ in range(5):  # failures burn availability, never latency
        engine.record(ok=False, duration_s=9.9)

    snap = engine.snapshot()
    (obj,) = snap["objectives"]
    # 2 slow of 20 SUCCESSFUL: ratio 0.1, burn 0.1/0.05 = 2
    assert obj["windows"]["5m"] == {
        "total": 20,
        "bad": 2,
        "bad_ratio": pytest.approx(0.1),
        "burn_rate": pytest.approx(2.0),
    }


def test_fast_burn_alert_needs_both_windows_over_threshold():
    clock = ManualClock(start=5.0)
    engine = availability_engine(clock)  # budget 0.01; page pair needs 14.4x
    (objective,) = engine.objectives
    # 20% errors: burn = 0.2/0.01 = 20 >= 14.4 in both 5m and 1h
    for i in range(10):
        engine.record(ok=i >= 2, duration_s=0.01)
    snap = engine.snapshot()
    page, ticket = snap["objectives"][0]["alerts"]
    assert page["severity"] == "page" and page["firing"]
    assert page["windows"] == ["5m", "1h"]
    # ticket pair: burn 20 >= 6 in 30m and 6h too
    assert ticket["severity"] == "ticket" and ticket["firing"]
    assert snap["alerting"] and snap["fast_burn_alerting"]

    # the 5m window slides clear; burn in 1h persists -> page must STOP
    # (that asymmetry is the whole point of the short window)
    clock.advance(320.0)
    for _ in range(100):
        engine.record(ok=True, duration_s=0.01)
    snap = engine.snapshot()
    page, ticket = snap["objectives"][0]["alerts"]
    assert page["short_burn_rate"] == 0.0
    assert page["long_burn_rate"] == pytest.approx(2 / 110 / 0.01)
    assert not page["firing"]
    assert not snap["fast_burn_alerting"]


def test_engine_without_objectives_is_inert():
    registry = Registry()
    engine = SloEngine([], metrics=registry)
    engine.record(ok=False, duration_s=1.0)
    assert engine.snapshot() == {
        "objectives": [],
        "alerting": False,
        "fast_burn_alerting": False,
    }
    assert "bci_slo_burn_rate" not in registry.metrics


def test_slo_gauges_reflect_engine_state():
    registry = Registry()
    clock = ManualClock(start=5.0)
    engine = SloEngine(
        parse_objectives(99.0, "100:95"), metrics=registry, clock=clock
    )
    for i in range(100):
        engine.record(ok=i >= 1, duration_s=0.01)  # 1 bad of 100

    import re

    text = registry.expose()

    def gauge_value(line_prefix: str) -> float:
        m = re.search(rf"^{re.escape(line_prefix)} (\S+)$", text, re.M)
        assert m, f"{line_prefix}: not exposed"
        return float(m.group(1))

    assert gauge_value(
        'bci_slo_burn_rate{objective="availability",window="5m"}'
    ) == pytest.approx(1.0)
    assert gauge_value(
        'bci_slo_error_budget_remaining_ratio{objective="availability"}'
    ) == pytest.approx(0.0)
    assert 'objective="latency_100ms"' in text


# ----------------------------------------------------- transport surfaces


async def test_http_slo_endpoint_healthz_and_bundle(local_executor):
    from aiohttp.test_utils import TestClient, TestServer

    from bee_code_interpreter_tpu.api.http_server import create_http_server
    from bee_code_interpreter_tpu.services.custom_tool_executor import (
        CustomToolExecutor,
    )

    registry = Registry()
    engine = SloEngine(parse_objectives(99.5, "2000:99"), metrics=registry)
    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        metrics=registry,
        slo=engine,
    )
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        resp = await client.post(
            "/v1/execute", json={"source_code": "print(1)"}
        )
        assert resp.status == 200

        slo = await (await client.get("/v1/slo")).json()
        names = {o["name"] for o in slo["objectives"]}
        assert names == {"availability", "latency_2000ms"}
        availability = next(
            o for o in slo["objectives"] if o["name"] == "availability"
        )
        # the successful execute was recorded as a good sample
        assert availability["windows"]["5m"]["total"] == 1
        assert availability["windows"]["5m"]["bad"] == 0
        assert availability["error_budget_remaining_ratio"] == 1.0
        assert slo["alerting"] is False

        verbose = await (await client.get("/healthz?verbose=1")).json()
        assert verbose["slo"]["fast_burn_alerting"] is False
        assert {o["name"] for o in verbose["slo"]["objectives"]} == names
        terse = await (await client.get("/healthz")).json()
        assert "slo" not in terse

        bundle = await (await client.get("/v1/debug/bundle")).json()
        assert {o["name"] for o in bundle["slo"]["objectives"]} == names
        assert bundle["traces"]["retained"] >= 1
        assert "bci_http_requests_total" in bundle["metrics"]
    finally:
        await client.close()


async def test_http_records_500_as_bad_and_422_as_good(local_executor):
    from aiohttp.test_utils import TestClient, TestServer

    from bee_code_interpreter_tpu.api.http_server import create_http_server
    from bee_code_interpreter_tpu.services.custom_tool_executor import (
        CustomToolExecutor,
    )

    class Exploding:
        async def execute(self, **kwargs):
            raise RuntimeError("backend on fire")

    engine = SloEngine(parse_objectives(99.0, None))
    (objective,) = engine.objectives
    app = create_http_server(
        code_executor=Exploding(),
        custom_tool_executor=CustomToolExecutor(code_executor=Exploding()),
        slo=engine,
    )
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        resp = await client.post(
            "/v1/execute", json={"source_code": "print(1)"}
        )
        assert resp.status == 500
        total, bad = engine._window_counts(objective, WINDOWS["5m"])
        assert (total, bad) == (1, 1)

        # a validation error is the CLIENT's fault: sampled, but good
        resp = await client.post("/v1/execute", json={"nope": True})
        assert resp.status == 422
        total, bad = engine._window_counts(objective, WINDOWS["5m"])
        assert (total, bad) == (2, 1)
    finally:
        await client.close()


async def test_grpc_records_slo_and_serves_observability_service(
    local_executor,
):
    import grpc.aio

    from bee_code_interpreter_tpu.api.grpc_server import (
        GrpcServer,
        observability_stubs,
        service_stubs,
    )
    from bee_code_interpreter_tpu.proto import code_interpreter_pb2 as pb
    from bee_code_interpreter_tpu.services.custom_tool_executor import (
        CustomToolExecutor,
    )

    engine = SloEngine(parse_objectives(99.5, "2000:99"))
    (availability, _) = engine.objectives
    server = GrpcServer(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        slo=engine,
        debug_bundle=lambda: {"from": "context"},
    )
    port = await server.start("127.0.0.1:0")
    try:
        import json as _json

        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stubs = service_stubs(channel)
            response = await stubs["Execute"](
                pb.ExecuteRequest(source_code="print(21 * 2)")
            )
            assert response.stdout == "42\n"
            total, bad = engine._window_counts(availability, WINDOWS["5m"])
            assert (total, bad) == (1, 0)

            obs = observability_stubs(channel)
            slo = _json.loads(await obs["GetSlo"](b""))
            assert slo["objectives"][0]["windows"]["5m"]["total"] == 1
            bundle = _json.loads(await obs["GetDebugBundle"](b""))
            assert bundle == {"from": "context"}

            # a validation reject is the CLIENT's fault: sampled as good,
            # mirroring the HTTP edge's 422 (identical workloads must
            # compute identical SLIs on both transports)
            with pytest.raises(grpc.aio.AioRpcError) as err:
                await stubs["Execute"](pb.ExecuteRequest(source_code=""))
            assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
            total, bad = engine._window_counts(availability, WINDOWS["5m"])
            assert (total, bad) == (2, 0)
    finally:
        await server.stop(grace=0.1)
