"""Multi-LoRA serving (S-LoRA style) in the continuous batcher.

The correctness bar: a request served under adapter i must produce EXACTLY
the tokens that solo ``generate_cached`` produces on
``merge_lora(params, adapter_i)`` — while other requests in the same batch
run under different adapters (or the base model). One compiled program
serves the whole heterogeneous batch; the per-row delta is applied
unmerged in the decode path (x@A[idx]@B[idx]·scale) and folded via
merge_lora for the admission prefill.

The decode path applies the delta UNMERGED (x@A@B + x@W) while the solo
oracle folds it (x@(W+AB)) — mathematically identical, separated only by
floating-point rounding. At bf16 that separation can flip near-tie
argmaxes, so this file pins token equality on an f32 config (the same
"f32 so the equality assert is trustworthy" precedent as
examples/speculative-decode.py); bf16 behavior is covered by the
within-batcher determinism test at the bottom.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bee_code_interpreter_tpu.models.lora import (
    init_lora,
    merge_lora,
    stack_lora_bank,
)
from bee_code_interpreter_tpu.models.serving import ContinuousBatcher
from bee_code_interpreter_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    init_params,
)

CFG = dataclasses.replace(
    TransformerConfig.tiny(), n_kv_heads=2, dtype=jnp.float32
)
PARAMS = init_params(CFG, jax.random.PRNGKey(0))
SCALE = 2.0
PROMPT = [5, 3, 7, 2, 9, 4, 1, 8]


def trained_adapter(seed, targets=("wq", "wv")):
    """A LoRA whose delta is actually non-zero (init_lora zeroes B, which
    would make the adapted model identical to the base — useless as a
    test): randomize B at a magnitude that visibly changes logits."""
    lora = init_lora(CFG, jax.random.PRNGKey(seed), rank=4, targets=targets)
    return {
        t: {
            "A": ab["A"],
            "B": jax.random.normal(
                jax.random.PRNGKey(seed + 100), ab["B"].shape, jnp.float32
            ) * 0.25,
        }
        for t, ab in lora.items()
    }


ADAPTERS = [trained_adapter(1), trained_adapter(2)]


def solo(params, prompt, n):
    model = Transformer(CFG)
    out = model.generate_cached(
        params, jnp.asarray(prompt, dtype=jnp.int32)[None, :],
        max_new_tokens=n,
    )
    return np.asarray(out[0, len(prompt):]).tolist()


def make_batcher(**kw):
    kw.setdefault("max_batch", 3)
    kw.setdefault("n_pages", 40)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_pages_per_seq", 8)
    kw.setdefault("adapters", ADAPTERS)
    kw.setdefault("lora_scale", SCALE)
    return ContinuousBatcher(PARAMS, CFG, **kw)


def test_heterogeneous_adapters_decode_together_solo_equal():
    n = 6
    want_base = solo(PARAMS, PROMPT, n)
    want_0 = solo(merge_lora(PARAMS, ADAPTERS[0], SCALE), PROMPT, n)
    want_1 = solo(merge_lora(PARAMS, ADAPTERS[1], SCALE), PROMPT, n)
    # the adapters must actually change behavior for this test to mean
    # anything
    assert want_0 != want_base or want_1 != want_base

    b = make_batcher()
    r_base = b.submit(PROMPT, n)
    r_0 = b.submit(PROMPT, n, adapter=0)
    r_1 = b.submit(PROMPT, n, adapter=1)
    b.run_to_completion()
    assert b.result(r_base) == want_base
    assert b.result(r_0) == want_0
    assert b.result(r_1) == want_1


def test_rows_recycle_across_adapters():
    n = 4
    b = make_batcher(max_batch=1)
    want_1 = solo(merge_lora(PARAMS, ADAPTERS[1], SCALE), PROMPT, n)
    for adapter, want in ((1, want_1), (None, solo(PARAMS, PROMPT, n)),
                          (1, want_1)):
        r = b.submit(PROMPT, n, adapter=adapter)
        b.run_to_completion()
        assert b.result(r) == want


def test_wk_wo_targets_served():
    adapters = [trained_adapter(5, targets=("wq", "wk", "wv", "wo"))]
    n = 5
    want = solo(merge_lora(PARAMS, adapters[0], SCALE), PROMPT, n)
    b = make_batcher(adapters=adapters)
    r = b.submit(PROMPT, n, adapter=0)
    b.run_to_completion()
    assert b.result(r) == want


def test_chunked_admission_under_adapter():
    long_prompt = (PROMPT * 3)[:18]
    n = 4
    want = solo(merge_lora(PARAMS, ADAPTERS[0], SCALE), long_prompt, n)
    b = make_batcher()
    r = b.submit(long_prompt, n, adapter=0, prefill_chunk=8)
    b.run_to_completion()
    assert b.result(r) == want


def test_prefix_cache_keys_by_adapter():
    """The same prompt under different adapters must NEVER share K/V
    pages; the same (prompt, adapter) pair must hit."""
    n = 4
    want_0 = solo(merge_lora(PARAMS, ADAPTERS[0], SCALE), PROMPT, n)
    want_1 = solo(merge_lora(PARAMS, ADAPTERS[1], SCALE), PROMPT, n)
    b = make_batcher(prefix_cache=True)

    def run(adapter):
        r = b.submit(PROMPT, n, adapter=adapter)
        b.run_to_completion()
        return b.result(r)

    assert run(0) == want_0
    assert run(1) == want_1          # different adapter: MUST miss
    assert b.prefix_stats["hits"] == 0
    assert run(0) == want_0          # same (prompt, adapter): hits
    assert run(1) == want_1
    assert b.prefix_stats["hits"] == 2


def test_speculative_target_adapters():
    """Draft-verify with a per-row adapted TARGET (the draft stays base):
    output equals the solo adapted greedy decode."""
    draft_cfg = dataclasses.replace(CFG, n_layers=1)
    draft = init_params(draft_cfg, jax.random.PRNGKey(9))
    n = 6
    want_0 = solo(merge_lora(PARAMS, ADAPTERS[0], SCALE), PROMPT, n)
    want_base = solo(PARAMS, PROMPT, n)
    b = make_batcher(
        max_batch=2, draft_params=draft, draft_config=draft_cfg, gamma=3
    )
    r_0 = b.submit(PROMPT, n, adapter=0)
    r_base = b.submit(PROMPT, n)
    b.run_to_completion()
    assert b.result(r_0) == want_0
    assert b.result(r_base) == want_base


def test_validation_errors():
    b = make_batcher()
    with pytest.raises(ValueError, match="out of range"):
        b.submit(PROMPT, 3, adapter=2)
    plain = ContinuousBatcher(PARAMS, CFG, max_batch=2, n_pages=16,
                              page_size=4, max_pages_per_seq=4)
    with pytest.raises(ValueError, match="no adapters"):
        plain.submit(PROMPT, 3, adapter=0)
    with pytest.raises(ValueError, match="attention projections"):
        ContinuousBatcher(
            PARAMS, CFG, adapters=[
                {"w_gate": {"A": jnp.zeros((2, 8, 2)),
                            "B": jnp.zeros((2, 2, 8))}}
            ],
        )


def test_bank_stacking_validation():
    with pytest.raises(ValueError, match="share targets"):
        stack_lora_bank([
            {"wq": {"A": jnp.zeros((2, 8, 2)), "B": jnp.zeros((2, 2, 8))}},
            {"wv": {"A": jnp.zeros((2, 8, 2)), "B": jnp.zeros((2, 2, 8))}},
        ])
    with pytest.raises(ValueError, match="disagree"):
        stack_lora_bank([
            {"wq": {"A": jnp.zeros((2, 8, 2)), "B": jnp.zeros((2, 2, 8))}},
            {"wq": {"A": jnp.zeros((2, 8, 4)), "B": jnp.zeros((2, 4, 8))}},
        ])
    with pytest.raises(ValueError, match="at least one"):
        stack_lora_bank([])


def test_bf16_within_batcher_determinism():
    """At the serving dtype (bf16) the unmerged-vs-merged rounding gap
    makes merged-solo token equality a near-tie coin flip (see module
    docstring) — what MUST hold is that the batcher itself is
    deterministic: the same (prompt, adapter) twice gives the same
    output, and adapters actually change behavior."""
    cfg = dataclasses.replace(TransformerConfig.tiny(), n_kv_heads=2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    adapters = [trained_adapter(1)]

    def run():
        b = ContinuousBatcher(
            params, cfg, max_batch=2, n_pages=40, page_size=4,
            max_pages_per_seq=8, adapters=adapters, lora_scale=SCALE,
        )
        r_a = b.submit(PROMPT, 5, adapter=0)
        r_base = b.submit(PROMPT, 5)
        b.run_to_completion()
        return b.result(r_a), b.result(r_base)

    first, second = run(), run()
    assert first == second
    assert first[0] != first[1]  # the adapter visibly changes the output


def test_bf16_base_rows_in_adapter_batcher_stay_solo_exact():
    """A base (adapter=None) non-hit admission in an adapter-enabled
    batcher keeps the one-shot _full_admit path — bitwise the program
    family solo generate_cached prefills with — so its bf16 output stays
    token-exact against a plain batcher (the window path would differ in
    final ulps and could flip near-ties)."""
    cfg = dataclasses.replace(TransformerConfig.tiny(), n_kv_heads=2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    adapters = [trained_adapter(1)]

    def run(with_adapters):
        kw = dict(max_batch=2, n_pages=40, page_size=4, max_pages_per_seq=8)
        if with_adapters:
            kw.update(adapters=adapters, lora_scale=SCALE)
        b = ContinuousBatcher(params, cfg, **kw)
        r = b.submit(PROMPT, 6)  # base row
        b.run_to_completion()
        return b.result(r)

    assert run(True) == run(False)


def test_adapters_serve_under_tp_mesh_solo_equal():
    """Multi-LoRA on a tensor-parallel batcher: the adapter bank stays
    replicated (correctness-first; GSPMD reshards the small delta einsums
    as needed) while base params and the page pool shard over tp — each
    adapter row must still equal solo decode on its merged params."""
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    n = 5
    want_0 = solo(merge_lora(PARAMS, ADAPTERS[0], SCALE), PROMPT, n)
    want_base = solo(PARAMS, [9, 8, 7], n)
    b = make_batcher(mesh=mesh)
    r0 = b.submit(PROMPT, n, adapter=0)
    rb = b.submit([9, 8, 7], n)
    b.run_to_completion()
    assert b.result(r0) == want_0
    assert b.result(rb) == want_base
