"""Mixture-of-Experts layer + expert-parallel transformer (virtual devices).

Covers models/moe.py (GShard-style dense dispatch) standalone and integrated:
single-expert oracle equivalence, capacity-drop behavior, load-balance aux,
and a full dp x ep x tp sharded train step on the 8-virtual-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models.moe import (
    expert_capacity,
    init_moe_params,
    moe_mlp,
)
from bee_code_interpreter_tpu.parallel import make_mesh


def test_single_expert_matches_dense_swiglu():
    # n_experts=1, top_k=1, ample capacity: every token goes to the one
    # expert with gate weight 1.0, so the MoE MLP must equal a plain SwiGLU
    # MLP using that expert's weights — an exact dense oracle.
    d_model, ff = 32, 64
    params = init_moe_params(jax.random.PRNGKey(0), d_model, ff, n_experts=1)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, d_model), jnp.float32)

    out, aux = moe_mlp(
        params, x, n_experts=1, top_k=1, capacity_factor=2.0, dtype=jnp.float32
    )
    w_gate = params["we_gate"][0]
    w_up = params["we_up"][0]
    w_down = params["we_down"][0]
    dense = jnp.einsum(
        "blf,fd->bld",
        jax.nn.silu(jnp.einsum("bld,df->blf", x, w_gate))
        * jnp.einsum("bld,df->blf", x, w_up),
        w_down,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense), atol=1e-5, rtol=1e-5)
    # one expert: fraction=1, mean_prob=1 -> aux == n_experts * 1 * 1 == 1
    assert abs(float(aux) - 1.0) < 1e-5


def test_capacity_drops_overflow_tokens():
    # Capacity 8 slots (the rounding floor) with 64 tokens routed by top-1:
    # at most C tokens per expert contribute; the rest must come out as
    # exactly zero (the residual stream carries them).
    d_model, ff, E = 16, 32, 2
    params = init_moe_params(jax.random.PRNGKey(0), d_model, ff, n_experts=E)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 64, d_model), jnp.float32)
    out, _ = moe_mlp(
        params, x, n_experts=E, top_k=1, capacity_factor=0.01, dtype=jnp.float32
    )
    per_token = np.abs(np.asarray(out[0])).sum(axis=-1)
    C = expert_capacity(64, E, 1, 0.01)
    assert C == 8
    nonzero = int((per_token > 1e-9).sum())
    assert nonzero <= E * C  # dropped tokens contribute exactly zero
    assert nonzero > 0  # ...but the winners did run


def test_capacity_rounding():
    assert expert_capacity(128, 4, 2, 1.0) == 64
    assert expert_capacity(10, 8, 1, 1.0) == 8  # floor


def test_moe_prefill_and_decode_logits_agree_dropfree():
    # Cached decode must route consistently with the full forward. The
    # comparison is drop-free (ample capacity) and at the LOGITS level:
    # under capacity pressure the full forward routes tokens in competition
    # across all positions/rows while decode routes each token alone — an
    # inherent property of capacity-based MoE (review r3 reproduced token
    # mismatches at the default factor) — and even drop-free, summation-order
    # differences make token-exactness a coin flip at near-ties.
    import dataclasses

    config = dataclasses.replace(
        T.TransformerConfig.tiny_moe(),
        moe_capacity_factor=8.0,
        dtype=jnp.float32,
    )
    B, L_pre, L_total = 2, 8, 12
    params = T.init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (B, L_total), 0, config.vocab_size
    )

    logits_full = T.forward(params, tokens, config)

    logits_pre, (k_pre, v_pre) = T.forward(
        params, tokens[:, :L_pre], config, return_kv=True
    )
    c = config
    cache = T.init_decode_cache(c, B, L_total, k_pre, v_pre)
    np.testing.assert_allclose(
        np.asarray(logits_pre),
        np.asarray(logits_full[:, :L_pre]),
        atol=1e-4,
        rtol=1e-4,
    )

    for pos in range(L_pre, L_total):
        step_logits, cache = T.decode_step(
            params, tokens[:, pos : pos + 1], jnp.int32(pos), cache, c
        )
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]),
            np.asarray(logits_full[:, pos]),
            atol=1e-4,
            rtol=1e-4,
        )


def test_moe_train_step_dp_ep_tp_sharded():
    # The full expert-parallel training step on the virtual 8-device mesh:
    # batch over dp, experts over ep, attention/MLP matmuls over tp. GSPMD
    # inserts the dispatch/combine all-to-alls; loss must be finite and
    # decrease over a few steps.
    mesh = make_mesh({"dp": 2, "ep": 2, "tp": 2}, devices=jax.devices()[:8])
    config = T.TransformerConfig.tiny_moe()
    model = T.Transformer(config, mesh)
    params = model.init(jax.random.PRNGKey(0))

    # expert weights actually landed on the ep axis
    spec = T.param_specs(config, mesh)["layers"]["moe"]["we_gate"]
    assert "ep" in jax.tree.leaves(spec, is_leaf=lambda x: x is not None) or (
        "ep" in [a for part in spec if part for a in (part if isinstance(part, tuple) else (part,))]
    )

    optimizer = model.make_optimizer(1e-2)
    opt_state = optimizer.init(params)
    step = model.make_train_step(optimizer)

    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, config.vocab_size)
    batch = jax.device_put(
        {"tokens": tokens[:, :-1], "targets": tokens[:, 1:]},
        model.batch_sharding(),
    )
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_moe_aux_loss_feeds_training():
    config = T.TransformerConfig.tiny_moe()
    params = T.init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, config.vocab_size)
    batch = {"tokens": tokens[:, :-1], "targets": tokens[:, 1:]}
    base = T.loss_fn(params, batch, config)
    # the aux term responds to the weight knob
    import dataclasses

    heavier = dataclasses.replace(config, moe_aux_weight=1.0)
    assert float(T.loss_fn(params, batch, heavier)) > float(base)


def test_grouped_routing_matches_single_group_with_ample_capacity():
    # With capacity ample enough that no token is dropped in either layout,
    # grouped routing (the memory-bounding GShard group axis) must produce
    # the same output as one global group.
    d_model, ff, E = 16, 32, 4
    params = init_moe_params(jax.random.PRNGKey(0), d_model, ff, E)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, d_model), jnp.float32)
    kwargs = dict(n_experts=E, top_k=2, capacity_factor=8.0, dtype=jnp.float32)
    out_grouped, aux_g = moe_mlp(params, x, group_size=32, **kwargs)
    out_single, aux_s = moe_mlp(params, x, group_size=1 << 30, **kwargs)
    np.testing.assert_allclose(
        np.asarray(out_grouped), np.asarray(out_single), atol=1e-5, rtol=1e-5
    )
    # aux is a mean over groups of identically-distributed terms; both stay O(1)
    assert 0.5 < float(aux_g) < float(E)
    assert 0.5 < float(aux_s) < float(E)


def test_group_capacity_is_bounded_by_group_size_not_global():
    # The memory bound: dispatch memory is G*E*C where C follows the GROUP
    # size — constant as the global token count grows (without the group
    # axis C itself grows with G, making dispatch quadratic; review r3).
    per_group = expert_capacity(1024, 8, 2, 1.25)
    single_group_16x = expert_capacity(16384, 8, 2, 1.25)
    assert single_group_16x >= 16 * per_group - 8 * 16  # C grew ~16x ungrouped
    # grouped dispatch at G=16384: 16 groups x [1024, 8, per_group] stays
    # 16x smaller than the single-group [16384, 8, single_group_16x]
    grouped_elems = 16 * 1024 * 8 * per_group
    single_elems = 16384 * 8 * single_group_16x
    assert grouped_elems * 8 <= single_elems


def test_dropless_capacity_never_drops():
    # Worst case: a router so biased every token top-1s the same expert.
    # Dropless capacity must carry all of them (dispatch mass == top_k per
    # token); the default factor provably drops in the same setup.
    E, g, D = 4, 32, 16
    params = init_moe_params(jax.random.PRNGKey(0), D, 32, E)
    params["router"] = params["router"].at[:].set(0.0)
    params["router"] = params["router"].at[:, 0].set(10.0)  # everyone → e0
    x = jax.random.normal(jax.random.PRNGKey(1), (1, g, D), jnp.float32)
    from bee_code_interpreter_tpu.models.moe import _route_group, expert_capacity

    xf = x.reshape(g, D)
    C_drop = expert_capacity(g, E, 2, 1.25)
    C_free = expert_capacity(g, E, 2, 1.25, dropless=True)
    assert C_free >= g  # every token fits even if all pick one expert
    d_drop, _, _ = _route_group(xf, params["router"], n_experts=E, top_k=2,
                                capacity=C_drop)
    d_free, _, _ = _route_group(xf, params["router"], n_experts=E, top_k=2,
                                capacity=C_free)
    per_token_drop = np.asarray(jnp.sum(d_drop, axis=(1, 2)))
    per_token_free = np.asarray(jnp.sum(d_free, axis=(1, 2)))
    assert (per_token_drop < 2).any()  # default factor drops here
    np.testing.assert_array_equal(per_token_free, np.full(g, 2.0))


def test_dropless_routing_is_batch_independent():
    # The serving-exactness property at its root: a row's forward output
    # must not change when other rows join the routing pool. With per-token
    # groups (moe_group_size=1) the pool size is only a batch dim of the
    # expert einsums, so equality is BITWISE (config.moe_exact). With a
    # shared group, capacity scales with the pool, reduction tiling varies
    # with the shape, and equality holds only to reduction-order ulps —
    # which is why moe_exact requires the per-token grouping.
    import dataclasses as dc

    toks_key, init_key = jax.random.PRNGKey(1), jax.random.PRNGKey(0)
    for group_size, exact in ((1, True), (1024, False)):
        config = dc.replace(T.TransformerConfig.tiny_moe(),
                            moe_dropless=True, moe_group_size=group_size,
                            dtype=jnp.float32)
        assert config.moe_exact is exact
        params = T.init_params(config, init_key)
        toks = jax.random.randint(toks_key, (4, 6), 0, config.vocab_size)
        solo = T.forward(params, toks[:1], config)
        batch = T.forward(params, toks, config)
        if exact:
            np.testing.assert_array_equal(np.asarray(solo[0]),
                                          np.asarray(batch[0]))
        else:
            np.testing.assert_allclose(np.asarray(solo[0]),
                                       np.asarray(batch[0]),
                                       atol=1e-5, rtol=1e-4)
