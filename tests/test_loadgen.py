"""Open-loop load generator + capacity reporter (ISSUE 18,
docs/capacity.md).

The units prove the two properties the whole capacity methodology rests
on: schedules are DETERMINISTIC (same shape + seed → identical arrival
instants) and the generator is genuinely OPEN-LOOP (a slow service
changes what comes back, never what goes out). The knee search runs as a
seconds-scale smoke sweep against a stub service with a known concurrency
ceiling — the real-fleet sweeps live in ``bench.py capacity`` and the
``slow``-marked fleet test."""

import asyncio

import httpx
import pytest
from aiohttp import web

from bee_code_interpreter_tpu.loadgen import (
    COST_CLASS_PAYLOADS,
    CapacityReporter,
    Diurnal,
    FlashCrowd,
    OpenLoopGenerator,
    Phases,
    Ramp,
    Steady,
    TrafficMix,
    arrival_times,
    evaluate_sustained,
    find_knee,
    heavy_tail_weights,
)
from bee_code_interpreter_tpu.observability import recommend_replicas
from bee_code_interpreter_tpu.utils.metrics import Registry
from tests.fakes import free_port

# ------------------------------------------------------------------ shapes


def test_steady_schedule_is_even_and_exact():
    times = arrival_times(Steady(rps=5.0, duration_s=4.0))
    assert len(times) == 20
    gaps = [b - a for a, b in zip(times, times[1:])]
    assert all(abs(g - 0.2) < 0.01 for g in gaps)


def test_schedules_are_deterministic_per_seed():
    shape = Ramp(start_rps=1.0, end_rps=9.0, duration_s=6.0)
    a = arrival_times(shape, jitter_s=0.05, seed=7)
    b = arrival_times(shape, jitter_s=0.05, seed=7)
    c = arrival_times(shape, jitter_s=0.05, seed=8)
    assert a == b
    assert a != c
    assert a == sorted(a)


def test_ramp_integrates_to_mean_rate():
    # 1→9 rps over 6s ≡ mean 5 rps → 30 arrivals, denser at the end.
    times = arrival_times(Ramp(start_rps=1.0, end_rps=9.0, duration_s=6.0))
    assert len(times) == 30
    first_half = sum(1 for t in times if t < 3.0)
    assert first_half < len(times) / 2


def test_flash_crowd_is_a_step_multiplier():
    shape = FlashCrowd(
        base_rps=2.0, duration_s=10.0, crowd_start_s=4.0, crowd_s=2.0,
        multiplier=10.0,
    )
    assert shape.rate_at(0.0) == 2.0
    assert shape.rate_at(5.0) == 20.0
    assert shape.rate_at(7.0) == 2.0
    # 2 rps × 10s base + (20−2) rps × 2s crowd = 56 arrivals.
    assert len(arrival_times(shape)) == 56


def test_diurnal_troughs_at_edges_and_peaks_mid_period():
    shape = Diurnal(base_rps=1.0, peak_rps=11.0, duration_s=8.0)
    assert shape.rate_at(0.0) == pytest.approx(1.0)
    assert shape.rate_at(4.0) == pytest.approx(11.0)


def test_phases_sequence_shapes():
    shape = Phases(
        phases=(
            Steady(rps=2.0, duration_s=3.0),
            Steady(rps=8.0, duration_s=2.0),
        )
    )
    assert shape.duration_s == 5.0
    assert shape.rate_at(1.0) == 2.0
    assert shape.rate_at(4.0) == 8.0
    assert len(arrival_times(shape)) == 22


def test_heavy_tail_mix_is_skewed_and_deterministic():
    tenants = [f"t{i}" for i in range(8)]
    mix = TrafficMix(tenants=heavy_tail_weights(tenants), seed=3)
    times = arrival_times(Steady(rps=50.0, duration_s=8.0))
    plan = mix.plan(times)
    assert [p.tenant for p in plan] == [p.tenant for p in mix.plan(times)]
    counts: dict[str, int] = {}
    for p in plan:
        counts[p.tenant] = counts.get(p.tenant, 0) + 1
    # Zipf head dominates: the hottest tenant beats the coldest by a lot.
    assert counts["t0"] > 4 * counts.get("t7", 1)
    # Every planned payload is one of the classifier-visible cost classes.
    assert {p.source for p in plan} <= set(COST_CLASS_PAYLOADS.values())


# ------------------------------------------------------ stub service


class StubService:
    """Minimal /v1/execute edge with a tunable service time and a hard
    concurrency ceiling (429 beyond it) — a known-capacity device under
    test for the open-loop and knee properties."""

    def __init__(self, *, delay_s: float = 0.0, max_in_flight: int = 10**9):
        self.delay_s = delay_s
        self.max_in_flight = max_in_flight
        self.in_flight = 0
        self.peak_in_flight = 0
        self.served = 0
        self.shed = 0
        self.runner = None
        self.url = ""

    async def _execute(self, request: web.Request) -> web.Response:
        if self.in_flight >= self.max_in_flight:
            self.shed += 1
            return web.json_response(
                {"reason": "capacity"}, status=429
            )
        self.in_flight += 1
        self.peak_in_flight = max(self.peak_in_flight, self.in_flight)
        try:
            if self.delay_s:
                await asyncio.sleep(self.delay_s)
            self.served += 1
            return web.json_response({"exit_code": 0, "stdout": "42\n"})
        finally:
            self.in_flight -= 1

    async def _slo(self, _request: web.Request) -> web.Response:
        return web.json_response(
            {"fast_burn_alerting": False, "alerting": False}
        )

    async def __aenter__(self) -> "StubService":
        app = web.Application()
        app.router.add_post("/v1/execute", self._execute)
        app.router.add_post(
            "/v1/sessions/{sid}/execute", self._execute
        )
        app.router.add_get("/v1/slo", self._slo)
        self.runner = web.AppRunner(app)
        await self.runner.setup()
        port = free_port()
        await web.TCPSite(self.runner, "127.0.0.1", port).start()
        self.url = f"http://127.0.0.1:{port}"
        return self

    async def __aexit__(self, *exc) -> None:
        await self.runner.cleanup()


# --------------------------------------------------------- open loop


async def test_generator_is_open_loop_not_response_gated():
    """20 offered arrivals in 1s against a 300ms service: a closed loop
    would serialize to ~3 sends; the open loop fires all 20 on schedule
    and the stub's peak concurrency proves they overlapped."""
    async with StubService(delay_s=0.3) as stub:
        async with httpx.AsyncClient() as client:
            generator = OpenLoopGenerator(
                client,
                stub.url,
                mix=TrafficMix(kinds=(("execute", 1.0),)),
                metrics=Registry(),
            )
            result = await generator.run(
                Steady(rps=20.0, duration_s=1.0), label="openloop"
            )
    assert result.sent == 20
    assert result.completed == 20
    assert stub.peak_in_flight >= 5
    assert result.lag_quantile_s(0.95) < 0.25
    doc = result.to_dict()
    assert doc["statuses"] == {"200": 20}
    assert doc["latency_ms"]["p50"] >= 300.0


async def test_generator_sessions_and_tenants_route_and_ledger():
    async with StubService() as stub:
        async with httpx.AsyncClient() as client:
            generator = OpenLoopGenerator(
                client,
                stub.url,
                mix=TrafficMix(
                    kinds=(("execute", 1.0), ("session", 1.0)),
                    tenants=[("abuser", 1.0)],
                ),
                session_ids=["s-1", "s-2"],
            )
            result = await generator.run(Steady(rps=30.0, duration_s=0.5))
    assert result.sent == 15
    assert result.completed == 15
    assert result.shed_ledger() == {}


async def test_overload_is_visible_sheds_and_undrained_count_as_errors():
    async with StubService(delay_s=0.2, max_in_flight=2) as stub:
        async with httpx.AsyncClient() as client:
            generator = OpenLoopGenerator(
                client, stub.url, mix=TrafficMix(kinds=(("execute", 1.0),))
            )
            result = await generator.run(
                Steady(rps=40.0, duration_s=0.5), drain_timeout_s=2.0
            )
    # Offered 20 in 0.5s against a 2-wide 0.2s service (≈10 rps capacity):
    # the collapse shows up as client-visible sheds, not a quietly slower
    # send loop.
    assert result.sent == 20
    assert result.sheds > 0
    assert result.completed < 20
    verdict = evaluate_sustained(result, p99_ms=1000.0)
    assert not verdict["sustained"]
    assert any("shed" in r for r in verdict["reasons"])


# -------------------------------------------------------- knee search


async def test_find_knee_brackets_the_stub_capacity():
    """Smoke sweep (the tier-1 scale one): a 2-wide 100ms stub saturates
    at ~20 rps; the bisection must land the knee between the known-good
    floor and the known-bad ceiling and keep every probe point."""
    async with StubService(delay_s=0.1, max_in_flight=2) as stub:
        async with httpx.AsyncClient() as client:
            generator = OpenLoopGenerator(
                client, stub.url, mix=TrafficMix(kinds=(("execute", 1.0),))
            )
            reporter = CapacityReporter(client, stub.url)
            knee, probes = await find_knee(
                generator,
                lo_rps=4.0,
                hi_rps=60.0,
                duration_s=1.0,
                p99_ms=2000.0,
                reporter=reporter,
                iterations=4,
                drain_timeout_s=2.0,
            )
    assert 4.0 <= knee < 60.0
    assert len(probes) >= 3
    assert probes[0]["sustained"] is True
    assert probes[1]["sustained"] is False
    offered = [p["offered_rps"] for p in probes]
    assert offered == sorted(set(offered), key=offered.index)


async def test_capacity_reporter_scrape_is_total():
    """A scrape against an edge with no /v1/autoscale (and then no edge at
    all) reports None sections — never an exception into the probe."""
    async with StubService() as stub:
        async with httpx.AsyncClient() as client:
            reporter = CapacityReporter(client, stub.url)
            scrape = await reporter.scrape()
            assert scrape["slo"] is not None
            assert scrape["autoscale"] is None
            assert scrape["fast_burn"] is False
            dead = CapacityReporter(client, "http://127.0.0.1:9")
            scrape = await dead.scrape()
            assert scrape["slo"] is None and scrape["autoscale"] is None


# ----------------------------------------------- replica recommendation


def test_recommend_replicas_sizing_and_reasons():
    # forecast 20 rps × 2s horizon = 40 slots / 8 per replica → 5.
    doc = recommend_replicas(
        forecast_rps=20.0, horizon_s=2.0, per_replica_capacity=8,
        current_replicas=3,
    )
    assert doc["target_replicas"] == 5 and doc["reason"] == "forecast"
    # Concurrency high-water floors the demand even when rates are low.
    doc = recommend_replicas(
        forecast_rps=0.5, horizon_s=1.0, concurrency_high_water=17.0,
        per_replica_capacity=8,
    )
    assert doc["target_replicas"] == 3
    # Idle fleet shrinks to the floor, and says that is why.
    doc = recommend_replicas(
        forecast_rps=0.0, horizon_s=2.0, current_replicas=4
    )
    assert doc["target_replicas"] == 1 and doc["reason"] == "idle"
    # An active fast-burn page vetoes shrink: grow by one instead.
    doc = recommend_replicas(
        forecast_rps=0.0, horizon_s=0.0, current_replicas=4,
        slo_fast_burn=True,
    )
    assert doc["target_replicas"] == 5 and doc["reason"] == "slo_burn"
    # The band clamps, and the clamp is named.
    doc = recommend_replicas(
        forecast_rps=1000.0, horizon_s=10.0, per_replica_capacity=1,
        max_replicas=8,
    )
    assert doc["target_replicas"] == 8 and doc["reason"] == "clamped"


def test_recommend_replicas_is_nan_and_inf_proof():
    nan = float("nan")
    inf = float("inf")
    doc = recommend_replicas(
        forecast_rps=nan, horizon_s=inf, concurrency_high_water=nan,
        per_replica_capacity=0, current_replicas=-3, min_replicas=-1,
        max_replicas=0,
    )
    assert doc["target_replicas"] == 0  # min_replicas clamped to 0
    assert isinstance(doc["target_replicas"], int)
    # Non-finite demand is GARBAGE, not "huge": it must not scale to max.
    doc = recommend_replicas(forecast_rps=inf, horizon_s=1.0)
    assert doc["target_replicas"] == 1 and doc["reason"] == "idle"
