"""Accelerator observability plane (ISSUE 20): TrackedJit exactly-once
compile detection, DeviceMonitor's three signals (compile/retrace wide
events + counters + backdated trace spans, CPU-degraded memory accounting,
mesh-shaped step telemetry), the forced-retrace e2e on a real tiny batcher,
the `GET /v1/accelerator` + `POST /v1/profile target=device` HTTP edges and
their gRPC mirrors (400 ↔ INVALID_ARGUMENT parity), and the serving-bench
overhead A/B with the device monitor riding the instrumented arm."""

import dataclasses
import json

import grpc.aio
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_tpu.api.grpc_server import (
    GrpcServer,
    observability_stubs,
)
from bee_code_interpreter_tpu.api.http_server import create_http_server
from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models.engine import Engine
from bee_code_interpreter_tpu.models.serving import ContinuousBatcher
from bee_code_interpreter_tpu.observability import (
    DeviceMonitor,
    DeviceProfiler,
    FlightRecorder,
    ServingMonitor,
    TraceStore,
)
from bee_code_interpreter_tpu.observability.tracing import (
    Trace,
    activate_trace,
)
from bee_code_interpreter_tpu.parallel.mesh import (
    mesh_descriptor,
    mesh_shape_key,
)
from bee_code_interpreter_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_tpu.utils.jitwatch import (
    TrackedJit,
    abstract_signature,
)
from bee_code_interpreter_tpu.utils.metrics import Registry

CFG = dataclasses.replace(
    T.TransformerConfig.tiny(), dtype=jnp.float32, n_kv_heads=2
)
PARAMS = T.init_params(CFG, jax.random.PRNGKey(0))


def counter_value(metrics: Registry, needle: str) -> float:
    for line in metrics.expose().splitlines():
        if line.startswith(needle + " ") or line.startswith(needle + "{"):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def monitored_stack():
    """Registry + recorder + trace store + both monitors over a tiny
    engine — the chaos scenario 19 wiring in miniature. page_size=4 so a
    3-token prompt pads to one page and a 6-token prompt to two: the
    second prefill shape forces a retrace during live serving."""
    metrics = Registry()
    store = TraceStore()
    recorder = FlightRecorder(metrics=metrics, max_events=256)
    serving = ServingMonitor(metrics=metrics, store=store, recorder=recorder)
    device = DeviceMonitor(metrics=metrics, recorder=recorder)
    batcher = ContinuousBatcher(
        PARAMS, CFG, max_batch=2, n_pages=16, page_size=4,
        max_pages_per_seq=4, metrics=metrics,
    )
    engine = Engine(batcher, max_queue=4, metrics=metrics)
    serving.attach(engine)
    device.attach(engine)
    return engine, device, serving, metrics, store, recorder


# ------------------------------------------------------------- TrackedJit


def test_tracked_jit_reports_each_compile_exactly_once():
    compiles = []

    class Hook:
        def on_compile(self, name, *, signature, duration_ms, trigger):
            compiles.append(
                {"name": name, "signature": signature, "trigger": trigger,
                 "duration_ms": duration_ms}
            )

    hook = Hook()
    fn = TrackedJit(jax.jit(lambda x: x * 2), "double", lambda: hook)
    a = jnp.ones((4,), jnp.float32)
    np.testing.assert_allclose(np.asarray(fn(a)), 2.0)
    assert [c["trigger"] for c in compiles] == ["first_call"]
    assert compiles[0]["name"] == "double"
    assert "float32[4]" in compiles[0]["signature"]
    assert compiles[0]["duration_ms"] > 0.0

    # same signature: cached executable, NO new report
    fn(jnp.zeros((4,), jnp.float32))
    assert len(compiles) == 1

    # new shape: one retrace, reported exactly once
    fn(jnp.ones((8,), jnp.float32))
    fn(jnp.ones((8,), jnp.float32))
    assert [c["trigger"] for c in compiles] == ["first_call", "retrace"]
    assert "float32[8]" in compiles[1]["signature"]


def test_tracked_jit_unmonitored_path_and_passthrough():
    fn = TrackedJit(jax.jit(lambda x: x + 1), "inc", lambda: None)
    assert int(fn(jnp.int32(1))) == 2  # no monitor: plain call
    assert callable(fn.lower)  # AOT attribute passthrough to the jit
    assert abstract_signature((jnp.ones((2, 3)),), {"n": 4}) == (
        "(float32[2,3], n=4)"
    )


# ----------------------------------------------------------- DeviceMonitor


def test_on_compile_event_counter_and_backdated_span_share_trace_id():
    metrics = Registry()
    recorder = FlightRecorder(metrics=metrics)
    monitor = DeviceMonitor(metrics=metrics, recorder=recorder)
    trace = Trace(None, "request", request_id="req-1")

    with activate_trace(trace):
        monitor.on_compile(
            "decode_step", signature="(float32[2,4])", duration_ms=120.0,
            trigger="retrace",
        )

    events = recorder.events(kind="compile")
    assert len(events) == 1
    event = events[0]
    assert event["function"] == "decode_step"
    assert event["trigger"] == "retrace"
    assert event["trace_id"] == trace.trace_id
    assert event["request_id"] == "req-1"

    spans = [s for s in trace.spans if s.name == "xla.compile"]
    assert len(spans) == 1
    # backdated: the span covers the stall that already happened
    assert spans[0].duration_ms == pytest.approx(120.0, rel=0.05)
    assert spans[0].attributes["trigger"] == "retrace"

    assert counter_value(metrics, 'bci_compile_total{trigger="retrace"}') == 1
    snap = monitor.snapshot()
    assert snap["compile"]["total"] == 1
    assert snap["compile"]["by_trigger"] == {"retrace": 1}
    assert snap["compile"]["recent"][0]["trace_id"] == trace.trace_id
    fn = snap["compile"]["functions"]["decode_step"]
    assert fn["compiles"] == 1 and fn["signatures"] == ["(float32[2,4])"]


def test_compile_without_ambient_trace_has_no_trace_id():
    recorder = FlightRecorder(metrics=Registry())
    monitor = DeviceMonitor(recorder=recorder)  # metrics=None path too
    monitor.on_compile(
        "prefill", signature="(int32[8])", duration_ms=5.0,
        trigger="first_call",
    )
    (event,) = recorder.events(kind="compile")
    assert "trace_id" not in event
    assert monitor.snapshot()["compile"]["by_trigger"] == {"first_call": 1}


def test_cpu_memory_degradation_snapshot():
    """No memory_stats() on the CPU backend: rows come from the live-buffer
    estimate, marked estimated, peak is a running max, limit unknown."""
    monitor = DeviceMonitor(metrics=Registry())
    keep = jnp.ones((256, 256), jnp.float32)  # a buffer the walk must see
    rows = monitor.sample_memory()
    assert rows, "no devices visible"
    assert all(r["estimated"] for r in rows)
    assert all(r["limit_bytes"] is None for r in rows)
    assert sum(r["live_bytes"] for r in rows) >= keep.nbytes

    snap = monitor.snapshot()
    assert snap["attached"] is False
    assert snap["memory"]["estimated"] is True
    assert snap["memory"]["samples"] >= 2  # constructor takes an eager one
    assert snap["kv_pool"] is None
    assert snap["mesh"] is None

    fleet = monitor.fleet_summary()
    assert fleet["hbm"]["estimated"] is True
    assert fleet["hbm"]["limit_bytes"] is None
    assert fleet["hbm"]["live_bytes"] >= keep.nbytes
    assert fleet["mesh"] is None and fleet["compiles"] == 0


def test_step_telemetry_aggregates_per_mesh_shape():
    monitor = DeviceMonitor(metrics=Registry())
    monitor.record_step(10.0)  # no mesh: the single-device "1" bucket
    monitor.set_mesh(mesh_descriptor(None))
    monitor.record_step(20.0)
    monitor.record_step(30.0, shape="dp=2,tp=4")

    shapes = monitor.snapshot()["steps"]["by_shape"]
    assert shapes["1"]["steps"] == 2
    assert shapes["1"]["total_ms"] == pytest.approx(30.0)
    assert shapes["1"]["min_ms"] == pytest.approx(10.0)
    assert shapes["1"]["max_ms"] == pytest.approx(20.0)
    assert shapes["dp=2,tp=4"] == {
        "steps": 1, "total_ms": 30.0, "min_ms": 30.0, "max_ms": 30.0,
        "last_ms": 30.0,
    }


def test_mesh_shape_key_and_descriptor():
    assert mesh_shape_key(None) == "1"
    desc = mesh_descriptor(None)
    assert desc["shape"] == "1" and desc["axes"] == {}
    from bee_code_interpreter_tpu.parallel import make_mesh

    n = len(jax.devices())
    if n >= 2:
        mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])
        assert mesh_shape_key(mesh) == "dp=2"
        d = mesh_descriptor(mesh)
        assert d["axes"] == {"dp": 2} and d["n_devices"] == 2
        assert d["platform"] == jax.devices()[0].platform


# ------------------------------------------------- e2e: retrace under load


def test_forced_retrace_during_serving_lands_in_all_three_surfaces():
    """Chaos scenario 19's core as a tier-1 test: a prompt that needs a new
    prefill page count retraces mid-serving — exactly one compile event,
    one counter increment, and one backdated xla.compile span inside the
    REQUEST's trace, all naming the same trace_id."""
    engine, device, serving, metrics, store, recorder = monitored_stack()

    t_a = engine.submit([1, 2, 3], 4)  # pads to 1 page: first_call compiles
    engine.run_to_completion()
    assert len(engine.result(t_a)) == 4
    baseline = device.snapshot()["compile"]["by_trigger"].get("retrace", 0)
    assert baseline == 0

    t_b = engine.submit([5, 3, 7, 2, 9, 11], 4)  # 2 pages: prefill retrace
    engine.run_to_completion()
    assert len(engine.result(t_b)) == 4

    retraces = [
        e for e in recorder.events(kind="compile")
        if e.get("trigger") == "retrace"
    ]
    assert retraces, "the page-count change must force a retrace"
    snap = device.snapshot()
    assert snap["attached"] is True
    assert snap["compile"]["by_trigger"]["retrace"] == len(retraces)
    assert counter_value(
        metrics, 'bci_compile_total{trigger="retrace"}'
    ) == len(retraces)
    # one compile event per compile overall, not just retraces
    all_compile_events = recorder.events(kind="compile")
    assert snap["compile"]["total"] == len(all_compile_events)

    # attribution: every retrace fired under request B's live trace
    trace_ids = {e.get("trace_id") for e in retraces}
    assert len(trace_ids) == 1 and None not in trace_ids
    trace = store.get(trace_ids.pop())
    assert trace is not None
    compile_spans = [s for s in trace.spans if s.name == "xla.compile"]
    assert len(compile_spans) == len(retraces)

    # step telemetry rode along, bucketed under the single-device shape
    assert snap["steps"]["by_shape"]["1"]["steps"] > 0
    # KV-pool occupancy joined from the live batcher
    assert snap["kv_pool"]["pages_total"] == 15


# --------------------------------------------------------- HTTP/gRPC twins


def make_app(local_executor, *, device=None, device_profiler=None):
    return create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        metrics=Registry(),
        device=device,
        device_profiler=device_profiler,
    )


async def with_client(app, fn):
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        await fn(client)
    finally:
        await client.close()


async def test_http_accelerator_endpoint(local_executor):
    device = DeviceMonitor(metrics=Registry())
    device.on_compile(
        "decode_step", signature="(f32[1])", duration_ms=3.0,
        trigger="first_call",
    )
    app = make_app(local_executor, device=device)

    async def go(client):
        resp = await client.get("/v1/accelerator")
        assert resp.status == 200
        snap = await resp.json()
        assert sorted(snap) == [
            "attached", "compile", "kv_pool", "memory", "mesh", "steps",
        ]
        assert snap["compile"]["total"] == 1
        assert snap["memory"]["devices"], "memory sample missing"
        trimmed = await (
            await client.get("/v1/accelerator", params={"recent": "0"})
        ).json()
        assert trimmed["compile"]["recent"] == []
        for bad in ({"recent": "nope"}, {"recent": "-1"}):
            assert (
                await client.get("/v1/accelerator", params=bad)
            ).status == 400

    await with_client(app, go)


async def test_http_accelerator_unwired_and_fleet_summary(local_executor):
    async def go_unwired(client):
        assert (await client.get("/v1/accelerator")).status == 501

    await with_client(make_app(local_executor), go_unwired)

    device = DeviceMonitor(metrics=Registry())
    app = make_app(local_executor, device=device)

    async def go_fleet(client):
        fleet = await (await client.get("/v1/fleet")).json()
        accel = fleet["accelerator"]
        assert accel["compiles"] == 0
        assert accel["hbm"]["estimated"] is True

    await with_client(app, go_fleet)


async def test_http_device_profile(local_executor, tmp_path):
    profiler = DeviceProfiler(trace_root=tmp_path)
    app = make_app(local_executor, device_profiler=profiler)

    async def go(client):
        resp = await client.post(
            "/v1/profile", json={"target": "device", "steps": 2}
        )
        if resp.status == 501:
            # backends without a working jax.profiler degrade to the
            # documented 501 + reason; CPU normally captures fine
            assert "detail" in await resp.json()
            return
        assert resp.status == 200
        body = await resp.json()
        assert body["target"] == "device"
        assert body["source"] == "probe"  # no engine attached
        assert body["steps"] == 2 and body["duration_ms"] >= 0

    await with_client(app, go)


async def test_http_device_profile_unwired_is_501(local_executor):
    async def go(client):
        resp = await client.post("/v1/profile", json={"target": "device"})
        assert resp.status == 501
        assert "device profiling unavailable" in (await resp.json())["detail"]

    await with_client(make_app(local_executor), go)


async def test_grpc_get_accelerator_twin(local_executor):
    device = DeviceMonitor(metrics=Registry())
    device.on_compile(
        "prefill_forward", signature="(i32[4])", duration_ms=7.0,
        trigger="first_call",
    )
    server = GrpcServer(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        metrics=Registry(),
        device=device,
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            obs = observability_stubs(channel)
            snap = json.loads(await obs["GetAccelerator"](b""))
            assert sorted(snap) == [
                "attached", "compile", "kv_pool", "memory", "mesh", "steps",
            ]
            assert snap["compile"]["functions"]["prefill_forward"][
                "compiles"
            ] == 1
            trimmed = json.loads(
                await obs["GetAccelerator"](b'{"recent": 0}')
            )
            assert trimmed["compile"]["recent"] == []
            # 400 ↔ INVALID_ARGUMENT parity with the HTTP edge
            for payload in (b"not json", b'{"recent": -1}', b'{"recent": "x"}'):
                with pytest.raises(grpc.aio.AioRpcError) as excinfo:
                    await obs["GetAccelerator"](payload)
                assert (
                    excinfo.value.code() == grpc.StatusCode.INVALID_ARGUMENT
                )
    finally:
        await server.stop(None)


async def test_grpc_get_accelerator_unimplemented_without_monitor(
    local_executor,
):
    server = GrpcServer(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        metrics=Registry(),
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            obs = observability_stubs(channel)
            with pytest.raises(grpc.aio.AioRpcError) as excinfo:
                await obs["GetAccelerator"](b"")
            assert excinfo.value.code() == grpc.StatusCode.UNIMPLEMENTED
    finally:
        await server.stop(None)


# ------------------------------------------------------------- overhead A/B


@pytest.mark.slow
def test_serving_bench_overhead_includes_device_monitor():
    """The bench's instrumented arm now carries the DeviceMonitor too, so
    its measured overhead prices compile tracking + per-step telemetry.
    Budget enforcement stays the bench artifact's job (CI boxes are too
    noisy for a hard < 5% assert here); this pins the fields and that the
    instrumented arm still produces tokens."""
    from bee_code_interpreter_tpu.models.serving_bench import (
        run_serving_bench,
    )

    result = run_serving_bench(
        n_requests=2, max_new_tokens=8, repeats=2, inner=1, max_batch=2
    )
    assert result["tokens_per_s"] > 0
    assert result["uninstrumented_tokens_per_s"] > 0
    assert result["overhead_pct"] >= 0.0
    assert result["overhead_budget_pct"] == 5.0
    assert isinstance(result["overhead_ok"], bool)
