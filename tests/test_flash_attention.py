"""Pallas flash attention vs the O(L²) reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bee_code_interpreter_tpu.ops import flash_attention
from bee_code_interpreter_tpu.parallel.ring_attention import reference_attention


def rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype=dtype)


@pytest.mark.parametrize("causal", [True, False])
def test_matches_reference(causal):
    B, H, L, D = 2, 2, 128, 32
    q, k, v = (rand((B, H, L, D), i) for i in range(3))
    out = flash_attention(q, k, v, causal, None, 64, 64)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_unaligned_length_padding():
    B, H, L, D = 1, 2, 100, 16  # not a multiple of the block
    q, k, v = (rand((B, H, L, D), i) for i in range(3))
    out = flash_attention(q, k, v, True, None, 64, 64)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_grad_matches_reference():
    B, H, L, D = 1, 1, 64, 16
    q, k, v = (rand((B, H, L, D), i) for i in range(3))

    def loss(q, k, v):
        return (flash_attention(q, k, v, True, None, 32, 32) ** 2).sum()

    def ref_loss(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, rg in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg), atol=5e-4, rtol=5e-4)


def test_bf16_forward():
    B, H, L, D = 1, 2, 128, 32
    q, k, v = (rand((B, H, L, D), i, jnp.bfloat16) for i in range(3))
    out = flash_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref, dtype=np.float32),
        atol=3e-2, rtol=3e-2,
    )


def test_jit_compiles():
    B, H, L, D = 1, 1, 64, 16
    q, k, v = (rand((B, H, L, D), i) for i in range(3))
    out = jax.jit(lambda a, b, c: flash_attention(a, b, c))(q, k, v)
    assert out.shape == (B, H, L, D)


def test_pallas_bwd_matches_blockwise_oracle():
    # The hand-written Pallas backward vs the retained jax-level blockwise
    # recompute (same lse, same math, independent implementation).
    import functools

    from bee_code_interpreter_tpu.ops.flash_attention import (
        _attention_bwd_blockwise,
        _flash_bwd_pallas,
        _flash_fwd,
    )

    B, H, L, D = 2, 3, 192, 64  # L not a multiple of the 128 block
    keys = jax.random.split(jax.random.PRNGKey(7), 4)
    q, k, v, do = (
        jax.random.normal(kk, (B * H, L, D), dtype=jnp.float32) for kk in keys
    )
    for causal in (True, False):
        sm_scale = D**-0.5
        o4, lse = _flash_fwd(
            q.reshape(B, H, L, D), k.reshape(B, H, L, D), v.reshape(B, H, L, D),
            causal, sm_scale, 128, 128, True,
        )
        o = o4.reshape(B * H, L, D)
        got = _flash_bwd_pallas(
            q, k, v, o, lse, do, causal, sm_scale, 128, 128, True, H, H
        )
        want = _attention_bwd_blockwise(q, k, v, o, lse, do, causal, sm_scale, 128)
        for g, w, name in zip(got, want, ("dq", "dk", "dv")):
            assert jnp.allclose(g, w, atol=2e-4, rtol=2e-4), (causal, name)


def test_grad_bf16_matches_reference():
    # bf16 end-to-end grads vs the dense reference attention at bf16 —
    # the VERDICT-requested grad-equivalence pin for the Pallas backward.
    from bee_code_interpreter_tpu.parallel.ring_attention import reference_attention

    B, H, L, D = 1, 2, 256, 64
    keys = jax.random.split(jax.random.PRNGKey(3), 3)
    q, k, v = (
        jax.random.normal(kk, (B, H, L, D), dtype=jnp.bfloat16) for kk in keys
    )

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, True).astype(jnp.float32).sum()

    def loss_ref(q, k, v):
        return reference_attention(q, k, v, causal=True).astype(jnp.float32).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, ("dq", "dk", "dv")):
        diff = jnp.max(jnp.abs(gf.astype(jnp.float32) - gr.astype(jnp.float32)))
        assert diff < 0.1, (name, float(diff))  # bf16 resolution over L=256 sums


def test_cross_attention_bwd_different_kv_length():
    # Lq != Lk exercises the padded-row/column masking in both kernels.
    B, H, Lq, Lk, D = 1, 2, 100, 160, 64
    keys = jax.random.split(jax.random.PRNGKey(5), 4)
    q = jax.random.normal(keys[0], (B, H, Lq, D))
    k = jax.random.normal(keys[1], (B, H, Lk, D))
    v = jax.random.normal(keys[2], (B, H, Lk, D))

    def loss(q, k, v):
        return flash_attention(q, k, v, False).sum()

    from bee_code_interpreter_tpu.parallel.ring_attention import reference_attention

    def loss_ref(q, k, v):
        return reference_attention(q, k, v, causal=False).sum()

    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, ("dq", "dk", "dv")):
        assert jnp.allclose(g, w, atol=1e-4, rtol=1e-4), name


@pytest.mark.parametrize("causal", [True, False])
def test_mismatched_block_sizes_visit_all_keys(causal):
    # Regression (ADVICE r2): L=384 with block_q=1024, block_k=256 rounded the
    # padded length to 384, silently truncating num_k to 1 — keys 256..383
    # were never visited. The padded length must be a common multiple of both
    # (clamped) block sizes.
    B, H, L, D = 1, 2, 384, 32
    q, k, v = (rand((B, H, L, D), i) for i in range(3))
    out = flash_attention(q, k, v, causal, None, 1024, 256)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_mismatched_block_sizes_grads():
    B, H, L, D = 1, 1, 384, 16
    q, k, v = (rand((B, H, L, D), i) for i in range(3))

    def loss(q, k, v):
        return (flash_attention(q, k, v, True, None, 1024, 256) ** 2).sum()

    def ref_loss(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=5e-4, rtol=5e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_gqa_forward_matches_broadcast_reference(causal):
    # Grouped-query K/V ([B, KVH, L, D], KVH < H) must equal attention against
    # the materialized jnp.repeat broadcast — the kernel index-maps KV heads
    # instead of broadcasting, so head→kv-head pairing is what's under test.
    B, H, KVH, L, D = 2, 8, 2, 192, 32
    q = rand((B, H, L, D), 0)
    k = rand((B, KVH, L, D), 1)
    v = rand((B, KVH, L, D), 2)
    out = flash_attention(q, k, v, causal)
    rep = H // KVH
    ref = reference_attention(
        q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1), causal=causal
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_gqa_grads_match_broadcast_reference():
    # dk/dv come back compact [B, KVH, L, D]: the dkdv kernel's sequential
    # grid runs over rep·q-blocks, accumulating the group's query heads in
    # VMEM. The reference gradient is the broadcast one segment-summed.
    B, H, KVH, L, D = 1, 4, 2, 160, 16
    q = rand((B, H, L, D), 3)
    k = rand((B, KVH, L, D), 4)
    v = rand((B, KVH, L, D), 5)
    rep = H // KVH

    def loss(q, k, v):
        return (flash_attention(q, k, v, True) ** 2).sum()

    def ref_loss(q, kf, vf):
        return (reference_attention(q, kf, vf, causal=True) ** 2).sum()

    dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert dk.shape == (B, KVH, L, D) and dv.shape == (B, KVH, L, D)
    dq_ref, dk_full, dv_full = jax.grad(ref_loss, argnums=(0, 1, 2))(
        q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1)
    )
    dk_ref = dk_full.reshape(B, KVH, rep, L, D).sum(axis=2)
    dv_ref = dv_full.reshape(B, KVH, rep, L, D).sum(axis=2)
    for g, w, name in zip((dq, dk, dv), (dq_ref, dk_ref, dv_ref), ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=5e-4, rtol=5e-4, err_msg=name
        )


def test_with_lse_matches_reference_logsumexp():
    from bee_code_interpreter_tpu.ops.flash_attention import (
        flash_attention_with_lse,
    )

    B, H, L, D = 1, 2, 128, 32
    q, k, v = (rand((B, H, L, D), i) for i in range(3))
    out, lse = flash_attention_with_lse(q, k, v, True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
    # reference lse of the scaled, causally-masked scores
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (D ** -0.5)
    row = jnp.arange(L)[:, None]
    col = jnp.arange(L)[None, :]
    scores = jnp.where(row >= col, scores, -jnp.inf)
    ref_lse = jax.scipy.special.logsumexp(scores, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), atol=2e-5, rtol=2e-5)


def test_with_lse_grads_through_lse_output():
    # The lse output carries REAL gradients (ring hop-merging differentiates
    # through it): a loss touching both outputs must match the dense
    # reference — this pins the delta-shift VJP trick.
    from bee_code_interpreter_tpu.ops.flash_attention import (
        flash_attention_with_lse,
    )

    B, H, L, D = 1, 2, 96, 16
    q, k, v = (rand((B, H, L, D), i + 10) for i in range(3))

    def loss(q, k, v):
        out, lse = flash_attention_with_lse(q, k, v, True)
        return (out ** 2).sum() + (jnp.sin(lse) ** 2).sum()

    def ref_loss(q, k, v):
        out = reference_attention(q, k, v, causal=True)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (D ** -0.5)
        row = jnp.arange(L)[:, None]
        col = jnp.arange(L)[None, :]
        scores = jnp.where(row >= col, scores, -jnp.inf)
        lse = jax.scipy.special.logsumexp(scores, axis=-1)
        return (out ** 2).sum() + (jnp.sin(lse) ** 2).sum()

    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=5e-4, rtol=5e-4, err_msg=name
        )
