"""Pallas flash attention vs the O(L²) reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bee_code_interpreter_tpu.ops import flash_attention
from bee_code_interpreter_tpu.parallel.ring_attention import reference_attention


def rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype=dtype)


@pytest.mark.parametrize("causal", [True, False])
def test_matches_reference(causal):
    B, H, L, D = 2, 2, 128, 32
    q, k, v = (rand((B, H, L, D), i) for i in range(3))
    out = flash_attention(q, k, v, causal, None, 64, 64)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_unaligned_length_padding():
    B, H, L, D = 1, 2, 100, 16  # not a multiple of the block
    q, k, v = (rand((B, H, L, D), i) for i in range(3))
    out = flash_attention(q, k, v, True, None, 64, 64)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_grad_matches_reference():
    B, H, L, D = 1, 1, 64, 16
    q, k, v = (rand((B, H, L, D), i) for i in range(3))

    def loss(q, k, v):
        return (flash_attention(q, k, v, True, None, 32, 32) ** 2).sum()

    def ref_loss(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, rg in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg), atol=5e-4, rtol=5e-4)


def test_bf16_forward():
    B, H, L, D = 1, 2, 128, 32
    q, k, v = (rand((B, H, L, D), i, jnp.bfloat16) for i in range(3))
    out = flash_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref, dtype=np.float32),
        atol=3e-2, rtol=3e-2,
    )


def test_jit_compiles():
    B, H, L, D = 1, 1, 64, 16
    q, k, v = (rand((B, H, L, D), i) for i in range(3))
    out = jax.jit(lambda a, b, c: flash_attention(a, b, c))(q, k, v)
    assert out.shape == (B, H, L, D)
