"""Admission control at the API edge (ISSUE 1 acceptance (c)): once the
in-flight bound plus queue is full, requests shed as HTTP 429 with
``Retry-After`` / gRPC RESOURCE_EXHAUSTED — never hang — and the shed/queue
counters are visible in /metrics. Deadline-exceeded maps to 504 /
DEADLINE_EXCEEDED."""

import asyncio

import grpc.aio
import pytest
from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_tpu.api.grpc_server import GrpcServer, service_stubs
from bee_code_interpreter_tpu.api.http_server import create_http_server
from bee_code_interpreter_tpu.proto import code_interpreter_pb2 as pb
from bee_code_interpreter_tpu.resilience import (
    AdmissionController,
    Deadline,
    DeadlineExceeded,
)
from bee_code_interpreter_tpu.services.code_executor import Result
from bee_code_interpreter_tpu.services.custom_tool_executor import CustomToolExecutor
from bee_code_interpreter_tpu.utils.metrics import Registry

pytestmark = pytest.mark.chaos


class GatedExecutor:
    """Executor whose executions block until released — lets a test hold the
    in-flight slots at a precise point."""

    def __init__(self):
        self.release = asyncio.Event()
        self.started = 0

    async def execute(self, source_code, files=None, env=None, timeout_s=None,
                      deadline=None):
        self.started += 1
        await self.release.wait()
        return Result(stdout="done\n", stderr="", exit_code=0, files={})


def make_app(executor, admission, metrics, request_deadline_s=30.0, analyzer=None):
    return create_http_server(
        code_executor=executor,
        custom_tool_executor=CustomToolExecutor(code_executor=executor),
        metrics=metrics,
        admission=admission,
        request_deadline_s=request_deadline_s,
        analyzer=analyzer,
    )


async def test_http_sheds_429_with_retry_after_once_full():
    metrics = Registry()
    gated = GatedExecutor()
    admission = AdmissionController(
        max_in_flight=1, max_queue=1, retry_after_s=7.0, metrics=metrics
    )
    client = TestClient(TestServer(make_app(gated, admission, metrics)))
    await client.start_server()
    try:
        body = {"source_code": "print(1)"}
        t1 = asyncio.create_task(client.post("/v1/execute", json=body))
        while gated.started < 1:
            await asyncio.sleep(0.01)  # t1 holds the in-flight slot
        t2 = asyncio.create_task(client.post("/v1/execute", json=body))
        while admission.queue_depth < 1:
            await asyncio.sleep(0.01)  # t2 is queued

        # Third request: in-flight + queue full -> shed immediately.
        resp = await client.post("/v1/execute", json=body)
        assert resp.status == 429
        assert resp.headers["Retry-After"] == "7"
        assert "overloaded" in (await resp.json())["detail"]

        # Counters visible on /metrics while the congestion is live.
        text = await (await client.get("/metrics")).text()
        assert 'bci_admission_shed_total{reason="queue_full"} 1' in text
        assert "bci_admission_in_flight 1" in text
        assert "bci_admission_queue_depth 1" in text

        # The held and queued requests complete normally once released:
        # shedding shed *only* the overflow.
        gated.release.set()
        r1, r2 = await asyncio.gather(t1, t2)
        assert r1.status == 200 and r2.status == 200
        assert (await r1.json())["stdout"] == "done\n"
    finally:
        await client.close()


async def test_http_queued_request_sheds_at_deadline_never_hangs():
    metrics = Registry()
    gated = GatedExecutor()  # never released while we measure
    admission = AdmissionController(
        max_in_flight=1, max_queue=8, retry_after_s=2.0, metrics=metrics
    )
    client = TestClient(
        TestServer(make_app(gated, admission, metrics, request_deadline_s=0.2))
    )
    await client.start_server()
    try:
        body = {"source_code": "print(1)"}
        t1 = asyncio.create_task(client.post("/v1/execute", json=body))
        while gated.started < 1:
            await asyncio.sleep(0.01)
        # Queued behind a stuck request: must come back 429 at its deadline,
        # not hang for as long as the stuck request does.
        resp = await asyncio.wait_for(
            client.post("/v1/execute", json=body), timeout=2.0
        )
        assert resp.status == 429
        assert 'bci_admission_shed_total{reason="queue_timeout"} 1' in metrics.expose()
        gated.release.set()
        assert (await t1).status == 200
    finally:
        await client.close()


async def test_http_deadline_exceeded_maps_to_504():
    class Exceeding:
        async def execute(self, source_code, files=None, env=None,
                          timeout_s=None, deadline=None):
            raise DeadlineExceeded("execute")

    metrics = Registry()
    app = make_app(Exceeding(), admission=None, metrics=metrics)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        resp = await client.post("/v1/execute", json={"source_code": "print(1)"})
        assert resp.status == 504
        assert (await resp.json())["detail"] == "Deadline exceeded"
        assert (
            'bci_deadline_exceeded_total{transport="http"} 1' in metrics.expose()
        )
    finally:
        await client.close()


async def test_http_open_breaker_maps_to_503_with_retry_after():
    from bee_code_interpreter_tpu.resilience import BreakerOpenError

    class Open:
        async def execute(self, source_code, files=None, env=None,
                          timeout_s=None, deadline=None):
            raise BreakerOpenError("k8s-spawn", 12.0)

    client = TestClient(TestServer(make_app(Open(), admission=None, metrics=Registry())))
    await client.start_server()
    try:
        resp = await client.post("/v1/execute", json={"source_code": "print(1)"})
        assert resp.status == 503
        assert resp.headers["Retry-After"] == "12"
        assert "unavailable" in (await resp.json())["detail"]
    finally:
        await client.close()


async def test_grpc_open_breaker_maps_to_unavailable():
    from bee_code_interpreter_tpu.resilience import BreakerOpenError

    class Open:
        async def execute(self, source_code, files=None, env=None,
                          timeout_s=None, deadline=None):
            raise BreakerOpenError("k8s-spawn", 12.0)

    server = GrpcServer(
        code_executor=Open(),
        custom_tool_executor=CustomToolExecutor(code_executor=Open()),
        request_deadline_s=30.0,
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stubs = service_stubs(channel)
            with pytest.raises(grpc.aio.AioRpcError) as exc:
                await stubs["Execute"](pb.ExecuteRequest(source_code="print(1)"))
            assert exc.value.code() == grpc.StatusCode.UNAVAILABLE
            assert "retry" in exc.value.details()
    finally:
        await server.stop(None)


async def test_grpc_sheds_resource_exhausted_once_full():
    gated = GatedExecutor()
    admission = AdmissionController(max_in_flight=1, max_queue=0, retry_after_s=3.0)
    server = GrpcServer(
        code_executor=gated,
        custom_tool_executor=CustomToolExecutor(code_executor=gated),
        admission=admission,
        request_deadline_s=30.0,
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stubs = service_stubs(channel)
            req = pb.ExecuteRequest(source_code="print(1)")

            async def first_call():
                return await stubs["Execute"](req)

            t1 = asyncio.create_task(first_call())
            while gated.started < 1:
                await asyncio.sleep(0.01)
            with pytest.raises(grpc.aio.AioRpcError) as exc:
                await stubs["Execute"](req)
            assert exc.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
            assert "retry" in exc.value.details()
            gated.release.set()
            resp = await t1
            assert resp.stdout == "done\n"
    finally:
        await server.stop(None)


async def test_grpc_deadline_exceeded_status():
    class Exceeding:
        async def execute(self, source_code, files=None, env=None,
                          timeout_s=None, deadline=None):
            raise DeadlineExceeded("execute")

    server = GrpcServer(
        code_executor=Exceeding(),
        custom_tool_executor=CustomToolExecutor(code_executor=Exceeding()),
        request_deadline_s=30.0,
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stubs = service_stubs(channel)
            with pytest.raises(grpc.aio.AioRpcError) as exc:
                await stubs["Execute"](pb.ExecuteRequest(source_code="print(1)"))
            assert exc.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
    finally:
        await server.stop(None)


async def test_grpc_client_deadline_caps_the_edge_deadline():
    captured = {}

    class Capturing:
        async def execute(self, source_code, files=None, env=None,
                          timeout_s=None, deadline=None):
            captured["deadline"] = deadline
            return Result(stdout="", stderr="", exit_code=0, files={})

    server = GrpcServer(
        code_executor=Capturing(),
        custom_tool_executor=CustomToolExecutor(code_executor=Capturing()),
        request_deadline_s=300.0,
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stubs = service_stubs(channel)
            await stubs["Execute"](
                pb.ExecuteRequest(source_code="print(1)"), timeout=5.0
            )
        deadline: Deadline = captured["deadline"]
        assert deadline is not None
        # budget = min(service 300s, client 5s) -> the client's 5s wins
        # (small tolerance: time_remaining() is measured wall-clock and can
        # read a few ms over the client's requested timeout)
        assert deadline.budget_s < 6.0
    finally:
        await server.stop(None)


# ------------------------------------------------------ cost-aware lane
# (docs/analysis.md "Cost classes"): APP_ADMISSION_COST_AWARE bounds
# heavy-classified executions to a secondary lane so expensive work can
# never occupy every slot cheap interactive turns need. Off by default.

IO_HEAVY_SOURCE = 'open("/tmp/bci-heavy-probe")\n'  # classifies io_heavy


async def test_heavy_lane_is_a_noop_by_default():
    admission = AdmissionController(max_in_flight=4)
    async with admission.heavy_lane("install_heavy"):
        assert admission.heavy_in_flight == 0  # not even counted


async def test_heavy_lane_bounds_heavy_classes_only():
    from bee_code_interpreter_tpu.resilience import AdmissionRejected

    admission = AdmissionController(
        max_in_flight=4, cost_aware=True, heavy_max_in_flight=1
    )
    async with admission.heavy_lane("io_heavy"):
        assert admission.heavy_in_flight == 1
        # cheap work is never heavy-gated, even at the bound
        async with admission.heavy_lane("cheap"):
            pass
        with pytest.raises(AdmissionRejected) as e:
            async with admission.heavy_lane("install_heavy"):
                raise AssertionError("must shed before entering")
        assert e.value.reason == "heavy_lane"
    assert admission.heavy_in_flight == 0  # slot returned


async def test_http_cost_aware_sheds_heavy_burst_keeps_cheap():
    from bee_code_interpreter_tpu.analysis import WorkloadAnalyzer

    metrics = Registry()
    gated = GatedExecutor()
    admission = AdmissionController(
        max_in_flight=4,
        max_queue=4,
        retry_after_s=3.0,
        metrics=metrics,
        cost_aware=True,
        heavy_max_in_flight=1,
    )
    app = make_app(gated, admission, metrics, analyzer=WorkloadAnalyzer())
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        heavy = {"source_code": IO_HEAVY_SOURCE}
        t1 = asyncio.create_task(client.post("/v1/execute", json=heavy))
        while gated.started < 1:
            await asyncio.sleep(0.01)  # t1 holds the one heavy slot

        # Second heavy request: heavy lane full -> shed as the ordinary
        # 429 contract, while plain admission still has 3 free slots.
        resp = await client.post("/v1/execute", json=heavy)
        assert resp.status == 429
        assert resp.headers["Retry-After"] == "3"
        assert (
            'bci_admission_shed_total{reason="heavy_lane"} 1'
            in metrics.expose()
        )
        assert "bci_admission_heavy_in_flight 1" in metrics.expose()

        # Cheap work sails past the saturated heavy lane.
        t2 = asyncio.create_task(
            client.post("/v1/execute", json={"source_code": "print(1)"})
        )
        while gated.started < 2:
            await asyncio.sleep(0.01)

        gated.release.set()
        r1, r2 = await asyncio.gather(t1, t2)
        assert r1.status == 200 and r2.status == 200
        assert (await r1.json())["analysis"]["cost_class"] == "io_heavy"
    finally:
        await client.close()


async def test_grpc_cost_aware_sheds_heavy_as_resource_exhausted():
    from bee_code_interpreter_tpu.analysis import WorkloadAnalyzer

    gated = GatedExecutor()
    admission = AdmissionController(
        max_in_flight=4, cost_aware=True, heavy_max_in_flight=1
    )
    server = GrpcServer(
        code_executor=gated,
        custom_tool_executor=CustomToolExecutor(code_executor=gated),
        admission=admission,
        analyzer=WorkloadAnalyzer(),
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stubs = service_stubs(channel)
            t1 = asyncio.ensure_future(
                stubs["Execute"](pb.ExecuteRequest(source_code=IO_HEAVY_SOURCE))
            )
            while gated.started < 1:
                await asyncio.sleep(0.01)
            try:
                await stubs["Execute"](
                    pb.ExecuteRequest(source_code=IO_HEAVY_SOURCE)
                )
            except grpc.aio.AioRpcError as e:
                assert e.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
                assert "heavy_lane" in e.details()
            else:
                raise AssertionError("expected RESOURCE_EXHAUSTED")
            gated.release.set()
            resp = await t1
            assert resp.stdout == "done\n"
    finally:
        await server.stop(None)
