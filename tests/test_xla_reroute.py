"""numpy→XLA reroute: creation on-ramp, device stickiness, graceful fallback.

Design constraint under test: the numpy namespace's *ufunc objects are never
replaced* (ml_dtypes/jax compatibility); big arrays enter the device world at
creation or via non-ufunc reductions, then ufunc chains ride
TpuArray.__array_ufunc__."""

import numpy as np
import pytest

from bee_code_interpreter_tpu.runtime import xla_reroute
from bee_code_interpreter_tpu.runtime.xla_reroute import TpuArray


@pytest.fixture(autouse=True)
def small_threshold(monkeypatch):
    # keep tests fast: reroute anything >= 1024 elements (the threshold is
    # re-read from the env per call — the warm-path opt-out contract)
    monkeypatch.setenv("BCI_XLA_REROUTE_MIN_ELEMS", "1024")
    monkeypatch.delenv("BCI_XLA_REROUTE", raising=False)
    xla_reroute.install(np)
    yield


def big(n=64):
    return np.random.rand(n, n)  # 4096 elems >= threshold -> TpuArray


def test_ufuncs_never_proxied():
    # the ml_dtypes constraint: ufunc objects in the numpy namespace stay pristine
    for name in ("add", "multiply", "square", "sqrt", "exp", "matmul"):
        assert isinstance(getattr(np, name), np.ufunc), name


def test_small_arrays_stay_numpy():
    a = np.random.rand(4, 4)
    assert isinstance(a, np.ndarray)
    assert isinstance(np.matmul(a, a), np.ndarray)
    assert isinstance(np.sum(a), np.floating)


def test_creation_onramp_random():
    a = big()
    assert isinstance(a, TpuArray)


def test_creation_onramp_zeros_ones():
    assert isinstance(np.zeros((64, 64)), TpuArray)
    assert isinstance(np.ones(2048), TpuArray)
    assert isinstance(np.arange(5), np.ndarray)  # small stays host


def test_matmul_on_device():
    a = big()
    out = np.matmul(a, a)
    assert isinstance(out, TpuArray)
    host = np.asarray(a)
    np.testing.assert_allclose(np.asarray(out), host @ host, rtol=1e-4)


def test_chained_ufuncs_stay_on_device():
    x = big()
    squared = np.square(x)  # real ufunc -> __array_ufunc__ -> jnp
    assert isinstance(squared, TpuArray)
    total = np.sum(squared)  # proxied reduction
    assert isinstance(total, TpuArray)
    host = np.asarray(x)
    assert float(total) == pytest.approx(float((host * host).sum()), rel=1e-4)


def test_benchmark_numpy_payload():
    # the reference benchmark payload (examples/benchmark-numpy.py:19-29):
    # rand -> square -> sum, end-to-end on device
    x = np.random.rand(4096)
    assert isinstance(x, TpuArray)
    result = np.sum(np.square(x))
    assert isinstance(result, TpuArray)
    assert float(result) / 4096 == pytest.approx(1 / 3, abs=0.05)


def test_reduction_proxy_onramps_plain_ndarray():
    host = np.asarray(big())  # plain ndarray above threshold
    total = np.sum(host)
    assert isinstance(total, TpuArray)


def test_einsum_and_dot_proxies():
    a, b = big(), big()
    out = np.einsum("ij,jk->ik", a, b)
    assert isinstance(out, TpuArray)
    out2 = np.dot(np.asarray(a), np.asarray(b))
    assert isinstance(out2, TpuArray)


def test_arithmetic_dunders():
    a, b = big(), big()
    c = (a + b) * 2 - b / 3
    assert isinstance(c, TpuArray)
    d = a @ b
    assert isinstance(d, TpuArray)
    assert d.shape == (64, 64)


def test_mixed_tpu_and_numpy_operands():
    a = big()
    host = np.full((64, 64), 1.0)
    host = np.asarray(host)
    out = a + host
    assert isinstance(out, TpuArray)
    out2 = host + a  # reflected: numpy defers via __array_ufunc__/__array_priority__
    assert isinstance(out2, TpuArray)


def test_graceful_fallback_to_host():
    a = big()
    host = np.asarray(a)
    assert isinstance(host, np.ndarray)
    assert host.shape == (64, 64)
    assert float(host[0, 0]) == pytest.approx(float(a[0, 0].item()), rel=1e-5)


def test_reductions_methods_and_indexing():
    a = big()
    assert a.sum().item() == pytest.approx(float(np.asarray(a).sum()), rel=1e-4)
    assert a[:2, :3].shape == (2, 3)
    assert a.T.shape == (64, 64)
    assert a.reshape(-1).shape == (64 * 64,)
    assert len(a) == 64


def test_array_function_dispatch():
    a = big()
    out = np.percentile(a, 50)
    assert 0 <= float(out) <= 1
    stacked = np.stack([a, a])
    assert stacked.shape == (2, 64, 64)


def test_jax_importable_after_install():
    # the exact failure mode that motivated the no-ufunc-proxy design
    import importlib

    import jax

    importlib.reload(jax.numpy) if False else None
    assert jax.numpy.add(1, 2) == 3


def test_install_idempotent():
    assert xla_reroute.install(np)
    assert xla_reroute.install(np)
    assert isinstance(np.sum, xla_reroute._EntryProxy)
    assert not isinstance(np.sum.__wrapped__, xla_reroute._EntryProxy)


def test_disable_via_env(monkeypatch):
    monkeypatch.setenv("BCI_XLA_REROUTE", "0")
    import types

    fake = types.ModuleType("fake_numpy")
    fake.sum = np.sum
    assert not xla_reroute.install(fake)


def test_array_api_device_probe():
    # scipy's array-api-compat reads .device on results and feeds it back into
    # asarray(..., device=...); numpy 2.x ndarrays report "cpu".
    a = big()
    assert a.device == "cpu"
    assert a.to_device("cpu") is a
    with pytest.raises(ValueError):
        a.to_device("tpu:0")


def test_unknown_ufunc_falls_back_to_host():
    # ufuncs with no jax.numpy equivalent (scipy.special et al.) must run on
    # host views rather than returning NotImplemented — numpy defers to
    # TpuArray's higher __array_priority__, so bailing poisons the expression.
    scipy_special = pytest.importorskip("scipy.special")
    a = big()
    out = scipy_special.erf(a)
    assert isinstance(out, np.ndarray)
    assert out.shape == (64, 64)


def test_ufunc_reduce_falls_back_to_host():
    # np.add.reduce(tpu_array) dispatches __array_ufunc__ with method="reduce";
    # no jnp lookup happens for non-__call__ methods, so this exercises the
    # host-fallback branch directly on a device array.
    a = big()
    total = np.add.reduce(a.reshape(-1))
    assert isinstance(total, np.floating)
    assert float(total) == pytest.approx(float(a.sum()), rel=1e-4)


def test_ufunc_at_refuses_device_target():
    # In-place scatter on a device array must fail loudly, not write to (or
    # through) a host view of the buffer.
    a = big()
    with pytest.raises(TypeError):
        np.add.at(a, [0], 1.0)


def test_scalar_renders_like_numpy():
    # 0-d results print as plain scalars (pandas cells call str/format/repr).
    s = big().mean()
    assert "TpuArray" not in str(s)
    assert "TpuArray" not in repr(s)
    assert float(f"{s:.6f}") == pytest.approx(s.item(), abs=1e-5)


# --- round-2 hardened contract: call-time opt-out, watchdog, uninstall ------
# Round-1 failure shape (BENCH_r01.json): a warm sandbox installed the proxies
# before the request env existed, so BCI_XLA_REROUTE=0 was silently ignored
# and the first big array hung on a blocking backend init. These pin the fix.


def test_calltime_optout_entry_and_creation(monkeypatch):
    # proxies are installed, then the env flips: every subsequent call must
    # stay on host numpy (install-time-only checking is the round-1 bug)
    monkeypatch.setenv("BCI_XLA_REROUTE", "0")
    host = np.asarray(np.random.rand(64, 64))
    assert isinstance(np.matmul(host, host), np.ndarray)
    assert isinstance(np.sum(host), np.floating)
    assert isinstance(np.zeros((64, 64)), np.ndarray)
    assert isinstance(np.random.rand(64, 64), np.ndarray)


def test_min_elems_reread_from_env(monkeypatch):
    monkeypatch.setenv("BCI_XLA_REROUTE_MIN_ELEMS", str(1 << 60))
    assert isinstance(np.random.rand(64, 64), np.ndarray)
    monkeypatch.setenv("BCI_XLA_REROUTE_MIN_ELEMS", "16")
    assert isinstance(np.random.rand(8, 8), TpuArray)


def test_uninstall_restores_numpy():
    assert getattr(np, "__bci_xla_rerouted__", False)
    xla_reroute.uninstall(np)
    try:
        assert not np.__bci_xla_rerouted__
        for name in xla_reroute.ENTRY_POINTS + xla_reroute.CREATION_FUNCS:
            fn = getattr(np, name, None)
            assert not isinstance(
                fn, (xla_reroute._EntryProxy, xla_reroute._CreationProxy)
            ), name
        assert isinstance(np.random.rand(64, 64), np.ndarray)
    finally:
        xla_reroute.install(np)


def test_backend_init_watchdog_falls_back(monkeypatch):
    # a backend whose init blocks (accelerator tunnel plugin) must degrade to
    # host numpy within BCI_XLA_INIT_TIMEOUT_S, not hang the user's script
    import time

    import jax

    monkeypatch.setattr(xla_reroute, "_backend_state", None)
    monkeypatch.setenv("BCI_XLA_INIT_TIMEOUT_S", "0.2")
    monkeypatch.setattr(jax, "devices", lambda *a, **k: time.sleep(60))
    try:
        t0 = time.monotonic()
        host = np.asarray(np.random.rand(64, 64))
        out = np.matmul(host, host)
        elapsed = time.monotonic() - t0
        assert isinstance(out, np.ndarray)
        assert elapsed < 10, elapsed
        assert xla_reroute._backend_state is False
        # sticky: later calls skip the probe entirely and stay host-side
        assert isinstance(np.matmul(host, host), np.ndarray)
    finally:
        monkeypatch.undo()
        xla_reroute._backend_state = None


def test_backend_probe_success_is_cached(monkeypatch):
    monkeypatch.setattr(xla_reroute, "_backend_state", None)
    try:
        assert xla_reroute._backend_ok() is True
        assert xla_reroute._backend_state is True
    finally:
        xla_reroute._backend_state = True
