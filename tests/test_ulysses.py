"""Ulysses all-to-all sequence parallelism vs the dense reference.

Runs on the virtual 8-device CPU mesh (tests/conftest.py). The exchange is
exact — unlike a blockwise approximation there is no tolerance relaxation
beyond dtype rounding.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bee_code_interpreter_tpu.parallel.mesh import make_mesh
from bee_code_interpreter_tpu.parallel.ring_attention import reference_attention
from bee_code_interpreter_tpu.parallel.ulysses import ulysses_attention_sharded


def rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype=dtype)


@pytest.mark.parametrize("causal", [True, False])
def test_matches_reference(causal):
    mesh = make_mesh({"sp": 4})
    B, H, L, D = 2, 4, 64, 16
    q, k, v = (rand((B, H, L, D), i) for i in range(3))
    out = ulysses_attention_sharded(mesh, q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window", [4, 24, 100])
def test_window_matches_reference(window):
    # Sliding window through Ulysses: after the sequence gather, global
    # positions == local positions, so the ordinary window mask is exact.
    mesh = make_mesh({"sp": 4})
    B, H, L, D = 1, 4, 64, 16
    q, k, v = (rand((B, H, L, D), i + 80) for i in range(3))
    out = ulysses_attention_sharded(mesh, q, k, v, causal=True, window=window)
    ref = reference_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_sharded_entry_use_flash_disables_vma_check():
    # On TPU the local attention is the Pallas kernel, which cannot lower
    # under shard_map's vma checker — use_flash=True must build the
    # shard_map with check_vma=False and still be exact (ADVICE r3 medium:
    # without the flag the standalone entry failed only on real hardware).
    mesh = make_mesh({"sp": 4})
    B, H, L, D = 2, 4, 64, 16
    q, k, v = (rand((B, H, L, D), i) for i in range(3))
    out = ulysses_attention_sharded(mesh, q, k, v, causal=True, use_flash=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_gqa_compact_kv():
    # KVH divides sp: the all-to-alls carry the compact KV (no broadcast).
    mesh = make_mesh({"sp": 4})
    B, H, KVH, L, D = 1, 8, 4, 64, 16
    q = rand((B, H, L, D), 0)
    k = rand((B, KVH, L, D), 1)
    v = rand((B, KVH, L, D), 2)
    out = ulysses_attention_sharded(mesh, q, k, v, causal=True)
    rep = H // KVH
    ref = reference_attention(
        q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1), causal=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_gqa_fewer_kv_heads_than_sp():
    # KVH < sp: broadcast-up fallback inside the exchange.
    mesh = make_mesh({"sp": 4})
    B, H, KVH, L, D = 1, 4, 2, 32, 8
    q = rand((B, H, L, D), 3)
    k = rand((B, KVH, L, D), 4)
    v = rand((B, KVH, L, D), 5)
    out = ulysses_attention_sharded(mesh, q, k, v, causal=True)
    rep = H // KVH
    ref = reference_attention(
        q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1), causal=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_grad_flows():
    mesh = make_mesh({"sp": 2})

    def loss(q, k, v):
        return (ulysses_attention_sharded(mesh, q, k, v) ** 2).sum()

    def ref_loss(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    B, H, L, D = 1, 2, 32, 8
    q, k, v = (rand((B, H, L, D), i) for i in range(3))
    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=5e-4, rtol=5e-4, err_msg=name
        )


def test_heads_must_divide_sp():
    mesh = make_mesh({"sp": 4})
    q, k, v = (rand((1, 2, 32, 8), i) for i in range(3))  # 2 heads, sp=4
    with pytest.raises(ValueError, match="must divide n_heads"):
        ulysses_attention_sharded(mesh, q, k, v)


def test_transformer_forward_ulysses_matches_ring():
    # The model-level switch: same params, same tokens, sp mesh — the two
    # sequence-parallel strategies must produce the same logits.
    import dataclasses

    from bee_code_interpreter_tpu.models.transformer import (
        TransformerConfig,
        forward,
        init_params,
        shard_params,
    )

    base = dataclasses.replace(
        TransformerConfig.tiny(), dtype=jnp.float32, n_kv_heads=2
    )
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    params = shard_params(init_params(base, jax.random.PRNGKey(0)), base, mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, base.vocab_size)

    ring = forward(params, tokens, base, mesh)
    uly_cfg = dataclasses.replace(base, sp_attention="ulysses")
    uly = forward(params, tokens, uly_cfg, mesh)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(uly), atol=2e-4, rtol=2e-4
    )


def test_gqa_partial_lcm_broadcast():
    # KVH=2, sp=4, H=8: K/V broadcast to lcm(2,4)=4 heads (1 per device),
    # NOT all the way to 8 — group-major pairing must survive.
    mesh = make_mesh({"sp": 4})
    B, H, KVH, L, D = 1, 8, 2, 64, 16
    q = rand((B, H, L, D), 6)
    k = rand((B, KVH, L, D), 7)
    v = rand((B, KVH, L, D), 8)
    out = ulysses_attention_sharded(mesh, q, k, v, causal=True)
    rep = H // KVH
    ref = reference_attention(
        q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1), causal=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
