"""Deterministic fault-injection harness (not a test module).

Scripts failures into the cluster seam the fakes already provide, so every
retry, breaker transition, fallback route, and shed path is exercised by
fast tier-1 tests — no real cluster, no randomness, no sleeps longer than
the deadline under test:

- ``FaultPlan`` holds per-operation FIFO scripts of behaviors. Operations:
  ``pod_create`` / ``pod_wait`` / ``pod_ip`` (control plane, consumed by
  ``ChaosKubectl``) and ``upload`` / ``execute`` / ``download`` (data plane,
  consumed by the ``FakeExecutorPods`` fault middleware). Each incoming call
  pops exactly one behavior — or ``None`` (healthy) when the script is empty
  — so a test's timeline is fully determined by what it scripted.
- Behaviors: ``Ok`` (explicit no-op placeholder, e.g. "worker 0 fine, worker
  1 fails"), ``Fail`` (control-plane error), ``Hang(seconds)`` (slow
  apiserver / slow sandbox), ``HttpStatus(status)`` (5xx/4xx data-plane
  answer), ``Reset`` (TCP connection torn down mid-request), ``NoIP``
  (pod-IP flap: the pod exists but status.podIP is empty for one poll).
- ``ManualClock`` drives ``Deadline`` and ``CircuitBreaker`` time
  deterministically (cooldowns advance by assignment, not sleeping).

Used by tests/test_chaos_kubernetes.py, tests/test_kubernetes_code_executor.py
and scripts/chaos_smoke.py (see docs/resilience.md).
"""

from __future__ import annotations

import asyncio
from collections import defaultdict, deque
from dataclasses import dataclass

from aiohttp import web

from tests.fakes import FakeKubectl


# ------------------------------------------------------------------ behaviors


@dataclass
class Ok:
    """Explicit healthy placeholder (consumes one script slot)."""


@dataclass
class Fail:
    message: str = "injected failure"


@dataclass
class Hang:
    seconds: float = 10.0


@dataclass
class HttpStatus:
    status: int = 503


@dataclass
class Reset:
    """Close the TCP connection without sending a response."""


@dataclass
class NoIP:
    """Pod-IP flap: one ``kubectl get`` sees the pod without status.podIP."""


@dataclass
class DieMidExecute:
    """The pod dies mid-``/execute``: the in-flight connection is reset AND
    the pod's server is torn down, so any later probe of the same sandbox
    fails too (a ``Reset`` only drops the one connection). Drives the
    replay acceptance: the executor must observe a transient failure,
    journal ``reaped{reason=died_mid_execute}``, and replay on a fresh
    sandbox."""


def block_loop(seconds: float) -> float:
    """Synchronously hog the event loop for ~``seconds`` (busy-wait, not
    ``time.sleep``, so a patched/virtual clock can't skip it): the
    deterministic way to make the loop-lag monitor observe a real stall
    (docs/observability.md "Event-loop health"). Returns the actual time
    burned."""
    import time

    start = time.perf_counter()
    while time.perf_counter() - start < seconds:
        pass
    return time.perf_counter() - start


class ManualClock:
    """Deterministic monotonic clock for Deadline/CircuitBreaker tests."""

    def __init__(self, start: float = 0.0) -> None:
        self.now = start

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


class FaultPlan:
    """Per-operation FIFO scripts of behaviors; ``log`` records consumption."""

    def __init__(self) -> None:
        self._scripts: dict[str, deque] = defaultdict(deque)
        self.log: list[tuple[str, object]] = []

    def script(self, op: str, *behaviors) -> "FaultPlan":
        self._scripts[op].extend(behaviors)
        return self

    def take(self, op: str):
        queue = self._scripts[op]
        behavior = queue.popleft() if queue else None
        if behavior is not None:
            self.log.append((op, behavior))
        return behavior

    def pending(self, op: str) -> int:
        return len(self._scripts[op])

    # Named fault kinds for the proactive-resilience suites (the supervisor
    # / replay / watchdog acceptance criteria name them by these verbs).

    def die_mid_execute(self) -> "FaultPlan":
        """Script one pod death mid-``/execute`` (connection reset + the
        pod's server torn down)."""
        return self.script("execute", DieMidExecute())

    def hang_execute(self, seconds: float = 30.0) -> "FaultPlan":
        """Script one ``/execute`` that hangs (stuck sandbox: the watchdog's
        prey — kill it before the hang outlives the hard cap)."""
        return self.script("execute", Hang(seconds))

    async def apply_http(self, op: str, request, kill=None) -> web.Response | None:
        """Data-plane injection hook (FakeExecutorPods middleware). Returns a
        response to short-circuit with, or None to proceed to the handler.
        ``kill`` is the middleware-provided sync callable that schedules the
        serving pod's teardown, anchored against GC by the caller (consumed
        by ``DieMidExecute``)."""
        behavior = self.take(op)
        if behavior is None or isinstance(behavior, Ok):
            return None
        if isinstance(behavior, Hang):
            await asyncio.sleep(behavior.seconds)
            return None
        if isinstance(behavior, HttpStatus):
            return web.Response(status=behavior.status, text="chaos: injected status")
        if isinstance(behavior, Reset):
            if request.transport is not None:
                request.transport.close()
            # The transport is gone; aiohttp drops the connection and the
            # client observes a reset rather than this response.
            return web.Response(status=500, text="chaos: reset")
        if isinstance(behavior, DieMidExecute):
            if request.transport is not None:
                request.transport.close()
            if kill is not None:
                # Scheduled, not awaited: the pod teardown must not block
                # this (already-dead) handler from unwinding.
                kill()
            return web.Response(status=500, text="chaos: pod died")
        raise AssertionError(f"behavior {behavior!r} not valid for op {op!r}")


class ChaosKubectl(FakeKubectl):
    """FakeKubectl with scripted control-plane faults: create errors, spawn
    hangs (slow readiness), and pod-IP flaps."""

    def __init__(self, pods, faults: FaultPlan) -> None:
        super().__init__(pods)
        self.faults = faults

    async def _control_plane(self, op: str) -> None:
        behavior = self.faults.take(op)
        if behavior is None or isinstance(behavior, Ok):
            return
        if isinstance(behavior, Hang):
            await asyncio.sleep(behavior.seconds)
            return
        if isinstance(behavior, Fail):
            raise RuntimeError(f"chaos {op}: {behavior.message}")
        raise AssertionError(f"behavior {behavior!r} not valid for op {op!r}")

    async def create(self, *args, _input=None, **kwargs):
        await self._control_plane("pod_create")
        return await super().create(*args, _input=_input, **kwargs)

    async def wait(self, target, **kwargs):
        await self._control_plane("pod_wait")
        return await super().wait(target, **kwargs)

    async def get(self, kind, name, **kwargs):
        pod = await super().get(kind, name, **kwargs)
        behavior = self.faults.take("pod_ip")
        if isinstance(behavior, NoIP):
            return {**pod, "status": {**pod["status"], "podIP": None}}
        return pod
