"""Every BASELINE.json config has a test that drives it (or its closest
CI-runnable variant) through the real service path (VERDICT r2 weak #3: three
of the five configs were never executed by anything).

| # | BASELINE config                                   | here                       |
|---|---------------------------------------------------|----------------------------|
| 1 | benchmark-numpy dense matmul via /v1/execute      | downsized payload, HTTP    |
| 2 | torch ResNet-50 inference                         | dep-guess + tiny-CNN run   |
| 3 | JAX MNIST training, 8 chips                       | pmap-psum smoke (full e2e: |
|   |                                                   | test_local_code_executor)  |
| 4 | transformers BERT-base inference                  | tiny random FlaxBert run   |
| 5 | Llama multi-host inference via execute-custom-tool| sharded transformer forward|
|   |                                                   | on the virtual 8-dev mesh  |

TPU-hardware scale (v5e-64 shapes) is validated separately by
scripts/validate-llama3-topology.py; these tests pin the *service path* for
each workload shape on the virtual CPU mesh.
"""

import json
from pathlib import Path

import pytest
from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_tpu.runtime.dep_guess import guess_dependencies

from tests.http_helpers import post_execute  # http_app fixture: conftest

REPO = Path(__file__).resolve().parent.parent
EXAMPLES = REPO / "examples"


@pytest.fixture
def local_executor(local_executor_factory):
    # Overrides conftest's 30s-capped executor (same as
    # tests/test_example_payloads.py): the BERT/CNN payloads jit-compile
    # real models, and on a loaded box — e.g. this file running while
    # another pytest process hogs the cores — compile alone can blow 30s.
    return local_executor_factory(execution_timeout_s=600.0)


async def test_config1_benchmark_numpy_via_execute(http_app):
    # The headline payload, downsized 100x so CI measures the path, not the
    # host (bench.py runs it at full size against the real chip).
    source = (EXAMPLES / "benchmark-numpy.py").read_text().replace("10**8", "10**6")
    body = await post_execute(http_app, {"source_code": source})
    assert body["exit_code"] == 0, body["stderr"]
    assert "sum(square(rand(1000000)))" in body["stdout"]


async def test_config2_resnet50_torch_path(http_app):
    # (a) the real payload's deps resolve: torch/torch_xla are pinned in the
    # image (never reinstalled), torchvision auto-installs
    source = (EXAMPLES / "resnet50-torch-xla.py").read_text()
    assert guess_dependencies(source) == ["torchvision"]
    # (b) a tiny ResNet-style torch forward runs through the service path
    pytest.importorskip("torch")
    tiny = (
        "import torch\n"
        "import torch.nn as nn\n"
        "net = nn.Sequential(\n"
        "    nn.Conv2d(3, 8, 3, stride=2, padding=1), nn.BatchNorm2d(8),\n"
        "    nn.ReLU(), nn.AdaptiveAvgPool2d(1), nn.Flatten(), nn.Linear(8, 10),\n"
        ").eval()\n"
        "with torch.no_grad():\n"
        "    out = net(torch.randn(2, 3, 32, 32))\n"
        "print('shape', tuple(out.shape))\n"
    )
    body = await post_execute(http_app, {"source_code": tiny})
    assert body["exit_code"] == 0, body["stderr"]
    assert "shape (2, 10)" in body["stdout"]


async def test_config3_jax_8chip_collective_smoke(http_app):
    # The sandbox sees the 8-device mesh and a cross-device psum works (the
    # full MNIST dp-training e2e on this path lives in
    # tests/test_local_code_executor.py::test_mnist_dp_8chip_example_end_to_end)
    source = (
        "import jax, jax.numpy as jnp\n"
        "n = jax.local_device_count()\n"
        "total = jax.pmap(lambda x: jax.lax.psum(x, 'i'), axis_name='i')(\n"
        "    jnp.ones(n))\n"
        "print('devices', n, 'psum', int(total[0]))\n"
    )
    body = await post_execute(
        http_app, {"source_code": source, "env": {"BCI_XLA_REROUTE": "0"}}
    )
    assert body["exit_code"] == 0, body["stderr"]
    assert "devices 8 psum 8" in body["stdout"]


async def test_config4_bert_inference_path(http_app):
    # The real payload downloads bert-base weights (no egress in CI); the
    # CI variant runs a randomly initialized tiny FlaxBert through the same
    # transformers API on the service path. Dep-guess: transformers resolves
    # (preinstalled in the image).
    pytest.importorskip("transformers")
    source = (EXAMPLES / "bert-inference.py").read_text()
    assert guess_dependencies(source) == ["transformers"]
    tiny = (
        "import numpy as np\n"
        "from transformers import BertConfig, FlaxBertModel\n"
        "config = BertConfig(vocab_size=99, hidden_size=32, num_hidden_layers=2,\n"
        "                    num_attention_heads=2, intermediate_size=64,\n"
        "                    max_position_embeddings=64)\n"
        "model = FlaxBertModel(config)\n"
        "batch = {'input_ids': np.ones((2, 16), dtype='int32'),\n"
        "         'attention_mask': np.ones((2, 16), dtype='int32')}\n"
        "out = model(**batch)\n"
        "print('hidden', out.last_hidden_state.shape)\n"
    )
    body = await post_execute(
        http_app, {"source_code": tiny, "env": {"BCI_XLA_REROUTE": "0"}}
    )
    assert body["exit_code"] == 0, body["stderr"]
    assert "hidden (2, 16, 32)" in body["stdout"]


async def test_config5_sharded_llama_forward_via_execute_custom_tool(http_app):
    # BASELINE config #5 is Llama-3-8B inference on a v5e-64 slice through
    # /v1/execute-custom-tool. CI approximation: the custom-tool path runs a
    # tp+dp-sharded models/transformer forward over the virtual 8-device mesh
    # — custom-tool wrapper + sharded compute combined, which no other test
    # covered. (8B-at-scale lowering: scripts/validate-llama3-topology.py.)
    tool = (
        "def sharded_llama_forward(seed: int) -> list:\n"
        "    import jax\n"
        "    import numpy as np\n"
        "    from bee_code_interpreter_tpu.models.transformer import (\n"
        "        Transformer, TransformerConfig)\n"
        "    from bee_code_interpreter_tpu.parallel import make_mesh\n"
        "    mesh = make_mesh({'dp': 2, 'tp': 4}, devices=jax.devices()[:8])\n"
        "    model = Transformer(TransformerConfig.tiny(), mesh)\n"
        "    params = model.init(jax.random.PRNGKey(seed))\n"
        "    tokens = np.zeros((2, 16), dtype='int32')\n"
        "    logits = model.apply(params, tokens)\n"
        "    assert bool(jax.numpy.isfinite(logits).all())\n"
        "    return [int(jax.device_count()), *logits.shape]\n"
    )
    client = TestClient(TestServer(http_app))
    await client.start_server()
    try:
        resp = await client.post(
            "/v1/execute-custom-tool",
            json={
                "tool_source_code": tool,
                "tool_input_json": json.dumps({"seed": 0}),
                "env": {"PYTHONPATH": str(REPO), "BCI_XLA_REROUTE": "0"},
            },
        )
        assert resp.status == 200, await resp.text()
        body = await resp.json()
        assert json.loads(body["tool_output_json"]) == [8, 2, 16, 256]
    finally:
        await client.close()
