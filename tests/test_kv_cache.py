"""Int8 KV cache: quantization primitives + decode-path accuracy."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.ops.kv_cache import dequantize, quantize


def test_quantize_roundtrip_error_bound():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 64)) * 3.0
    q, s = quantize(x)
    assert q.dtype == jnp.int8
    assert s.shape == (4, 8, 1)
    back = dequantize(q, s)
    # symmetric absmax: error per element ≤ absmax/127 (half a step after round)
    bound = np.asarray(jnp.max(jnp.abs(x), axis=-1, keepdims=True)) / 127.0
    err = np.abs(np.asarray(back) - np.asarray(x))
    assert (err <= bound * 0.5 + 1e-6).all()


def test_quantize_zero_rows():
    x = jnp.zeros((2, 3, 16))
    q, s = quantize(x)
    assert (np.asarray(q) == 0).all()
    assert (np.asarray(dequantize(q, s)) == 0).all()


def test_int8_cache_decode_agrees_where_margin_allows():
    # Greedy tokens from the int8 cache must match the bf16 cache wherever
    # the bf16 argmax margin (top1 − top2 logit) exceeds the quantization
    # drift — with an untrained random model many positions are near-ties,
    # so an unconditional token-equality pin would be testing noise. The
    # margin-gated positions are exactly where a trained model lives.
    config = dataclasses.replace(
        T.TransformerConfig.tiny(), dtype=jnp.float32, n_kv_heads=2
    )
    int8_config = dataclasses.replace(config, kv_cache_dtype="int8")
    params = T.init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 13), 0, config.vocab_size)
    L_pre = 7

    _, (k_pre, v_pre) = T.forward(params, tokens[:, :L_pre], config, return_kv=True)
    cache16 = T.init_decode_cache(config, 2, 13, k_pre, v_pre)
    cache8 = T.init_decode_cache(int8_config, 2, 13, k_pre, v_pre)

    checked = 0
    for pos in range(L_pre, 13):
        lg16, cache16 = T.decode_step(
            params, tokens[:, pos : pos + 1], jnp.int32(pos), cache16, config
        )
        lg8, cache8 = T.decode_step(
            params, tokens[:, pos : pos + 1], jnp.int32(pos), cache8, int8_config
        )
        top2 = jnp.sort(lg16[:, 0], axis=-1)[:, -2:]
        margin = np.asarray(top2[:, 1] - top2[:, 0])  # [B]
        same = np.asarray(
            jnp.argmax(lg16[:, 0], -1) == jnp.argmax(lg8[:, 0], -1)
        )
        for b in range(2):
            if margin[b] > 0.5:  # far above the measured int8 drift (~0.2)
                assert same[b], (pos, b, float(margin[b]))
                checked += 1
    assert checked > 0  # the gate must have exercised something


def test_int8_cache_logit_drift_bounded():
    config = dataclasses.replace(T.TransformerConfig.tiny(), dtype=jnp.float32)
    int8_config = dataclasses.replace(config, kv_cache_dtype="int8")
    params = T.init_params(config, jax.random.PRNGKey(2))
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 12), 0, config.vocab_size)
    L_pre = 8

    _, (k_pre, v_pre) = T.forward(params, tokens[:, :L_pre], config, return_kv=True)
    logits_full = T.forward(params, tokens, config)

    for cfg in (config, int8_config):
        cache = T.init_decode_cache(cfg, 1, 12, k_pre, v_pre)
        worst = 0.0
        for pos in range(L_pre, 12):
            step_logits, cache = T.decode_step(
                params, tokens[:, pos : pos + 1], jnp.int32(pos), cache, cfg
            )
            worst = max(
                worst,
                float(jnp.max(jnp.abs(step_logits[:, 0] - logits_full[:, pos]))),
            )
        # bf16 path is (near-)exact; int8 drift stays small relative to
        # logit scale (~10 for the tiny model)
        limit = 1e-3 if cfg.kv_cache_dtype == "bf16" else 0.2
        assert worst < limit, (cfg.kv_cache_dtype, worst)


def test_int8_cache_is_actually_int8():
    config = dataclasses.replace(
        T.TransformerConfig.tiny(), kv_cache_dtype="int8"
    )
    k_pre = jnp.ones((config.n_layers, 1, config.kv_heads, 4, config.head_dim))
    cache = T.init_decode_cache(config, 1, 8, k_pre, k_pre)
    assert cache["k"].dtype == jnp.int8
    assert cache["v"].dtype == jnp.int8
    assert cache["k_s"].dtype == jnp.float32
