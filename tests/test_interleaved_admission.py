"""Sarathi-style interleaved chunked admission: a long prompt's prefill
advances one window per step while other rows keep decoding — the result
must be IDENTICAL to the blocking admission (same window program family),
and the scheduler bookkeeping (occupancy, pages, cancel, snapshot) must
treat a prefilling row as occupied-but-not-active."""

import dataclasses
import pickle

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models.engine import Engine
from bee_code_interpreter_tpu.models.serving import (
    ContinuousBatcher,
    SamplingParams,
)

CFG = dataclasses.replace(
    T.TransformerConfig.tiny(), dtype=jnp.float32, n_kv_heads=2
)
PARAMS = T.init_params(CFG, jax.random.PRNGKey(0))
LONG = [int(x) for x in np.random.default_rng(7).integers(0, 200, 21)]
SHORT = [5, 3, 7, 2]


def make(**kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("n_pages", 32)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_pages_per_seq", 8)
    return ContinuousBatcher(PARAMS, CFG, **kw)


def solo(prompt, n, sampling=None):
    b = make(max_batch=1)
    r = b.submit(prompt, n, sampling=sampling)
    b.run_to_completion()
    return b.result(r)


def test_interleaved_matches_blocking_and_solo():
    want = solo(LONG, 5)
    b = make()
    r = b.submit(LONG, 5, interleave_admission=8)
    assert b.results[r] == []  # nothing yet: no model ran at submit
    assert b.stats["prefilling_rows"] == 1
    b.run_to_completion()
    assert b.result(r) == want
    assert b.finish_reason(r) == "length"
    assert b.stats["prefilling_rows"] == 0


def test_interleaved_sampled_with_logprobs_matches_blocking():
    sp = SamplingParams(temperature=0.7, top_k=30, seed=11, logprobs=True)
    blocking = make()
    rb = blocking.submit(LONG, 5, sampling=sp)
    blocking.run_to_completion()
    b = make()
    r = b.submit(LONG, 5, sampling=sp, interleave_admission=4)
    b.run_to_completion()
    assert b.result(r) == blocking.result(rb)
    # logprobs agree to reduction-order ulps: the window family and the
    # one-shot prefill are numerically distinct programs (tokens are
    # pinned exact; the drift lives below sampling resolution)
    assert b.result_logprobs(r) == pytest.approx(
        blocking.result_logprobs(rb), rel=1e-4
    )


def test_other_rows_keep_decoding_during_admission():
    """The point of interleaving: a short request decodes a token on every
    step while the long prompt's prefill is still windowing in."""
    b = make()
    r_short = b.submit(SHORT, 8)
    r_long = b.submit(LONG, 4, interleave_admission=4)  # 6 windows of 4
    produced = []
    while b.prefill_state:
        before = len(b.results[r_short])
        b.step()
        produced.append(len(b.results[r_short]) - before)
    # every interleave step also advanced the short row (until it retired)
    assert sum(produced) > 0
    assert all(d == 1 for d in produced[: min(len(produced), 7)])
    b.run_to_completion()
    assert b.result(r_short) == solo(SHORT, 8)
    assert b.result(r_long) == solo(LONG, 4)


def test_interleaved_registers_prefix_pages():
    b = make(prefix_cache=True)
    r1 = b.submit(LONG, 4, interleave_admission=4)
    b.run_to_completion()
    r2 = b.submit(LONG, 4)  # repeat: must hit the pages the windows wrote
    b.run_to_completion()
    assert b.prefix_stats["hits"] >= 1
    assert b.result(r1) == b.result(r2) == solo(LONG, 4)


def test_cancel_mid_prefill_releases_everything():
    b = make()
    r = b.submit(LONG, 4, interleave_admission=4)
    b.step()  # one window in
    assert b.prefill_state
    b.cancel(r)
    assert not b.prefill_state
    assert b.finish_reason(r) == "cancelled"
    assert b.result(r) == []
    b.run_to_completion()
    assert int(b.stats["held_pages"]) == 0
    # the freed row and pages admit a fresh request
    r2 = b.submit(LONG, 4)
    b.run_to_completion()
    assert b.result(r2) == solo(LONG, 4)


def test_snapshot_mid_prefill_resumes_exactly():
    want = solo(LONG, 5)
    a = make()
    r = a.submit(LONG, 5, interleave_admission=4)
    a.step(); a.step()  # part-way through the windows
    snap = pickle.dumps(a.state_dict())
    del a
    b = make()
    b.load_state_dict(pickle.loads(snap))
    assert b.prefill_state  # resumed mid-admission
    b.run_to_completion()
    assert b.result(r) == want


def test_speculative_interleaved_matches_solo():
    draft_cfg = dataclasses.replace(CFG, n_layers=1)
    draft_params = T.init_params(draft_cfg, jax.random.PRNGKey(1))
    want_b = make(
        draft_params=draft_params, draft_config=draft_cfg, gamma=3,
    )
    rb = want_b.submit(LONG, 5)
    want_b.run_to_completion()
    b = make(draft_params=draft_params, draft_config=draft_cfg, gamma=3)
    r = b.submit(LONG, 5, interleave_admission=4)
    b.run_to_completion()
    assert b.result(r) == want_b.result(rb) == solo(LONG, 5)


def test_width_validated_and_row_occupancy():
    b = make()
    with pytest.raises(ValueError, match="interleave_admission"):
        b.submit(LONG, 4, interleave_admission=3)  # not a page multiple
    r1 = b.submit(LONG, 4, interleave_admission=4)
    r2 = b.submit(SHORT, 4)  # second row
    with pytest.raises(RuntimeError, match="no free batch row"):
        b.submit(SHORT, 4)  # prefilling row counts as occupied
    b.run_to_completion()
    assert b.result(r1) == solo(LONG, 4)
    assert b.result(r2) == solo(SHORT, 4)


def test_engine_passthrough():
    want = solo(LONG, 4)
    eng = Engine(make())
    t = eng.submit(LONG, 4, interleave_admission=4)
    eng.run_to_completion()
    assert eng.result(t) == want


def test_engine_validates_width_eagerly():
    eng = Engine(make())
    with pytest.raises(ValueError, match="interleave_admission"):
        eng.submit(LONG, 4, interleave_admission=3)  # fails AT submit


def test_interleaved_speculative_preserves_shared_draft_prefix():
    """Zeroing discipline under speculative + prefix cache: an interleaved
    admission hitting a shared prefix must zero only its FRESH draft
    pages — wiping the matched pages would corrupt the draft K/V a
    decoding batch-mate is reading right now."""
    draft_cfg = dataclasses.replace(CFG, n_layers=1)
    draft_params = T.init_params(draft_cfg, jax.random.PRNGKey(1))

    def spec(**kw):
        return make(draft_params=draft_params, draft_config=draft_cfg,
                    gamma=3, prefix_cache=True, **kw)

    solo_b = spec(max_batch=1)
    rs = solo_b.submit(LONG, 6)
    solo_b.run_to_completion()
    want = solo_b.result(rs)

    b = spec()
    r1 = b.submit(LONG, 6)  # registers the prefix pages
    b.step()  # r1 mid-decode, sharing its prefix
    r2 = b.submit(LONG, 6, interleave_admission=4)  # hits the prefix
    b.run_to_completion()
    assert b.result(r1) == want  # batch-mate untouched by the admission
    assert b.result(r2) == want


def test_bad_seed_releases_pages_even_at_activation():
    """A first-token failure AFTER the pages were allocated (e.g. a bad
    rng seed surfacing at activation) must release them — on the blocking
    path by propagating post-release, on the interleaved path by failing
    the ticket without crashing the step loop."""
    b = make()
    with pytest.raises(ValueError):
        b.submit(SHORT, 4, sampling=SamplingParams(seed=-1))
    assert int(b.stats["held_pages"]) == 0  # blocking path released
    r = b.submit(SHORT, 4, sampling=SamplingParams(seed=-1),
                 interleave_admission=4)
    b.run_to_completion()  # the failure lands on the ticket, loop survives
    assert b.finish_reason(r) == "error"
    assert "ValueError" in b.request_error(r)
    assert int(b.stats["held_pages"]) == 0
