"""Proactive resilience (ISSUE 4): pool supervisor self-healing, stuck-
execution watchdog, transparent replay, hedged execution, and the drain
controller. Faults are scripted through tests/chaos.py against the in-repo
fake cluster — no real cluster, no unbounded sleeps."""

import asyncio
import time

import pytest

from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.resilience import (
    Deadline,
    DrainController,
    HedgingExecutor,
    InflightRegistry,
    PoolSupervisor,
    SandboxTransientError,
)
from bee_code_interpreter_tpu.services.kubernetes_code_executor import (
    KubernetesCodeExecutor,
)
from bee_code_interpreter_tpu.utils.metrics import Registry
from tests.chaos import ChaosKubectl, FaultPlan, Hang, ManualClock
from tests.fakes import FakeExecutorPods, FakeKubectl

pytestmark = pytest.mark.chaos


@pytest.fixture
def faults():
    return FaultPlan()


@pytest.fixture
def pods(tmp_path, faults):
    return FakeExecutorPods(tmp_path / "pods", faults=faults)


def make_executor(pods, storage, faults, *, metrics=None, **config_overrides):
    overrides = dict(
        executor_backend="kubernetes",
        executor_port=pods.port,
        executor_pod_queue_target_length=0,
        pod_ready_timeout_s=5,
        executor_retry_attempts=1,
        executor_retry_wait_min_s=0.01,
        executor_retry_wait_max_s=0.05,
        health_probe_timeout_s=0.5,
    )
    overrides.update(config_overrides)
    return KubernetesCodeExecutor(
        kubectl=ChaosKubectl(pods, faults),
        storage=storage,
        config=Config(**overrides),
        metrics=metrics,
        ip_poll_interval_s=0.02,
    )


# ------------------------------------------------------- supervisor sweeps


async def test_supervisor_reaps_unhealthy_idle_and_replenishes(
    pods, storage, faults
):
    # Two warm groups; one dies in place (preemption). The sweep must reap
    # it as unhealthy_idle and refill the pool back to target — BEFORE any
    # request has to discover the corpse at checkout time.
    metrics = Registry()
    executor = make_executor(
        pods, storage, faults,
        metrics=metrics, executor_pod_queue_target_length=2,
    )
    supervisor = PoolSupervisor(executor, interval_s=60, metrics=metrics)
    try:
        await executor.fill_executor_pod_queue()
        assert executor.pool_ready_count == 2
        victim = executor._queue[0]
        for ip in victim.pod_ips:
            await pods.stop_pod(ip)

        swept = await supervisor.sweep_once()
        assert swept["reaped"] == 1
        for _ in range(200):  # refill is kicked fire-and-forget
            if executor.pool_ready_count == 2:
                break
            await asyncio.sleep(0.01)
        assert executor.pool_ready_count == 2  # replenished to target
        reaped = [
            e for e in executor.journal.events() if e["state"] == "reaped"
        ]
        assert [e["pod"] for e in reaped] == [victim.name]
        assert reaped[0]["reason"] == "unhealthy_idle"
        text = metrics.expose()
        assert 'bci_pod_reaped_total{reason="unhealthy_idle"} 1' in text
        assert "bci_supervisor_probe_seconds_count 1" in text
        assert supervisor.snapshot()["reaped"] == 1
    finally:
        await pods.close()


async def test_supervisor_healthy_sweep_reaps_nothing(pods, storage, faults):
    executor = make_executor(
        pods, storage, faults, executor_pod_queue_target_length=1
    )
    supervisor = PoolSupervisor(executor, interval_s=60)
    try:
        await executor.fill_executor_pod_queue()
        swept = await supervisor.sweep_once()
        assert swept == {
            "reaped": 0,
            "watchdog_killed": 0,
            "duration_s": swept["duration_s"],
        }
        assert executor.pool_ready_count == 1
    finally:
        await pods.close()


async def test_supervisor_background_loop_sweeps_on_cadence(
    pods, storage, faults
):
    executor = make_executor(pods, storage, faults)
    supervisor = PoolSupervisor(executor, interval_s=0.05)
    try:
        supervisor.start()
        assert supervisor.running
        for _ in range(100):
            if supervisor.sweeps_total >= 2:
                break
            await asyncio.sleep(0.02)
        assert supervisor.sweeps_total >= 2
        assert supervisor.snapshot()["last_sweep_age_s"] is not None
    finally:
        await supervisor.stop()
        assert not supervisor.running
        await pods.close()


# ------------------------------------------------------------- watchdog


async def test_watchdog_kills_stuck_execution_as_transient(
    pods, storage, faults
):
    # The sandbox wedges mid-/execute. The watchdog must kill it: the
    # request fails TRANSIENT (replayable), the journal says hung_execute,
    # and the in-flight slot is freed.
    executor = make_executor(pods, storage, faults)
    supervisor = PoolSupervisor(
        executor, interval_s=60, execute_hard_cap_s=0.2
    )
    faults.hang_execute(30.0)
    try:
        request = asyncio.ensure_future(executor.execute("print(1)"))
        await asyncio.sleep(0.3)
        assert len(executor.inflight) == 1
        swept = await supervisor.sweep_once()
        assert swept["watchdog_killed"] == 1
        with pytest.raises(SandboxTransientError, match="watchdog"):
            await request
        assert len(executor.inflight) == 0  # slot freed
        reaped = [
            e for e in executor.journal.events() if e["state"] == "reaped"
        ]
        assert reaped and reaped[0]["reason"] == "hung_execute"
    finally:
        await pods.close()


async def test_watchdog_spares_executions_under_the_cap(pods, storage, faults):
    executor = make_executor(pods, storage, faults)
    supervisor = PoolSupervisor(
        executor, interval_s=60, execute_hard_cap_s=30.0
    )
    try:
        request = asyncio.ensure_future(executor.execute("print('fine')"))
        await asyncio.sleep(0)
        swept = await supervisor.sweep_once()
        assert swept["watchdog_killed"] == 0
        result = await request
        assert result.stdout == "fine\n"
    finally:
        await pods.close()


def test_inflight_registry_converts_only_watchdog_cancels():
    async def go():
        registry = InflightRegistry()

        async def tracked(trigger: asyncio.Event):
            with registry.track("box-1", kill=None):
                trigger.set()
                await asyncio.sleep(30)

        # Watchdog kill -> SandboxTransientError with the hung_execute reason.
        trigger = asyncio.Event()
        task = asyncio.ensure_future(tracked(trigger))
        await trigger.wait()
        (entry,) = registry.overdue(0.0)
        registry.kill(entry)
        with pytest.raises(SandboxTransientError, match="watchdog") as exc:
            await task
        assert exc.value.reap_reason == "hung_execute"
        assert len(registry) == 0

        # A plain cancel (deadline, client gone) passes through untouched.
        trigger = asyncio.Event()
        task = asyncio.ensure_future(tracked(trigger))
        await trigger.wait()
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
        assert len(registry) == 0

    asyncio.run(go())


# ------------------------------------------------------------------ replay


async def test_pod_death_mid_execute_is_replayed_transparently(
    pods, storage, faults
):
    # Acceptance: a pod killed mid-execute still returns a successful
    # ExecuteResponse via replay within the request deadline, with
    # reaped{reason=died_mid_execute} + bci_execution_replays_total
    # observable.
    metrics = Registry()
    executor = make_executor(pods, storage, faults, metrics=metrics)
    hedged = HedgingExecutor(executor, replay_max=1, metrics=metrics)
    faults.die_mid_execute()
    try:
        result = await hedged.execute(
            "print(21 * 2)", deadline=Deadline.after(30)
        )
        assert result.stdout == "42\n"
        text = metrics.expose()
        assert "bci_execution_replays_total 1" in text
        assert 'bci_pod_reaped_total{reason="died_mid_execute"} 1' in text
        reaped = [
            e for e in executor.journal.events() if e["state"] == "reaped"
        ]
        assert reaped and reaped[0]["reason"] == "died_mid_execute"
    finally:
        await pods.close()


async def test_replay_budget_and_deadline_bound_it(pods, storage, faults):
    # Every attempt dies: the replay budget must bound the attempts and the
    # original transient error must surface (no infinite heal loop).
    metrics = Registry()
    executor = make_executor(pods, storage, faults, metrics=metrics)
    hedged = HedgingExecutor(executor, replay_max=2, metrics=metrics)
    for _ in range(3):
        faults.die_mid_execute()
    try:
        with pytest.raises(SandboxTransientError):
            await hedged.execute("print(1)")
        assert "bci_execution_replays_total 2" in metrics.expose()
        # an expired deadline stops replays immediately
        faults.die_mid_execute()
        clock = ManualClock()
        expired = Deadline.after(5.0, clock=clock)
        clock.advance(10.0)
        with pytest.raises(Exception):
            await hedged.execute("print(1)", deadline=expired)
    finally:
        await pods.close()


# ------------------------------------------------------------------ hedging


async def test_hedged_execution_second_sandbox_wins(pods, storage, faults):
    # The first attempt's /execute hangs; after the hedge delay a second
    # sandbox runs the same request and wins. The loser is cancelled and
    # its pod journaled out.
    metrics = Registry()
    executor = make_executor(pods, storage, faults, metrics=metrics)
    hedged = HedgingExecutor(
        executor, replay_max=0, hedge_delay_s=0.1, metrics=metrics
    )
    faults.hang_execute(30.0)  # first /execute call hangs; second is healthy
    try:
        result = await hedged.execute("print('win')")
        assert result.stdout == "win\n"
        assert 'bci_hedge_total{outcome="hedge_won"} 1' in metrics.expose()
        await asyncio.sleep(0.05)  # let the loser's cancellation land
        released = [
            e for e in executor.journal.events() if e["state"] == "released"
        ]
        assert any(e["reason"] == "cancelled" for e in released)
    finally:
        await pods.close()


async def test_near_expired_deadline_does_not_reap_healthy_warm_pool(
    pods, storage, faults
):
    # Review regression: a request arriving with ~no budget left must fail
    # DeadlineExceeded — NOT instant-timeout the health probe and destroy
    # every healthy warm group on its way out.
    from bee_code_interpreter_tpu.resilience import DeadlineExceeded

    executor = make_executor(
        pods, storage, faults, executor_pod_queue_target_length=2
    )
    try:
        await executor.fill_executor_pod_queue()
        assert executor.pool_ready_count == 2
        clock = ManualClock()
        nearly_gone = Deadline.after(10.0, clock=clock)
        clock.advance(9.999)  # ~1ms of budget left
        with pytest.raises(DeadlineExceeded):
            async with executor.executor_pod_group(deadline=nearly_gone):
                pass
        assert executor.pool_ready_count == 2  # pool untouched
        assert not any(
            e["state"] == "reaped" for e in executor.journal.events()
        )
    finally:
        await pods.close()


async def test_refill_racing_aclose_deletes_spawned_group(
    pods, storage, faults
):
    # Review regression: a refill in flight when aclose() lands must delete
    # its freshly spawned pods, never append them to the dead pool (leaked
    # cluster pods after every graceful restart).
    executor = make_executor(
        pods, storage, faults, executor_pod_queue_target_length=1
    )
    try:
        refill = asyncio.ensure_future(executor.fill_executor_pod_queue())
        await asyncio.sleep(0)  # refill reserves its spawn slot
        await executor.aclose()
        await refill
        assert executor.pool_ready_count == 0
        await asyncio.sleep(0.05)  # let fire-and-forget deletes land
        kubectl = executor._kubectl
        created = {m["metadata"]["name"] for m in kubectl.created_manifests}
        assert created and created <= set(kubectl.deleted)
        reasons = [
            e.get("reason")
            for e in executor.journal.events()
            if e["state"] == "reaped"
        ]
        assert reasons == ["shutdown"]
    finally:
        await pods.close()


async def test_hedge_suppressed_when_deadline_cannot_cover_the_delay(
    pods, storage, faults
):
    # Review regression: remaining <= hedge_delay must mean "never hedge",
    # not "hedge immediately" — a second attempt bounded by the same
    # expiring deadline can never win and just burns a warm sandbox.
    metrics = Registry()
    executor = make_executor(pods, storage, faults, metrics=metrics)
    hedged = HedgingExecutor(
        executor, replay_max=0, hedge_delay_s=60.0, metrics=metrics
    )
    try:
        result = await hedged.execute(
            "print('one sandbox')", deadline=Deadline.after(30.0)
        )
        assert result.stdout == "one sandbox\n"
        assert len(pods.execute_counts) == 1  # exactly one pod executed
        assert "bci_hedge_total{" not in metrics.expose()
    finally:
        await pods.close()


async def test_fast_primary_never_hedges(pods, storage, faults):
    metrics = Registry()
    executor = make_executor(pods, storage, faults, metrics=metrics)
    hedged = HedgingExecutor(
        executor, replay_max=0, hedge_delay_s=5.0, metrics=metrics
    )
    try:
        result = await hedged.execute("print('solo')")
        assert result.stdout == "solo\n"
        assert "bci_hedge_total{" not in metrics.expose()  # no hedge launched
        assert len(pods.execute_counts) == 1  # exactly one pod executed
    finally:
        await pods.close()


# -------------------------------------------------------------------- drain


async def test_drain_controller_tracks_and_waits():
    metrics = Registry()
    drain = DrainController(metrics=metrics, retry_after_s=2.0)
    assert not drain.draining

    release = asyncio.Event()

    async def request():
        with drain.track():
            await release.wait()

    task = asyncio.ensure_future(request())
    await asyncio.sleep(0)
    assert drain.in_flight == 1
    assert "bci_drain_inflight 1" in metrics.expose()

    flipped: list[str] = []
    drain.on_drain(lambda: flipped.append("health"))
    drain.begin()
    drain.begin()  # idempotent
    assert drain.draining
    assert flipped == ["health"]
    # a late-registered callback (server built after the drain began) fires
    drain.on_drain(lambda: flipped.append("late"))
    assert flipped == ["health", "late"]

    # grace expires while the request is still running
    assert await drain.wait_idle(0.05) is False
    release.set()
    await task
    assert await drain.wait_idle(1.0) is True
    assert drain.in_flight == 0


async def test_supervisor_stops_refilling_during_drain(pods, storage, faults):
    drain = DrainController()
    executor = make_executor(
        pods, storage, faults, executor_pod_queue_target_length=2
    )
    supervisor = PoolSupervisor(executor, interval_s=60, drain=drain)
    try:
        drain.begin()
        await supervisor.sweep_once()
        await asyncio.sleep(0.1)  # would be enough for a (wrongly) kicked refill
        assert executor.pool_ready_count == 0  # no refill while draining
    finally:
        await pods.close()


def test_health_check_draining_classification():
    """Satellite: the liveness probe must map a draining service to its own
    exit code (3), distinct from dead (2), off the verbose healthz body."""
    from bee_code_interpreter_tpu.health_check import DRAINING_EXIT, is_draining

    assert DRAINING_EXIT == 3
    assert is_draining({"status": "draining", "drain_inflight": 2})
    assert not is_draining({"status": "ok"})
    assert not is_draining({})


# ------------------------------------------------- native deterministic close


async def test_native_shutdown_closes_http_client_deterministically(
    tmp_path, storage
):
    """Satellite regression: the old shutdown() scheduled _http.aclose() as
    a fire-and-forget task the closing loop could cancel before it ran;
    aclose() must leave the client closed when it returns."""
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )

    config = Config(
        executor_backend="local",
        local_workspace_root=str(tmp_path / "ws"),
        executor_pod_queue_target_length=0,
        disable_dep_install=True,
    )
    executor = NativeProcessCodeExecutor(
        storage=storage, config=config, binary="/bin/true"
    )
    assert not executor._http.is_closed
    await executor.aclose()
    assert executor._http.is_closed
    assert executor._closed


async def test_kubernetes_aclose_reaps_queue_and_closes_client(
    pods, storage, faults
):
    executor = make_executor(
        pods, storage, faults, executor_pod_queue_target_length=1
    )
    try:
        await executor.fill_executor_pod_queue()
        assert executor.pool_ready_count == 1
        await executor.aclose()
        assert executor.pool_ready_count == 0
        assert executor._http.is_closed
        reaped = [
            e for e in executor.journal.events() if e["state"] == "reaped"
        ]
        assert reaped and reaped[0]["reason"] == "shutdown"
    finally:
        await pods.close()
