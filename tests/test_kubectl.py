"""Kubectl CLI wrapper: argv construction, JSON parsing, error surfacing —
exercised against a stub `kubectl` binary."""

import json
import os
import stat

import pytest

from bee_code_interpreter_tpu.services.kubectl import Kubectl, KubectlError

STUB = """#!/bin/sh
# echoes its argv back as a JSON object; fails when first arg is "fail-me"
if [ "$1" = "fail-me" ]; then
  echo "boom" >&2
  exit 3
fi
printf '{"argv": ['
first=1
for a in "$@"; do
  [ $first -eq 1 ] || printf ', '
  printf '"%s"' "$a"
  first=0
done
printf '], "stdin": "'
if [ ! -t 0 ]; then tr -d '\\n"' ; fi
printf '"}'
"""


@pytest.fixture
def kubectl(tmp_path):
    stub = tmp_path / "kubectl"
    stub.write_text(STUB)
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    return Kubectl(kubectl_path=str(stub))


async def test_json_command_adds_output_json(kubectl):
    out = await kubectl.get("pod", "my-pod")
    assert out["argv"] == ["get", "pod", "my-pod", "--output=json"]


async def test_kwargs_become_flags(kubectl):
    out = await kubectl.wait("pod/x", for_="condition=Ready", timeout="60s")
    assert out["argv"] == ["wait", "pod/x", "--output=json", "--for=condition=Ready", "--timeout=60s"]


async def test_underscore_to_dash_in_command_and_flags(kubectl):
    out = await kubectl.delete("pod", "x", ignore_not_found="true")
    assert out["argv"][0] == "delete"
    assert "--ignore-not-found=true" in out["argv"]


async def test_stdin_manifest(kubectl):
    out = await kubectl.create("-f", "-", _input='{"kind":"Pod"}')
    assert out["stdin"] == "{kind:Pod}"


async def test_namespace_injected(tmp_path, kubectl):
    k = Kubectl(kubectl_path=kubectl._kubectl, namespace="sandbox")
    out = await k.get("pod", "p")
    assert "--namespace=sandbox" in out["argv"]


async def test_error_raises_with_stderr(kubectl):
    with pytest.raises(KubectlError) as e:
        await kubectl.fail_me()
    assert e.value.returncode == 3
    assert "boom" in e.value.stderr


async def test_non_json_command_returns_text(kubectl):
    out = await kubectl.logs("pod-x")
    assert isinstance(out, str)  # "logs" is not a JSON-output command
