"""Tier-1 await-aware concurrency lint (docs/analysis.md "Concurrency lint
rules"): the whole derived control-plane scope — api/, services/,
resilience/, observability/, sessions/, fleet/, analysis/ plus the
top-level modules — must carry ZERO unexplained violations, with every
suppression still earning its justification (a stale suppression is itself
a failure), exactly the asynclint contract.

The second half unit-tests each rule on synthetic snippets so a regression
names the broken rule; the dataflow-engine units live in
tests/test_analysis.py next to the policy consumers."""

from bee_code_interpreter_tpu.analysis.concurrencylint import (
    EXTRA_EXCLUDES,
    SUPPRESSIONS,
    lint_concurrency_paths,
    lint_concurrency_source,
)


def _rules(source: str) -> list[str]:
    return [v.rule for v in lint_concurrency_source(source)]


# ------------------------------------------------------------- the repo


def test_control_plane_has_zero_unexplained_violations():
    report = lint_concurrency_paths()
    assert report.files_scanned >= 50  # the derived scope actually resolved
    assert not report.violations, "\n" + report.summary()


def test_no_stale_suppressions():
    report = lint_concurrency_paths()
    assert not report.stale_suppressions, (
        "suppressions no longer matching any violation — delete them:\n"
        + report.summary()
    )
    used = {s for _, s in report.suppressed}
    assert used == set(SUPPRESSIONS)


def test_every_suppression_is_justified():
    for s in SUPPRESSIONS:
        assert len(s.reason.split()) >= 8, (
            f"{s.path} [{s.rule}]: a suppression needs a real justification"
        )


def test_scope_is_the_derived_control_plane():
    """The lint shares asynclint's derived-scope rule (a new subsystem is
    in scope by default) minus the extra non-event-loop excludes."""
    assert set(EXTRA_EXCLUDES) == {"proto", "runtime", "utils"}
    report = lint_concurrency_paths(packages=("analysis",), suppressions=())
    assert report.files_scanned >= 6  # analysis/ itself is linted


# ------------------------------------------- unlocked-rmw-across-await


def test_rmw_across_await_flagged():
    assert _rules(
        """
        class C:
            async def bump(self):
                n = self.count
                await self.flush()
                self.count = n + 1
        """
    ) == ["unlocked-rmw-across-await"]


def test_rmw_in_one_statement_flagged():
    # the read happens, the await suspends, THEN the store runs: the
    # written value is stale even though it is one line of code
    assert _rules(
        """
        class C:
            async def bump(self, q):
                self.total += await q.get()
        """
    ) == ["unlocked-rmw-across-await"]
    assert _rules(
        """
        class C:
            async def bump(self, q):
                self.total = self.total + await q.get()
        """
    ) == ["unlocked-rmw-across-await"]


def test_rmw_under_shared_lock_is_clean():
    assert _rules(
        """
        class C:
            async def bump(self):
                async with self._lock:
                    n = self.count
                    await self.flush()
                    self.count = n + 1
        """
    ) == []


def test_rmw_without_await_is_clean():
    # between awaits the event loop cannot interleave: plain counters are
    # atomic by construction and must not be flagged
    assert _rules(
        """
        class C:
            async def bump(self):
                self.count += 1
                n = self.count
                self.count = n + 1
        """
    ) == []


def test_rmw_write_before_await_is_clean():
    assert _rules(
        """
        class C:
            async def bump(self):
                n = self.count
                self.count = n + 1
                await self.flush()
        """
    ) == []


def test_rmw_on_module_global_flagged():
    assert _rules(
        """
        counter = 0
        async def bump(q):
            global counter
            n = counter
            await q.put(n)
            counter = n + 1
        """
    ) == ["unlocked-rmw-across-await"]


# ------------------------------------------------- lock-not-released


def test_lock_leak_on_early_return_flagged():
    assert _rules(
        """
        class C:
            async def f(self):
                await self._lock.acquire()
                if self.bad:
                    return None
                self._lock.release()
        """
    ) == ["lock-not-released"]


def test_lock_released_in_finally_is_clean():
    assert _rules(
        """
        class C:
            async def f(self):
                await self._lock.acquire()
                try:
                    return self.x
                finally:
                    self._lock.release()
        """
    ) == []


def test_async_with_lock_is_clean():
    assert _rules(
        """
        class C:
            async def f(self):
                async with self._lock:
                    return self.x
        """
    ) == []


# ------------------------------------- await-under-lock-self-deadlock


def test_self_deadlock_flagged():
    assert _rules(
        """
        class C:
            async def outer(self):
                async with self._lock:
                    await self.inner()
            async def inner(self):
                async with self._lock:
                    return 1
        """
    ) == ["await-under-lock-self-deadlock"]


def test_awaiting_lockless_method_under_lock_is_clean():
    assert _rules(
        """
        class C:
            async def outer(self):
                async with self._lock:
                    await self.inner()
            async def inner(self):
                return 1
        """
    ) == []


def test_different_locks_do_not_deadlock():
    assert _rules(
        """
        class C:
            async def outer(self):
                async with self._lock:
                    await self.inner()
            async def inner(self):
                async with self._other_lock:
                    return 1
        """
    ) == []


# ------------------------------------------------- unawaited-teardown


def test_unawaited_teardown_flagged():
    assert _rules(
        """
        class Pump:
            async def aclose(self):
                pass
        def build():
            p = Pump()
            return p
        """
    ) == ["unawaited-teardown"]


def test_awaited_teardown_is_clean():
    assert _rules(
        """
        class Pump:
            async def aclose(self):
                pass
        async def run():
            p = Pump()
            await p.aclose()
        """
    ) == []


def test_factory_named_binding_satisfies_teardown():
    # the cached_property / builder pattern: constructed inside `def pump`,
    # torn down as `ctx.pump`
    assert _rules(
        """
        class Pump:
            async def stop(self):
                pass
        class Ctx:
            def pump(self):
                p = Pump()
                return p
            async def aclose(self):
                await self.pump.stop()
        """
    ) == []


def test_never_constructed_class_not_flagged():
    # a library class nobody in the corpus instantiates makes no claim
    assert _rules(
        """
        class Exported:
            async def aclose(self):
                pass
        """
    ) == []


def test_async_with_usage_satisfies_teardown():
    assert _rules(
        """
        class Pump:
            async def aclose(self):
                pass
            async def __aenter__(self):
                return self
            async def __aexit__(self, *exc):
                await self.aclose()
        async def run():
            async with Pump() as p:
                return p
        """
    ) == []


# ------------------------------------------------- thread-loop-touch


def test_thread_target_touching_loop_flagged():
    assert _rules(
        """
        import threading
        class C:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
            def _run(self):
                self._loop.create_task(self._cb())
        """
    ) == ["thread-loop-touch"]


def test_thread_target_set_result_flagged():
    assert _rules(
        """
        import threading
        def start(fut):
            t = threading.Thread(target=worker)
            return t
        def worker(fut):
            fut.set_result(None)
        """
    ) == ["thread-loop-touch"]


def test_call_soon_threadsafe_is_clean():
    assert _rules(
        """
        import threading
        class C:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
            def _run(self):
                self._loop.call_soon_threadsafe(self._cb)
        """
    ) == []


def test_nested_def_scheduled_onto_loop_is_clean():
    # a closure handed to call_soon_threadsafe RUNS ON the loop — loop
    # calls inside it are the sanctioned pattern, not a violation
    assert _rules(
        """
        import threading
        class C:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
            def _run(self):
                def on_loop():
                    self._loop.create_task(self._cb())
                self._loop.call_soon_threadsafe(on_loop)
        """
    ) == []


def test_loop_calls_outside_thread_targets_are_clean():
    assert _rules(
        """
        import asyncio
        class C:
            def kick(self):
                self._task = asyncio.get_event_loop().create_task(self._cb())
        """
    ) == []


def test_rmw_across_two_lock_scopes_flagged():
    # two separate `async with self._lock` blocks hold the same lock NAME
    # but release it across the await between them — scope identity, not
    # name equality, is what protects an RMW (code-review regression)
    assert _rules(
        """
        class C:
            async def bump(self):
                async with self._lock:
                    n = self.count
                await self.flush()
                async with self._lock:
                    self.count = n + 1
        """
    ) == ["unlocked-rmw-across-await"]


def test_self_deadlock_via_explicit_acquire_flagged():
    # the holder side spelled `await self._lock.acquire()` + release in a
    # finally is still a held lock at the awaited call (code-review
    # regression: held_locks only saw `async with`)
    assert _rules(
        """
        class C:
            async def outer(self):
                await self._lock.acquire()
                try:
                    await self.inner()
                finally:
                    self._lock.release()
            async def inner(self):
                async with self._lock:
                    return 1
        """
    ) == ["await-under-lock-self-deadlock"]
    # released BEFORE the await: nothing held, nothing flagged
    assert _rules(
        """
        class C:
            async def outer(self):
                await self._lock.acquire()
                self._lock.release()
                await self.inner()
            async def inner(self):
                async with self._lock:
                    return 1
        """
    ) == []
