"""bench-mfu.py payload mechanics on CPU: a tiny-config variant must run
through the identical sandbox path and print both result markers (the real
run differs only in shapes and backend)."""

import asyncio
import importlib.util
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


bench = load("bench", REPO / "bench.py")
bench_mfu = load("bench_mfu", REPO / "scripts" / "bench-mfu.py")


def test_payload_is_valid_python():
    compile(bench_mfu.build_payload(), "<mfu payload>", "exec")


def test_tiny_payload_runs_end_to_end():
    tiny = dict(vocab_size=64, d_model=16, n_layers=1, n_heads=2,
                n_kv_heads=2, d_ff=32, max_seq_len=64)
    src = bench_mfu.build_payload(
        CONFIG=tiny, B=1, L=16, N_TRAIN=6, B_DEC=1, L_PROMPT=4, N_DEC=24
    )
    # chain_diff's jitter guard can legitimately trip at toy shapes on a
    # loaded box (e.g. the full suite running in parallel); mechanics
    # (payload runs, markers parse) are the point, so chains are long for
    # margin and the whole payload retries before failing.
    for attempt in range(3):
        try:
            results = asyncio.run(
                bench.run_payload_multi(
                    src, {"JAX_PLATFORMS": "cpu"}, 240.0,
                    ("RESULT_TRAIN", "RESULT_DECODE"),
                )
            )
            break
        except bench.PayloadError:
            if attempt:
                raise
    per_step_ms, tflops, n_params = results["RESULT_TRAIN"]
    assert per_step_ms > 0 and tflops > 0
    assert n_params > tiny["vocab_size"] * tiny["d_model"]
    per_tok_ms, tps = results["RESULT_DECODE"]
    assert per_tok_ms > 0 and tps > 0
