"""Seeded scheduling fuzz over the serving engine.

The unit suites pin each feature in isolation; this drives a RANDOM
interleaving of submits (mixed lengths, budgets, priorities, sampling),
steps, cancels and releases against one engine, then checks the global
contract: every request that ran to completion equals its solo decode,
cancelled tickets report 'cancelled', and the page pool balances to empty.
Seeded, so a failure is a repro, not a flake."""

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models.engine import Engine
from bee_code_interpreter_tpu.models.serving import (
    ContinuousBatcher,
    SamplingParams,
)

CFG = dataclasses.replace(
    T.TransformerConfig.tiny(), dtype=jnp.float32, n_kv_heads=2
)
PARAMS = T.init_params(CFG, jax.random.PRNGKey(0))


def solo(prompt, n, sampling=None):
    b = ContinuousBatcher(
        PARAMS, CFG, max_batch=1, n_pages=16, page_size=4,
        max_pages_per_seq=4,
    )
    r = b.submit(prompt, n, sampling=sampling)
    b.run_to_completion()
    return b.result(r)


def test_random_schedule_matches_solo_oracle():
    rng = np.random.default_rng(20260731)
    eng = Engine(
        ContinuousBatcher(
            PARAMS, CFG, max_batch=2, n_pages=16, page_size=4,
            max_pages_per_seq=4,
        ),
        max_queue=6,
    )
    live: dict[int, tuple[list[int], int, SamplingParams | None]] = {}
    cancelled: set[int] = set()
    finished: dict[int, tuple[list[int], int, SamplingParams | None]] = {}

    for op_i in range(120):
        op = rng.choice(["submit", "step", "cancel", "step", "step"])
        if op == "submit":
            prompt = [int(x) for x in rng.integers(0, 200, rng.integers(2, 8))]
            n = int(rng.integers(1, 6))
            sampling = None
            if rng.random() < 0.4:
                sampling = SamplingParams(
                    temperature=0.8, top_k=20, seed=int(rng.integers(1e6))
                )
            try:
                t = eng.submit(
                    prompt, n, sampling=sampling,
                    priority=int(rng.integers(0, 3)),
                )
            except RuntimeError:
                continue  # queue full: legal backpressure
            live[t] = (prompt, n, sampling)
        elif op == "cancel" and live and rng.random() < 0.5:
            t = int(rng.choice(list(live)))
            eng.cancel(t)
            cancelled.add(t)
            del live[t]
        else:
            eng.step()
        for t in list(live):
            if eng.is_done(t):
                finished[t] = live.pop(t)
    eng.run_to_completion()
    finished.update(live)

    # every completed request equals its solo decode (sampling included:
    # per-row seeded generators are batch-independent)
    assert len(finished) >= 10, "fuzz schedule degenerated"
    for t, (prompt, n, sampling) in finished.items():
        assert eng.result(t) == solo(prompt, n, sampling), (t, prompt)
        assert eng.finish_reason(t) == "length"
    for t in cancelled:
        assert eng.finish_reason(t) == "cancelled"
    # pool drains back to empty: no leaked pages, no stuck rows
    st = eng.stats
    assert st["active_rows"] == 0 and st["queued"] == 0
    assert st["held_pages"] == 0
