import os

from bee_code_interpreter_tpu.runtime.executor_core import (
    EXECUTION_TIMED_OUT,
    ExecutorCore,
    changed_files,
    snapshot_workspace,
)


def make_core(tmp_path, **kw):
    kw.setdefault("disable_dep_install", True)
    return ExecutorCore(workspace=tmp_path / "ws", **kw)


async def test_stdout_stderr_exit_code(tmp_path):
    core = make_core(tmp_path)
    out = await core.execute("import sys\nprint('out')\nprint('err', file=sys.stderr)\nsys.exit(3)\n")
    assert out.stdout == "out\n"
    assert out.stderr == "err\n"
    assert out.exit_code == 3
    assert out.files == []


async def test_crash_has_nonzero_exit(tmp_path):
    # examples/crash.py behavior (reference examples; SURVEY.md §2 Examples)
    out = await core_exec(tmp_path, "raise RuntimeError('boom')")
    assert out.exit_code != 0
    assert "boom" in out.stderr


async def core_exec(tmp_path, src, **kw):
    return await make_core(tmp_path).execute(src, **kw)


async def test_changed_file_detection_recursive(tmp_path):
    core = make_core(tmp_path)
    out = await core.execute(
        "import pathlib\n"
        "pathlib.Path('top.txt').write_text('x')\n"
        "pathlib.Path('sub/dir').mkdir(parents=True)\n"
        "pathlib.Path('sub/dir/nested.txt').write_text('y')\n"
    )
    assert out.files == ["/workspace/sub/dir/nested.txt", "/workspace/top.txt"]


async def test_unchanged_files_not_reported(tmp_path):
    core = make_core(tmp_path)
    (core.workspace / "old.txt").write_text("preexisting")
    out = await core.execute("print(open('old.txt').read())")
    assert out.files == []
    assert out.stdout == "preexisting\n"


async def test_env_passthrough(tmp_path):
    out = await core_exec(tmp_path, "import os\nprint(os.environ['MY_VAR'])", env={"MY_VAR": "42"})
    assert out.stdout == "42\n"


async def test_timeout(tmp_path):
    core = make_core(tmp_path, default_timeout_s=0.5)
    out = await core.execute("import time\ntime.sleep(30)")
    assert out.exit_code == -1
    assert out.stderr == EXECUTION_TIMED_OUT


async def test_tpu_topology_env_forwarded(tmp_path):
    os.environ["TPU_WORKER_ID"] = "3"
    try:
        out = await core_exec(tmp_path, "import os\nprint(os.environ.get('TPU_WORKER_ID'))")
        assert out.stdout == "3\n"
    finally:
        del os.environ["TPU_WORKER_ID"]


async def test_accelerator_env_forwarded_by_prefix(tmp_path):
    # The accelerator stack's env surface is open-ended (libtpu, pallas,
    # platform plugins); forwarding is by prefix, and unrelated host env must
    # NOT leak into the sandbox.
    os.environ["PALLAS_TEST_FLAG"] = "on"
    os.environ["LIBTPU_INIT_ARGS"] = "--xla_foo"
    os.environ["UNRELATED_SECRET"] = "nope"
    # k8s service-link shapes inside a matching prefix must NOT leak
    os.environ["TPU_PROXY_SERVICE_HOST"] = "10.0.0.5"
    os.environ["TPU_PROXY_PORT_80_TCP"] = "tcp://10.0.0.5:80"
    try:
        out = await core_exec(
            tmp_path,
            "import os\n"
            "print(os.environ.get('PALLAS_TEST_FLAG'))\n"
            "print(os.environ.get('LIBTPU_INIT_ARGS'))\n"
            "print(os.environ.get('UNRELATED_SECRET'))\n"
            "print(os.environ.get('TPU_PROXY_SERVICE_HOST'))\n"
            "print(os.environ.get('TPU_PROXY_PORT_80_TCP'))",
        )
        assert out.stdout == "on\n--xla_foo\nNone\nNone\nNone\n"
    finally:
        for key in (
            "PALLAS_TEST_FLAG",
            "LIBTPU_INIT_ARGS",
            "UNRELATED_SECRET",
            "TPU_PROXY_SERVICE_HOST",
            "TPU_PROXY_PORT_80_TCP",
        ):
            del os.environ[key]


async def test_jax_cache_dir_exported(tmp_path, monkeypatch):
    # A developer's own JAX_COMPILATION_CACHE_DIR would win over the opt-in
    # (pod env beats service config by design); clear it for determinism.
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    os.environ["APP_JAX_CACHE_DIR"] = "/shared/xla-cache"
    try:
        out = await core_exec(
            tmp_path,
            "import os\nprint(os.environ.get('JAX_COMPILATION_CACHE_DIR'))",
        )
        assert out.stdout == "/shared/xla-cache\n"
    finally:
        del os.environ["APP_JAX_CACHE_DIR"]


def test_resolve_strips_logical_prefix(tmp_path):
    core = make_core(tmp_path)
    ws = core.workspace.resolve()
    assert core.resolve("/workspace/a.txt") == ws / "a.txt"
    assert core.resolve("workspace/a.txt") == ws / "a.txt"
    assert core.resolve("b/c.txt") == ws / "b" / "c.txt"


def test_resolve_rejects_escape(tmp_path):
    core = make_core(tmp_path)
    for bad in ("/workspace/../../etc/passwd", "../outside", "/workspace/a/../../x"):
        try:
            core.resolve(bad)
        except ValueError:
            continue
        raise AssertionError(f"escape not rejected: {bad}")


def test_snapshot_diff(tmp_path):
    ws = tmp_path / "ws"
    ws.mkdir()
    (ws / "a.txt").write_text("1")
    before = snapshot_workspace(ws)
    (ws / "a.txt").write_text("22")  # size change
    (ws / "b.txt").write_text("new")
    after = snapshot_workspace(ws)
    assert changed_files(before, after) == ["a.txt", "b.txt"]


async def test_timeout_kills_grandchildren(tmp_path):
    # 3 s budget: interpreter startup alone costs ~0.6 s on hosts whose
    # sitecustomize registers an accelerator plugin; the timeout must fire
    # after the payload has written pid.txt, not during python boot.
    core = make_core(tmp_path, default_timeout_s=3.0)
    marker = "grandchild-timeout-probe"
    out = await core.execute(
        "import subprocess, sys, time\n"
        "p = subprocess.Popen([sys.executable, '-c', "
        f"'_ = \"{marker}\"; import time; time.sleep(60)'])\n"
        "open('pid.txt','w').write(str(p.pid))\n"
        "time.sleep(60)\n"
    )
    assert out.exit_code == -1
    pid = int((core.workspace / "pid.txt").read_text())
    import time
    from pathlib import Path

    def grandchild_alive() -> bool:
        # pid-identity check: with pid_max 32768 a busy host recycles pids
        # within a suite run, so a bare os.kill(pid, 0) probe can hit an
        # unrelated process and report a phantom survivor.
        try:
            cmdline = Path(f"/proc/{pid}/cmdline").read_bytes()
        except (FileNotFoundError, ProcessLookupError):
            return False
        return marker.encode() in cmdline

    for _ in range(20):  # grandchild should be gone promptly
        if not grandchild_alive():
            break
        time.sleep(0.1)
    else:
        raise AssertionError(f"grandchild {pid} survived the timeout kill")


def test_accelerator_port_vars_pass_through():
    # ADVICE round 1: libtpu/megascale topology vars share the k8s
    # service-link suffix shape; they must pass through unless the definitive
    # sibling *_SERVICE_HOST signature marks them as service links.
    from bee_code_interpreter_tpu.runtime.executor_core import _is_passthrough_env

    env = {"TPU_PROCESS_PORT": "8476", "MEGASCALE_PORT": "8080"}
    assert _is_passthrough_env("TPU_PROCESS_PORT", env)
    assert _is_passthrough_env("MEGASCALE_PORT", env)
    assert _is_passthrough_env("TPU_PROCESS_ADDRESSES", env)
    # the same key becomes a service link when k8s injected the pair
    linked = {"TPU_PROXY_SERVICE_HOST": "10.0.0.5", "TPU_PROXY_PORT": "tcp://10.0.0.5:80"}
    assert not _is_passthrough_env("TPU_PROXY_PORT", linked)
    assert not _is_passthrough_env("TPU_PROXY_PORT_80_TCP", linked)
    assert not _is_passthrough_env("TPU_PROXY_SERVICE_HOST", linked)
    # non-accelerator prefixes never pass regardless
    assert not _is_passthrough_env("FOO_PORT", {})


async def test_xonsh_shellisms_are_a_documented_delta(tmp_path):
    # Deliberate behavior difference vs the reference (executor_core.py:10-13):
    # payloads run under plain CPython, not xonsh, saving ~80 ms/exec
    # (reference server.rs:149-154 notes the cost as a TODO). Pin the exact
    # delta: xonsh-isms fail as a SyntaxError like any invalid Python, and
    # the supported escape is subprocess.
    core = ExecutorCore(tmp_path / "ws", disable_dep_install=True)

    xonshism = await core.execute('files = $(ls).split()\nprint(files)\n')
    assert xonshism.exit_code == 1
    assert "SyntaxError" in xonshism.stderr

    supported = await core.execute(
        "import subprocess\n"
        "out = subprocess.run(['echo', 'shell-works'], capture_output=True, text=True)\n"
        "print(out.stdout.strip())\n"
    )
    assert supported.exit_code == 0
    assert supported.stdout == "shell-works\n"


async def test_request_accelerator_scrub_optout(tmp_path, monkeypatch):
    # BCI_SCRUB_ACCELERATOR=1 must drop tunnel-plugin vars from the sandbox
    # env (a request can't REMOVE inherited vars any other way; without this
    # a wedged TPU tunnel turns every CPU-pinned payload into a timeout).
    monkeypatch.setenv("PALLAS_TUNNEL_TARGET", "grpc://wedged:1")
    monkeypatch.setenv("AXON_POOL_KEY", "abc")
    core = make_core(tmp_path)
    probe = (
        "import os\n"
        "print(sorted(k for k in os.environ"
        " if k.startswith(('PALLAS_', 'AXON_'))))\n"
    )
    r_default = await core.execute(probe)
    assert "PALLAS_TUNNEL_TARGET" in r_default.stdout  # passthrough by default
    r_scrubbed = await core.execute(probe, env={"BCI_SCRUB_ACCELERATOR": "1"})
    assert r_scrubbed.stdout == "[]\n"
