"""Paged KV cache: decode over a block-table-indirected page pool must be
an indexing-only change — logits equal to the contiguous decode_step for
any page placement."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.ops.paged_kv_cache import (
    alloc_paged_cache,
    paged_read,
)


def cfg(**kw):
    return dataclasses.replace(
        T.TransformerConfig.tiny(), dtype=jnp.float32, n_kv_heads=2, **kw
    )


def seed_pages(cache, k_pre, v_pre, block_table, page_size):
    """Prefill seeding THROUGH the shared primitive serving uses
    (ops/paged_kv_cache.seed_prefill), one sequence at a time — the
    equality tests pin the exact code path ContinuousBatcher.submit runs."""
    from bee_code_interpreter_tpu.ops.paged_kv_cache import seed_prefill

    L = k_pre.shape[3]
    B = k_pre.shape[1]
    n_pages = -(-L // page_size)
    for b in range(B):
        cache = seed_prefill(
            cache,
            jnp.asarray(block_table[b, :n_pages], dtype=jnp.int32),
            k_pre[:, b], v_pre[:, b],
        )
    return cache


def assert_paged_matches_contiguous(config, table="identity", *, B=2, L=11,
                                    ps=4, P=6, steps=4):
    """THE paged-vs-contiguous equality loop (single copy): seed both
    caches from one prefill, decode ``steps`` tokens through each path,
    assert per-step logit equality."""
    params = T.init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, L + steps + 1), 0,
                                config.vocab_size)
    _, (k_pre, v_pre) = T.forward(params, tokens[:, :L], config, return_kv=True)

    contiguous = T.init_decode_cache(config, B, P * ps, k_pre, v_pre)
    paged = alloc_paged_cache(config, n_pages=1 + B * P, page_size=ps)
    if table == "identity":
        bt = np.arange(1, 1 + B * P).reshape(B, P).astype(np.int32)
    else:
        rng = np.random.RandomState(7)
        bt = (1 + rng.permutation(B * P)).reshape(B, P).astype(np.int32)
    paged = seed_pages(paged, k_pre, v_pre, bt, ps)
    bt = jnp.asarray(bt)

    cur = tokens[:, L : L + 1]
    for i in range(steps):
        pos = jnp.int32(L + i)
        lg_c, contiguous = T.decode_step(params, cur, pos, contiguous, config)
        lg_p, paged = T.decode_step_paged(
            params, cur, jnp.full((B,), pos), paged, bt, config
        )
        np.testing.assert_allclose(
            np.asarray(lg_p), np.asarray(lg_c), atol=1e-4, rtol=1e-4,
            err_msg=f"step {i} table={table}",
        )
        cur = jnp.argmax(lg_c[:, -1:, :], axis=-1).astype(jnp.int32)


@pytest.mark.parametrize("table", ["identity", "permuted"])
@pytest.mark.parametrize("kv_cache_dtype", ["bf16", "int8"])
def test_paged_decode_matches_contiguous(table, kv_cache_dtype):
    # Same prompt in both caches; logits must agree at every step
    # regardless of which physical pages back the sequence. int8 pools
    # quantize per row exactly like the contiguous strategy, so the
    # equality holds there too (scale planes gathered with the pages).
    assert_paged_matches_contiguous(cfg(kv_cache_dtype=kv_cache_dtype), table)


def test_heterogeneous_positions():
    # Two rows at DIFFERENT lengths in one paged batch — each must match
    # its own single-row contiguous decode (the property continuous
    # batching rests on).
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    ps, P = 4, 5
    lens = [3, 9]
    prompts = [
        jax.random.randint(jax.random.PRNGKey(10 + i), (1, L), 0,
                           config.vocab_size)
        for i, L in enumerate(lens)
    ]

    paged = alloc_paged_cache(config, n_pages=1 + 2 * P, page_size=ps)
    bt = np.full((2, P), 0, np.int32)
    singles = []
    curs = []
    for b, (L, prompt) in enumerate(zip(lens, prompts)):
        logits, (k_pre, v_pre) = T.forward(
            params, prompt, config, return_kv=True
        )
        bt[b] = np.arange(1 + b * P, 1 + (b + 1) * P)
        paged = seed_pages(
            paged, k_pre, v_pre, bt[b : b + 1], ps
        )
        singles.append(T.init_decode_cache(config, 1, P * ps, k_pre, v_pre))
        curs.append(jnp.argmax(logits[:, -1:, :], axis=-1).astype(jnp.int32))
    bt = jnp.asarray(bt)

    pos = np.array(lens, np.int32)
    cur = jnp.concatenate(curs, axis=0)
    for i in range(3):
        lg_p, paged = T.decode_step_paged(
            params, cur, jnp.asarray(pos), paged, bt, config
        )
        nxt = []
        for b in range(2):
            lg_s, singles[b] = T.decode_step(
                params, cur[b : b + 1], jnp.int32(int(pos[b])),
                singles[b], config,
            )
            np.testing.assert_allclose(
                np.asarray(lg_p[b]), np.asarray(lg_s[0]),
                atol=1e-4, rtol=1e-4, err_msg=f"row {b} step {i}",
            )
            nxt.append(jnp.argmax(lg_s[:, -1:, :], axis=-1).astype(jnp.int32))
        cur = jnp.concatenate(nxt, axis=0)
        pos = pos + 1


@pytest.mark.parametrize("table", ["identity", "permuted"])
def test_paged_decode_sliding_window_matches_contiguous(table):
    # paged x sliding_window: the per-row window mask composes with the
    # block-table gather exactly as with the contiguous cache — the mask
    # must apply in LOGICAL order, so the permuted table is the case that
    # would catch physical-order masking.
    assert_paged_matches_contiguous(
        cfg(sliding_window=5), table, B=2, L=9, ps=4, P=4, steps=3
    )


@pytest.mark.parametrize("kv_cache_dtype", ["bf16", "int8"])
def test_paged_window_matches_sequential_steps(kv_cache_dtype):
    # The paged verify primitive: one W-token window (crossing a page
    # boundary) must equal W sequential paged steps — same cache
    # evolution, same logits — for both pool layouts. This is what makes
    # speculative decoding inside continuous batching exact.
    config = cfg(kv_cache_dtype=kv_cache_dtype)
    params = T.init_params(config, jax.random.PRNGKey(0))
    B, L, ps, P, W = 2, 6, 4, 4, 4  # window spans slots 6..9: pages 1..2
    tokens = jax.random.randint(jax.random.PRNGKey(3), (B, L + W), 0,
                                config.vocab_size)
    _, (k_pre, v_pre) = T.forward(params, tokens[:, :L], config, return_kv=True)
    paged_a = alloc_paged_cache(config, n_pages=1 + B * P, page_size=ps)
    bt = np.arange(1, 1 + B * P).reshape(B, P).astype(np.int32)
    paged_a = seed_pages(paged_a, k_pre, v_pre, bt, ps)
    paged_b = jax.tree.map(jnp.copy, paged_a)
    bt = jnp.asarray(bt)

    win_logits, paged_a = T.decode_window_paged(
        params, tokens[:, L:], jnp.full((B,), L), paged_a, bt, config
    )
    for i in range(W):
        step_logits, paged_b = T.decode_step_paged(
            params, tokens[:, L + i : L + i + 1], jnp.full((B,), L + i),
            paged_b, bt, config,
        )
        np.testing.assert_allclose(
            np.asarray(win_logits[:, i]), np.asarray(step_logits[:, 0]),
            atol=1e-4, rtol=1e-4, err_msg=f"row {i}",
        )
    for a, b in zip(jax.tree.leaves(paged_a), jax.tree.leaves(paged_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_paged_window_heterogeneous_positions():
    # Two rows verify windows at DIFFERENT cursors in one call — each must
    # match its own contiguous decode_window (per-row speculative verify).
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    ps, P, W = 4, 5, 3
    lens = [3, 8]
    paged = alloc_paged_cache(config, n_pages=1 + 2 * P, page_size=ps)
    bt = np.zeros((2, P), np.int32)
    contigs = []
    wins = []
    for b, L in enumerate(lens):
        prompt = jax.random.randint(jax.random.PRNGKey(40 + b), (1, L), 0,
                                    config.vocab_size)
        _, (k_pre, v_pre) = T.forward(params, prompt, config, return_kv=True)
        bt[b] = np.arange(1 + b * P, 1 + (b + 1) * P)
        paged = seed_pages(paged, k_pre, v_pre, bt[b : b + 1], ps)
        contigs.append(T.init_decode_cache(config, 1, P * ps, k_pre, v_pre))
        wins.append(jax.random.randint(jax.random.PRNGKey(50 + b), (1, W), 0,
                                       config.vocab_size))
    bt = jnp.asarray(bt)

    lg_p, _ = T.decode_window_paged(
        params, jnp.concatenate(wins, axis=0),
        jnp.asarray(lens, jnp.int32), paged, bt, config,
    )
    for b, L in enumerate(lens):
        lg_c, _ = T.decode_window(
            params, wins[b], jnp.int32(L), contigs[b], config
        )
        np.testing.assert_allclose(
            np.asarray(lg_p[b]), np.asarray(lg_c[0]),
            atol=1e-4, rtol=1e-4, err_msg=f"row {b}",
        )


def test_paged_read_layout():
    # The gather view reassembles logical order from scattered pages.
    config = cfg(n_layers=1)
    cache = alloc_paged_cache(config, n_pages=4, page_size=2)
    kvh, dh = config.kv_heads, config.head_dim
    vals = jnp.arange(4 * kvh * 2 * dh, dtype=jnp.float32).reshape(
        4, kvh, 2, dh
    )
    cache = {"k": cache["k"].at[0].set(vals), "v": cache["v"].at[0].set(vals)}
    bt = jnp.asarray([[3, 1]], jnp.int32)  # logical 0 -> page 3, 1 -> page 1
    kf, vf = paged_read(
        {"k": cache["k"][0], "v": cache["v"][0]}, bt, jnp.float32
    )
    assert kf.shape == (1, kvh, 4, dh)
    np.testing.assert_array_equal(np.asarray(kf[0, :, :2]), np.asarray(vals[3]))
    np.testing.assert_array_equal(np.asarray(kf[0, :, 2:]), np.asarray(vals[1]))


def test_alloc_validates_page_size():
    with pytest.raises(ValueError, match="page_size"):
        alloc_paged_cache(cfg(), n_pages=4, page_size=0)
