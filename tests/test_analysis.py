"""Unit tests for the edge static-analysis subsystem (docs/analysis.md):
the single-pass inspector, the policy engine, the dep pre-resolution, and
the WorkloadAnalyzer's metrics/trace accounting."""

import subprocess
import sys

import pytest

from bee_code_interpreter_tpu.analysis import (
    PolicyEngine,
    WorkloadAnalyzer,
    inspect_source,
)
from bee_code_interpreter_tpu.analysis.context import (
    predicted_deps,
    stash_predicted_deps,
)
from bee_code_interpreter_tpu.observability import Tracer
from bee_code_interpreter_tpu.runtime import dep_guess
from bee_code_interpreter_tpu.runtime.executor_core import ExecutorCore
from bee_code_interpreter_tpu.utils.metrics import Registry

# ---------------------------------------------------------------- inspect


def test_syntax_error_matches_in_sandbox_stderr_shape(tmp_path):
    """The fail-fast stderr must be the shape ``python script.py`` prints:
    File line, source line, caret, final ``SyntaxError:`` line — compared
    structurally against a REAL interpreter run of the same source."""
    source = "def broken(:\n"
    inspection = inspect_source(source)
    assert inspection.syntax_error is not None

    script = tmp_path / "script.py"
    script.write_text(source)
    real = subprocess.run(
        [sys.executable, str(script)], capture_output=True, text=True
    )
    assert real.returncode == 1
    real_lines = real.stderr.strip().splitlines()
    edge_lines = inspection.syntax_error.strip().splitlines()
    # same structure: File header first, SyntaxError verdict last
    assert edge_lines[0].lstrip().startswith('File "')
    assert real_lines[0].lstrip().startswith('File "')
    assert edge_lines[-1] == real_lines[-1]  # identical SyntaxError line
    assert any("^" in line for line in edge_lines)


def test_inspection_collects_imports_calls_paths():
    src = (
        "import subprocess as sp\n"
        "from os import fork\n"
        "import socket\n"
        "sp.run(['ls'])\n"
        "while True:\n"
        "    fork()\n"
        "x = open('/etc/passwd').read()\n"
    )
    inspection = inspect_source(src)
    assert inspection.syntax_error is None
    assert {"subprocess", "os", "socket"} <= inspection.imports
    names = inspection.call_names()
    assert "subprocess.run" in names  # alias-resolved
    assert "os.fork" in names  # from-import resolved
    forks = [c for c in inspection.calls if c.name == "os.fork"]
    assert forks and all(c.in_loop for c in forks)
    runs = [c for c in inspection.calls if c.name == "subprocess.run"]
    assert runs and not any(c.in_loop for c in runs)
    assert "/etc/passwd" in inspection.path_literals


def test_inspection_loop_context_resets_in_nested_function():
    src = "for i in range(3):\n    def f():\n        g()\n"
    calls = {c.name: c for c in inspect_source(src).calls}
    assert not calls["g"].in_loop  # def body only runs when called
    assert calls["range"].in_loop is False  # the iterable is evaluated once


def test_inspection_loop_context_once_only_constructs():
    """Constructs that execute exactly once must not read as looped — a
    fork_in_loop deny on a for-else body would 422 correct code."""
    cases = {
        # for-else: the else suite runs at most once, after the loop
        "import os\nfor i in range(3):\n    pass\nelse:\n    os.fork()\n": False,
        # while-else: same
        "import os\nwhile f():\n    pass\nelse:\n    os.fork()\n": False,
        # a comprehension's OUTERMOST iterable evaluates once
        "import os\nxs = [y for y in range(os.fork())]\n": False,
        # ...but the element expression runs per element
        "import os\nxs = [os.fork() for y in range(3)]\n": True,
        # and a while test re-evaluates every iteration
        "import os\nwhile os.fork():\n    pass\n": True,
    }
    for src, expect_in_loop in cases.items():
        forks = [
            c for c in inspect_source(src).calls if c.name == "os.fork"
        ]
        assert forks, src
        assert forks[0].in_loop is expect_in_loop, src


def test_inspection_predicts_deps_from_same_tree():
    inspection = inspect_source("import pandas\nimport yaml\nimport json\n")
    assert inspection.predicted_deps == ["PyYAML", "pandas"]  # stdlib dropped


def test_null_byte_source_is_unanalyzable_never_truncated():
    """The sandbox's FILE tokenizer handles NUL line-dependently (on this
    image's 3.10 a NUL drops only the rest of its own line — later lines
    still execute), so edge truncation at the first NUL would let
    'print(1)\\n\\x00\\nimport socket' pass a deny-imports gate and then
    run the denied import. NUL-bearing source must make NO claim: it is
    unanalyzable, never a crash (ast.parse would raise ValueError) and
    never a prefix-only analysis."""
    for src in (
        "print(1)\n\x00\nimport socket\nsocket.socket()\n",  # NUL on its own line
        "import socket\nprint('ran')\x00junk junk",  # NUL mid-line, trailing
    ):
        inspection = inspect_source(src)
        assert inspection.syntax_error is None
        assert inspection.analysis_error is not None
        assert not inspection.imports  # no partial claims from a prefix


def test_null_byte_cannot_bypass_policy_or_skip_pod_scan():
    """End-to-end shape of the review finding: under a declared policy a
    NUL-bearing submission is refused fail-closed; with no policy it
    proceeds with predicted_deps=None — no truncated-prefix dep claim is
    ever stashed; the pod's own (best-effort) scan is authoritative."""
    evasion = "print(1)\n\x00\nimport socket\nsocket.socket()\n"
    guarded = WorkloadAnalyzer(
        PolicyEngine(deny_imports=("socket",))
    ).analyze(evasion)
    assert guarded.denials and guarded.denials[0].rule == "unanalyzable"
    open_gate = WorkloadAnalyzer().analyze(evasion)
    assert not open_gate.denials
    assert open_gate.predicted_deps is None  # the pod must scan itself


def test_deep_unary_chain_is_analyzable():
    """ast.parse accepts expressions far deeper than the recursion limit
    (a 2KB ----…x chain is a valid program the sandbox runs); the walker
    must be iterative, never a RecursionError → 500."""
    inspection = inspect_source("import pandas\ny = " + "-" * 5000 + "1\n")
    assert inspection.analysis_error is None
    assert inspection.predicted_deps == ["pandas"]


def test_unanalyzable_source_fails_closed_only_under_policy():
    import bee_code_interpreter_tpu.analysis.inspect as inspect_mod

    blown = inspect_mod.SourceInspection(
        analysis_error="RecursionError('maximum recursion depth exceeded')"
    )
    real = inspect_mod.inspect_source
    try:
        inspect_mod.inspect_source = lambda _src: blown
        # reload the symbol policy.py bound at import time
        import bee_code_interpreter_tpu.analysis.policy as policy_mod

        orig = policy_mod.inspect_source
        policy_mod.inspect_source = lambda _src: blown
        try:
            registry = Registry()
            guarded = WorkloadAnalyzer(
                PolicyEngine(deny_imports=("socket",)), metrics=registry
            ).analyze("whatever")
            assert guarded.denials and guarded.denials[0].rule == "unanalyzable"
            assert (
                'bci_analysis_rejections_total{rule="unanalyzable"} 1'
                in registry.expose()
            )
            open_gate = WorkloadAnalyzer().analyze("whatever")
            # no policy: proceed, but with NO dep claim — the sandbox must
            # run its own scan
            assert not open_gate.denials
            assert open_gate.predicted_deps is None
        finally:
            policy_mod.inspect_source = orig
    finally:
        inspect_mod.inspect_source = real


# ----------------------------------------------------------------- policy


def test_policy_import_matching_and_severity():
    engine = PolicyEngine(
        deny_imports=("socket",), warn_imports=("requests",)
    )
    findings = engine.evaluate(
        inspect_source("import socket\nimport requests\nimport math\n")
    )
    by_rule = {f.rule: f for f in findings}
    assert by_rule["import:socket"].severity == "deny"
    assert by_rule["import:requests"].severity == "warn"
    assert len(findings) == 2


def test_policy_import_matches_submodules():
    engine = PolicyEngine(deny_imports=("socket",))
    assert engine.evaluate(inspect_source("from socket import socket\n"))
    assert engine.evaluate(inspect_source("import socket.timeout\n"))
    assert not engine.evaluate(inspect_source("import socketserver2\n"))


def test_policy_call_wildcards_and_shapes():
    engine = PolicyEngine(
        deny_calls=("subprocess.*", "fork_in_loop"),
        warn_calls=("raw_socket",),
    )
    src = (
        "import subprocess, os, socket\n"
        "subprocess.check_output(['id'])\n"
        "for _ in range(10):\n"
        "    os.fork()\n"
        "socket.socket()\n"
    )
    findings = engine.evaluate(inspect_source(src))
    rules = {f.rule: f.severity for f in findings}
    assert rules["call:subprocess.*"] == "deny"
    assert rules["shape:fork_in_loop"] == "deny"
    assert rules["shape:raw_socket"] == "warn"
    # a single fork OUTSIDE a loop does not trip the shape
    assert not PolicyEngine(deny_calls=("fork_in_loop",)).evaluate(
        inspect_source("import os\nos.fork()\n")
    )


def test_policy_path_prefixes():
    engine = PolicyEngine(deny_paths=("/etc",), warn_paths=("/tmp",))
    findings = engine.evaluate(
        inspect_source("a = '/etc/shadow'\nb = '/tmp/x'\nc = '/workspace/f'\n")
    )
    rules = {f.rule: f.severity for f in findings}
    assert rules == {"path:/etc": "deny", "path:/tmp": "warn"}
    # prefix means path-component prefix: /etcetera must not match /etc
    assert not engine.evaluate(inspect_source("a = '/etcetera'\n"))
    # "/etc/" and "/etc" declare the same rule: both match the bare
    # directory literal and everything under it
    slashed = PolicyEngine(deny_paths=("/etc/",))
    assert slashed.evaluate(inspect_source("a = '/etc'\n"))
    assert slashed.evaluate(inspect_source("a = '/etc/passwd'\n"))
    assert not slashed.evaluate(inspect_source("a = '/etcetera'\n"))


# ---------------------------------------------------- dep pre-resolution


def test_filter_predicted_drops_preinstalled_and_pinned():
    predicted = ["pandas", "PyYAML", "jax", "torch", "numpy"]
    out = dep_guess.filter_predicted(predicted, preinstalled={"NumPy"})
    # numpy preinstalled (normalized match), jax/torch pinned-stack skip
    assert out == ["PyYAML", "pandas"]


def test_filter_predicted_drops_this_interpreters_stdlib():
    """Edge and sandbox may run different Python versions: a module that
    is stdlib HERE must never be pip-installed because an older/newer
    edge identity-mapped it to a same-named PyPI package (dependency
    confusion). sqlite3 stands in for the telnetlib-style divergence."""
    out = dep_guess.filter_predicted(["sqlite3", "asyncio", "pandas"])
    assert out == ["pandas"]


async def test_executor_core_skips_scan_when_prediction_attached(
    tmp_path, monkeypatch
):
    core = ExecutorCore(
        workspace=tmp_path / "ws", disable_dep_install=True
    )

    def boom(*a, **k):
        raise AssertionError("sandbox ran its own scan despite a prediction")

    monkeypatch.setattr(dep_guess, "guess_dependencies", boom)
    installed, notes = await core.ensure_dependencies(
        "import pandas\n", predicted_deps=["pandas"]
    )
    assert (installed, notes) == ([], "")  # install disabled; scan skipped
    # without a prediction the scan still runs (and here, raises)
    with pytest.raises(AssertionError):
        await core.ensure_dependencies("import pandas\n")


def test_context_stash_roundtrip():
    assert predicted_deps() is None
    stash_predicted_deps(["pandas"])
    assert predicted_deps() == ["pandas"]
    stash_predicted_deps([])  # "scanned, nothing to install" is a claim
    assert predicted_deps() == []
    stash_predicted_deps(None)
    assert predicted_deps() is None


# ------------------------------------------------------------- analyzer


def test_analyzer_accounts_rejections_and_predictions():
    registry = Registry()
    analyzer = WorkloadAnalyzer(
        PolicyEngine(deny_imports=("socket",)), metrics=registry
    )
    assert analyzer.analyze("def broken(:\n").syntax_error is not None
    assert analyzer.analyze("import socket\n").denials
    ok = analyzer.analyze("import pandas\n")
    assert not ok.denials and ok.predicted_deps == ["pandas"]
    text = registry.expose()
    assert 'bci_analysis_rejections_total{rule="syntax"} 1' in text
    assert 'bci_analysis_rejections_total{rule="import:socket"} 1' in text
    assert "bci_analysis_dep_predictions_total 1" in text
    assert "bci_analysis_seconds_count 3" in text


def test_analyzer_counts_warnings():
    registry = Registry()
    analyzer = WorkloadAnalyzer(
        PolicyEngine(warn_imports=("requests",)), metrics=registry
    )
    analyzer.analyze("import requests\n")
    analyzer.analyze("import requests\n")
    assert (
        'bci_analysis_warnings_total{rule="import:requests"} 2'
        in registry.expose()
    )


def test_analyzer_records_analysis_stage_span():
    registry = Registry()
    tracer = Tracer(metrics=registry)
    analyzer = WorkloadAnalyzer(metrics=registry)
    with tracer.trace("/v1/execute") as trace:
        verdict = analyzer.analyze("import pandas\n")
    assert verdict.predicted_deps == ["pandas"]
    assert "analysis" in trace.stage_ms()
    assert 'stage="analysis"' in registry.expose()
    span = next(s for s in trace.spans if s.name == "analysis")
    assert span.attributes["analysis.outcome"] == "ok"
    assert span.attributes["analysis.predicted_deps"] == "pandas"


def test_analyzer_annotation_shape():
    analyzer = WorkloadAnalyzer(PolicyEngine(warn_calls=("subprocess",)))
    verdict = analyzer.analyze("import subprocess\nsubprocess.run(['ls'])\n")
    annotation = verdict.annotation()
    assert annotation["warnings"][0]["rule"] == "shape:subprocess"
    assert "predicted_deps" not in annotation  # key absent when empty
    # clean source still annotates the cost hint (docs/analysis.md "Cost
    # classes") and nothing else
    assert WorkloadAnalyzer().analyze("print(1)\n").annotation() == {
        "cost_class": "cheap"
    }


def test_analyzer_size_bound_is_unanalyzable_not_a_stall():
    """The gate runs ON the event loop: a multi-MB source must never be
    parsed there. Over the bound it is `unanalyzable` — fail-closed with
    a policy declared, admitted (prediction None, pod scans) without."""
    big = "x = 1\n" * 200  # ~1.2KB, over a tiny test bound
    guarded = WorkloadAnalyzer(
        PolicyEngine(deny_imports=("socket",)), max_source_bytes=512
    ).analyze(big)
    assert guarded.denials and guarded.denials[0].rule == "unanalyzable"
    open_gate = WorkloadAnalyzer(max_source_bytes=512).analyze(big)
    assert not open_gate.denials
    assert open_gate.predicted_deps is None  # the pod must scan itself
    # under the bound everything works as usual
    ok = WorkloadAnalyzer(max_source_bytes=1 << 20).analyze(big)
    assert ok.predicted_deps == []


def test_analyzer_size_bound_measures_utf8_bytes_not_chars():
    """The knob is a BYTE bound (what arrived on the wire): 200 chars of
    4-byte emoji is 800 bytes and must trip a 512-byte bound even though
    the char count passes."""
    wide = "x = '" + "\U0001f600" * 200 + "'\n"
    assert len(wide) < 512 < len(wide.encode("utf-8"))
    verdict = WorkloadAnalyzer(
        PolicyEngine(deny_imports=("socket",)), max_source_bytes=512
    ).analyze(wide)
    assert verdict.denials and verdict.denials[0].rule == "unanalyzable"


def test_analyzer_from_config_honors_enable_switch():
    from bee_code_interpreter_tpu.config import Config

    assert WorkloadAnalyzer.from_config(Config(analysis_enabled=False)) is None
    analyzer = WorkloadAnalyzer.from_config(
        Config(policy_deny_imports="socket, ctypes")
    )
    assert analyzer.policy.deny_imports == ("socket", "ctypes")


# ------------------------------------------------------- dataflow layer
# (docs/analysis.md "Dataflow layer"): the CFG engine the concurrency lint
# walks and the flow-insensitive bindings the policy consumer resolves
# through. The evasion-closing edge behavior lives in test_analysis_edge.


def test_cfg_reaching_defs_and_await_annotations():
    import ast

    from bee_code_interpreter_tpu.analysis.dataflow import EXIT, FunctionFlow

    src = (
        "async def f(self, q):\n"
        "    n = self.count\n"
        "    if n:\n"
        "        n = 0\n"
        "    await q.put(n)\n"
        "    self.count = n\n"
    )
    func = ast.parse(src).body[0]
    flow = FunctionFlow(func)
    # last statement sees BOTH definitions of n (the if is a real branch)
    write_idx = next(
        n.idx for n in flow.nodes if isinstance(n.stmt, ast.Assign)
        and isinstance(n.stmt.targets[0], ast.Attribute)
    )
    read_idx = next(
        n.idx for n in flow.nodes if isinstance(n.stmt, ast.Assign)
        and not isinstance(n.stmt.targets[0], ast.Attribute)
    )
    assert len(flow.reach_in(write_idx)["n"]) == 2
    # the await stmt is annotated and lies between the read and the write
    assert flow.await_between(read_idx, write_idx)
    assert not flow.await_between(write_idx, read_idx)
    assert EXIT in flow.nodes[write_idx].succs


def test_cfg_lock_scopes_annotate_statements():
    import ast

    from bee_code_interpreter_tpu.analysis.dataflow import FunctionFlow

    src = (
        "async def f(self):\n"
        "    a = 1\n"
        "    async with self._lock:\n"
        "        b = 2\n"
        "    c = 3\n"
    )
    flow = FunctionFlow(ast.parse(src).body[0])
    held = {
        n.stmt.targets[0].id: n.held_locks
        for n in flow.nodes
        if isinstance(n.stmt, ast.Assign)
    }
    assert held["a"] == frozenset()
    assert held["b"] == frozenset({"self._lock"})
    assert held["c"] == frozenset()


def test_scope_bindings_union_semantics():
    import ast

    from bee_code_interpreter_tpu.analysis.dataflow import ScopeBindings

    tree = ast.parse(
        'x = print\n'
        'x = __import__\n'
        's = "soc"\n'
        's2 = s + "ket"\n'
        'other = s if x else "tls"\n'
    )
    scope = ScopeBindings(tree, {})
    # a rebound name resolves to BOTH origins (order-blind, over-approx)
    assert scope.origins("x") == {"print", "__import__"}
    # constants fold through names and concatenation...
    assert scope.fold_str(ast.parse('s + "ket"').body[0].value) == "socket"
    # ...but a name with a non-foldable definition does not fold
    assert scope._fold_name("other") is None


def test_inspection_dynamic_fields_and_trigger_gate():
    # no trigger tokens -> the dataflow pass is skipped entirely
    clean = inspect_source("x = 1\nprint(x)\n")
    assert clean.dynamic_imports == {}
    assert clean.dynamic_import_sites == []
    resolved = inspect_source('imp = __import__\nimp("socket")\n')
    assert resolved.dynamic_imports == {"socket": [2]}
    dyn = inspect_source("n = input()\n__import__(n)\n")
    assert [line for line, _ in dyn.dynamic_import_sites] == [2]


def test_dynamic_import_value_flows_into_call_names():
    # m = __import__("subprocess"); m.run(...) is a subprocess.run call
    insp = inspect_source('m = __import__("subprocess")\nm.run(["id"])\n')
    assert "subprocess.run" in insp.call_names()
    findings = PolicyEngine(deny_calls=("subprocess",)).evaluate(insp)
    assert [f.rule for f in findings] == ["shape:subprocess"]


def test_dynamic_import_off_mode_is_silent():
    insp = inspect_source("n = input()\n__import__(n)\n")
    assert PolicyEngine(dynamic_import="off").evaluate(insp) == []
    assert not PolicyEngine(dynamic_import="off").declared
    assert PolicyEngine(dynamic_import="deny").declared  # fail-closed mode


# ----------------------------------------------------------- cost classes


def test_cost_classification_ladder():
    from bee_code_interpreter_tpu.analysis import classify_cost

    assert classify_cost(inspect_source("print(1)\n")) == "cheap"
    assert classify_cost(inspect_source(
        "for i in range(9):\n    print(i)\n"
    )) == "cheap"  # a single loop is just a program
    assert classify_cost(inspect_source(
        "for i in range(9):\n    for j in range(9):\n        print(j)\n"
    )) == "loopy"
    assert classify_cost(inspect_source('open("/tmp/x")\n')) == "io_heavy"
    # an install dwarfs everything else, loops included
    assert classify_cost(inspect_source(
        "import pandas\nfor i in range(9):\n    for j in range(9):\n"
        "        open('/t')\n"
    )) == "install_heavy"


def test_analyzer_stamps_cost_class_on_span_and_counts():
    registry = Registry()
    tracer = Tracer(metrics=registry)
    analyzer = WorkloadAnalyzer(metrics=registry)
    with tracer.trace("/v1/execute") as trace:
        verdict = analyzer.analyze('open("/tmp/x")\n')
    assert verdict.cost_class == "io_heavy"
    span = next(s for s in trace.spans if s.name == "analysis")
    assert span.attributes["analysis.cost_class"] == "io_heavy"
    assert analyzer.cost_class_counts["io_heavy"] == 1
    assert (
        'bci_analysis_cost_class_total{class="io_heavy"} 1'
        in registry.expose()
    )


def test_cost_class_lands_on_wide_event():
    """The flight recorder lifts analysis.* span attributes into the wide
    event's `analysis` block — the cost hint must arrive there for free."""
    from bee_code_interpreter_tpu.observability import FlightRecorder

    registry = Registry()
    tracer = Tracer(metrics=registry)
    recorder = FlightRecorder(metrics=registry)
    tracer.add_sink(recorder.record_trace)
    analyzer = WorkloadAnalyzer(metrics=registry)
    with tracer.trace("/v1/execute"):
        analyzer.analyze("print(1)\n")
    event = recorder.events(limit=1)[0]
    assert event["analysis"]["cost_class"] == "cheap"


def test_unanalyzable_source_has_no_cost_class():
    verdict = WorkloadAnalyzer(max_source_bytes=8).analyze("x = 1\n" * 10)
    assert verdict.cost_class is None
    assert verdict.annotation() is None


def test_accelerator_class_outranks_every_expense_rung():
    """`accelerator` is a PLACEMENT signal (docs/analysis.md "Cost
    classes"): a jax/torch submission routes to a TPU-capable replica
    whatever else it does, and the image-pinned frameworks never appear
    in predicted_deps so no other rung can witness them."""
    from bee_code_interpreter_tpu.analysis import classify_cost

    assert classify_cost(inspect_source("import jax\n")) == "accelerator"
    assert classify_cost(inspect_source(
        "import jax.numpy as jnp\nprint(jnp.zeros(3))\n"
    )) == "accelerator"
    # even alongside an install + I/O + nested loops
    assert classify_cost(inspect_source(
        "import torch\nimport pandas\nfor i in range(9):\n"
        "    for j in range(9):\n        open('/t')\n"
    )) == "accelerator"
    # jax-free submissions land exactly where they always did
    assert classify_cost(inspect_source(
        "try:\n    import pandas\nexcept ImportError:\n    pass\n"
    )) == "install_heavy"


def test_heavy_lane_mirror_includes_accelerator():
    """resilience/ deliberately re-spells HEAVY_COST_CLASSES instead of
    importing the analysis layer — this pin is what keeps the two sets
    from drifting."""
    from bee_code_interpreter_tpu.analysis import (
        COST_CLASSES,
        HEAVY_COST_CLASSES,
    )
    from bee_code_interpreter_tpu.resilience.admission import (
        _HEAVY_COST_CLASSES,
    )

    assert HEAVY_COST_CLASSES == _HEAVY_COST_CLASSES
    assert "accelerator" in HEAVY_COST_CLASSES
    assert "accelerator" in COST_CLASSES


def test_cyclic_alias_chain_still_resolves():
    """Code-review regression: a resolution cycle (x = y; y = x) must not
    poison the memo — `y` still resolves to __import__ and the socket
    import is denied regardless of call/query order."""
    insp = inspect_source('x = y\ny = x\nx = __import__\ny("socket")\nx("os")\n')
    assert insp.dynamic_imports == {"os": [5], "socket": [4]}
    findings = PolicyEngine(deny_imports=("socket",)).evaluate(insp)
    assert [f.rule for f in findings] == ["import:socket"]


def test_resolved_calls_keep_loop_context():
    """Code-review regression: `m = x("os"); m.fork()` inside a for loop
    must keep in_loop so fork_in_loop still matches through the
    indirection the dataflow layer resolves."""
    insp = inspect_source(
        'x = __import__\nfor i in range(3):\n    m = x("os")\n    m.fork()\n'
    )
    assert ("os.fork", True) in {(c.name, c.in_loop) for c in insp.calls}
    findings = PolicyEngine(deny_calls=("fork_in_loop",)).evaluate(insp)
    assert [f.rule for f in findings] == ["shape:fork_in_loop"]
