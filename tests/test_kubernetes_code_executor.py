"""Pod-pool scheduler + remote execution driver against fake kubectl and real
in-process executor servers (unit coverage the reference lacks; SURVEY.md §4).
The retry/teardown paths are exercised through the deterministic
fault-injection harness (tests/chaos.py)."""

import asyncio

import pytest

from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.resilience import (
    SandboxFatalError,
    SandboxTransientError,
)
from bee_code_interpreter_tpu.services.kubernetes_code_executor import (
    KubernetesCodeExecutor,
)
from tests.chaos import ChaosKubectl, Fail, FaultPlan, HttpStatus, NoIP, Ok
from tests.fakes import FakeExecutorPods, FakeKubectl


@pytest.fixture
def pods(tmp_path):
    return FakeExecutorPods(tmp_path / "pods")


def make_executor(pods, storage, *, faults=None, **config_overrides):
    defaults = dict(
        executor_backend="kubernetes",
        executor_port=pods.port,
        executor_pod_queue_target_length=2,
        pod_ready_timeout_s=5,
    )
    defaults.update(config_overrides)
    config = Config(**defaults)
    kubectl = ChaosKubectl(pods, faults) if faults is not None else FakeKubectl(pods)
    return KubernetesCodeExecutor(
        kubectl=kubectl, storage=storage, config=config, ip_poll_interval_s=0.02
    )


async def drain_tasks():
    # let fire-and-forget deletes/refills run
    for _ in range(3):
        await asyncio.sleep(0.05)


async def test_execute_single_host(pods, storage):
    executor = make_executor(pods, storage)
    try:
        result = await executor.execute("print(21 * 2)")
        assert result.stdout == "42\n"
        assert result.exit_code == 0
    finally:
        await pods.close()


async def test_single_use_pod_and_refill(pods, storage):
    executor = make_executor(pods, storage)
    kubectl = executor._kubectl
    try:
        await executor.execute("print('one')")
        await drain_tasks()
        # the used group was deleted (single-use hygiene)
        assert len(kubectl.deleted) >= 1
        # pool refilled toward target length
        assert len(executor._queue) == 2
    finally:
        await pods.close()


async def test_file_roundtrip_through_pod_http(pods, storage):
    executor = make_executor(pods, storage)
    try:
        r1 = await executor.execute("open('artifact.txt','w').write('via pod http')")
        assert set(r1.files) == {"/workspace/artifact.txt"}
        r2 = await executor.execute("print(open('artifact.txt').read())", files=r1.files)
        assert r2.stdout == "via pod http\n"
    finally:
        await pods.close()


async def test_pool_fill_accounting_no_overshoot(pods, storage):
    executor = make_executor(pods, storage)
    try:
        await asyncio.gather(
            executor.fill_executor_pod_queue(),
            executor.fill_executor_pod_queue(),
            executor.fill_executor_pod_queue(),
        )
        assert len(executor._queue) == 2  # target, not 6
    finally:
        await pods.close()


async def test_multihost_gang_spawn_and_spmd_execute(pods, storage):
    executor = make_executor(pods, storage, tpu_hosts_per_slice=2)
    kubectl = executor._kubectl
    try:
        result = await executor.execute("print('hello from spmd')")
        assert result.stdout == "hello from spmd\n"
        # both workers executed the program
        assert sorted(pods.execute_counts.values()) == [1, 1]
        # worker-1 manifest got the coordinator address of worker-0's IP
        w1 = next(
            m for m in kubectl.created_manifests
            if m["metadata"]["labels"]["executor-worker"] == "1"
        )
        env = {e["name"]: e["value"] for e in w1["spec"]["containers"][0]["env"]}
        assert env["JAX_PROCESS_ID"] == "1"
        assert env["JAX_NUM_PROCESSES"] == "2"
        assert env["JAX_COORDINATOR_ADDRESS"].endswith(":8476")
        assert not env["JAX_COORDINATOR_ADDRESS"].startswith("0.0.0.0")
        await drain_tasks()
        # single-use: both members deleted
        assert sum(1 for d in kubectl.deleted if "-w" in d) >= 2
    finally:
        await pods.close()


async def test_gang_spawn_failure_tears_down_all_members(pods, storage):
    executor = make_executor(pods, storage, tpu_hosts_per_slice=2)
    kubectl = executor._kubectl

    # Fail readiness of worker 1 of whatever group spawns.
    orig_wait = kubectl.wait

    async def failing_wait(target, **kwargs):
        if target.endswith("-w1"):
            raise RuntimeError("fake: worker 1 never Ready")
        return await orig_wait(target, **kwargs)

    kubectl.wait = failing_wait
    try:
        with pytest.raises(RuntimeError):
            # bypass tenacity (4-10s backoff) and call the wrapped spawn once
            await executor.spawn_pod_group.__wrapped__(executor)
        await drain_tasks()
        # every created member of the failed gang was torn down
        created = {m["metadata"]["name"] for m in kubectl.created_manifests}
        assert created <= set(kubectl.deleted) | set()
    finally:
        await pods.close()


async def test_tpu_pod_spec(pods, storage):
    executor = make_executor(
        pods,
        storage,
        tpu_accelerator_type="tpu-v5-lite-podslice",
        tpu_topology="2x4",
        tpu_chips_per_host=8,
    )
    kubectl = executor._kubectl
    try:
        group = await executor.spawn_pod_group.__wrapped__(executor)
        manifest = kubectl.created_manifests[0]
        spec = manifest["spec"]
        assert spec["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"] == (
            "tpu-v5-lite-podslice"
        )
        assert spec["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x4"
        limits = spec["containers"][0]["resources"]["limits"]
        assert limits["google.com/tpu"] == 8
        env = {e["name"]: e["value"] for e in spec["containers"][0]["env"]}
        assert env["TPU_ACCELERATOR_TYPE"] == "tpu-v5-lite-podslice"
        assert env["TPU_TOPOLOGY"] == "2x4"
    finally:
        await pods.close()


async def test_preempted_warm_group_discarded_not_used(pods, storage):
    # SURVEY.md §5 (TPU build addition): v5e pods are preemptible. A warm
    # group whose pod vanished while queued must be health-probed out of the
    # pool and the request served by a healthy group — not burned as a failed
    # attempt.
    executor = make_executor(pods, storage)
    kubectl = executor._kubectl
    try:
        await executor.fill_executor_pod_queue()
        assert len(executor._queue) == 2
        victim = executor._queue[0]
        await pods.stop_pod(victim.pod_ips[0])  # "preemption"

        result = await executor.execute("print('still served')")
        assert result.stdout == "still served\n"
        assert result.exit_code == 0
        await drain_tasks()
        # the preempted group was torn down, not reused
        assert victim.pod_names[0] in kubectl.deleted
    finally:
        await pods.close()


# ----------------------------------------------------- retry paths (chaos)


async def test_execute_retry_backoff_schedule_observed(pods, storage):
    # Two 5xx answers, then healthy: the execute retry walks the exponential
    # schedule wait_min * 2**(n-1) and the request still succeeds.
    faults = FaultPlan().script("execute", HttpStatus(503), HttpStatus(502))
    pods.faults = faults
    executor = make_executor(
        pods, storage, faults=faults,
        executor_retry_wait_min_s=0.01, executor_retry_wait_max_s=0.04,
    )
    try:
        result = await executor.execute("print('survived')")
        assert result.stdout == "survived\n"
        assert [
            (op, pytest.approx(s)) for op, s in executor.retry_backoffs
        ] == [("execute", 0.01), ("execute", 0.02)]
    finally:
        await pods.close()


async def test_spawn_retry_backoff_schedule_observed(pods, storage):
    # Spawn fails twice (apiserver flake), succeeds on the third attempt —
    # all inside ONE execute call, via the spawn retry policy.
    faults = FaultPlan().script("pod_create", Fail(), Fail())
    pods.faults = faults
    executor = make_executor(
        pods, storage, faults=faults,
        executor_retry_wait_min_s=0.01, executor_retry_wait_max_s=0.04,
        executor_pod_queue_target_length=0,
    )
    try:
        result = await executor.execute("print('third time lucky')")
        assert result.stdout == "third time lucky\n"
        assert [op for op, _ in executor.retry_backoffs] == ["spawn", "spawn"]
    finally:
        await pods.close()


async def test_fatal_4xx_not_retried(pods, storage):
    # A 400 from the sandbox is final: exactly one /execute request, no
    # backoff burned, SandboxFatalError surfaced.
    faults = FaultPlan().script("execute", HttpStatus(400))
    pods.faults = faults
    executor = make_executor(pods, storage, faults=faults)
    try:
        with pytest.raises(SandboxFatalError):
            await executor.execute("print(1)")
        assert sum(pods.execute_counts.values()) == 1
        assert executor.retry_backoffs == []
    finally:
        await pods.close()


async def test_single_use_teardown_on_mid_execute_failure(pods, storage):
    # A group whose execution failed mid-flight is still torn down (single-use
    # hygiene holds on the failure path, not just on success).
    faults = FaultPlan().script("execute", HttpStatus(503))
    pods.faults = faults
    executor = make_executor(
        pods, storage, faults=faults, executor_retry_attempts=1,
    )
    kubectl = executor._kubectl
    try:
        with pytest.raises(SandboxTransientError):
            await executor.execute("print(1)")
        await drain_tasks()
        created = {m["metadata"]["name"] for m in kubectl.created_manifests}
        # every group created for (or refilled around) the failed request that
        # is not sitting warm in the queue has been deleted
        warm = {name for g in executor._queue for name in g.pod_names}
        assert created - warm <= set(kubectl.deleted)
        assert len(created - warm) >= 1
    finally:
        await pods.close()


async def test_gang_teardown_on_partial_spawn_failure_chaos(pods, storage):
    # Worker 0 creates fine, worker 1's create errors: every created member
    # of the failed gang is deleted (all-or-nothing spawn), driven through
    # the chaos harness instead of monkeypatching.
    faults = FaultPlan().script("pod_create", Ok(), Fail("worker 1 rejected"))
    pods.faults = faults
    executor = make_executor(
        pods, storage, faults=faults,
        tpu_hosts_per_slice=2, executor_pod_queue_target_length=0,
        executor_retry_attempts=1,
    )
    kubectl = executor._kubectl
    try:
        with pytest.raises(RuntimeError):
            await executor.execute("print(1)")
        await drain_tasks()
        created = {m["metadata"]["name"] for m in kubectl.created_manifests}
        assert created  # w0 was created...
        assert created <= set(kubectl.deleted)  # ...and torn down with the gang
    finally:
        await pods.close()


async def test_pod_ip_flap_retried_within_spawn(pods, storage):
    # status.podIP empty on the first two polls (pod scheduled, IP not yet
    # assigned): the IP wait polls through the flap without failing the spawn.
    faults = FaultPlan().script("pod_ip", NoIP(), NoIP())
    pods.faults = faults
    executor = make_executor(
        pods, storage, faults=faults,
        tpu_hosts_per_slice=2, executor_pod_queue_target_length=0,
    )
    try:
        result = await executor.execute("print('flap survived')")
        assert result.stdout == "flap survived\n"
        assert faults.pending("pod_ip") == 0  # the flap was actually consumed
    finally:
        await pods.close()


async def test_gang_changed_files_union_across_workers(pods, storage):
    # A payload where each gang worker writes a per-host file (orbax-style
    # sharded checkpoint output) must surface ALL shards in the result, not
    # just worker 0's (VERDICT r2 weak #6); a shared name resolves to worker
    # 0's copy (process-0-owns-I/O convention).
    executor = make_executor(pods, storage, tpu_hosts_per_slice=2)
    payload = (
        "from pathlib import Path\n"
        "me = Path.cwd().name\n"  # fake pod workspaces are named by pod IP
        "Path(f'shard-{me}.txt').write_text(f'shard of {me}')\n"
        "Path('common.txt').write_text(me)\n"
    )
    try:
        result = await executor.execute(payload)
        assert result.exit_code == 0, result.stderr
        shards = sorted(p for p in result.files if "/shard-" in p)
        assert len(shards) == 2, result.files
        for path in shards:
            ip = path.removeprefix("/workspace/shard-").removesuffix(".txt")
            assert await storage.read(result.files[path]) == f"shard of {ip}".encode()
        # worker 0 wins the shared-name collision (gang spawn creates worker 0
        # first — coordinator-IP bake-in — so it gets the fake's first IP)
        common = await storage.read(result.files["/workspace/common.txt"])
        assert common.decode() == "127.1.0.1"
    finally:
        await pods.close()
