"""Plain helper(s) for tests that drive payloads through the in-process
HTTP app (the ``http_app`` fixture lives in conftest.py; importing helpers
from conftest would double-import it — a pytest anti-pattern)."""


async def post_execute(app, payload: dict) -> dict:
    """POST /v1/execute against an in-process app; asserts HTTP 200."""
    from aiohttp.test_utils import TestClient, TestServer

    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        resp = await client.post("/v1/execute", json=payload)
        assert resp.status == 200, await resp.text()
        return await resp.json()
    finally:
        await client.close()
