"""The one-client capture surface (round-4 tunnel discovery).

scripts/tpu-oneshot.py runs every hardware measurement inside ONE jax
client because the tunnel serves at best one client per healthy window.
These tests pin the import surface the oneshot battery depends on —
``run_measurements(emit)`` on each measurement script, ``run_inprocess`` on
the MFU script — and the oneshot's own platform gate, so a rename cannot
silently drop a case from the battery.
"""

import importlib.util
import inspect
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def test_measurement_scripts_expose_run_measurements():
    for script in ("bench-flash-attention", "bench-decode",
                   "validate-shardmap-pallas"):
        mod = load(script.replace("-", "_"), REPO / "scripts" / f"{script}.py")
        fn = getattr(mod, "run_measurements", None)
        assert callable(fn), f"{script}.py lost run_measurements"
        params = list(inspect.signature(fn).parameters)
        assert params[0] == "emit", f"{script}.py run_measurements signature"


def test_mfu_script_exposes_run_inprocess_and_parsers():
    mfu = load("bench_mfu_surface", REPO / "scripts" / "bench-mfu.py")
    assert callable(mfu.run_inprocess)
    results = mfu._parse_results(
        "noise\nRESULT_TRAIN 12.5 80.0 123456\nRESULT_DECODE 1.5 666.7\n"
    )
    assert results["RESULT_TRAIN"] == [12.5, 80.0, 123456.0]
    assert results["RESULT_DECODE"] == [1.5, 666.7]
    try:
        mfu._parse_results("RESULT_TRAIN 1 2 3\n")  # decode marker missing
    except RuntimeError as e:
        assert "RESULT_DECODE" in str(e)
    else:
        raise AssertionError("missing marker must raise")


def test_mfu_emit_results_separates_service_and_inprocess_cases():
    mfu = load("bench_mfu_cases", REPO / "scripts" / "bench-mfu.py")
    results = {"RESULT_TRAIN": [10.0, 50.0, 1000.0],
               "RESULT_DECODE": [2.0, 500.0]}
    seen = []

    def emit(case, payload):
        seen.append((case, payload))

    mfu._emit_results(emit, results, via="service execution path")
    mfu._emit_results(emit, results, via="in-process one-client battery")
    cases = [c for c, _ in seen]
    # the service-path decode row and the in-process one must never share a
    # ledger case (latest_per_case would let one mask the other's provenance)
    assert cases == ["mfu_train", "service_decode", "mfu_train", "mfu_decode"]
    assert seen[0][1]["via"] == "service execution path"
    assert seen[2][1]["via"] == "in-process one-client battery"
    assert seen[0][1]["mfu"] > 0


def test_oneshot_exits_2_on_non_tpu_backend(tmp_path):
    """On a CPU backend the oneshot must exit 2 (nothing to capture) without
    touching the real evidence ledger — the same process-level gate the
    patient loop keys off."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BCI_EVIDENCE_PATH"] = str(tmp_path / "ledger.jsonl")
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "tpu-oneshot.py")],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )
    assert out.returncode == 2, (out.stdout, out.stderr)
    assert not (tmp_path / "ledger.jsonl").exists()
