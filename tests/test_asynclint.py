"""Tier-1 self-lint (docs/analysis.md "Self-lint"): the asyncio control
plane — api/, services/, resilience/, observability/ — must carry ZERO
unexplained asynclint violations, and every suppression must still be
earning its justification (a stale suppression is itself a failure).

The second half unit-tests each rule on synthetic snippets so a lint
regression names the broken rule, not just "the repo got dirty"."""

import textwrap

from bee_code_interpreter_tpu.analysis.asynclint import (
    DEFAULT_EXCLUDES,
    SUPPRESSIONS,
    default_packages,
    lint_paths,
    lint_source,
)


def _rules(source: str, docs_text: str | None = None) -> list[str]:
    return [
        v.rule
        for v in lint_source(textwrap.dedent(source), docs_text=docs_text)
    ]


# ------------------------------------------------------------- the repo


def test_control_plane_has_zero_unexplained_violations():
    report = lint_paths()
    assert not report.violations, "\n" + report.summary()


def test_no_stale_suppressions():
    report = lint_paths()
    assert not report.stale_suppressions, (
        "suppressions no longer matching any violation — delete them:\n"
        + report.summary()
    )
    # every shipped suppression actually fired (the list is exact, not
    # aspirational)
    used = {s for _, s in report.suppressed}
    assert used == set(SUPPRESSIONS)


def test_every_suppression_is_justified():
    for s in SUPPRESSIONS:
        assert len(s.reason.split()) >= 8, (
            f"{s.path} [{s.rule}]: a suppression needs a real justification"
        )


def test_lint_covers_every_registered_bci_metric():
    """The undocumented-metric rule only means something if the scan sees
    the registrations: the control-plane registry surface must be found.
    Since the scope became derived (analysis/ included), the linter's own
    metrics are lintees too — no package gets to grade itself out."""
    report = lint_paths()
    assert "bci_stage_seconds" in report.metric_names
    assert "bci_analysis_seconds" in report.metric_names
    assert len(report.metric_names) >= 20


def test_default_scope_is_derived_not_hand_maintained():
    """The scope comes from the package tree minus the explicit exclude
    list — the hand-maintained include list silently skipped every new
    top-level package (fleet/ shipped a whole PR unlinted that way)."""
    packages = default_packages()
    # the control plane is all in scope...
    for required in ("api", "services", "resilience", "observability",
                     "sessions", "fleet", "analysis"):
        assert required in packages
    # ...and only the declared excludes are out
    for excluded in ("models", "parallel", "ops"):
        assert excluded not in packages
    assert "runtime" in packages  # runtime/ is in; runtime/shim is excluded
    assert "runtime/shim" in DEFAULT_EXCLUDES


def test_fresh_package_is_in_scope_by_default(tmp_path):
    """Regression for the omission bug class: a freshly created top-level
    package must be linted WITHOUT anyone editing a scope list."""
    pkg_root = tmp_path / "fakepkg"
    shiny = pkg_root / "shiny_new_subsystem"
    shiny.mkdir(parents=True)
    (shiny / "__init__.py").write_text("")
    (shiny / "svc.py").write_text(
        "import time\nasync def f():\n    time.sleep(1)\n"
    )
    # excluded subtrees stay out even when present
    excluded = pkg_root / "models"
    excluded.mkdir()
    (excluded / "__init__.py").write_text("")
    (excluded / "bad.py").write_text(
        "import time\nasync def g():\n    time.sleep(1)\n"
    )
    assert default_packages(pkg_root) == ("shiny_new_subsystem",)
    report = lint_paths(pkg_root, docs_path=None, suppressions=())
    assert [v.rule for v in report.violations] == ["blocking-call-in-async"]
    assert report.violations[0].path.endswith("shiny_new_subsystem/svc.py")


# ----------------------------------------------------------- rule units


def test_blocking_calls_flagged_only_in_async_context():
    assert _rules(
        """
        import time
        async def f():
            time.sleep(1)
        """
    ) == ["blocking-call-in-async"]
    # the sanctioned pattern: a sync helper nested inside the async def
    assert _rules(
        """
        import subprocess
        async def f():
            def helper():
                return subprocess.run(["ls"])
            return helper
        """
    ) == []
    # module level / plain sync functions are not the event loop's problem
    assert _rules("import time\ntime.sleep(1)\n") == []
    assert _rules("import time\ndef f():\n    time.sleep(1)\n") == []


def test_blocking_call_resolves_aliases():
    assert _rules(
        """
        import requests as rq
        async def f():
            rq.get("http://x")
        """
    ) == ["blocking-call-in-async"]
    assert _rules(
        """
        from time import sleep
        async def f():
            sleep(1)
        """
    ) == ["blocking-call-in-async"]


def test_sync_open_flagged_in_async_def():
    assert _rules('async def f():\n    open("/tmp/x")\n') == [
        "blocking-call-in-async"
    ]
    # asyncio.sleep and method opens (self.storage.open) are fine
    assert _rules(
        """
        import asyncio
        async def f(self):
            await asyncio.sleep(1)
            self.storage.open("x")
        """
    ) == []


def test_fire_and_forget_task_flagged():
    assert _rules(
        """
        import asyncio
        async def f(c):
            asyncio.ensure_future(c)
        """
    ) == ["fire-and-forget-task"]
    assert _rules(
        """
        import asyncio
        async def f(c):
            asyncio.get_running_loop().create_task(c)
        """
    ) == ["fire-and-forget-task"]
    # Name-rooted receivers are the COMMON spelling and must be caught too
    assert _rules(
        """
        import asyncio
        async def f(c):
            loop = asyncio.get_event_loop()
            loop.create_task(c)
        """
    ) == ["fire-and-forget-task"]
    assert _rules(
        """
        async def f(self, c):
            self._loop.create_task(c)
        """
    ) == ["fire-and-forget-task"]
    # retained handles satisfy the rule: assigned, awaited, passed on
    assert _rules(
        """
        import asyncio
        async def f(self, c, d, e):
            self._task = asyncio.create_task(c)
            await asyncio.ensure_future(d)
            self._tasks.add(asyncio.ensure_future(e))
        """
    ) == []


def test_bare_except_flagged():
    assert _rules(
        """
        def f():
            try:
                pass
            except:
                pass
        """
    ) == ["bare-except"]
    assert _rules(
        """
        def f():
            try:
                pass
            except Exception:
                pass
        """
    ) == []


def test_env_bypass_flagged_for_app_vars_only():
    assert _rules('import os\nos.environ.get("APP_FOO")\n') == ["env-bypass"]
    assert _rules('import os\nos.getenv("APP_FOO", "x")\n') == ["env-bypass"]
    assert _rules('import os\nos.environ["APP_FOO"]\n') == ["env-bypass"]
    assert _rules('import os\nos.environ.get("HOSTNAME")\n') == []
    # writing APP_* into a CHILD env dict is the contract, not a bypass
    assert _rules('env = {"APP_FOO": "1"}\n') == []


def test_undocumented_metric_rule_uses_docs_corpus():
    src = 'metrics.counter("bci_new_thing_total", "help")\n'
    assert _rules(src, docs_text="`bci_new_thing_total` is ...") == []
    assert _rules(src, docs_text="other text") == ["undocumented-metric"]
    # word-bounded: being a substring of a DIFFERENT documented metric
    # does not count as documented...
    assert _rules(
        'metrics.counter("bci_new_thing", "help")\n',
        docs_text="`bci_new_thing_total` is ...",
    ) == ["undocumented-metric"]
    # ...but a trailing label-set brace is not a word character
    assert _rules(
        src, docs_text="bci_new_thing_total{rule} counts ..."
    ) == []
    # without a docs corpus the rule is off (unit-test isolation)
    assert _rules(src) == []
