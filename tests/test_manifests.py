"""Deployment manifests stay loadable and structurally sound.

The reference ships k8s/local.yaml + k8s/pull.yaml (SURVEY.md §2 "k8s
manifests"); ours are local.yaml + tpu.yaml. A malformed manifest only
surfaces at kubectl-apply time in production — catch it in CI instead.
"""

from pathlib import Path

import pytest

yaml = pytest.importorskip("yaml")  # PyYAML: not a runtime dependency

K8S = Path(__file__).resolve().parent.parent / "k8s"


def _docs(name: str) -> list[dict]:
    return [d for d in yaml.safe_load_all((K8S / name).read_text()) if d]


def test_manifests_parse():
    for name in ("local.yaml", "tpu.yaml"):
        docs = _docs(name)
        assert docs, name
        for doc in docs:
            assert "kind" in doc and "metadata" in doc, (name, doc)


def test_rbac_covers_pod_lifecycle():
    # The scheduler creates/waits/deletes pods and streams exec/logs; the
    # Role must allow all of it (reference k8s/local.yaml grants pods +
    # pods/exec + pods/log with verbs *).
    for name in ("local.yaml", "tpu.yaml"):
        roles = [d for d in _docs(name) if d["kind"] == "Role"]
        assert roles, f"{name}: no Role"
        rules = roles[0]["rules"]
        resources = {r for rule in rules for r in rule["resources"]}
        assert {"pods", "pods/exec", "pods/log"} <= resources, (name, resources)
        for rule in rules:
            verbs = set(rule["verbs"])
            assert "*" in verbs or {"create", "get", "delete", "watch"} <= verbs, (
                name,
                verbs,
            )


def test_service_pod_wires_ports_and_storage():
    for name in ("local.yaml", "tpu.yaml"):
        pods = [d for d in _docs(name) if d["kind"] == "Pod"]
        assert pods, f"{name}: no service Pod"
        container = pods[0]["spec"]["containers"][0]
        ports = {p["containerPort"] for p in container.get("ports", [])}
        assert {50051, 50081} <= ports, (name, ports)
        env = {e["name"]: e.get("value") for e in container.get("env", [])}
        assert "APP_FILE_STORAGE_PATH" in env, name


def test_tpu_manifest_sets_slice_topology():
    pods = [d for d in _docs("tpu.yaml") if d["kind"] == "Pod"]
    env = {
        e["name"]: e.get("value")
        for e in pods[0]["spec"]["containers"][0].get("env", [])
    }
    assert "APP_EXECUTOR_IMAGE" in env
    assert any(k.startswith("APP_TPU_") for k in env), env
