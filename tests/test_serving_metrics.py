"""Serving-engine instrumentation (ISSUE 2 acceptance): after a batched
decode, TTFT / inter-token / tokens-per-second / occupancy metrics appear in
the Prometheus exposition, and the engine's queue metrics track intake."""

import jax
import numpy as np

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models.engine import Engine
from bee_code_interpreter_tpu.models.serving import ContinuousBatcher
from bee_code_interpreter_tpu.utils.metrics import Registry


def make_batcher(registry, **kw):
    config = T.TransformerConfig.tiny()
    params = T.init_params(config, jax.random.PRNGKey(0))
    defaults = dict(
        max_batch=2, n_pages=16, page_size=4, max_pages_per_seq=4,
        metrics=registry,
    )
    defaults.update(kw)
    return ContinuousBatcher(params, config, **defaults)


def test_batched_decode_exports_ttft_and_throughput():
    registry = Registry()
    b = make_batcher(registry)
    prompts = [
        np.asarray(
            jax.random.randint(jax.random.PRNGKey(i + 1), (L,), 0,
                               b.config.vocab_size)
        )
        for i, L in enumerate([3, 5])
    ]
    r0 = b.submit(prompts[0], 6)
    r1 = b.submit(prompts[1], 6)
    b.run_to_completion()
    assert b.is_done(r0) and b.is_done(r1)

    text = registry.expose()
    # one TTFT observation per request
    assert "bci_serving_ttft_seconds_count 2" in text
    # 2 requests x 6 tokens
    assert "bci_serving_tokens_total 12" in text
    # steps ran and were timed; inter-token latency observed
    assert "bci_serving_step_seconds_count" in text
    assert "bci_serving_inter_token_seconds_count" in text
    # throughput gauge reads a real rate after a batched decode
    tps = float(
        next(
            line.split()[-1]
            for line in text.splitlines()
            if line.startswith("bci_serving_tokens_per_second ")
        )
    )
    assert tps > 0.0
    # batch drained: occupancy gauges read empty again
    assert "bci_serving_active_rows 0" in text
    assert "bci_serving_batch_occupancy 0" in text


def test_metrics_free_batcher_pays_nothing():
    # metrics=None keeps the hot loop untouched (no attributes, no observes)
    b = make_batcher(None)
    r = b.submit(np.asarray([1, 2, 3]), 4)
    b.run_to_completion()
    assert b.is_done(r)
    assert b._metrics is None


def test_engine_queue_metrics_track_intake_and_wait():
    registry = Registry()
    b = make_batcher(registry, max_batch=1, n_pages=8)
    engine = Engine(b, max_queue=2, metrics=registry)
    t0 = engine.submit(np.asarray([1, 2, 3]), 4)
    t1 = engine.submit(np.asarray([4, 5, 6]), 4)  # waits for the single row
    assert engine.pending == 2  # admission happens inside step()
    text = registry.expose()
    assert "bci_serving_queue_depth 2" in text
    engine.run_to_completion()
    assert engine.is_done(t0) and engine.is_done(t1)
    text = registry.expose()
    # both tickets eventually admitted; their queue wait was observed
    assert "bci_serving_queue_wait_seconds_count 2" in text
    assert "bci_serving_queue_depth 0" in text
    # the requeue/rejection counters exist for scrapers even when zero here
    assert "# TYPE bci_serving_requeues_total counter" in text
    assert "# TYPE bci_serving_queue_rejected_total counter" in text


def test_snapshot_restore_does_not_replay_metrics():
    # Counters are per-process: adopting a snapshot must not pour the
    # snapshot's lifetime token total into the fresh registry, and restored
    # in-flight state must not observe TTFT against a foreign clock.
    reg1 = Registry()
    b1 = make_batcher(reg1)
    b1.submit(np.asarray([1, 2, 3]), 6)
    b1.step()
    b1.step()
    snap = b1.state_dict()

    reg2 = Registry()
    b2 = make_batcher(reg2)
    b2.load_state_dict(snap)
    assert b2._t_submit is None
    import re

    assert not re.search(
        r"^bci_serving_tokens_total \d", reg2.expose(), re.M
    ), "restored lifetime total replayed into the fresh registry"
    b2.run_to_completion()
    generated_before = snap["host"]["n_tokens_generated"]
    expected = b2.n_tokens_generated - generated_before
    assert f"bci_serving_tokens_total {expected}" in reg2.expose()


def test_tokens_per_second_decays_to_zero_when_idle():
    registry = Registry()
    b = make_batcher(registry)
    b.submit(np.asarray([1, 2, 3]), 6)
    b.run_to_completion()
    assert b._tokens_per_second() > 0.0
    # age the window out: an idle server must not report its last burst
    b._rate_samples = type(b._rate_samples)(
        ((t - 1000.0, n) for t, n in b._rate_samples),
        maxlen=b._rate_samples.maxlen,
    )
    assert b._tokens_per_second() == 0.0


def test_engine_counts_queue_rejections():
    registry = Registry()
    b = make_batcher(registry, max_batch=1, n_pages=8)
    engine = Engine(b, max_queue=0, metrics=registry)
    import pytest

    with pytest.raises(RuntimeError, match="queue full"):
        engine.submit(np.asarray([1, 2, 3]), 4)
    assert "bci_serving_queue_rejected_total 1" in registry.expose()
