"""Storage-backend conformance (ISSUE 11, docs/fleet.md "Storage backends").

ONE parametrized suite every backend must pass — local flat directory,
shared mounted directory, and the S3-shaped HTTP backend against the
in-repo ``FakeS3`` — so "snapshot ids resolve identically from any replica"
is proven per backend, never assumed. The cross-instance tests build a
SECOND backend instance over the same root/bucket, which is exactly what a
second replica is."""

import asyncio
import hashlib

import pytest

from bee_code_interpreter_tpu.services.storage import (
    LocalDirectoryBackend,
    S3HttpBackend,
    SharedDirectoryBackend,
    Storage,
)
from tests.fakes import FakeS3

BACKENDS = ("local", "shared", "s3")


class _Harness:
    """Builds N independent Storage instances over ONE shared substrate
    (directory or fake bucket) — instance #2 models a second replica."""

    def __init__(self, kind: str, tmp_path) -> None:
        self.kind = kind
        self.tmp_path = tmp_path
        self.s3: FakeS3 | None = None
        self._instances: list[Storage] = []

    async def start(self) -> "_Harness":
        if self.kind == "s3":
            self.s3 = await FakeS3().start()
        return self

    def instance(self) -> Storage:
        if self.kind == "local":
            backend = LocalDirectoryBackend(self.tmp_path / "objects")
        elif self.kind == "shared":
            backend = SharedDirectoryBackend(
                self.tmp_path / "objects", orphan_min_age_s=3600.0
            )
        else:
            backend = S3HttpBackend(self.s3.endpoint, "snapshots")
        storage = Storage(backend=backend)
        self._instances.append(storage)
        return storage

    def stored_object_count(self) -> int:
        if self.kind == "s3":
            return len(self.s3.objects)
        root = self.tmp_path / "objects"
        if not root.is_dir():
            return 0
        return sum(1 for p in root.iterdir() if not p.name.startswith(".tmp-"))

    async def stop(self) -> None:
        for storage in self._instances:
            await storage.aclose()
        if self.s3 is not None:
            await self.s3.stop()


@pytest.fixture(params=BACKENDS)
def harness_kind(request):
    return request.param


async def _with_harness(kind, tmp_path, body):
    harness = await _Harness(kind, tmp_path).start()
    try:
        await body(harness)
    finally:
        await harness.stop()


async def test_roundtrip_is_hash_identical_across_backends(
    harness_kind, tmp_path
):
    """The object id is the sha256 of the content on EVERY backend — the
    invariant that lets a snapshot id minted on one replica resolve on
    another regardless of which backend either runs."""

    async def body(harness):
        storage = harness.instance()
        data = b"deterministic snapshot bytes"
        object_id = await storage.write(data)
        assert object_id == hashlib.sha256(data).hexdigest()
        assert await storage.read(object_id) == data
        assert await storage.exists(object_id)

    await _with_harness(harness_kind, tmp_path, body)


async def test_identical_content_dedups_to_one_object(harness_kind, tmp_path):
    async def body(harness):
        storage = harness.instance()
        a = await storage.write(b"same bytes")
        b = await storage.write(b"same bytes")
        assert a == b
        assert harness.stored_object_count() == 1

    await _with_harness(harness_kind, tmp_path, body)


async def test_concurrent_writers_are_safe(harness_kind, tmp_path):
    """Racing writers — identical AND distinct content, interleaved chunked
    streams — all commit; identical content still lands as one object."""

    async def body(harness):
        storage = harness.instance()

        async def write_chunked(payload: bytes) -> str:
            async with storage.writer() as w:
                for i in range(0, len(payload), 7):
                    await w.write(payload[i : i + 7])
                    await asyncio.sleep(0)
            return w.hash

        same = b"contended identical content" * 3
        ids = await asyncio.gather(
            write_chunked(same),
            write_chunked(same),
            write_chunked(b"writer three has its own bytes"),
            write_chunked(same),
        )
        assert ids[0] == ids[1] == ids[3]
        assert ids[2] != ids[0]
        assert harness.stored_object_count() == 2
        for object_id, payload in ((ids[0], same), (ids[2], b"writer three has its own bytes")):
            assert await storage.read(object_id) == payload

    await _with_harness(harness_kind, tmp_path, body)


async def test_missing_object_errors_uniformly(harness_kind, tmp_path):
    async def body(harness):
        storage = harness.instance()
        missing = "0" * 64
        assert not await storage.exists(missing)
        with pytest.raises(FileNotFoundError):
            await storage.read(missing)

    await _with_harness(harness_kind, tmp_path, body)


async def test_aborted_write_publishes_nothing(harness_kind, tmp_path):
    async def body(harness):
        class Boom(Exception):
            pass

        storage = harness.instance()
        with pytest.raises(Boom):
            async with storage.writer() as w:
                await w.write(b"partial upload")
                raise Boom()
        assert harness.stored_object_count() == 0

    await _with_harness(harness_kind, tmp_path, body)


async def test_second_instance_reads_what_first_wrote(harness_kind, tmp_path):
    """Replica-agnosticism proven, not assumed (the acceptance criterion):
    a snapshot written via one backend instance is readable — and reports
    exists() — from a second instance pointed at the same root/bucket."""

    async def body(harness):
        writer_replica = harness.instance()
        object_id = await writer_replica.write(b"checkpointed on replica A")
        reader_replica = harness.instance()
        assert await reader_replica.exists(object_id)
        assert await reader_replica.read(object_id) == b"checkpointed on replica A"
        # and the reverse direction, for symmetry
        back = await reader_replica.write(b"written on replica B")
        assert await writer_replica.read(back) == b"written on replica B"

    await _with_harness(harness_kind, tmp_path, body)


# ------------------------------------------------- orphan startup sweep


async def test_startup_sweep_reaps_crashed_writer_temps(tmp_path):
    """A crash mid-ObjectWriter leaks ``.tmp-*`` forever (the TTL sweep
    skips in-flight temps by design); the NEXT process's once-only sweep —
    kicked by its first write, or explicitly at boot — reaps them, counted
    once."""
    import os
    import time

    root = tmp_path / "objects"
    root.mkdir(parents=True)
    past = time.time() - 30  # crashed before this process started
    for name in (".tmp-deadbeefdeadbeef", ".tmp-cafecafecafecafe"):
        (root / name).write_bytes(b"crashed upload")
        os.utime(root / name, (past, past))
    # the TTL sweep's own crash-recovery guards are NOT this sweep's to touch
    guard = root / (".tmp-sweep-" + "a" * 64)
    guard.write_bytes(b"ttl sweep guard")

    storage = Storage(root)
    assert storage.orphans_recovered is None  # not yet swept
    assert await storage.recover_orphans() == 2
    assert storage.orphans_recovered == 2
    names = {p.name for p in root.iterdir()}
    assert names == {guard.name}
    # the sweep is once-only, and a write triggers it on a fresh instance
    assert await storage.recover_orphans() == 2
    fresh = Storage(root)
    await fresh.write(b"first write kicks the sweep")
    assert fresh.orphans_recovered == 0


async def test_shared_backend_startup_sweep_spares_live_uploads(tmp_path):
    """On a SHARED root another replica may be mid-upload: only temps older
    than the min-age gate are orphans."""
    import os
    import time

    root = tmp_path / "objects"
    root.mkdir(parents=True)
    fresh = root / ".tmp-0123456789abcdef"
    fresh.write_bytes(b"another replica, still uploading")
    stale = root / ".tmp-fedcba9876543210"
    stale.write_bytes(b"crashed last week")
    past = time.time() - 7200
    os.utime(stale, (past, past))

    backend = SharedDirectoryBackend(root, orphan_min_age_s=3600.0)
    assert await backend.recover_orphans() == 1
    assert fresh.exists() and not stale.exists()


async def test_shared_backend_commit_survives_to_second_instance(tmp_path):
    """The fsync'd commit path round-trips (behavioral smoke — durability
    itself needs a crash harness) and streams chunk-by-chunk like the
    driver does."""
    a = Storage(backend=SharedDirectoryBackend(tmp_path / "objects"))
    async with a.writer() as w:
        await w.write(b"part1-")
        await w.write(b"part2")
    b = Storage(backend=SharedDirectoryBackend(tmp_path / "objects"))
    chunks = []
    async with b.reader(w.hash) as r:
        async for chunk in r:
            chunks.append(chunk)
    assert b"".join(chunks) == b"part1-part2"


async def test_s3_backend_sweep_is_accounted_noop(tmp_path):
    s3 = await FakeS3().start()
    try:
        storage = Storage(backend=S3HttpBackend(s3.endpoint, "snapshots"))
        object_id = await storage.write(b"lifecycle-managed")
        assert await storage.sweep(max_age_s=0.001) == 0
        assert await storage.read(object_id) == b"lifecycle-managed"
        await storage.aclose()
    finally:
        await s3.stop()


async def test_s3_backend_surfaces_server_errors(tmp_path):
    s3 = await FakeS3().start()
    try:
        storage = Storage(backend=S3HttpBackend(s3.endpoint, "snapshots"))
        s3.fail_next = 1
        with pytest.raises(OSError):
            await storage.write(b"rejected upload")
        s3.fail_next = 0
        object_id = await storage.write(b"accepted upload")
        s3.fail_next = 1
        with pytest.raises(OSError):
            await storage.read(object_id)
        await storage.aclose()
    finally:
        await s3.stop()


def test_from_config_selects_backend(tmp_path):
    from bee_code_interpreter_tpu.config import Config

    base = dict(file_storage_path=str(tmp_path / "objects"))
    assert Storage.from_config(Config(**base)).describe()["backend"] == "local"
    shared = Storage.from_config(Config(**base, storage_backend="shared"))
    assert shared.describe()["backend"] == "shared"
    s3 = Storage.from_config(
        Config(
            **base,
            storage_backend="s3",
            storage_s3_endpoint="http://127.0.0.1:9",
            storage_s3_bucket="snaps",
        )
    )
    assert s3.describe() == {
        "backend": "s3",
        "endpoint": "http://127.0.0.1:9",
        "bucket": "snaps",
    }
    with pytest.raises(ValueError, match="STORAGE_S3_ENDPOINT"):
        Storage.from_config(Config(**base, storage_backend="s3"))
