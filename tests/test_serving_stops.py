"""Stop sequences, finish reasons, and per-token logprobs in the
continuous batcher — the request-level serving contract on top of the
decode machinery (models/serving.py).

Semantics pinned here: a matched stop sequence retires the request and is
TRIMMED from the result (eos, the model's own stop, stays in); finish
reasons are 'eos' | 'stop' | 'length'; logprobs report the UNFILTERED
model distribution (log-softmax of the raw logits row), so the same token
reports the same value whatever top-k/top-p produced it, and they are
identical between the plain and speculative paths (same tokens, same
target distributions).
"""

import dataclasses
import math

import numpy as np
import pytest

import jax

from bee_code_interpreter_tpu.models.serving import (
    ContinuousBatcher,
    SamplingParams,
    logprob_of,
)
from bee_code_interpreter_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)

CFG = dataclasses.replace(TransformerConfig.tiny(), n_kv_heads=2)
PARAMS = init_params(CFG, jax.random.PRNGKey(0))
PROMPT = [5, 3, 7, 2, 9, 4, 1, 8]


def make_batcher(**kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("n_pages", 32)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_pages_per_seq", 8)
    return ContinuousBatcher(PARAMS, CFG, **kw)


def run_one(b, prompt, n, **kw):
    r = b.submit(prompt, n, **kw)
    b.run_to_completion()
    return r


def greedy_tokens(n):
    b = make_batcher()
    return b.result(run_one(b, PROMPT, n))


def test_stop_sequence_trims_and_reports_stop():
    want = greedy_tokens(10)
    # stop on the 4th+5th greedy tokens: result must be the first three
    stop = (want[3], want[4])
    b = make_batcher()
    r = run_one(b, PROMPT, 10, sampling=SamplingParams(stop_sequences=(stop,)))
    assert b.result(r) == want[:3]
    assert b.finish_reason(r) == "stop"


def test_first_token_stop_can_empty_the_result():
    want = greedy_tokens(3)
    b = make_batcher()
    r = run_one(b, PROMPT, 3,
                sampling=SamplingParams(stop_sequences=((want[0],),)))
    assert b.result(r) == []
    assert b.finish_reason(r) == "stop"


def test_finish_reasons_length_and_eos():
    want = greedy_tokens(6)
    b = make_batcher()
    r = run_one(b, PROMPT, 6)
    assert b.finish_reason(r) == "length"
    # eos: pick the 3rd greedy token as eos; it stays in the output
    b2 = make_batcher(eos_id=want[2])
    r2 = run_one(b2, PROMPT, 6)
    assert b2.result(r2) == want[:3]
    assert b2.finish_reason(r2) == "eos"
    # finish reason survives release; still-decoding raises
    b2.release(r2)
    assert b2.finish_reason(r2) == "eos"
    with pytest.raises(KeyError):
        b.finish_reason(999)


def test_eos_wins_over_stop_sequence():
    want = greedy_tokens(6)
    b = make_batcher(eos_id=want[2])
    r = run_one(b, PROMPT, 6,
                sampling=SamplingParams(stop_sequences=((want[2],),)))
    assert b.result(r) == want[:3]  # eos kept, not trimmed
    assert b.finish_reason(r) == "eos"


def test_greedy_logprobs_match_manual_log_softmax():
    n = 5
    want = greedy_tokens(n)
    b = make_batcher()
    r = run_one(b, PROMPT, n, sampling=SamplingParams(logprobs=True))
    assert b.result(r) == want
    lps = b.result_logprobs(r)
    assert len(lps) == n
    # greedy tokens are each row's argmax -> every logprob is the max
    # log-softmax entry, finite and <= 0
    assert all(math.isfinite(x) and x <= 0.0 for x in lps)
    # spot-check the helper against numpy on a synthetic row
    row = np.array([0.1, 2.0, -1.0, 0.5], dtype=np.float32)
    want_lp = float(
        np.log(np.exp(row.astype(np.float64) - row.max())
               / np.exp(row.astype(np.float64) - row.max()).sum())[1]
    )
    assert abs(logprob_of(row, 1) - want_lp) < 1e-12


def test_logprobs_are_unfiltered_under_sampling():
    """A top-k=1 sampled request emits the greedy tokens; its logprobs
    must equal the greedy request's (the filter never changes the
    report)."""
    n = 5
    b = make_batcher()
    r_greedy = run_one(b, PROMPT, n, sampling=SamplingParams(logprobs=True))
    greedy_lps = b.result_logprobs(r_greedy)
    b2 = make_batcher()
    r_k1 = run_one(
        b2, PROMPT, n,
        sampling=SamplingParams(temperature=0.7, top_k=1, logprobs=True,
                                seed=3),
    )
    assert b2.result(r_k1) == b.result(r_greedy)
    np.testing.assert_allclose(b2.result_logprobs(r_k1), greedy_lps,
                               rtol=1e-5)


def test_speculative_logprobs_and_stops_match_plain():
    draft_cfg = dataclasses.replace(CFG, n_layers=1)
    draft = init_params(draft_cfg, jax.random.PRNGKey(2))
    n = 8
    want = greedy_tokens(n)
    stop = (want[4], want[5])
    sp = SamplingParams(stop_sequences=(stop,), logprobs=True)

    plain = make_batcher()
    r_p = run_one(plain, PROMPT, n, sampling=sp)

    b = ContinuousBatcher(
        PARAMS, CFG, max_batch=2, n_pages=32, page_size=4,
        max_pages_per_seq=8, draft_params=draft, draft_config=draft_cfg,
        gamma=3,
    )
    r_s = run_one(b, PROMPT, n, sampling=sp)
    assert b.result(r_s) == plain.result(r_p) == want[:4]
    assert b.finish_reason(r_s) == plain.finish_reason(r_p) == "stop"
    # same tokens, same target distributions -> same logprobs (the verify
    # window and the single-step program differ only at the ULP level)
    np.testing.assert_allclose(
        b.result_logprobs(r_s), plain.result_logprobs(r_p), atol=1e-3
    )


def test_logprobs_released_and_unrecorded_requests_raise():
    b = make_batcher()
    r_plain = run_one(b, PROMPT, 3)
    with pytest.raises(KeyError, match="did not record"):
        b.result_logprobs(r_plain)
    r_lp = run_one(b, PROMPT, 3, sampling=SamplingParams(logprobs=True))
    assert len(b.result_logprobs(r_lp)) == 3
    b.release(r_lp)
    with pytest.raises(KeyError, match="released"):
        b.result_logprobs(r_lp)


def test_empty_stop_sequence_rejected():
    with pytest.raises(ValueError, match="non-empty"):
        SamplingParams(stop_sequences=((),))


def test_unknown_request_logprobs_says_unknown():
    with pytest.raises(KeyError, match="unknown request"):
        make_batcher().result_logprobs(999)


def test_moe_serving_is_deterministic_not_solo_pinned():
    """MoE through the plain batcher: usable and deterministic — two
    identical batcher runs produce identical outputs — but NOT pinned
    equal to solo decode (capacity routing couples batch-mates and the
    padded admission prompt; the module docstring documents the stance,
    tests/test_moe.py the underlying inherent property)."""
    cfg = dataclasses.replace(TransformerConfig.tiny_moe(),
                              moe_capacity_factor=8.0)
    params = init_params(cfg, jax.random.PRNGKey(0))

    def run():
        b = ContinuousBatcher(params, cfg, max_batch=2, n_pages=32,
                              page_size=4, max_pages_per_seq=8)
        r1 = b.submit(PROMPT, 5)
        r2 = b.submit([3, 1, 4, 1, 5], 5)
        b.run_to_completion()
        return b.result(r1), b.result(r2)

    first, second = run(), run()
    assert first == second
    assert all(len(out) == 5 for out in first)
