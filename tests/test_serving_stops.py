"""Stop sequences, finish reasons, and per-token logprobs in the
continuous batcher — the request-level serving contract on top of the
decode machinery (models/serving.py).

Semantics pinned here: a matched stop sequence retires the request and is
TRIMMED from the result (eos, the model's own stop, stays in); finish
reasons are 'eos' | 'stop' | 'length'; logprobs report the UNFILTERED
model distribution (log-softmax of the raw logits row), so the same token
reports the same value whatever top-k/top-p produced it, and they are
identical between the plain and speculative paths (same tokens, same
target distributions).
"""

import dataclasses
import math

import numpy as np
import pytest

import jax

from bee_code_interpreter_tpu.models.serving import (
    ContinuousBatcher,
    SamplingParams,
    logprob_of,
)
from bee_code_interpreter_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)

CFG = dataclasses.replace(TransformerConfig.tiny(), n_kv_heads=2)
PARAMS = init_params(CFG, jax.random.PRNGKey(0))
PROMPT = [5, 3, 7, 2, 9, 4, 1, 8]


def make_batcher(**kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("n_pages", 32)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_pages_per_seq", 8)
    return ContinuousBatcher(PARAMS, CFG, **kw)


def run_one(b, prompt, n, **kw):
    r = b.submit(prompt, n, **kw)
    b.run_to_completion()
    return r


def greedy_tokens(n):
    b = make_batcher()
    return b.result(run_one(b, PROMPT, n))


def test_stop_sequence_trims_and_reports_stop():
    want = greedy_tokens(10)
    # stop on the 4th+5th greedy tokens: result must be the first three
    stop = (want[3], want[4])
    b = make_batcher()
    r = run_one(b, PROMPT, 10, sampling=SamplingParams(stop_sequences=(stop,)))
    assert b.result(r) == want[:3]
    assert b.finish_reason(r) == "stop"


def test_first_token_stop_can_empty_the_result():
    want = greedy_tokens(3)
    b = make_batcher()
    r = run_one(b, PROMPT, 3,
                sampling=SamplingParams(stop_sequences=((want[0],),)))
    assert b.result(r) == []
    assert b.finish_reason(r) == "stop"


def test_finish_reasons_length_and_eos():
    want = greedy_tokens(6)
    b = make_batcher()
    r = run_one(b, PROMPT, 6)
    assert b.finish_reason(r) == "length"
    # eos: pick the 3rd greedy token as eos; it stays in the output
    b2 = make_batcher(eos_id=want[2])
    r2 = run_one(b2, PROMPT, 6)
    assert b2.result(r2) == want[:3]
    assert b2.finish_reason(r2) == "eos"
    # finish reason survives release; still-decoding raises
    b2.release(r2)
    assert b2.finish_reason(r2) == "eos"
    with pytest.raises(KeyError):
        b.finish_reason(999)


def test_eos_wins_over_stop_sequence():
    want = greedy_tokens(6)
    b = make_batcher(eos_id=want[2])
    r = run_one(b, PROMPT, 6,
                sampling=SamplingParams(stop_sequences=((want[2],),)))
    assert b.result(r) == want[:3]  # eos kept, not trimmed
    assert b.finish_reason(r) == "eos"


def test_greedy_logprobs_match_manual_log_softmax():
    n = 5
    want = greedy_tokens(n)
    b = make_batcher()
    r = run_one(b, PROMPT, n, sampling=SamplingParams(logprobs=True))
    assert b.result(r) == want
    lps = b.result_logprobs(r)
    assert len(lps) == n
    # greedy tokens are each row's argmax -> every logprob is the max
    # log-softmax entry, finite and <= 0
    assert all(math.isfinite(x) and x <= 0.0 for x in lps)
    # spot-check the helper against numpy on a synthetic row
    row = np.array([0.1, 2.0, -1.0, 0.5], dtype=np.float32)
    want_lp = float(
        np.log(np.exp(row.astype(np.float64) - row.max())
               / np.exp(row.astype(np.float64) - row.max()).sum())[1]
    )
    assert abs(logprob_of(row, 1) - want_lp) < 1e-12


def test_logprobs_are_unfiltered_under_sampling():
    """A top-k=1 sampled request emits the greedy tokens; its logprobs
    must equal the greedy request's (the filter never changes the
    report)."""
    n = 5
    b = make_batcher()
    r_greedy = run_one(b, PROMPT, n, sampling=SamplingParams(logprobs=True))
    greedy_lps = b.result_logprobs(r_greedy)
    b2 = make_batcher()
    r_k1 = run_one(
        b2, PROMPT, n,
        sampling=SamplingParams(temperature=0.7, top_k=1, logprobs=True,
                                seed=3),
    )
    assert b2.result(r_k1) == b.result(r_greedy)
    np.testing.assert_allclose(b2.result_logprobs(r_k1), greedy_lps,
                               rtol=1e-5)


def test_speculative_logprobs_and_stops_match_plain():
    draft_cfg = dataclasses.replace(CFG, n_layers=1)
    draft = init_params(draft_cfg, jax.random.PRNGKey(2))
    n = 8
    want = greedy_tokens(n)
    stop = (want[4], want[5])
    sp = SamplingParams(stop_sequences=(stop,), logprobs=True)

    plain = make_batcher()
    r_p = run_one(plain, PROMPT, n, sampling=sp)

    b = ContinuousBatcher(
        PARAMS, CFG, max_batch=2, n_pages=32, page_size=4,
        max_pages_per_seq=8, draft_params=draft, draft_config=draft_cfg,
        gamma=3,
    )
    r_s = run_one(b, PROMPT, n, sampling=sp)
    assert b.result(r_s) == plain.result(r_p) == want[:4]
    assert b.finish_reason(r_s) == plain.finish_reason(r_p) == "stop"
    # same tokens, same target distributions -> same logprobs (the verify
    # window and the single-step program differ only at the ULP level)
    np.testing.assert_allclose(
        b.result_logprobs(r_s), plain.result_logprobs(r_p), atol=1e-3
    )


def test_logprobs_released_and_unrecorded_requests_raise():
    b = make_batcher()
    r_plain = run_one(b, PROMPT, 3)
    with pytest.raises(KeyError, match="did not record"):
        b.result_logprobs(r_plain)
    r_lp = run_one(b, PROMPT, 3, sampling=SamplingParams(logprobs=True))
    assert len(b.result_logprobs(r_lp)) == 3
    b.release(r_lp)
    with pytest.raises(KeyError, match="released"):
        b.result_logprobs(r_lp)


def test_empty_stop_sequence_rejected():
    with pytest.raises(ValueError, match="non-empty"):
        SamplingParams(stop_sequences=((),))


def test_unknown_request_logprobs_says_unknown():
    with pytest.raises(KeyError, match="unknown request"):
        make_batcher().result_logprobs(999)


def test_moe_serving_is_deterministic_not_solo_pinned():
    """MoE through the plain batcher: usable and deterministic — two
    identical batcher runs produce identical outputs — but NOT pinned
    equal to solo decode (capacity routing couples batch-mates and the
    padded admission prompt; the module docstring documents the stance,
    tests/test_moe.py the underlying inherent property)."""
    cfg = dataclasses.replace(TransformerConfig.tiny_moe(),
                              moe_capacity_factor=8.0)
    params = init_params(cfg, jax.random.PRNGKey(0))

    def run():
        b = ContinuousBatcher(params, cfg, max_batch=2, n_pages=32,
                              page_size=4, max_pages_per_seq=8)
        r1 = b.submit(PROMPT, 5)
        r2 = b.submit([3, 1, 4, 1, 5], 5)
        b.run_to_completion()
        return b.result(r1), b.result(r2)

    first, second = run(), run()
    assert first == second
    assert all(len(out) == 5 for out in first)


# ------------------------- logit_bias / allowed_tokens (constrained decode)

def test_logit_bias_bans_and_forces():
    want = greedy_tokens(4)
    # ban the greedy first token: output must start differently
    b = make_batcher()
    r = run_one(b, PROMPT, 4,
                sampling=SamplingParams(logit_bias={want[0]: -1e9}))
    banned = b.result(r)
    # the bias applies at EVERY step, not just admission
    assert want[0] not in banned
    # force an arbitrary token everywhere with a huge positive bias
    b2 = make_batcher()
    r2 = run_one(b2, PROMPT, 4, sampling=SamplingParams(logit_bias={7: 1e9}))
    assert b2.result(r2) == [7, 7, 7, 7]


def test_allowed_tokens_masks_greedy_to_the_set():
    allowed_set = [2, 3, 5, 7, 11, 13]
    b = make_batcher()
    r = run_one(b, PROMPT, 6,
                sampling=SamplingParams(
                    allowed_tokens=lambda generated: allowed_set))
    assert all(t in allowed_set for t in b.result(r))


def test_allowed_tokens_sees_generated_prefixes():
    seen = []

    def constraint(generated):
        seen.append(list(generated))
        return None  # unconstrained: output must equal plain greedy

    b = make_batcher()
    r = run_one(b, PROMPT, 4,
                sampling=SamplingParams(allowed_tokens=constraint))
    out = b.result(r)
    assert out == greedy_tokens(4)
    assert seen == [out[:i] for i in range(4)]


def test_grammar_style_constraint_drives_a_sequence():
    """A stateful grammar: after token A only B is legal, after B only A —
    the closure-over-parser-state pattern a JSON engine would use."""
    A, B = 9, 17

    def alternate(generated):
        if not generated:
            return [A]
        return [B] if generated[-1] == A else [A]

    b = make_batcher()
    r = run_one(b, PROMPT, 6,
                sampling=SamplingParams(allowed_tokens=alternate))
    assert b.result(r) == [A, B, A, B, A, B]


def test_sampled_constrained_draws_stay_in_set_and_are_seeded():
    allowed_set = [1, 2, 3, 4]
    sp = SamplingParams(temperature=1.5, seed=11,
                        allowed_tokens=lambda g: allowed_set)
    b = make_batcher()
    out1 = b.result(run_one(b, PROMPT, 8, sampling=sp))
    b2 = make_batcher()
    out2 = b2.result(run_one(b2, PROMPT, 8, sampling=sp))
    assert out1 == out2  # same seed, same draws
    assert all(t in allowed_set for t in out1)
    assert len(set(out1)) > 1  # hot temperature actually explores the set


def test_logprobs_report_model_distribution_even_when_steered():
    b = make_batcher()
    r = run_one(b, PROMPT, 3,
                sampling=SamplingParams(logit_bias={7: 1e9}, logprobs=True))
    assert b.result(r) == [7, 7, 7]
    # 7 is (whp) not the model's argmax: its raw logprob is well below 0,
    # proving the report ignores the bias that forced it
    assert all(lp < -0.5 for lp in b.result_logprobs(r))


def test_speculative_refuses_steering():
    draft_cfg = dataclasses.replace(CFG, n_layers=1)
    draft = init_params(draft_cfg, jax.random.PRNGKey(2))
    b = ContinuousBatcher(
        PARAMS, CFG, max_batch=2, n_pages=32, page_size=4,
        max_pages_per_seq=8, draft_params=draft, draft_config=draft_cfg,
    )
    with pytest.raises(ValueError, match="unsteered argmax"):
        b.submit(PROMPT, 4, sampling=SamplingParams(logit_bias={1: 5.0}))
    with pytest.raises(ValueError, match="unsteered argmax"):
        b.submit(PROMPT, 4,
                 sampling=SamplingParams(allowed_tokens=lambda g: [1]))


def test_terminal_constraint_at_admission_completes_empty():
    """A grammar already in its terminal state at step 0 is a FINISHED
    request with an empty output — not an error, and no leaked pages."""
    b = make_batcher()
    free0 = len(b.free_pages)
    r = b.submit(PROMPT, 4,
                 sampling=SamplingParams(allowed_tokens=lambda g: [],
                                         logprobs=True))
    assert b.is_done(r)
    assert b.result(r) == []
    assert b.result_logprobs(r) == []
    assert b.finish_reason(r) == "constraint"
    assert len(b.free_pages) == free0  # nothing leaked


def test_terminal_constraint_mid_decode_retires_cleanly():
    """A grammar completing after 3 tokens retires the request with
    finish reason 'constraint'; its batch-mate keeps decoding."""
    A, B_tok = 9, 17

    def three_then_done(generated):
        if len(generated) >= 3:
            return []
        return [A] if len(generated) % 2 == 0 else [B_tok]

    b = make_batcher()
    r_grammar = b.submit(
        PROMPT, 10,
        sampling=SamplingParams(allowed_tokens=three_then_done),
    )
    r_plain = b.submit([3, 1, 4, 1, 5], 6)
    b.run_to_completion()
    assert b.result(r_grammar) == [A, B_tok, A]
    assert b.finish_reason(r_grammar) == "constraint"
    assert len(b.result(r_plain)) == 6  # batch-mate unaffected
    assert b.finish_reason(r_plain) == "length"
    assert (b.page_ref > 0).sum() == 0  # all pages back


def test_buggy_constraint_retires_with_error_not_wedge():
    """A user callable that raises mid-decode retires ITS row with finish
    reason 'error' (message recorded); the batch keeps serving."""

    def explode_after_two(generated):
        if len(generated) >= 2:
            raise KeyError("grammar state corrupted")
        return None

    b = make_batcher()
    r_bad = b.submit(
        PROMPT, 8, sampling=SamplingParams(allowed_tokens=explode_after_two)
    )
    r_ok = b.submit([3, 1, 4, 1, 5], 6)
    b.run_to_completion()
    assert b.finish_reason(r_bad) == "error"
    assert "grammar state corrupted" in b.request_error(r_bad)
    assert len(b.result(r_bad)) == 2  # tokens before the failure kept
    assert len(b.result(r_ok)) == 6
    assert b.request_error(r_ok) is None
    assert (b.page_ref > 0).sum() == 0


def test_out_of_vocab_constraint_is_an_error():
    b = make_batcher()
    r = b.submit(
        PROMPT, 4,
        sampling=SamplingParams(
            allowed_tokens=lambda g: [10**9] if g else None
        ),
    )
    b.run_to_completion()
    assert b.finish_reason(r) == "error"
    assert "out-of-vocab" in b.request_error(r)


def test_cancel_frees_the_row_and_keeps_partial_output():
    b = make_batcher()
    r_cancel = b.submit(PROMPT, 20)
    r_keep = b.submit([3, 1, 4, 1, 5], 6)
    b.step()
    b.step()
    free_before = len(b.free_pages)
    b.cancel(r_cancel)
    assert b.is_done(r_cancel)
    assert b.finish_reason(r_cancel) == "cancelled"
    assert len(b.result(r_cancel)) == 3  # first token + two steps
    assert len(b.free_pages) > free_before  # pages back immediately
    # the freed row is admittable again while the batch-mate finishes
    r_new = b.submit(PROMPT, 4)
    b.run_to_completion()
    assert len(b.result(r_keep)) == 6
    assert b.result(r_new) == greedy_tokens(4)
    # cancelling a finished request is a no-op, not an error
    b.cancel(r_keep)
    assert b.finish_reason(r_keep) == "length"


def test_cancel_unknown_id_raises():
    with pytest.raises(KeyError, match="unknown request"):
        make_batcher().cancel(999)
