"""LoRA fine-tuning: zero-init identity, lora-only training, counts."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models.lora import (
    init_lora,
    lora_param_count,
    make_lora_train_step,
    merge_lora,
)


def cfg():
    return dataclasses.replace(T.TransformerConfig.tiny(), dtype=jnp.float32)


def test_zero_init_is_identity():
    # B starts at zero, so the adapted model IS the base model.
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    lora = init_lora(config, jax.random.PRNGKey(1), rank=4)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, config.vocab_size)
    base = T.forward(params, tokens, config)
    merged = T.forward(merge_lora(params, lora), tokens, config)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(merged))


def test_lora_training_decreases_loss_and_freezes_base():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    base_snapshot = jax.tree.map(lambda x: np.asarray(x).copy(), params)
    lora = init_lora(config, jax.random.PRNGKey(1), rank=4)
    step, optimizer = make_lora_train_step(config)
    opt_state = optimizer.init(lora)

    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 17), 0, config.vocab_size)
    batch = {"tokens": tokens[:, :-1], "targets": tokens[:, 1:]}

    losses = []
    for _ in range(8):
        lora, opt_state, loss = step(lora, opt_state, params, batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    # the base never moves
    for got, want in zip(jax.tree.leaves(params), jax.tree.leaves(base_snapshot)):
        np.testing.assert_array_equal(np.asarray(got), want)
    # and the adapters did
    assert any(
        float(jnp.abs(ab["B"]).max()) > 0 for ab in lora.values()
    )


def test_param_count_is_small():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    n_base = sum(x.size for x in jax.tree.leaves(params))
    lora = init_lora(config, jax.random.PRNGKey(1), rank=4)
    assert lora_param_count(lora) < n_base * 0.05


def test_unknown_target_rejected():
    with pytest.raises(ValueError, match="no LoRA target"):
        init_lora(cfg(), jax.random.PRNGKey(0), targets=("w_nope",))


def test_merged_decode_consistency():
    # A trained adapter merged into the base must decode consistently
    # through the cached path (merge produces ordinary params).
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    lora = init_lora(config, jax.random.PRNGKey(1), rank=2)
    # give B some nonzero content so the adapter actually changes logits
    lora = jax.tree.map(lambda x: x + 0.01, lora)
    merged = merge_lora(params, lora)
    model = T.Transformer(config)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 5), 0, config.vocab_size)
    a = model.generate(merged, prompt, max_new_tokens=4)
    b = model.generate_cached(merged, prompt, max_new_tokens=4)
    assert (a == b).all()


def test_lora_generalizes_to_vit():
    # init_lora_from_layers works for any stacked-layer family — the ViT's
    # encoder blocks here: zero-init identity, then a lora-only train step
    # moves logits while the base stays frozen.
    import optax

    from bee_code_interpreter_tpu.models import vit as V
    from bee_code_interpreter_tpu.models.lora import (
        init_lora_from_layers,
        merge_lora,
    )

    config = dataclasses.replace(V.ViTConfig.tiny(), dtype=jnp.float32)
    params = V.init_params(config, jax.random.PRNGKey(0))
    lora = init_lora_from_layers(
        params["layers"], jax.random.PRNGKey(1), rank=4, targets=("wq", "wv")
    )
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 32, 3))
    base = V.forward(params, x, config)
    merged = V.forward(merge_lora(params, lora), x, config)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(merged))

    def lora_loss(lora, params, batch):
        logits = V.forward(merge_lora(params, lora), batch["images"], config)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["labels"]
        ).mean()

    batch = {
        "images": x,
        "labels": jax.random.randint(jax.random.PRNGKey(3), (2,), 0, 10),
    }
    grads = jax.grad(lora_loss)(lora, params, batch)
    assert any(float(jnp.abs(g).max()) > 0 for g in jax.tree.leaves(grads))
