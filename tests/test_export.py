"""Telemetry exporter (observability/export.py): OTLP/JSON wire shape,
batching, bounded-queue drop accounting, and retry/backoff against a fake
collector. The invariant under test throughout: every enqueued trace ends up
exported, dropped-and-accounted, or still queued — never silently lost."""

import json
import re

import pytest

from bee_code_interpreter_tpu.observability import (
    TelemetryExporter,
    Tracer,
    metrics_payload,
    span,
    spans_payload,
)
from bee_code_interpreter_tpu.resilience import RetryPolicy
from bee_code_interpreter_tpu.utils.metrics import Registry
from tests.fakes import FakeCollector

FAST_RETRY = RetryPolicy(attempts=3, wait_min_s=0.001, wait_max_s=0.002)


def make_trace(tracer: Tracer, name: str = "/v1/execute"):
    with tracer.trace(name, request_id="rid-1") as t:
        with span("execute", pod="pod-1"):
            pass
    return t


def counter_value(registry: Registry, name: str, **labels) -> float:
    metric = registry.metrics[name]
    return metric._values.get(tuple(sorted(labels.items())), 0.0)


class CaptureTransport:
    """Records (path, payload) per send; scripts failures via ``fail_next``."""

    def __init__(self, fail_next: int = 0) -> None:
        self.sent: list[tuple[str, dict]] = []
        self.calls = 0
        self.fail_next = fail_next

    async def __call__(self, path: str, body: bytes) -> None:
        self.calls += 1
        if self.fail_next > 0:
            self.fail_next -= 1
            raise RuntimeError("collector unreachable")
        self.sent.append((path, json.loads(body)))


def make_exporter(registry: Registry, transport, **kwargs) -> TelemetryExporter:
    kwargs.setdefault("retry", FAST_RETRY)
    kwargs.setdefault("flush_interval_s", 60.0)  # tests flush explicitly
    return TelemetryExporter(
        "http://collector.invalid:4318", registry, transport=transport, **kwargs
    )


# ----------------------------------------------------------- wire format


def test_spans_payload_is_otlp_json_shaped():
    """Golden shape test: the hand-rolled payload must look exactly like
    what an OTLP/HTTP collector parses — resourceSpans/scopeSpans nesting,
    base16 ids, uint64-nanos-as-strings, stringValue attributes."""
    tracer = Tracer()
    trace = make_trace(tracer)
    payload = spans_payload([trace], service_name="bci-test")

    assert list(payload) == ["resourceSpans"]
    resource_spans = payload["resourceSpans"]
    assert len(resource_spans) == 1
    assert resource_spans[0]["resource"]["attributes"] == [
        {"key": "service.name", "value": {"stringValue": "bci-test"}}
    ]
    scope_spans = resource_spans[0]["scopeSpans"]
    assert len(scope_spans) == 1
    assert scope_spans[0]["scope"]["name"] == (
        "bee_code_interpreter_tpu.observability"
    )
    spans = scope_spans[0]["spans"]
    assert len(spans) == 2  # root + execute

    root = next(s for s in spans if s["name"] == "/v1/execute")
    child = next(s for s in spans if s["name"] == "execute")
    assert root["traceId"] == trace.trace_id
    assert re.fullmatch(r"[0-9a-f]{32}", root["traceId"])
    assert re.fullmatch(r"[0-9a-f]{16}", root["spanId"])
    assert "parentSpanId" not in root  # root of a fresh trace
    assert child["parentSpanId"] == root["spanId"]
    assert child["traceId"] == trace.trace_id
    for s in (root, child):
        assert s["kind"] == 1  # SPAN_KIND_INTERNAL
        assert s["status"] == {"code": 1}  # STATUS_CODE_OK
        # uint64 nanos are decimal STRINGS per proto3 JSON
        assert re.fullmatch(r"\d{19}", s["startTimeUnixNano"])
        assert int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"])
    assert {"key": "pod", "value": {"stringValue": "pod-1"}} in child[
        "attributes"
    ]
    json.dumps(payload)  # round-trips as plain JSON


def test_error_spans_carry_error_status():
    tracer = Tracer()
    with pytest.raises(RuntimeError):
        with tracer.trace("/v1/execute") as t:
            raise RuntimeError("boom")
    payload = spans_payload([t], service_name="s")
    (root,) = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert root["status"] == {"code": 2}  # STATUS_CODE_ERROR
    assert {"key": "error", "value": {"stringValue": "RuntimeError('boom')"}} in (
        root["attributes"]
    )


def test_metrics_payload_covers_all_three_metric_types():
    registry = Registry()
    c = registry.counter("bci_reqs_total", "requests")
    c.inc(3, route="/x")
    registry.gauge("bci_depth", "queue depth", lambda: 7.0)
    h = registry.histogram("bci_lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)

    payload = metrics_payload(
        registry, service_name="bci-test", start_unix=1000.0
    )
    metrics = payload["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]
    by_name = {m["name"]: m for m in metrics}

    counter = by_name["bci_reqs_total"]["sum"]
    assert counter["isMonotonic"] is True
    assert counter["aggregationTemporality"] == 2  # cumulative
    (point,) = counter["dataPoints"]
    assert point["asDouble"] == 3.0
    assert point["attributes"] == [
        {"key": "route", "value": {"stringValue": "/x"}}
    ]
    # cumulative points carry the accumulation start so consumers can
    # detect counter resets across restarts
    assert point["startTimeUnixNano"] == str(int(1000.0 * 1e9))
    assert int(point["timeUnixNano"]) > int(point["startTimeUnixNano"])

    (gauge_point,) = by_name["bci_depth"]["gauge"]["dataPoints"]
    assert gauge_point["asDouble"] == 7.0

    (hist_point,) = by_name["bci_lat_seconds"]["histogram"]["dataPoints"]
    assert hist_point["startTimeUnixNano"] == str(int(1000.0 * 1e9))
    assert hist_point["count"] == "3"
    assert hist_point["explicitBounds"] == [0.1, 1.0]
    # per-bucket (NOT cumulative) with one overflow bucket: 0.05 | 0.5 | 5.0
    assert hist_point["bucketCounts"] == ["1", "1", "1"]
    assert hist_point["sum"] == pytest.approx(5.55)
    json.dumps(payload)


# ------------------------------------------------- batching and accounting


async def test_flush_batches_traces_and_pushes_metrics():
    registry = Registry()
    tracer = Tracer(metrics=registry)
    transport = CaptureTransport()
    exporter = make_exporter(registry, transport)
    tracer.add_sink(exporter.enqueue_trace)

    traces = [make_trace(tracer) for _ in range(5)]
    assert exporter.queue_depth == 5
    summary = await exporter.flush_once()

    assert summary["traces_exported"] == 5
    trace_posts = [p for p in transport.sent if p[0] == "/v1/traces"]
    assert len(trace_posts) == 1  # one batch, not five posts
    batch_ids = {
        s["traceId"]
        for s in trace_posts[0][1]["resourceSpans"][0]["scopeSpans"][0]["spans"]
    }
    assert batch_ids == {t.trace_id for t in traces}
    # a metrics snapshot rides every flush
    metric_posts = [p for p in transport.sent if p[0] == "/v1/metrics"]
    assert len(metric_posts) == 1
    assert counter_value(
        registry, "bci_telemetry_exported_total", signal="traces"
    ) == 5
    assert counter_value(
        registry, "bci_telemetry_exported_total", signal="metrics"
    ) == 1
    assert exporter.queue_depth == 0


async def test_oversize_queue_drains_in_multiple_batches():
    registry = Registry()
    tracer = Tracer()
    transport = CaptureTransport()
    exporter = make_exporter(registry, transport, batch_max=2)
    for _ in range(5):
        exporter.enqueue_trace(make_trace(tracer))
    await exporter.flush_once()
    trace_posts = [p for p in transport.sent if p[0] == "/v1/traces"]
    assert len(trace_posts) == 3  # 2 + 2 + 1
    assert exporter.queue_depth == 0


async def test_bounded_queue_drops_new_traces_and_accounts_them():
    registry = Registry()
    tracer = Tracer()
    exporter = make_exporter(registry, CaptureTransport(), queue_max=2)
    for _ in range(5):
        exporter.enqueue_trace(make_trace(tracer))
    assert exporter.queue_depth == 2  # bounded, never grows past the cap
    assert counter_value(
        registry, "bci_telemetry_dropped_total", signal="traces", reason="queue_full"
    ) == 3
    await exporter.flush_once()
    # invariant: enqueued == exported + dropped
    assert counter_value(
        registry, "bci_telemetry_exported_total", signal="traces"
    ) == 2


# ------------------------------------------------------- retry and failure


async def test_send_retries_with_backoff_then_succeeds():
    registry = Registry()
    tracer = Tracer()
    transport = CaptureTransport(fail_next=2)
    exporter = make_exporter(registry, transport)
    exporter.enqueue_trace(make_trace(tracer))
    summary = await exporter.flush_once()
    assert summary["traces_exported"] == 1
    # 2 failures + 1 success for the trace batch, then 1 metrics push
    assert transport.calls == 4
    assert counter_value(
        registry, "bci_telemetry_dropped_total", signal="traces", reason="send_failed"
    ) == 0


async def test_exhausted_retries_drop_the_batch_and_account_it():
    registry = Registry()
    tracer = Tracer()

    async def always_down(path, body):
        raise RuntimeError("connection refused")

    exporter = make_exporter(registry, always_down)
    for _ in range(3):
        exporter.enqueue_trace(make_trace(tracer))
    summary = await exporter.flush_once()
    assert summary["traces_dropped"] == 3
    assert exporter.queue_depth == 0
    assert counter_value(
        registry, "bci_telemetry_dropped_total", signal="traces", reason="send_failed"
    ) == 3
    assert counter_value(
        registry, "bci_telemetry_dropped_total", signal="metrics", reason="send_failed"
    ) == 1
    assert counter_value(
        registry, "bci_telemetry_exported_total", signal="traces"
    ) == 0


async def test_failed_batch_ends_the_drain_but_keeps_the_rest_queued():
    """One dead-collector batch must not burn the retry budget once per
    queued batch: the first failure stops this flush; the remainder waits."""
    registry = Registry()
    tracer = Tracer()

    async def always_down(path, body):
        raise RuntimeError("connection refused")

    exporter = make_exporter(registry, always_down, batch_max=2)
    for _ in range(6):
        exporter.enqueue_trace(make_trace(tracer))
    await exporter.flush_once()
    assert exporter.queue_depth == 4  # only the first batch was spent
    assert counter_value(
        registry, "bci_telemetry_dropped_total", signal="traces", reason="send_failed"
    ) == 2


async def test_stop_is_bounded_against_a_hanging_collector():
    """SIGTERM teardown must never wait out a blackholed collector: stop()
    caps the final flush at its timeout and accounts everything still
    queued as reason="shutdown" — the exported+dropped==enqueued invariant
    survives even a cancelled in-flight send."""
    import asyncio
    import time

    registry = Registry()
    tracer = Tracer()

    async def blackhole(path, body):
        await asyncio.sleep(60)

    exporter = make_exporter(registry, blackhole)
    for _ in range(3):
        exporter.enqueue_trace(make_trace(tracer))
    t0 = time.monotonic()
    await exporter.stop(timeout_s=0.1)
    assert time.monotonic() - t0 < 2.0
    assert exporter.queue_depth == 0
    assert counter_value(
        registry, "bci_telemetry_dropped_total", signal="traces", reason="shutdown"
    ) == 3
    assert counter_value(
        registry, "bci_telemetry_exported_total", signal="traces"
    ) == 0


# ------------------------------------------------ real HTTP to a collector


async def test_exporter_pushes_to_a_real_collector_over_http():
    """No transport injection: the default httpx path against an in-process
    OTLP collector — wire bytes, content type, and 503-retry behavior."""
    collector = await FakeCollector().start()
    registry = Registry()
    tracer = Tracer(metrics=registry)
    exporter = TelemetryExporter(
        collector.endpoint, registry, retry=FAST_RETRY, flush_interval_s=60.0
    )
    try:
        collector.fail_next = 1  # first post 503s; the retry lands it
        t1, t2 = make_trace(tracer), make_trace(tracer)
        exporter.enqueue_trace(t1)
        exporter.enqueue_trace(t2)
        summary = await exporter.flush_once()
        assert summary["traces_exported"] == 2
        assert collector.span_trace_ids() == {t1.trace_id, t2.trace_id}
        assert len(collector.metric_batches) == 1
        metric_names = {
            m["name"]
            for m in collector.metric_batches[0]["resourceMetrics"][0][
                "scopeMetrics"
            ][0]["metrics"]
        }
        assert "bci_telemetry_exported_total" in metric_names
        assert "bci_stage_seconds" in metric_names
    finally:
        await exporter.stop()
        await collector.stop()


async def test_background_loop_flushes_on_interval_and_stop_flushes_tail():
    import asyncio

    collector = await FakeCollector().start()
    registry = Registry()
    tracer = Tracer()
    exporter = TelemetryExporter(
        collector.endpoint, registry, retry=FAST_RETRY, flush_interval_s=0.02
    )
    try:
        exporter.start()
        exporter.enqueue_trace(make_trace(tracer))
        for _ in range(200):
            if collector.trace_batches:
                break
            await asyncio.sleep(0.01)
        assert collector.trace_batches, "background loop never flushed"
        # the tail enqueued after the last interval is flushed by stop()
        tail = make_trace(tracer)
        exporter.enqueue_trace(tail)
        await exporter.stop()
        assert tail.trace_id in collector.span_trace_ids()
    finally:
        await collector.stop()
