"""Tier-1 cross-transport contract lint (docs/analysis.md "Contract
lint"): the HTTP/gRPC/router edges must carry ZERO unexplained
contractlint violations, the checked-in ``docs/api_surface.json`` golden
must match the extracted model byte-for-byte, and every suppression must
still earn its justification — the asynclint/jaxlint contract, pointed at
the API surface.

Three sections: the repo itself; per-rule units on synthetic edge trees
(so a regression names the broken rule); and both-transport regressions
for the drift defects the PR 15 audit surfaced and FIXED (server faults
as INTERNAL never UNKNOWN, negative-limit coercion parity, standalone
gRPC observability parity)."""

import json
from pathlib import Path

import pytest

from bee_code_interpreter_tpu.analysis.asynclint import Suppression
from bee_code_interpreter_tpu.analysis.contractlint import (
    EXEMPTIONS,
    SUPPRESSIONS,
    TWINS,
    Exemption,
    Twin,
    lint_contract_paths,
    surface_json,
)

REPO = Path(__file__).resolve().parent.parent


# ------------------------------------------------------------- the repo


def test_edges_have_zero_unexplained_violations():
    report = lint_contract_paths()
    assert report.files_scanned >= 5  # both edges + router + core + models
    assert not report.violations, "\n" + report.summary()


def test_no_stale_suppressions():
    report = lint_contract_paths()
    assert not report.stale_suppressions, (
        "suppressions no longer matching any violation — delete them:\n"
        + report.summary()
    )
    used = {s for _, s in report.suppressed}
    assert used == set(SUPPRESSIONS)


def test_every_suppression_and_exemption_is_justified():
    for s in SUPPRESSIONS:
        assert len(s.reason.split()) >= 8, (
            f"{s.path} [{s.rule}]: a suppression needs a real justification"
        )
    for e in EXEMPTIONS:
        assert len(e.reason.split()) >= 5, (
            f"{e.surface}: an exemption needs a real reason"
        )


def test_stale_suppression_fails():
    report = lint_contract_paths(
        suppressions=(
            *SUPPRESSIONS,
            Suppression(
                path="api/http_server.py",
                rule="sli-parity",
                reason="does not match anything",
            ),
        )
    )
    assert any(s.rule == "sli-parity" for s in report.stale_suppressions)
    assert not report.clean


def test_surface_golden_matches_checked_in_document():
    """The golden contract: ANY surface change — a new route, a new
    status, a coercion change — must land as a reviewed diff of
    docs/api_surface.json. Regenerate with
    `python scripts/analyze.py --surface > docs/api_surface.json`."""
    golden = json.loads((REPO / "docs" / "api_surface.json").read_text())
    assert surface_json() == golden, (
        "the extracted API surface no longer matches docs/api_surface.json "
        "— regenerate it (scripts/analyze.py --surface) and review the diff"
    )


def test_surface_section_served_in_debug_bundle():
    from bee_code_interpreter_tpu.analysis.contractlint import surface_section
    from bee_code_interpreter_tpu.observability import build_debug_bundle

    surface_section()  # fill the cache synchronously: no warming race
    bundle = build_debug_bundle()
    surface = bundle["surface"]
    assert surface["lint"]["clean"] is True
    assert surface["lint"]["stale_suppressions"] == 0
    assert {r["path"] for r in surface["model"]["http"]} >= {
        "/v1/execute",
        "/v1/sessions",
    }
    # the router's Retry-After passthrough contract is golden-pinned
    assert "Retry-After" in surface["model"]["router_headers"][
        "response_passthrough"
    ]


def test_twin_map_covers_every_v1_http_route():
    """Belt and braces over the rule itself: every non-exempt /v1 route
    is twinned, so the map cannot silently rot."""
    report = lint_contract_paths()
    declared = {t.http for t in TWINS}
    for route in report.surface.http:
        exempt = any(e.matches(route.key) for e in EXEMPTIONS)
        assert route.key in declared or exempt, route.key


# -------------------------------------------------- synthetic edge trees


HTTP_OK = """
from aiohttp import web

async def with_resilience(run):
    try:
        return await run(None)
    except AdmissionRejected:
        return web.json_response({}, status=429)
    except DeadlineExceeded:
        return web.json_response({}, status=504)
    except BreakerOpenError:
        return web.json_response({}, status=503)

async def execute(request):
    async def run(deadline):
        limit = int(request.query.get("limit", "1"))
        if limit < 0:
            return web.json_response({}, status=400)
        return web.json_response({})
    return await with_resilience(run)

def build(app):
    app.router.add_post("/v1/execute", execute)
"""

GRPC_OK = """
import grpc
import json

SERVICE_NAME = "x.v1.Demo"
_METHODS = ("Execute",)

class Servicer:
    async def _resilience_scope(self, context):
        try:
            yield None
        except AdmissionRejected:
            context.set_trailing_metadata((("retry-after-s", "1"),))
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, "shed")
        except DeadlineExceeded:
            await context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, "late")
        except BreakerOpenError:
            context.set_trailing_metadata((("retry-after-s", "1"),))
            await context.abort(grpc.StatusCode.UNAVAILABLE, "open")

    async def Execute(self, request, context):
        body = json.loads(request.decode() or "{}")
        limit = int(body.get("limit", 1))
        if limit < 0:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "neg")
        async with self._resilience_scope(context):
            return b"{}"

def handler(servicer):
    return grpc.method_handlers_generic_handler(
        SERVICE_NAME,
        {
            name: grpc.unary_unary_rpc_method_handler(
                getattr(servicer, name),
                request_deserializer=bytes,
                response_serializer=bytes,
            )
            for name in _METHODS
        },
    )
"""

TWIN = (Twin("POST /v1/execute", ("Demo.Execute",)),)
DOCS = "/v1/execute and Execute are documented here"


def make_tree(tmp_path, http_source=HTTP_OK, grpc_source=GRPC_OK):
    pkg = tmp_path / "fakepkg"
    (pkg / "api").mkdir(parents=True, exist_ok=True)
    (pkg / "api" / "http_server.py").write_text(http_source)
    (pkg / "api" / "grpc_server.py").write_text(grpc_source)
    return pkg


def rules_for(pkg, twins=TWIN, exemptions=(), docs_text=DOCS):
    report = lint_contract_paths(
        pkg, twins=twins, exemptions=exemptions, suppressions=(),
        docs_text=docs_text,
    )
    return [v.rule for v in report.violations], report


def test_synthetic_twin_pair_is_clean(tmp_path):
    rules, report = rules_for(make_tree(tmp_path))
    assert rules == [], "\n" + report.summary()


def test_new_route_is_scoped_by_default(tmp_path):
    """The omission bug class: a freshly added route (or servicer method)
    is a route-twin-missing finding until someone DECLARES its twin or
    its exemption — mirror coverage is a reviewed decision."""
    http = HTTP_OK + """
async def shiny(request):
    return web.json_response({})

def build2(app):
    app.router.add_get("/v1/shiny", shiny)
"""
    rules, report = rules_for(
        make_tree(tmp_path, http_source=http), docs_text=DOCS + " /v1/shiny"
    )
    assert rules == ["route-twin-missing"]
    assert "/v1/shiny" in report.violations[0].message


def test_stale_twin_and_stale_exemption_fail(tmp_path):
    rules, _ = rules_for(
        make_tree(tmp_path),
        twins=(*TWIN, Twin("POST /v1/gone", ("Demo.Gone",))),
    )
    assert rules.count("route-twin-missing") == 2  # route AND method stale
    rules, _ = rules_for(
        make_tree(tmp_path),
        exemptions=(Exemption("GET /nope", "never existed at all"),),
    )
    assert rules == ["route-twin-missing"]


def test_status_mapping_drift_forward(tmp_path):
    # HTTP grows a 404 arm; the twin has no NOT_FOUND
    http = HTTP_OK.replace(
        '        return web.json_response({})\n    return await',
        '        if limit == 9:\n'
        '            return web.json_response({}, status=404)\n'
        '        return web.json_response({})\n    return await',
    )
    rules, report = rules_for(make_tree(tmp_path, http_source=http))
    assert rules == ["status-mapping-drift"]
    assert "NOT_FOUND" in report.violations[0].message


def test_status_mapping_requires_retry_after_trailer(tmp_path):
    grpc_source = GRPC_OK.replace(
        '        except AdmissionRejected:\n'
        '            context.set_trailing_metadata((("retry-after-s", "1"),))\n',
        '        except AdmissionRejected:\n',
    ).replace(
        '        except BreakerOpenError:\n'
        '            context.set_trailing_metadata((("retry-after-s", "1"),))\n',
        '        except BreakerOpenError:\n',
    )
    rules, report = rules_for(make_tree(tmp_path, grpc_source=grpc_source))
    assert "status-mapping-drift" in rules
    assert any("retry-after-s" in v.message for v in report.violations)


def test_sli_parity_drift(tmp_path):
    # the gRPC method stops using the ladder while the HTTP twin keeps it
    grpc_source = GRPC_OK.replace(
        "        async with self._resilience_scope(context):\n"
        "            return b\"{}\"",
        "        return b\"{}\"",
    )
    rules, _ = rules_for(make_tree(tmp_path, grpc_source=grpc_source))
    assert "sli-parity" in rules


def test_param_coercion_kind_drift(tmp_path):
    grpc_source = GRPC_OK.replace(
        'limit = int(body.get("limit", 1))', 'limit = float(body.get("limit", 1))'
    )
    rules, report = rules_for(make_tree(tmp_path, grpc_source=grpc_source))
    assert "param-coercion-drift" in rules
    assert any("`limit`" in v.message for v in report.violations)


def test_param_coercion_bound_drift(tmp_path):
    # gRPC stops rejecting negative limits; HTTP still 400s them — the
    # GetFleetEvents max(0, …) clamp bug class
    grpc_source = GRPC_OK.replace(
        "        if limit < 0:\n"
        '            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "neg")\n',
        "        limit = max(0, limit)\n",
    )
    rules, report = rules_for(make_tree(tmp_path, grpc_source=grpc_source))
    assert "param-coercion-drift" in rules
    assert any("negative" in v.message for v in report.violations)


def test_undocumented_route_flagged(tmp_path):
    rules, _ = rules_for(make_tree(tmp_path), docs_text="nothing relevant")
    assert rules.count("undocumented-route") == 2  # the route AND the RPC


ESCAPE_HTTP = """
from aiohttp import web
from fakepkg.services.executor import Executor

def create(executor: Executor):
    async def boom(request):
        return web.json_response(await executor.run())

    async def safe(request):
        try:
            return web.json_response(await executor.run())
        except Exception:
            return web.json_response({}, status=500)

    def build(app):
        app.router.add_post("/v1/boom", boom)
        app.router.add_post("/v1/safe", safe)
    return build
"""

ESCAPE_SERVICE = """
class KabloomError(Exception):
    pass

class Executor:
    async def run(self):
        raise KabloomError("pod exploded")
"""


def test_exception_escape_flagged_and_catching_clears_it(tmp_path):
    pkg = make_tree(tmp_path, http_source=ESCAPE_HTTP)
    (pkg / "services").mkdir()
    (pkg / "services" / "executor.py").write_text(ESCAPE_SERVICE)
    twins = ()
    exemptions = (Exemption("POST /v1/boom", "synthetic tree for the rule"),
                  Exemption("POST /v1/safe", "synthetic tree for the rule"),
                  Exemption("Demo.Execute", "synthetic tree for the rule"))
    report = lint_contract_paths(
        pkg, twins=twins, exemptions=exemptions, suppressions=(),
        docs_text="/v1/boom /v1/safe Execute",
    )
    escapes = [v for v in report.violations if v.rule == "exception-escapes-as-500"]
    assert len(escapes) == 1
    assert "KabloomError" in escapes[0].message
    assert "boom" in escapes[0].message  # `safe` catches: no finding


ELSE_ESCAPE_HTTP = """
from aiohttp import web
from fakepkg.services.executor import Executor

def create(executor: Executor):
    async def sneaky(request):
        try:
            prepared = 1
        except Exception:
            return web.json_response({}, status=500)
        else:
            # runs AFTER the try body: the arms above DON'T cover it
            return web.json_response(await executor.run())

    def build(app):
        app.router.add_post("/v1/sneaky", sneaky)
    return build
"""


def test_exception_escape_in_else_block_is_not_covered(tmp_path):
    """A try's else block runs outside its arms' protection — a raise
    there escapes (code-review regression: the coverage walk used to
    treat orelse like the body and silently under-reported the rule)."""
    pkg = make_tree(tmp_path, http_source=ELSE_ESCAPE_HTTP)
    (pkg / "services").mkdir()
    (pkg / "services" / "executor.py").write_text(ESCAPE_SERVICE)
    report = lint_contract_paths(
        pkg,
        twins=(),
        exemptions=(
            Exemption("POST /v1/sneaky", "synthetic tree for the rule"),
            Exemption("Demo.Execute", "synthetic tree for the rule"),
        ),
        suppressions=(),
        docs_text="/v1/sneaky Execute",
    )
    escapes = [
        v for v in report.violations if v.rule == "exception-escapes-as-500"
    ]
    assert len(escapes) == 1 and "KabloomError" in escapes[0].message


# ------------------------------------- both-transport drift regressions


class _BoomCodeExecutor:
    """Executor whose sandbox 'dies' with a raw exception: the verdict
    must be the canonical 500/INTERNAL pair, never UNKNOWN."""

    async def execute(self, **kwargs):
        raise RuntimeError("sandbox exploded")


class _BoomToolExecutor:
    async def execute(self, **kwargs):
        raise RuntimeError("tool sandbox exploded")


class _DyingSessionManager:
    """Session manager whose leased sandbox dies mid-execute."""

    def get(self, session_id):
        return self

    async def execute(self, session_id, source_code, **kwargs):
        from bee_code_interpreter_tpu.resilience import SandboxTransientError

        raise SandboxTransientError("leased pod died")


async def _http_client(app):
    from aiohttp.test_utils import TestClient, TestServer

    client = TestClient(TestServer(app))
    await client.start_server()
    return client


async def test_execute_server_fault_is_500_and_internal_on_both(
    local_executor,
):
    import grpc
    import grpc.aio

    from bee_code_interpreter_tpu.api.grpc_server import (
        GrpcServer,
        service_stubs,
    )
    from bee_code_interpreter_tpu.api.http_server import create_http_server
    from bee_code_interpreter_tpu.observability import SloEngine, parse_objectives
    from bee_code_interpreter_tpu.observability.slo import WINDOWS
    from bee_code_interpreter_tpu.proto import code_interpreter_pb2 as pb
    from bee_code_interpreter_tpu.services.custom_tool_executor import (
        CustomToolExecutor,
    )

    # HTTP: explicit JSON 500 and an SLI-bad sample
    http_slo = SloEngine(parse_objectives(99.5, "2000:99"))
    app = create_http_server(
        code_executor=_BoomCodeExecutor(),
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        slo=http_slo,
    )
    client = await _http_client(app)
    try:
        resp = await client.post("/v1/execute", json={"source_code": "print(1)"})
        assert resp.status == 500
        assert (await resp.json())["detail"] == "Execution failed"
    finally:
        await client.close()
    (availability, _) = http_slo.objectives
    assert http_slo._window_counts(availability, WINDOWS["5m"]) == (1, 1)

    # gRPC: the same failure aborts INTERNAL (it escaped as UNKNOWN
    # before PR 15) and burns budget identically
    grpc_slo = SloEngine(parse_objectives(99.5, "2000:99"))
    server = GrpcServer(
        code_executor=_BoomCodeExecutor(),
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        slo=grpc_slo,
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stubs = service_stubs(channel)
            with pytest.raises(grpc.aio.AioRpcError) as err:
                await stubs["Execute"](pb.ExecuteRequest(source_code="print(1)"))
            assert err.value.code() == grpc.StatusCode.INTERNAL
            assert "execution failed" in err.value.details()
    finally:
        await server.stop(None)
    (availability, _) = grpc_slo.objectives
    assert grpc_slo._window_counts(availability, WINDOWS["5m"]) == (1, 1)


async def test_custom_tool_server_fault_is_500_and_internal_on_both(
    local_executor,
):
    import grpc
    import grpc.aio

    from bee_code_interpreter_tpu.api.grpc_server import (
        GrpcServer,
        service_stubs,
    )
    from bee_code_interpreter_tpu.api.http_server import create_http_server
    from bee_code_interpreter_tpu.proto import code_interpreter_pb2 as pb

    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=_BoomToolExecutor(),
    )
    client = await _http_client(app)
    try:
        resp = await client.post(
            "/v1/execute-custom-tool",
            json={
                "tool_source_code": "def t(a: int) -> int:\n  return a",
                "tool_input_json": '{"a": 1}',
            },
        )
        # before PR 15 this was aiohttp's default text/plain 500
        assert resp.status == 500
        assert (await resp.json())["detail"] == "Execution failed"
    finally:
        await client.close()

    server = GrpcServer(
        code_executor=local_executor, custom_tool_executor=_BoomToolExecutor()
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stubs = service_stubs(channel)
            with pytest.raises(grpc.aio.AioRpcError) as err:
                await stubs["ExecuteCustomTool"](
                    pb.ExecuteCustomToolRequest(
                        tool_source_code="def t(a: int) -> int:\n  return a",
                        tool_input_json='{"a": 1}',
                    )
                )
            assert err.value.code() == grpc.StatusCode.INTERNAL
    finally:
        await server.stop(None)


async def test_dead_leased_sandbox_is_500_and_internal_on_both(
    local_executor,
):
    import grpc
    import grpc.aio

    from bee_code_interpreter_tpu.api.grpc_server import (
        GrpcServer,
        session_stubs,
    )
    from bee_code_interpreter_tpu.api.http_server import create_http_server
    from bee_code_interpreter_tpu.services.custom_tool_executor import (
        CustomToolExecutor,
    )

    tools = CustomToolExecutor(code_executor=local_executor)
    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=tools,
        sessions=_DyingSessionManager(),
    )
    client = await _http_client(app)
    try:
        resp = await client.post(
            "/v1/sessions/sess-x/execute", json={"source_code": "print(1)"}
        )
        assert resp.status == 500
        assert "sandbox died" in (await resp.json())["detail"]
    finally:
        await client.close()

    server = GrpcServer(
        code_executor=local_executor,
        custom_tool_executor=tools,
        sessions=_DyingSessionManager(),
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stubs = session_stubs(channel)
            with pytest.raises(grpc.aio.AioRpcError) as err:
                await stubs["ExecuteInSession"](
                    json.dumps(
                        {"session_id": "sess-x", "source_code": "print(1)"}
                    ).encode()
                )
            # escaped as UNKNOWN before PR 15
            assert err.value.code() == grpc.StatusCode.INTERNAL
            assert "sandbox died" in err.value.details()
    finally:
        await server.stop(None)


async def test_negative_limit_rejected_identically_on_both(local_executor):
    import grpc
    import grpc.aio

    from bee_code_interpreter_tpu.api.grpc_server import (
        GrpcServer,
        fleet_stubs,
        observability_stubs,
    )
    from bee_code_interpreter_tpu.api.http_server import create_http_server
    from bee_code_interpreter_tpu.services.custom_tool_executor import (
        CustomToolExecutor,
    )

    tools = CustomToolExecutor(code_executor=local_executor)
    app = create_http_server(code_executor=local_executor, custom_tool_executor=tools)
    client = await _http_client(app)
    try:
        assert (await client.get("/v1/events?limit=-1")).status == 400
        assert (await client.get("/v1/fleet/events?limit=-1")).status == 400
    finally:
        await client.close()

    server = GrpcServer(code_executor=local_executor, custom_tool_executor=tools)
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            obs = observability_stubs(channel)
            with pytest.raises(grpc.aio.AioRpcError) as err:
                # accepted (and mis-sliced) before PR 15
                await obs["GetEvents"](b'{"limit": -1}')
            assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
            fleet = fleet_stubs(channel)
            with pytest.raises(grpc.aio.AioRpcError) as err:
                # silently clamped to 0 before PR 15
                await fleet["GetFleetEvents"](b'{"limit": -1}')
            assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    finally:
        await server.stop(None)


async def test_standalone_grpc_serves_events_and_bundle_like_http(
    local_executor,
):
    """create_http_server always wired a default FlightRecorder and a
    debug-bundle fallback; a standalone GrpcServer aborted UNIMPLEMENTED
    for both. The twins must answer alike (PR 15)."""
    import grpc.aio

    from bee_code_interpreter_tpu.analysis.contractlint import surface_section
    from bee_code_interpreter_tpu.api.grpc_server import (
        GrpcServer,
        observability_stubs,
    )
    from bee_code_interpreter_tpu.services.custom_tool_executor import (
        CustomToolExecutor,
    )

    surface_section()  # fill the cache synchronously: no warming race
    server = GrpcServer(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            obs = observability_stubs(channel)
            events = json.loads(await obs["GetEvents"](b""))
            assert events == {"events": []}
            bundle = json.loads(await obs["GetDebugBundle"](b""))
            assert "traces" in bundle and "slo" in bundle
            assert bundle["surface"]["lint"]["clean"] is True
    finally:
        await server.stop(None)
