"""Fleet-router acceptance (ISSUE 11, docs/fleet.md): consistent-hash
placement, cross-replica retry, session pinning, and lease handoff on
drain — chaos scenario 14's tier-1 twin.

The harness is N COMPLETE in-process replicas: each one the real HTTP edge
(create_http_server) over the real KubernetesCodeExecutor against its own
fake-pod cluster, with its own SessionManager/SLO/admission/drain — all
sharing ONE SharedDirectoryBackend snapshot root, exactly the production
fleet shape minus kubectl. The real FleetRouter fronts them over real
sockets."""

import asyncio

import httpx
import pytest
from aiohttp import web

from bee_code_interpreter_tpu.fleet import (
    FleetRouter,
    HashRing,
    NoReplicasAvailable,
    affinity_key,
    create_router_app,
)
from bee_code_interpreter_tpu.health_check import assess_router
from tests.fakes import ReplicaStack, free_port

pytestmark = pytest.mark.chaos


# ------------------------------------------------------------------ units


def test_ring_preference_is_stable_under_replica_loss():
    ring = HashRing(vnodes=64)
    for name in ("r0", "r1", "r2"):
        ring.add(name)
    keys = [affinity_key({f"/workspace/{i}.txt": "ab" * 32}) for i in range(64)]
    owners_before = {k: ring.owner(k) for k in keys}
    ring.remove("r1")
    for key, owner in owners_before.items():
        if owner != "r1":
            # keys not owned by the lost replica keep their warm home
            assert ring.owner(key) == owner
        else:
            assert ring.owner(key) in ("r0", "r2")


def test_ring_shares_sum_to_one_and_spread():
    ring = HashRing(vnodes=128)
    for name in ("a", "b", "c", "d"):
        ring.add(name)
    shares = ring.shares()
    assert abs(sum(shares.values()) - 1.0) < 1e-9
    assert all(0.1 < s < 0.5 for s in shares.values()), shares


def test_affinity_key_semantics():
    assert affinity_key(None) is None
    assert affinity_key({}) is None
    a = affinity_key({"/workspace/x": "11" * 32, "/workspace/y": "22" * 32})
    b = affinity_key({"/workspace/y": "22" * 32, "/workspace/x": "11" * 32})
    assert a == b  # order-independent
    assert a != affinity_key({"/workspace/x": "11" * 32})


def _synthetic_router(clock):
    router = FleetRouter(
        [(f"r{i}", f"http://127.0.0.1:{i + 1}") for i in range(3)],
        refresh_interval_s=0.2,
        dead_after_s=5.0,
        clock=clock,
    )
    for replica in router.replicas.values():
        replica.last_refresh_mono = clock()
    return router


async def test_placement_eligibility_and_spill():
    now = [100.0]
    router = _synthetic_router(lambda: now[0])
    key = affinity_key({"/workspace/a": "ab" * 32})
    owner = router.ring.owner(key)
    assert router.place(key)[0].name == owner

    # a saturated owner with NO warm capacity spills to a healthier
    # replica; with even one ready sandbox the warm owner keeps the key
    # (it is still the fastest home)
    router.replicas[owner].utilization = 0.95
    router.replicas[owner].ready_pods = 0
    spilled = router.place(key)[0]
    assert spilled.name != owner
    assert router.affinity_result(key, spilled.name) == "spill"
    router.replicas[owner].ready_pods = 1
    assert router.place(key)[0].name == owner
    router.replicas[owner].utilization = 0.0

    # an SLO page on the owner is the same veto
    router.replicas[owner].slo_fast_burn = True
    assert router.place(key)[0].name != owner
    router.replicas[owner].slo_fast_burn = False
    assert router.place(key)[0].name == owner
    assert router.affinity_result(key, owner) == "warm"

    # draining and stale replicas leave placement
    router.replicas[owner].draining = True
    assert all(r.name != owner for r in router.place(key))
    router.replicas[owner].draining = False
    now[0] += 10.0  # every refresh is now stale
    with pytest.raises(NoReplicasAvailable):
        router.place(key)


async def test_keyless_placement_prefers_least_loaded():
    now = [50.0]
    router = _synthetic_router(lambda: now[0])
    router.replicas["r0"].utilization = 0.8
    router.replicas["r1"].utilization = 0.1
    router.replicas["r2"].utilization = 0.4
    assert router.place(None)[0].name == "r1"
    assert router.affinity_result(None, "r1") == "keyless"


def test_assess_router_exit_ladder():
    def body(*states):
        return {
            "replicas": [
                {"name": f"r{i}", "state": s} for i, s in enumerate(states)
            ]
        }

    assert assess_router(body("healthy", "healthy"))[0] == 0
    code, message = assess_router(body("healthy", "dead", "dead"))
    assert code == 2 and "r1" in message and "r2" in message
    assert assess_router(body("healthy", "draining"))[0] == 3
    # dead outranks draining; an empty fleet is dead
    assert assess_router(body("draining", "dead"))[0] == 2
    assert assess_router({"replicas": []})[0] == 2
    assert assess_router(body("draining"))[0] == 2  # no healthy replica left


# ----------------------------------------------------------- fleet harness
# ReplicaStack (tests/fakes.py): one complete in-process replica — real HTTP
# edge + KubernetesCodeExecutor over fake pods + SessionManager/SLO/admission/
# drain — sharing one SharedDirectoryBackend snapshot root. Shared with chaos
# scenario 14 (scripts/chaos_smoke.py).


async def _start_fleet(tmp_path, n=3, **router_kwargs):
    shared_root = tmp_path / "shared-objects"
    stacks = [
        await ReplicaStack(f"r{i}", tmp_path, shared_root).start()
        for i in range(n)
    ]
    router_kwargs.setdefault("refresh_interval_s", 0.2)
    router_kwargs.setdefault("dead_after_s", 0.5)
    router = FleetRouter(
        [(s.name, s.base_url) for s in stacks], **router_kwargs
    )
    runner = web.AppRunner(create_router_app(router))
    await runner.setup()
    port = free_port()
    await web.TCPSite(runner, "127.0.0.1", port).start()
    await router.refresh_once()
    # the production shape: the background loop keeps the placement view
    # fresh (and auto-evacuates draining replicas) while load flows
    router.start()
    return stacks, router, runner, f"http://127.0.0.1:{port}"


async def _stop_fleet(stacks, router, runner, client):
    await client.aclose()
    await runner.cleanup()
    await router.stop()
    for stack in stacks:
        await stack.stop()


async def test_chaos14_affinity_handoff_and_accounting(tmp_path):
    """Chaos scenario 14's tier-1 twin: 3 replicas under mixed load, the
    replica holding leases drains and dies — affinity stays >= 90% warm,
    every live lease migrates (checkpoint -> re-lease -> restore through
    shared storage), zero lease-scoped 5xx after the kill, the surviving
    replicas' SLO page alerts stay silent, and the decision/event/counter
    accounting agrees exactly."""
    stacks, router, runner, url = await _start_fleet(tmp_path, n=3)
    client = httpx.AsyncClient(timeout=30.0)
    try:
        # Seed the SHARED store once; three distinct snapshot chains.
        seeds = []
        for i in range(3):
            object_id = await stacks[0].storage.write(f"chain-{i}".encode())
            seeds.append({"/workspace/seed.txt": object_id})

        # --- keyed warm-affinity load: 4 rounds over 3 chains
        landed: dict[int, set[str]] = {i: set() for i in range(3)}
        for _round in range(4):
            for i, files in enumerate(seeds):
                response = await client.post(
                    f"{url}/v1/execute",
                    json={
                        "source_code": "print(open('seed.txt').read())",
                        "files": files,
                    },
                )
                assert response.status_code == 200, response.text
                body = response.json()
                assert body["exit_code"] == 0
                assert f"chain-{i}" in body["stdout"]
                event = router.recorder.events(kind="routing", limit=1)[0]
                landed[i].add(event["replica"])
        # Repeat traffic lands where its chain is warm — the acceptance bar
        # is >= 90% warm placements. (Not "exactly one replica per chain":
        # a sustained-saturation spill is CORRECT router behavior, and on a
        # loaded CI box one such spill can legitimately occur.)
        total_keyed = sum(router.affinity_totals.values())
        assert router.affinity_totals["warm"] / total_keyed >= 0.9, (
            router.affinity_totals,
            landed,
        )

        # --- two live sessions through the router
        session_ids = []
        for i in range(2):
            response = await client.post(f"{url}/v1/sessions", json={})
            assert response.status_code == 200, response.text
            session_id = response.json()["session_id"]
            session_ids.append(session_id)
            response = await client.post(
                f"{url}/v1/sessions/{session_id}/execute",
                json={
                    "source_code": (
                        f"open('state.txt', 'w').write('state-{i}')\n"
                        "print('written')"
                    )
                },
            )
            assert response.status_code == 200, response.text

        # --- the replica holding session 0 drains (its SIGTERM path)
        victim_name = router.sessions[session_ids[0]].replica
        victim = next(s for s in stacks if s.name == victim_name)
        pinned_to_victim = [
            sid
            for sid in session_ids
            if router.sessions[sid].replica == victim_name
        ]
        victim.drain.begin()
        await router.refresh_once()
        assert router.replicas[victim_name].draining
        # evacuations are background tasks (a busy lease must not stall the
        # refresh loop); the background loop may have claimed the handoff
        # first, so await our spawn AND poll until the pins have moved
        await asyncio.gather(*await router.evacuate_draining())
        for _ in range(100):
            if all(
                router.sessions[sid].replica != victim_name
                for sid in pinned_to_victim
            ):
                break
            await asyncio.sleep(0.05)

        for sid in pinned_to_victim:
            assert router.sessions[sid].replica != victim_name
            assert router.sessions[sid].migrations == 1
        assert router.totals["migrations_ok"] == len(pinned_to_victim)
        assert router.totals["migrations_failed"] == 0

        # --- kill the victim outright
        await victim.stop(hard=True)
        survivors = [s for s in stacks if s.name != victim_name]

        # Every session keeps serving under its ORIGINAL id with its state
        # intact (restored from the shared checkpoint) — zero lease-scoped
        # 5xx after the kill window.
        for i, sid in enumerate(session_ids):
            response = await client.post(
                f"{url}/v1/sessions/{sid}/execute",
                json={"source_code": "print(open('state.txt').read())"},
            )
            assert response.status_code == 200, response.text
            body = response.json()
            assert body["session_id"] == sid
            assert f"state-{i}" in body["stdout"]

        # Stateless traffic re-homes (dead replica's keys spill).
        for files in seeds:
            response = await client.post(
                f"{url}/v1/execute",
                json={"source_code": "print('alive')", "files": files},
            )
            assert response.status_code == 200, response.text

        # The dead replica is visible as dead once its refresh goes stale.
        await asyncio.sleep(0.6)
        await router.refresh_once()
        snapshot = (await client.get(f"{url}/v1/fleet/replicas")).json()
        by_name = {r["name"]: r for r in snapshot["replicas"]}
        assert by_name[victim_name]["state"] == "dead"
        code, message = assess_router(snapshot)
        assert code == 2 and victim_name in message

        # SLO page alerts silent on the survivors.
        for stack in survivors:
            assert stack.slo.snapshot()["fast_burn_alerting"] is False

        # --- exactly-once accounting across the three surfaces
        routing_events = router.recorder.events(kind="routing", limit=10_000)
        assert len(routing_events) == router.totals["routed"]
        migrate_events = router.recorder.events(
            kind="lease_migrate", limit=10_000
        )
        assert len(migrate_events) == (
            router.totals["migrations_ok"] + router.totals["migrations_failed"]
        )
        requests_counter = router.metrics.metrics["bci_router_requests_total"]
        assert (
            sum(requests_counter._values.values()) == router.totals["routed"]
        )
        migrations_counter = router.metrics.metrics[
            "bci_router_lease_migrations_total"
        ]
        assert sum(migrations_counter._values.values()) == len(migrate_events)
        placed_events = [
            e for e in routing_events if e.get("replica") is not None
        ]
        assert sum(r["routed_total"] for r in by_name.values()) == len(
            placed_events
        )
        affinity_counter = router.metrics.metrics["bci_router_affinity_total"]
        assert sum(affinity_counter._values.values()) == sum(
            router.affinity_totals.values()
        )
    finally:
        await _stop_fleet(stacks, router, runner, client)


async def test_router_retries_shed_and_dead_replicas(tmp_path):
    """A replica that sheds (429) or drops off the network mid-fleet: the
    router walks the ring and the client sees one clean 200."""
    stacks, router, runner, url = await _start_fleet(tmp_path, n=2)
    client = httpx.AsyncClient(timeout=30.0)
    try:
        # Kill replica 0's listener WITHOUT telling the router: the first
        # routed attempt may hit it, fail transport, and must retry to r1.
        await stacks[0].stop(hard=True)
        ok = 0
        for i in range(4):
            response = await client.post(
                f"{url}/v1/execute",
                json={"source_code": f"print({i} + 1)"},
            )
            assert response.status_code == 200, response.text
            ok += 1
        assert ok == 4
        # the dead replica's breaker/refresh keeps later placements away
        await asyncio.sleep(0.6)
        await router.refresh_once()
        assert router.replicas["r0"].state(
            router._clock(), router.dead_after_s
        ) == "dead"
    finally:
        await _stop_fleet(stacks, router, runner, client)


async def test_router_streaming_passthrough_and_session_404(tmp_path):
    stacks, router, runner, url = await _start_fleet(tmp_path, n=2)
    client = httpx.AsyncClient(timeout=30.0)
    try:
        # SSE passthrough: stdout chunk events + exactly one result event.
        events = []
        async with client.stream(
            "POST",
            f"{url}/v1/execute",
            params={"stream": "1"},
            json={"source_code": "print('chunk-one')\nprint('chunk-two')"},
        ) as response:
            assert response.status_code == 200
            assert response.headers["content-type"].startswith(
                "text/event-stream"
            )
            async for line in response.aiter_lines():
                if line.startswith("event: "):
                    events.append(line.removeprefix("event: "))
        assert events.count("result") == 1
        assert "stdout" in events

        # Unknown session id at the router edge: 404, no replica touched.
        response = await client.post(
            f"{url}/v1/sessions/sess-nope/execute",
            json={"source_code": "print(1)"},
        )
        assert response.status_code == 404

        # Router healthz + drain endpoint contracts.
        health = (await client.get(f"{url}/healthz")).json()
        assert health["status"] == "ok"
        assert set(health["replicas"]["healthy"]) == {"r0", "r1"}
        response = await client.post(f"{url}/v1/fleet/replicas/nope/drain")
        assert response.status_code == 404

        # /metrics exposes the router family.
        text = (await client.get(f"{url}/metrics")).text
        assert "bci_router_requests_total" in text
        assert "bci_router_replicas" in text
    finally:
        await _stop_fleet(stacks, router, runner, client)


async def test_exhausted_retries_return_the_honest_upstream_verdict(tmp_path):
    """When every replica answers a clean shed/drain verdict, the router
    proxies the LAST verdict — Retry-After included — instead of masking
    it as a 502; on both the buffered and streaming paths."""
    stacks, router, runner, url = await _start_fleet(tmp_path, n=2)
    client = httpx.AsyncClient(timeout=30.0)
    try:
        # Drain both replicas WITHOUT letting the router refresh: the
        # proxied attempts hit live 503s rather than failing placement.
        await router.stop()  # stop the background refresh loop
        for stack in stacks:
            stack.drain.begin()
        response = await client.post(
            f"{url}/v1/execute", json={"source_code": "print(1)"}
        )
        assert response.status_code == 503, response.text
        assert "Retry-After" in response.headers
        assert "draining" in response.json()["detail"]  # the replica's body
        async with client.stream(
            "POST",
            f"{url}/v1/execute",
            params={"stream": "1"},
            json={"source_code": "print(1)"},
        ) as stream_response:
            assert stream_response.status_code == 503
            assert "Retry-After" in stream_response.headers
        # every shed attempt was counted as a retry, none as unreachable
        retries = router.metrics.metrics["bci_router_retries_total"]._values
        assert retries.get((("reason", "unavailable"),), 0) >= 2
        assert (("reason", "unreachable"),) not in retries
    finally:
        await _stop_fleet(stacks, router, runner, client)


async def test_checkpoint_is_exempt_from_the_drain_gate(tmp_path):
    """The lease-handoff enabler: a DRAINING replica still answers session
    checkpoint (and delete) — evacuating existing state is part of
    finishing up — while new work (execute/create) keeps getting the
    drain 503."""
    shared_root = tmp_path / "shared-objects"
    stack = await ReplicaStack("r0", tmp_path, shared_root).start()
    client = httpx.AsyncClient(timeout=30.0)
    try:
        response = await client.post(f"{stack.base_url}/v1/sessions", json={})
        session_id = response.json()["session_id"]
        response = await client.post(
            f"{stack.base_url}/v1/sessions/{session_id}/execute",
            json={"source_code": "open('kept.txt', 'w').write('kept')"},
        )
        assert response.status_code == 200

        stack.drain.begin()
        # new work: rejected retryably
        response = await client.post(
            f"{stack.base_url}/v1/execute", json={"source_code": "print(1)"}
        )
        assert response.status_code == 503
        response = await client.post(
            f"{stack.base_url}/v1/sessions/{session_id}/execute",
            json={"source_code": "print(1)"},
        )
        assert response.status_code == 503
        # evacuation: checkpoint works THROUGH the drain window
        response = await client.post(
            f"{stack.base_url}/v1/sessions/{session_id}/checkpoint", json={}
        )
        assert response.status_code == 200, response.text
        files = response.json()["files"]
        assert "/workspace/kept.txt" in files
        # and the checkpointed bytes are real shared-storage objects
        assert (
            await stack.storage.read(files["/workspace/kept.txt"]) == b"kept"
        )
        response = await client.delete(
            f"{stack.base_url}/v1/sessions/{session_id}"
        )
        assert response.status_code == 200
    finally:
        await client.aclose()
        await stack.stop()


async def test_drain_endpoint_cordons_and_migrates(tmp_path):
    """Operator-initiated drain via the router API: the replica is cordoned
    out of placement and its pinned leases move — while the replica itself
    is still serving (preStop ordering, docs/fleet.md)."""
    stacks, router, runner, url = await _start_fleet(tmp_path, n=2)
    client = httpx.AsyncClient(timeout=30.0)
    try:
        response = await client.post(f"{url}/v1/sessions", json={})
        session_id = response.json()["session_id"]
        home = router.sessions[session_id].replica
        await client.post(
            f"{url}/v1/sessions/{session_id}/execute",
            json={"source_code": "open('x.txt', 'w').write('pre-drain')"},
        )
        response = await client.post(f"{url}/v1/fleet/replicas/{home}/drain")
        assert response.status_code == 200
        body = response.json()
        assert body["migrated"] == 1 and body["failed"] == 0
        assert router.sessions[session_id].replica != home
        assert router.replicas[home].cordoned
        # cordoned replicas take no new placements
        for _ in range(3):
            response = await client.post(
                f"{url}/v1/execute", json={"source_code": "print('x')"}
            )
            assert response.status_code == 200
            event = router.recorder.events(kind="routing", limit=1)[0]
            assert event["replica"] != home
        # the migrated session still reads its pre-drain state
        response = await client.post(
            f"{url}/v1/sessions/{session_id}/execute",
            json={"source_code": "print(open('x.txt').read())"},
        )
        assert response.status_code == 200
        assert "pre-drain" in response.json()["stdout"]
    finally:
        await _stop_fleet(stacks, router, runner, client)
