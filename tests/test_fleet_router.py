"""Fleet-router acceptance (ISSUE 11, docs/fleet.md): consistent-hash
placement, cross-replica retry, session pinning, and lease handoff on
drain — chaos scenario 14's tier-1 twin.

The harness is N COMPLETE in-process replicas: each one the real HTTP edge
(create_http_server) over the real KubernetesCodeExecutor against its own
fake-pod cluster, with its own SessionManager/SLO/admission/drain — all
sharing ONE SharedDirectoryBackend snapshot root, exactly the production
fleet shape minus kubectl. The real FleetRouter fronts them over real
sockets."""

import asyncio
import json
import statistics
import time

import httpx
import pytest
from aiohttp import web

from bee_code_interpreter_tpu.fleet import (
    FleetRouter,
    HashRing,
    NoReplicasAvailable,
    affinity_key,
    create_router_app,
    rendezvous_rank,
    subset_size,
)
from bee_code_interpreter_tpu.health_check import assess_router
from bee_code_interpreter_tpu.tenancy import (
    TENANT_HEADER,
    TenantRegistry,
    parse_tenants,
)
from tests.fakes import ReplicaStack, free_port

pytestmark = pytest.mark.chaos


# ------------------------------------------------------------------ units


def test_ring_preference_is_stable_under_replica_loss():
    ring = HashRing(vnodes=64)
    for name in ("r0", "r1", "r2"):
        ring.add(name)
    keys = [affinity_key({f"/workspace/{i}.txt": "ab" * 32}) for i in range(64)]
    owners_before = {k: ring.owner(k) for k in keys}
    ring.remove("r1")
    for key, owner in owners_before.items():
        if owner != "r1":
            # keys not owned by the lost replica keep their warm home
            assert ring.owner(key) == owner
        else:
            assert ring.owner(key) in ("r0", "r2")


def test_ring_shares_sum_to_one_and_spread():
    ring = HashRing(vnodes=128)
    for name in ("a", "b", "c", "d"):
        ring.add(name)
    shares = ring.shares()
    assert abs(sum(shares.values()) - 1.0) < 1e-9
    assert all(0.1 < s < 0.5 for s in shares.values()), shares


def test_affinity_key_semantics():
    assert affinity_key(None) is None
    assert affinity_key({}) is None
    a = affinity_key({"/workspace/x": "11" * 32, "/workspace/y": "22" * 32})
    b = affinity_key({"/workspace/y": "22" * 32, "/workspace/x": "11" * 32})
    assert a == b  # order-independent
    assert a != affinity_key({"/workspace/x": "11" * 32})


def _synthetic_router(clock):
    router = FleetRouter(
        [(f"r{i}", f"http://127.0.0.1:{i + 1}") for i in range(3)],
        refresh_interval_s=0.2,
        dead_after_s=5.0,
        clock=clock,
    )
    for replica in router.replicas.values():
        replica.last_refresh_mono = clock()
    return router


async def test_placement_eligibility_and_spill():
    now = [100.0]
    router = _synthetic_router(lambda: now[0])
    key = affinity_key({"/workspace/a": "ab" * 32})
    owner = router.ring.owner(key)
    assert router.place(key)[0].name == owner

    # a saturated owner with NO warm capacity spills to a healthier
    # replica; with even one ready sandbox the warm owner keeps the key
    # (it is still the fastest home)
    router.replicas[owner].utilization = 0.95
    router.replicas[owner].ready_pods = 0
    spilled = router.place(key)[0]
    assert spilled.name != owner
    assert router.affinity_result(key, spilled.name) == "spill"
    router.replicas[owner].ready_pods = 1
    assert router.place(key)[0].name == owner
    router.replicas[owner].utilization = 0.0

    # an SLO page on the owner is the same veto
    router.replicas[owner].slo_fast_burn = True
    assert router.place(key)[0].name != owner
    router.replicas[owner].slo_fast_burn = False
    assert router.place(key)[0].name == owner
    assert router.affinity_result(key, owner) == "warm"

    # draining and stale replicas leave placement
    router.replicas[owner].draining = True
    assert all(r.name != owner for r in router.place(key))
    router.replicas[owner].draining = False
    now[0] += 10.0  # every refresh is now stale
    with pytest.raises(NoReplicasAvailable):
        router.place(key)


async def test_keyless_placement_prefers_least_loaded():
    now = [50.0]
    router = _synthetic_router(lambda: now[0])
    router.replicas["r0"].utilization = 0.8
    router.replicas["r1"].utilization = 0.1
    router.replicas["r2"].utilization = 0.4
    assert router.place(None)[0].name == "r1"
    assert router.affinity_result(None, "r1") == "keyless"


def _tenant_router(clock, n=4, spec="small:weight=1:rps=5,big:weight=3:rps=30"):
    router = FleetRouter(
        [(f"r{i}", f"http://127.0.0.1:{i + 1}") for i in range(n)],
        refresh_interval_s=0.2,
        dead_after_s=5.0,
        clock=clock,
        tenancy=TenantRegistry(parse_tenants(spec)),
    )
    for replica in router.replicas.values():
        replica.last_refresh_mono = clock()
    return router


async def test_tenant_placement_lands_on_exactly_the_rendezvous_subset():
    """ISSUE 16 tentpole (a): a declared tenant's keyless traffic lands on
    exactly its rendezvous subset — k replicas proportional to weight — so
    per-replica quota enforcement composes into a fleet-wide bound."""
    now = [10.0]
    router = _tenant_router(lambda: now[0])
    small = router._tenancy.get("small")
    big = router._tenancy.get("big")

    expected_small = set(router.tenant_subset(small))
    expected_big = set(router.tenant_subset(big))
    assert len(expected_small) == subset_size(small.weight, 4) == 1
    assert len(expected_big) == subset_size(big.weight, 4) == 3

    landed_small, landed_big = set(), set()
    for _ in range(32):
        landed_small.add(router.place(None, tenant=small)[0].name)
        landed_big.add(router.place(None, tenant=big)[0].name)
    assert landed_small == expected_small
    assert landed_big <= expected_big
    chosen = router.place(None, tenant=big)[0].name
    assert router.affinity_result(None, chosen, tenant=big) == "tenant"

    # keyless/default traffic keeps pure load-based placement
    landed_keyless = {router.place(None)[0].name for _ in range(32)}
    assert landed_keyless == set(router.replicas)
    default = router._tenancy.resolve("nobody").tenant
    assert (
        router.affinity_result(
            None, router.place(None, tenant=default)[0].name, tenant=default
        )
        == "keyless"
    )


async def test_tenant_subset_reforms_minimally_when_a_replica_dies():
    """Rendezvous re-form: when a subset member dies, ONLY its slot moves —
    to the next-ranked eligible replica — and other tenants' subsets are
    untouched."""
    now = [10.0]
    router = _tenant_router(lambda: now[0])
    small = router._tenancy.get("small")
    ranking = rendezvous_rank("small", sorted(router.replicas))
    home, backup = ranking[0], ranking[1]
    assert router.place(None, tenant=small)[0].name == home

    # the subset member drops out of eligibility -> the NEXT-ranked name
    # takes its slot (not an arbitrary least-loaded replica)
    router.replicas[home].draining = True
    assert router.place(None, tenant=small)[0].name == backup
    # …and recovery restores the original subset
    router.replicas[home].draining = False
    assert router.place(None, tenant=small)[0].name == home

    # another tenant whose subset does not contain the dead replica is
    # completely unmoved by the churn
    big = router._tenancy.get("big")
    before = {router.place(None, tenant=big)[0].name for _ in range(16)}
    victim = next(n for n in router.replicas if n not in before)
    router.replicas[victim].draining = True
    after = {router.place(None, tenant=big)[0].name for _ in range(16)}
    assert after <= before


async def test_accelerator_cost_class_steers_to_capable_replicas():
    """ISSUE 16 tentpole (a): cost_class="accelerator" submissions steer to
    replicas whose learned cost-class mix shows accelerator capability."""
    now = [10.0]
    router = _tenant_router(lambda: now[0])
    router.replicas["r2"].cost_classes = {"accelerator": 5, "cpu_light": 20}
    for _ in range(8):
        assert router.place(None, cost_class="accelerator")[0].name == "r2"
        # non-accelerator work is NOT steered
        assert {r.name for r in router.place(None)[:2]} != {"r2"}
    # with no capability signal anywhere the order stands untouched
    router.replicas["r2"].cost_classes = {}
    landed = {router.place(None, cost_class="accelerator")[0].name for _ in range(16)}
    assert len(landed) > 1


async def test_router_retries_debit_the_tenant_retry_budget():
    """ISSUE 16 satellite 2: cross-replica retries consult the tenant's
    router-side retry budget — an exhausted budget ends the walk instead of
    amplifying a retry storm through the proxy."""
    now = [10.0]
    router = _tenant_router(lambda: now[0])
    small = router._tenancy.get("small")

    calls = []

    async def unreachable(replica, *a, **k):
        calls.append(replica.name)
        raise OSError("replica down")

    router.call_replica = unreachable

    # budget present: the walk retries across replicas as before
    with pytest.raises(OSError):
        await router.route_buffered(
            "/v1/execute", "POST", "/v1/execute",
            key=None, body=b"{}", headers={}, tenant=small,
        )
    assert len(calls) == router.retry_attempts

    # drain the remaining budget (burst 10; 2 already spent above)
    while router.spend_retry_budget(small):
        pass
    calls.clear()
    with pytest.raises(OSError):
        await router.route_buffered(
            "/v1/execute", "POST", "/v1/execute",
            key=None, body=b"{}", headers={}, tenant=small,
        )
    assert len(calls) == 1  # first attempt only — no budget, no retry
    denied = router.metrics.metrics[
        "bci_router_retry_budget_denied_total"
    ]._values
    assert sum(denied.values()) >= 2

    # anonymous / unlimited tenants keep pre-tenancy behavior
    calls.clear()
    with pytest.raises(OSError):
        await router.route_buffered(
            "/v1/execute", "POST", "/v1/execute",
            key=None, body=b"{}", headers={}, tenant=None,
        )
    assert len(calls) == router.retry_attempts


def test_sticky_shed_recognizes_tenant_scoped_verdicts():
    assert FleetRouter.sticky_shed(b'{"detail": "x", "reason": "tenant_quota"}')
    assert FleetRouter.sticky_shed(b'{"detail": "x", "reason": "heavy_lane"}')
    assert not FleetRouter.sticky_shed(b'{"detail": "x", "reason": "queue_full"}')
    assert not FleetRouter.sticky_shed(b"not json")
    assert not FleetRouter.sticky_shed(b"[1, 2]")


def test_assess_router_exit_ladder():
    def body(*states):
        return {
            "replicas": [
                {"name": f"r{i}", "state": s} for i, s in enumerate(states)
            ]
        }

    assert assess_router(body("healthy", "healthy"))[0] == 0
    code, message = assess_router(body("healthy", "dead", "dead"))
    assert code == 2 and "r1" in message and "r2" in message
    assert assess_router(body("healthy", "draining"))[0] == 3
    # dead outranks draining; an empty fleet is dead
    assert assess_router(body("draining", "dead"))[0] == 2
    assert assess_router({"replicas": []})[0] == 2
    assert assess_router(body("draining"))[0] == 2  # no healthy replica left


# ----------------------------------------------------------- fleet harness
# ReplicaStack (tests/fakes.py): one complete in-process replica — real HTTP
# edge + KubernetesCodeExecutor over fake pods + SessionManager/SLO/admission/
# drain — sharing one SharedDirectoryBackend snapshot root. Shared with chaos
# scenario 14 (scripts/chaos_smoke.py).


async def _start_fleet(tmp_path, n=3, **router_kwargs):
    shared_root = tmp_path / "shared-objects"
    stacks = [
        await ReplicaStack(f"r{i}", tmp_path, shared_root).start()
        for i in range(n)
    ]
    router_kwargs.setdefault("refresh_interval_s", 0.2)
    router_kwargs.setdefault("dead_after_s", 0.5)
    router = FleetRouter(
        [(s.name, s.base_url) for s in stacks], **router_kwargs
    )
    runner = web.AppRunner(create_router_app(router))
    await runner.setup()
    port = free_port()
    await web.TCPSite(runner, "127.0.0.1", port).start()
    await router.refresh_once()
    # the production shape: the background loop keeps the placement view
    # fresh (and auto-evacuates draining replicas) while load flows
    router.start()
    return stacks, router, runner, f"http://127.0.0.1:{port}"


async def _stop_fleet(stacks, router, runner, client):
    await client.aclose()
    await runner.cleanup()
    await router.stop()
    for stack in stacks:
        await stack.stop()


async def test_chaos14_affinity_handoff_and_accounting(tmp_path):
    """Chaos scenario 14's tier-1 twin: 3 replicas under mixed load, the
    replica holding leases drains and dies — affinity stays >= 90% warm,
    every live lease migrates (checkpoint -> re-lease -> restore through
    shared storage), zero lease-scoped 5xx after the kill, the surviving
    replicas' SLO page alerts stay silent, and the decision/event/counter
    accounting agrees exactly."""
    stacks, router, runner, url = await _start_fleet(tmp_path, n=3)
    client = httpx.AsyncClient(timeout=30.0)
    try:
        # Seed the SHARED store once; three distinct snapshot chains.
        seeds = []
        for i in range(3):
            object_id = await stacks[0].storage.write(f"chain-{i}".encode())
            seeds.append({"/workspace/seed.txt": object_id})

        # --- keyed warm-affinity load: 4 rounds over 3 chains
        landed: dict[int, set[str]] = {i: set() for i in range(3)}
        for _round in range(4):
            for i, files in enumerate(seeds):
                response = await client.post(
                    f"{url}/v1/execute",
                    json={
                        "source_code": "print(open('seed.txt').read())",
                        "files": files,
                    },
                )
                assert response.status_code == 200, response.text
                body = response.json()
                assert body["exit_code"] == 0
                assert f"chain-{i}" in body["stdout"]
                event = router.recorder.events(kind="routing", limit=1)[0]
                landed[i].add(event["replica"])
        # Repeat traffic lands where its chain is warm — the acceptance bar
        # is >= 90% warm placements. (Not "exactly one replica per chain":
        # a sustained-saturation spill is CORRECT router behavior, and on a
        # loaded CI box one such spill can legitimately occur.)
        total_keyed = sum(router.affinity_totals.values())
        assert router.affinity_totals["warm"] / total_keyed >= 0.9, (
            router.affinity_totals,
            landed,
        )

        # --- two live sessions through the router
        session_ids = []
        for i in range(2):
            response = await client.post(f"{url}/v1/sessions", json={})
            assert response.status_code == 200, response.text
            session_id = response.json()["session_id"]
            session_ids.append(session_id)
            response = await client.post(
                f"{url}/v1/sessions/{session_id}/execute",
                json={
                    "source_code": (
                        f"open('state.txt', 'w').write('state-{i}')\n"
                        "print('written')"
                    )
                },
            )
            assert response.status_code == 200, response.text

        # --- the replica holding session 0 drains (its SIGTERM path)
        victim_name = router.sessions[session_ids[0]].replica
        victim = next(s for s in stacks if s.name == victim_name)
        pinned_to_victim = [
            sid
            for sid in session_ids
            if router.sessions[sid].replica == victim_name
        ]
        victim.drain.begin()
        await router.refresh_once()
        assert router.replicas[victim_name].draining
        # evacuations are background tasks (a busy lease must not stall the
        # refresh loop); the background loop may have claimed the handoff
        # first, so await our spawn AND poll until the pins have moved
        await asyncio.gather(*await router.evacuate_draining())
        for _ in range(100):
            if all(
                router.sessions[sid].replica != victim_name
                for sid in pinned_to_victim
            ):
                break
            await asyncio.sleep(0.05)

        for sid in pinned_to_victim:
            assert router.sessions[sid].replica != victim_name
            assert router.sessions[sid].migrations == 1
        assert router.totals["migrations_ok"] == len(pinned_to_victim)
        assert router.totals["migrations_failed"] == 0

        # --- kill the victim outright
        await victim.stop(hard=True)
        survivors = [s for s in stacks if s.name != victim_name]

        # Every session keeps serving under its ORIGINAL id with its state
        # intact (restored from the shared checkpoint) — zero lease-scoped
        # 5xx after the kill window.
        for i, sid in enumerate(session_ids):
            response = await client.post(
                f"{url}/v1/sessions/{sid}/execute",
                json={"source_code": "print(open('state.txt').read())"},
            )
            assert response.status_code == 200, response.text
            body = response.json()
            assert body["session_id"] == sid
            assert f"state-{i}" in body["stdout"]

        # Stateless traffic re-homes (dead replica's keys spill).
        for files in seeds:
            response = await client.post(
                f"{url}/v1/execute",
                json={"source_code": "print('alive')", "files": files},
            )
            assert response.status_code == 200, response.text

        # The dead replica is visible as dead once its refresh goes stale.
        await asyncio.sleep(0.6)
        await router.refresh_once()
        snapshot = (await client.get(f"{url}/v1/fleet/replicas")).json()
        by_name = {r["name"]: r for r in snapshot["replicas"]}
        assert by_name[victim_name]["state"] == "dead"
        code, message = assess_router(snapshot)
        assert code == 2 and victim_name in message

        # SLO page alerts silent on the survivors.
        for stack in survivors:
            assert stack.slo.snapshot()["fast_burn_alerting"] is False

        # --- exactly-once accounting across the three surfaces
        routing_events = router.recorder.events(kind="routing", limit=10_000)
        assert len(routing_events) == router.totals["routed"]
        migrate_events = router.recorder.events(
            kind="lease_migrate", limit=10_000
        )
        assert len(migrate_events) == (
            router.totals["migrations_ok"] + router.totals["migrations_failed"]
        )
        requests_counter = router.metrics.metrics["bci_router_requests_total"]
        assert (
            sum(requests_counter._values.values()) == router.totals["routed"]
        )
        migrations_counter = router.metrics.metrics[
            "bci_router_lease_migrations_total"
        ]
        assert sum(migrations_counter._values.values()) == len(migrate_events)
        placed_events = [
            e for e in routing_events if e.get("replica") is not None
        ]
        assert sum(r["routed_total"] for r in by_name.values()) == len(
            placed_events
        )
        affinity_counter = router.metrics.metrics["bci_router_affinity_total"]
        assert sum(affinity_counter._values.values()) == sum(
            router.affinity_totals.values()
        )
    finally:
        await _stop_fleet(stacks, router, runner, client)


async def test_router_retries_shed_and_dead_replicas(tmp_path):
    """A replica that sheds (429) or drops off the network mid-fleet: the
    router walks the ring and the client sees one clean 200."""
    stacks, router, runner, url = await _start_fleet(tmp_path, n=2)
    client = httpx.AsyncClient(timeout=30.0)
    try:
        # Kill replica 0's listener WITHOUT telling the router: the first
        # routed attempt may hit it, fail transport, and must retry to r1.
        await stacks[0].stop(hard=True)
        ok = 0
        for i in range(4):
            response = await client.post(
                f"{url}/v1/execute",
                json={"source_code": f"print({i} + 1)"},
            )
            assert response.status_code == 200, response.text
            ok += 1
        assert ok == 4
        # the dead replica's breaker/refresh keeps later placements away
        await asyncio.sleep(0.6)
        await router.refresh_once()
        assert router.replicas["r0"].state(
            router._clock(), router.dead_after_s
        ) == "dead"
    finally:
        await _stop_fleet(stacks, router, runner, client)


async def test_router_streaming_passthrough_and_session_404(tmp_path):
    stacks, router, runner, url = await _start_fleet(tmp_path, n=2)
    client = httpx.AsyncClient(timeout=30.0)
    try:
        # SSE passthrough: stdout chunk events + exactly one result event.
        events = []
        async with client.stream(
            "POST",
            f"{url}/v1/execute",
            params={"stream": "1"},
            json={"source_code": "print('chunk-one')\nprint('chunk-two')"},
        ) as response:
            assert response.status_code == 200
            assert response.headers["content-type"].startswith(
                "text/event-stream"
            )
            async for line in response.aiter_lines():
                if line.startswith("event: "):
                    events.append(line.removeprefix("event: "))
        assert events.count("result") == 1
        assert "stdout" in events

        # Unknown session id at the router edge: 404, no replica touched.
        response = await client.post(
            f"{url}/v1/sessions/sess-nope/execute",
            json={"source_code": "print(1)"},
        )
        assert response.status_code == 404

        # Router healthz + drain endpoint contracts.
        health = (await client.get(f"{url}/healthz")).json()
        assert health["status"] == "ok"
        assert set(health["replicas"]["healthy"]) == {"r0", "r1"}
        response = await client.post(f"{url}/v1/fleet/replicas/nope/drain")
        assert response.status_code == 404

        # /metrics exposes the router family.
        text = (await client.get(f"{url}/metrics")).text
        assert "bci_router_requests_total" in text
        assert "bci_router_replicas" in text
    finally:
        await _stop_fleet(stacks, router, runner, client)


async def test_exhausted_retries_return_the_honest_upstream_verdict(tmp_path):
    """When every replica answers a clean shed/drain verdict, the router
    proxies the LAST verdict — Retry-After included — instead of masking
    it as a 502; on both the buffered and streaming paths."""
    stacks, router, runner, url = await _start_fleet(tmp_path, n=2)
    client = httpx.AsyncClient(timeout=30.0)
    try:
        # Drain both replicas WITHOUT letting the router refresh: the
        # proxied attempts hit live 503s rather than failing placement.
        await router.stop()  # stop the background refresh loop
        for stack in stacks:
            stack.drain.begin()
        response = await client.post(
            f"{url}/v1/execute", json={"source_code": "print(1)"}
        )
        assert response.status_code == 503, response.text
        assert "Retry-After" in response.headers
        assert "draining" in response.json()["detail"]  # the replica's body
        async with client.stream(
            "POST",
            f"{url}/v1/execute",
            params={"stream": "1"},
            json={"source_code": "print(1)"},
        ) as stream_response:
            assert stream_response.status_code == 503
            assert "Retry-After" in stream_response.headers
        # every shed attempt was counted as a retry, none as unreachable
        retries = router.metrics.metrics["bci_router_retries_total"]._values
        assert retries.get((("reason", "unavailable"),), 0) >= 2
        assert (("reason", "unreachable"),) not in retries
    finally:
        await _stop_fleet(stacks, router, runner, client)


async def test_checkpoint_is_exempt_from_the_drain_gate(tmp_path):
    """The lease-handoff enabler: a DRAINING replica still answers session
    checkpoint (and delete) — evacuating existing state is part of
    finishing up — while new work (execute/create) keeps getting the
    drain 503."""
    shared_root = tmp_path / "shared-objects"
    stack = await ReplicaStack("r0", tmp_path, shared_root).start()
    client = httpx.AsyncClient(timeout=30.0)
    try:
        response = await client.post(f"{stack.base_url}/v1/sessions", json={})
        session_id = response.json()["session_id"]
        response = await client.post(
            f"{stack.base_url}/v1/sessions/{session_id}/execute",
            json={"source_code": "open('kept.txt', 'w').write('kept')"},
        )
        assert response.status_code == 200

        stack.drain.begin()
        # new work: rejected retryably
        response = await client.post(
            f"{stack.base_url}/v1/execute", json={"source_code": "print(1)"}
        )
        assert response.status_code == 503
        response = await client.post(
            f"{stack.base_url}/v1/sessions/{session_id}/execute",
            json={"source_code": "print(1)"},
        )
        assert response.status_code == 503
        # evacuation: checkpoint works THROUGH the drain window
        response = await client.post(
            f"{stack.base_url}/v1/sessions/{session_id}/checkpoint", json={}
        )
        assert response.status_code == 200, response.text
        files = response.json()["files"]
        assert "/workspace/kept.txt" in files
        # and the checkpointed bytes are real shared-storage objects
        assert (
            await stack.storage.read(files["/workspace/kept.txt"]) == b"kept"
        )
        response = await client.delete(
            f"{stack.base_url}/v1/sessions/{session_id}"
        )
        assert response.status_code == 200
    finally:
        await client.aclose()
        await stack.stop()


async def test_tenant_quota_sheds_are_never_retried_cross_replica(tmp_path):
    """ISSUE 16 satellite 1, both transports: a ``reason="tenant_quota"``
    429 is a per-TENANT verdict — the router must return it verbatim
    (Retry-After intact) instead of "retrying" it into a fresh replica's
    token bucket, which would silently multiply the tenant's quota."""
    spec = "capped:weight=1:rps=1:burst=1"
    shared_root = tmp_path / "shared-objects"
    stacks = [
        await ReplicaStack(f"r{i}", tmp_path, shared_root, tenants=spec).start()
        for i in range(2)
    ]
    router = FleetRouter(
        [(s.name, s.base_url) for s in stacks],
        refresh_interval_s=0.2,
        dead_after_s=0.5,
        tenancy=TenantRegistry(parse_tenants(spec)),
    )
    runner = web.AppRunner(create_router_app(router))
    await runner.setup()
    port = free_port()
    await web.TCPSite(runner, "127.0.0.1", port).start()
    await router.refresh_once()
    router.start()
    url = f"http://127.0.0.1:{port}"
    client = httpx.AsyncClient(timeout=30.0)
    headers = {TENANT_HEADER: "capped"}
    try:
        # burn the burst-1 bucket, then hit the quota on BOTH transports
        response = await client.post(
            f"{url}/v1/execute",
            json={"source_code": "print('ok')"},
            headers=headers,
        )
        assert response.status_code == 200, response.text

        response = await client.post(
            f"{url}/v1/execute",
            json={"source_code": "print('ok')"},
            headers=headers,
        )
        assert response.status_code == 429, response.text
        assert response.json()["reason"] == "tenant_quota"  # verbatim body
        assert "Retry-After" in response.headers

        async with client.stream(
            "POST",
            f"{url}/v1/execute",
            params={"stream": "1"},
            json={"source_code": "print('ok')"},
            headers=headers,
        ) as stream_response:
            assert stream_response.status_code == 429
            assert "Retry-After" in stream_response.headers
            body = json.loads(await stream_response.aread())
            assert body["reason"] == "tenant_quota"

        # ZERO cross-replica shed retries: the verdicts were terminal
        retries = router.metrics.metrics["bci_router_retries_total"]._values
        assert retries.get((("reason", "shed"),), 0) == 0
        # and only ONE replica's bucket was ever charged for the tenant
        charged = [
            s
            for s in stacks
            if "capped" in s.admission.tenant_snapshot()
        ]
        assert len(charged) == 1
    finally:
        await client.aclose()
        await runner.cleanup()
        await router.stop()
        for stack in stacks:
            await stack.stop()


# ------------------------------------------------------- chaos 16 twin
# Chaos scenario 16 (scripts/chaos_smoke.py): fleet-wide tenancy under a
# router-edge kill. 3 replicas + 2 peered router edges; a keyless abuser
# flooding 100x its fleet-wide quota through both edges is held to <= 1.2x
# that quota; victims' p50 stays within 10% with zero sheds; one router is
# killed mid-flood with zero lease-scoped 5xx; sheds + leases account
# exactly once across /v1/tenants <-> wide events <-> metrics.


async def test_chaos16_twin_fleet_tenancy_survives_router_kill(tmp_path):
    spec = "abuser:weight=1:rps=2:burst=2,victim:weight=4"
    shared_root = tmp_path / "shared-objects"
    port_a, port_b = free_port(), free_port()
    url_a = f"http://127.0.0.1:{port_a}"
    url_b = f"http://127.0.0.1:{port_b}"
    # each replica leases its fleet-wide quota slices from BOTH edges,
    # preferring A — exactly the failover the kill must exercise
    stacks = [
        await ReplicaStack(
            f"r{i}",
            tmp_path,
            shared_root,
            tenants=spec,
            lease_router_urls=[url_a, url_b],
        ).start()
        for i in range(3)
    ]

    def make_router(rid, peer_name, peer_url):
        return FleetRouter(
            [(s.name, s.base_url) for s in stacks],
            refresh_interval_s=0.2,
            dead_after_s=1.0,
            tenancy=TenantRegistry(parse_tenants(spec)),
            peers=[(peer_name, peer_url)],
            quota_ttl_s=1.0,
            router_id=rid,
        )

    router_a = make_router("A", "b", url_b)
    router_b = make_router("B", "a", url_a)
    runners = []
    for router, port in ((router_a, port_a), (router_b, port_b)):
        runner = web.AppRunner(create_router_app(router))
        await runner.setup()
        await web.TCPSite(runner, "127.0.0.1", port).start()
        await router.refresh_once()
        router.start()
        runners.append(runner)
    runner_a, runner_b = runners
    client = httpx.AsyncClient(timeout=30.0)
    abuse_statuses: list[int] = []
    try:
        body = {"source_code": "print('ok')"}

        # --- a session created through edge A, state written
        response = await client.post(f"{url_a}/v1/sessions", json={})
        assert response.status_code == 200, response.text
        session_id = response.json()["session_id"]
        response = await client.post(
            f"{url_a}/v1/sessions/{session_id}/execute",
            json={"source_code": "open('state.txt', 'w').write('sixteen')"},
        )
        assert response.status_code == 200

        async def victim_request(base_url) -> float:
            t0 = time.perf_counter()
            resp = await client.post(
                f"{base_url}/v1/execute",
                json=body,
                headers={TENANT_HEADER: "victim"},
            )
            assert resp.status_code == 200, resp.text
            return time.perf_counter() - t0

        # --- victim baseline through edge B (the surviving edge)
        baseline = []
        for _ in range(12):
            baseline.append(await victim_request(url_b))
            await asyncio.sleep(0.02)
        p50_base = statistics.median(baseline)

        flood_start = time.monotonic()

        async def abuse(base_url) -> None:
            resp = await client.post(
                f"{base_url}/v1/execute",
                json=body,
                headers={TENANT_HEADER: "abuser"},
            )
            assert resp.status_code in (200, 429), resp.text
            abuse_statuses.append(resp.status_code)

        # --- wave 1: the abuser sprays keyless across BOTH edges while
        # the victim keeps its steady trickle through B
        wave1 = [
            asyncio.create_task(abuse(url_a if i % 2 else url_b))
            for i in range(60)
        ]
        during = []
        for _ in range(6):
            during.append(await victim_request(url_b))
            await asyncio.sleep(0.02)
        await asyncio.gather(*wave1)
        # give the pin/ledger gossip + lease refresh one full beat
        await asyncio.sleep(0.5)

        # --- kill edge A mid-flood
        await runner_a.cleanup()
        await router_a.stop()

        # --- wave 2: the flood continues through the survivor
        wave2 = [asyncio.create_task(abuse(url_b)) for i in range(60)]
        for _ in range(6):
            during.append(await victim_request(url_b))
            await asyncio.sleep(0.02)
        await asyncio.gather(*wave2)
        elapsed = time.monotonic() - flood_start
        p50_during = statistics.median(during)

        # --- the abuser is held to <= 1.2x its FLEET-wide quota
        admitted = sum(
            s.admission.tenant_snapshot()
            .get("abuser", {})
            .get("admitted", 0)
            for s in stacks
        )
        abuser = router_b._tenancy.get("abuser")
        bound = 1.2 * (abuser.rps * elapsed + abuser.burst_depth)
        assert abuse_statuses.count(200) == admitted
        assert admitted <= bound, (admitted, bound, elapsed)
        assert admitted >= 1  # the quota is enforced, not the service down

        # --- victims provably untouched: p50 within 10% (+ jitter floor),
        # ZERO victim sheds on any replica, on every ledger
        assert p50_during <= p50_base * 1.10 + 0.01, (p50_base, p50_during)
        for stack in stacks:
            snapshot = stack.admission.tenant_snapshot()
            assert snapshot.get("victim", {}).get("sheds", {}) == {}
            assert (
                stack.recorder.events(outcome="shed", tenant="victim") == []
            )

        # --- zero lease-scoped 5xx: the session created through the DEAD
        # edge keeps serving through the survivor (pins gossiped), state
        # intact, same public id
        response = await client.post(
            f"{url_b}/v1/sessions/{session_id}/execute",
            json={"source_code": "print(open('state.txt').read())"},
        )
        assert response.status_code == 200, response.text
        assert "sixteen" in response.json()["stdout"]
        assert response.json()["session_id"] == session_id

        # --- the survivor noticed the dead peer (operator signal), and
        # its ledger holds the reconciled lease state
        assert router_b.peers["a"].failures >= 1
        ledger = router_b.ledger.snapshot()
        assert "abuser" in ledger["tenants"]
        lessees = set(ledger["tenants"]["abuser"]["lessees"])
        assert len(lessees) == 1  # single-subset tenant: ONE lessee
        # the lessee replica holds a live lease for its FULL fleet slice
        lessee_stack = next(s for s in stacks if s.name in lessees)
        lease = lessee_stack.quota_leases.lease("abuser")
        assert lease is not None
        assert lease.rps == pytest.approx(abuser.rps)
        # replicas the abuser never reached never claimed a slice
        for stack in stacks:
            if stack.name not in lessees:
                assert stack.quota_leases.lease("abuser") is None

        # --- sticky sheds: no tenant_quota verdict was ever re-walked
        retries_b = router_b.metrics.metrics[
            "bci_router_retries_total"
        ]._values
        assert retries_b.get((("reason", "shed"),), 0) == 0

        # --- exactly-once shed accounting across the three surfaces,
        # summed over the fleet: admission snapshot <-> tenant usage
        # (/v1/tenants) <-> wide events <-> bci_tenant_shed_total
        total_sheds = 0
        for stack in stacks:
            lane = stack.admission.tenant_snapshot().get("abuser")
            sheds = sum((lane or {}).get("sheds", {}).values())
            total_sheds += sheds
            wide = stack.recorder.events(
                outcome="shed", tenant="abuser", limit=10_000
            )
            assert len(wide) == sheds
            counter = sum(
                v
                for key, v in stack.metrics.metrics["bci_tenant_shed_total"]
                ._values.items()
                if ("tenant", "abuser") in key
            )
            assert counter == sheds
            tenants_doc = (
                await client.get(f"{stack.base_url}/v1/tenants")
            ).json()
            usage = tenants_doc["tenants"].get("abuser", {}).get("usage")
            if usage is not None:
                assert usage["sheds"] == sheds
        assert total_sheds == abuse_statuses.count(429)
        assert admitted + total_sheds == len(abuse_statuses)
    finally:
        await client.aclose()
        await runner_b.cleanup()
        await router_b.stop()
        await router_a.stop()
        for stack in stacks:
            await stack.stop()


async def test_drain_endpoint_cordons_and_migrates(tmp_path):
    """Operator-initiated drain via the router API: the replica is cordoned
    out of placement and its pinned leases move — while the replica itself
    is still serving (preStop ordering, docs/fleet.md)."""
    stacks, router, runner, url = await _start_fleet(tmp_path, n=2)
    client = httpx.AsyncClient(timeout=30.0)
    try:
        response = await client.post(f"{url}/v1/sessions", json={})
        session_id = response.json()["session_id"]
        home = router.sessions[session_id].replica
        await client.post(
            f"{url}/v1/sessions/{session_id}/execute",
            json={"source_code": "open('x.txt', 'w').write('pre-drain')"},
        )
        response = await client.post(f"{url}/v1/fleet/replicas/{home}/drain")
        assert response.status_code == 200
        body = response.json()
        assert body["migrated"] == 1 and body["failed"] == 0
        assert router.sessions[session_id].replica != home
        assert router.replicas[home].cordoned
        # cordoned replicas take no new placements
        for _ in range(3):
            response = await client.post(
                f"{url}/v1/execute", json={"source_code": "print('x')"}
            )
            assert response.status_code == 200
            event = router.recorder.events(kind="routing", limit=1)[0]
            assert event["replica"] != home
        # the migrated session still reads its pre-drain state
        response = await client.post(
            f"{url}/v1/sessions/{session_id}/execute",
            json={"source_code": "print(open('x.txt').read())"},
        )
        assert response.status_code == 200
        assert "pre-drain" in response.json()["stdout"]
    finally:
        await _stop_fleet(stacks, router, runner, client)
