"""Model family on the virtual 8-device CPU mesh: forward, sharded training
convergence, ring-attention path, generation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from bee_code_interpreter_tpu.models import MnistMlp, Transformer, TransformerConfig
from bee_code_interpreter_tpu.parallel import make_mesh


@pytest.fixture(scope="module")
def tiny():
    return TransformerConfig.tiny()


def toy_batch(config, B=8, L=32, key=0):
    tokens = jax.random.randint(
        jax.random.PRNGKey(key), (B, L + 1), 0, config.vocab_size
    )
    return {"tokens": tokens[:, :-1], "targets": tokens[:, 1:]}


def test_forward_shapes_no_mesh(tiny):
    model = Transformer(tiny)
    params = model.init(jax.random.PRNGKey(0))
    batch = toy_batch(tiny)
    logits = model.apply(params, batch["tokens"])
    assert logits.shape == (8, 32, tiny.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_causality(tiny):
    """Changing a future token must not affect earlier logits."""
    model = Transformer(tiny)
    params = model.init(jax.random.PRNGKey(0))
    tokens = toy_batch(tiny, B=1, L=16)["tokens"]
    logits1 = model.apply(params, tokens)
    perturbed = tokens.at[0, -1].set((tokens[0, -1] + 1) % tiny.vocab_size)
    logits2 = model.apply(params, perturbed)
    np.testing.assert_allclose(
        np.asarray(logits1[0, :-1]), np.asarray(logits2[0, :-1]), atol=1e-5
    )


@pytest.mark.parametrize(
    "axes",
    [{"dp": 8}, {"dp": 2, "tp": 4}, {"dp": 2, "sp": 2, "tp": 2}, {"fsdp": 4, "tp": 2}],
)
def test_train_step_sharded(tiny, axes):
    """The full training step compiles and runs under every mesh shape."""
    mesh = make_mesh(axes)
    model = Transformer(tiny, mesh)
    params = model.init(jax.random.PRNGKey(0))
    optimizer = model.make_optimizer(1e-3)
    opt_state = optimizer.init(params)
    step = model.make_train_step(optimizer)
    batch = jax.device_put(toy_batch(tiny), model.batch_sharding())
    params, opt_state, loss1 = step(params, opt_state, batch)
    params, opt_state, loss2 = step(params, opt_state, batch)
    assert jnp.isfinite(loss1) and jnp.isfinite(loss2)
    assert float(loss2) < float(loss1)  # same batch: loss must drop


def f32_tiny():
    import dataclasses
    return dataclasses.replace(TransformerConfig.tiny(), dtype=jnp.float32)


def test_tp_matches_single_device():
    """Tensor-parallel forward must be numerically equal to unsharded (f32:
    bf16 would differ by reduction order across tp shards)."""
    tiny = f32_tiny()
    tokens = toy_batch(tiny, B=2, L=16)["tokens"]
    single = Transformer(tiny)
    params = single.init(jax.random.PRNGKey(0))
    ref = single.apply(params, tokens)

    mesh = make_mesh({"dp": 2, "tp": 4})
    sharded_model = Transformer(tiny, mesh)
    from bee_code_interpreter_tpu.models.transformer import shard_params

    sharded = shard_params(params, tiny, mesh)
    out = sharded_model.apply(sharded, tokens)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-4, rtol=2e-4)


def test_ring_attention_path_matches():
    """sp > 1 (ring attention) must equal the sp == 1 result."""
    tiny = f32_tiny()
    tokens = toy_batch(tiny, B=2, L=32)["tokens"]
    params = Transformer(tiny).init(jax.random.PRNGKey(0))

    mesh_sp = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    from bee_code_interpreter_tpu.models.transformer import shard_params

    out_sp = Transformer(tiny, mesh_sp).apply(
        shard_params(params, tiny, mesh_sp), tokens
    )
    ref = Transformer(tiny).apply(params, tokens)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out_sp), atol=2e-4, rtol=2e-4)


def test_generate(tiny):
    model = Transformer(tiny)
    params = model.init(jax.random.PRNGKey(0))
    prompt = jnp.ones((2, 4), dtype=jnp.int32)
    out = model.generate(params, prompt, max_new_tokens=4)
    assert out.shape == (2, 8)
    assert (out[:, :4] == prompt).all()
    # greedy decode is deterministic
    out2 = model.generate(params, prompt, max_new_tokens=4)
    assert (out == out2).all()


def test_mnist_dp_training_converges():
    mesh = make_mesh({"dp": 8})
    model = MnistMlp(hidden_sizes=(64,), mesh=mesh)
    params = model.init(jax.random.PRNGKey(0))
    step, optimizer = model.make_train_step(0.1)
    opt_state = optimizer.init(params)

    key = jax.random.PRNGKey(1)
    images = jax.random.normal(key, (256, 784))
    labels = jax.random.randint(key, (256,), 0, 10)
    # memorize a small random batch: loss must fall substantially
    batch = jax.device_put({"image": images, "label": labels}, model.batch_sharding())
    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_generate_cached_matches_uncached():
    # The cached decode (decode_step + generate_cached) must be token-exact
    # vs the full-re-encode generate. f32 avoids bf16 argmax tie drift
    # obscuring a real mismatch.
    import dataclasses

    import jax
    import jax.numpy as jnp

    from bee_code_interpreter_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )

    config = dataclasses.replace(
        TransformerConfig.tiny(), dtype=jnp.float32, n_kv_heads=2
    )
    model = Transformer(config)
    params = model.init(jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0, config.vocab_size)

    uncached = model.generate(params, prompt, max_new_tokens=6)
    cached = model.generate_cached(params, prompt, max_new_tokens=6)
    assert cached.shape == uncached.shape
    assert (cached == uncached).all(), (cached, uncached)


def test_generate_cached_single_token():
    # max_new_tokens=1 takes the zero-decode-steps path (prefill only)
    import jax

    from bee_code_interpreter_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )

    model = Transformer(TransformerConfig.tiny())
    params = model.init(jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 5), 0, 256)
    uncached = model.generate(params, prompt, max_new_tokens=1)
    cached = model.generate_cached(params, prompt, max_new_tokens=1)
    assert (cached == uncached).all()


def test_llama3_8b_lowering_at_baseline_topology():
    # VERDICT r2 weak #4: the flagship config was never validated at its own
    # scale. Lower (not compile) the full 8B train step on a virtual v5e-64
    # mesh ({"fsdp":8,"tp":8}) and prefill+cached-decode on {"dp":2,"sp":4,
    # "tp":8}, with the analytic per-device HBM fit check. Runs in a
    # subprocess because it needs 64 virtual devices (the suite pins 8).
    import json
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("PALLAS_", "AXON_"))
    }
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=64"
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, str(repo / "scripts" / "validate-llama3-topology.py")],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=repo,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    cases = [json.loads(line) for line in out.stdout.splitlines() if line.strip()]
    by_case = {c["case"]: c for c in cases}
    assert by_case["train"]["lowered"]
    assert by_case["train"]["per_device_state_gib"] < 16
    assert by_case["decode"]["prefill_lowered"]
    assert by_case["decode"]["decode_lowered"]
    # flagship MoE (Mixtral-8x7B shapes) over fsdp x ep x tp
    assert by_case["train_moe"]["lowered"]
    assert by_case["train_moe"]["per_device_state_gib"] < 16


def test_gqa_partial_broadcast_when_tp_exceeds_kv_heads():
    # kv_heads=2 on a tp=4 mesh: K/V broadcast to lcm(2,4)=4 heads (the
    # minimal multiple that shards over tp), NOT all the way to n_heads=8 —
    # and group-major q→kv pairing must survive, i.e. the sharded forward
    # equals the single-shard one.
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bee_code_interpreter_tpu.models.transformer import (
        TransformerConfig,
        forward,
        init_params,
        shard_params,
    )
    from bee_code_interpreter_tpu.parallel.mesh import make_mesh

    config = dataclasses.replace(
        TransformerConfig.tiny(), dtype=jnp.float32, n_heads=8, n_kv_heads=2
    )
    mesh = make_mesh({"tp": 4}, devices=jax.devices()[:4])
    params = init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 24), 0, config.vocab_size)

    want = forward(params, tokens, config)  # mesh=None
    got = forward(shard_params(params, config, mesh), tokens, config, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4)


def test_rope_scaling_context_extension():
    # Linear position interpolation: scaling=s must equal running rope at
    # positions/s, the identity the context-extension recipe rests on; and
    # the cached decode stays consistent under a scaled config.
    import dataclasses

    from bee_code_interpreter_tpu.models.transformer import rope

    x = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 8, 16))
    pos = jnp.arange(8, dtype=jnp.int32)[None, :] * 4
    a = rope(x, pos, 10000.0, scaling=4.0)
    b = rope(x, (pos / 4).astype(jnp.float32), 10000.0)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-6)

    config = dataclasses.replace(
        TransformerConfig.tiny(), dtype=jnp.float32, rope_scaling=4.0
    )
    model = Transformer(config)
    params = model.init(jax.random.PRNGKey(1))
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 6), 0, config.vocab_size)
    assert (
        model.generate(params, prompt, 5)
        == model.generate_cached(params, prompt, 5)
    ).all()


def test_rope_scaling_validated():
    from bee_code_interpreter_tpu.models.transformer import rope

    x = jnp.zeros((1, 1, 4, 8))
    pos = jnp.arange(4, dtype=jnp.int32)[None, :]
    with pytest.raises(ValueError, match="rope scaling must be > 0"):
        rope(x, pos, 10000.0, scaling=0.0)
