"""Event-loop health monitor + continuous profiler (ISSUE 8): ManualClock-
driven lag/stall detection, the live probe against a real loop hog, the
task inventory, profiler collapsed-stack shape and bounded overhead, and
the debug endpoints serving real data through the HTTP edge."""

import asyncio
import threading
import time

import pytest

from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_tpu.api.http_server import create_http_server
from bee_code_interpreter_tpu.observability import (
    ContinuousProfiler,
    FlightRecorder,
    LoopMonitor,
    collapse_stack,
    task_inventory,
)
from bee_code_interpreter_tpu.observability.contprof import ProfileWindow
from bee_code_interpreter_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_tpu.utils.metrics import Registry
from tests.chaos import ManualClock, block_loop


# ------------------------------------------------------------- loop monitor


def test_lag_probe_under_manual_clock():
    clock = ManualClock()
    metrics = Registry()
    recorder = FlightRecorder(metrics=metrics)
    monitor = LoopMonitor(
        interval_s=1.0,
        stall_threshold_s=0.5,
        recorder=recorder,
        metrics=metrics,
        clock=clock,
    )
    # on-time wakeup: zero lag, no stall
    monitor.arm()
    clock.advance(1.0)
    assert monitor.tick() == 0.0
    assert monitor.stalls == 0
    # a wakeup 1.5s late: lag recorded, stall captured
    monitor.arm()
    clock.advance(2.5)
    assert monitor.tick() == pytest.approx(1.5)
    assert monitor.probes == 2
    assert monitor.stalls == 1
    assert monitor.last_lag_s == pytest.approx(1.5)
    assert monitor.max_lag_s == pytest.approx(1.5)
    stall = monitor.last_stall
    assert stall is not None and stall["lag_s"] == pytest.approx(1.5)
    assert "tasks" in stall  # the dump shape exists even outside a loop
    # the stall became a wide event and the metrics observed both probes
    events = recorder.events(kind="loop_stall")
    assert len(events) == 1 and events[0]["outcome"] == "stall"
    assert events[0]["lag_s"] == pytest.approx(1.5)
    text = metrics.expose()
    assert "bci_event_loop_lag_seconds_count 2" in text
    assert "bci_loop_stalls_total 1" in text
    # sub-threshold lag never captures
    monitor.arm()
    clock.advance(1.2)
    monitor.tick()
    assert monitor.stalls == 1


async def test_live_probe_catches_a_real_loop_hog():
    recorder = FlightRecorder()
    monitor = LoopMonitor(
        interval_s=0.05, stall_threshold_s=0.15, recorder=recorder
    )
    monitor.start()
    try:
        await asyncio.sleep(0.12)  # a couple of healthy probes
        block_loop(0.3)  # the loop hog the monitor exists to catch
        await asyncio.sleep(0.12)  # let the late wakeup fire
    finally:
        await monitor.stop()
    assert monitor.probes >= 2
    assert monitor.stalls >= 1
    assert monitor.max_lag_s >= 0.15
    stall_events = recorder.events(kind="loop_stall")
    assert stall_events
    # the dump was taken from inside the running loop: real tasks captured
    assert stall_events[0]["tasks"]["count"] >= 1


async def test_task_inventory_names_and_stacks():
    release = asyncio.Event()

    async def parked():
        await release.wait()

    task = asyncio.get_running_loop().create_task(parked(), name="bci-parked")
    await asyncio.sleep(0)
    try:
        inventory = task_inventory()
        assert inventory["count"] >= 2  # this test's task + parked
        mine = [t for t in inventory["tasks"] if t["name"] == "bci-parked"]
        assert len(mine) == 1
        assert mine[0]["done"] is False
        assert any("parked" in frame for frame in mine[0]["stack"])
    finally:
        release.set()
        await task


def test_disabled_monitor_never_starts():
    monitor = LoopMonitor(interval_s=0)
    assert monitor.enabled is False
    monitor.start()  # no loop needed: disabled start is a no-op
    assert monitor.running is False


# -------------------------------------------------------- continuous profiler


def _burn_for_profiler(stop: threading.Event) -> None:
    x = 0
    while not stop.is_set():
        x += 1


def test_profiler_collapsed_stack_shape_and_trace_ids():
    metrics = Registry()
    profiler = ContinuousProfiler(
        hz=50,
        window_s=3600,
        active_trace_ids=lambda: ("deadbeef" * 4,),
        metrics=metrics,
    )
    stop = threading.Event()
    worker = threading.Thread(
        target=_burn_for_profiler, args=(stop,), daemon=True
    )
    worker.start()
    try:
        for _ in range(25):
            profiler.sample_once()
    finally:
        stop.set()
        worker.join()
    window = profiler.latest_window()
    assert window.samples == 25
    # folded format: every line is "frame;frame;... count", and the busy
    # worker's function is visible as a leaf frame
    folded = profiler.collapsed()
    assert folded
    for line in folded.splitlines():
        stack, _, count = line.rpartition(" ")
        assert stack and count.isdigit()
    assert "_burn_for_profiler" in folded
    assert "deadbeef" * 4 in window.trace_ids
    snapshot = profiler.snapshot()
    assert snapshot["window"]["samples"] == 25
    assert snapshot["window"]["hot_stacks"]
    assert "bci_contprof_samples_total 25" in metrics.expose()


def test_profiler_excludes_its_own_thread():
    profiler = ContinuousProfiler(hz=50, window_s=3600)
    profiler.start()
    try:
        time.sleep(0.15)
    finally:
        profiler.stop()
    window = profiler.latest_window()
    assert window.samples >= 2
    assert all("contprof" not in stack for stack in window.stacks)


def test_profiler_window_rolls_and_bounds_stacks():
    clock_now = [1000.0]
    profiler = ContinuousProfiler(
        hz=50, window_s=10.0, max_windows=2, clock=lambda: clock_now[0]
    )
    profiler.sample_once()
    clock_now[0] += 11.0  # past the window bound -> roll on next sample
    profiler.sample_once()
    clock_now[0] += 11.0
    profiler.sample_once()
    windows = profiler.windows()
    assert len(windows) == 3  # two completed + current
    assert windows[0].end_unix is not None
    # direct bound check: past max_stacks new stacks aggregate as truncated
    window = ProfileWindow(0.0, max_stacks=2, max_trace_ids=4)
    for name in ("a;b", "a;c", "d;e", "f;g"):
        window.add(name)
    assert len(window.stacks) == 3
    assert window.stacks["<truncated>"] == 2


def test_profiler_overhead_is_bounded():
    """The always-on budget: one sample must stay far below the ~53ms
    sampling period, or "low overhead" is a lie. 5ms/sample would be <10%
    of the period; real cost is tens of microseconds."""
    profiler = ContinuousProfiler(hz=19)
    profiler.sample_once()  # warm
    n = 200
    start = time.perf_counter()
    for _ in range(n):
        profiler.sample_once()
    per_sample = (time.perf_counter() - start) / n
    assert per_sample < 0.005, f"{per_sample * 1000:.2f}ms per sample"


def test_collapse_stack_depth_capped():
    def recurse(depth):
        if depth == 0:
            import sys

            return collapse_stack(
                sys._current_frames()[threading.get_ident()], max_depth=5
            )
        return recurse(depth - 1)

    collapsed = recurse(20)
    assert collapsed.count(";") == 4  # 5 frames -> 4 separators


# ------------------------------------------------- debug endpoints (HTTP e2e)


async def test_debug_endpoints_serve_real_data(local_executor):
    """Acceptance: with the monitor and profiler ON, /v1/debug/tasks,
    /v1/debug/pprof and bci_event_loop_lag_seconds all serve real data
    through the HTTP edge, and healthz?verbose=1 carries the loop view."""
    metrics = Registry()
    monitor = LoopMonitor(interval_s=0.05, metrics=metrics)
    profiler = ContinuousProfiler(hz=100, window_s=3600, metrics=metrics)
    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        metrics=metrics,
        loopmon=monitor,
        contprof=profiler,
    )
    client = TestClient(TestServer(app))
    await client.start_server()
    monitor.start()
    profiler.start()
    try:
        await client.post("/v1/execute", json={"source_code": "print(1)"})
        await asyncio.sleep(0.2)  # a few probes and samples land

        tasks = await (await client.get("/v1/debug/tasks")).json()
        assert tasks["count"] >= 1 and tasks["threads"]
        assert tasks["monitor"]["probes"] >= 1

        pprof = await client.get("/v1/debug/pprof")
        assert pprof.status == 200
        assert (await pprof.text()).strip()  # collapsed stacks present
        pprof_json = await (
            await client.get("/v1/debug/pprof", params={"format": "json"})
        ).json()
        assert pprof_json["window"]["samples"] >= 1

        health = await (
            await client.get("/healthz", params={"verbose": "1"})
        ).json()
        assert health["loop"]["probes"] >= 1

        text = (
            await (await client.get("/metrics")).text()
        )
        assert "bci_event_loop_lag_seconds_count" in text
        assert "bci_contprof_samples_total" in text
    finally:
        profiler.stop()
        await monitor.stop()
        await client.close()


async def test_pprof_unwired_is_501(local_executor):
    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
    )
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        assert (await client.get("/v1/debug/pprof")).status == 501
    finally:
        await client.close()
