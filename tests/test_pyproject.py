"""pyproject.toml is the one source of truth for dependencies: every
third-party import in the package must be covered by ``dependencies`` or
the ``models`` extra (so ``pip install .[models]`` yields a working
install — the property the reference's poetry metadata had, reference
pyproject.toml:9-30), the control plane must need CORE deps only (its
Docker image deliberately ships without the jax stack), and CI must
install from the metadata rather than a hand-kept list."""

import ast
import sys
from pathlib import Path

import pytest

# tomllib landed in 3.11; on older interpreters skip (don't error) collection.
tomllib = pytest.importorskip("tomllib")

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "bee_code_interpreter_tpu"

# import name -> PyPI distribution name, where they differ
DIST_OF = {
    "grpc": "grpcio",
    "google": "protobuf",  # google.protobuf
    "orbax": "orbax-checkpoint",
}

# imports that are deliberately NOT dependencies
EXEMPT = {
    "bee_code_interpreter_tpu",  # self
    "torch_xla",  # sandbox-image-only, inside a try/except in the shim
    "libtpu",  # probed, never required
}

# the model/serving stack: installed via the `models` (or `tpu`) extra
MODELS_SUBTREES = ("models", "ops", "parallel")


def load_meta() -> dict:
    return tomllib.loads((REPO / "pyproject.toml").read_text())


def dist_names(specs: list[str]) -> set[str]:
    out = set()
    for spec in specs:
        name = (
            spec.split(";")[0].split("[")[0].split(">")[0].split("<")[0]
            .split("=")[0].split("!")[0].split("~")[0].strip()
        )
        out.add(name.lower())
    return out


def imports_of(path: Path) -> set[str]:
    """Top-level names imported in one file (module level or function
    level — a lazy import is still a runtime dependency)."""
    found = set()
    tree = ast.parse(path.read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                found.add(alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            if node.module:
                found.add(node.module.split(".")[0])
    return found


def third_party(names: set[str]) -> set[str]:
    return {
        DIST_OF.get(n, n).lower()
        for n in names
        if n not in sys.stdlib_module_names and n not in EXEMPT
    }


def test_every_third_party_import_is_declared():
    meta = load_meta()
    covered = dist_names(meta["project"]["dependencies"]) | dist_names(
        meta["project"]["optional-dependencies"]["models"]
    )
    missing = []
    for path in PKG.rglob("*.py"):
        for dist in sorted(third_party(imports_of(path))):
            if dist not in covered:
                missing.append(f"{path.relative_to(REPO)}: {dist}")
    assert not missing, (
        f"package imports not covered by pyproject metadata: {missing}"
    )


def test_control_plane_needs_core_deps_only():
    """The service entrypoint path (api/services/config/...) must run on
    the CORE dependency list — the control-plane image ships without the
    jax stack (Dockerfile installs plain `.`)."""
    core = dist_names(load_meta()["project"]["dependencies"])
    offenders = []
    for path in PKG.rglob("*.py"):
        rel = path.relative_to(PKG).parts
        # models stack (models extra) and sandbox-side runtime (executor
        # image installs its own scientific stack via requirements.txt)
        if rel[0] in MODELS_SUBTREES or rel[0] == "runtime":
            continue
        if rel[-1] == "checkpoint.py" and rel[0] == "utils":
            continue  # orbax checkpoint util rides the models extra
        for dist in sorted(third_party(imports_of(path)) - core):
            offenders.append(f"{'/'.join(rel)}: {dist}")
    assert not offenders, (
        f"control-plane modules import beyond core deps: {offenders}"
    )


def test_no_unused_declared_dependency():
    all_imports = set()
    for path in PKG.rglob("*.py"):
        all_imports |= third_party(imports_of(path))
    meta = load_meta()
    declared = dist_names(meta["project"]["dependencies"]) | dist_names(
        meta["project"]["optional-dependencies"]["models"]
    )
    unused = declared - all_imports
    assert not unused, f"declared but never imported: {unused}"


def test_ci_installs_from_metadata():
    ci = (REPO / ".github" / "workflows" / "ci.yaml").read_text()
    assert "pip install -e .[test,models]" in ci
    # no hand-kept list: the only pip install lines go through the metadata
    for line in ci.splitlines():
        if "pip install" in line:
            assert "-e ." in line, f"hand-listed pip install in CI: {line}"


def test_entry_point_resolves():
    meta = load_meta()
    target = meta["project"]["scripts"]["bee-code-interpreter-tpu"]
    module, func = target.split(":")
    import importlib

    assert callable(getattr(importlib.import_module(module), func))
