"""Fleet observability plane acceptance (ISSUE 17, docs/observability.md
"Fleet observability"): the router as a first-class trace participant and
the federated traces/events/SLO/tenants/debug surface at the router edge —
chaos scenario 17's tier-1 twin.

Same harness as tests/test_fleet_router.py: N COMPLETE in-process replicas
(real HTTP edge over fake pods, sharing one snapshot root) behind the real
FleetRouter over real sockets. The distributed-trace assertions here are
end-to-end: one client request, one trace_id, spans recorded by TWO
processes' tracers, stitched back together by the federated query."""

import asyncio
import time

import httpx
import pytest
from aiohttp import web

from bee_code_interpreter_tpu.fleet import FleetRouter, create_router_app
from bee_code_interpreter_tpu.health_check import (
    SLO_BURN_EXIT,
    assess_router_burn,
)
from bee_code_interpreter_tpu.observability import parse_objectives
from tests.fakes import ReplicaStack, free_port

pytestmark = pytest.mark.chaos


async def _start_fleet(tmp_path, n=3, **router_kwargs):
    shared_root = tmp_path / "shared-objects"
    stacks = [
        await ReplicaStack(f"r{i}", tmp_path, shared_root).start()
        for i in range(n)
    ]
    router_kwargs.setdefault("refresh_interval_s", 0.2)
    router_kwargs.setdefault("dead_after_s", 0.5)
    router = FleetRouter(
        [(s.name, s.base_url) for s in stacks], **router_kwargs
    )
    runner = web.AppRunner(create_router_app(router))
    await runner.setup()
    port = free_port()
    await web.TCPSite(runner, "127.0.0.1", port).start()
    await router.refresh_once()
    router.start()
    return stacks, router, runner, f"http://127.0.0.1:{port}"


async def _stop_fleet(stacks, router, runner, client):
    await client.aclose()
    await runner.cleanup()
    await router.stop()
    for stack in stacks:
        await stack.stop()


async def _wait_for_state(router, name, state, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        snap = {
            r["name"]: r["state"] for r in router.snapshot()["replicas"]
        }
        if snap.get(name) == state:
            return
        await asyncio.sleep(0.05)
    raise AssertionError(f"{name} never reached state {state!r}")


# ------------------------------------------------- end-to-end distributed


async def test_one_trace_spans_router_and_replica(tmp_path):
    """THE tentpole acceptance: a client request through the router yields
    ONE distributed trace — router stage spans (placement / breaker /
    attempt / proxy) and the owning replica's pipeline spans under the SAME
    trace_id, queryable as one document from the federated
    ``GET /v1/traces/{id}`` — and an inbound client ``traceparent`` is
    continued, not replaced."""
    stacks, router, runner, url = await _start_fleet(tmp_path, n=2)
    client = httpx.AsyncClient(timeout=30.0)
    try:
        object_id = await stacks[0].storage.write(b"trace-seed")
        files = {"/workspace/seed.txt": object_id}

        # --- no client traceparent: the router roots the trace
        response = await client.post(
            f"{url}/v1/execute",
            json={"source_code": "print(1)", "files": files},
        )
        assert response.status_code == 200
        trace_id = response.headers.get("X-Trace-Id")
        request_id = response.headers.get("X-Request-Id")
        assert trace_id and request_id

        doc = (
            (await client.get(f"{url}/v1/traces/{trace_id}"))
            .raise_for_status()
            .json()
        )
        assert doc["trace_id"] == trace_id
        # Stitched from BOTH ends: the router's own document plus exactly
        # one replica's continuation.
        assert "router" in doc["sources"]
        replica_sources = [s for s in doc["sources"] if s != "router"]
        assert len(replica_sources) == 1
        assert doc["replicas_failed"] == {}

        router_doc = doc["router"]
        assert router_doc["trace_id"] == trace_id
        for stage in ("placement", "breaker", "attempt", "proxy"):
            assert stage in router_doc["stage_ms"], router_doc["stage_ms"]

        replica_doc = doc["replicas"][replica_sources[0]]
        assert replica_doc["trace_id"] == trace_id
        # The replica edge recorded its own pipeline stages (admission,
        # spawn/pop, upload, execute, download — exact set is the replica's
        # contract; here: non-empty and contained in the router's total).
        assert replica_doc["stage_ms"]
        assert router_doc["duration_ms"] >= sum(
            replica_doc["stage_ms"].values()
        ) * 0.5  # halved: two monotonic clocks, zero tolerance is flaky
        # The merged span list stamps every span's origin.
        assert {s["source"] for s in doc["spans"]} == {
            "router",
            replica_sources[0],
        }

        # The replica's root span is a CHILD of the router's trace — the
        # injected traceparent carried the router's active span id down.
        replica_root = replica_doc["spans"][0]
        router_span_ids = {s["span_id"] for s in router_doc["spans"]}
        assert replica_root["parent_id"] in router_span_ids

        # --- routing wide event carries the correlation handles
        events = (
            (await client.get(f"{url}/v1/events", params={"kind": "routing"}))
            .raise_for_status()
            .json()["events"]
        )
        correlated = [e for e in events if e.get("trace_id") == trace_id]
        assert correlated and correlated[0]["request_id"] == request_id
        assert correlated[0]["source"] == "router"

        # --- inbound client traceparent is CONTINUED
        client_trace = "0af7651916cd43dd8448eb211c80319c"
        client_span = "b7ad6b7169203331"
        response = await client.post(
            f"{url}/v1/execute",
            json={"source_code": "print(2)", "files": files},
            headers={"traceparent": f"00-{client_trace}-{client_span}-01"},
        )
        assert response.status_code == 200
        assert response.headers["X-Trace-Id"] == client_trace
        doc = (
            (await client.get(f"{url}/v1/traces/{client_trace}"))
            .raise_for_status()
            .json()
        )
        assert "router" in doc["sources"]
        # The router's root span parents at the CLIENT's span.
        root = doc["router"]["spans"][0]
        assert root["parent_id"] == client_span
    finally:
        await _stop_fleet(stacks, router, runner, client)


async def test_router_correlation_headers_on_error_paths(tmp_path):
    """The header contract holds on every path, not just 200s: a pinned
    404 and a federated trace miss still answer with ``X-Request-Id`` (and
    ``X-Trace-Id`` on the traced data plane)."""
    stacks, router, runner, url = await _start_fleet(tmp_path, n=2)
    client = httpx.AsyncClient(timeout=30.0)
    try:
        response = await client.post(
            f"{url}/v1/sessions/sess-does-not-exist/execute",
            json={"source_code": "print(1)"},
        )
        assert response.status_code == 404
        assert response.headers.get("X-Request-Id")
        assert response.headers.get("X-Trace-Id")

        response = await client.get(f"{url}/v1/traces/{'0' * 32}")
        assert response.status_code == 404
        assert response.headers.get("X-Request-Id")
        # Even the miss carries the partial-result accounting.
        body = response.json()
        assert body["sources"] == []
        assert sorted(body["replicas_reporting"]) == ["r0", "r1"]
    finally:
        await _stop_fleet(stacks, router, runner, client)


async def test_unrouteable_shed_carries_headers():
    """A 503 from an empty/dead fleet — the shed path that never touches a
    replica — still carries both correlation headers."""
    router = FleetRouter([("r0", "http://127.0.0.1:9")], dead_after_s=0.1)
    runner = web.AppRunner(create_router_app(router))
    await runner.setup()
    port = free_port()
    await web.TCPSite(runner, "127.0.0.1", port).start()
    client = httpx.AsyncClient(timeout=10.0)
    try:
        response = await client.post(
            f"http://127.0.0.1:{port}/v1/execute",
            json={"source_code": "print(1)"},
        )
        assert response.status_code == 503
        assert "Retry-After" in response.headers
        assert response.headers.get("X-Request-Id")
        trace_id = response.headers.get("X-Trace-Id")
        assert trace_id
        # The shed is itself traced: the placement span that found nobody.
        trace = router.trace_store.get(trace_id)
        assert trace is not None and "placement" in trace.stage_ms()
    finally:
        await client.aclose()
        await runner.cleanup()
        await router.stop()


# ------------------------------------------------------------- federation


async def test_federated_queries_survive_replica_death(tmp_path):
    """Chaos scenario 17's core clause, tier-1: with 1 of 3 replicas
    killed, every federated query still answers from the survivors with
    exact ``replicas_failed`` accounting — never a 500."""
    stacks, router, runner, url = await _start_fleet(tmp_path, n=3)
    client = httpx.AsyncClient(timeout=30.0)
    try:
        object_id = await stacks[0].storage.write(b"fed-seed")
        files = {"/workspace/seed.txt": object_id}
        response = await client.post(
            f"{url}/v1/execute",
            json={"source_code": "print(1)", "files": files},
        )
        assert response.status_code == 200

        # Kill mid-fleet and query IMMEDIATELY — before the refresh loop
        # marks it dead the fan-out eats the failure live (unreachable /
        # breaker / http error), and the answer is already partial-valid.
        await stacks[2].stop(hard=True)
        body = (
            (await client.get(f"{url}/v1/slo")).raise_for_status().json()
        )
        assert "r2" in body["replicas_failed"]
        assert "r2" not in body["replicas_reporting"]

        # Once the refresh loop has marked it dead, the accounting is the
        # cheap, exact form: reason "dead", no network call spent.
        await _wait_for_state(router, "r2", "dead")
        for path in ("/v1/slo", "/v1/traces", "/v1/events", "/v1/tenants"):
            body = (
                (await client.get(f"{url}{path}")).raise_for_status().json()
            )
            assert body["replicas_failed"] == {"r2": "dead"}, path
            assert sorted(body["replicas_reporting"]) == ["r0", "r1"], path

        # The incident snapshot: router's own bundle + every survivor's.
        bundle = (
            (await client.get(f"{url}/v1/fleet/debug/bundle"))
            .raise_for_status()
            .json()
        )
        assert bundle["replicas_failed"] == {"r2": "dead"}
        assert sorted(bundle["replicas"]) == ["r0", "r1"]
        assert bundle["router"]["snapshot"]["totals"]["routed"] >= 1
        assert bundle["router"]["slo"] is not None
        for name in ("r0", "r1"):
            assert bundle["replicas"][name]["slo"] is not None

        # Fleet SLO rollup: survivors' budget snapshots ride under "fleet".
        slo = (await client.get(f"{url}/v1/slo")).raise_for_status().json()
        assert sorted(slo["fleet"]) == ["r0", "r1"]
        assert slo["fleet_fast_burn"] is False
    finally:
        await _stop_fleet(stacks, router, runner, client)


async def test_federated_events_merge_and_tail(tmp_path):
    """The federated ``GET /v1/events`` merges the router's routing journal
    with the replicas' request journals (each stamped ``source``), and
    ``?follow=1`` tails the router's own decisions live over SSE."""
    stacks, router, runner, url = await _start_fleet(tmp_path, n=2)
    client = httpx.AsyncClient(timeout=30.0)
    try:
        response = await client.post(
            f"{url}/v1/execute", json={"source_code": "print(1)"}
        )
        assert response.status_code == 200
        events = (
            (await client.get(f"{url}/v1/events"))
            .raise_for_status()
            .json()["events"]
        )
        sources = {e["source"] for e in events}
        assert "router" in sources
        assert sources & {"r0", "r1"}  # at least the serving replica's view
        assert any(e["kind"] == "routing" for e in events)
        assert any(e["kind"] == "request" for e in events)

        # Live SSE tail of the router's own journal.
        lines: list[str] = []
        async with client.stream(
            "GET",
            f"{url}/v1/events",
            params={"follow": "1", "kind": "routing", "limit": 5},
        ) as stream:
            assert stream.status_code == 200
            async for line in stream.aiter_lines():
                lines.append(line)
                if line.startswith("data:"):
                    break
        assert any(line == "event: wide_event" for line in lines)
        data = next(line for line in lines if line.startswith("data:"))
        assert '"source": "router"' in data
    finally:
        await _stop_fleet(stacks, router, runner, client)


# ------------------------------------------------- router SLO + burn exit


def test_router_slo_is_user_perceived():
    """The router engine samples what the CLIENT saw: ok/4xx/cancelled are
    good, error/unavailable/unreachable/unrouteable burn budget, and sheds
    (deliberate per-tenant verdicts) are excluded entirely."""
    now = [100.0]
    router = FleetRouter(
        [("r0", "http://127.0.0.1:1")],
        clock=lambda: now[0],
        slo_objectives=parse_objectives(99.5, None),
    )
    for outcome in ("ok", "client_error", "cancelled"):
        router.record_route("/v1/execute", outcome=outcome, replica="r0")
    for outcome in ("error", "unavailable", "unreachable", "unrouteable"):
        router.record_route("/v1/execute", outcome=outcome, replica="r0")
    router.record_route("/v1/execute", outcome="shed", replica="r0")
    window = router.slo.snapshot()["objectives"][0]["windows"]["5m"]
    assert window["total"] == 7  # the shed never landed
    assert window["bad"] == 4


def test_assess_router_burn_exit_ladder():
    assert assess_router_burn(None) == (0, None)
    assert assess_router_burn({}) == (0, None)
    assert assess_router_burn({"fast_burn_alerting": False}) == (0, None)
    code, message = assess_router_burn({"fast_burn_alerting": True})
    assert code == SLO_BURN_EXIT and "router edge" in message
    code, message = assess_router_burn(
        {
            "fleet_fast_burn": True,
            "fleet": {
                "r1": {"fast_burn_alerting": True},
                "r0": {"fast_burn_alerting": False},
            },
        }
    )
    assert code == SLO_BURN_EXIT and "r1" in message and "r0" not in message
