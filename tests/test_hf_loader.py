"""HF Llama checkpoint loading (models/hf_loader.py), pinned by LOGITS
PARITY against transformers' own forward pass — the strongest correctness
statement the transformer family has: every component (RoPE convention,
RMSNorm, SwiGLU, GQA layout, scaling) must agree simultaneously for the
full-model logits to match to 1e-4 in f32."""

import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from bee_code_interpreter_tpu.models.hf_loader import (  # noqa: E402
    config_from_hf,
    load_llama_params,
)
from bee_code_interpreter_tpu.models.serving import (  # noqa: E402
    ContinuousBatcher,
)
from bee_code_interpreter_tpu.models.transformer import (  # noqa: E402
    Transformer,
    forward,
)


def tiny_hf(tie=False, **kw):
    cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        attention_bias=False, attention_dropout=0.0,
        tie_word_embeddings=tie, **kw,
    )
    torch.manual_seed(0)
    return transformers.LlamaForCausalLM(cfg).eval()


TOKENS = np.array([[5, 3, 7, 2, 9, 4, 1, 8, 100, 200, 17, 42],
                   [1, 1, 2, 3, 5, 8, 13, 21, 34, 55, 89, 144]],
                  dtype=np.int32)


def hf_logits(model):
    with torch.no_grad():
        return model(torch.tensor(TOKENS, dtype=torch.long)).logits.numpy()


def test_logits_parity_with_transformers():
    model = tiny_hf()
    params, config = load_llama_params(model, dtype=jnp.float32)
    ours = np.asarray(forward(params, jnp.asarray(TOKENS), config))
    np.testing.assert_allclose(ours, hf_logits(model), atol=1e-4, rtol=1e-4)


def test_tied_embeddings_fall_back():
    model = tiny_hf(tie=True)
    params, config = load_llama_params(model, dtype=jnp.float32)
    ours = np.asarray(forward(params, jnp.asarray(TOKENS), config))
    np.testing.assert_allclose(ours, hf_logits(model), atol=1e-4, rtol=1e-4)


def test_loaded_model_decodes_and_serves():
    """The loaded weights run the decode family: cached greedy decode
    matches HF's own greedy generation, and the paged batcher serves it."""
    model = tiny_hf()
    params, config = load_llama_params(model, dtype=jnp.float32)
    prompt = TOKENS[0, :8]
    n = 6
    with torch.no_grad():
        hf_out = model.generate(
            torch.tensor(prompt[None, :], dtype=torch.long),
            max_new_tokens=n, do_sample=False, num_beams=1,
        )[0, len(prompt):].numpy().tolist()
    ours = Transformer(config).generate_cached(
        params, jnp.asarray(prompt[None, :]), max_new_tokens=n
    )
    assert np.asarray(ours[0, len(prompt):]).tolist() == hf_out

    b = ContinuousBatcher(params, config, max_batch=2, n_pages=16,
                          page_size=4, max_pages_per_seq=8)
    r = b.submit(prompt, n)
    b.run_to_completion()
    assert b.result(r) == hf_out


def test_config_mapping_and_refusals():
    model = tiny_hf()
    config = config_from_hf(model.config)
    assert (config.d_model, config.n_layers, config.n_heads,
            config.kv_heads, config.ff_dim) == (64, 2, 4, 2, 128)
    bad_eps = dataclasses.replace  # noqa: F841 (readability anchor)
    cfg = transformers.LlamaConfig(rms_norm_eps=1e-6)
    with pytest.raises(ValueError, match="rms_norm_eps"):
        config_from_hf(cfg)
    cfg = transformers.LlamaConfig(rms_norm_eps=1e-5, attention_bias=True)
    with pytest.raises(ValueError, match="attention_bias"):
        config_from_hf(cfg)
    cfg = transformers.LlamaConfig(
        rms_norm_eps=1e-5,
        rope_scaling={"rope_type": "yarn", "factor": 4.0},
    )
    with pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf(cfg)


def test_linear_rope_scaling_maps():
    model = tiny_hf(rope_scaling={"rope_type": "linear", "factor": 2.0})
    params, config = load_llama_params(model, dtype=jnp.float32)
    assert config.rope_scaling == 2.0
    ours = np.asarray(forward(params, jnp.asarray(TOKENS), config))
    np.testing.assert_allclose(ours, hf_logits(model), atol=1e-4, rtol=1e-4)


def test_state_dict_path_needs_config():
    model = tiny_hf()
    with pytest.raises(ValueError, match="hf_config"):
        load_llama_params(model.state_dict())
    params, config = load_llama_params(
        model.state_dict(), hf_config=model.config, dtype=jnp.float32
    )
    ours = np.asarray(forward(params, jnp.asarray(TOKENS), config))
    np.testing.assert_allclose(ours, hf_logits(model), atol=1e-4, rtol=1e-4)


def test_hidden_act_and_mlp_bias_refused():
    cfg = transformers.LlamaConfig(rms_norm_eps=1e-5, hidden_act="gelu")
    with pytest.raises(ValueError, match="hidden_act"):
        config_from_hf(cfg)
    cfg = transformers.LlamaConfig(rms_norm_eps=1e-5, mlp_bias=True)
    with pytest.raises(ValueError, match="mlp_bias"):
        config_from_hf(cfg)


def test_non_derived_head_dim_refused():
    """Checkpoints with an explicit head_dim != hidden_size // n_heads
    (increasingly common in HF Llama-family configs) must refuse at
    config construction, not fail later with an opaque reshape error."""
    cfg = transformers.LlamaConfig(
        rms_norm_eps=1e-5, hidden_size=64, num_attention_heads=4,
        head_dim=32,
    )
    with pytest.raises(ValueError, match="head_dim"):
        config_from_hf(cfg)
    # a derived (or absent) head_dim still loads
    cfg = transformers.LlamaConfig(
        rms_norm_eps=1e-5, hidden_size=64, num_attention_heads=4,
        head_dim=16,
    )
    assert config_from_hf(cfg).d_model == 64
