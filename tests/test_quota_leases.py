"""Fleet-wide quota-lease protocol units (ISSUE 16, docs/fleet.md
"Fleet-wide tenancy"): the router-side ledger (grant/split/expiry/merge),
the replica-side cache (lease-capped enforcement, the fail-SAFE 1/N
fallback on partition), the refresh client's router failover, and the
admission controller enforcing LEASED slices instead of full local quotas.
Everything runs on a ManualClock — no sleeps, no wall-clock flake."""

import asyncio

import pytest

from bee_code_interpreter_tpu.fleet.tenancy_plane import (
    QuotaLedger,
    RetryBudget,
    rendezvous_rank,
    subset_size,
)
from bee_code_interpreter_tpu.resilience import (
    AdmissionController,
    AdmissionRejected,
)
from bee_code_interpreter_tpu.tenancy import (
    QuotaLeaseCache,
    QuotaLeaseClient,
    TenantRegistry,
    parse_tenants,
)
from bee_code_interpreter_tpu.utils.metrics import Registry


class ManualClock:
    def __init__(self, start: float = 1000.0) -> None:
        self.now = start

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


def _registry() -> TenantRegistry:
    return TenantRegistry(
        parse_tenants("alpha:weight=4:rps=20:burst=10,beta:rps=5,free:weight=2")
    )


# ------------------------------------------------------------- rendezvous


def test_rendezvous_rank_is_deterministic_and_minimally_disruptive():
    names = [f"r{i}" for i in range(5)]
    ranked = rendezvous_rank("alpha", names)
    assert sorted(ranked) == sorted(names)
    # pure function of the names: every router edge agrees
    assert rendezvous_rank("alpha", list(reversed(names))) == ranked
    # removing one name never reorders the others
    survivor_rank = rendezvous_rank("alpha", [n for n in names if n != ranked[0]])
    assert survivor_rank == ranked[1:]
    # different tenants get (generally) different orders
    assert any(
        rendezvous_rank(t, names) != ranked for t in ("beta", "gamma", "delta")
    )


def test_subset_size_is_weight_proportional_and_clamped():
    assert subset_size(1.0, 5) == 1
    assert subset_size(4.0, 5) == 4
    assert subset_size(2.5, 5) == 3  # ceil
    assert subset_size(100.0, 3) == 3  # never beyond the fleet
    assert subset_size(0.0, 3) == 1  # never zero


# ----------------------------------------------------------------- ledger


def test_ledger_splits_equally_over_active_lessees():
    clock = ManualClock()
    ledger = QuotaLedger(_registry(), ttl_s=3.0, clock=clock)

    # first lessee: the full declared quota
    leases = ledger.grant("r0", ["alpha"])
    assert leases["alpha"]["rps"] == 20.0
    assert leases["alpha"]["burst"] == 10.0
    assert leases["alpha"]["ttl_s"] == 3.0

    # second lessee: the split halves — fleet-wide sum == declared quota
    assert ledger.grant("r1", ["alpha"])["alpha"]["rps"] == 10.0
    # ...and the first lessee converges on ITS next refresh
    assert ledger.grant("r0", ["alpha"])["alpha"]["rps"] == 10.0
    assert ledger.active_count() == 2

    # an expired lessee leaves the split
    clock.advance(2.0)
    ledger.grant("r0", ["alpha"])  # r0 renews at t+2, r1 does not
    clock.advance(1.5)  # r1's lease (t0+3) is now past
    assert ledger.grant("r0", ["alpha"])["alpha"]["rps"] == 20.0
    assert ledger.active_count() == 1


def test_ledger_skips_unknown_and_unlimited_tenants():
    ledger = QuotaLedger(_registry(), clock=ManualClock())
    leases = ledger.grant("r0", ["alpha", "free", "ghost"])
    assert set(leases) == {"alpha"}  # free has no rps; ghost is undeclared
    # no registry at all: every grant is honestly empty
    bare = QuotaLedger(None, clock=ManualClock())
    assert bare.grant("r0", ["alpha"]) == {}


def test_ledger_export_merge_reconciles_peers():
    clock = ManualClock()
    a = QuotaLedger(_registry(), ttl_s=3.0, clock=clock)
    b = QuotaLedger(_registry(), ttl_s=3.0, clock=clock)
    a.grant("r0", ["alpha"])
    a.grant("r1", ["alpha", "beta"])
    b.grant("r2", ["alpha"])

    # B pulls A's ledger: it now knows every lessee A granted to, so its
    # next grant splits over the FULL set instead of re-issuing quota —
    # the reconciliation that bounds double-issue to one TTL of skew.
    merged = b.merge(a.export())
    assert merged == 3  # (alpha,r0) (alpha,r1) (beta,r1)
    assert b.grant("r2", ["alpha"])["alpha"]["rps"] == pytest.approx(20 / 3)

    # merge is max-expiry-wins and idempotent for fresher local state
    assert b.merge(a.export()) == 0
    # garbage peers are ignored, not fatal
    assert b.merge({"alpha": "nope"}) == 0
    assert b.merge("garbage") == 0
    # a peer cannot extend a lease beyond the local TTL cap
    b2 = QuotaLedger(_registry(), ttl_s=3.0, clock=clock)
    b2.merge({"alpha": {"r9": 9999.0}})
    snap = b2.snapshot()
    assert snap["tenants"]["alpha"]["lessees"]["r9"] <= 3.0


def test_ledger_snapshot_is_operator_readable():
    clock = ManualClock()
    ledger = QuotaLedger(_registry(), ttl_s=3.0, clock=clock)
    ledger.grant("r0", ["alpha"])
    ledger.grant("r1", ["alpha"])
    snap = ledger.snapshot()
    assert snap["tenants"]["alpha"]["rps"] == 20.0
    assert snap["tenants"]["alpha"]["slice_rps"] == 10.0
    assert set(snap["tenants"]["alpha"]["lessees"]) == {"r0", "r1"}
    assert snap["granted_total"] == 2


# ------------------------------------------------------------------ cache


def test_cache_enforces_leased_slice_and_expires_to_fallback():
    clock = ManualClock()
    cache = QuotaLeaseCache(clock=clock)
    alpha = _registry().get("alpha")

    # no lease ever seen, fleet size unknown (hint 1): full local quota —
    # the standalone replica behaves exactly as before the fleet tier
    assert cache.effective(alpha) == (20.0, 10.0)

    cache.update("alpha", rps=10.0, burst=5.0, ttl_s=3.0, router="A")
    cache.observe_fleet_size(4)
    assert cache.effective(alpha) == (10.0, 5.0)

    # lease expiry degrades to the 1/N split over the LAST KNOWN fleet
    # size — tighter than the lease, never open
    clock.advance(3.1)
    assert cache.lease("alpha") is None
    assert cache.effective(alpha) == (5.0, 2.5)
    assert cache.fallbacks == 2  # the pre-lease answer was a fallback too


def test_quota_fails_safe_never_unlimited_on_partition():
    """The dedicated partition fail-safe (ISSUE 16 acceptance): with every
    router unreachable, enforcement degrades to a LOCAL 1/N split — never
    unlimited, and a buggy/malicious router grant can tighten the quota
    but never widen it past the tenant's own declared numbers."""
    clock = ManualClock()
    cache = QuotaLeaseCache(fleet_size_hint=3, clock=clock)
    alpha = _registry().get("alpha")

    # partitioned from birth: 1/N of the DECLARED quota, not infinity
    rps, burst = cache.effective(alpha)
    assert rps == pytest.approx(20.0 / 3)
    assert 1.0 <= burst <= alpha.burst_depth

    # an over-generous (buggy router) lease is capped at the declared quota
    cache.update("alpha", rps=1e9, burst=1e9, ttl_s=3.0)
    assert cache.effective(alpha) == (20.0, 10.0)

    # partition after convergence: fallback uses the learned fleet size
    cache.observe_fleet_size(5)
    clock.advance(10.0)
    rps, burst = cache.effective(alpha)
    assert rps == pytest.approx(4.0)
    assert rps <= alpha.rps
    # burst never collapses below one admission
    tiny = QuotaLeaseCache(fleet_size_hint=100, clock=clock)
    assert tiny.effective(alpha)[1] >= 1.0


# ------------------------------------------------- admission x lease cache


def test_admission_enforces_leased_slice_with_manual_clock():
    clock = ManualClock()
    registry = _registry()
    cache = QuotaLeaseCache(clock=clock)
    admission = AdmissionController(
        max_in_flight=100,
        max_queue=100,
        tenancy=registry,
        quota_leases=cache,
        clock=clock,
    )

    alpha = registry.get("alpha")

    async def spend_until_shed(limit=1000) -> int:
        admitted = 0
        for _ in range(limit):
            try:
                async with admission.admit(tenant=alpha):
                    admitted += 1
            except AdmissionRejected as e:
                assert e.reason == "tenant_quota"
                return admitted
        raise AssertionError("never shed")

    async def run() -> None:
        # leased slice: 2 rps / burst 2 of the declared 20/10
        cache.update("alpha", rps=2.0, burst=2.0, ttl_s=5.0)
        assert await spend_until_shed() == 2  # the leased burst, not 10
        # refill happens at the LEASED rate: +1 token after 0.5 s
        clock.advance(0.5)
        assert await spend_until_shed() == 1
        # the lease expires mid-traffic -> 1/N fallback over the learned
        # fleet size, still never the full local quota
        cache.observe_fleet_size(2)
        clock.advance(10.0)  # lease gone; 10 s * (20/2 rps) but burst caps
        assert cache.lease("alpha") is None
        admitted = await spend_until_shed()
        assert 1 <= admitted <= registry.get("alpha").burst_depth / 2
        # the tenant snapshot exposes the effective (degraded) quota
        quota = admission.tenant_snapshot()["alpha"]["quota"]
        assert quota["leased"] is False
        assert quota["effective_rps"] == pytest.approx(10.0)

    asyncio.run(run())


def test_quota_tenants_lists_only_rate_quota_lanes():
    clock = ManualClock()
    registry = _registry()
    admission = AdmissionController(
        max_in_flight=8, tenancy=registry, clock=clock
    )

    async def run() -> None:
        assert admission.quota_tenants() == []  # no lanes yet
        for tid in ("alpha", "free", "nobody"):
            async with admission.admit(tenant=registry.resolve(tid)):
                pass
        # alpha has rps; free does not; "nobody" collapses into default
        assert admission.quota_tenants() == ["alpha"]

    asyncio.run(run())


# ------------------------------------------------------------ lease client


class _FakeLeaseResponse:
    def __init__(self, status: int, doc: dict) -> None:
        self.status = status
        self._doc = doc

    async def json(self) -> dict:
        return self._doc

    async def __aenter__(self) -> "_FakeLeaseResponse":
        return self

    async def __aexit__(self, *exc) -> None:
        return None


class _FakeHttpClient:
    """aiohttp-shaped POST stub: per-URL scripted answers (an Exception
    means unreachable)."""

    def __init__(self, answers: dict) -> None:
        self.answers = answers
        self.calls: list[str] = []
        self.closed = False

    def post(self, url: str, **kwargs):
        self.calls.append(url)
        answer = self.answers[url.removesuffix("/v1/fleet/quota/lease")]
        if isinstance(answer, Exception):
            raise answer
        return answer

    async def close(self) -> None:
        self.closed = True


class _FakeAdmission:
    def __init__(self, tenants: list[str]) -> None:
        self._tenants = tenants

    def quota_tenants(self) -> list[str]:
        return self._tenants


def test_lease_client_fails_over_and_applies_grants():
    clock = ManualClock()
    cache = QuotaLeaseCache(clock=clock)
    metrics = Registry()
    grant = {
        "router": "B",
        "fleet_size": 3,
        "leases": {"alpha": {"rps": 10.0, "burst": 5.0, "ttl_s": 3.0}},
    }
    http = _FakeHttpClient(
        {
            "http://a": OSError("connection refused"),
            "http://b": _FakeLeaseResponse(200, grant),
        }
    )
    client = QuotaLeaseClient(
        cache,
        _FakeAdmission(["alpha"]),
        replica="r0",
        router_urls=["http://a", "http://b"],
        metrics=metrics,
        http_client=http,
    )

    async def run() -> None:
        assert await client.refresh_once() is True
        lease = cache.lease("alpha")
        assert lease is not None and lease.rps == 10.0 and lease.router == "B"
        assert cache.fleet_size == 3
        # failover is sticky: the next refresh goes straight to B
        assert await client.refresh_once() is True
        assert http.calls[-1].startswith("http://b")
        assert http.calls.count("http://a/v1/fleet/quota/lease") == 1
        refresh = metrics.metrics["bci_quota_lease_refresh_total"]._values
        assert refresh[(("outcome", "ok"),)] == 2
        await client.stop()
        assert http.closed

    asyncio.run(run())


def test_lease_client_total_unreachability_is_not_an_error():
    clock = ManualClock()
    cache = QuotaLeaseCache(fleet_size_hint=2, clock=clock)
    metrics = Registry()
    http = _FakeHttpClient(
        {"http://a": OSError("down"), "http://b": OSError("down")}
    )
    client = QuotaLeaseClient(
        cache,
        _FakeAdmission(["alpha"]),
        replica="r0",
        router_urls=["http://a", "http://b"],
        metrics=metrics,
        http_client=http,
    )
    alpha = _registry().get("alpha")

    async def run() -> None:
        assert await client.refresh_once() is False
        refresh = metrics.metrics["bci_quota_lease_refresh_total"]._values
        assert refresh[(("outcome", "unreachable"),)] == 1
        # the data plane never sees the failure: enforcement degrades to
        # the 1/N split, tighter than any lease — never open
        assert cache.effective(alpha) == (10.0, 5.0)
        await client.stop()

    asyncio.run(run())


def test_lease_client_ignores_malformed_grants():
    clock = ManualClock()
    cache = QuotaLeaseCache(clock=clock)
    doc = {
        "router": "A",
        "fleet_size": "not-a-number",
        "leases": {
            "alpha": {"rps": 10.0, "burst": 5.0, "ttl_s": 3.0},
            "beta": {"rps": "garbage"},
            "gamma": None,
        },
    }
    http = _FakeHttpClient({"http://a": _FakeLeaseResponse(200, doc)})
    client = QuotaLeaseClient(
        cache,
        _FakeAdmission(["alpha", "beta", "gamma"]),
        replica="r0",
        router_urls=["http://a"],
        http_client=http,
    )

    async def run() -> None:
        assert await client.refresh_once() is True
        assert cache.lease("alpha") is not None  # good grant applied
        assert cache.lease("beta") is None  # malformed ones skipped
        assert cache.fleet_size == 1  # bogus fleet size ignored
        await client.stop()

    asyncio.run(run())


# ------------------------------------------------------ router retry budget


def test_router_retry_budget_caps_and_refills():
    clock = ManualClock()
    budget = RetryBudget(20.0, clock=clock)  # 10% of 20 rps = 2/s, burst 10
    assert sum(budget.spend() for _ in range(15)) == 10
    assert budget.denied == 5
    clock.advance(1.0)
    assert budget.spend() and budget.spend()
    assert not budget.spend()
