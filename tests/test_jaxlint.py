"""Tier-1 accelerator-stack lint (docs/analysis.md "Accelerator lint"):
the trees the asyncio lints exclude — models/, parallel/, ops/,
runtime/shim/ — must carry ZERO unexplained jaxlint violations, with
every suppression still earning its justification (a stale suppression
is itself a failure), exactly the asynclint/concurrencylint contract.

The second half unit-tests each rule on synthetic snippets so a
regression names the broken rule."""

from bee_code_interpreter_tpu.analysis.asynclint import DEFAULT_EXCLUDES
from bee_code_interpreter_tpu.analysis.jaxlint import (
    ACCELERATOR_SCOPE,
    SUPPRESSIONS,
    lint_jax_paths,
    lint_jax_source,
)


def _rules(source: str) -> list[str]:
    return [v.rule for v in lint_jax_source(source)]


# ------------------------------------------------------------- the repo


def test_accelerator_stack_has_zero_unexplained_violations():
    report = lint_jax_paths()
    assert report.files_scanned >= 25  # the derived scope actually resolved
    assert not report.violations, "\n" + report.summary()


def test_no_stale_suppressions():
    report = lint_jax_paths()
    assert not report.stale_suppressions, (
        "suppressions no longer matching any violation — delete them:\n"
        + report.summary()
    )
    used = {s for _, s in report.suppressed}
    assert used == set(SUPPRESSIONS)


def test_every_suppression_is_justified():
    for s in SUPPRESSIONS:
        assert len(s.reason.split()) >= 8, (
            f"{s.path} [{s.rule}]: a suppression needs a real justification"
        )


def test_scope_is_the_asynclint_exclude_partition():
    """jaxlint's scope IS asynclint's exclude tuple — the same object, so
    the two lint families partition the tree and cannot drift apart."""
    assert ACCELERATOR_SCOPE is DEFAULT_EXCLUDES
    assert set(ACCELERATOR_SCOPE) == {
        "models", "parallel", "ops", "runtime/shim",
    }


def test_fresh_module_under_models_is_in_scope_by_default(tmp_path):
    """Regression for the omission bug class (mirrors asynclint's
    tmp-tree test): a new module dropped under models/ or parallel/ is
    jaxlint-scoped without anyone editing a scope list, and control-plane
    trees stay out of THIS lint's scope."""
    pkg_root = tmp_path / "fakepkg"
    models = pkg_root / "models"
    models.mkdir(parents=True)
    (models / "__init__.py").write_text("")
    (models / "shiny_new_model.py").write_text(
        "import jax\n"
        "def f(fns):\n"
        "    out = []\n"
        "    for fn in fns:\n"
        "        out.append(jax.jit(fn))\n"
        "    return out\n"
    )
    # a control-plane package with the same shape stays out of THIS scope
    api = pkg_root / "api"
    api.mkdir()
    (api / "__init__.py").write_text("")
    (api / "svc.py").write_text(
        "import jax\nfor i in range(3):\n    g = jax.jit(print)\n"
    )
    report = lint_jax_paths(pkg_root, suppressions=())
    assert [v.rule for v in report.violations] == ["jit-in-loop"]
    assert report.violations[0].path.endswith("models/shiny_new_model.py")


def test_jax_free_files_short_circuit():
    """The trigger pre-scan: a file with no jax spelling anywhere costs
    one token walk and produces nothing (the same discipline as the
    dynamic-import trigger scan)."""
    assert _rules(
        """
        import numpy as np
        def f(items):
            out = []
            for it in items:
                out.append(np.asarray(it))
            return out
        """
    ) == []


# ------------------------------------------------- host-sync-in-hot-loop


def test_host_sync_on_jitted_result_in_loop_flagged():
    assert _rules(
        """
        import jax
        import numpy as np
        def _step(x):
            return x + 1
        m = jax.jit(_step)
        def decode(params):
            out = []
            for _ in range(10):
                logits = m(params)
                out.append(np.asarray(logits))
            return out
        """
    ) == ["host-sync-in-hot-loop"]


def test_item_on_device_value_in_loop_flagged():
    assert _rules(
        """
        import jax.numpy as jnp
        def f(xs):
            total = 0.0
            for x in xs:
                y = jnp.sin(x)
                total += y.item()
            return total
        """
    ) == ["host-sync-in-hot-loop"]


def test_block_until_ready_in_loop_flagged():
    assert _rules(
        """
        import jax.numpy as jnp
        def f():
            for _ in range(3):
                jnp.ones(3).block_until_ready()
        """
    ) == ["host-sync-in-hot-loop"]


def test_step_path_sync_flagged_without_lexical_loop():
    # `step()` runs per token in every serving loop: a transfer anywhere
    # it reaches is per-token work even with no `for` in sight
    assert _rules(
        """
        import numpy as np
        import jax.numpy as jnp
        class Batcher:
            def step(self):
                return self._tick()
            def _tick(self):
                logits = jnp.ones((2, 2))
                return np.asarray(logits)
        """
    ) == ["host-sync-in-hot-loop"]


def test_sync_via_jit_attribute_alias_tracked():
    # self._verify = self._window aliasing: one compiled program, two
    # roles — the alias must still mark results as device values
    assert _rules(
        """
        import jax
        import numpy as np
        class B:
            def __init__(self, f):
                self._window = jax.jit(f)
                self._verify = self._window
            def step(self):
                t = self._verify(1)
                return np.asarray(t)
        """
    ) == ["host-sync-in-hot-loop"]


def test_cold_path_sync_is_clean():
    # a one-shot transfer outside any loop / step path is the normal way
    # results leave the device — not a finding
    assert _rules(
        """
        import numpy as np
        import jax.numpy as jnp
        def admit():
            x = jnp.ones(3)
            return np.asarray(x)
        """
    ) == []


def test_host_numpy_in_loop_is_clean():
    # np.asarray over plain host data in a loop is ordinary numpy code;
    # only alias-tracked DEVICE values count
    assert _rules(
        """
        import numpy as np
        import jax.numpy as jnp
        def f(items):
            dev = jnp.ones(3)  # jax present, but not what crosses
            out = []
            for it in items:
                out.append(np.asarray(it))
            return out
        """
    ) == []


# ------------------------------------------------ jit-in-loop / retrace


def test_jit_in_loop_flagged():
    assert "jit-in-loop" in _rules(
        """
        import jax
        def f(fns):
            out = []
            for fn in fns:
                out.append(jax.jit(fn))
            return out
        """
    )


def test_immediate_jit_invocation_flagged():
    assert _rules(
        """
        import jax
        def f(g, x):
            return jax.jit(g)(x)
        """
    ) == ["retrace-hazard"]


def test_jit_built_and_called_per_call_flagged():
    assert _rules(
        """
        import jax
        def f(step, x):
            g = jax.jit(step)
            return g(x)
        """
    ) == ["retrace-hazard"]


def test_jit_factory_return_is_clean():
    # the mnist/transformer make_train_step shape: build once, hand the
    # compiled callable to the caller
    assert _rules(
        """
        import jax
        def make_step(step):
            return jax.jit(step, donate_argnums=(0, 1))
        """
    ) == []


def test_jit_bound_to_self_in_init_is_clean():
    # the serving-engine shape: compiled once at construction
    assert _rules(
        """
        import jax
        class B:
            def __init__(self, f):
                self._decode = jax.jit(f, donate_argnums=(1,))
        """
    ) == []


def test_nonconstant_static_argnums_flagged():
    assert _rules(
        """
        import jax
        def f(g, idxs):
            return jax.jit(g, static_argnums=idxs)
        """
    ) == ["retrace-hazard"]


def test_constant_static_argnames_clean():
    assert _rules(
        """
        import jax
        def make(g):
            return jax.jit(g, static_argnames=("total_len", "chunk"))
        """
    ) == []


# ------------------------------------------------------ missing-donation


def test_undonated_state_threading_jit_flagged():
    assert _rules(
        """
        import jax
        def train_step(params, opt_state, batch):
            return params, opt_state, 1.0
        def make():
            return jax.jit(train_step)
        """
    ) == ["missing-donation"]


def test_donated_state_threading_jit_clean():
    assert _rules(
        """
        import jax
        def train_step(params, opt_state, batch):
            return params, opt_state, 1.0
        def make():
            return jax.jit(train_step, donate_argnums=(0, 1))
        """
    ) == []


def test_jit_without_state_out_is_clean():
    # forward-only functions return fresh values, nothing to donate
    assert _rules(
        """
        import jax
        def forward(params, tokens):
            return tokens
        def make():
            return jax.jit(lambda p, t: p)  # unresolvable target: no claim
        def make2():
            return jax.jit(forward)
        """
    ) == ["missing-donation"]  # forward returns its `tokens` param


def test_partial_bound_state_not_donation_candidate():
    # a functools.partial-bound kwarg is a Python constant at trace time,
    # not a donatable buffer argument
    assert _rules(
        """
        import functools
        import jax
        def apply(cfg, x):
            return cfg
        def make(cfg):
            return jax.jit(functools.partial(apply, cfg=cfg))
        """
    ) == []


# ------------------------------------------------- traced-python-branch


def test_branch_on_traced_param_flagged():
    assert _rules(
        """
        import jax
        def f(x):
            if x > 0:
                return x * 2
            return -x
        g = jax.jit(f)
        """
    ) == ["traced-python-branch"]


def test_while_on_traced_param_flagged():
    assert _rules(
        """
        import jax
        def f(x):
            while x > 0:
                x = x - 1
            return x + 0
        g = jax.jit(f)
        """
    ) == ["traced-python-branch"]


def test_shape_dtype_none_and_len_tests_are_static():
    assert _rules(
        """
        import jax
        def f(x, mask):
            if x.shape[0] > 1:
                x = x + 1
            if mask is None:
                return x * 1
            if len(x) > 2:
                return x + 1
            return x * 1
        g = jax.jit(f)
        """
    ) == []


def test_default_valued_flag_param_is_static():
    # a flag the jit caller leaves at its default is a concrete Python
    # value during tracing — the return_kv / lora_bank idiom
    assert _rules(
        """
        import jax
        def f(x, return_aux=False):
            if return_aux:
                return x * 1, x.sum()
            return x * 1
        g = jax.jit(f)
        """
    ) == []


def test_static_argnums_sanctions_the_branch():
    assert _rules(
        """
        import jax
        def f(n, x):
            if n > 4:
                return x * 2
            return x * 1
        g = jax.jit(f, static_argnums=(0,))
        """
    ) == []


def test_unjitted_function_branches_freely():
    assert _rules(
        """
        import jax.numpy as jnp
        def host_helper(x):
            if x > 0:
                return jnp.ones(3)
            return jnp.zeros(3)
        """
    ) == []


# -------------------------------------------- collective-axis-mismatch


def test_unbound_literal_axis_flagged():
    assert _rules(
        """
        from jax import lax
        def f(x):
            return lax.psum(x, "tp")
        """
    ) == ["collective-axis-mismatch"]


def test_axis_bound_by_partition_spec_clean():
    assert _rules(
        """
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P
        def f(x):
            return lax.psum(x, "tp")
        def wrap(mesh, x):
            fn = jax.shard_map(
                f, mesh=mesh, in_specs=(P("tp"),), out_specs=P()
            )
            return fn(x)
        """
    ) == []


def test_axis_from_parameter_chain_clean():
    # the ring/ulysses idiom: the axis arrives as a parameter (with the
    # mesh-side binding completed by the *_sharded wrapper's specs)
    assert _rules(
        """
        from jax import lax
        def ring(x, axis_name="sp"):
            n = lax.axis_size(axis_name)
            return lax.ppermute(x, axis_name, [(i, (i + 1) % n) for i in range(2)])
        """
    ) == []


def test_axis_from_enclosing_closure_param_clean():
    # shard_map bodies close over the OUTER function's axis param
    # (parallel/pipeline.py's per_rank/tick nesting)
    assert _rules(
        """
        from jax import lax
        def pipelined(x, axis="pp"):
            def per_rank(h):
                idx = lax.axis_index(axis)
                return lax.psum(h, axis) + idx
            return per_rank
        """
    ) == []


def test_unauditable_axis_name_flagged():
    assert _rules(
        """
        from jax import lax
        AXIS = object()
        def f(x):
            return lax.psum(x, AXIS)
        """
    ) == ["collective-axis-mismatch"]


def test_kwarg_axis_name_checked_too():
    assert _rules(
        """
        from jax import lax
        def f(x):
            return lax.all_to_all(
                x, axis_name="sp", split_axis=1, concat_axis=2
            )
        """
    ) == ["collective-axis-mismatch"]


# ------------------------------------------- code-review regressions


def test_closure_factory_is_clean():
    # the canonical jit factory: build once, return a closure that calls
    # it — the nested call must not read as "rebuilt per invocation"
    # (ast.walk does not prune skipped FunctionDef bodies)
    assert _rules(
        """
        import jax
        def make_step(f):
            g = jax.jit(f, donate_argnums=(0,))
            def step(x):
                return g(x)
            return step
        """
    ) == []


def test_nested_def_device_bindings_do_not_leak_out():
    # a nested def's `logits = jnp.zeros(...)` is ITS scope's name; the
    # enclosing function's same-named host list must not inherit it
    assert _rules(
        """
        import numpy as np
        import jax.numpy as jnp
        def outer(rows):
            def inner():
                logits = jnp.zeros(3)
                return logits
            logits = [1.0, 2.0]
            out = []
            for r in rows:
                out.append(np.asarray(logits))
            return out, inner
        """
    ) == []


def test_lambda_body_sync_in_loop_flagged():
    # a sort key runs per comparison inside the loop: a device->host
    # float() there is exactly the per-iteration sync the rule targets
    assert _rules(
        """
        import jax.numpy as jnp
        def f(rows):
            logits = jnp.zeros((3, 3))
            for r in rows:
                rows = sorted(rows, key=lambda i: float(logits[i].sum()))
            return rows
        """
    ) == ["host-sync-in-hot-loop"]


def test_aliased_cross_file_jit_target_still_checked(tmp_path):
    # `from m import forward as fwd; jax.jit(fwd)` must route to m's
    # `forward` for the traced-branch pass, same as the unaliased import
    pkg_root = tmp_path / "fakepkg"
    models = pkg_root / "models"
    models.mkdir(parents=True)
    (models / "__init__.py").write_text("")
    (models / "deff.py").write_text(
        "import jax.numpy as jnp\n"
        "def forward(x):\n"
        "    if x > 0:\n"
        "        return x * 2\n"
        "    return -x\n"
    )
    (models / "caller.py").write_text(
        "import jax\n"
        "from fakepkg.models.deff import forward as fwd\n"
        "g = jax.jit(fwd)\n"
    )
    report = lint_jax_paths(pkg_root, suppressions=())
    assert [v.rule for v in report.violations] == ["traced-python-branch"]
    assert report.violations[0].path.endswith("models/deff.py")
