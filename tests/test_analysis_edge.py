"""Edge behavior of the pre-flight code gate (docs/analysis.md), on both
transports: syntax fail-fast without a sandbox checkout, policy deny as a
client fault (422 / INVALID_ARGUMENT), warn annotations, and the dep
prediction riding the execution."""

import grpc.aio
import pytest
from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_tpu.analysis import PolicyEngine, WorkloadAnalyzer
from bee_code_interpreter_tpu.api.grpc_server import GrpcServer, service_stubs
from bee_code_interpreter_tpu.api.http_server import create_http_server
from bee_code_interpreter_tpu.observability import FleetJournal
from bee_code_interpreter_tpu.proto import code_interpreter_pb2 as pb
from bee_code_interpreter_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_tpu.utils.metrics import Registry


class CountingExecutor:
    """Wraps the real local executor; counts how many executions actually
    reached a sandbox — the gate's whole point is keeping this at zero for
    doomed submissions."""

    def __init__(self, inner):
        self.inner = inner
        self.executions = 0

    async def execute(self, *args, **kwargs):
        self.executions += 1
        return await self.inner.execute(*args, **kwargs)


@pytest.fixture
def counting_executor(local_executor):
    return CountingExecutor(local_executor)


def make_app(executor, analyzer, metrics=None, fleet=None):
    return create_http_server(
        code_executor=executor,
        custom_tool_executor=CustomToolExecutor(code_executor=executor),
        metrics=metrics,
        analyzer=analyzer,
        fleet=fleet,
    )


async def with_client(app, fn):
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        return await fn(client)
    finally:
        await client.close()


async def test_http_syntax_failfast_zero_checkouts(counting_executor):
    metrics = Registry()
    fleet = FleetJournal()
    analyzer = WorkloadAnalyzer(metrics=metrics)
    app = make_app(counting_executor, analyzer, metrics=metrics, fleet=fleet)

    async def go(client):
        resp = await client.post(
            "/v1/execute", json={"source_code": "def broken(:\n"}
        )
        # a normal ExecuteResponse, exactly as if the sandbox had died at
        # parse: HTTP 200, exit_code=1, the in-sandbox stderr shape
        assert resp.status == 200
        body = await resp.json()
        assert body["exit_code"] == 1
        assert body["stdout"] == ""
        lines = body["stderr"].strip().splitlines()
        assert lines[0].lstrip().startswith('File "')
        assert lines[-1].startswith("SyntaxError:")
        assert body["files"] == {}
        # the analysis stage is the ONLY stage the request paid for
        assert "analysis" in body["timings_ms"]
        assert "execute" not in body["timings_ms"]
        assert body["trace_id"]

    await with_client(app, go)
    # zero sandbox checkouts: nothing reached an executor, nothing in the
    # fleet journal
    assert counting_executor.executions == 0
    assert len(fleet) == 0
    assert (
        'bci_analysis_rejections_total{rule="syntax"} 1' in metrics.expose()
    )


async def test_http_policy_deny_is_422(counting_executor):
    metrics = Registry()
    analyzer = WorkloadAnalyzer(
        PolicyEngine(deny_imports=("socket",), deny_calls=("subprocess",)),
        metrics=metrics,
    )
    app = make_app(counting_executor, analyzer, metrics=metrics)

    async def go(client):
        resp = await client.post(
            "/v1/execute", json={"source_code": "import socket\n"}
        )
        assert resp.status == 422
        body = await resp.json()
        assert body["violations"][0]["rule"] == "import:socket"
        resp = await client.post(
            "/v1/execute",
            json={"source_code": "import subprocess\nsubprocess.run(['id'])\n"},
        )
        assert resp.status == 422

    await with_client(app, go)
    assert counting_executor.executions == 0
    text = metrics.expose()
    assert 'bci_analysis_rejections_total{rule="import:socket"} 1' in text
    assert 'bci_analysis_rejections_total{rule="shape:subprocess"} 1' in text


async def test_http_warn_annotates_and_executes(counting_executor):
    analyzer = WorkloadAnalyzer(PolicyEngine(warn_imports=("json",)))
    app = make_app(counting_executor, analyzer)

    async def go(client):
        resp = await client.post(
            "/v1/execute",
            json={"source_code": "import json\nprint(json.dumps(1))"},
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["stdout"] == "1\n"
        assert body["exit_code"] == 0
        warned = body["analysis"]["warnings"]
        assert warned[0]["rule"] == "import:json"
        assert warned[0]["severity"] == "warn"

    await with_client(app, go)
    assert counting_executor.executions == 1  # warn does not block


async def test_http_clean_source_carries_cost_class(counting_executor):
    """No warnings, no deps → the analysis block carries exactly the
    cost hint (docs/analysis.md "Cost classes") and nothing else."""
    app = make_app(counting_executor, WorkloadAnalyzer())

    async def go(client):
        body = await (
            await client.post(
                "/v1/execute", json={"source_code": "print(21 * 2)"}
            )
        ).json()
        assert body["stdout"] == "42\n"
        assert body["analysis"] == {"cost_class": "cheap"}

    await with_client(app, go)


async def test_http_dep_prediction_annotated(counting_executor):
    app = make_app(counting_executor, WorkloadAnalyzer())

    async def go(client):
        body = await (
            await client.post(
                "/v1/execute",
                json={
                    "source_code": (
                        "try:\n    import pandas\nexcept ImportError:\n"
                        "    print('no pandas')\n"
                    )
                },
            )
        ).json()
        assert body["analysis"]["predicted_deps"] == ["pandas"]

    await with_client(app, go)


async def test_http_custom_tool_policy(counting_executor):
    analyzer = WorkloadAnalyzer(PolicyEngine(deny_imports=("socket",)))
    app = make_app(counting_executor, analyzer)

    async def go(client):
        # deny applies to tool source too
        resp = await client.post(
            "/v1/execute-custom-tool",
            json={
                "tool_source_code": (
                    "import socket\ndef t(a: int) -> int:\n    return a"
                ),
                "tool_input_json": '{"a": 1}',
            },
        )
        assert resp.status == 422
        # but a syntax error keeps the PARSER's 400 contract
        resp = await client.post(
            "/v1/execute-custom-tool",
            json={"tool_source_code": "def t(:\n", "tool_input_json": "{}"},
        )
        assert resp.status == 400
        assert "error_messages" in await resp.json()

    await with_client(app, go)
    assert counting_executor.executions == 0


async def test_http_custom_tool_policy_applies_to_indented_source(
    counting_executor,
):
    """The parser dedents uniformly indented tool sources before parsing —
    the policy must see the SAME preprocessing, or indentation becomes a
    policy bypass (raw parse fails → deny check skipped → tool runs)."""
    analyzer = WorkloadAnalyzer(PolicyEngine(deny_imports=("socket",)))
    app = make_app(counting_executor, analyzer)
    indented = (
        "    import socket\n"
        "    def t(a: int) -> int:\n"
        "        return a\n"
    )

    async def go(client):
        resp = await client.post(
            "/v1/execute-custom-tool",
            json={"tool_source_code": indented, "tool_input_json": '{"a": 1}'},
        )
        assert resp.status == 422
        body = await resp.json()
        assert body["violations"][0]["rule"] == "import:socket"

    await with_client(app, go)
    assert counting_executor.executions == 0


class DepSpyExecutor:
    """Records the ambient dep prediction at the moment the executor runs —
    what the data-plane driver would ship to the sandbox."""

    def __init__(self, inner):
        self.inner = inner
        self.seen: list = []

    async def execute(self, *args, **kwargs):
        from bee_code_interpreter_tpu.analysis.context import predicted_deps

        self.seen.append(predicted_deps())
        return await self.inner.execute(*args, **kwargs)


async def test_http_prediction_stash_per_route(local_executor):
    """/v1/execute ships its prediction; custom tools and profiled runs must
    ship NONE — the sandbox executes generated/unanalyzed source there and
    its own scan must run (and a prediction stashed by an earlier request
    in the same connection task must never leak forward)."""
    spy = DepSpyExecutor(local_executor)
    app = make_app(spy, WorkloadAnalyzer())

    async def go(client):
        payload = (
            "try:\n    import pandas\nexcept ImportError:\n    pass\n"
        )
        await client.post("/v1/execute", json={"source_code": payload})
        await client.post(
            "/v1/execute-custom-tool",
            json={
                "tool_source_code": "def t(a: int) -> int:\n    return a",
                "tool_input_json": '{"a": 1}',
            },
        )
        resp = await client.post(
            "/v1/profile",
            json={"target": "sandbox", "source_code": "print(1)"},
        )
        assert resp.status == 200

    await with_client(app, go)
    assert spy.seen[0] == ["pandas"]  # /v1/execute: prediction shipped
    assert spy.seen[1] is None  # custom tool: pod scans the wrapper itself
    assert spy.seen[2] is None  # profile: unanalyzed, pod scans itself


# ------------------------------------------------------------------ gRPC


async def run_grpc(server: GrpcServer, fn):
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            await fn(service_stubs(channel))
    finally:
        await server.stop(None)


async def test_grpc_syntax_failfast(counting_executor):
    server = GrpcServer(
        code_executor=counting_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=counting_executor),
        analyzer=WorkloadAnalyzer(),
    )

    async def go(stubs):
        resp = await stubs["Execute"](
            pb.ExecuteRequest(source_code="def broken(:\n")
        )
        assert resp.exit_code == 1
        assert resp.stdout == ""
        assert resp.stderr.strip().splitlines()[-1].startswith("SyntaxError:")

    await run_grpc(server, go)
    assert counting_executor.executions == 0


async def test_grpc_policy_deny_invalid_argument(counting_executor):
    server = GrpcServer(
        code_executor=counting_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=counting_executor),
        analyzer=WorkloadAnalyzer(PolicyEngine(deny_imports=("socket",))),
    )

    async def go(stubs):
        try:
            await stubs["Execute"](pb.ExecuteRequest(source_code="import socket"))
        except grpc.aio.AioRpcError as e:
            assert e.code() == grpc.StatusCode.INVALID_ARGUMENT
            assert "import:socket" in e.details()
        else:
            raise AssertionError("expected INVALID_ARGUMENT")

    await run_grpc(server, go)
    assert counting_executor.executions == 0


async def test_grpc_custom_tool_policy_applies_to_indented_source(
    counting_executor,
):
    server = GrpcServer(
        code_executor=counting_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=counting_executor),
        analyzer=WorkloadAnalyzer(PolicyEngine(deny_imports=("socket",))),
    )
    indented = (
        "    import socket\n"
        "    def t(a: int) -> int:\n"
        "        return a\n"
    )

    async def go(stubs):
        try:
            await stubs["ExecuteCustomTool"](
                pb.ExecuteCustomToolRequest(
                    tool_source_code=indented, tool_input_json='{"a": 1}'
                )
            )
        except grpc.aio.AioRpcError as e:
            assert e.code() == grpc.StatusCode.INVALID_ARGUMENT
            assert "import:socket" in e.details()
        else:
            raise AssertionError("expected INVALID_ARGUMENT")

    await run_grpc(server, go)
    assert counting_executor.executions == 0


# ------------------------------------------- dataflow evasion closings
# (docs/analysis.md "Dataflow layer"): the four regression shapes, each on
# BOTH transports, each with zero sandbox checkouts under deny.

EVASIONS = {
    "dunder_alias": 'x = __import__\nx("socket")\n',
    "importlib_from": (
        "from importlib import import_module as f\n"
        'f("socket")\n'
    ),
    "getattr_chain": (
        "import os\n"
        'g = getattr(os, "sys" + "tem")\n'
        'g("id")\n'
    ),
}
EVASION_POLICY = dict(
    deny_imports=("socket",), deny_calls=("os.system",)
)


async def test_http_dynamic_import_evasions_denied(counting_executor):
    metrics = Registry()
    analyzer = WorkloadAnalyzer(
        PolicyEngine(**EVASION_POLICY), metrics=metrics
    )
    app = make_app(counting_executor, analyzer, metrics=metrics)

    async def go(client):
        for name, src in EVASIONS.items():
            resp = await client.post(
                "/v1/execute", json={"source_code": src}
            )
            assert resp.status == 422, name
            rules = {v["rule"] for v in (await resp.json())["violations"]}
            assert rules & {"import:socket", "call:os.system"}, (name, rules)

    await with_client(app, go)
    assert counting_executor.executions == 0
    assert (
        'bci_analysis_dynamic_imports_total{action="resolved"}'
        in metrics.expose()
    )


async def test_http_dynamic_import_warn_path(counting_executor):
    """Non-constant import target under the default fail-open policy:
    the execution proceeds, annotated `dynamic_import` + counted."""
    metrics = Registry()
    analyzer = WorkloadAnalyzer(
        PolicyEngine(dynamic_import="warn"), metrics=metrics
    )
    app = make_app(counting_executor, analyzer, metrics=metrics)

    async def go(client):
        resp = await client.post(
            "/v1/execute",
            json={"source_code": 'name = str(1)\n__import__(name)\n'},
        )
        assert resp.status == 200
        body = await resp.json()
        warned = body["analysis"]["warnings"]
        assert warned[0]["rule"] == "dynamic_import"

    await with_client(app, go)
    assert counting_executor.executions == 1  # warn does not block
    assert (
        'bci_analysis_dynamic_imports_total{action="warn"} 1'
        in metrics.expose()
    )


async def test_http_dynamic_import_deny_mode(counting_executor):
    analyzer = WorkloadAnalyzer(PolicyEngine(dynamic_import="deny"))
    app = make_app(counting_executor, analyzer)

    async def go(client):
        resp = await client.post(
            "/v1/execute",
            json={"source_code": 'name = str(1)\n__import__(name)\n'},
        )
        assert resp.status == 422
        body = await resp.json()
        assert body["violations"][0]["rule"] == "dynamic_import"

    await with_client(app, go)
    assert counting_executor.executions == 0


async def test_grpc_dynamic_import_evasions_denied(counting_executor):
    server = GrpcServer(
        code_executor=counting_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=counting_executor),
        analyzer=WorkloadAnalyzer(PolicyEngine(**EVASION_POLICY)),
    )

    async def go(stubs):
        for name, src in EVASIONS.items():
            try:
                await stubs["Execute"](pb.ExecuteRequest(source_code=src))
            except grpc.aio.AioRpcError as e:
                assert e.code() == grpc.StatusCode.INVALID_ARGUMENT, name
                assert (
                    "import:socket" in e.details()
                    or "call:os.system" in e.details()
                ), (name, e.details())
            else:
                raise AssertionError(f"{name}: expected INVALID_ARGUMENT")

    await run_grpc(server, go)
    assert counting_executor.executions == 0


async def test_grpc_dynamic_import_warn_rides_trailer(counting_executor):
    server = GrpcServer(
        code_executor=counting_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=counting_executor),
        analyzer=WorkloadAnalyzer(PolicyEngine(dynamic_import="warn")),
    )

    async def go(stubs):
        call = stubs["Execute"](
            pb.ExecuteRequest(
                # statically a dynamic-import site; never actually runs
                source_code=(
                    "name = str(1)\n"
                    "if not name:\n    __import__(name)\n"
                    "print(1)\n"
                )
            )
        )
        resp = await call
        assert resp.exit_code == 0
        trailers = {k: v for k, v in await call.trailing_metadata()}
        assert "dynamic_import" in trailers.get("bci-analysis-warnings", "")

    await run_grpc(server, go)
    assert counting_executor.executions == 1


async def test_grpc_dynamic_import_deny_mode(counting_executor):
    server = GrpcServer(
        code_executor=counting_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=counting_executor),
        analyzer=WorkloadAnalyzer(PolicyEngine(dynamic_import="deny")),
    )

    async def go(stubs):
        try:
            await stubs["Execute"](
                pb.ExecuteRequest(source_code='n = str(1)\n__import__(n)\n')
            )
        except grpc.aio.AioRpcError as e:
            assert e.code() == grpc.StatusCode.INVALID_ARGUMENT
            assert "dynamic_import" in e.details()
        else:
            raise AssertionError("expected INVALID_ARGUMENT")

    await run_grpc(server, go)
    assert counting_executor.executions == 0


# ----------------------------------------------------------- cost class


async def test_grpc_cost_class_rides_trailer(counting_executor):
    server = GrpcServer(
        code_executor=counting_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=counting_executor),
        analyzer=WorkloadAnalyzer(),
    )

    async def go(stubs):
        call = stubs["Execute"](pb.ExecuteRequest(source_code="print(1)\n"))
        await call
        trailers = {k: v for k, v in await call.trailing_metadata()}
        assert trailers.get("bci-analysis-cost-class") == "cheap"

    await run_grpc(server, go)


async def test_http_cost_class_on_fleet_snapshot(counting_executor):
    """The running cost-class mix is exported on GET /v1/fleet for the
    router's placement view (docs/fleet.md)."""
    analyzer = WorkloadAnalyzer()
    app = make_app(counting_executor, analyzer)

    async def go(client):
        await client.post(
            "/v1/execute", json={"source_code": "print(1)\n"}
        )
        snap = await (await client.get("/v1/fleet")).json()
        assert snap["cost_classes"]["cheap"] == 1

    await with_client(app, go)


ACCELERATOR_SOURCE = "import jax\nprint(jax.numpy.zeros(3).sum())\n"


async def test_http_accelerator_submission_classified_end_to_end(
    counting_executor,
):
    """An accelerator-shaped submission gets `accelerator` on the
    response AND the /v1/fleet cost-mix export, with the classification
    itself spending zero sandbox checkouts (the execute below is the
    request's own run, not the classifier's)."""
    analyzer = WorkloadAnalyzer()
    app = make_app(counting_executor, analyzer)

    async def go(client):
        body = await (
            await client.post(
                "/v1/execute", json={"source_code": ACCELERATOR_SOURCE}
            )
        ).json()
        assert body["analysis"]["cost_class"] == "accelerator"
        snap = await (await client.get("/v1/fleet")).json()
        assert snap["cost_classes"]["accelerator"] == 1

    await with_client(app, go)
    assert counting_executor.executions == 1  # the run itself, nothing more


async def test_grpc_accelerator_class_rides_trailer(counting_executor):
    server = GrpcServer(
        code_executor=counting_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=counting_executor),
        analyzer=WorkloadAnalyzer(),
    )

    async def go(stubs):
        call = stubs["Execute"](
            pb.ExecuteRequest(source_code=ACCELERATOR_SOURCE)
        )
        await call
        trailers = {k: v for k, v in await call.trailing_metadata()}
        assert trailers.get("bci-analysis-cost-class") == "accelerator"

    await run_grpc(server, go)


def test_accelerator_class_lands_on_wide_event():
    """Same flight-recorder lift as the other classes: the span attribute
    becomes the wide event's analysis block."""
    from bee_code_interpreter_tpu.observability import (
        FlightRecorder,
        Tracer,
    )
    from bee_code_interpreter_tpu.utils.metrics import Registry

    registry = Registry()
    tracer = Tracer(metrics=registry)
    recorder = FlightRecorder(metrics=registry)
    tracer.add_sink(recorder.record_trace)
    analyzer = WorkloadAnalyzer(metrics=registry)
    with tracer.trace("/v1/execute"):
        analyzer.analyze(ACCELERATOR_SOURCE)
    event = recorder.events(limit=1)[0]
    assert event["analysis"]["cost_class"] == "accelerator"
    assert (
        'bci_analysis_cost_class_total{class="accelerator"} 1'
        in registry.expose()
    )


async def test_grpc_clean_source_executes(counting_executor):
    server = GrpcServer(
        code_executor=counting_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=counting_executor),
        analyzer=WorkloadAnalyzer(PolicyEngine(warn_imports=("json",))),
    )

    async def go(stubs):
        resp = await stubs["Execute"](
            pb.ExecuteRequest(source_code="import json\nprint(json.dumps(2))")
        )
        assert resp.stdout == "2\n"

    await run_grpc(server, go)
    assert counting_executor.executions == 1
