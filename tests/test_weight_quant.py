"""Weight-only int8 quantization (ops/weight_quant.py + the qeinsum
dispatch in models/transformer.py).

Two distinct claims, tested separately:

1. EXACTNESS ACROSS PATHS on the same quantized pytree: forward, cached
   decode, and the paged batcher all route weights through the one
   qeinsum dispatch, so the cross-path pins (decode == forward token
   stream, batched == solo) hold verbatim on the quantized model.
2. CLOSENESS TO THE FP MODEL: a quantization-quality property — int8
   per-out-channel keeps logits near and argmax mostly unchanged; it is
   never exact and is asserted with tolerances.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bee_code_interpreter_tpu.models.serving import ContinuousBatcher
from bee_code_interpreter_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    forward,
    init_params,
    qeinsum,
)
from bee_code_interpreter_tpu.ops.weight_quant import (
    quantize_weight,
    quantize_weights,
    quantized_nbytes,
)

CFG = dataclasses.replace(TransformerConfig.tiny(), n_kv_heads=2)
PARAMS = init_params(CFG, jax.random.PRNGKey(0))
QPARAMS = quantize_weights(PARAMS)
PROMPT = [5, 3, 7, 2, 9, 4, 1, 8]
TOKENS = jnp.asarray([PROMPT], dtype=jnp.int32)


def test_qeinsum_epilogue_is_exact_algebra():
    w = jax.random.normal(jax.random.PRNGKey(1), (32, 16), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 4, 32), jnp.float32)
    leaf = quantize_weight(w)
    got = qeinsum("bld,dk->blk", x, leaf, jnp.float32)
    # dequantize-first oracle: x @ (q * s) — per-out scales commute with
    # the contraction, so the epilogue form must match to float noise
    dequant = leaf["q"].astype(jnp.float32) * leaf["s"][None, :]
    want = jnp.einsum("bld,dk->blk", x, dequant)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_quantization_halves_weight_bytes():
    fp = quantized_nbytes(PARAMS)
    q = quantized_nbytes(QPARAMS)
    # embeddings/norms stay fp; the seven projections + lm_head drop 4x
    # (f32 masters -> int8+scales), so total must shrink well past half
    assert q < 0.5 * fp
    leaf = QPARAMS["layers"]["wq"]
    assert leaf["q"].dtype == jnp.int8
    assert leaf["s"].dtype == jnp.float32
    assert leaf["q"].shape == PARAMS["layers"]["wq"].shape
    assert leaf["s"].shape == PARAMS["layers"]["wq"].shape[:-2] + (
        PARAMS["layers"]["wq"].shape[-1],
    )
    # non-targets untouched
    assert not isinstance(QPARAMS["layers"]["ln1"], dict)
    assert not isinstance(QPARAMS["embed"], dict)


def test_quantized_model_is_close_to_fp():
    f32 = dataclasses.replace(CFG, dtype=jnp.float32)
    lg_fp = np.asarray(forward(PARAMS, TOKENS, f32))
    lg_q = np.asarray(forward(QPARAMS, TOKENS, f32))
    # quality, not exactness: logits near, argmax mostly unchanged
    scale = np.abs(lg_fp).max()
    assert np.abs(lg_q - lg_fp).max() < 0.25 * scale
    agree = (lg_q.argmax(-1) == lg_fp.argmax(-1)).mean()
    assert agree >= 0.75, agree


def test_cross_path_exactness_on_quantized_params():
    """generate_cached and the paged batcher on the SAME qparams produce
    identical tokens — the serving pins hold verbatim quantized."""
    model = Transformer(CFG)
    solo = np.asarray(model.generate_cached(
        QPARAMS, TOKENS, max_new_tokens=6
    )[0, len(PROMPT):]).tolist()
    b = ContinuousBatcher(QPARAMS, CFG, max_batch=2, n_pages=24,
                          page_size=4, max_pages_per_seq=8)
    r = b.submit(PROMPT, 6)
    r2 = b.submit([3, 1, 4, 1, 5], 4)  # a batch-mate changes nothing
    b.run_to_completion()
    assert b.result(r) == solo
    assert len(b.result(r2)) == 4


def test_quantized_with_int8_kv_cache_and_prefix_cache():
    cfg = dataclasses.replace(CFG, kv_cache_dtype="int8")
    qp = quantize_weights(init_params(cfg, jax.random.PRNGKey(3)))

    def run():
        b = ContinuousBatcher(qp, cfg, max_batch=2, n_pages=24,
                              page_size=4, max_pages_per_seq=8,
                              prefix_cache=True)
        out = []
        for _ in range(2):
            req = b.submit(PROMPT, 5)
            b.run_to_completion()
            out.append(b.result(req))
        return out, b.prefix_stats["hits"]

    first, hits = run()
    second, _ = run()
    assert first == second          # deterministic
    assert first[0] == first[1]     # prefix hit changes nothing
    assert hits == 1


def test_quantized_base_serves_adapters():
    """Multi-LoRA on a weight-only-int8 base: adapter admissions route
    through the (quantization-aware) window prefill, so the combination
    serves. Pins: the zero-delta adapter is the quantized base EXACTLY, a
    real adapter visibly changes the output, and both are deterministic."""
    from bee_code_interpreter_tpu.models.lora import init_lora

    zero = init_lora(CFG, jax.random.PRNGKey(5), rank=4)  # B == 0: identity
    real = {
        t: {"A": ab["A"],
            "B": jax.random.normal(jax.random.PRNGKey(6), ab["B"].shape,
                                   jnp.float32) * 0.3}
        for t, ab in zero.items()
    }

    def run(adapter):
        b = ContinuousBatcher(QPARAMS, CFG, max_batch=2, n_pages=24,
                              page_size=4, max_pages_per_seq=8,
                              adapters=[zero, real], lora_scale=2.0)
        r = b.submit(PROMPT, 5, adapter=adapter)
        b.run_to_completion()
        return b.result(r)

    base = ContinuousBatcher(QPARAMS, CFG, max_batch=2, n_pages=24,
                             page_size=4, max_pages_per_seq=8)
    rb = base.submit(PROMPT, 5)
    base.run_to_completion()
    assert run(0) == base.result(rb)   # zero delta == quantized base
    adapted = run(1)
    assert adapted != base.result(rb)  # the adapter actually acts
    assert run(1) == adapted           # deterministic


def test_merge_refuses_quantized_with_clear_error():
    from bee_code_interpreter_tpu.models.lora import init_lora, merge_lora

    lora = init_lora(CFG, jax.random.PRNGKey(5), rank=4)
    with pytest.raises(NotImplementedError, match="quantize AFTER merging"):
        merge_lora(QPARAMS, lora)


def test_quantized_params_shard_and_match_unsharded():
    """tp-sharded quantized forward == unsharded quantized forward: q
    takes the fp weight's Megatron spec, the per-out scales ride the same
    shards (d_in axis dropped from the spec), so the qeinsum epilogue
    stays local."""
    from bee_code_interpreter_tpu.models.transformer import shard_params
    from bee_code_interpreter_tpu.parallel import make_mesh

    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    sharded = shard_params(QPARAMS, CFG, mesh)
    leaf = sharded["layers"]["wq"]
    assert leaf["q"].sharding.spec[-1] == "tp"
    assert leaf["s"].sharding.spec[-1] == "tp"
    # f32 compute so the only difference is the tp reduction split (bf16
    # reduction-order noise would need a sloppy tolerance)
    f32 = dataclasses.replace(CFG, dtype=jnp.float32)
    lg_sharded = np.asarray(forward(sharded, TOKENS, f32, mesh))
    lg_local = np.asarray(forward(QPARAMS, TOKENS, f32, None))
    np.testing.assert_allclose(lg_sharded, lg_local, atol=1e-4, rtol=1e-4)
