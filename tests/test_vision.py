"""ResNet vision family: shapes, sharded training, resnet50 structure."""

import jax
import jax.numpy as jnp
import numpy as np

from bee_code_interpreter_tpu.models.vision import (
    ResNet,
    ResNetConfig,
    forward,
    init_params,
)
from bee_code_interpreter_tpu.parallel.mesh import make_mesh


def test_forward_shape_and_dtype():
    config = ResNetConfig.tiny()
    params = init_params(config, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    logits = forward(params, x, config)
    assert logits.shape == (2, config.num_classes)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_resnet50_structure():
    # The flagship config matches the classic 50-layer bottleneck layout:
    # 3-4-6-3 stages, 2048 final channels, ~25.5M params.
    config = ResNetConfig.resnet50()
    params = jax.eval_shape(lambda k: init_params(config, k), jax.random.PRNGKey(0))
    n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    assert 25_000_000 < n < 26_500_000, n
    assert params["fc"]["w"].shape == (2048, 1000)
    assert len(params["stage2"]) == 6


def test_training_decreases_loss_on_dp_mesh():
    import optax

    config = ResNetConfig.tiny()
    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    model = ResNet(config, mesh)
    params = model.init(jax.random.PRNGKey(0))

    images = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3)),
        model.batch_sharding(),
    )
    labels = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(2), (8,), 0, config.num_classes),
        model.batch_sharding(),
    )
    batch = {"images": images, "labels": labels}

    optimizer = optax.sgd(0.05, momentum=0.9)
    step = model.make_train_step(optimizer)
    opt_state = optimizer.init(params)

    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_single_vs_sharded_forward_agree():
    config = ResNetConfig.tiny()
    mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])
    params = init_params(config, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 32, 32, 3))
    a = forward(params, x, config)
    b = forward(params, x, config, mesh)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-2, rtol=2e-2)


def test_group_norm_non_divisible_channels():
    # width=48 with default norm_groups=32: groups clamp to the largest
    # divisor of C (16), not crash the reshape.
    from bee_code_interpreter_tpu.models.vision import group_norm

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 48))
    out = group_norm(x, jnp.ones((48,)), jnp.zeros((48,)), groups=32)
    assert out.shape == x.shape
    assert bool(jnp.isfinite(out).all())
