"""TPU_EVIDENCE.jsonl ledger: append-only hardware evidence that survives a
wedged tunnel (VERDICT r3 next-round #1b). Tests point the ledger at a
tmpdir via BCI_EVIDENCE_PATH so they never dirty the real one."""

import json

import pytest

from bee_code_interpreter_tpu.utils import evidence


@pytest.fixture
def ledger(tmp_path, monkeypatch):
    path = tmp_path / "ledger.jsonl"
    monkeypatch.setenv("BCI_EVIDENCE_PATH", str(path))
    return path


def test_record_appends_timestamped_attributed_entry(ledger):
    entry = evidence.record(
        "dense_matmul", {"gflops": 185134.0}, script="bench.py"
    )
    assert entry["case"] == "dense_matmul"
    assert entry["data"] == {"gflops": 185134.0}
    assert entry["script"] == "bench.py"
    assert entry["ts"].endswith("+00:00")  # UTC, attributable
    on_disk = [json.loads(l) for l in ledger.read_text().splitlines()]
    assert on_disk == [entry]


def test_record_is_append_only(ledger):
    evidence.record("a", {"v": 1}, script="s")
    evidence.record("b", {"v": 2}, script="s")
    assert len(ledger.read_text().splitlines()) == 2


def test_latest_per_case_keeps_newest_and_skips_torn_lines(ledger):
    evidence.record("decode", {"tokens_per_sec": 100}, script="s")
    evidence.record("dense_matmul", {"gflops": 1.0}, script="s")
    with ledger.open("a") as f:
        f.write('{"torn json\n')  # a crashed writer must not break readers
    evidence.record("decode", {"tokens_per_sec": 200}, script="s")
    latest = evidence.latest_per_case()
    by_case = {e["case"]: e["data"] for e in latest}
    assert by_case == {
        "decode": {"tokens_per_sec": 200},
        "dense_matmul": {"gflops": 1.0},
    }


def test_read_all_missing_file_is_empty(ledger):
    assert evidence.read_all() == []
    assert evidence.latest_per_case() == []


def test_record_never_raises_on_unwritable_path(tmp_path, monkeypatch):
    # The ledger is a side channel: an unwritable target must not turn an
    # already-successful hardware measurement into a failed script.
    monkeypatch.setenv(
        "BCI_EVIDENCE_PATH", str(tmp_path / "no" / "such" / "dir" / "l.jsonl")
    )
    entry = evidence.record("decode", {"tps": 1}, script="s")
    assert "ledger_error" in entry
    assert entry["case"] == "decode"


def test_bench_embeds_ledger(ledger):
    # bench.py's hardware_evidence() is the embed point: a wedged driver run
    # must still carry the dated ledger entries.
    import importlib.util
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location("bench", repo / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    sys.modules["bench_for_evidence_test"] = bench
    spec.loader.exec_module(bench)
    evidence.record("flash_attention", {"tflops": 99.3}, script="bench.py")
    embedded = bench.hardware_evidence()
    assert [e["case"] for e in embedded] == ["flash_attention"]
    assert embedded[0]["data"]["tflops"] == 99.3
