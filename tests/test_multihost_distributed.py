"""Real multi-process jax.distributed bring-up through the sandbox runtime.

The control-plane tests fake the pod group; this is the other half, run for
real: two separate interpreter processes given exactly the env the pod-group
scheduler bakes into workers (JAX_COORDINATOR_ADDRESS → worker 0,
JAX_NUM_PROCESSES, JAX_PROCESS_ID; kubernetes_code_executor.spawn_pod_group)
bring up one jax world via ``parallel.initialize_distributed()`` and run a
cross-process collective. On TPU pods the same code path spans a multi-host
slice over ICI; here the two "hosts" are CPU processes on localhost.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

WORKER_SCRIPT = """
import jax
from bee_code_interpreter_tpu.parallel import initialize_distributed

assert initialize_distributed(), "should initialize from pod-group env"
assert jax.process_count() == 2, jax.process_count()

import numpy as np
from jax.experimental import multihost_utils

gathered = multihost_utils.process_allgather(np.array([jax.process_index()]))
print("GATHERED", sorted(int(x) for x in np.asarray(gathered).ravel()))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_world_via_pod_group_env(tmp_path):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(WORKER_SCRIPT)

    procs = []
    for worker_id in range(2):
        env = {
            "PATH": os.environ.get("PATH", ""),
            "HOME": os.environ.get("HOME", "/tmp"),
            "PYTHONPATH": str(REPO),
            # exactly what spawn_pod_group bakes into each worker pod
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(worker_id),
            "TPU_WORKER_ID": str(worker_id),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        }
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )

    outs = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        if "Multiprocess computations aren't implemented" in err:
            # The 2-process world rendezvoused; this jax build's CPU backend
            # just can't run the collective math.
            pytest.skip("jax CPU backend lacks multiprocess collectives")
        assert p.returncode == 0, f"worker failed:\n{err}"
        outs.append(out)

    # every process saw the full world
    for out in outs:
        assert "GATHERED [0, 1]" in out, outs
