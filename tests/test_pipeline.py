"""GPipe pipeline primitive vs the sequential scan oracle (virtual devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bee_code_interpreter_tpu.parallel import make_mesh, spmd_pipeline


def make_layers(n_layers: int, d: int, key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), n_layers)
    return {
        "w": jax.vmap(
            lambda k: jax.random.normal(k, (d, d), jnp.float32) / np.sqrt(d)
        )(ks),
        "b": jnp.zeros((n_layers, d), jnp.float32),
    }


def stage_fn(h, layer):
    return jax.nn.relu(h @ layer["w"] + layer["b"])


def sequential(layers, x):
    def body(h, layer):
        return stage_fn(h, layer), None

    h, _ = jax.lax.scan(body, x, layers)
    return h


@pytest.mark.parametrize("pp,n_microbatches", [(2, 2), (4, 4), (4, 8)])
def test_pipeline_matches_sequential(pp, n_microbatches):
    mesh = make_mesh({"pp": pp}, devices=jax.devices()[:pp])
    layers = make_layers(n_layers=8, d=16)
    x = jax.random.normal(jax.random.PRNGKey(1), (n_microbatches * 2, 16))
    got = spmd_pipeline(
        stage_fn, layers, x, mesh=mesh, n_microbatches=n_microbatches
    )
    want = sequential(layers, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_pipeline_is_differentiable():
    # Training through the pipeline: grads must equal the sequential oracle's
    # (ppermute/psum transpose cleanly; XLA derives the reverse schedule).
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    layers = make_layers(n_layers=4, d=8)
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 8))

    def loss_pipe(layers):
        return (
            spmd_pipeline(stage_fn, layers, x, mesh=mesh, n_microbatches=4) ** 2
        ).sum()

    def loss_seq(layers):
        return (sequential(layers, x) ** 2).sum()

    g_pipe = jax.grad(loss_pipe)(layers)
    g_seq = jax.grad(loss_seq)(layers)
    for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_pipeline_composes_with_dp():
    # dp x pp mesh: batch sharded over dp, layers over pp.
    mesh = make_mesh({"dp": 2, "pp": 4}, devices=jax.devices()[:8])
    layers = make_layers(n_layers=4, d=16)
    x = jax.random.normal(jax.random.PRNGKey(3), (16, 16))
    got = spmd_pipeline(
        stage_fn, layers, x, mesh=mesh, n_microbatches=4, batch_axes=("dp",)
    )
    want = sequential(layers, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_pipeline_validates_divisibility():
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    layers = make_layers(n_layers=6, d=8)  # 6 % 4 != 0
    x = jnp.zeros((8, 8))
    with pytest.raises(ValueError, match="not divisible"):
        spmd_pipeline(stage_fn, layers, x, mesh=mesh, n_microbatches=4)
    layers = make_layers(n_layers=8, d=8)
    with pytest.raises(ValueError, match="microbatches"):
        spmd_pipeline(stage_fn, layers, x[:6], mesh=mesh, n_microbatches=4)
