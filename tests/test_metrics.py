"""Prometheus-style metrics: exposition format and the /metrics endpoint.
(New capability — the reference ships no metrics at all, SURVEY.md §5.)"""

from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_tpu.api.http_server import create_http_server
from bee_code_interpreter_tpu.services.custom_tool_executor import CustomToolExecutor
from bee_code_interpreter_tpu.utils.metrics import Registry


def test_counter_labels_and_format():
    reg = Registry()
    c = reg.counter("x_total", "help here")
    c.inc(route="/a", status="200")
    c.inc(route="/a", status="200")
    c.inc(route="/b", status="500")
    text = reg.expose()
    assert "# TYPE x_total counter" in text
    assert 'x_total{route="/a",status="200"} 2' in text
    assert 'x_total{route="/b",status="500"} 1' in text


def test_histogram_buckets_sum_count():
    reg = Registry()
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05, route="/a")
    h.observe(0.5, route="/a")
    h.observe(5.0, route="/a")
    text = reg.expose()
    assert 'lat_seconds_bucket{le="0.1",route="/a"} 1' in text
    assert 'lat_seconds_bucket{le="1",route="/a"} 2' in text
    assert 'lat_seconds_bucket{le="+Inf",route="/a"} 3' in text
    assert 'lat_seconds_count{route="/a"} 3' in text
    assert 'lat_seconds_sum{route="/a"} 5.55' in text


def test_gauge_reads_callback_at_scrape():
    reg = Registry()
    pool = [1, 2, 3]
    reg.gauge("pool_size", "pool", lambda: len(pool))
    assert "pool_size 3" in reg.expose()
    pool.append(4)
    assert "pool_size 4" in reg.expose()


async def test_metrics_endpoint_counts_requests(local_executor):
    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
    )
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        resp = await client.post("/v1/execute", json={"source_code": "print(1)"})
        assert resp.status == 200
        # unmatched paths bucket into one label (no attacker-driven cardinality)
        await client.get('/%22injected%22/scan1')
        await client.get("/scan2")
        text = await (await client.get("/metrics")).text()
        assert 'bci_http_requests_total{route="/v1/execute",status="200"} 1' in text
        assert 'bci_http_request_seconds_count{route="/v1/execute"} 1' in text
        assert 'bci_http_requests_total{route="unmatched",status="404"} 2' in text
        assert "injected" not in text and "scan2" not in text
    finally:
        await client.close()
