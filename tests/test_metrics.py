"""Prometheus-style metrics: exposition format and the /metrics endpoint.
(New capability — the reference ships no metrics at all, SURVEY.md §5.)"""

from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_tpu.api.http_server import create_http_server
from bee_code_interpreter_tpu.services.custom_tool_executor import CustomToolExecutor
from bee_code_interpreter_tpu.utils.metrics import Registry


def test_counter_labels_and_format():
    reg = Registry()
    c = reg.counter("x_total", "help here")
    c.inc(route="/a", status="200")
    c.inc(route="/a", status="200")
    c.inc(route="/b", status="500")
    text = reg.expose()
    assert "# TYPE x_total counter" in text
    assert 'x_total{route="/a",status="200"} 2' in text
    assert 'x_total{route="/b",status="500"} 1' in text


def test_histogram_buckets_sum_count():
    reg = Registry()
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05, route="/a")
    h.observe(0.5, route="/a")
    h.observe(5.0, route="/a")
    text = reg.expose()
    assert 'lat_seconds_bucket{le="0.1",route="/a"} 1' in text
    assert 'lat_seconds_bucket{le="1",route="/a"} 2' in text
    assert 'lat_seconds_bucket{le="+Inf",route="/a"} 3' in text
    assert 'lat_seconds_count{route="/a"} 3' in text
    assert 'lat_seconds_sum{route="/a"} 5.55' in text


def test_gauge_reads_callback_at_scrape():
    reg = Registry()
    pool = [1, 2, 3]
    reg.gauge("pool_size", "pool", lambda: len(pool))
    assert "pool_size 3" in reg.expose()
    pool.append(4)
    assert "pool_size 4" in reg.expose()


def test_raising_gauge_emits_nan_without_aborting_scrape():
    # A callback that raises (e.g. a pool property read during executor
    # teardown) must cost only its own sample, never the whole exposition.
    reg = Registry()
    c = reg.counter("ok_total", "fine")
    c.inc()

    def boom():
        raise RuntimeError("pool torn down")

    reg.gauge("broken_gauge", "raises at scrape", boom)
    reg.gauge("healthy_gauge", "fine", lambda: 7)
    text = reg.expose()
    assert "broken_gauge NaN" in text
    assert "healthy_gauge 7" in text
    assert "ok_total 1" in text  # the rest of the exposition survived


def test_labeled_gauges_share_one_metric_block():
    reg = Registry()
    reg.gauge("breaker_state", "state", lambda: 0, breaker="spawn")
    reg.gauge("breaker_state", "state", lambda: 2, breaker="http")
    text = reg.expose()
    assert text.count("# TYPE breaker_state gauge") == 1
    assert 'breaker_state{breaker="spawn"} 0' in text
    assert 'breaker_state{breaker="http"} 2' in text


def test_registry_dedupes_by_name():
    # Two components asking for the same counter share one object — no
    # duplicate HELP/TYPE blocks, one merged value stream.
    reg = Registry()
    a = reg.counter("shared_total", "shared")
    b = reg.counter("shared_total", "shared")
    assert a is b
    a.inc(); b.inc()
    text = reg.expose()
    assert text.count("# TYPE shared_total counter") == 1
    assert "shared_total 2" in text


async def test_metrics_endpoint_counts_requests(local_executor):
    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
    )
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        resp = await client.post("/v1/execute", json={"source_code": "print(1)"})
        assert resp.status == 200
        # unmatched paths bucket into one label (no attacker-driven cardinality)
        await client.get('/%22injected%22/scan1')
        await client.get("/scan2")
        text = await (await client.get("/metrics")).text()
        assert 'bci_http_requests_total{route="/v1/execute",status="200"} 1' in text
        assert 'bci_http_request_seconds_count{route="/v1/execute"} 1' in text
        assert 'bci_http_requests_total{route="unmatched",status="404"} 2' in text
        assert "injected" not in text and "scan2" not in text
    finally:
        await client.close()
