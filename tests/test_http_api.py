"""HTTP API e2e tests against an in-process server with the local executor
backend — same coverage shape as the reference's live-service suite
(test/e2e/test_http.py) without requiring a cluster (SURVEY.md §4)."""

import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_tpu.api.http_server import create_http_server
from bee_code_interpreter_tpu.services.custom_tool_executor import CustomToolExecutor


@pytest.fixture
def http_app(local_executor):
    return create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
    )


async def with_client(app, fn):
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        return await fn(client)
    finally:
        await client.close()


async def test_execute_basic(http_app):
    async def go(client: TestClient):
        resp = await client.post("/v1/execute", json={"source_code": "print(21 * 2)"})
        assert resp.status == 200
        body = await resp.json()
        assert body["stdout"] == "42\n"
        assert body["exit_code"] == 0
        assert body["files"] == {}

    await with_client(http_app, go)


async def test_execute_file_roundtrip(http_app):
    # reference test_http.py:47-85
    async def go(client: TestClient):
        r1 = await (
            await client.post(
                "/v1/execute",
                json={"source_code": "open('state.txt','w').write('round trip')"},
            )
        ).json()
        assert set(r1["files"]) == {"/workspace/state.txt"}
        r2 = await (
            await client.post(
                "/v1/execute",
                json={
                    "source_code": "print(open('state.txt').read())",
                    "files": r1["files"],
                },
            )
        ).json()
        assert r2["stdout"] == "round trip\n"

    await with_client(http_app, go)


async def test_execute_env(http_app):
    # reference test_http.py:88-99
    async def go(client: TestClient):
        resp = await client.post(
            "/v1/execute",
            json={
                "source_code": "import os; print(os.environ['GREETING'])",
                "env": {"GREETING": "hi"},
            },
        )
        assert (await resp.json())["stdout"] == "hi\n"

    await with_client(http_app, go)


async def test_execute_validation_error(http_app):
    async def go(client: TestClient):
        resp = await client.post("/v1/execute", json={"files": {"bad": "x"}})
        assert resp.status == 422

    await with_client(http_app, go)


async def test_parse_custom_tool_success(http_app):
    async def go(client: TestClient):
        resp = await client.post(
            "/v1/parse-custom-tool",
            json={
                "tool_source_code": (
                    'def adder(a: int, b: int) -> int:\n    """Adds.\n\n'
                    '    :param a: first\n    :param b: second\n    :return: the sum\n    """\n'
                    "    return a + b"
                )
            },
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["tool_name"] == "adder"
        assert body["tool_description"] == "Adds.\n\nReturns: int -- the sum"
        schema = json.loads(body["tool_input_schema_json"])
        assert schema["required"] == ["a", "b"]
        assert schema["$schema"] == "http://json-schema.org/draft-07/schema#"

    await with_client(http_app, go)


async def test_parse_custom_tool_error_400(http_app):
    async def go(client: TestClient):
        resp = await client.post(
            "/v1/parse-custom-tool",
            json={"tool_source_code": "def t(*args) -> int:\n  return 1"},
        )
        assert resp.status == 400
        assert (await resp.json())["error_messages"] == [
            "The tool function must not have *args"
        ]

    await with_client(http_app, go)


async def test_execute_custom_tool_success(http_app):
    async def go(client: TestClient):
        resp = await client.post(
            "/v1/execute-custom-tool",
            json={
                "tool_source_code": "def adding_tool(a: int, b: int) -> int:\n  return a + b",
                "tool_input_json": '{"a": 1, "b": 2}',
            },
        )
        assert resp.status == 200
        assert json.loads((await resp.json())["tool_output_json"]) == 3

    await with_client(http_app, go)


async def test_execute_custom_tool_error_400(http_app):
    async def go(client: TestClient):
        resp = await client.post(
            "/v1/execute-custom-tool",
            json={
                "tool_source_code": "def div(a: int, b: int) -> int:\n  return a / b",
                "tool_input_json": '{"a": 0, "b": 0}',
            },
        )
        assert resp.status == 400
        assert "division by zero" in (await resp.json())["stderr"]

    await with_client(http_app, go)


async def test_healthz(http_app):
    async def go(client: TestClient):
        resp = await client.get("/healthz")
        assert resp.status == 200

    await with_client(http_app, go)


# ------------------------------------------------------------ graceful drain


async def test_drain_rejects_new_work_while_inflight_completes(local_executor):
    # Acceptance: after begin_drain, an in-flight execution completes
    # successfully while concurrent new requests get 503 + Retry-After.
    import asyncio

    from bee_code_interpreter_tpu.resilience import DrainController

    drain = DrainController(retry_after_s=2.0)
    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        drain=drain,
    )

    async def go(client: TestClient):
        inflight = asyncio.ensure_future(
            client.post(
                "/v1/execute",
                json={
                    "source_code": "import time; time.sleep(0.6); print('done')"
                },
            )
        )
        # wait until the slow request is actually tracked in flight
        for _ in range(100):
            if drain.in_flight > 0:
                break
            await asyncio.sleep(0.01)
        assert drain.in_flight == 1

        drain.begin()
        shed = await client.post(
            "/v1/execute", json={"source_code": "print(1)"}
        )
        assert shed.status == 503
        assert shed.headers["Retry-After"] == "2"
        assert "draining" in (await shed.json())["detail"].lower()

        # liveness stays green but names the state; verbose carries depth
        health = await (await client.get("/healthz")).json()
        assert health["status"] == "draining"
        verbose = await (
            await client.get("/healthz", params={"verbose": "1"})
        ).json()
        assert verbose["status"] == "draining"
        assert verbose["drain_inflight"] == 1

        # the in-flight execution is NOT killed by the drain
        resp = await inflight
        assert resp.status == 200
        assert (await resp.json())["stdout"] == "done\n"
        assert await drain.wait_idle(1.0) is True

    await with_client(app, go)


async def test_drain_waits_for_admission_queued_waiters(local_executor):
    # Review regression: a request QUEUED at the admission gate when the
    # drain begins was admitted past the drain check and will execute —
    # wait_idle must count it, or teardown closes the executor under it.
    import asyncio

    from bee_code_interpreter_tpu.resilience import (
        AdmissionController,
        DrainController,
    )

    admission = AdmissionController(max_in_flight=1, max_queue=4)
    drain = DrainController()
    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        admission=admission,
        drain=drain,
    )

    async def go(client: TestClient):
        slow = {"source_code": "import time; time.sleep(0.4); print('ok')"}
        first = asyncio.ensure_future(client.post("/v1/execute", json=slow))
        for _ in range(100):
            if admission.in_flight == 1:
                break
            await asyncio.sleep(0.01)
        queued = asyncio.ensure_future(client.post("/v1/execute", json=slow))
        for _ in range(100):
            if drain.in_flight == 2:  # tracked while still QUEUED
                break
            await asyncio.sleep(0.01)
        assert drain.in_flight == 2

        drain.begin()
        assert await drain.wait_idle(5.0) is True  # waits for BOTH
        for resp in (await first, await queued):
            assert resp.status == 200
            assert (await resp.json())["stdout"] == "ok\n"

    await with_client(app, go)


async def test_fleet_snapshot_carries_drain_and_supervisor_state(
    local_executor,
):
    from bee_code_interpreter_tpu.resilience import (
        DrainController,
        PoolSupervisor,
    )

    drain = DrainController()
    supervisor = PoolSupervisor(local_executor, interval_s=60)
    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        drain=drain,
        supervisor=supervisor,
    )

    async def go(client: TestClient):
        snap = await (await client.get("/v1/fleet")).json()
        assert snap["draining"] is False
        assert snap["supervisor"]["sweeps"] == 0
        assert snap["supervisor"]["running"] is False
        drain.begin()
        snap = await (await client.get("/v1/fleet")).json()
        assert snap["draining"] is True

    await with_client(app, go)
