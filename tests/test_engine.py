"""The serving engine (models/engine.py): queueing, priorities, page
backpressure, streaming reads, and cancellation over the continuous
batcher — the admit-when-capacity-frees loop as library code, pinned
against the same solo-decode bar as the batcher itself."""

import dataclasses

import numpy as np
import pytest

import jax

from bee_code_interpreter_tpu.models.engine import Engine
from bee_code_interpreter_tpu.models.serving import (
    ContinuousBatcher,
    SamplingParams,
)
from bee_code_interpreter_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)

CFG = dataclasses.replace(TransformerConfig.tiny(), n_kv_heads=2)
PARAMS = init_params(CFG, jax.random.PRNGKey(0))
PROMPT = [5, 3, 7, 2, 9, 4, 1, 8]


def make_engine(max_queue=None, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("n_pages", 24)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_pages_per_seq", 8)
    return Engine(ContinuousBatcher(PARAMS, CFG, **kw), max_queue=max_queue)


def greedy(prompt, n):
    b = ContinuousBatcher(PARAMS, CFG, max_batch=1, n_pages=24, page_size=4,
                          max_pages_per_seq=8)
    r = b.submit(prompt, n)
    b.run_to_completion()
    return b.result(r)


def test_overload_queues_and_everyone_finishes_solo_equal():
    """6 requests into a 2-row batch: the queue absorbs the overload and
    every output still equals its solo decode."""
    eng = make_engine()
    prompts = [
        [int(x) for x in np.random.default_rng(i).integers(0, 200, 5 + i)]
        for i in range(6)
    ]
    tickets = [eng.submit(p, 4) for p in prompts]
    assert eng.pending >= 4  # only 2 rows: the rest queued
    eng.run_to_completion()
    for t, p in zip(tickets, prompts):
        assert eng.result(t) == greedy(p, 4)
        assert eng.finish_reason(t) == "length"
    assert eng.pending == 0


def test_priority_admits_before_earlier_arrivals():
    # all three are queued before the first step (admission happens in
    # step, not submit): the high-priority one admits first, the other
    # two in arrival order
    eng = make_engine(max_batch=1)
    t_first = eng.submit(PROMPT, 3)
    t_normal = eng.submit([1, 2, 3], 3)
    t_urgent = eng.submit([4, 5, 6], 3, priority=5)
    order = []
    seen = set()
    for _ in range(60):
        eng.step()
        for t in (t_first, t_normal, t_urgent):
            if eng.is_done(t) and t not in seen:
                seen.add(t)
                order.append(t)
        if len(seen) == 3:
            break
    assert order == [t_urgent, t_first, t_normal]
    assert eng.result(t_urgent) == greedy([4, 5, 6], 3)


def test_page_backpressure_without_head_of_line_bypass():
    """A big request at the head waits for ITS pages; the small one behind
    it does NOT jump the line (no starvation of large requests)."""
    eng = make_engine(max_batch=2, n_pages=9, max_pages_per_seq=8)
    # 4 usable pages (9 minus scratch... 8): hold most of the pool
    t_hold = eng.submit(PROMPT, 12)        # 8+12=20 -> 5 pages
    t_big = eng.submit(PROMPT, 8)          # 8+8=16 -> 4 pages: must wait
    t_small = eng.submit([1, 2], 2)        # 1 page: arrives later
    eng.step()
    # the big request is still queued AND the small one behind it too
    assert not eng.is_done(t_big)
    assert eng.pending == 2
    eng.run_to_completion()
    assert eng.result(t_hold) == greedy(PROMPT, 12)
    assert eng.result(t_big) == greedy(PROMPT, 8)
    assert eng.result(t_small) == greedy([1, 2], 2)


def test_streaming_reads_concatenate_to_result():
    eng = make_engine()
    t = eng.submit(PROMPT, 6)
    streamed = []
    for _ in range(40):
        streamed += eng.new_tokens(t)
        if eng.is_done(t):
            break
        eng.step()
    streamed += eng.new_tokens(t)
    assert streamed == eng.result(t)
    # incremental: the stream arrived in more than one chunk
    assert len(streamed) == 6


def test_streaming_holdback_never_disowns_under_stop_trim():
    want = greedy(PROMPT, 10)
    stop = (want[3], want[4])
    eng = make_engine()
    t = eng.submit(PROMPT, 10,
                   sampling=SamplingParams(stop_sequences=(stop,)))
    streamed = []
    for _ in range(40):
        streamed += eng.new_tokens(t)
        if eng.is_done(t):
            break
        # every token streamed so far must survive into the final result
        assert streamed == want[:len(streamed)][:3]
        eng.step()
    streamed += eng.new_tokens(t)
    assert streamed == eng.result(t) == want[:3]
    assert eng.finish_reason(t) == "stop"


def test_cancel_queued_and_admitted():
    eng = make_engine(max_batch=1)
    t_active = eng.submit(PROMPT, 10)
    t_queued = eng.submit([1, 2, 3], 5)
    eng.cancel(t_queued)                      # never touches the device
    assert eng.is_done(t_queued)
    assert eng.finish_reason(t_queued) == "cancelled"
    assert eng.result(t_queued) == []
    eng.step()
    eng.cancel(t_active)                      # mid-decode
    assert eng.finish_reason(t_active) == "cancelled"
    assert len(eng.result(t_active)) >= 1
    # the queue entry was lazily dropped; nothing admits it later
    t_next = eng.submit([7, 7], 3)
    eng.run_to_completion()
    assert eng.result(t_next) == greedy([7, 7], 3)
    assert eng.pending == 0


def test_queue_bound_and_validation_at_submit():
    eng = make_engine(max_queue=1, max_batch=1)
    eng.submit(PROMPT, 3)          # admitted at first step... still queued
    eng.step()
    eng.submit([1, 2], 3)          # queue slot 1
    with pytest.raises(RuntimeError, match="queue full"):
        eng.submit([3, 4], 3)
    # validation errors fire at submit, not at admission
    with pytest.raises(ValueError, match="exceeds the block table"):
        eng.submit(PROMPT, 1000)
    with pytest.raises(ValueError, match="non-empty"):
        eng.submit([], 3)
    with pytest.raises(KeyError, match="unknown ticket"):
        eng.result(12345)


def test_release_and_logprobs_proxy():
    eng = make_engine()
    t = eng.submit(PROMPT, 3, sampling=SamplingParams(logprobs=True))
    eng.run_to_completion()
    assert len(eng.result_logprobs(t)) == 3
    eng.release(t)
    assert eng.is_done(t)
    assert eng.new_tokens(t) == []  # released: stream is empty, not an error


def test_intake_validation_is_the_batchers():
    """Engine.submit runs the batcher's validate_request: speculative
    constraints and permanent pool misfits fail at INTAKE, never wedge a
    queued ticket later."""
    draft_cfg = dataclasses.replace(CFG, n_layers=1)
    draft = init_params(draft_cfg, jax.random.PRNGKey(2))
    spec = Engine(ContinuousBatcher(
        PARAMS, CFG, max_batch=1, n_pages=24, page_size=4,
        max_pages_per_seq=8, draft_params=draft, draft_config=draft_cfg,
    ))
    with pytest.raises(ValueError, match="unsteered argmax"):
        spec.submit(PROMPT, 3, sampling=SamplingParams(logit_bias={1: 5.0}))
    # sampled speculative is SUPPORTED (rejection sampling) — intake
    # accepts it and the request completes
    t = spec.submit(PROMPT, 3, sampling=SamplingParams(temperature=0.7))
    spec.run_to_completion()
    assert len(spec.result(t)) == 3
    # a request that can NEVER fit the pool is a ValueError at submit,
    # not an eternally-queued head-of-line blocker
    tiny_pool = make_engine(n_pages=4, max_pages_per_seq=8)
    with pytest.raises(ValueError, match="permanent misfit"):
        tiny_pool.submit(PROMPT, 12)  # 5 pages, pool has 3 usable


def test_release_and_cancel_drop_streaming_state():
    eng = make_engine()
    t = eng.submit(PROMPT, 3)
    eng.run_to_completion()
    eng.result(t)
    eng.release(t)
    assert t not in eng._holdback and t not in eng._stream_cursor
    t2 = eng.submit(PROMPT, 3)
    eng.cancel(t2)  # cancelled while queued
    assert t2 not in eng._holdback and t2 not in eng._stream_cursor


def test_intake_validates_prefill_chunk():
    with pytest.raises(ValueError, match="chunk must be"):
        make_engine().submit(PROMPT, 3, prefill_chunk=0)


def test_admission_counts_prefix_credit():
    """A repeat prompt whose prefix pages are held by an ACTIVE sharing
    row must admit on its fresh-page need alone — ignoring the credit
    would stall it (and everything queued behind it) until the sharer
    retires."""
    long_prompt = PROMPT + [6, 2, 7, 1]  # 12 tokens: 2 matchable pages
    eng = Engine(ContinuousBatcher(
        PARAMS, CFG, max_batch=2, n_pages=7, page_size=4,
        max_pages_per_seq=8, prefix_cache=True,
    ))
    t1 = eng.submit(long_prompt, 4)  # 12+4=16 -> 4 pages
    eng.step()                       # t1 admitted; 2 of 6 usable pages free
    assert eng.batcher.prefix_credit(long_prompt) == 2
    t2 = eng.submit(long_prompt, 4)  # needs 4, credit 2 -> 2 fresh: fits NOW
    eng.step()
    assert eng.pending == 0          # admitted while t1 still active
    assert eng.batcher.prefix_stats["hits"] == 1
    eng.run_to_completion()
    assert eng.result(t1) == eng.result(t2) == greedy(long_prompt, 4)


def test_stats_surface():
    eng = make_engine(max_batch=1)
    t1 = eng.submit(PROMPT, 3)
    t2 = eng.submit([1, 2, 3], 3)
    eng.step()
    st = eng.stats
    assert st["active_rows"] == 1 and st["queued"] == 1
    assert st["requests_submitted"] == 2
    eng.run_to_completion()
    st = eng.stats
    assert st["requests_finished"] == 2
    assert st["tokens_generated"] == 6
    assert st["active_rows"] == 0 and st["queued"] == 0
    assert st["held_pages"] == 0
    assert eng.result(t1) and eng.result(t2)


def test_device_failure_during_admission_becomes_error_ticket():
    """Only the batcher's CapacityError requeues; any other RuntimeError
    (jaxlib's XlaRuntimeError subclasses RuntimeError — a device OOM
    during admission prefill) must reach the error-ticket path instead of
    being retried against a failing device forever."""
    eng = make_engine()

    def boom(*a, **kw):
        raise RuntimeError("INTERNAL: XLA allocation failed")

    eng.batcher.submit = boom
    t = eng.submit(PROMPT, 3)
    eng.step()  # must not spin: the failure lands on the ticket
    assert eng.is_done(t)
    assert eng.finish_reason(t) == "error"
    assert "XLA allocation failed" in eng.ticket_error(t)


def test_capacity_error_requeues_not_errors():
    """The capacity signal itself still requeues: a one-shot CapacityError
    from submit leaves the ticket queued, and it completes once the
    batcher accepts it."""
    from bee_code_interpreter_tpu.models.serving import CapacityError

    eng = make_engine()
    real_submit = eng.batcher.submit
    calls = {"n": 0}

    def flaky(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise CapacityError("page pool exhausted (transient)")
        return real_submit(*a, **kw)

    eng.batcher.submit = flaky
    t = eng.submit(PROMPT, 3)
    eng.step()
    assert not eng.is_done(t)  # requeued, not failed
    eng.run_to_completion()
    assert eng.result(t) == greedy(PROMPT, 3)


def test_engine_snapshot_resume_with_queued_requests():
    """Engine-level preemption recovery: a snapshot taken with requests
    BOTH in flight and still queued resumes on a fresh engine — queued
    tickets admit in their original priority/arrival order and every
    output equals the uninterrupted run's."""
    import pickle

    def run(interrupt: bool):
        eng = make_engine(max_batch=1)
        t0 = eng.submit(PROMPT, 4)
        t1 = eng.submit([1, 2, 3], 4)
        t2 = eng.submit([4, 5, 6], 4, priority=5)
        for _ in range(2):
            eng.step()
        if interrupt:
            snap = pickle.dumps(eng.state_dict())
            del eng
            eng = make_engine(max_batch=1)
            eng.load_state_dict(pickle.loads(snap))
            t3 = eng.submit([9, 9], 3)  # fresh ticket ids keep counting
            assert t3 > t2
        eng.run_to_completion()
        return {t: eng.result(t) for t in (t0, t1, t2)}

    assert run(interrupt=True) == run(interrupt=False)
