"""Composition-root wiring: the service must assemble executors exactly as the
operator-facing config describes (reference application_context.py:36-125).

Regression anchor: the sandbox shim (sitecustomize display patches + numpy→XLA
reroute) must be wired into the local executor by *default* — it broke silently
once because only hand-built LocalCodeExecutor fixtures passed shim_dir.
"""

from pathlib import Path

from bee_code_interpreter_tpu.application_context import ApplicationContext
from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.services.local_code_executor import LocalCodeExecutor


def _local_config(tmp_path, **overrides) -> Config:
    return Config(
        executor_backend="local",
        file_storage_path=str(tmp_path / "files"),
        local_workspace_root=str(tmp_path / "ws"),
        disable_dep_install=True,
        **overrides,
    )


def test_local_backend_gets_default_shim(tmp_path):
    ctx = ApplicationContext(_local_config(tmp_path))
    executor = ctx.code_executor
    assert isinstance(executor, LocalCodeExecutor)
    shim_dir = executor._shim_dir
    assert shim_dir is not None
    assert (Path(shim_dir) / "sitecustomize.py").is_file()


def test_shim_disabled_by_empty_string(tmp_path):
    ctx = ApplicationContext(_local_config(tmp_path, shim_dir=""))
    assert ctx.code_executor._shim_dir is None


def test_shim_disabled_via_env(tmp_path):
    # The env surface drops empty values (env_ignore_empty), so the documented
    # disable spelling is APP_SHIM_DIR=none.
    config = Config.from_env(
        {"APP_EXECUTOR_BACKEND": "local", "APP_SHIM_DIR": "none"}
    )
    assert config.resolved_shim_dir() is None


def test_shim_dir_env_override(tmp_path):
    config = Config.from_env(
        {
            "APP_EXECUTOR_BACKEND": "local",
            "APP_SHIM_DIR": str(tmp_path / "custom-shim"),
        }
    )
    assert config.resolved_shim_dir() == str(tmp_path / "custom-shim")


def test_servers_share_one_executor(tmp_path):
    ctx = ApplicationContext(_local_config(tmp_path))
    assert ctx.custom_tool_executor._code_executor is ctx.code_executor
