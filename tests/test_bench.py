"""bench.py self-diagnosis: the artifact must name the failing stage
(VERDICT r2: two rounds of BENCH_r*.json couldn't distinguish "chip absent"
from "init hung" from "payload too slow")."""

import importlib.util
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
bench = importlib.util.module_from_spec(spec)
sys.modules["bench"] = bench
spec.loader.exec_module(bench)


def test_diagnose_unreachable_backend():
    probes = [
        {"ok": False, "seconds": 75.0, "error": "jax.devices() hung past 75s",
         "at_s": 0.0},
        {"ok": False, "seconds": 75.0, "error": "jax.devices() hung past 75s",
         "at_s": 120.0},
    ]
    got = bench.diagnose_tpu_failure(probes, [])
    assert got.startswith("tpu_backend_unreachable:")
    assert "hung" in got
    assert "2 probes" in got  # patient mode: the wait itself is evidence


def test_diagnose_no_tpu_device():
    probes = [{"ok": True, "seconds": 4.2, "platform": "cpu", "device_count": 8}]
    got = bench.diagnose_tpu_failure(probes, [{"ok": False, "error": "x"}])
    assert got.startswith("no_tpu_device:")
    assert "cpu" in got


def test_diagnose_payload_timeout():
    probes = [{"ok": True, "seconds": 3.0, "platform": "tpu", "device_count": 1}]
    attempts = [
        {"ok": False, "seconds": 210.0, "error": "payload failed (exit -1)",
         "stderr_tail": "Execution timed out"},
    ]
    assert bench.diagnose_tpu_failure(probes, attempts).startswith("payload_timeout:")


def test_diagnose_payload_error():
    probes = [{"ok": True, "seconds": 3.0, "platform": "tpu", "device_count": 1}]
    attempts = [
        {"ok": False, "seconds": 12.0,
         "error": "payload failed (exit 1)",
         "stderr_tail": "RuntimeError: Mosaic compile error"},
    ]
    got = bench.diagnose_tpu_failure(probes, attempts)
    assert got.startswith("payload_error:")
    assert "exit 1" in got


def test_compact_probes_elides_long_waits_and_keeps_last_stderr():
    probes = [
        {"ok": False, "at_s": float(i), "stderr_tail": f"tail{i}"}
        for i in range(20)
    ]
    out = bench.compact_probes(probes)
    assert len(out) == 9  # 2 + elision marker + 6
    assert out[2] == {"elided_probes": 12}
    assert "stderr_tail" not in out[0]
    assert out[-1]["stderr_tail"] == "tail19"  # only the last keeps its tail
    # short histories pass through un-elided
    assert len(bench.compact_probes(probes[:3])) == 3


def _fake_values(result):
    async def fake(source, env, timeout_s, marker="RESULT_GFLOPS"):
        return list(result)

    return fake


def test_patient_capture_cpu_backend_gets_one_attempt(monkeypatch):
    # A real (non-tunnel) CPU backend: no waiting, but ONE bounded payload
    # attempt still runs — the executor's env (accelerator passthrough) is
    # not guaranteed identical to the probe's. The payload self-reports its
    # platform, so a CPU-mechanics run is never accepted as the headline.
    calls = []

    def fake_probe(timeout_s=75.0):
        calls.append(1)
        return {"ok": True, "seconds": 0.5, "platform": "cpu", "device_count": 8}

    monkeypatch.setattr(bench, "probe_tpu", fake_probe)
    monkeypatch.setattr(bench, "run_payload_values", _fake_values([98.0, 0]))
    state = {"probes": [], "attempts": []}
    assert bench.patient_tpu_capture(state, patience_s=300.0) is None
    assert len(calls) == 1
    assert state["attempts"] == [
        {"ok": False, "seconds": state["attempts"][0]["seconds"],
         "payload_platform": "cpu"}
    ]


def test_patient_capture_divergent_env_payload_wins(monkeypatch):
    # The probe sees CPU but the payload (through the executor) lands on a
    # TPU: the payload's own platform report decides the headline.
    monkeypatch.setattr(
        bench, "probe_tpu",
        lambda timeout_s=75.0: {"ok": True, "seconds": 0.5,
                                "platform": "cpu", "device_count": 8},
    )
    monkeypatch.setattr(bench, "run_payload_values", _fake_values([185000.0, 1]))
    state = {"probes": [], "attempts": []}
    assert bench.patient_tpu_capture(state, patience_s=300.0) == 185000.0
    assert state["attempts"][0]["payload_platform"] == "tpu"


def test_patient_capture_payload_first_wins_without_probing(monkeypatch):
    # Round-4 tunnel discovery: the first client must BE the measurement.
    # On a healthy chip the payload-first attempt lands the headline and NO
    # probe client ever touches the tunnel.
    def fail_probe(timeout_s=75.0):
        raise AssertionError("no probe may run when the payload lands")

    monkeypatch.setattr(bench, "probe_tpu", fail_probe)
    monkeypatch.setattr(bench, "run_payload_values", _fake_values([185000.0, 1]))
    state = {"probes": [], "attempts": []}
    assert bench.patient_tpu_capture(state, patience_s=600.0) == 185000.0
    assert state["probes"] == []
    assert state["attempts"][0]["ok"] is True


def test_patient_capture_measures_on_recovery(monkeypatch):
    # Payload-first attempt fails on the wedged tunnel; then wedged,
    # wedged, healthy probes → the payload re-runs on the healthy probe.
    # Sleeps are stubbed so the test is instant.
    seq = [
        {"ok": False, "seconds": 75.0, "error": "hung"},
        {"ok": False, "seconds": 75.0, "error": "hung"},
        {"ok": True, "seconds": 0.7, "platform": "tpu", "device_count": 1},
    ]
    monkeypatch.setattr(bench, "probe_tpu", lambda timeout_s=75.0: seq.pop(0))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    results = [bench.PayloadError("payload failed (exit -1)"), [185000.0, 1]]

    async def fake(source, env, timeout_s, marker="RESULT_GFLOPS"):
        r = results.pop(0)
        if isinstance(r, Exception):
            raise r
        return list(r)

    monkeypatch.setattr(bench, "run_payload_values", fake)
    state = {"probes": [], "attempts": []}
    got = bench.patient_tpu_capture(state, patience_s=600.0)
    assert got == 185000.0
    assert len(state["probes"]) == 3
    assert state["attempts"][0]["ok"] is False  # the payload-first attempt
    assert state["attempts"][1]["ok"] is True
    assert state["attempts"][1]["payload_platform"] == "tpu"


def test_patient_capture_respects_deadline(monkeypatch):
    # Permanently wedged tunnel: the payload-first attempt fails, the probe
    # loop must stop at the patience ceiling, not spin forever. Clock is
    # virtual (sleep/probe/payload advance it).
    now = [0.0]
    monkeypatch.setattr(bench.time, "time", lambda: now[0])

    def fake_sleep(s):
        now[0] += s

    monkeypatch.setattr(bench.time, "sleep", fake_sleep)

    def fake_probe(timeout_s=75.0):
        now[0] += 75.0
        return {"ok": False, "seconds": 75.0, "error": "hung"}

    monkeypatch.setattr(bench, "probe_tpu", fake_probe)

    async def always_wedged(source, env, timeout_s, marker="RESULT_GFLOPS"):
        now[0] += timeout_s
        raise bench.PayloadError("payload failed (exit -1)")

    monkeypatch.setattr(bench, "run_payload_values", always_wedged)
    state = {"probes": [], "attempts": []}
    assert bench.patient_tpu_capture(state, patience_s=400.0) is None
    # 75s probe + interval sleep per lap → ceiling hit, loop stops
    assert 1 <= len(state["probes"]) <= 5
    assert len(state["attempts"]) == 1  # the payload-first attempt
    assert state["attempts"][0]["ok"] is False


def test_probe_runs_against_this_interpreter():
    # Real bounded subprocess probe; under the test env (virtual CPU devices)
    # it must come back ok with a platform string, never hang the suite.
    result = bench.probe_tpu(timeout_s=120.0)
    assert result["ok"], result
    assert result["platform"] in ("cpu", "tpu")
    assert result["device_count"] >= 1


def test_payloads_are_valid_python():
    # The TPU/flash payloads only execute on a healthy chip — a syntax error
    # would otherwise surface for the first time inside the driver's window.
    for name in ("TPU_PAYLOAD", "CPU_PAYLOAD", "FLASH_PAYLOAD",
                 "SERVING_PAYLOAD"):
        compile(getattr(bench, name), f"<{name}>", "exec")


def test_run_payload_values_parses_marker_floats():
    import asyncio

    src = "print('RESULT_FLASH 12.5 3.25')"
    vals = asyncio.run(
        bench.run_payload_values(src, {}, timeout_s=30.0, marker="RESULT_FLASH")
    )
    assert vals == [12.5, 3.25]


def test_run_payload_json_parses_marker_object():
    import asyncio

    src = "import json; print('RESULT_X', json.dumps({'a': 1.5, 'b': None}))"
    got = asyncio.run(
        bench.run_payload_json(src, {}, timeout_s=30.0, marker="RESULT_X")
    )
    assert got == {"a": 1.5, "b": None}


def test_serving_payload_imports_library_code():
    # The serving phase's arithmetic lives in models/serving_bench.py and
    # is covered by the tier-1 test_serving_trace suite; this module only
    # pins the payload↔library seam (the payload runs inside a sandbox
    # whose import path is the request's PYTHONPATH, not the host's).
    assert "serving_bench import run_serving_bench" in bench.SERVING_PAYLOAD
    assert "RESULT_SERVING_JSON" in bench.SERVING_PAYLOAD


def test_benchclock_chain_diff_guard():
    # The shared chained-clock: exact difference when the chain dominates,
    # loud failure when readback-RTT jitter swamps it (a floored difference
    # would print absurd TFLOPS as evidence).
    import pytest

    from bee_code_interpreter_tpu.utils.benchclock import chain_diff

    assert abs(chain_diff(1.0, 0.1, 10) - 0.1) < 1e-12
    with pytest.raises(AssertionError, match="clock failed"):
        chain_diff(0.105, 0.100, 10)


def test_analysis_budget_guard_still_raises():
    """The warm-path < 1 ms p50 budget must stay a HARD raise with the
    accelerator classifier active — not drift into a report nobody reads
    (docs/analysis.md "Observability")."""
    import pytest

    bench.check_analysis_budget({"analysis_ms": 0.4})  # under: silent
    with pytest.raises(RuntimeError, match="analysis gate over budget"):
        bench.check_analysis_budget(
            {"analysis_ms": bench.ANALYSIS_BUDGET_MS}
        )


def test_jax_free_payload_stays_inside_analysis_budget():
    """The accelerator cost classifier is a set intersection over facts
    the one AST pass already collected — a jax-free submission (the bench
    latency payload) must stay an order of magnitude inside the 1 ms
    budget, while an accelerator payload classifies without any extra
    pass either."""
    import statistics
    import time

    from bee_code_interpreter_tpu.analysis import WorkloadAnalyzer

    analyzer = WorkloadAnalyzer()
    samples = []
    for _ in range(60):
        t0 = time.perf_counter()
        verdict = analyzer.analyze(bench.LATENCY_PAYLOAD)
        samples.append((time.perf_counter() - t0) * 1000.0)
        assert verdict.cost_class == "cheap"
    p50 = statistics.median(samples)
    assert p50 < bench.ANALYSIS_BUDGET_MS, f"analysis p50 {p50:.3f} ms"
    accel = analyzer.analyze("import jax\nprint(jax.devices())\n")
    assert accel.cost_class == "accelerator"
