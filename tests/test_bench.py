"""bench.py self-diagnosis: the artifact must name the failing stage
(VERDICT r2: two rounds of BENCH_r*.json couldn't distinguish "chip absent"
from "init hung" from "payload too slow")."""

import importlib.util
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
bench = importlib.util.module_from_spec(spec)
sys.modules["bench"] = bench
spec.loader.exec_module(bench)


def test_diagnose_unreachable_backend():
    probe = {"ok": False, "seconds": 75.0, "error": "jax.devices() hung past 75s"}
    got = bench.diagnose_tpu_failure(probe, [])
    assert got.startswith("tpu_backend_unreachable:")
    assert "hung" in got


def test_diagnose_no_tpu_device():
    probe = {"ok": True, "seconds": 4.2, "platform": "cpu", "device_count": 8}
    got = bench.diagnose_tpu_failure(probe, [{"ok": False, "error": "x"}])
    assert got.startswith("no_tpu_device:")
    assert "cpu" in got


def test_diagnose_payload_timeout():
    probe = {"ok": True, "seconds": 3.0, "platform": "tpu", "device_count": 1}
    attempts = [
        {"ok": False, "seconds": 210.0, "error": "payload failed (exit -1)",
         "stderr_tail": "Execution timed out"},
    ]
    assert bench.diagnose_tpu_failure(probe, attempts).startswith("payload_timeout:")


def test_diagnose_payload_error():
    probe = {"ok": True, "seconds": 3.0, "platform": "tpu", "device_count": 1}
    attempts = [
        {"ok": False, "seconds": 12.0,
         "error": "payload failed (exit 1)",
         "stderr_tail": "RuntimeError: Mosaic compile error"},
    ]
    got = bench.diagnose_tpu_failure(probe, attempts)
    assert got.startswith("payload_error:")
    assert "exit 1" in got


def test_probe_runs_against_this_interpreter():
    # Real bounded subprocess probe; under the test env (virtual CPU devices)
    # it must come back ok with a platform string, never hang the suite.
    result = bench.probe_tpu(timeout_s=120.0)
    assert result["ok"], result
    assert result["platform"] in ("cpu", "tpu")
    assert result["device_count"] >= 1


def test_payloads_are_valid_python():
    # The TPU/flash payloads only execute on a healthy chip — a syntax error
    # would otherwise surface for the first time inside the driver's window.
    for name in ("TPU_PAYLOAD", "CPU_PAYLOAD", "FLASH_PAYLOAD"):
        compile(getattr(bench, name), f"<{name}>", "exec")


def test_run_payload_values_parses_marker_floats():
    import asyncio

    src = "print('RESULT_FLASH 12.5 3.25')"
    vals = asyncio.run(
        bench.run_payload_values(src, {}, timeout_s=30.0, marker="RESULT_FLASH")
    )
    assert vals == [12.5, 3.25]


def test_benchclock_chain_diff_guard():
    # The shared chained-clock: exact difference when the chain dominates,
    # loud failure when readback-RTT jitter swamps it (a floored difference
    # would print absurd TFLOPS as evidence).
    import pytest

    from bee_code_interpreter_tpu.utils.benchclock import chain_diff

    assert abs(chain_diff(1.0, 0.1, 10) - 0.1) < 1e-12
    with pytest.raises(AssertionError, match="clock failed"):
        chain_diff(0.105, 0.100, 10)
