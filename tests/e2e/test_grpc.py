"""gRPC e2e suite against the live service (reference test/e2e/test_grpc.py).

Mirrors the HTTP suite plus the wire details the reference asserts: oneof
success/error dispatch on the tool RPCs (test_grpc.py:136, :236, :253) and exact
JSON encoding of tool outputs ("3", "\"The year is 2000\"" :254, :271).
"""

from __future__ import annotations

import json
from pathlib import Path

import grpc.aio
import pytest

from bee_code_interpreter_tpu.api.grpc_server import service_stubs
from bee_code_interpreter_tpu.proto import code_interpreter_pb2 as pb

EXAMPLES = Path(__file__).resolve().parent.parent.parent / "examples"


@pytest.fixture
def grpc_addr(service):
    return service.grpc_addr


async def call(addr, method, request):
    async with grpc.aio.insecure_channel(addr) as channel:
        return await service_stubs(channel)[method](request, timeout=120)


async def test_execute(grpc_addr):
    response = await call(
        grpc_addr, "Execute", pb.ExecuteRequest(source_code="print(21 * 2)")
    )
    assert response.stdout == "42\n"
    assert response.exit_code == 0


async def test_imports(grpc_addr):
    # Reference test_grpc.py:64 reads the example payload from disk.
    response = await call(
        grpc_addr,
        "Execute",
        pb.ExecuteRequest(source_code=(EXAMPLES / "using_imports.py").read_text()),
    )
    assert response.stderr == ""
    assert response.exit_code == 0


async def test_per_request_timeout(grpc_addr):
    response = await call(
        grpc_addr,
        "Execute",
        pb.ExecuteRequest(source_code="import time\ntime.sleep(30)", timeout=0.5),
    )
    assert response.exit_code == -1
    assert response.stderr == "Execution timed out"


async def test_file_round_trip(grpc_addr):
    response = await call(
        grpc_addr,
        "Execute",
        pb.ExecuteRequest(
            source_code='with open("data.txt", "w") as f:\n    f.write("round-trip")'
        ),
    )
    assert response.exit_code == 0
    assert "/workspace/data.txt" in response.files

    response = await call(
        grpc_addr,
        "Execute",
        pb.ExecuteRequest(
            source_code='print(open("data.txt").read())',
            files=dict(response.files),
        ),
    )
    assert response.stdout == "round-trip\n"


async def test_env_passthrough(grpc_addr):
    # Parity improvement over the reference: its gRPC servicer silently drops
    # `env` (code_interpreter_servicer.py:67-70); ours forwards it like HTTP.
    response = await call(
        grpc_addr,
        "Execute",
        pb.ExecuteRequest(
            source_code='import os; print(os.environ["GRPC_VAR"])',
            env={"GRPC_VAR": "via-grpc"},
        ),
    )
    assert response.stdout == "via-grpc\n"


async def test_parse_custom_tool_oneof_success(grpc_addr):
    response = await call(
        grpc_addr,
        "ParseCustomTool",
        pb.ParseCustomToolRequest(
            tool_source_code='''
def current_weather(lat: float, lon: float):
    """
    Get the current weather at a location.

    :param lat: A latitude.
    :param lon: A longitude.
    :return: A dictionary with the current weather.
    """
    return {"lat": lat, "lon": lon}
'''
        ),
    )
    assert response.WhichOneof("response") == "success"
    assert response.success.tool_name == "current_weather"
    schema = json.loads(response.success.tool_input_schema_json)
    assert schema["required"] == ["lat", "lon"]


async def test_parse_custom_tool_oneof_error(grpc_addr):
    response = await call(
        grpc_addr,
        "ParseCustomTool",
        pb.ParseCustomToolRequest(
            tool_source_code="def my_tool(a, /, b, *args, **kwargs) -> int:\n  return 1"
        ),
    )
    assert response.WhichOneof("response") == "error"
    assert set(response.error.error_messages) == {
        "The tool function must not have positional-only arguments",
        "The tool function must not have *args",
        "The tool function must not have **kwargs",
        "The tool function arguments must have type annotations",
    }


async def test_execute_custom_tool_exact_json(grpc_addr):
    # Reference test_grpc.py:254 asserts the literal string "3".
    response = await call(
        grpc_addr,
        "ExecuteCustomTool",
        pb.ExecuteCustomToolRequest(
            tool_source_code="def adding_tool(a: int, b: int) -> int:\n  return a + b",
            tool_input_json='{"a": 1, "b": 2}',
        ),
    )
    assert response.WhichOneof("response") == "success"
    assert response.success.tool_output_json == "3"


async def test_execute_custom_tool_datetime(grpc_addr):
    # Reference test_grpc.py:271 asserts "\"The year is 2000\"".
    response = await call(
        grpc_addr,
        "ExecuteCustomTool",
        pb.ExecuteCustomToolRequest(
            tool_source_code=(
                "import datetime\n"
                "def year_tool(when: datetime.datetime) -> str:\n"
                '    return f"The year is {when.year}"'
            ),
            tool_input_json='{"when": "2000-01-01T00:00:00"}',
        ),
    )
    assert response.WhichOneof("response") == "success"
    assert response.success.tool_output_json == '"The year is 2000"'


async def test_execute_custom_tool_oneof_error(grpc_addr):
    response = await call(
        grpc_addr,
        "ExecuteCustomTool",
        pb.ExecuteCustomToolRequest(
            tool_source_code="def boom() -> int:\n  raise ValueError('it broke')",
            tool_input_json="{}",
        ),
    )
    assert response.WhichOneof("response") == "error"
    assert "it broke" in response.error.stderr


async def test_execute_custom_tool_indented_source(grpc_addr):
    # Parity with the HTTP case: uniformly indented tool source dedents
    # (reference custom_tool_executor.py:59).
    response = await call(
        grpc_addr,
        "ExecuteCustomTool",
        pb.ExecuteCustomToolRequest(
            tool_source_code=(
                "    def doubler(a: int) -> int:\n        return a * 2"
            ),
            tool_input_json='{"a": 21}',
        ),
    )
    assert response.WhichOneof("response") == "success"
    assert response.success.tool_output_json == "42"
