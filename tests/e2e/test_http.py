"""HTTP e2e suite against the live service (reference test/e2e/test_http.py).

Coverage mirrors the reference behavior-for-behavior (SURVEY.md §4): preinstalled
imports, workspace file round-trip across two executions, env passthrough, custom
tool parse/execute happy paths, parse errors as 400 with the exact message set,
tool runtime errors surfaced as 400 stderr, tool env. The on-the-fly pip-install
case (reference test_http.py:34-44, cowsay) is exercised at the unit layer
against a fake index — this environment has no network egress.
"""

from __future__ import annotations

import json
from pathlib import Path

import httpx
import pytest

EXAMPLES = Path(__file__).resolve().parent.parent.parent / "examples"


@pytest.fixture(scope="module")
def client(service):
    with httpx.Client(base_url=service.http_url, timeout=120) as c:
        yield c


def test_healthz(client):
    assert client.get("/healthz").json() == {"status": "ok"}


def test_imports(client):
    # Reference test_http.py:23-31 reads examples/using_imports.py from disk.
    response = client.post(
        "/v1/execute",
        json={"source_code": (EXAMPLES / "using_imports.py").read_text()},
    )
    response.raise_for_status()
    result = response.json()
    assert result["stderr"] == ""
    assert result["exit_code"] == 0


def test_create_file_in_interpreter(client):
    # Reference test_http.py:47-85: files written by one execution come back as
    # {path: id} and can be re-mounted into a later execution.
    file_content = "Hello, World!"
    response = client.post(
        "/v1/execute",
        json={
            "source_code": f'''
with open("file.txt", "w") as f:
    f.write("{file_content}")
''',
        },
    )
    response.raise_for_status()
    result = response.json()
    assert result["exit_code"] == 0
    assert "/workspace/file.txt" in result["files"]

    response = client.post(
        "/v1/execute",
        json={
            "source_code": '''
with open("file.txt", "r") as f:
    print(f.read())
''',
            "files": result["files"],
        },
    )
    response.raise_for_status()
    result = response.json()
    assert result["stdout"] == file_content + "\n"
    # Reading a file does not re-snapshot it (ctime/mtime unchanged).
    assert result["files"] == {}


def test_hello_world_examples_round_trip(client):
    # The hello_world example pair as payloads: write_file snapshots
    # example.txt, read_file restores it via the files map in a second
    # execution (reference examples/hello_world_{write,read}_file.py).
    response = client.post(
        "/v1/execute",
        json={"source_code": (EXAMPLES / "hello_world_write_file.py").read_text()},
    )
    response.raise_for_status()
    result = response.json()
    assert result["exit_code"] == 0
    assert "/workspace/example.txt" in result["files"]

    response = client.post(
        "/v1/execute",
        json={
            "source_code": (EXAMPLES / "hello_world_read_file.py").read_text(),
            "files": result["files"],
        },
    )
    response.raise_for_status()
    result = response.json()
    assert result["stdout"] == "Hello, world! How are you?\n"
    assert result["exit_code"] == 0


def test_env_passthrough(client):
    # Reference test_http.py:88-99.
    response = client.post(
        "/v1/execute",
        json={
            "source_code": 'import os; print(os.environ["TEST_VAR"])',
            "env": {"TEST_VAR": "hello-from-env"},
        },
    )
    response.raise_for_status()
    result = response.json()
    assert result["stdout"] == "hello-from-env\n"
    assert result["exit_code"] == 0


def test_torch_runs_in_sandbox(client):
    # torch (CPU build here; torch-xla in the TPU image) must work out of the
    # box — the shim's torch patch only engages when torch_xla is importable.
    # The local-backend sandbox shares this venv, so importorskip is an exact
    # availability proxy (CI installs no torch).
    pytest.importorskip("torch")
    response = client.post(
        "/v1/execute",
        json={
            "source_code": (
                "import torch\n"
                "x = torch.arange(6, dtype=torch.float32).reshape(2, 3)\n"
                "print(int((x @ x.T).diag().sum().item()))"
            ),
            "timeout": 120,
        },
    )
    response.raise_for_status()
    result = response.json()
    assert result["exit_code"] == 0, result["stderr"]
    assert result["stdout"] == "55\n"


def test_per_request_timeout(client):
    # New over the reference: its executor had the timeout field but the
    # service never exposed it (server.rs:32). Clamped to the configured max.
    response = client.post(
        "/v1/execute",
        json={"source_code": "import time\ntime.sleep(30)", "timeout": 0.5},
    )
    response.raise_for_status()
    result = response.json()
    assert result["exit_code"] == -1
    assert result["stderr"] == "Execution timed out"


def test_nonzero_exit(client):
    response = client.post(
        "/v1/execute",
        json={"source_code": (EXAMPLES / "crash.py").read_text()},
    )
    response.raise_for_status()
    result = response.json()
    assert result["exit_code"] != 0
    assert result["stderr"] != ""


def test_parse_custom_tool(client):
    # Reference test_http.py:103-221 (happy path with typing + docstring).
    response = client.post(
        "/v1/parse-custom-tool",
        json={
            "tool_source_code": '''
def current_weather(lat: float, lon: float):
    """
    Get the current weather at a location.

    :param lat: A latitude.
    :param lon: A longitude.
    :return: A dictionary with the current weather.
    """
    return {"lat": lat, "lon": lon}
'''
        },
    )
    response.raise_for_status()
    tool = response.json()
    assert tool["tool_name"] == "current_weather"
    assert tool["tool_description"] == (
        "Get the current weather at a location.\n\n"
        "Returns: A dictionary with the current weather."
    )
    schema = json.loads(tool["tool_input_schema_json"])
    assert schema["properties"]["lat"] == {"type": "number", "description": "A latitude."}
    assert schema["required"] == ["lat", "lon"]


def test_parse_custom_tool_error(client):
    # Reference test_http.py:257-271: 400 with the exact message set.
    response = client.post(
        "/v1/parse-custom-tool",
        json={"tool_source_code": "def my_tool(a, /, b, *args, **kwargs) -> int:\n  return 1"},
    )
    assert response.status_code == 400
    assert set(response.json()["error_messages"]) == {
        "The tool function must not have positional-only arguments",
        "The tool function must not have *args",
        "The tool function must not have **kwargs",
        "The tool function arguments must have type annotations",
    }


def test_execute_custom_tool(client):
    # Reference test_http.py:224-254.
    response = client.post(
        "/v1/execute-custom-tool",
        json={
            "tool_source_code": "def adding_tool(a: int, b: int) -> int:\n  return a + b",
            "tool_input_json": '{"a": 1, "b": 2}',
        },
    )
    response.raise_for_status()
    assert response.json()["tool_output_json"] == "3"


def test_execute_custom_tool_indented_source(client):
    # Uniformly indented tool source (an agent lifting a method out of a
    # class) must dedent-parse and execute — reference
    # custom_tool_executor.py:59 textwrap.dedent behavior.
    response = client.post(
        "/v1/execute-custom-tool",
        json={
            "tool_source_code": (
                "    def doubler(a: int) -> int:\n        return a * 2"
            ),
            "tool_input_json": '{"a": 21}',
        },
    )
    response.raise_for_status()
    assert response.json()["tool_output_json"] == "42"


def test_execute_custom_tool_datetime_coercion(client):
    response = client.post(
        "/v1/execute-custom-tool",
        json={
            "tool_source_code": '''
import datetime

def year_tool(when: datetime.datetime) -> str:
    return f"The year is {when.year}"
''',
            "tool_input_json": '{"when": "2000-01-01T00:00:00"}',
        },
    )
    response.raise_for_status()
    assert response.json()["tool_output_json"] == '"The year is 2000"'


def test_execute_custom_tool_runtime_error(client):
    # Reference test_http.py:274-285: tool raising → 400 with stderr.
    response = client.post(
        "/v1/execute-custom-tool",
        json={
            "tool_source_code": "def boom() -> int:\n  raise ValueError('it broke')",
            "tool_input_json": "{}",
        },
    )
    assert response.status_code == 400
    assert "it broke" in response.json()["stderr"]


def test_execute_custom_tool_env(client):
    # Reference test_http.py:288-302.
    response = client.post(
        "/v1/execute-custom-tool",
        json={
            "tool_source_code": '''
import os

def env_tool() -> str:
    return os.environ["TOOL_VAR"]
''',
            "tool_input_json": "{}",
            "env": {"TOOL_VAR": "tool-env-value"},
        },
    )
    response.raise_for_status()
    assert response.json()["tool_output_json"] == '"tool-env-value"'


def test_session_lease_checkpoint_rollback(client):
    # Sessions (docs/sessions.md) against the LIVE service: one lease,
    # executions sharing workspace state, checkpoint + rollback, release.
    response = client.post("/v1/sessions", json={})
    response.raise_for_status()
    created = response.json()
    sid = created["session_id"]
    try:
        response = client.post(
            f"/v1/sessions/{sid}/execute",
            json={"source_code": "open('s.txt', 'w').write('v1')\nprint('one')"},
        )
        response.raise_for_status()
        result = response.json()
        assert result["stdout"] == "one\n"
        assert result["changed_paths"] == ["/workspace/s.txt"]

        checkpoint = client.post(f"/v1/sessions/{sid}/checkpoint").json()
        assert list(checkpoint["files"]) == ["/workspace/s.txt"]

        client.post(
            f"/v1/sessions/{sid}/execute",
            json={"source_code": "open('s.txt', 'w').write('v2')"},
        ).raise_for_status()
        client.post(
            f"/v1/sessions/{sid}/rollback",
            json={"checkpoint_id": checkpoint["checkpoint_id"]},
        ).raise_for_status()

        response = client.post(
            f"/v1/sessions/{sid}/execute",
            json={"source_code": "print(open('s.txt').read())"},
        )
        response.raise_for_status()
        assert response.json()["stdout"] == "v1\n"
    finally:
        assert client.delete(f"/v1/sessions/{sid}").status_code == 200
    response = client.post(
        f"/v1/sessions/{sid}/execute", json={"source_code": "print(1)"}
    )
    assert response.status_code == 404


def test_execute_stream_sse(client):
    # Streaming (docs/sessions.md): stdout chunks arrive before the
    # terminal result event, whose envelope matches the buffered path.
    events: list[tuple[str, dict]] = []
    with client.stream(
        "POST",
        "/v1/execute?stream=1",
        json={
            "source_code": (
                "import time\n"
                "print('first', flush=True)\n"
                "time.sleep(0.3)\n"
                "print('second', flush=True)\n"
            )
        },
    ) as response:
        assert response.status_code == 200
        assert response.headers["content-type"].startswith("text/event-stream")
        event = None
        for line in response.iter_lines():
            if line.startswith("event: "):
                event = line[len("event: "):]
            elif line.startswith("data: "):
                events.append((event, json.loads(line[len("data: "):])))
    stdout_chunks = [d["text"] for e, d in events if e == "stdout"]
    # >=1 chunk on every backend: the native C++ executor predates the
    # stream route and degrades to one buffered chunk (docs/sessions.md);
    # the genuinely-chunked >=2 acceptance runs tier-1 over the fake-pod
    # stack (tests/test_sessions.py), whose pods are the Python server.
    assert len(stdout_chunks) >= 1, events
    assert events[-1][0] == "result"
    result = events[-1][1]
    assert result["exit_code"] == 0
    assert result["stdout"] == "first\nsecond\n"
    assert "".join(stdout_chunks) == result["stdout"]
