"""Multi-host pod GROUPS behind the live service.

The reference's operational unit is one pod per execution; this rebuild's
kubernetes executor schedules pod *groups* (one executor per TPU host of a
slice, SURVEY.md §2 parallelism). Here the REAL service runs with
``tpu_hosts_per_slice=2`` against the fake cluster CLI, so every execution
gang-spawns two real executor processes: worker-0 first (its pod IP becomes
the baked-in jax.distributed coordinator address), then worker-1; the
execute fans out SPMD to both; stdout is worker 0's; changed files are the
union across the gang."""

from __future__ import annotations

import json
import time
from pathlib import Path

import httpx
import pytest

from tests.e2e.conftest import booted_service


@pytest.fixture(scope="module")
def gang_service(tmp_path_factory, native_binary):
    if native_binary is None:
        pytest.skip("native toolchain unavailable")
    tmp = tmp_path_factory.mktemp("e2e-gang")
    overrides = {
        "APP_EXECUTOR_BACKEND": "kubernetes",
        "APP_KUBECTL_PATH": str(Path(__file__).parent / "fake_kubectl.py"),
        "APP_EXECUTOR_POD_QUEUE_TARGET_LENGTH": "1",
        "APP_POD_READY_TIMEOUT_S": "30",
        "APP_TPU_HOSTS_PER_SLICE": "2",
        "FAKE_KUBECTL_STATE": str(tmp / "cluster"),
        "FAKE_KUBECTL_EXECUTOR_BINARY": str(native_binary),
    }
    with booted_service(tmp, overrides) as svc:
        yield svc, tmp / "cluster"


def test_gang_executes_and_reports_worker0_stdout(gang_service):
    service, cluster = gang_service
    r = httpx.post(
        f"{service.http_url}/v1/execute",
        json={"source_code":
              "import os\nprint('worker', os.environ.get('TPU_WORKER_ID'))"},
        timeout=120,
    )
    r.raise_for_status()
    body = r.json()
    assert body["exit_code"] == 0
    # SPMD fan-out ran on both workers; the response carries worker 0's IO
    assert body["stdout"] == "worker 0\n"


def test_gang_spawns_pairs_with_baked_coordinator(gang_service):
    service, cluster = gang_service
    # force at least one execution so pod records exist and rotate
    httpx.post(
        f"{service.http_url}/v1/execute",
        json={"source_code": "print(1)"}, timeout=120,
    ).raise_for_status()
    # warm pool refills with fresh groups: inspect the recorded manifests
    deadline = time.monotonic() + 30
    workers = {}
    while time.monotonic() < deadline:
        workers = {}
        for rec in cluster.glob("pod-*.json"):
            data = json.loads(rec.read_text())
            env = {e["name"]: e["value"]
                   for e in data["manifest"]["spec"]["containers"][0]["env"]}
            workers.setdefault(env.get("TPU_WORKER_ID"), []).append(
                (data, env)
            )
        if workers.get("0") and workers.get("1"):
            break
        time.sleep(0.5)
    assert workers.get("0") and workers.get("1"), "no full gang alive"
    # every worker knows the gang size...
    for _, env in workers["0"] + workers["1"]:
        assert env["JAX_NUM_PROCESSES"] == "2"
    # ...and worker-1's coordinator address is worker-0's ACTUAL pod IP
    w0_ips = {data["ip"] for data, _ in workers["0"]}
    for _, env in workers["1"]:
        coord_ip = env["JAX_COORDINATOR_ADDRESS"].split(":")[0]
        assert coord_ip in w0_ips


def test_gang_union_file_downloads(gang_service):
    service, cluster = gang_service
    # each worker writes a distinct file; the snapshot must carry BOTH
    # (per-host outputs exist only on their writer)
    r = httpx.post(
        f"{service.http_url}/v1/execute",
        json={"source_code":
              "import os\n"
              "w = os.environ.get('TPU_WORKER_ID', '0')\n"
              "open(f'out-{w}.txt', 'w').write(f'from {w}')\n"
              "print('ok')"},
        timeout=120,
    )
    r.raise_for_status()
    body = r.json()
    assert body["exit_code"] == 0
    assert set(body["files"]) == {"/workspace/out-0.txt", "/workspace/out-1.txt"}
    # round-trip: restore both into a fresh gang and read them back
    r2 = httpx.post(
        f"{service.http_url}/v1/execute",
        json={"source_code":
              "print(open('out-0.txt').read(), open('out-1.txt').read())",
              "files": body["files"]},
        timeout=120,
    )
    r2.raise_for_status()
    assert r2.json()["stdout"] == "from 0 from 1\n"
