"""Live-service e2e harness.

Mirrors the reference's top test layer (SURVEY.md §4): a *real* service process
listening on real sockets, gated on the gRPC health check before any test runs
(the reference's `poe test` runs health_check.py then pytest,
pyproject.toml:42-44), then HTTP and gRPC parity suites (reference
test/e2e/test_http.py, test_grpc.py). The reference requires a deployed k8s
cluster + port-forward for this; here the service boots with the local executor
backend so the suite is self-contained and runs in CI.
"""

from __future__ import annotations

import asyncio
import os
import socket
import subprocess
import sys
import time
from contextlib import contextmanager
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent.parent
EXAMPLES = REPO / "examples"


from bee_code_interpreter_tpu.services.native_process_code_executor import (
    _free_port,
)


class Service:
    def __init__(self, http_port: int, grpc_port: int, proc: subprocess.Popen, log: Path):
        self.http_url = f"http://127.0.0.1:{http_port}"
        self.grpc_addr = f"127.0.0.1:{grpc_port}"
        self.proc = proc
        self.log = log


@contextmanager
def booted_service(tmp: Path, env_overrides: dict[str, str]):
    """Boot the real service, gate on the gRPC health check (exactly like
    the reference's `poe test`), yield a :class:`Service`, tear down. When
    the overrides carry ``FAKE_KUBECTL_STATE``, any detached fake-cluster
    pods the service didn't get to delete are swept at exit (a real cluster
    outlives its clients; the fake must not leak processes)."""
    http_port, grpc_port = _free_port(), _free_port()
    log_path = tmp / "service.log"
    env = dict(os.environ)
    env.update(
        APP_HTTP_LISTEN_ADDR=f"127.0.0.1:{http_port}",
        APP_GRPC_LISTEN_ADDR=f"127.0.0.1:{grpc_port}",
        APP_FILE_STORAGE_PATH=str(tmp / "files"),
        APP_DISABLE_DEP_INSTALL="1",
        # Sandbox subprocesses must stay on the virtual CPU mesh in CI.
        JAX_PLATFORMS="cpu",
    )
    env.update(env_overrides)
    log = open(log_path, "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "bee_code_interpreter_tpu"],
        cwd=REPO,
        env=env,
        stdout=log,
        stderr=subprocess.STDOUT,
    )

    from bee_code_interpreter_tpu import health_check

    deadline = time.monotonic() + 60
    last_error: Exception | None = None
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            break
        try:
            asyncio.run(health_check.check(f"127.0.0.1:{grpc_port}"))
            last_error = None
            break
        except Exception as e:  # noqa: BLE001 - retried until deadline
            last_error = e
            time.sleep(0.5)
    else:
        last_error = last_error or TimeoutError("health check never passed")
    if proc.poll() is not None or last_error is not None:
        proc.terminate()
        proc.wait(timeout=10)
        log.close()
        pytest.fail(
            f"service failed to become healthy: {last_error!r}\n"
            f"--- service log ---\n{log_path.read_text(errors='replace')}"
        )

    try:
        yield Service(http_port, grpc_port, proc, log_path)
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        log.close()
        cluster = env_overrides.get("FAKE_KUBECTL_STATE")
        if cluster:
            import json as _json
            import signal as _signal

            for rec_path in Path(cluster).glob("pod-*.json"):
                try:
                    pid = _json.loads(rec_path.read_text())["pid"]
                    os.killpg(os.getpgid(pid), _signal.SIGKILL)
                except (OSError, ValueError, KeyError):
                    pass


# The whole e2e suite runs once per backend: the pure-Python in-process
# executor, (toolchain permitting) the native C++ executor-server pool, and
# the REAL kubernetes executor fronted by a fake cluster CLI
# (fake_kubectl.py) whose "pods" are native executor processes on distinct
# loopback IPs — all must present identical behavior through the service API.
@pytest.fixture(scope="session", params=["python", "native", "kubernetes"])
def service(request, tmp_path_factory, native_binary):
    tmp = tmp_path_factory.mktemp(f"e2e-{request.param}")
    overrides = {
        "APP_EXECUTOR_BACKEND": "local",
        "APP_LOCAL_WORKSPACE_ROOT": str(tmp / "workspaces"),
    }
    if request.param == "native":
        if native_binary is None:
            pytest.skip("native toolchain unavailable")
        overrides["APP_LOCAL_EXECUTOR_BINARY"] = str(native_binary)
        # Keep warm-pool startup cheap for the test session.
        overrides["APP_EXECUTOR_POD_QUEUE_TARGET_LENGTH"] = "2"
    if request.param == "kubernetes":
        if native_binary is None:
            pytest.skip("native toolchain unavailable")
        overrides.update(
            APP_EXECUTOR_BACKEND="kubernetes",
            APP_KUBECTL_PATH=str(Path(__file__).parent / "fake_kubectl.py"),
            APP_EXECUTOR_POD_QUEUE_TARGET_LENGTH="2",
            # wait --for=condition=Ready polls /healthz; pods boot in ~ms
            APP_POD_READY_TIMEOUT_S="30",
            FAKE_KUBECTL_STATE=str(tmp / "cluster"),
            FAKE_KUBECTL_EXECUTOR_BINARY=str(native_binary),
        )
    with booted_service(tmp, overrides) as svc:
        yield svc
