#!/usr/bin/env python3
"""A ``kubectl`` CLI impostor backed by real native executor processes.

The e2e suite boots the ACTUAL service with ``APP_EXECUTOR_BACKEND=kubernetes``
and ``APP_KUBECTL_PATH`` pointing here — the full KubernetesCodeExecutor code
path (manifest build, gang spawn, ``wait --for=condition=Ready``, pod-IP
addressing, delete-on-failure) runs unmodified, while "pods" are
executor-server processes bound to distinct loopback IPs (Linux routes all of
127/8 to lo, so every pod keeps the REAL ``podIP:executor_port`` addressing).

Implements exactly the subcommand surface services/kubectl.py emits:

    create -f - --output=json     spawn a pod process from the stdin manifest
    wait pod/N --for=... --timeout=..s   poll the pod's /healthz
    get pod N --output=json       pod JSON with status.podIP
    delete pod N ...              kill the process

State (pod records, IP allocator) lives under $FAKE_KUBECTL_STATE; the
executor binary comes from $FAKE_KUBECTL_EXECUTOR_BINARY.
"""

from __future__ import annotations

import fcntl
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

STATE = Path(os.environ["FAKE_KUBECTL_STATE"])
BINARY = os.environ["FAKE_KUBECTL_EXECUTOR_BINARY"]


def flags_and_args(argv: list[str]) -> tuple[dict[str, str], list[str]]:
    flags, args = {}, []
    for a in argv:
        if a.startswith("--"):
            key, _, value = a[2:].partition("=")
            flags[key] = value
        else:
            args.append(a)
    return flags, args


def record_path(name: str) -> Path:
    return STATE / f"pod-{name}.json"


def alloc_ip() -> str:
    """Next unused loopback IP (127.1.x.y), under an exclusive lock."""
    counter = STATE / "ip-counter"
    with open(STATE / ".lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        n = int(counter.read_text()) if counter.exists() else 0
        counter.write_text(str(n + 1))
    n += 2  # start at 127.1.0.2
    return f"127.1.{n // 256}.{n % 256}"


def pod_json(name: str, ip: str, phase: str = "Running") -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "uid": f"fake-uid-{name}"},
        "status": {
            "podIP": ip,
            "phase": phase,
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def create() -> int:
    manifest = json.loads(sys.stdin.read())
    name = manifest["metadata"]["name"]
    container = manifest["spec"]["containers"][0]
    env = {e["name"]: e["value"] for e in container.get("env", [])}
    ip = alloc_ip()
    port = env.get("APP_LISTEN_ADDR", "0.0.0.0:8000").rsplit(":", 1)[1]
    workspace = STATE / "ws" / name
    workspace.mkdir(parents=True, exist_ok=True)
    env.update(
        APP_LISTEN_ADDR=f"{ip}:{port}",
        APP_WORKSPACE=str(workspace),
        APP_DISABLE_DEP_INSTALL="1",
        PATH=os.environ.get("PATH", "/usr/bin:/bin"),
        JAX_PLATFORMS="cpu",
    )
    log = open(STATE / f"pod-{name}.log", "wb")
    proc = subprocess.Popen(
        [BINARY], env=env, stdout=log, stderr=subprocess.STDOUT,
        start_new_session=True,  # survives this kubectl process exiting
    )
    record = {"name": name, "ip": ip, "port": int(port), "pid": proc.pid,
              "manifest": manifest}
    record_path(name).write_text(json.dumps(record))
    print(json.dumps(pod_json(name, ip, phase="Pending")))
    return 0


def wait(args: list[str], flags: dict[str, str]) -> int:
    target = args[0]  # "pod/NAME"
    name = target.split("/", 1)[1]
    timeout = float(flags.get("timeout", "60s").rstrip("s"))
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        path = record_path(name)
        if path.exists():
            rec = json.loads(path.read_text())
            try:
                with urllib.request.urlopen(
                    f"http://{rec['ip']}:{rec['port']}/healthz", timeout=1
                ) as resp:
                    if resp.status == 200:
                        print(json.dumps(pod_json(name, rec["ip"])))
                        return 0
            except (urllib.error.URLError, OSError):
                pass
        time.sleep(0.1)
    print(f"error: timed out waiting for the condition on {target}",
          file=sys.stderr)
    return 1


def get(args: list[str]) -> int:
    kind, name = args[0], args[1]
    if kind != "pod":
        print(f"error: unsupported kind {kind}", file=sys.stderr)
        return 1
    path = record_path(name)
    if not path.exists():
        print(f'Error from server (NotFound): pods "{name}" not found',
              file=sys.stderr)
        return 1
    rec = json.loads(path.read_text())
    print(json.dumps(pod_json(name, rec["ip"])))
    return 0


def delete(args: list[str], flags: dict[str, str]) -> int:
    kind, name = args[0], args[1]
    path = record_path(name)
    if not path.exists():
        if flags.get("ignore-not-found") == "true":
            print("{}")
            return 0
        print(f'Error from server (NotFound): pods "{name}" not found',
              file=sys.stderr)
        return 1
    rec = json.loads(path.read_text())
    try:
        os.killpg(os.getpgid(rec["pid"]), signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    path.unlink(missing_ok=True)
    print(json.dumps({"kind": "Status", "status": "Success"}))
    return 0


def main() -> int:
    STATE.mkdir(parents=True, exist_ok=True)
    command = sys.argv[1]
    flags, args = flags_and_args(sys.argv[2:])
    if command == "create":
        return create()
    if command == "wait":
        return wait(args, flags)
    if command == "get":
        return get(args)
    if command == "delete":
        return delete(args, flags)
    print(f"error: fake kubectl does not implement {command!r}",
          file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
