"""Speculative decoding: exactness vs target-greedy for any draft."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models.speculative import speculative_generate


def cfg(**kw):
    return dataclasses.replace(
        T.TransformerConfig.tiny(), dtype=jnp.float32, **kw
    )


def test_perfect_draft_matches_target_greedy():
    # draft == target: every proposal is accepted; output must equal the
    # target's own greedy decode exactly.
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, config.vocab_size)

    want = T.Transformer(config).generate_cached(params, prompt, max_new_tokens=9)
    got = speculative_generate(
        params, config, params, config, prompt, max_new_tokens=9, gamma=3
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_unrelated_draft_still_exact():
    # Exactness is draft-independent: a random different-architecture draft
    # (fewer layers, different d_model) must yield the same tokens as the
    # target's greedy decode — the draft only changes the round count.
    config = cfg(n_kv_heads=2)
    draft_config = cfg(n_layers=1, d_model=32, n_heads=2, d_ff=64)
    params = T.init_params(config, jax.random.PRNGKey(0))
    draft_params = T.init_params(draft_config, jax.random.PRNGKey(42))
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 5), 0, config.vocab_size)

    want = T.Transformer(config).generate_cached(params, prompt, max_new_tokens=8)
    for gamma in (1, 2, 4):
        got = speculative_generate(
            params, config, draft_params, draft_config, prompt,
            max_new_tokens=8, gamma=gamma,
        )
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want), err_msg=f"gamma={gamma}"
        )


def test_single_token_and_window_overrun():
    # max_new_tokens smaller than gamma exercises the padded-buffer path
    # (fixed-width window writes near the end of the buffer).
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 4), 0, config.vocab_size)
    want = T.Transformer(config).generate_cached(params, prompt, max_new_tokens=2)
    got = speculative_generate(
        params, config, params, config, prompt, max_new_tokens=2, gamma=5
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_vocab_mismatch_rejected():
    config = cfg()
    draft_config = cfg(vocab_size=128)
    params = T.init_params(config, jax.random.PRNGKey(0))
    draft_params = T.init_params(draft_config, jax.random.PRNGKey(1))
    with pytest.raises(ValueError, match="share a vocabulary"):
        speculative_generate(
            params, config, draft_params, draft_config,
            jnp.zeros((1, 4), jnp.int32),
        )


@pytest.mark.parametrize("kv_cache_dtype", ["bf16", "int8"])
def test_decode_window_matches_sequential_steps(kv_cache_dtype):
    # The verify primitive itself: one W-token window forward must equal W
    # sequential decode_steps (same cache evolution, same logits). For int8
    # this is what makes speculative decoding exact over the quantized
    # cache: per-row scales mean a window append == W single appends.
    config = cfg(n_kv_heads=2, kv_cache_dtype=kv_cache_dtype)
    params = T.init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 12), 0, config.vocab_size)
    L_pre, W = 6, 4

    _, (k_pre, v_pre) = T.forward(params, tokens[:, :L_pre], config, return_kv=True)
    cache_a = T.init_decode_cache(config, 2, 16, k_pre, v_pre)
    cache_b = jax.tree.map(jnp.copy, cache_a)

    win_logits, cache_a = T.decode_window(
        params, tokens[:, L_pre : L_pre + W], jnp.int32(L_pre), cache_a, config
    )
    for i in range(W):
        step_logits, cache_b = T.decode_step(
            params, tokens[:, L_pre + i : L_pre + i + 1],
            jnp.int32(L_pre + i), cache_b, config,
        )
        np.testing.assert_allclose(
            np.asarray(win_logits[:, i]), np.asarray(step_logits[:, 0]),
            atol=1e-4, rtol=1e-4, err_msg=f"row {i}",
        )
    for a, b in zip(jax.tree.leaves(cache_a), jax.tree.leaves(cache_b)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5
        )


def test_moe_target_rejected():
    config = dataclasses.replace(cfg(), n_experts=4)
    params = T.init_params(config, jax.random.PRNGKey(0))
    draft = cfg()
    draft_params = T.init_params(draft, jax.random.PRNGKey(1))
    with pytest.raises(NotImplementedError, match="moe_exact"):
        speculative_generate(
            params, config, draft_params, draft,
            jnp.zeros((1, 4), jnp.int32),
        )


def test_int8_target_cache_exact():
    # The round-4 matrix close (VERDICT r3 #5c): speculative decoding over
    # an int8 target cache must equal the target's own int8-cache greedy
    # decode — the unified decode_window quantizes the verify window per
    # row, so the cache evolves identically either way.
    config = cfg(n_kv_heads=2, kv_cache_dtype="int8")
    draft_config = cfg(n_layers=1, d_model=32, n_heads=2, d_ff=64)
    params = T.init_params(config, jax.random.PRNGKey(0))
    draft_params = T.init_params(draft_config, jax.random.PRNGKey(42))
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 5), 0, config.vocab_size)

    want = T.Transformer(config).generate_cached(params, prompt, max_new_tokens=8)
    got = speculative_generate(
        params, config, draft_params, draft_config, prompt,
        max_new_tokens=8, gamma=3,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_moe_dropless_target_accepted_and_exact():
    """A moe_dropless target routes per-token independently, so the verify
    window's pool size stops mattering: speculative output must equal the
    target's own greedy decode, token for token."""
    config = dataclasses.replace(
        T.TransformerConfig.tiny_moe(), moe_dropless=True,
        moe_group_size=1, dtype=jnp.float32
    )
    params = T.init_params(config, jax.random.PRNGKey(0))
    draft = cfg()
    draft_params = T.init_params(draft, jax.random.PRNGKey(1))
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 5), 0,
                                config.vocab_size)
    want = T.Transformer(config).generate_cached(params, prompt,
                                                 max_new_tokens=6)
    got = speculative_generate(
        params, config, draft_params, draft, prompt, max_new_tokens=6,
        gamma=3,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
