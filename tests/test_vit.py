"""ViT encoder family: shapes, flagship structure, sharded training."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from bee_code_interpreter_tpu.models.vit import (
    ViT,
    ViTConfig,
    forward,
    init_params,
    shard_params,
)
from bee_code_interpreter_tpu.parallel.mesh import make_mesh


def test_forward_shape():
    config = ViTConfig.tiny()
    params = init_params(config, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    logits = forward(params, x, config)
    assert logits.shape == (2, config.num_classes)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_vit_b16_structure():
    # ViT-Base/16: 12 layers x 768, 196 patches, ~86M params.
    config = ViTConfig.vit_b16()
    assert config.n_patches == 196
    params = jax.eval_shape(lambda k: init_params(config, k), jax.random.PRNGKey(0))
    n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    assert 85_000_000 < n < 88_000_000, n


def test_single_vs_tp_sharded_forward_agree():
    config = dataclasses.replace(ViTConfig.tiny(), dtype=jnp.float32)
    mesh = make_mesh({"dp": 2, "tp": 4})
    params = init_params(config, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 32, 32, 3))
    a = forward(params, x, config)
    b = forward(shard_params(params, config, mesh), x, config, mesh)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4)


def test_training_decreases_loss():
    import optax

    config = dataclasses.replace(ViTConfig.tiny(), dtype=jnp.float32)
    mesh = make_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
    model = ViT(config, mesh)
    params = model.init(jax.random.PRNGKey(0))
    optimizer = optax.adamw(1e-3)
    step = model.make_train_step(optimizer)
    opt_state = optimizer.init(params)

    batch = {
        "images": jax.device_put(
            jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3)),
            model.batch_sharding(),
        ),
        "labels": jax.device_put(
            jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 10),
            model.batch_sharding(),
        ),
    }
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_sp_mesh_ring_encoder_attention():
    # Bidirectional ring attention over an sp mesh: token grid sharded on
    # the sequence axis, non-causal hops — the encoder counterpart of the
    # decoder's causal ring path.
    config = dataclasses.replace(ViTConfig.tiny(), dtype=jnp.float32)
    mesh = make_mesh({"sp": 4})
    params = init_params(config, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 32, 32, 3))
    a = forward(params, x, config)
    b = forward(shard_params(params, config, mesh), x, config, mesh)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4)
