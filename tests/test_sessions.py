"""Sessions acceptance (ISSUE 7): leased sandboxes over the fake-pod stack,
checkpoint/rollback through content-addressed storage, live output
streaming on both paths, and the supervisor/drain/chaos integration that
keeps leases honest.

The fake-pod stack is the REAL KubernetesCodeExecutor + real SessionManager
against in-process executor servers (tests/fakes.py) — production wiring
minus kubectl, exactly like the chaos suites."""

import asyncio
import json
import statistics
import time

import pytest

from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.resilience import (
    PoolSupervisor,
    SandboxTransientError,
)
from bee_code_interpreter_tpu.services.kubernetes_code_executor import (
    KubernetesCodeExecutor,
)
from bee_code_interpreter_tpu.sessions import (
    SessionLimitExceeded,
    SessionManager,
    SessionNotFound,
    streamed_events,
)
from bee_code_interpreter_tpu.utils.metrics import Registry
from tests.chaos import FaultPlan, ManualClock
from tests.fakes import FakeExecutorPods, FakeKubectl

pytestmark = pytest.mark.chaos


@pytest.fixture
def faults():
    return FaultPlan()


@pytest.fixture
def pods(tmp_path, faults):
    return FakeExecutorPods(tmp_path / "pods", faults=faults)


def make_k8s(pods, storage, *, metrics=None, queue_len=1, **overrides):
    config = Config(
        executor_backend="kubernetes",
        executor_port=pods.port,
        executor_pod_queue_target_length=queue_len,
        pod_ready_timeout_s=5,
        executor_retry_attempts=1,
        **overrides,
    )
    return KubernetesCodeExecutor(
        kubectl=FakeKubectl(pods),
        storage=storage,
        config=config,
        metrics=metrics,
        ip_poll_interval_s=0.02,
    )


def make_manager(executor, storage, **kwargs):
    kwargs.setdefault("max_sessions", 4)
    kwargs.setdefault("ttl_s", 60.0)
    kwargs.setdefault("idle_s", 60.0)
    return SessionManager(executor, storage, **kwargs)


# ------------------------------------------------- lease over the fake pods


async def test_one_lease_serves_many_executes_on_one_sandbox(
    pods, storage
):
    """The acceptance core: one lease, 3 executes with a checkpoint +
    rollback in between, all on a SINGLE sandbox (the fleet journal shows
    exactly one assignment), with workspace state persisting across
    executes and rollback undoing post-checkpoint changes."""
    k8s = make_k8s(pods, storage)
    manager = make_manager(k8s, storage)
    try:
        await k8s.fill_executor_pod_queue()
        session = await manager.create()

        _, o1 = await manager.execute(
            session.session_id,
            "open('state.txt', 'w').write('v1')\nprint('one')",
        )
        assert o1.stdout == "one\n" and o1.exit_code == 0
        assert "/workspace/state.txt" in o1.changed_paths

        _, checkpoint = await manager.checkpoint(session.session_id)
        assert set(checkpoint.files) == {"/workspace/state.txt"}
        # The checkpoint map is real content-addressed storage objects.
        assert (await storage.read(checkpoint.files["/workspace/state.txt"])) == b"v1"

        _, o2 = await manager.execute(
            session.session_id,
            "open('state.txt', 'w').write('v2')\n"
            "open('stray.txt', 'w').write('x')\nprint('two')",
        )
        assert o2.stdout == "two\n"

        await manager.rollback(session.session_id, checkpoint.checkpoint_id)

        _, o3 = await manager.execute(
            session.session_id,
            "import os\n"
            "print(open('state.txt').read(), os.path.exists('stray.txt'))",
        )
        assert o3.stdout == "v1 False\n"  # content restored, stray evicted

        events = k8s.journal.events()
        assigned = [e for e in events if e["state"] == "assigned"]
        assert len(assigned) == 1, assigned  # ONE sandbox for the whole lease
        leased = [e for e in events if e["state"] == "leased"]
        assert leased and leased[-1]["session"] == session.session_id

        await manager.release(session.session_id)
        terminal = [
            e
            for e in k8s.journal.events()
            if e["state"] in ("released", "lease_expired", "reaped")
        ]
        assert [(e["state"], e.get("reason")) for e in terminal] == [
            ("released", "lease_released")
        ]
        with pytest.raises(SessionNotFound):
            manager.get(session.session_id)
    finally:
        await manager.close_all()
        await pods.close()


async def test_in_session_warm_p50_beats_stateless(pods, storage):
    """The point of the lease: executes inside it skip restore + snapshot,
    so the in-session warm p50 lands measurably below the stateless path
    running the SAME payload on the same stack (which pays checkout probe,
    upload, and the changed-file download every time)."""
    k8s = make_k8s(pods, storage, queue_len=2)
    manager = make_manager(k8s, storage)
    # The payload writes a file so the stateless path pays a real snapshot
    # download per execute — exactly the tax sessions amortize.
    payload = "open('out.bin', 'wb').write(b'x' * 65536)\nprint('ok')"
    try:
        await k8s.fill_executor_pod_queue()
        stateless = []
        for _ in range(5):
            t0 = time.perf_counter()
            result = await k8s.execute(payload)
            assert result.stdout == "ok\n"
            stateless.append(time.perf_counter() - t0)
            await asyncio.sleep(0.05)  # let the refill land
        session = await manager.create()
        leased = []
        for i in range(6):
            t0 = time.perf_counter()
            _, outcome = await manager.execute(session.session_id, payload)
            assert outcome.stdout == "ok\n"
            if i:  # №2..N: the in-session warm rate
                leased.append(time.perf_counter() - t0)
        p50_stateless = statistics.median(stateless)
        p50_leased = statistics.median(leased)
        assert p50_leased < p50_stateless, (
            f"in-session p50 {p50_leased * 1000:.1f}ms not below "
            f"stateless {p50_stateless * 1000:.1f}ms"
        )
    finally:
        await manager.close_all()
        await pods.close()


async def test_lease_cap_and_bad_restore(pods, storage):
    k8s = make_k8s(pods, storage, queue_len=2)
    metrics = Registry()
    manager = make_manager(k8s, storage, max_sessions=1, metrics=metrics)
    try:
        await k8s.fill_executor_pod_queue()
        session = await manager.create()
        with pytest.raises(SessionLimitExceeded):
            await manager.create()
        await manager.release(session.session_id)
        # A create whose initial restore fails must not leak its lease.
        with pytest.raises(Exception):
            await manager.create(files={"/workspace/a": "0" * 64})
        assert manager.active_count == 0
        ends = metrics.metrics["bci_session_expirations_total"]._values
        assert ends.get((("reason", "sandbox_died"),), 0) == 1
        session2 = await manager.create()  # the slot is actually free again
        assert manager.active_count == 1
        await manager.release(session2.session_id)
    finally:
        await manager.close_all()
        await pods.close()


# ------------------------------------------------------------ expiry sweeps


async def test_ttl_idle_and_drain_expiry(pods, storage):
    clock = ManualClock()
    metrics = Registry()
    k8s = make_k8s(pods, storage, queue_len=2)
    manager = make_manager(
        k8s, storage, ttl_s=100.0, idle_s=30.0, metrics=metrics, clock=clock
    )
    try:
        await k8s.fill_executor_pod_queue()
        idle_victim = await manager.create()
        await manager.execute(idle_victim.session_id, "print(1)")
        survivor = await manager.create()

        clock.advance(31.0)  # idle_victim past idle; survivor just created?
        # survivor was created at t=0 too — touch it so only idle matters
        await manager.execute(survivor.session_id, "print(2)")
        expired = await manager.sweep_once()
        assert expired == 1 and manager.active_count == 1
        assert (
            manager.get(survivor.session_id).session_id
            == survivor.session_id
        )
        with pytest.raises(SessionNotFound):
            manager.get(idle_victim.session_id)

        clock.advance(80.0)  # survivor's TTL (100s) now exceeded
        await manager.execute(survivor.session_id, "print(3)")  # active but old
        assert await manager.sweep_once() == 1
        events = [
            (e.get("reason"))
            for e in k8s.journal.events()
            if e["state"] == "lease_expired"
        ]
        assert sorted(events) == ["idle", "ttl"]
        ends = metrics.metrics["bci_session_expirations_total"]._values
        assert ends.get((("reason", "idle"),), 0) == 1
        assert ends.get((("reason", "ttl"),), 0) == 1
    finally:
        await manager.close_all()
        await pods.close()


async def test_drain_bounds_lease_lifetimes(pods, storage):
    from bee_code_interpreter_tpu.resilience import DrainController

    drain = DrainController()
    metrics = Registry()
    k8s = make_k8s(pods, storage)
    manager = make_manager(k8s, storage, metrics=metrics, drain=drain)
    try:
        await k8s.fill_executor_pod_queue()
        session = await manager.create()
        assert await manager.sweep_once() == 0  # healthy lease, no drain
        drain.begin()
        assert await manager.sweep_once() == 1  # drain reclaims it NOW
        assert manager.active_count == 0
        events = [
            e
            for e in k8s.journal.events()
            if e["state"] == "lease_expired" and e.get("reason") == "drain"
        ]
        assert len(events) == 1
        ends = metrics.metrics["bci_session_expirations_total"]._values
        assert ends.get((("reason", "drain"),), 0) == 1
        assert session.closed
    finally:
        await manager.close_all()
        await pods.close()


# ------------------------------------------- supervisor/watchdog integration


async def test_leased_idle_sandbox_survives_supervisor_sweep(pods, storage):
    """A leased, healthy-but-idle sandbox is OWNED, not stuck: the
    supervisor's idle reaper (which probes only queued inventory) and the
    stuck-execution watchdog (which sees only in-flight executes) must both
    leave it alone — while a genuinely wedged leased execute still dies."""
    k8s = make_k8s(pods, storage, queue_len=1)
    manager = make_manager(k8s, storage)
    supervisor = PoolSupervisor(k8s, interval_s=60, execute_hard_cap_s=0.3)
    try:
        await k8s.fill_executor_pod_queue()
        session = await manager.create()
        await manager.execute(session.session_id, "print('warm')")
        swept = await supervisor.sweep_once()
        assert swept["reaped"] == 0 and swept["watchdog_killed"] == 0
        # The lease is alive and still serves.
        _, outcome = await manager.execute(session.session_id, "print('still')")
        assert outcome.stdout == "still\n"
        reaps = [e for e in k8s.journal.events() if e["state"] == "reaped"]
        assert reaps == []
    finally:
        await manager.close_all()
        await pods.close()


async def test_watchdog_kills_wedged_leased_execute(pods, storage, faults):
    metrics = Registry()
    k8s = make_k8s(pods, storage, queue_len=1)
    manager = make_manager(k8s, storage, metrics=metrics)
    supervisor = PoolSupervisor(k8s, interval_s=60, execute_hard_cap_s=0.2)
    try:
        await k8s.fill_executor_pod_queue()
        session = await manager.create()
        faults.hang_execute(30.0)
        request = asyncio.ensure_future(
            manager.execute(session.session_id, "print('wedged')")
        )
        await asyncio.sleep(0.3)
        swept = await supervisor.sweep_once()
        assert swept["watchdog_killed"] == 1
        with pytest.raises(SandboxTransientError):
            await request
        # The kill ended the lease: reaped with the watchdog's reason, the
        # session is gone, and the end is accounted.
        assert manager.active_count == 0
        reaped = [
            e
            for e in k8s.journal.events()
            if e["state"] == "reaped" and e.get("reason") == "hung_execute"
        ]
        assert len(reaped) == 1
        ends = metrics.metrics["bci_session_expirations_total"]._values
        assert ends.get((("reason", "sandbox_died"),), 0) == 1
    finally:
        await manager.close_all()
        await pods.close()


# ----------------------------------------------------- chaos: scenario 10


async def test_vanished_stream_client_lease_reaped_on_ttl(pods, storage):
    """Chaos scenario 10a/10b in tier-1: a streaming client vanishes
    mid-chunk — the lease survives the disconnect and the TTL sweep reaps
    it; the pool refills; accounting is exact."""
    clock = ManualClock()
    metrics = Registry()
    k8s = make_k8s(pods, storage, queue_len=1)
    manager = make_manager(
        k8s, storage, ttl_s=5.0, idle_s=60.0, metrics=metrics, clock=clock
    )
    try:
        await k8s.fill_executor_pod_queue()
        session = await manager.create()
        got_chunk = asyncio.Event()

        async def on_event(kind, text):
            got_chunk.set()

        vanished = asyncio.ensure_future(
            manager.execute(
                session.session_id,
                "import time\nprint('c', flush=True)\ntime.sleep(20)",
                on_event=on_event,
            )
        )
        await asyncio.wait_for(got_chunk.wait(), timeout=10)
        vanished.cancel()
        with pytest.raises(asyncio.CancelledError):
            await vanished
        assert manager.active_count == 1  # the lease survives the client

        clock.advance(6.0)  # past the TTL
        assert await manager.sweep_once() == 1
        for _ in range(300):  # lease end kicks a refill fire-and-forget
            if k8s.pool_ready_count >= 1:
                break
            await asyncio.sleep(0.01)
        assert k8s.pool_ready_count >= 1
        ttl_ends = [
            e
            for e in k8s.journal.events()
            if e["state"] == "lease_expired" and e.get("reason") == "ttl"
        ]
        assert len(ttl_ends) == 1
        ends = metrics.metrics["bci_session_expirations_total"]._values
        assert ends == {(("reason", "ttl"),): 1}
    finally:
        await manager.close_all()
        await pods.close()


async def test_sandbox_death_mid_lease_and_terminal_error_event(
    pods, storage, faults
):
    """Chaos scenario 10c/10d in tier-1: the sandbox dies mid-lease (the
    session ends as reaped/died_mid_lease, pool refills) and a stateless
    stream whose pod dies delivers a terminal error event."""
    metrics = Registry()
    k8s = make_k8s(pods, storage, queue_len=1)
    manager = make_manager(k8s, storage, metrics=metrics)
    try:
        await k8s.fill_executor_pod_queue()
        session = await manager.create()
        faults.die_mid_execute()
        with pytest.raises(SandboxTransientError):
            await manager.execute(session.session_id, "print('x')")
        assert manager.active_count == 0
        died = [
            e
            for e in k8s.journal.events()
            if e["state"] == "reaped" and e.get("reason") == "died_mid_lease"
        ]
        assert len(died) == 1
        ends = metrics.metrics["bci_session_expirations_total"]._values
        assert ends.get((("reason", "sandbox_died"),), 0) == 1

        faults.die_mid_execute()

        async def run(on_event):
            return await k8s.execute_stream("print('doomed')", on_event=on_event)

        events = [item async for item in streamed_events(run)]
        assert events and events[-1].get("event") == "error"
        assert isinstance(events[-1]["error"], SandboxTransientError)
    finally:
        await manager.close_all()
        await pods.close()


# ------------------------------------------------------------- HTTP edge


def make_app(executor, storage, metrics, manager=None, tracer=None, **kwargs):
    from bee_code_interpreter_tpu.api.http_server import create_http_server
    from bee_code_interpreter_tpu.services.custom_tool_executor import (
        CustomToolExecutor,
    )

    return create_http_server(
        code_executor=executor,
        custom_tool_executor=CustomToolExecutor(code_executor=executor),
        metrics=metrics,
        tracer=tracer,
        sessions=manager,
        **kwargs,
    )


async def sse_events(resp):
    """[(event, parsed data), ...] from an SSE response body."""
    out = []
    event = None
    async for raw in resp.content:
        line = raw.decode().rstrip("\n")
        if line.startswith("event: "):
            event = line[len("event: "):]
        elif line.startswith("data: "):
            out.append((event, json.loads(line[len("data: "):])))
    return out


async def test_http_sse_streams_chunks_with_matching_trace(pods, storage):
    """Acceptance: an SSE client observes >=2 stdout chunks before the
    terminal event, and the terminal envelope's trace_id resolves in
    /v1/traces."""
    from aiohttp.test_utils import TestClient, TestServer

    from bee_code_interpreter_tpu.observability import Tracer, TraceStore

    metrics = Registry()
    tracer = Tracer(store=TraceStore(), metrics=metrics)
    k8s = make_k8s(pods, storage, metrics=metrics)
    manager = make_manager(k8s, storage, metrics=metrics)
    app = make_app(k8s, storage, metrics, manager, tracer=tracer)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        await k8s.fill_executor_pod_queue()
        resp = await client.post(
            "/v1/execute?stream=1",
            json={
                "source_code": (
                    "import time\n"
                    "print('alpha', flush=True)\n"
                    "time.sleep(0.25)\n"
                    "print('omega', flush=True)\n"
                )
            },
        )
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        events = await sse_events(resp)
        stdout_chunks = [d["text"] for e, d in events if e == "stdout"]
        terminals = [d for e, d in events if e == "result"]
        assert len(stdout_chunks) >= 2, events
        assert events[-1][0] == "result" and len(terminals) == 1
        result = terminals[0]
        assert result["stdout"] == "alpha\nomega\n"
        assert result["exit_code"] == 0
        # chunks arrived BEFORE the terminal event carried the total
        assert "".join(stdout_chunks) == result["stdout"]
        trace = await client.get(f"/v1/traces/{result['trace_id']}")
        assert trace.status == 200
        assert (await trace.json())["trace_id"] == result["trace_id"]
    finally:
        await client.close()
        await manager.close_all()
        await pods.close()


async def test_http_session_routes_end_to_end(pods, storage):
    from aiohttp.test_utils import TestClient, TestServer

    from bee_code_interpreter_tpu.analysis import WorkloadAnalyzer

    metrics = Registry()
    k8s = make_k8s(pods, storage, metrics=metrics, queue_len=2)
    manager = make_manager(k8s, storage, max_sessions=1, metrics=metrics)
    app = make_app(
        k8s, storage, metrics, manager, analyzer=WorkloadAnalyzer(metrics=metrics)
    )
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        await k8s.fill_executor_pod_queue()
        resp = await client.post("/v1/sessions", json={})
        assert resp.status == 200
        created = await resp.json()
        sid = created["session_id"]
        assert created["expires_at"] > time.time()

        # cap: the second lease sheds with Retry-After, like admission
        resp = await client.post("/v1/sessions", json={})
        assert resp.status == 429 and "Retry-After" in resp.headers

        resp = await client.post(
            f"/v1/sessions/{sid}/execute",
            json={"source_code": "open('f.txt','w').write('1')\nprint('a')"},
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["stdout"] == "a\n" and body["execution"] == 1
        assert body["changed_paths"] == ["/workspace/f.txt"]
        assert body["session_id"] == sid and body["trace_id"]

        # the syntax gate fail-fasts without burning a lease execute
        execs_before = k8s.journal.executions_total
        resp = await client.post(
            f"/v1/sessions/{sid}/execute", json={"source_code": "def broken(:"}
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["exit_code"] == 1 and "SyntaxError" in body["stderr"]
        assert k8s.journal.executions_total == execs_before

        resp = await client.post(f"/v1/sessions/{sid}/checkpoint")
        checkpoint = await resp.json()
        assert resp.status == 200
        assert list(checkpoint["files"]) == ["/workspace/f.txt"]

        resp = await client.post(
            f"/v1/sessions/{sid}/execute",
            json={"source_code": "open('f.txt','w').write('2')\nprint('b')"},
        )
        assert resp.status == 200

        resp = await client.post(
            f"/v1/sessions/{sid}/rollback",
            json={"checkpoint_id": checkpoint["checkpoint_id"]},
        )
        assert resp.status == 200

        resp = await client.post(
            f"/v1/sessions/{sid}/execute",
            json={"source_code": "print(open('f.txt').read())"},
        )
        assert (await resp.json())["stdout"] == "1\n"

        # unknown checkpoint and unknown session → 404
        resp = await client.post(
            f"/v1/sessions/{sid}/rollback", json={"checkpoint_id": "nope"}
        )
        assert resp.status == 404
        resp = await client.post(
            "/v1/sessions/sess-missing/execute",
            json={"source_code": "print(1)"},
        )
        assert resp.status == 404

        # /v1/fleet shows the leased sandbox with its owner + lease age
        snap = await (await client.get("/v1/fleet")).json()
        leased_pods = [p for p in snap["pods"] if p["state"] == "leased"]
        assert len(leased_pods) == 1
        assert leased_pods[0]["session"] == sid
        assert leased_pods[0]["lease_age_s"] >= 0
        # 4 POSTs, but the syntax fail-fast never touched the sandbox
        assert leased_pods[0]["executions"] == 3
        assert snap["sessions"]["active"] == 1

        resp = await client.delete(f"/v1/sessions/{sid}")
        assert resp.status == 200 and (await resp.json())["released"]
        resp = await client.delete(f"/v1/sessions/{sid}")
        assert resp.status == 404
    finally:
        await client.close()
        await manager.close_all()
        await pods.close()


async def test_http_sessionful_sse_and_drain(pods, storage):
    from aiohttp.test_utils import TestClient, TestServer

    from bee_code_interpreter_tpu.resilience import DrainController

    metrics = Registry()
    drain = DrainController()
    k8s = make_k8s(pods, storage, metrics=metrics)
    manager = make_manager(k8s, storage, metrics=metrics, drain=drain)
    app = make_app(k8s, storage, metrics, manager, drain=drain)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        await k8s.fill_executor_pod_queue()
        sid = (await (await client.post("/v1/sessions", json={})).json())[
            "session_id"
        ]
        resp = await client.post(
            f"/v1/sessions/{sid}/execute?stream=1",
            json={
                "source_code": (
                    "import time\n"
                    "print('s1', flush=True)\n"
                    "time.sleep(0.25)\n"
                    "print('s2', flush=True)\n"
                )
            },
        )
        events = await sse_events(resp)
        chunks = [d["text"] for e, d in events if e == "stdout"]
        assert len(chunks) >= 2
        terminal = events[-1]
        assert terminal[0] == "result"
        assert terminal[1]["session_id"] == sid
        assert terminal[1]["stdout"] == "s1\ns2\n"

        # drain: no new leases, no session executes; existing lease expires
        drain.begin()
        resp = await client.post("/v1/sessions", json={})
        assert resp.status == 503
        resp = await client.post(
            f"/v1/sessions/{sid}/execute", json={"source_code": "print(1)"}
        )
        assert resp.status == 503
        assert await manager.sweep_once() == 1
        ends = metrics.metrics["bci_session_expirations_total"]._values
        assert ends.get((("reason", "drain"),), 0) == 1
    finally:
        await client.close()
        await manager.close_all()
        await pods.close()


async def test_http_sse_mid_stream_failure_burns_slo_budget(
    pods, storage, faults
):
    """SSE spends its 200 at prepare time, so a mid-stream sandbox death is
    an in-band error event — but the SLI sample must still be bad, exactly
    like the buffered path's 500 and the gRPC ExecuteStream twin."""
    from aiohttp.test_utils import TestClient, TestServer

    class SloSpy:
        def __init__(self):
            self.samples = []

        def record(self, ok, duration_s):
            self.samples.append(ok)

    metrics = Registry()
    slo = SloSpy()
    k8s = make_k8s(pods, storage, metrics=metrics)
    app = make_app(k8s, storage, metrics, slo=slo)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        await k8s.fill_executor_pod_queue()
        resp = await client.post(
            "/v1/execute?stream=1", json={"source_code": "print('ok')"}
        )
        events = await sse_events(resp)
        assert events[-1][0] == "result"
        assert slo.samples == [True]

        faults.die_mid_execute()
        resp = await client.post(
            "/v1/execute?stream=1", json={"source_code": "print('doomed')"}
        )
        assert resp.status == 200  # the status was already spent
        events = await sse_events(resp)
        assert events[-1][0] == "error"
        assert slo.samples == [True, False]
    finally:
        await client.close()
        await pods.close()


# ------------------------------------------------------------- gRPC edge


async def test_grpc_session_service_and_execute_stream(pods, storage):
    import grpc.aio

    from bee_code_interpreter_tpu.analysis import WorkloadAnalyzer
    from bee_code_interpreter_tpu.api.grpc_server import (
        GrpcServer,
        execute_stream_stub,
        session_stubs,
    )
    from bee_code_interpreter_tpu.services.custom_tool_executor import (
        CustomToolExecutor,
    )

    metrics = Registry()
    k8s = make_k8s(pods, storage, metrics=metrics, queue_len=2)
    manager = make_manager(k8s, storage, metrics=metrics)
    server = GrpcServer(
        k8s,
        CustomToolExecutor(code_executor=k8s),
        metrics=metrics,
        request_deadline_s=30,
        sessions=manager,
        analyzer=WorkloadAnalyzer(metrics=metrics),
    )
    port = await server.start("127.0.0.1:0")
    try:
        await k8s.fill_executor_pod_queue()
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stubs = session_stubs(channel)
            created = json.loads(await stubs["CreateSession"](b"{}"))
            sid = created["session_id"]

            result = json.loads(
                await stubs["ExecuteInSession"](
                    json.dumps(
                        {
                            "session_id": sid,
                            "source_code": (
                                "open('g.txt','w').write('g1')\nprint('go')"
                            ),
                        }
                    ).encode()
                )
            )
            assert result["stdout"] == "go\n" and result["execution"] == 1

            checkpoint = json.loads(
                await stubs["Checkpoint"](
                    json.dumps({"session_id": sid}).encode()
                )
            )
            assert list(checkpoint["files"]) == ["/workspace/g.txt"]

            rolled = json.loads(
                await stubs["Rollback"](
                    json.dumps(
                        {
                            "session_id": sid,
                            "checkpoint_id": checkpoint["checkpoint_id"],
                        }
                    ).encode()
                )
            )
            assert rolled["checkpoint_id"] == checkpoint["checkpoint_id"]

            # policy/deny parity: gRPC session execute aborts INVALID_ARGUMENT
            # for a denied import exactly like the stateless RPC
            server_analyzer_denied = False
            try:
                await stubs["ExecuteInSession"](
                    json.dumps(
                        {"session_id": sid, "source_code": "def broken(:"}
                    ).encode()
                )
            except grpc.aio.AioRpcError:
                server_analyzer_denied = True
            assert not server_analyzer_denied  # syntax error is a normal reply

            # sessionful server stream: >=2 chunks then a terminal result
            call = execute_stream_stub(channel)(
                json.dumps(
                    {
                        "session_id": sid,
                        "source_code": (
                            "import time\n"
                            "print('g1', flush=True)\n"
                            "time.sleep(0.25)\n"
                            "print('g2', flush=True)\n"
                        ),
                    }
                ).encode()
            )
            events = [json.loads(raw) async for raw in call]
            chunks = [e for e in events if e.get("stream") == "stdout"]
            assert len(chunks) >= 2
            assert events[-1]["event"] == "result"
            assert events[-1]["session_id"] == sid
            assert events[-1]["stdout"] == "g1\ng2\n"

            # stateless stream through the same RPC (no session_id)
            events = [
                json.loads(raw)
                async for raw in execute_stream_stub(channel)(
                    json.dumps({"source_code": "print('solo')"}).encode()
                )
            ]
            assert events[-1]["event"] == "result"
            assert events[-1]["stdout"] == "solo\n"

            released = json.loads(
                await stubs["DeleteSession"](
                    json.dumps({"session_id": sid}).encode()
                )
            )
            assert released["released"] is True
            with pytest.raises(grpc.aio.AioRpcError) as err:
                await stubs["ExecuteInSession"](
                    json.dumps(
                        {"session_id": sid, "source_code": "print(1)"}
                    ).encode()
                )
            assert err.value.code() == grpc.StatusCode.NOT_FOUND
    finally:
        await server.stop(grace=0.5)
        await manager.close_all()
        await pods.close()


async def test_grpc_create_session_rejects_malformed_lease_params(
    pods, storage
):
    """The JSON-bytes gRPC edge has no pydantic message, so the manager is
    the validation backstop — a malformed ttl_s/files must answer
    INVALID_ARGUMENT (the twin of HTTP's 422, SLI-good) BEFORE any sandbox
    is checked out, never UNKNOWN."""
    import grpc.aio

    from bee_code_interpreter_tpu.api.grpc_server import (
        GrpcServer,
        session_stubs,
    )
    from bee_code_interpreter_tpu.services.custom_tool_executor import (
        CustomToolExecutor,
    )

    metrics = Registry()
    k8s = make_k8s(pods, storage, metrics=metrics)
    manager = make_manager(k8s, storage, metrics=metrics)
    server = GrpcServer(
        k8s,
        CustomToolExecutor(code_executor=k8s),
        metrics=metrics,
        request_deadline_s=30,
        sessions=manager,
    )
    port = await server.start("127.0.0.1:0")
    try:
        await k8s.fill_executor_pod_queue()
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stubs = session_stubs(channel)
            for body in (
                {"ttl_s": "abc"},
                {"ttl_s": -5},
                {"idle_s": 0},
                {"files": [1, 2]},
                {"files": {"/workspace/a.txt": 7}},
            ):
                with pytest.raises(grpc.aio.AioRpcError) as err:
                    await stubs["CreateSession"](json.dumps(body).encode())
                assert (
                    err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
                ), body
        # rejected before checkout: no lease leaked, no sandbox consumed
        assert manager.active_count == 0
        assert not [
            e for e in k8s.journal.events() if e["state"] == "leased"
        ]
    finally:
        await server.stop(grace=0.5)
        await manager.close_all()
        await pods.close()


# ------------------------------------------------------ local-backend lease


async def test_local_backend_sessions(local_executor, storage):
    """Sessions work over the in-process backend too (the dev/e2e python
    path): persistent workspace, checkpoint/rollback, streaming."""
    manager = make_manager(local_executor, storage)
    session = await manager.create()
    try:
        _, o1 = await manager.execute(
            session.session_id, "open('l.txt','w').write('L1')\nprint('one')"
        )
        assert o1.stdout == "one\n"
        _, checkpoint = await manager.checkpoint(session.session_id)
        assert set(checkpoint.files) == {"/workspace/l.txt"}
        await manager.execute(
            session.session_id,
            "open('l.txt','w').write('L2')\nopen('s.txt','w').write('s')",
        )
        await manager.rollback(session.session_id, checkpoint.checkpoint_id)
        _, o2 = await manager.execute(
            session.session_id,
            "import os\nprint(open('l.txt').read(), os.path.exists('s.txt'))",
        )
        assert o2.stdout == "L1 False\n"

        chunks = []

        async def on_event(kind, text):
            chunks.append((kind, text))

        _, streamed = await manager.execute(
            session.session_id,
            "import time\nprint('x', flush=True)\ntime.sleep(0.2)\nprint('y')",
            on_event=on_event,
        )
        assert streamed.stdout == "x\ny\n"
        assert any(kind == "stdout" for kind, _ in chunks)
    finally:
        await manager.close_all()


# --------------------------------------------------- core streaming contract


async def test_executor_core_stream_timeout_matches_buffered_contract(tmp_path):
    from bee_code_interpreter_tpu.runtime.executor_core import (
        EXECUTION_TIMED_OUT,
        ExecutorCore,
    )

    core = ExecutorCore(workspace=tmp_path / "ws", disable_dep_install=True)
    seen = []
    outcome = None
    gen = core.execute_stream(
        "import time\nprint('pre', flush=True)\ntime.sleep(30)",
        timeout_s=0.5,
    )
    async for kind, payload in gen:
        if kind == "end":
            outcome = payload
        else:
            seen.append((kind, payload))
    # chunks delivered before the timeout stay delivered (boundaries are
    # whatever the pipe carried); the envelope mirrors the buffered path's
    # timeout contract exactly
    assert "pre\n" in "".join(t for k, t in seen if k == "stdout")
    assert outcome.exit_code == -1
    assert outcome.stdout == "" and outcome.stderr == EXECUTION_TIMED_OUT


async def test_executor_core_abandoned_stream_reaps_child(tmp_path):
    from bee_code_interpreter_tpu.runtime.executor_core import ExecutorCore

    core = ExecutorCore(workspace=tmp_path / "ws", disable_dep_install=True)
    marker = tmp_path / "ws" / "still-running.txt"
    gen = core.execute_stream(
        "import time\n"
        "print('started', flush=True)\n"
        "time.sleep(3)\n"
        "open('still-running.txt', 'w').write('leaked')\n",
        timeout_s=30,
    )
    async for kind, payload in gen:
        if kind == "stdout":
            break  # consumer vanishes after the first chunk
    await gen.aclose()
    # the child was killed with the stream: it never got to write the marker
    await asyncio.sleep(0.3)
    assert not marker.exists()


async def test_executor_core_cancelled_execute_reaps_child(tmp_path):
    """The buffered twin of the abandoned-stream contract: cancelling an
    in-flight execute (vanished client, watchdog kill) must not leave the
    user process mutating the workspace — under a lease that workspace
    survives the call, and an orphan would corrupt the next REPL turn."""
    from bee_code_interpreter_tpu.runtime.executor_core import ExecutorCore

    core = ExecutorCore(workspace=tmp_path / "ws", disable_dep_install=True)
    marker = tmp_path / "ws" / "still-running.txt"
    task = asyncio.ensure_future(
        core.execute(
            "import time\n"
            "time.sleep(1)\n"
            "open('still-running.txt', 'w').write('leaked')\n",
            timeout_s=30,
        )
    )
    await asyncio.sleep(0.4)  # let the child start its sleep
    task.cancel()
    with pytest.raises(asyncio.CancelledError):
        await task
    # an orphan would write the marker ~0.6s from now; a killed child never
    # does — wait past that point so a leak cannot pass silently
    await asyncio.sleep(1.2)
    assert not marker.exists()
