"""Continuous batching: per-request outputs must be independent of what
else shares the batch, and pages must recycle."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models.serving import (
    ContinuousBatcher,
    SamplingParams,
)


def cfg(**kw):
    return dataclasses.replace(
        T.TransformerConfig.tiny(), dtype=jnp.float32, n_kv_heads=2, **kw
    )


def reference_tokens(params, config, prompt, n):
    """The target each request must reproduce: the model's own greedy
    cached decode, run solo."""
    out = T.Transformer(config).generate_cached(
        params, jnp.asarray(prompt)[None, :], max_new_tokens=n
    )
    return np.asarray(out[0, len(prompt):]).tolist()


def test_staggered_requests_match_solo_decode():
    # Three prompts of different lengths admitted at different times; each
    # result must equal that prompt's solo greedy decode token-for-token.
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompts = [
        np.asarray(jax.random.randint(jax.random.PRNGKey(i + 1), (L,), 0,
                                      config.vocab_size))
        for i, L in enumerate([3, 7, 5])
    ]
    want = [reference_tokens(params, config, p, 6) for p in prompts]

    b = ContinuousBatcher(
        params, config, max_batch=2, n_pages=16, page_size=4,
        max_pages_per_seq=4,
    )
    r0 = b.submit(prompts[0], 6)
    r1 = b.submit(prompts[1], 6)
    b.step(); b.step()
    # batch full: third request waits until a row frees
    with pytest.raises(RuntimeError, match="no free batch row"):
        b.submit(prompts[2], 6)
    b.run_to_completion()
    r2 = b.submit(prompts[2], 6)  # admitted into a recycled row + pages
    b.run_to_completion()

    assert b.result(r0) == want[0]
    assert b.result(r1) == want[1]
    assert b.result(r2) == want[2]


def test_pages_recycle():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    b = ContinuousBatcher(
        params, config, max_batch=1, n_pages=5, page_size=4,
        max_pages_per_seq=4,
    )
    free0 = len(b.free_pages)
    prompt = np.asarray([1, 2, 3, 4, 5])
    row = b.submit(prompt, 4)
    assert len(b.free_pages) < free0  # pages held while decoding
    b.run_to_completion()
    assert b.is_done(row)
    assert len(b.free_pages) == free0  # all pages back after retirement


def test_budget_and_pool_validation():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    b = ContinuousBatcher(
        params, config, max_batch=2, n_pages=3, page_size=4,
        max_pages_per_seq=2,
    )
    with pytest.raises(ValueError, match="exceeds the block table"):
        b.submit(np.arange(1, 8), 4)  # 7 + 4 > 2*4
    with pytest.raises(ValueError, match="max_new_tokens"):
        b.submit(np.arange(1, 4), 0)  # asking for zero tokens is a bug
    with pytest.raises(RuntimeError, match="page pool exhausted"):
        b.submit(np.arange(1, 6), 3)  # needs 2 pages, pool has (3-1)=2... ok
        b.submit(np.arange(1, 6), 3)  # second request: pool empty


def test_eos_retires_early():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = np.asarray([1, 2, 3])
    solo = reference_tokens(params, config, prompt, 8)
    # pick an eos value whose FIRST occurrence is past the first token, so
    # the stop is genuinely early and genuinely at that position
    stop_at = next(
        (i for i in range(1, len(solo)) if solo[i] not in solo[:i]), None
    )
    if stop_at is None:
        pytest.skip("greedy output has no late first-occurrence token")
    b = ContinuousBatcher(
        params, config, max_batch=1, n_pages=8, page_size=4,
        max_pages_per_seq=3, eos_id=solo[stop_at],
    )
    req = b.submit(prompt, 8)
    b.run_to_completion()
    assert b.result(req) == solo[: stop_at + 1]  # stopped at eos, prefix identical


def test_per_request_sampling_deterministic_and_isolated():
    # Heterogeneous sampling in one batch: a greedy request batched with
    # sampled ones must still equal its solo greedy decode (per-request
    # isolation), and a sampled request with a fixed seed must reproduce
    # exactly across separate batcher instances.
    from bee_code_interpreter_tpu.models.serving import SamplingParams

    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    p_greedy = np.asarray([3, 1, 4, 1, 5])
    p_sampled = np.asarray([9, 2, 6])
    want_greedy = reference_tokens(params, config, p_greedy, 6)
    hot = SamplingParams(temperature=1.0, top_k=8, seed=123)

    def run():
        b = ContinuousBatcher(
            params, config, max_batch=2, n_pages=16, page_size=4,
            max_pages_per_seq=4,
        )
        rg = b.submit(p_greedy, 6)
        rs = b.submit(p_sampled, 6, sampling=hot)
        b.run_to_completion()
        return b.result(rg), b.result(rs)

    g1, s1 = run()
    g2, s2 = run()
    assert g1 == want_greedy == g2  # greedy unaffected by sampled batchmate
    assert s1 == s2  # fixed seed: fully deterministic
    other = ContinuousBatcher(
        params, config, max_batch=1, n_pages=16, page_size=4,
        max_pages_per_seq=4,
    )
    r = other.submit(
        p_sampled, 6, sampling=SamplingParams(temperature=1.0, top_k=8, seed=7)
    )
    other.run_to_completion()
    assert other.result(r) != s1  # different seed: different draw (whp)


def test_sampling_filters_respected():
    # top_k=1 degenerates to greedy regardless of temperature; top_p tiny
    # keeps only the argmax mass — both must equal the greedy output.
    from bee_code_interpreter_tpu.models.serving import SamplingParams

    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = np.asarray([2, 7, 1, 8])
    want = reference_tokens(params, config, prompt, 5)
    for sp in (
        SamplingParams(temperature=1.0, top_k=1, seed=11),
        SamplingParams(temperature=0.7, top_p=1e-9, seed=12),
        # degenerate top_p=0 keeps at least the top token (sample_logits
        # parity) instead of masking the vocab into NaNs
        SamplingParams(temperature=0.7, top_p=0.0, seed=13),
    ):
        b = ContinuousBatcher(
            params, config, max_batch=1, n_pages=16, page_size=4,
            max_pages_per_seq=4,
        )
        r = b.submit(prompt, 5, sampling=sp)
        b.run_to_completion()
        assert b.result(r) == want, sp


def test_sampling_params_validated():
    from bee_code_interpreter_tpu.models.serving import SamplingParams

    with pytest.raises(ValueError, match="top_k must be >= 1"):
        SamplingParams(top_k=0)
    with pytest.raises(ValueError, match="temperature must be >= 0"):
        SamplingParams(temperature=-1.0)


def test_host_filter_parity_with_device():
    # The host sampler must draw from EXACTLY the distribution the device
    # filter defines — not just on degenerate cases: random logits (with
    # planted ties to exercise tie semantics) across a top-k/top-p grid,
    # comparing the full filtered probability vectors.
    from bee_code_interpreter_tpu.models.serving import (
        SamplingParams,
        filtered_probs_host,
    )
    from bee_code_interpreter_tpu.models.transformer import filter_logits

    rng = np.random.default_rng(0)
    V = 64
    for trial in range(4):
        logits = rng.normal(size=V).astype(np.float32)
        logits[5] = logits[9]  # planted tie
        for temperature in (0.5, 1.3):
            for top_k in (None, 1, 7, V):
                for top_p in (None, 0.0, 0.3, 0.95, 1.0):
                    params = SamplingParams(
                        temperature=temperature, top_k=top_k, top_p=top_p
                    )
                    host = filtered_probs_host(logits, params)
                    dev = np.asarray(
                        jax.nn.softmax(
                            filter_logits(
                                jnp.asarray(logits)[None, :] / temperature,
                                top_k, top_p,
                            ),
                            axis=-1,
                        )[0]
                    )
                    np.testing.assert_allclose(
                        host, dev, atol=1e-6, rtol=1e-5,
                        err_msg=f"t={temperature} k={top_k} p={top_p}",
                    )


def test_failed_submit_does_not_leak_pages():
    # An admission that fails AFTER pages were allocated (here: top_k
    # larger than the vocab blows up in the first-token draw) must return
    # its pages and leave the row free — otherwise repeated failures drain
    # the pool permanently.
    from bee_code_interpreter_tpu.models.serving import SamplingParams

    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    b = ContinuousBatcher(
        params, config, max_batch=1, n_pages=8, page_size=4,
        max_pages_per_seq=4,
    )
    free0 = len(b.free_pages)
    bad = SamplingParams(temperature=1.0, top_k=config.vocab_size + 1)
    for _ in range(3):
        with pytest.raises(Exception):
            b.submit(np.asarray([1, 2, 3]), 4, sampling=bad)
    assert len(b.free_pages) == free0
    assert not b.active.any()
    # the pool still admits a good request afterwards
    req = b.submit(np.asarray([1, 2, 3]), 4)
    b.run_to_completion()
    assert b.result(req) == reference_tokens(params, config, [1, 2, 3], 4)


def test_release_frees_results():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    b = ContinuousBatcher(
        params, config, max_batch=1, n_pages=8, page_size=4,
        max_pages_per_seq=4,
    )
    req = b.submit(np.asarray([5, 6]), 3)
    with pytest.raises(RuntimeError, match="still decoding"):
        b.release(req)
    b.run_to_completion()
    b.result(req)
    b.release(req)
    assert req not in b.results
    assert b.is_done(req)  # terminal state stays observable after release
    with pytest.raises(KeyError, match="released"):
        b.result(req)


def test_chunked_admission_matches_one_shot():
    # submit(prefill_chunk=...) — the bounded-memory long-prompt admission —
    # must produce the same tokens as the one-shot O(L^2) admission (f32
    # config: the chunked prefill is pinned exactly equal to the full
    # forward, so the whole request pipeline must agree).
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = np.asarray(jax.random.randint(jax.random.PRNGKey(21), (11,), 0,
                                           config.vocab_size))

    def run(**kw):
        b = ContinuousBatcher(
            params, config, max_batch=1, n_pages=16, page_size=4,
            max_pages_per_seq=4,
        )
        r = b.submit(prompt, 5, **kw)
        b.run_to_completion()
        return b.result(r)

    assert run(prefill_chunk=4) == run()


def test_chunked_admission_int8_matches_generate_cached():
    # int8 + chunked admission: the pool is seeded by VERBATIM copy of the
    # chunked cache's int8 leaves (never re-quantized), so the batcher
    # equals generate_cached(prefill_chunk=...) on the same config.
    config = cfg(kv_cache_dtype="int8")
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = np.asarray(jax.random.randint(jax.random.PRNGKey(22), (9,), 0,
                                           config.vocab_size))
    want = np.asarray(T.Transformer(config).generate_cached(
        params, jnp.asarray(prompt)[None, :], max_new_tokens=4,
        prefill_chunk=4,
    )[0, len(prompt):]).tolist()
    b = ContinuousBatcher(
        params, config, max_batch=1, n_pages=16, page_size=4,
        max_pages_per_seq=4,
    )
    r = b.submit(prompt, 4, prefill_chunk=4)
    b.run_to_completion()
    assert b.result(r) == want


def draft_cfg():
    return dataclasses.replace(
        T.TransformerConfig.tiny(), dtype=jnp.float32, n_layers=1,
        d_model=32, n_heads=2, d_ff=64,
    )


def test_speculative_serving_matches_solo_greedy():
    # Speculative continuous batching: staggered heterogeneous requests,
    # an unrelated random draft, per-row accept lengths — every request
    # must equal its solo greedy decode token-for-token.
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    dparams = T.init_params(draft_cfg(), jax.random.PRNGKey(42))
    prompts = [
        np.asarray(jax.random.randint(jax.random.PRNGKey(60 + i), (L,), 0,
                                      config.vocab_size))
        for i, L in enumerate([3, 7, 5])
    ]
    want = [reference_tokens(params, config, p, 6) for p in prompts]

    b = ContinuousBatcher(
        params, config, max_batch=2, n_pages=24, page_size=4,
        max_pages_per_seq=6, draft_params=dparams, draft_config=draft_cfg(),
        gamma=3,
    )
    r0 = b.submit(prompts[0], 6)
    r1 = b.submit(prompts[1], 6)
    b.step()
    with pytest.raises(RuntimeError, match="no free batch row"):
        b.submit(prompts[2], 6)
    b.run_to_completion()
    r2 = b.submit(prompts[2], 6)
    b.run_to_completion()
    assert [b.result(r) for r in (r0, r1, r2)] == want


def test_speculative_serving_perfect_draft_fewer_rounds():
    # draft == target: every proposal accepted, so a request finishes in
    # ~max_new/(gamma+1) rounds instead of max_new — and stays exact.
    #
    # The prompt is chosen TIE-FREE: the draft proposes via the one-token
    # decode_step_paged and the target verifies via the windowed
    # decode_window_paged — different XLA programs whose reduction order
    # can differ by ~1e-6 (and flip between standalone and in-suite runs,
    # which is how the old [5, 3, 8, 2] fixture went env-sensitive). Along
    # this prompt's greedy path every top-2 logit gap is >= 0.02 (paged
    # paths >= 0.037 measured), so the argmax is deterministic in any run
    # order. The canary below fails loudly — instead of flaking — if a
    # config/seed change ever erodes that margin.
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompt = np.asarray([8, 2, 5, 9])
    want = reference_tokens(params, config, prompt, 8)
    toks = prompt.tolist()
    for tok in want:
        last = T.forward(params, jnp.asarray(toks)[None, :], config)[0, -1, :]
        top2 = np.sort(np.asarray(last, dtype=np.float64))[-2:]
        assert top2[1] - top2[0] > 0.01, (
            "fixture no longer tie-free: re-pick a prompt with a clear "
            f"argmax margin (got {top2[1] - top2[0]:.2e} at {len(toks)})"
        )
        toks.append(tok)
    b = ContinuousBatcher(
        params, config, max_batch=1, n_pages=16, page_size=4,
        max_pages_per_seq=4, draft_params=params, draft_config=config,
        gamma=3,
    )
    r = b.submit(prompt, 8)
    rounds = 0
    while not b.is_done(r):
        b.step()
        rounds += 1
    assert b.result(r) == want
    assert rounds <= 3  # ceil((8-1)/(gamma+1)) = 2 plus slack


def test_speculative_serving_int8_target():
    # The full stack composed: speculative + paged + int8 target pool must
    # equal the solo int8 greedy decode (the draft stays bf16 — drafts
    # only propose).
    config = cfg(kv_cache_dtype="int8")
    params = T.init_params(config, jax.random.PRNGKey(0))
    dparams = T.init_params(draft_cfg(), jax.random.PRNGKey(42))
    prompt = np.asarray([7, 1, 6, 3, 9])
    want = reference_tokens(params, config, prompt, 6)
    b = ContinuousBatcher(
        params, config, max_batch=1, n_pages=16, page_size=4,
        max_pages_per_seq=4, draft_params=dparams, draft_config=draft_cfg(),
        gamma=3,
    )
    r = b.submit(prompt, 6)
    b.run_to_completion()
    assert b.result(r) == want


def test_speculative_rounds_pool_history_independent():
    # Pages are zeroed at admission, so a request's round count (draft
    # acceptance) must not depend on what a PREVIOUS request left in the
    # recycled pages — throughput isolation, not just output isolation.
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    dparams = T.init_params(draft_cfg(), jax.random.PRNGKey(42))
    prompt = np.asarray([6, 2, 9, 1])

    def make():
        return ContinuousBatcher(
            params, config, max_batch=1, n_pages=16, page_size=4,
            max_pages_per_seq=4, draft_params=dparams,
            draft_config=draft_cfg(), gamma=3,
        )

    def run(b):
        r = b.submit(prompt, 6)
        n = 0
        while not b.is_done(r):
            b.step()
            n += 1
        return b.result(r), n

    out_fresh, n_fresh = run(make())
    dirty = make()
    r0 = dirty.submit(np.asarray([8, 8, 8, 8, 8, 8, 8]), 6)  # dirty the pool
    dirty.run_to_completion()
    out_reused, n_reused = run(dirty)
    assert out_fresh == out_reused
    assert n_fresh == n_reused


def test_speculative_serving_validations():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    dparams = T.init_params(draft_cfg(), jax.random.PRNGKey(1))
    with pytest.raises(ValueError, match="share a vocabulary"):
        ContinuousBatcher(
            params, config,
            draft_params=dparams,
            draft_config=dataclasses.replace(draft_cfg(), vocab_size=17),
        )
    with pytest.raises(ValueError, match="BOTH draft_params"):
        ContinuousBatcher(params, config, draft_params=dparams)
    with pytest.raises(ValueError, match="gamma"):
        ContinuousBatcher(
            params, config, draft_params=dparams, draft_config=draft_cfg(),
            gamma=0,
        )
    from bee_code_interpreter_tpu.models.serving import SamplingParams

    b = ContinuousBatcher(
        params, config, max_batch=1, n_pages=16, page_size=4,
        max_pages_per_seq=4, draft_params=dparams, draft_config=draft_cfg(),
    )
    # sampled speculative is supported since round 4 (rejection sampling,
    # tests/test_speculative_sampling.py); steering is still refused
    with pytest.raises(ValueError, match="unsteered argmax"):
        b.submit(np.asarray([1, 2]), 3,
                 sampling=SamplingParams(logit_bias={1: 5.0}))
    r = b.submit(np.asarray([1, 2]), 3,
                 sampling=SamplingParams(temperature=1.0))
    b.run_to_completion()
    assert len(b.result(r)) == 3


def test_speculative_serving_eos_stops_early():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    dparams = T.init_params(draft_cfg(), jax.random.PRNGKey(7))
    prompt = np.asarray([4, 9, 2])
    solo = reference_tokens(params, config, prompt, 8)
    stop_at = next(
        (i for i in range(1, len(solo)) if solo[i] not in solo[:i]), None
    )
    if stop_at is None:
        pytest.skip("greedy output has no late first-occurrence token")
    b = ContinuousBatcher(
        params, config, max_batch=1, n_pages=16, page_size=4,
        max_pages_per_seq=6, draft_params=dparams, draft_config=draft_cfg(),
        eos_id=solo[stop_at], gamma=3,
    )
    r = b.submit(prompt, 8)
    b.run_to_completion()
    assert b.result(r) == solo[: stop_at + 1]


def test_int8_pool_matches_solo_int8_decode():
    # The int8 paged pool (scale planes per page) must reproduce the solo
    # int8 contiguous decode — both quantize per (token, head) row, so the
    # cache evolutions are identical.
    config = cfg(kv_cache_dtype="int8")
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompts = [
        np.asarray(jax.random.randint(jax.random.PRNGKey(i + 30), (L,), 0,
                                      config.vocab_size))
        for i, L in enumerate([4, 9])
    ]
    want = [reference_tokens(params, config, p, 5) for p in prompts]
    b = ContinuousBatcher(
        params, config, max_batch=2, n_pages=16, page_size=4,
        max_pages_per_seq=4,
    )
    reqs = [b.submit(p, 5) for p in prompts]
    b.run_to_completion()
    assert [b.result(r) for r in reqs] == want


def moe_dropless_cfg():
    return dataclasses.replace(
        T.TransformerConfig.tiny_moe(), moe_dropless=True,
        moe_group_size=1, dtype=jnp.float32
    )


def test_moe_dropless_serving_matches_solo_decode():
    """With dropless routing no token can be evicted, so routing is per-
    token independent and the batcher's solo-equality bar — previously
    dense-only — extends to MoE: each request's output equals its own solo
    greedy decode, whatever shares the batch."""
    config = moe_dropless_cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompts = [
        np.asarray(jax.random.randint(jax.random.PRNGKey(i + 1), (L,), 0,
                                      config.vocab_size))
        for i, L in enumerate([3, 7, 5])
    ]
    want = [reference_tokens(params, config, p, 5) for p in prompts]
    b = ContinuousBatcher(
        params, config, max_batch=2, n_pages=32, page_size=4,
        max_pages_per_seq=8,
    )
    r0 = b.submit(prompts[0], 5)
    b.step()  # staggered admission: r1 joins mid-decode of r0
    r1 = b.submit(prompts[1], 5)
    b.run_to_completion()
    r2 = b.submit(prompts[2], 5)
    b.run_to_completion()
    assert b.result(r0) == want[0]
    assert b.result(r1) == want[1]
    assert b.result(r2) == want[2]


def test_moe_dropless_prefix_cache_accepted_and_exact():
    """The prefix-cache guard lifts for dropless configs: shared-prefix
    admissions reuse pages AND still reproduce solo decode exactly."""
    config = moe_dropless_cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    shared = np.asarray(jax.random.randint(jax.random.PRNGKey(9), (8,), 0,
                                           config.vocab_size))
    p1 = np.concatenate([shared, [1, 2]])
    p2 = np.concatenate([shared, [3]])
    want1 = reference_tokens(params, config, p1, 4)
    want2 = reference_tokens(params, config, p2, 4)
    b = ContinuousBatcher(
        params, config, max_batch=2, n_pages=32, page_size=4,
        max_pages_per_seq=8, prefix_cache=True,
    )
    r1 = b.submit(p1, 4)
    b.run_to_completion()
    r2 = b.submit(p2, 4)  # shares the prefix pages of r1
    b.run_to_completion()
    assert b.prefix_stats["hits"] >= 1
    assert b.result(r1) == want1
    assert b.result(r2) == want2


def test_snapshot_resume_matches_uninterrupted_run():
    """Preemption recovery: snapshot mid-decode, restore into a FRESH
    batcher (fresh jits, fresh pools), finish there — tokens, logprobs,
    finish reasons, and page accounting must equal the uninterrupted run,
    including a request admitted only after the restore."""
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    prompts = [[5, 3, 7, 2, 9, 4, 1, 8], [1, 2, 3], [4, 4, 2, 6]]

    def make():
        return ContinuousBatcher(
            params, config, max_batch=2, n_pages=24, page_size=4,
            max_pages_per_seq=6,
        )

    # uninterrupted reference
    ref = make()
    r0 = ref.submit(prompts[0], 6, sampling=SamplingParams(
        temperature=0.8, top_k=40, seed=7, logprobs=True))
    r1 = ref.submit(prompts[1], 6)
    for _ in range(3):
        ref.step()
    ref.run_to_completion()
    r2 = ref.submit(prompts[2], 5)
    ref.run_to_completion()

    # interrupted run: 3 steps, snapshot, resume elsewhere
    a = make()
    a0 = a.submit(prompts[0], 6, sampling=SamplingParams(
        temperature=0.8, top_k=40, seed=7, logprobs=True))
    a1 = a.submit(prompts[1], 6)
    for _ in range(3):
        a.step()
    snap = a.state_dict()
    del a  # the preempted host is gone

    b = make()
    b.load_state_dict(snap)
    b.run_to_completion()
    b2 = b.submit(prompts[2], 5)  # post-restore admission reuses pages
    b.run_to_completion()

    assert b.result(a0) == ref.result(r0)
    assert b.result_logprobs(a0) == ref.result_logprobs(r0)
    assert b.result(a1) == ref.result(r1)
    assert b.result(b2) == ref.result(r2)
    assert b.finish_reason(a0) == ref.finish_reason(r0)
    assert sorted(b.free_pages) == sorted(ref.free_pages)


def test_snapshot_survives_pickle_and_geometry_is_checked():
    import pickle

    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    b1 = ContinuousBatcher(
        params, config, max_batch=2, n_pages=24, page_size=4,
        max_pages_per_seq=6,
    )
    r = b1.submit([5, 3, 7, 2], 4, sampling=SamplingParams(seed=3))
    b1.step()
    blob = pickle.dumps(b1.state_dict())  # disk-persistable
    want = None
    b1.run_to_completion()
    want = b1.result(r)

    b2 = ContinuousBatcher(
        params, config, max_batch=2, n_pages=24, page_size=4,
        max_pages_per_seq=6,
    )
    b2.load_state_dict(pickle.loads(blob))
    b2.run_to_completion()
    assert b2.result(r) == want

    wrong = ContinuousBatcher(
        params, config, max_batch=2, n_pages=32, page_size=4,
        max_pages_per_seq=6,
    )
    with pytest.raises(ValueError, match="geometry mismatch"):
        wrong.load_state_dict(pickle.loads(blob))


def test_snapshot_while_serving_continues_is_stable():
    """Periodic-checkpoint pattern: the snapshot must own its memory — the
    decode jits donate the pool buffer, so further step()s after
    state_dict() must not corrupt an earlier snapshot."""
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    kw = dict(max_batch=2, n_pages=24, page_size=4, max_pages_per_seq=6)
    a = ContinuousBatcher(params, config, **kw)
    r = a.submit([5, 3, 7, 2, 9], 6)
    for _ in range(2):
        a.step()
    snap = a.state_dict()
    frozen = {k: v.copy() for k, v in snap["device"]["cache"].items()}
    a.run_to_completion()  # keeps serving; donates the pool repeatedly
    want = a.result(r)
    for k in frozen:
        np.testing.assert_array_equal(frozen[k], snap["device"]["cache"][k])
    b = ContinuousBatcher(params, config, **kw)
    b.load_state_dict(snap)
    b.run_to_completion()
    assert b.result(r) == want


def test_snapshot_geometry_checks_behavioral_fields():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    kw = dict(max_batch=2, n_pages=24, page_size=4, max_pages_per_seq=6)
    snap = ContinuousBatcher(params, config, eos_id=2, **kw).state_dict()
    other = ContinuousBatcher(params, config, eos_id=None, **kw)
    with pytest.raises(ValueError, match="eos_id"):
        other.load_state_dict(snap)
