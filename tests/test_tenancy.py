"""Multi-tenant isolation acceptance (ISSUE 13): identity resolution at
both edges, weighted-fair admission under saturation, per-tenant quota
verdicts on both transports, per-tenant SLO slices / usage metering /
session caps, the shed-after-wait demand-accounting regression, and the
chaos scenario 15 tier-1 twin (one abusive tenant floods 100x its quota
through the real HTTP edge over the fake-pod stack; everyone else's
latency, sheds, and error budgets are provably untouched)."""

import asyncio
import statistics
import time

import grpc.aio
import pytest
from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_tpu.api.grpc_server import (
    GrpcServer,
    observability_stubs,
    service_stubs,
)
from bee_code_interpreter_tpu.api.http_server import create_http_server
from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.observability import (
    DemandTracker,
    FlightRecorder,
    SloEngine,
    Tracer,
    parse_objectives,
)
from bee_code_interpreter_tpu.proto import code_interpreter_pb2 as pb
from bee_code_interpreter_tpu.resilience import (
    AdmissionController,
    AdmissionRejected,
)
from bee_code_interpreter_tpu.services.code_executor import Result
from bee_code_interpreter_tpu.services.custom_tool_executor import CustomToolExecutor
from bee_code_interpreter_tpu.services.kubernetes_code_executor import (
    KubernetesCodeExecutor,
)
from bee_code_interpreter_tpu.sessions import SessionLimitExceeded, SessionManager
from bee_code_interpreter_tpu.tenancy import (
    TENANT_HEADER,
    TenantRegistry,
    bearer_token,
    parse_tenants,
    tenant_scope,
)
from bee_code_interpreter_tpu.utils.metrics import Registry
from tests.chaos import FaultPlan, ManualClock
from tests.fakes import FakeExecutorPods, FakeKubectl

pytestmark = pytest.mark.chaos


class EchoExecutor:
    async def execute(self, source_code, files=None, env=None, timeout_s=None,
                      deadline=None):
        return Result(stdout="ok\n", stderr="", exit_code=0, files={})


def make_app(executor, admission, metrics, tenancy, slo=None, **kwargs):
    return create_http_server(
        code_executor=executor,
        custom_tool_executor=CustomToolExecutor(code_executor=executor),
        metrics=metrics,
        admission=admission,
        request_deadline_s=30.0,
        tenancy=tenancy,
        slo=slo,
        **kwargs,
    )


# ----------------------------------------------------------------- grammar


def test_parse_tenants_grammar_and_default_catch_all():
    tenants = parse_tenants(
        "alpha:weight=4:max_in_flight=8:rps=20,beta:weight=1:rps=5:burst=10,"
        "gold:key=sk-gold:sessions=2"
    )
    assert tenants["alpha"].weight == 4.0
    assert tenants["alpha"].max_in_flight == 8
    assert tenants["alpha"].rps == 20.0
    assert tenants["alpha"].burst_depth == 20.0  # default burst = rps
    assert tenants["beta"].burst_depth == 10.0
    assert tenants["gold"].api_key == "sk-gold"
    assert tenants["gold"].max_sessions == 2
    # the catch-all is implied, unlimited
    assert tenants["default"].rps is None
    assert tenants["default"].max_in_flight is None

    # a declared default customizes the catch-all instead
    tenants = parse_tenants("default:weight=2:rps=3")
    assert tenants["default"].weight == 2.0


@pytest.mark.parametrize(
    "bad",
    [
        "alpha:weight=0",  # weight must be > 0
        "alpha:rps=-1",
        "alpha:nope=1",  # unknown attribute
        "alpha:weight",  # not key=value
        "alpha,alpha",  # duplicate
        "a:key=k,b:key=k",  # duplicate API key
    ],
)
def test_parse_tenants_rejects_malformed_specs(bad):
    with pytest.raises(ValueError):
        parse_tenants(bad)


def test_registry_resolution_and_bounded_unknown_labels():
    registry = TenantRegistry(
        parse_tenants("alpha:weight=2,gold:key=sk-gold"), max_labels=3
    )
    assert registry.resolve("alpha").tenant.id == "alpha"
    # API key wins, header unnecessary
    assert registry.resolve(None, api_key="sk-gold").tenant.id == "gold"
    assert bearer_token("Bearer sk-gold") == "sk-gold"
    assert bearer_token("Basic abc") is None
    # anonymous -> default
    anon = registry.resolve(None)
    assert anon.tenant.id == "default" and anon.label == "default"
    # unknown ids share the default tenant's lane with a bounded label
    u1 = registry.resolve("mystery-1")
    assert u1.tenant.id == "default" and u1.label == "mystery-1"
    for i in range(5):
        registry.resolve(f"flood-{i}")
    overflowed = registry.resolve("flood-99")
    assert overflowed.label == "other"
    assert registry.unknown_overflow >= 1
    # hostile ids are sanitized before becoming labels
    hostile = registry.resolve('evil"\n' + "x" * 200)
    assert '"' not in hostile.label and "\n" not in hostile.label
    assert len(hostile.label) <= 64


# --------------------------------------------------------------------- WFQ


async def test_wfq_grants_track_weights_under_saturation():
    """The WFQ math: with three saturated tenants weighted 4:2:1 over ONE
    execution slot, the grant mix over a full backlog tracks the weights
    within +/-10% — arrival order stops mattering."""
    registry = TenantRegistry(parse_tenants("a:weight=4,b:weight=2,c:weight=1"))
    # ManualClock: the DRR math must not depend on wall time at all — the
    # token buckets (the only clock consumer) stay frozen throughout.
    admission = AdmissionController(
        max_in_flight=1, max_queue=1000, tenancy=registry, clock=ManualClock()
    )
    release = asyncio.Event()
    order: list[str] = []

    async def blocker():
        async with admission.admit(tenant=registry.resolve("a")):
            await release.wait()

    async def one(name: str):
        async with admission.admit(tenant=registry.resolve(name)):
            order.append(name)

    holder = asyncio.create_task(blocker())
    while admission.in_flight < 1:
        await asyncio.sleep(0.001)
    per_tenant = 30
    tasks = [
        asyncio.create_task(one(name))
        for _ in range(per_tenant)
        for name in ("c", "b", "a")  # adversarial arrival order
    ]
    while admission.queue_depth < 3 * per_tenant:
        await asyncio.sleep(0.001)
    release.set()
    await holder
    await asyncio.gather(*tasks)
    assert len(order) == 3 * per_tenant

    # While ALL three tenants still have backlog, shares must track the
    # weights within 10%. a (weight 4) drains its 30-deep queue first,
    # after ~30/4 rounds of 7 grants — 49 grants is safely inside that.
    window = order[: 7 * 7]
    for name, weight in (("a", 4), ("b", 2), ("c", 1)):
        share = window.count(name) / len(window)
        assert abs(share - weight / 7) <= 0.10 * weight / 7 + 1 / len(window), (
            name, share, window[:21],
        )


async def test_tenant_concurrency_cap_queues_not_starves():
    """A tenant over its max_in_flight queues in ITS lane while other
    tenants keep flowing through the free global slots."""
    registry = TenantRegistry(parse_tenants("small:max_in_flight=1,big:weight=1"))
    admission = AdmissionController(max_in_flight=4, max_queue=16, tenancy=registry)
    small_gate = asyncio.Event()
    done: list[str] = []

    async def small_hold():
        async with admission.admit(tenant=registry.resolve("small")):
            await small_gate.wait()

    async def small_second():
        async with admission.admit(tenant=registry.resolve("small")):
            done.append("small2")

    async def big():
        async with admission.admit(tenant=registry.resolve("big")):
            done.append("big")

    holder = asyncio.create_task(small_hold())
    while admission.in_flight < 1:
        await asyncio.sleep(0.001)
    second = asyncio.create_task(small_second())
    while admission.queue_depth < 1:
        await asyncio.sleep(0.001)
    # big sails past the queued small request (global slots are free)
    await asyncio.wait_for(big(), timeout=2.0)
    assert done == ["big"]
    assert not second.done()
    small_gate.set()
    await holder
    await asyncio.wait_for(second, timeout=2.0)
    assert done == ["big", "small2"]


async def test_solo_backlog_cannot_bankrupt_a_lane():
    """Review regression: a lane served solo (the single-eligible dispatch
    path skips top-ups) must not accrue unbounded deficit debt — otherwise
    the moment a second tenant starts queuing, the weights invert until
    the debt is paid off and the HIGH-weight tenant is starved."""
    from bee_code_interpreter_tpu.resilience.admission import (
        _DEFICIT_CAP_ROUNDS,
        _REQUEST_COST,
    )

    registry = TenantRegistry(parse_tenants("a:weight=4,b:weight=1"))
    admission = AdmissionController(
        max_in_flight=1, max_queue=200, tenancy=registry, clock=ManualClock()
    )
    order: list[str] = []
    admitted_gates: list[asyncio.Event] = []

    async def one(name: str):
        gate = asyncio.Event()
        async with admission.admit(tenant=registry.resolve(name)):
            order.append(name)
            admitted_gates.append(gate)
            await gate.wait()

    async def serve_until(n: int) -> None:
        while len(order) < n:
            if admitted_gates:
                admitted_gates[-1].set()
            await asyncio.sleep(0.001)

    tasks = [asyncio.create_task(one("a")) for _ in range(50)]
    while admission.queue_depth < 49:
        await asyncio.sleep(0.001)
    # Serve 40 solo grants while a's queue STAYS non-empty (no idle reset).
    await serve_until(41)
    lane = admission._lane_for(registry.resolve("a"))
    floor = -lane.tenant.weight * _DEFICIT_CAP_ROUNDS
    assert lane.deficit >= floor - _REQUEST_COST, lane.deficit
    # A second tenant arriving now is not handed an inverted schedule:
    # a's bounded debt pays off within a few rounds and both keep flowing.
    b_tasks = [asyncio.create_task(one("b")) for _ in range(5)]
    while admission.queue_depth < 14:
        await asyncio.sleep(0.001)
    await serve_until(55)
    admitted_gates[-1].set()
    await asyncio.gather(*tasks, *b_tasks)
    mixed = order[41:]
    assert "a" in mixed[:8] and "b" in mixed[:8], mixed


# ----------------------------------------------------- quota verdicts: HTTP


async def test_http_tenant_rate_quota_sheds_429_tenant_quota():
    clock = ManualClock(100.0)
    registry = TenantRegistry(parse_tenants("alpha:rps=1:burst=1"))
    metrics = Registry()
    admission = AdmissionController(
        max_in_flight=8, max_queue=8, metrics=metrics, tenancy=registry,
        clock=clock,
    )
    client = TestClient(
        TestServer(make_app(EchoExecutor(), admission, metrics, registry))
    )
    await client.start_server()
    try:
        headers = {TENANT_HEADER: "alpha"}
        body = {"source_code": "print(1)"}
        r1 = await client.post("/v1/execute", json=body, headers=headers)
        assert r1.status == 200
        r2 = await client.post("/v1/execute", json=body, headers=headers)
        assert r2.status == 429
        payload = await r2.json()
        assert payload["reason"] == "tenant_quota"
        assert "tenant_quota" in payload["detail"]
        assert int(r2.headers["Retry-After"]) >= 1
        # other tenants are untouched by alpha's quota
        r3 = await client.post(
            "/v1/execute", json=body, headers={TENANT_HEADER: "someone-else"}
        )
        assert r3.status == 200
        # the bucket refills with time
        clock.advance(1.5)
        r4 = await client.post("/v1/execute", json=body, headers=headers)
        assert r4.status == 200
        text = metrics.expose()
        assert (
            'bci_tenant_shed_total{reason="tenant_quota",tenant="alpha"} 1'
            in text
        )
        # /v1/tenants carries the same verdict
        snap = await (await client.get("/v1/tenants")).json()
        assert snap["tenants"]["alpha"]["admission"]["sheds"] == {
            "tenant_quota": 1
        }
        assert snap["tenants"]["alpha"]["usage"]["sheds"] == 1
    finally:
        await client.close()


# ----------------------------------------------------- quota verdicts: gRPC


async def test_grpc_tenant_rate_quota_resource_exhausted():
    clock = ManualClock(100.0)
    registry = TenantRegistry(parse_tenants("alpha:rps=1:burst=1"))
    admission = AdmissionController(
        max_in_flight=8, max_queue=8, tenancy=registry, clock=clock
    )
    executor = EchoExecutor()
    server = GrpcServer(
        code_executor=executor,
        custom_tool_executor=CustomToolExecutor(code_executor=executor),
        admission=admission,
        request_deadline_s=30.0,
        tenancy=registry,
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stubs = service_stubs(channel)
            req = pb.ExecuteRequest(source_code="print(1)")
            metadata = (("x-tenant-id", "alpha"),)
            resp = await stubs["Execute"](req, metadata=metadata)
            assert resp.stdout == "ok\n"
            with pytest.raises(grpc.aio.AioRpcError) as exc:
                await stubs["Execute"](req, metadata=metadata)
            assert exc.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
            assert "tenant_quota" in exc.value.details()
            # anonymous traffic shares the (unlimited) default lane
            resp = await stubs["Execute"](req)
            assert resp.stdout == "ok\n"
            # the GetTenants mirror reports the shed
            import json as _json

            obs = observability_stubs(channel)
            snap = _json.loads(await obs["GetTenants"](b""))
            assert (
                snap["tenants"]["alpha"]["admission"]["sheds"]["tenant_quota"]
                == 1
            )
    finally:
        await server.stop(None)


# ------------------------------------------------ shed-after-wait regression


async def test_shed_after_wait_releases_demand_sample_exactly_once():
    """Regression (ISSUE 13 bugfix): a queued waiter that is shed after
    waiting — including one whose slot grant races its abandonment — must
    produce exactly ONE demand-tracker shed, ZERO admitted samples, and
    return the granted slot, never leak it."""
    demand = DemandTracker()
    admission = AdmissionController(
        max_in_flight=1, max_queue=4, demand=demand
    )
    lane = admission._lane_for(None)

    # The race, reproduced white-box: a waiter is granted by dispatch and
    # abandoned (its wait timed out) before it could proceed.
    fut = asyncio.get_running_loop().create_future()
    lane.waiters.append(fut)
    admission._queued += 1
    admission._dispatch()
    assert fut.done() and admission.in_flight == 1
    admission._abandon_wait(fut, lane)
    assert admission.in_flight == 0  # the granted slot came back exactly once
    assert admission.queue_depth == 0

    # End-to-end: a waiter behind a stuck holder sheds at its queue bound.
    from bee_code_interpreter_tpu.resilience import Deadline

    release = asyncio.Event()

    async def holder():
        async with admission.admit():
            await release.wait()

    task = asyncio.create_task(holder())
    while admission.in_flight < 1:
        await asyncio.sleep(0.001)
    with pytest.raises(AdmissionRejected) as exc:
        async with admission.admit(deadline=Deadline.after(0.05)):
            raise AssertionError("must shed, not admit")
    assert exc.value.reason == "queue_timeout"
    release.set()
    await task
    assert admission.in_flight == 0 and admission.queue_depth == 0
    # demand ledger: 2 arrivals (holder + waiter), 1 admitted, 1 shed —
    # the shed waiter contributed exactly one shed and no admitted sample.
    assert demand.arrivals_total == 2
    assert demand.sheds_total == 1
    admitted = sum(b.admitted for b in demand._buckets.values())
    assert admitted == 1
    # and the gate still works (no leaked slot or phantom queue entry)
    async with admission.admit():
        pass


# ------------------------------------------------------------ retry budgets


async def test_tenant_retry_budget_fails_fast_when_exhausted():
    clock = ManualClock(50.0)
    registry = TenantRegistry(parse_tenants("alpha:rps=10"))
    admission = AdmissionController(tenancy=registry, clock=clock)
    ctx = registry.resolve("alpha")
    spend = admission.tenant_retry_budget(ctx)
    assert spend is not None
    # burst of 10 retry tokens, then dry until time passes
    assert all(spend() for _ in range(10))
    assert spend() is False
    clock.advance(1.0)  # 10 rps * 10% = 1 retry token per second
    assert spend() is True
    assert spend() is False

    # unlimited tenants get no budget: pre-tenancy retry behavior
    assert admission.tenant_retry_budget(registry.resolve(None)) is None

    # the retry loop consults the ambient budget and fails fast
    from bee_code_interpreter_tpu.resilience.retry import RetryPolicy, retryable

    class Flaky:
        policy = RetryPolicy(attempts=3, wait_min_s=0.001, wait_max_s=0.002)
        calls = 0

        @retryable("policy", "flaky-op")
        async def run(self):
            self.calls += 1
            raise RuntimeError("transient")

    ctx.retry_budget = lambda: False  # budget already dry
    flaky = Flaky()
    with tenant_scope(ctx):
        with pytest.raises(RuntimeError):
            await flaky.run()
    assert flaky.calls == 1  # failed fast: no retry attempts burned

    flaky2 = Flaky()
    with pytest.raises(RuntimeError):
        await flaky2.run()  # outside any tenant scope: retries as before
    assert flaky2.calls == 3


# ------------------------------------------------------- session tenant caps


async def test_per_tenant_session_cap_429(storage, tmp_path):
    pods = FakeExecutorPods(tmp_path / "pods")
    config = Config(
        executor_backend="kubernetes",
        executor_port=pods.port,
        executor_pod_queue_target_length=3,
        pod_ready_timeout_s=5,
        executor_retry_attempts=1,
    )
    k8s = KubernetesCodeExecutor(
        kubectl=FakeKubectl(pods), storage=storage, config=config,
        ip_poll_interval_s=0.02,
    )
    registry = TenantRegistry(parse_tenants("alpha:sessions=1,beta:weight=1"))
    manager = SessionManager(k8s, storage, max_sessions=8)
    try:
        await k8s.fill_executor_pod_queue()
        with tenant_scope(registry.resolve("alpha")):
            first = await manager.create()
            with pytest.raises(SessionLimitExceeded) as exc:
                await manager.create()
            assert "alpha" in str(exc.value)
        # beta (and the global cap) are untouched by alpha's cap
        with tenant_scope(registry.resolve("beta")):
            second = await manager.create()
        assert manager.tenant_counts() == {"alpha": 1, "beta": 1}
        assert manager.snapshot()["by_tenant"] == {"alpha": 1, "beta": 1}
        # releasing frees alpha's slot
        await manager.release(first.session_id)
        with tenant_scope(registry.resolve("alpha")):
            third = await manager.create()
        await manager.release(second.session_id)
        await manager.release(third.session_id)
    finally:
        await manager.close_all()
        await k8s.aclose()
        await pods.close()


async def test_default_session_cap_not_multiplied_by_spoofed_ids(
    storage, tmp_path
):
    """Review regression: unknown X-Tenant-Id values share the DEFAULT
    tenant's session allotment — each spoofed id must not get a fresh
    quota (the cap is keyed on the resolved tenant, not the label)."""
    pods = FakeExecutorPods(tmp_path / "pods-spoof")
    config = Config(
        executor_backend="kubernetes",
        executor_port=pods.port,
        executor_pod_queue_target_length=2,
        pod_ready_timeout_s=5,
        executor_retry_attempts=1,
    )
    k8s = KubernetesCodeExecutor(
        kubectl=FakeKubectl(pods), storage=storage, config=config,
        ip_poll_interval_s=0.02,
    )
    registry = TenantRegistry(parse_tenants("default:sessions=1"))
    manager = SessionManager(k8s, storage, max_sessions=8)
    try:
        await k8s.fill_executor_pod_queue()
        with tenant_scope(registry.resolve("spoof-1")):
            first = await manager.create()
        with tenant_scope(registry.resolve("spoof-2")):
            with pytest.raises(SessionLimitExceeded) as exc:
                await manager.create()
        assert "default" in str(exc.value)
        # the label still shows WHO held the lease
        assert manager.tenant_counts() == {"spoof-1": 1}
        await manager.release(first.session_id)
    finally:
        await manager.close_all()
        await k8s.aclose()
        await pods.close()


# ------------------------------------------------- chaos scenario 15 (twin)


async def test_chaos15_twin_abusive_tenant_cannot_touch_the_others(
    storage, tmp_path
):
    """One tenant floods 100x its rate quota through the REAL HTTP edge
    over the fake-pod stack. The victims' p50 stays within 10% of their
    no-abuse baseline, ZERO victim requests shed, victim SLO burn alerts
    stay silent — and the abuser's sheds are accounted exactly once across
    bci_tenant_shed_total <-> the wide events <-> /v1/tenants."""
    faults = FaultPlan()
    pods = FakeExecutorPods(tmp_path / "pods15", faults=faults)
    config = Config(
        executor_backend="kubernetes",
        executor_port=pods.port,
        executor_pod_queue_target_length=2,
        pod_ready_timeout_s=5,
        executor_retry_attempts=1,
    )
    metrics = Registry()
    k8s = KubernetesCodeExecutor(
        kubectl=FakeKubectl(pods), storage=storage, config=config,
        metrics=metrics, ip_poll_interval_s=0.02,
    )
    registry = TenantRegistry(
        parse_tenants("abuser:weight=1:rps=2:burst=2,victim:weight=4"),
        metrics=metrics,
    )
    admission = AdmissionController(
        max_in_flight=4, max_queue=8, retry_after_s=0.2,
        metrics=metrics, tenancy=registry,
    )
    slo = SloEngine(parse_objectives(99.5, None), metrics=metrics)
    tracer = Tracer(metrics=metrics)
    recorder = FlightRecorder(max_events=2048, metrics=metrics)
    tracer.add_sink(recorder.record_trace)
    app = create_http_server(
        code_executor=k8s,
        custom_tool_executor=CustomToolExecutor(code_executor=k8s),
        metrics=metrics,
        admission=admission,
        request_deadline_s=30.0,
        tracer=tracer,
        recorder=recorder,
        slo=slo,
        tenancy=registry,
    )
    client = TestClient(TestServer(app))
    await client.start_server()
    N_ABUSE = 200  # 100x the abuser's burst-2 bucket
    try:
        await k8s.fill_executor_pod_queue()
        body = {"source_code": "print('ok')"}

        async def victim_request() -> float:
            t0 = time.perf_counter()
            resp = await client.post(
                "/v1/execute", json=body, headers={TENANT_HEADER: "victim"}
            )
            assert resp.status == 200, await resp.text()
            return time.perf_counter() - t0

        # Baseline: the victim alone, paced.
        baseline = []
        for _ in range(15):
            baseline.append(await victim_request())
            await asyncio.sleep(0.02)
        p50_base = statistics.median(baseline)

        async def abuse() -> None:
            await client.post(
                "/v1/execute", json=body, headers={TENANT_HEADER: "abuser"}
            )

        # The flood: 100x quota, concurrent with the victim's steady trickle.
        flood = [asyncio.create_task(abuse()) for _ in range(N_ABUSE)]
        during = []
        for _ in range(15):
            during.append(await victim_request())
            await asyncio.sleep(0.02)
        await asyncio.gather(*flood)
        p50_during = statistics.median(during)

        # Victim latency provably untouched (10% + scheduling-jitter floor).
        assert p50_during <= p50_base * 1.10 + 0.005, (p50_base, p50_during)

        # ZERO victim sheds, on every ledger.
        victim_lane = admission.tenant_snapshot()["victim"]
        assert victim_lane["sheds"] == {}
        assert recorder.events(outcome="shed", tenant="victim") == []
        tenants_doc = await (await client.get("/v1/tenants")).json()
        assert tenants_doc["tenants"]["victim"]["usage"]["sheds"] == 0

        # The victim's SLO slice is silent; the global page alert too.
        victim_slo = await (
            await client.get("/v1/slo", params={"tenant": "victim"})
        ).json()
        assert victim_slo["fast_burn_alerting"] is False
        assert victim_slo["alerting"] is False
        global_slo = await (await client.get("/v1/slo")).json()
        assert global_slo["fast_burn_alerting"] is False

        # The abuser's sheds are real and accounted EXACTLY ONCE across
        # counter <-> wide events <-> /v1/tenants.
        abuser_lane = admission.tenant_snapshot()["abuser"]
        shed_count = sum(abuser_lane["sheds"].values())
        assert shed_count > 0
        assert shed_count + abuser_lane["admitted"] == N_ABUSE
        counter_total = sum(
            v
            for key, v in metrics.metrics["bci_tenant_shed_total"]
            ._values.items()
            if ("tenant", "abuser") in key
        )
        assert counter_total == shed_count
        wide_sheds = recorder.events(
            outcome="shed", tenant="abuser", limit=10_000
        )
        assert len(wide_sheds) == shed_count
        assert (
            tenants_doc["tenants"]["abuser"]["usage"]["sheds"] == shed_count
        )
        # the fleet view exports the tenant mix for the router
        fleet_doc = await (await client.get("/v1/fleet")).json()
        assert fleet_doc["tenants"]["victim"] == 30
        assert fleet_doc["tenants"]["abuser"] == N_ABUSE
    finally:
        await client.close()
        await k8s.aclose()
        await pods.close()
