"""Sliding-window attention: kernels vs reference, decode consistency."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.ops.flash_attention import flash_attention
from bee_code_interpreter_tpu.parallel.ring_attention import reference_attention


def rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype=dtype)


@pytest.mark.parametrize("window", [1, 32, 100, 500])
def test_flash_window_matches_reference(window):
    # windows smaller than, comparable to, and larger than the block size —
    # the block-skip predicate and the in-block mask must both be right
    B, H, L, D = 1, 2, 320, 32
    q, k, v = (rand((B, H, L, D), i) for i in range(3))
    out = flash_attention(q, k, v, True, None, 128, 128, None, window)
    ref = reference_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_window_gqa():
    B, H, KVH, L, D = 1, 4, 2, 256, 32
    q = rand((B, H, L, D), 0)
    k = rand((B, KVH, L, D), 1)
    v = rand((B, KVH, L, D), 2)
    out = flash_attention(q, k, v, True, None, 128, 128, None, 64)
    ref = reference_attention(
        q, jnp.repeat(k, 2, 1), jnp.repeat(v, 2, 1), causal=True, window=64
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_window_grads():
    B, H, L, D = 1, 1, 192, 16
    q, k, v = (rand((B, H, L, D), i + 5) for i in range(3))

    def loss(q, k, v):
        return (flash_attention(q, k, v, True, None, 64, 64, None, 48) ** 2).sum()

    def ref_loss(q, k, v):
        return (reference_attention(q, k, v, causal=True, window=48) ** 2).sum()

    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=5e-4, rtol=5e-4, err_msg=name
        )


def test_window_validation():
    q, k, v = (rand((1, 1, 64, 16), i) for i in range(3))
    with pytest.raises(ValueError, match="window requires causal"):
        flash_attention(q, k, v, False, None, 64, 64, None, 8)
    with pytest.raises(ValueError, match="window must be >= 1"):
        flash_attention(q, k, v, True, None, 64, 64, None, 0)


def windowed_cfg():
    return dataclasses.replace(
        T.TransformerConfig.tiny(), dtype=jnp.float32, n_kv_heads=2,
        sliding_window=6,
    )


def test_windowed_generate_cached_matches_generate():
    # forward uses the windowed attention path; decode uses the windowed
    # cache-visibility mask — the two must agree token-for-token (window
    # smaller than the sequence so it actually bites).
    config = windowed_cfg()
    model = T.Transformer(config)
    params = model.init(jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, config.vocab_size)
    a = model.generate(params, prompt, max_new_tokens=7)
    b = model.generate_cached(params, prompt, max_new_tokens=7)
    assert (a == b).all(), (a, b)


def test_windowed_chunked_prefill_matches_forward():
    config = windowed_cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 20), 0, config.vocab_size)
    full = T.forward(params, tokens, config)
    last, _ = T.prefill_chunked(params, tokens, config, 24, chunk=8)
    np.testing.assert_allclose(
        np.asarray(last), np.asarray(full[:, -1, :]), atol=1e-4, rtol=1e-4
    )


@pytest.mark.parametrize("sp_attention", ["ring", "ulysses"])
def test_windowed_forward_on_sp_mesh_matches_single(sp_attention):
    # The round-4 matrix close (VERDICT r3 #5b): sliding_window through
    # both sp strategies. window=6 with L_local=8 makes the ring's window
    # boundary straddle the block edge (the hard per-hop-mask case);
    # Ulysses applies the local mask after its gather.
    from bee_code_interpreter_tpu.parallel.mesh import make_mesh

    config = dataclasses.replace(windowed_cfg(), sp_attention=sp_attention)
    mesh = make_mesh({"sp": 2}, devices=jax.devices()[:2])
    params = T.init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0, config.vocab_size)
    sharded = T.forward(params, tokens, config, mesh)
    single = T.forward(params, tokens, config, None)
    np.testing.assert_allclose(
        np.asarray(sharded), np.asarray(single), atol=1e-4, rtol=1e-4
    )


def test_reference_window_requires_causal_like_flash():
    q, k, v = (rand((1, 1, 32, 8), i) for i in range(3))
    with pytest.raises(ValueError, match="window requires causal"):
        reference_attention(q, k, v, causal=False, window=4)


def test_windowed_int8_cache_decode_consistent():
    # The int8 decode_step branch has its own window mask — pin its
    # per-step logits against the bf16 path, margin-gated (same approach as
    # tests/test_kv_cache.py: int8 drift is ~0.2 logits, so assert token
    # agreement only where the bf16 top1-top2 margin clears it, but ALWAYS
    # assert the windowed logits stay within the drift bound — a
    # sign-flipped window mask moves logits by whole units, not 0.2).
    cfg16 = windowed_cfg()
    cfg8 = dataclasses.replace(cfg16, kv_cache_dtype="int8")
    params = T.init_params(cfg16, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 15), 0, cfg16.vocab_size)
    L_pre = 7

    _, (k_pre, v_pre) = T.forward(params, tokens[:, :L_pre], cfg16, return_kv=True)
    c16 = T.init_decode_cache(cfg16, 1, 15, k_pre, v_pre)
    c8 = T.init_decode_cache(cfg8, 1, 15, k_pre, v_pre)

    for pos in range(L_pre, 15):
        lg16, c16 = T.decode_step(
            params, tokens[:, pos : pos + 1], jnp.int32(pos), c16, cfg16
        )
        lg8, c8 = T.decode_step(
            params, tokens[:, pos : pos + 1], jnp.int32(pos), c8, cfg8
        )
        drift = float(jnp.max(jnp.abs(lg8 - lg16)))
        assert drift < 0.5, (pos, drift)  # a wrong mask shifts whole units
