"""NativeProcessCodeExecutor: warm process pool driving real C++ servers.

The single-TPU-VM backend — pool semantics mirror the pod pool (single-use
sandboxes, async refill, spawning-count accounting) with local processes
standing in for pods."""

import asyncio
import subprocess
from pathlib import Path

import pytest

from bee_code_interpreter_tpu.config import Config

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _require_native(native_binary):
    if native_binary is None:
        pytest.skip("native toolchain unavailable")


@pytest.fixture
def native_executor(storage, tmp_path, native_binary):
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )

    config = Config(
        executor_backend="local",
        local_executor_binary=str(native_binary),
        local_workspace_root=str(tmp_path / "ws"),
        disable_dep_install=True,
        executor_pod_queue_target_length=2,
        execution_timeout_s=30.0,
        pod_ready_timeout_s=20.0,
        shim_dir="none",
    )
    executor = NativeProcessCodeExecutor(storage=storage, config=config)
    yield executor
    executor.shutdown()


async def test_execute_round_trip(native_executor):
    result = await native_executor.execute("print(21 * 2)")
    assert result.stdout == "42\n"
    assert result.exit_code == 0


async def test_file_snapshot_round_trip(native_executor):
    first = await native_executor.execute(
        'with open("out.txt", "w") as f:\n    f.write("native")'
    )
    assert first.exit_code == 0
    assert "/workspace/out.txt" in first.files
    second = await native_executor.execute(
        'print(open("out.txt").read())', files=first.files
    )
    assert second.stdout == "native\n"


async def test_env_passthrough(native_executor):
    result = await native_executor.execute(
        'import os; print(os.environ["NATIVE_VAR"])', env={"NATIVE_VAR": "yes"}
    )
    assert result.stdout == "yes\n"


async def test_sandboxes_are_single_use(native_executor):
    # A file created in one run must not be visible to the next (fresh
    # process + fresh workspace per execution).
    await native_executor.execute('open("leak.txt", "w").write("x")')
    result = await native_executor.execute(
        'import os; print(os.path.exists("leak.txt"))'
    )
    assert result.stdout == "False\n"


async def test_pool_refills_and_reuses_warm_sandboxes(native_executor):
    await native_executor.fill_sandbox_queue()
    assert native_executor.pool_ready_count == 2
    assert native_executor.pool_spawning_count == 0
    # An execution takes a warm sandbox (no cold spawn) and triggers a refill;
    # the refill is asynchronous, so wait for the pool to converge.
    result = await native_executor.execute("print('warm')")
    assert result.stdout == "warm\n"
    for _ in range(200):
        await native_executor.fill_sandbox_queue()
        if (
            native_executor.pool_ready_count == 2
            and native_executor.pool_spawning_count == 0
        ):
            break
        await asyncio.sleep(0.05)
    assert native_executor.pool_ready_count == 2


async def test_shutdown_kills_warm_pool(native_executor):
    await native_executor.fill_sandbox_queue()
    procs = [box.proc for box in native_executor._queue]
    native_executor.shutdown()
    assert native_executor.pool_ready_count == 0
    for proc in procs:
        assert proc.poll() is not None


def test_missing_binary_is_a_loud_error(storage, tmp_path):
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )

    with pytest.raises(FileNotFoundError):
        NativeProcessCodeExecutor(
            storage=storage,
            config=Config(local_executor_binary=str(tmp_path / "nope")),
        )


async def test_sandboxes_die_with_parent_kill(native_executor, tmp_path, native_binary):
    # PDEATHSIG guarantee: a SIGKILLed controller must not leave orphan
    # sandboxes behind. Simulate by spawning a sandbox from a disposable child
    # process and SIGKILLing it.
    import os
    import signal
    import textwrap
    import time

    script = textwrap.dedent(f"""
        import asyncio, os, sys
        sys.path.insert(0, {str(REPO)!r})
        from bee_code_interpreter_tpu.config import Config
        from bee_code_interpreter_tpu.services.storage import Storage
        from bee_code_interpreter_tpu.services.native_process_code_executor import (
            NativeProcessCodeExecutor,
        )

        async def main():
            ex = NativeProcessCodeExecutor(
                storage=Storage({str(tmp_path / "obj")!r}),
                config=Config(
                    local_executor_binary={str(native_binary)!r},
                    local_workspace_root={str(tmp_path / "ws2")!r},
                    executor_pod_queue_target_length=1,
                    disable_dep_install=True,
                    shim_dir="none",
                ),
            )
            await ex.fill_sandbox_queue()
            print(ex._queue[0].proc.pid, flush=True)
            await asyncio.sleep(60)

        asyncio.run(main())
    """)
    controller = subprocess.Popen(
        ["python", "-c", script], stdout=subprocess.PIPE, text=True
    )
    sandbox_pid = int(controller.stdout.readline())
    assert _alive(sandbox_pid)
    controller.kill()
    controller.wait()
    deadline = time.time() + 10
    while _alive(sandbox_pid) and time.time() < deadline:
        time.sleep(0.1)
    assert not _alive(sandbox_pid), "sandbox outlived its SIGKILLed controller"


def _alive(pid: int) -> bool:
    import os

    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False


async def test_concurrent_executes_pool_accounting(storage, tmp_path, native_binary):
    # 10 concurrent requests against a 3-deep pool: every request succeeds,
    # the accounting never overshoots the target, and shutdown leaves no
    # processes behind (SURVEY.md §5 notes the reference relies on
    # cooperative scheduling for pool accounting; ours must hold under real
    # concurrency).
    import asyncio

    from bee_code_interpreter_tpu.config import Config

    config = Config(
        file_storage_path=str(tmp_path / "objects"),
        local_workspace_root=str(tmp_path / "ws"),
        executor_pod_queue_target_length=3,
        disable_dep_install=True,
        shim_dir="none",
    )
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )

    executor = NativeProcessCodeExecutor(
        storage=storage, config=config, binary=native_binary
    )
    try:
        results = await asyncio.gather(
            *(executor.execute(f"print({i} * 10)") for i in range(10))
        )
        assert [r.stdout for r in results] == [f"{i * 10}\n" for i in range(10)]
        assert all(r.exit_code == 0 for r in results)
        # let in-flight refills settle (spawns now hold sandboxes back until
        # their warm worker preloads, so give the pipeline time), then check
        # the invariant
        await executor.fill_sandbox_queue()
        deadline = asyncio.get_running_loop().time() + 30
        while (
            executor.pool_ready_count == 0
            and asyncio.get_running_loop().time() < deadline
        ):
            await asyncio.sleep(0.1)
        assert (
            executor.pool_ready_count + executor.pool_spawning_count
            <= config.executor_pod_queue_target_length
        )
        # snapshot warm sandboxes BEFORE shutdown drains the queue, so the
        # no-survivors assertion actually checks something
        warm_boxes = list(executor._queue)
        assert warm_boxes, "pool should have warm sandboxes to verify against"
    finally:
        executor.shutdown()
    # all sandbox processes down after shutdown (shutdown destroys
    # synchronously; no watchdog delay involved)
    for box in warm_boxes:
        assert box.proc.poll() is not None


async def test_dead_warm_sandbox_discarded(storage, tmp_path, native_binary):
    # A sandbox whose server process died while queued (OOM/crash) must be
    # skipped, and the request served by a live one.
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )

    config = Config(
        file_storage_path=str(tmp_path / "objects"),
        local_workspace_root=str(tmp_path / "ws"),
        executor_pod_queue_target_length=2,
        disable_dep_install=True,
        shim_dir="none",
    )
    executor = NativeProcessCodeExecutor(
        storage=storage, config=config, binary=native_binary
    )
    try:
        await executor.fill_sandbox_queue()
        victim = executor._queue[0]
        victim.proc.kill()
        victim.proc.wait()

        r = await executor.execute("print('alive path')")
        assert r.stdout == "alive path\n"
        assert r.exit_code == 0
    finally:
        executor.shutdown()


async def test_sandbox_unshare_hides_storage_root(storage, tmp_path, native_binary):
    # Opt-in mount-namespace hardening: user code must see an empty tmpfs
    # where the object-storage root is, while the control plane keeps using
    # the real directory (VERDICT r2 weak #5).
    import shutil
    import subprocess as sp

    from bee_code_interpreter_tpu.config import Config
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )

    # Probe with the production argv shape (non-root takes --map-root-user)
    import os as _os

    probe = ["unshare", "--mount"]
    if _os.geteuid() != 0:
        probe.append("--map-root-user")
    probe.append("true")
    if shutil.which("unshare") is None or sp.run(
        probe, capture_output=True
    ).returncode != 0:
        pytest.skip("unshare unavailable in this environment")

    object_id = await storage.write(b"secret session data")
    storage_root = tmp_path / "objects"  # the shared `storage` fixture root
    assert (storage_root / object_id).exists()

    config = Config(
        file_storage_path=str(storage_root),
        local_workspace_root=str(tmp_path / "ws"),
        executor_pod_queue_target_length=1,
        disable_dep_install=True,
        sandbox_unshare=True,
        shim_dir="none",
    )
    executor = NativeProcessCodeExecutor(
        storage=storage, config=config, binary=native_binary
    )
    try:
        result = await executor.execute(
            f"import os\nprint(sorted(os.listdir({str(storage_root)!r})))\n"
        )
        assert result.exit_code == 0, result.stderr
        assert result.stdout == "[]\n"  # empty tmpfs, not the real objects
        # the control plane still reads the real object
        assert await storage.read(object_id) == b"secret session data"
        # and the round-trip contract still works under hardening
        r2 = await executor.execute("open('out.txt','w').write('ok')")
        assert set(r2.files) == {"/workspace/out.txt"}
        if shutil.which("setpriv"):
            # the overmount must be capability-locked: deliberate user code
            # calling umount2() cannot uncover the real storage directory
            r3 = await executor.execute(
                "import ctypes, os\n"
                "libc = ctypes.CDLL(None, use_errno=True)\n"
                f"rc = libc.umount2({str(storage_root).encode()!r}, 2)\n"
                "print('umount rc', rc)\n"
                f"print('visible', sorted(os.listdir({str(storage_root)!r})))\n"
            )
            assert r3.exit_code == 0, r3.stderr
            assert "umount rc -1" in r3.stdout, r3.stdout
            assert "visible []" in r3.stdout, r3.stdout
    finally:
        executor.shutdown()


async def test_background_refill_concurrency_is_bounded(native_executor):
    """Refill spawns are CPU-bound (each boots a python warm worker); they
    go through a semaphore so a burst cannot starve the serving path. The
    request-blocking spawn (pool empty) deliberately bypasses the gate."""
    live = 0
    high_water = 0
    real_spawn = native_executor.spawn_sandbox

    async def counting_spawn(wait_warm=True):
        nonlocal live, high_water
        live += 1
        high_water = max(high_water, live)
        try:
            return await real_spawn(wait_warm=wait_warm)
        finally:
            live -= 1

    native_executor.spawn_sandbox = counting_spawn
    native_executor._refill_gate = asyncio.Semaphore(1)
    native_executor._config.executor_pod_queue_target_length = 4
    await native_executor.fill_sandbox_queue()
    assert native_executor.pool_ready_count == 4
    assert high_water == 1  # gate held refills to one at a time


async def test_drained_pool_dispatches_before_preload_completes(
    storage, tmp_path, native_binary
):
    """With an empty pool, execute() must not sit in the healthz poll loop
    waiting for preload-done — the server itself gates dispatch on its warm
    worker, so the request overlaps the preload tail instead."""
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )

    config = Config(
        executor_backend="local",
        local_executor_binary=str(native_binary),
        local_workspace_root=str(tmp_path / "ws"),
        disable_dep_install=True,
        executor_pod_queue_target_length=0,  # never any warm pool
        execution_timeout_s=30.0,
        pod_ready_timeout_s=20.0,
        shim_dir="none",
    )
    executor = NativeProcessCodeExecutor(storage=storage, config=config)
    try:
        seen: list[bool] = []
        real_spawn = executor.spawn_sandbox

        async def recording_spawn(wait_warm=True):
            seen.append(wait_warm)
            return await real_spawn(wait_warm=wait_warm)

        executor.spawn_sandbox = recording_spawn
        result = await executor.execute("print(6 * 7)")
        assert result.stdout == "42\n" and result.exit_code == 0
        assert seen and seen[0] is False  # request path skipped the warm wait
    finally:
        executor.shutdown()
