"""Metric-naming conventions lint (tier-1): every metric the service can
register must carry the ``bci_`` namespace prefix, non-empty HELP text, and
unit-suffixed names where the type implies a unit (counters ``_total``,
histograms ``_seconds``/``_bytes``). The registry itself must refuse a name
re-registered as a different metric type — the duplicate-registration bug
class where two components silently share one exposition block with the
wrong TYPE line."""

import pytest

from bee_code_interpreter_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
)


def build_service_registry(tmp_path) -> Registry:
    """Assemble the registry the way the composition root does — kubernetes
    backend with local fallback, both transports, admission, tracing — so
    the lint sees every metric the service can register."""
    from bee_code_interpreter_tpu.application_context import ApplicationContext
    from bee_code_interpreter_tpu.config import Config

    ctx = ApplicationContext(
        Config(
            executor_backend="kubernetes",
            fallback_to_local=True,
            file_storage_path=str(tmp_path / "objects"),
            local_workspace_root=str(tmp_path / "ws"),
            disable_dep_install=True,
            # telemetry export + SLO objectives, so their metrics register
            otlp_endpoint="http://127.0.0.1:4318",
            slo_availability=99.5,
            slo_latency_ms="2000:99",
            # fleet-wide tenancy (ISSUE 16): a lease client wires the
            # replica-side bci_quota_lease_* surface (never started here)
            tenants="alpha:weight=2:rps=5",
            quota_lease_urls="http://127.0.0.1:1",
        )
    )
    _ = ctx.code_executor  # registers executor, breaker, pool, fallback
    _ = ctx.admission
    _ = ctx.http_server
    _ = ctx.grpc_server
    return ctx.metrics


def register_serving_metrics(registry: Registry) -> None:
    """The models-layer registrations (batcher + engine), on a tiny CPU
    config — construction registers everything; no decode needed."""
    import jax

    from bee_code_interpreter_tpu.models import transformer as T
    from bee_code_interpreter_tpu.models.engine import Engine
    from bee_code_interpreter_tpu.models.serving import ContinuousBatcher

    config = T.TransformerConfig.tiny()
    params = T.init_params(config, jax.random.PRNGKey(0))
    batcher = ContinuousBatcher(
        params, config, max_batch=2, n_pages=8, page_size=4,
        max_pages_per_seq=2, metrics=registry,
    )
    Engine(batcher, metrics=registry)


def register_router_metrics(registry: Registry) -> None:
    """The fleet-router edge (docs/fleet.md) registers into ITS OWN
    registry in production; constructing one here holds its bci_router_*
    family to the same conventions."""
    import asyncio

    from bee_code_interpreter_tpu.fleet import FleetRouter
    from bee_code_interpreter_tpu.tenancy import TenantRegistry, parse_tenants

    router = FleetRouter(
        [("r0", "http://127.0.0.1:1")],
        metrics=registry,
        # fleet-wide tenancy (ISSUE 16): a declared tenant table + a peer
        # edge register the quota-ledger and gossip families too
        tenancy=TenantRegistry(parse_tenants("alpha:weight=2:rps=5")),
        peers=[("p1", "http://127.0.0.1:2")],
    )
    asyncio.run(router.stop())


def register_device_metrics(registry: Registry) -> None:
    """The accelerator plane (ISSUE 20): DeviceMonitor registers the
    compile/step families at construction, and its eager memory sample
    creates the per-device ``bci_device_hbm_bytes`` gauge series — no
    batcher attachment needed."""
    from bee_code_interpreter_tpu.observability.device import DeviceMonitor

    DeviceMonitor(metrics=registry)


def register_loadgen_metrics(registry: Registry) -> None:
    """The capacity harness's client-side family (ISSUE 18): the open-loop
    generator registers its sent/lag/offered surface when handed a
    registry — same conventions as the service it measures."""
    from bee_code_interpreter_tpu.loadgen import OpenLoopGenerator

    OpenLoopGenerator(
        client=None, base_url="http://127.0.0.1:1", metrics=registry
    )


def test_every_registered_metric_follows_conventions(tmp_path):
    registry = build_service_registry(tmp_path)
    register_serving_metrics(registry)
    register_router_metrics(registry)
    register_loadgen_metrics(registry)
    register_device_metrics(registry)
    metrics = registry.metrics
    assert len(metrics) >= 20, sorted(metrics)  # the wiring actually ran

    # The fleet-observability metrics (ISSUE 3) must be part of the wired
    # surface, so this lint covers their prefix/HELP/unit conventions too:
    # silently dropping one of them from the composition root would
    # otherwise pass unnoticed.
    for required in (
        "bci_pool_spawn_seconds",
        "bci_pool_utilization",
        "bci_pod_reaped_total",
        "bci_execution_cpu_seconds",
        "bci_execution_peak_rss_bytes",
        # proactive resilience (ISSUE 4): supervisor / replay / hedge / drain
        "bci_supervisor_probe_seconds",
        "bci_execution_replays_total",
        "bci_hedge_total",
        "bci_drain_inflight",
        # telemetry export + SLOs (ISSUE 5)
        "bci_telemetry_exported_total",
        "bci_telemetry_dropped_total",
        "bci_telemetry_queue_depth",
        "bci_slo_error_budget_remaining_ratio",
        "bci_slo_burn_rate",
        # edge static analysis (ISSUE 6): the pre-flight code gate
        "bci_analysis_seconds",
        "bci_analysis_rejections_total",
        "bci_analysis_warnings_total",
        "bci_analysis_dep_predictions_total",
        # dataflow layer + cost classes (ISSUE 12): dynamic-import
        # resolution accounting and the scheduling hint, plus the
        # cost-aware heavy lane's occupancy gauge
        "bci_analysis_dynamic_imports_total",
        "bci_analysis_cost_class_total",
        "bci_admission_heavy_in_flight",
        # sessions (ISSUE 7): leased sandboxes + checkpoint/rollback
        "bci_session_active",
        "bci_session_lease_seconds",
        "bci_session_expirations_total",
        # flight recorder + loop health + continuous profiler (ISSUE 8)
        "bci_events_emitted_total",
        "bci_events_dropped_total",
        "bci_event_loop_lag_seconds",
        "bci_loop_stalls_total",
        "bci_contprof_samples_total",
        # streaming promoted from bench-only numbers to production metrics
        "bci_stream_ttfb_seconds",
        "bci_stream_chunks_total",
        # serving deep observability (ISSUE 9): the ServingMonitor's
        # per-request rollups register in the composition root; the
        # batcher/engine aggregates register at model wiring (the
        # register_serving_metrics call above)
        "bci_serving_requests_total",
        "bci_serving_request_seconds",
        "bci_serving_preemptions_total",
        "bci_serving_spec_tokens_total",
        "bci_serving_spec_accept_ratio",
        "bci_serving_prefix_hit_ratio",
        "bci_serving_page_fragmentation",
        "bci_serving_ttft_seconds",
        "bci_serving_inter_token_seconds",
        "bci_serving_step_seconds",
        "bci_serving_tokens_total",
        "bci_serving_active_rows",
        "bci_serving_batch_occupancy",
        "bci_serving_free_pages",
        "bci_serving_tokens_per_second",
        "bci_serving_queue_wait_seconds",
        "bci_serving_requeues_total",
        "bci_serving_queue_rejected_total",
        "bci_serving_queue_depth",
        # capacity observability + predictive autoscaling (ISSUE 10): the
        # demand tracker + forecaster register in the composition root, the
        # autoscaler with the pool executor
        "bci_demand_rps",
        "bci_forecast_rps",
        "bci_warm_pop_ratio",
        "bci_pool_target_size",
        "bci_autoscale_decisions_total",
        # multi-tenant isolation (ISSUE 13): per-tenant admission/quota/
        # usage surface + the label-cardinality guard's overflow counter
        "bci_tenant_shed_total",
        "bci_tenant_admitted_total",
        "bci_tenant_queue_wait_seconds",
        "bci_tenant_in_flight",
        "bci_tenant_queue_depth",
        "bci_tenant_requests_total",
        "bci_tenant_cpu_seconds_total",
        "bci_tenant_bytes_total",
        "bci_metrics_label_overflow_total",
        # fleet router (ISSUE 11): the replica-aware edge's own surface
        "bci_router_requests_total",
        "bci_router_request_seconds",
        "bci_router_retries_total",
        "bci_router_affinity_total",
        "bci_router_lease_migrations_total",
        "bci_router_replicas",
        "bci_router_pinned_sessions",
        # fleet-wide tenancy (ISSUE 16): router-held quota-lease ledger,
        # peer gossip, tenant retry budgets, and the replica-side lease
        # client's refresh/fleet-size surface
        "bci_router_quota_leases_total",
        "bci_router_quota_active_leases",
        "bci_router_peer_sync_total",
        "bci_router_peer_up",
        "bci_router_retry_budget_denied_total",
        "bci_quota_lease_refresh_total",
        "bci_quota_lease_fleet_size",
        # fleet observability plane (ISSUE 17): federated scatter-gather
        # at the router edge + the router's own stage-span histogram
        # (bci_stage_seconds registers via the router Tracer; slo gauges
        # via the router SloEngine when objectives are configured)
        "bci_federation_requests_total",
        "bci_federation_replica_errors_total",
        "bci_federation_fanout_seconds",
        "bci_stage_seconds",
        # capacity harness + forecaster→replica-count loop (ISSUE 18):
        # the open-loop generator's client-side family and the federated
        # recommendation gauge the router edge publishes
        "bci_loadgen_sent_total",
        "bci_loadgen_lag_seconds",
        "bci_loadgen_offered_rps",
        "bci_fleet_target_replicas",
        # accelerator observability plane (ISSUE 20): compile/retrace
        # tracking, per-device HBM accounting, and mesh-shaped step
        # telemetry from the DeviceMonitor
        "bci_compile_total",
        "bci_compile_seconds",
        "bci_device_hbm_bytes",
        "bci_device_step_seconds",
    ):
        assert required in metrics, f"{required}: not registered by the wiring"
    assert isinstance(metrics["bci_pool_spawn_seconds"], Histogram)
    assert isinstance(metrics["bci_pool_utilization"], Gauge)
    assert isinstance(metrics["bci_pod_reaped_total"], Counter)
    assert isinstance(metrics["bci_execution_cpu_seconds"], Histogram)
    assert isinstance(metrics["bci_execution_peak_rss_bytes"], Histogram)
    assert isinstance(metrics["bci_supervisor_probe_seconds"], Histogram)
    assert isinstance(metrics["bci_execution_replays_total"], Counter)
    assert isinstance(metrics["bci_hedge_total"], Counter)
    assert isinstance(metrics["bci_drain_inflight"], Gauge)
    assert isinstance(metrics["bci_telemetry_exported_total"], Counter)
    assert isinstance(metrics["bci_telemetry_dropped_total"], Counter)
    assert isinstance(metrics["bci_telemetry_queue_depth"], Gauge)
    assert isinstance(metrics["bci_slo_error_budget_remaining_ratio"], Gauge)
    assert isinstance(metrics["bci_slo_burn_rate"], Gauge)
    assert isinstance(metrics["bci_analysis_seconds"], Histogram)
    assert isinstance(metrics["bci_analysis_rejections_total"], Counter)
    assert isinstance(metrics["bci_analysis_dep_predictions_total"], Counter)
    assert isinstance(metrics["bci_analysis_dynamic_imports_total"], Counter)
    assert isinstance(metrics["bci_analysis_cost_class_total"], Counter)
    assert isinstance(metrics["bci_admission_heavy_in_flight"], Gauge)
    assert isinstance(metrics["bci_session_active"], Gauge)
    assert isinstance(metrics["bci_session_lease_seconds"], Histogram)
    assert isinstance(metrics["bci_session_expirations_total"], Counter)
    assert isinstance(metrics["bci_events_emitted_total"], Counter)
    assert isinstance(metrics["bci_events_dropped_total"], Counter)
    assert isinstance(metrics["bci_event_loop_lag_seconds"], Histogram)
    assert isinstance(metrics["bci_loop_stalls_total"], Counter)
    assert isinstance(metrics["bci_contprof_samples_total"], Counter)
    assert isinstance(metrics["bci_stream_ttfb_seconds"], Histogram)
    assert isinstance(metrics["bci_stream_chunks_total"], Counter)
    assert isinstance(metrics["bci_serving_requests_total"], Counter)
    assert isinstance(metrics["bci_serving_request_seconds"], Histogram)
    assert isinstance(metrics["bci_serving_preemptions_total"], Counter)
    assert isinstance(metrics["bci_serving_spec_tokens_total"], Counter)
    assert isinstance(metrics["bci_serving_spec_accept_ratio"], Gauge)
    assert isinstance(metrics["bci_serving_prefix_hit_ratio"], Gauge)
    assert isinstance(metrics["bci_serving_page_fragmentation"], Gauge)
    assert isinstance(metrics["bci_demand_rps"], Gauge)
    assert isinstance(metrics["bci_forecast_rps"], Gauge)
    assert isinstance(metrics["bci_warm_pop_ratio"], Gauge)
    assert isinstance(metrics["bci_pool_target_size"], Gauge)
    assert isinstance(metrics["bci_autoscale_decisions_total"], Counter)
    assert isinstance(metrics["bci_tenant_shed_total"], Counter)
    assert isinstance(metrics["bci_tenant_queue_wait_seconds"], Histogram)
    assert isinstance(metrics["bci_tenant_in_flight"], Gauge)
    assert isinstance(metrics["bci_tenant_requests_total"], Counter)
    assert isinstance(metrics["bci_tenant_cpu_seconds_total"], Counter)
    assert isinstance(metrics["bci_metrics_label_overflow_total"], Counter)
    assert isinstance(metrics["bci_router_requests_total"], Counter)
    assert isinstance(metrics["bci_router_request_seconds"], Histogram)
    assert isinstance(metrics["bci_router_lease_migrations_total"], Counter)
    assert isinstance(metrics["bci_router_replicas"], Gauge)
    assert isinstance(metrics["bci_router_quota_leases_total"], Counter)
    assert isinstance(metrics["bci_router_quota_active_leases"], Gauge)
    assert isinstance(metrics["bci_router_peer_sync_total"], Counter)
    assert isinstance(metrics["bci_router_peer_up"], Gauge)
    assert isinstance(
        metrics["bci_router_retry_budget_denied_total"], Counter
    )
    assert isinstance(metrics["bci_quota_lease_refresh_total"], Counter)
    assert isinstance(metrics["bci_quota_lease_fleet_size"], Gauge)
    assert isinstance(metrics["bci_loadgen_sent_total"], Counter)
    assert isinstance(metrics["bci_loadgen_lag_seconds"], Histogram)
    assert isinstance(metrics["bci_loadgen_offered_rps"], Gauge)
    assert isinstance(metrics["bci_fleet_target_replicas"], Gauge)
    assert isinstance(metrics["bci_compile_total"], Counter)
    assert isinstance(metrics["bci_compile_seconds"], Histogram)
    assert isinstance(metrics["bci_device_hbm_bytes"], Gauge)
    assert isinstance(metrics["bci_device_step_seconds"], Histogram)

    for name, metric in metrics.items():
        assert name.startswith("bci_"), (
            f"{name}: metrics must live in the bci_ namespace"
        )
        assert metric.help and metric.help.strip(), (
            f"{name}: HELP text must be non-empty"
        )
        if isinstance(metric, Counter):
            assert name.endswith("_total"), (
                f"{name}: counters must end in _total"
            )
        elif isinstance(metric, Histogram):
            assert name.endswith(("_seconds", "_bytes")), (
                f"{name}: histograms must be unit-suffixed "
                "(_seconds or _bytes)"
            )
        else:
            assert isinstance(metric, Gauge), f"{name}: unknown metric type"
            # gauges describe states/counts; they must not masquerade as
            # counters or timers
            assert not name.endswith(("_total", "_seconds")), (
                f"{name}: gauge misusing a counter/histogram unit suffix"
            )

    # the full exposition renders without error and every metric appears once
    text = registry.expose()
    for name in metrics:
        assert text.count(f"# HELP {name} ") == 1, (
            f"{name}: duplicate or missing exposition block"
        )


def test_every_serving_metric_is_documented(tmp_path):
    """asynclint's undocumented-metric rule scopes to the control plane
    (api/ + services/ + resilience/ + observability/ + sessions/) and
    deliberately does not lint models/ — hold the serving-engine metrics
    to the same standard here: every registered ``bci_serving_*`` name
    must appear (word-bounded) in docs/observability.md."""
    import re
    from pathlib import Path

    registry = build_service_registry(tmp_path)
    register_serving_metrics(registry)
    doc = (
        Path(__file__).resolve().parent.parent / "docs" / "observability.md"
    ).read_text()
    serving = sorted(
        n for n in registry.metrics if n.startswith("bci_serving_")
    )
    assert len(serving) >= 16, serving  # both layers actually registered
    for name in serving:
        assert re.search(rf"\b{name}\b", doc), (
            f"{name}: registered but not documented in docs/observability.md"
        )


def test_every_cost_class_label_is_documented():
    """`bci_analysis_cost_class_total{class}` is a CLOSED label set
    (COST_CLASSES); an operator reading docs/observability.md must find
    every value it can take — `accelerator` joined the set with the
    jaxlint PR and must not be the last one anyone documents."""
    from pathlib import Path

    from bee_code_interpreter_tpu.analysis import COST_CLASSES

    doc = (
        Path(__file__).resolve().parent.parent / "docs" / "observability.md"
    ).read_text()
    row = next(
        line
        for line in doc.splitlines()
        if "bci_analysis_cost_class_total" in line and line.startswith("|")
    )
    for cls in COST_CLASSES:
        assert f"`{cls}`" in row, (
            f"cost class {cls!r} missing from the "
            "bci_analysis_cost_class_total row in docs/observability.md"
        )
    assert "accelerator" in row


def test_analysis_stage_appears_in_stage_seconds(tmp_path):
    """The edge gate's work is a first-class request stage: one analyzed
    submission under a trace must surface as
    ``bci_stage_seconds{stage="analysis"}`` — the same histogram every
    other stage (admission/spawn/upload/execute/download) feeds, so
    dashboards see the gate's cost next to what it saves."""
    from bee_code_interpreter_tpu.analysis import WorkloadAnalyzer
    from bee_code_interpreter_tpu.observability import Tracer

    registry = build_service_registry(tmp_path)
    tracer = Tracer(metrics=registry)
    analyzer = WorkloadAnalyzer(metrics=registry)
    with tracer.trace("/v1/execute"):
        analyzer.analyze("print(1)\n")
    text = registry.expose()
    assert 'bci_stage_seconds_count{stage="analysis"} 1' in text


def test_every_seconds_histogram_carries_exemplars_when_trace_active(tmp_path):
    """Exemplar lint: an observation made under an active trace must surface
    that trace's id on the OpenMetrics exposition of EVERY ``bci_*_seconds``
    histogram — the metric↔trace linkage is only useful if no histogram
    silently opts out."""
    import re

    from bee_code_interpreter_tpu.observability import Tracer

    registry = build_service_registry(tmp_path)
    tracer = Tracer(metrics=registry)
    histograms = {
        name: metric
        for name, metric in registry.metrics.items()
        if isinstance(metric, Histogram) and name.endswith("_seconds")
    }
    assert len(histograms) >= 5, sorted(histograms)

    with tracer.trace("exemplar-lint") as trace:
        for metric in histograms.values():
            metric.observe(0.012)

    text = registry.expose(openmetrics=True)
    for name in histograms:
        pattern = re.compile(
            rf'^{name}_bucket{{[^}}]*}} \d+ '
            rf'# {{trace_id="{trace.trace_id}",span_id="[0-9a-f]{{16}}"}} '
            rf"[0-9.e+-]+ [0-9.]+$",
            re.M,
        )
        assert pattern.search(text), f"{name}: no exemplar on any bucket"
    assert text.rstrip().endswith("# EOF")

    # the classic Prometheus format must stay exemplar-free (its parsers
    # reject the syntax) and observations made OUTSIDE a trace add none
    classic = registry.expose()
    assert "trace_id=" not in classic
    assert "# EOF" not in classic
    fresh = Registry()
    fresh.histogram("bci_plain_seconds", "untraced").observe(0.5)
    assert "trace_id=" not in fresh.expose(openmetrics=True)


def test_tenant_label_cardinality_guard_collapses_to_other():
    """ISSUE 13 satellite: the Registry bounds per-label-value cardinality
    — a tenant-id flood collapses into one 'other' series past the bound,
    with every collapsed observation counted, so /metrics cannot OOM."""
    registry = Registry()
    registry.bound_label("tenant", 3)
    shed = registry.counter("bci_tenant_shed_total", "sheds per tenant")
    for i in range(50):
        shed.inc(tenant=f"flood-{i}", reason="tenant_quota")
    text = registry.expose()
    # exactly 3 distinct tenant series + the collapsed bucket
    assert text.count('reason="tenant_quota",tenant="flood-') == 3
    assert (
        'bci_tenant_shed_total{reason="tenant_quota",tenant="other"} 47'
        in text
    )
    assert 'bci_metrics_label_overflow_total{label="tenant"} 47' in text
    # already-seen values keep their own series (no flapping to "other")
    shed.inc(tenant="flood-0", reason="tenant_quota")
    assert (
        'bci_tenant_shed_total{reason="tenant_quota",tenant="flood-0"} 2'
        in registry.expose()
    )
    # histograms and gauges honor the same bound
    hist = registry.histogram("bci_tenant_queue_wait_seconds", "wait")
    for i in range(10):
        hist.observe(0.01, tenant=f"h-{i}")
    om = registry.expose()
    assert om.count("bci_tenant_queue_wait_seconds_count") <= 4
    gauge_values = iter(range(100))
    for i in range(10):
        registry.gauge(
            "bci_tenant_in_flight", "in flight",
            (lambda v: lambda: v)(next(gauge_values)),
            tenant=f"g-{i}",
        )
    assert registry.expose().count("bci_tenant_in_flight{tenant=") <= 4

    # every registry ships a default bound for the tenant label: even a
    # bare Registry cannot be flooded
    bare = Registry()
    c = bare.counter("bci_tenant_requests_total", "reqs")
    for i in range(100):
        c.inc(tenant=f"t-{i}")
    assert 'tenant="other"' in bare.expose()


def test_openmetrics_counter_family_drops_total_suffix():
    registry = Registry()
    registry.counter("bci_things_total", "things").inc(2)
    om = registry.expose(openmetrics=True)
    assert "# TYPE bci_things counter" in om
    assert "bci_things_total 2" in om  # the sample keeps the suffix
    classic = registry.expose()
    assert "# TYPE bci_things_total counter" in classic


def test_registry_rejects_type_conflicting_reregistration():
    registry = Registry()
    registry.counter("bci_things_total", "things")
    with pytest.raises(ValueError, match="already registered"):
        registry.histogram("bci_things_total", "things, but a histogram")
    with pytest.raises(ValueError, match="already registered"):
        registry.gauge("bci_things_total", "things, but a gauge", lambda: 0)
    # same name, same type remains a shared object, not an error
    assert registry.counter("bci_things_total", "things") is not None
