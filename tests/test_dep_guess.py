from bee_code_interpreter_tpu.runtime.dep_guess import (
    guess_dependencies,
    guessed_imports,
    load_requirements_set,
)


def test_collects_top_level_imports():
    src = "import numpy as np\nfrom pandas.io import api\nimport os, sys\n"
    assert guessed_imports(src) == {"numpy", "pandas", "os", "sys"}


def test_stdlib_and_relative_excluded():
    src = "import json\nfrom . import sibling\nfrom ..pkg import thing\n"
    assert guess_dependencies(src) == []


def test_pypi_name_mapping():
    src = "import cv2\nimport sklearn\nfrom PIL import Image\nimport yaml\n"
    assert guess_dependencies(src) == ["PyYAML", "opencv-python", "pillow", "scikit-learn"]


def test_preinstalled_filtered_with_normalization():
    pre = frozenset({"opencv-python", "scikit_learn", "PyYAML"})
    src = "import cv2\nimport sklearn\nimport yaml\nimport cowsay\n"
    assert guess_dependencies(src, preinstalled=pre) == ["cowsay"]


def test_accelerator_stack_never_reinstalled():
    src = "import jax\nimport torch\nimport flax\nimport libtpu\n"
    assert guess_dependencies(src) == []


def test_syntax_error_returns_empty():
    assert guess_dependencies("def broken(:\n") == []


def test_null_byte_returns_empty_not_valueerror():
    """ast.parse raises ValueError (not SyntaxError) on NUL bytes, but the
    FILE tokenizer the sandbox runs the script with tolerates them — the
    best-effort guesser must degrade to 'no deps', never fail the
    execution with a 500."""
    assert guess_dependencies("print(1)\n\x00\nimport pandas\n") == []


def test_nested_function_imports_found():
    src = "def f():\n    import requests\n    return requests\n"
    assert guess_dependencies(src) == ["requests"]


def test_load_requirements_set(tmp_path):
    req = tmp_path / "requirements.txt"
    req.write_text("pandas[excel]==2.2\n# comment\nPy_YAML>=6 ; python_version>'3'\n\nscipy\n")
    skip = tmp_path / "skip.txt"
    skip.write_text("ffmpeg  # OS package\n")
    got = load_requirements_set(req, skip, tmp_path / "missing.txt")
    assert got == frozenset({"pandas", "py-yaml", "scipy", "ffmpeg"})


def test_media_alias_traps_resolve():
    # The reference image's hard-won alias corrections (its
    # requirements-skip.txt:22-26), expressed here through the map: the alias
    # import resolves to the REAL dist, so a missing target still installs.
    src = "import fitz\nimport ffmpeg\nimport yt_dlp\nimport bson\nimport pylab\n"
    assert guess_dependencies(src) == [
        "ffmpeg-python", "matplotlib", "pymongo", "pymupdf", "yt-dlp",
    ]
    # ...and with the image's stack preinstalled, none of them reinstall
    pre = load_requirements_set(
        "executor/requirements.txt", "executor/requirements-skip.txt"
    )
    assert guess_dependencies(src, preinstalled=pre) == ["pymongo"]


def test_image_skip_file_blocks_os_and_accel_names():
    pre = load_requirements_set("executor/requirements-skip.txt")
    src = "import pandoc\nimport libtpu\nimport jaxlib\nimport tpu_info\n"
    assert guess_dependencies(src, preinstalled=pre) == []


def test_namespace_package_imports_resolve_past_top_level():
    # `import google.protobuf` must NOT install the obsolete `google` dist:
    # the guesser retains the second level so the map entry is reachable
    # (ADVICE r2: first-dot truncation made "google.protobuf" a dead row).
    src = (
        "import google.protobuf\n"
        "from google.protobuf import json_format\n"
        "import google.generativeai as genai\n"
        "from google.cloud import storage\n"
        "from google import auth\n"
    )
    assert guessed_imports(src) == {
        "google.protobuf",
        "google.generativeai",
        "google.cloud.storage",
        "google.auth",
    }
    assert guess_dependencies(src) == [
        "google-auth",
        "google-cloud-storage",
        "google-generativeai",
        "protobuf",
    ]


def test_bare_namespace_import_installs_nothing():
    assert guess_dependencies("import google\n") == []


def test_pypi_map_tsv_in_sync_with_oracle():
    # The C++ server loads executor/pypi_map.tsv; it must match the Python
    # oracle exactly (regenerate with scripts/generate-pypi-map.py).
    from bee_code_interpreter_tpu.runtime.dep_guess import PYPI_MAP

    rows = {}
    for line in open("executor/pypi_map.tsv"):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        imp, dist = line.split("\t")
        rows[imp] = dist
    assert rows == PYPI_MAP


def test_long_tail_aliases_resolve():
    # Sampled long-tail traps (VERDICT r2: only the high-traffic head was
    # covered; these all exist in upm's full map and bit real users).
    cases = {
        "import faiss": ["faiss-cpu"],
        "import talib": ["TA-Lib"],
        "from dns import resolver": ["dnspython"],
        "import binance": ["python-binance"],
        "import llama_cpp": ["llama-cpp-python"],
        "import hydra": ["hydra-core"],
        "import imblearn": ["imbalanced-learn"],
        "import win32api, win32con": ["pywin32"],
        "import webview": ["pywebview"],
        "import airflow": ["apache-airflow"],
        "from spellchecker import SpellChecker": ["pyspellchecker"],
        "import MeCab": ["mecab-python3"],
    }
    for source, expected in cases.items():
        assert guess_dependencies(source) == expected, source


def test_map_size_floor():
    # The tsv must stay at long-tail scale — a regression to the curated head
    # alone (~340 rows) would silently reopen the alias gap.
    from bee_code_interpreter_tpu.runtime.dep_guess import PYPI_MAP

    assert len(PYPI_MAP) >= 590


def test_azure_namespace_resolves_per_component():
    # azure is a pure PEP-420 namespace: the bare import installs nothing,
    # every component maps by the dots->dashes convention, down to the
    # keyvault/mgmt/storage third level.
    src = (
        "import azure\n"
        "from azure.identity import DefaultAzureCredential\n"
        "from azure.storage.blob import BlobServiceClient\n"
        "import azure.cosmos\n"
        "from azure.keyvault.secrets import SecretClient\n"
        "import azure.mgmt.compute\n"
    )
    assert guess_dependencies(src) == [
        "azure-cosmos", "azure-identity", "azure-keyvault-secrets",
        "azure-mgmt-compute", "azure-storage-blob",
    ]
    # third-level namespaces beyond storage/keyvault/mgmt (review r5: the
    # two-level truncation resolved these to real-but-deprecated dists)
    deep = (
        "from azure.search.documents import SearchClient\n"
        "import azure.ai.ml\n"
        "from azure.data.tables import TableClient\n"
        "import azure.monitor.query\n"
        "import azure.iot.device\n"
    )
    assert guess_dependencies(deep) == [
        "azure-ai-ml", "azure-data-tables", "azure-iot-device",
        "azure-monitor-query", "azure-search-documents",
    ]


def test_r5_long_tail_aliases_resolve():
    src = (
        "import pwn\nimport z3\nimport skopt\nimport telebot\n"
        "import board, busio\n"
    )
    assert guess_dependencies(src) == [
        "Adafruit-Blinka", "pwntools", "pyTelegramBotAPI",
        "scikit-optimize", "z3-solver",
    ]
    # haiku maps to dm-haiku but sits in the accelerator-stack SKIP set
    # (image-pinned); the alias must never trigger a reinstall
    assert guess_dependencies("import haiku\n") == []
    # functorch resolves to torch, which is pinned: SKIP must win even
    # when the deployment's preinstalled set omits torch (review r5)
    assert guess_dependencies("import functorch\n") == []
