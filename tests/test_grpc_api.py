"""gRPC e2e tests against an in-process grpc.aio server (local executor backend),
mirroring the reference suite's coverage incl. oneof assertions
(test/e2e/test_grpc.py:136,202,236,253)."""

import json

import grpc.aio
import pytest

from bee_code_interpreter_tpu.api.grpc_server import GrpcServer, service_stubs
from bee_code_interpreter_tpu.proto import code_interpreter_pb2 as pb
from bee_code_interpreter_tpu.services.custom_tool_executor import CustomToolExecutor


@pytest.fixture
def grpc_server(local_executor):
    return GrpcServer(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
    )


async def run_with(server: GrpcServer, fn):
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            await fn(service_stubs(channel))
    finally:
        await server.stop(None)


async def test_execute(grpc_server):
    async def go(stubs):
        resp = await stubs["Execute"](pb.ExecuteRequest(source_code="print(21 * 2)"))
        assert resp.stdout == "42\n"
        assert resp.exit_code == 0

    await run_with(grpc_server, go)


async def test_execute_env_forwarded(grpc_server):
    # Improvement over the reference, which drops env on gRPC (servicer :67-70).
    async def go(stubs):
        req = pb.ExecuteRequest(source_code="import os; print(os.environ['K'])")
        req.env["K"] = "V"
        resp = await stubs["Execute"](req)
        assert resp.stdout == "V\n"

    await run_with(grpc_server, go)


async def test_execute_empty_source_rejected(grpc_server):
    async def go(stubs):
        try:
            await stubs["Execute"](pb.ExecuteRequest(source_code=""))
        except grpc.aio.AioRpcError as e:
            assert e.code() == grpc.StatusCode.INVALID_ARGUMENT
            return
        raise AssertionError("expected INVALID_ARGUMENT")

    await run_with(grpc_server, go)


async def test_file_roundtrip(grpc_server):
    async def go(stubs):
        r1 = await stubs["Execute"](
            pb.ExecuteRequest(source_code="open('f.txt','w').write('grpc state')")
        )
        assert dict(r1.files).keys() == {"/workspace/f.txt"}
        req = pb.ExecuteRequest(source_code="print(open('f.txt').read())")
        for k, v in r1.files.items():
            req.files[k] = v
        r2 = await stubs["Execute"](req)
        assert r2.stdout == "grpc state\n"

    await run_with(grpc_server, go)


async def test_parse_custom_tool_oneof_success(grpc_server):
    async def go(stubs):
        resp = await stubs["ParseCustomTool"](
            pb.ParseCustomToolRequest(
                tool_source_code="def t(a: int) -> int:\n  return a"
            )
        )
        assert resp.WhichOneof("response") == "success"
        assert resp.success.tool_name == "t"
        schema = json.loads(resp.success.tool_input_schema_json)
        assert schema["properties"]["a"] == {"type": "integer"}

    await run_with(grpc_server, go)


async def test_parse_custom_tool_oneof_error(grpc_server):
    async def go(stubs):
        resp = await stubs["ParseCustomTool"](
            pb.ParseCustomToolRequest(tool_source_code="def t(**kw) -> int:\n  return 1")
        )
        assert resp.WhichOneof("response") == "error"
        assert list(resp.error.error_messages) == ["The tool function must not have **kwargs"]

    await run_with(grpc_server, go)


async def test_execute_custom_tool_oneof_success_exact_json(grpc_server):
    async def go(stubs):
        resp = await stubs["ExecuteCustomTool"](
            pb.ExecuteCustomToolRequest(
                tool_source_code="def add(a: int, b: int) -> int:\n  return a + b",
                tool_input_json='{"a": 1, "b": 2}',
            )
        )
        assert resp.WhichOneof("response") == "success"
        assert resp.success.tool_output_json == "3"  # exact encoding (test_grpc.py:254)

    await run_with(grpc_server, go)


async def test_execute_custom_tool_oneof_error(grpc_server):
    async def go(stubs):
        resp = await stubs["ExecuteCustomTool"](
            pb.ExecuteCustomToolRequest(
                tool_source_code="def div(a: int, b: int) -> int:\n  return a / b",
                tool_input_json='{"a": 1, "b": 0}',
            )
        )
        assert resp.WhichOneof("response") == "error"
        assert "division by zero" in resp.error.stderr

    await run_with(grpc_server, go)


async def test_health_check_protocol(grpc_server):
    # Standard grpc.health.v1 Check — the reference's acknowledged TODO
    # (reference grpc_server.py:71), so any stock gRPC prober works against us.
    from bee_code_interpreter_tpu.api.grpc_server import SERVICE_NAME, health_stub
    from bee_code_interpreter_tpu.proto import health_pb2

    port = await grpc_server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            check = health_stub(channel)
            for service in ("", SERVICE_NAME):
                resp = await check(health_pb2.HealthCheckRequest(service=service))
                assert resp.status == health_pb2.HealthCheckResponse.SERVING

            try:
                await check(health_pb2.HealthCheckRequest(service="no.such.Service"))
            except grpc.aio.AioRpcError as e:
                assert e.code() == grpc.StatusCode.NOT_FOUND
            else:
                raise AssertionError("expected NOT_FOUND")

            grpc_server.health.set_status(
                "", health_pb2.HealthCheckResponse.NOT_SERVING
            )
            resp = await check(health_pb2.HealthCheckRequest(service=""))
            assert resp.status == health_pb2.HealthCheckResponse.NOT_SERVING
    finally:
        await grpc_server.stop(None)


async def test_drain_aborts_new_rpcs_and_flips_health_not_serving(
    local_executor,
):
    # Acceptance: after begin_drain, new Execute RPCs abort UNAVAILABLE
    # with a retry hint while gRPC health answers NOT_SERVING — an in-flight
    # RPC admitted before the drain still completes.
    import asyncio

    from bee_code_interpreter_tpu.api.grpc_server import health_stub
    from bee_code_interpreter_tpu.proto import health_pb2
    from bee_code_interpreter_tpu.resilience import DrainController

    drain = DrainController(retry_after_s=1.5)
    server = GrpcServer(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        drain=drain,
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stubs = service_stubs(channel)
            inflight = asyncio.ensure_future(
                stubs["Execute"](
                    pb.ExecuteRequest(
                        source_code="import time; time.sleep(0.6); print('done')"
                    )
                )
            )
            for _ in range(100):
                if drain.in_flight > 0:
                    break
                await asyncio.sleep(0.01)
            assert drain.in_flight == 1

            drain.begin()
            try:
                await stubs["Execute"](pb.ExecuteRequest(source_code="print(1)"))
            except grpc.aio.AioRpcError as e:
                assert e.code() == grpc.StatusCode.UNAVAILABLE
                assert "draining" in e.details()
                # Metadata iterates as (key, value) pairs but is not a dict
                trailing = {k: v for k, v in (e.trailing_metadata() or ())}
            else:
                raise AssertionError("expected UNAVAILABLE while draining")
            assert trailing.get("retry-after-s") == "1.5"

            check = health_stub(channel)
            for service in ("", "code_interpreter.v1.CodeInterpreterService"):
                resp = await check(health_pb2.HealthCheckRequest(service=service))
                assert resp.status == health_pb2.HealthCheckResponse.NOT_SERVING

            # the RPC admitted before the drain still completes
            resp = await inflight
            assert resp.stdout == "done\n"
            assert await drain.wait_idle(1.0) is True
    finally:
        await server.stop(None)


async def test_invalid_files_rejected_invalid_argument(grpc_server):
    # Transport parity (round-1 missing #2): malformed files keys/hashes must
    # abort INVALID_ARGUMENT on gRPC exactly as pydantic 422s them on HTTP,
    # never reach the executor.
    async def go(stubs):
        req = pb.ExecuteRequest(source_code="print(1)")
        req.files["relative/path.txt"] = "deadbeef"  # key must be absolute
        try:
            await stubs["Execute"](req)
        except grpc.aio.AioRpcError as e:
            assert e.code() == grpc.StatusCode.INVALID_ARGUMENT
            assert "relative/path.txt" in e.details() or "pattern" in e.details()
        else:
            raise AssertionError("expected INVALID_ARGUMENT")

        req2 = pb.ExecuteRequest(source_code="print(1)")
        req2.files["/workspace/ok.txt"] = "not a hash!!"  # value must be token-safe
        try:
            await stubs["Execute"](req2)
        except grpc.aio.AioRpcError as e:
            assert e.code() == grpc.StatusCode.INVALID_ARGUMENT
        else:
            raise AssertionError("expected INVALID_ARGUMENT")

    await run_with(grpc_server, go)


async def test_negative_timeout_rejected_invalid_argument(grpc_server):
    async def go(stubs):
        req = pb.ExecuteRequest(source_code="print(1)", timeout=-5.0)
        try:
            await stubs["Execute"](req)
        except grpc.aio.AioRpcError as e:
            assert e.code() == grpc.StatusCode.INVALID_ARGUMENT
            assert "timeout" in e.details()
        else:
            raise AssertionError("expected INVALID_ARGUMENT")

    await run_with(grpc_server, go)


async def test_server_reflection_list_and_describe(grpc_server):
    # grpcurl-style discovery (reference grpc_server.py:67-69): list exposes
    # the 3 services; file_containing_symbol returns the descriptor closure
    # from which the Execute method can be reconstructed.
    from google.protobuf import descriptor_pb2, descriptor_pool
    from bee_code_interpreter_tpu.api.grpc_server import (
        FLEET_SERVICE_NAME,
        HEALTH_SERVICE_NAME,
        OBSERVABILITY_SERVICE_NAME,
        REFLECTION_SERVICE_NAME,
        SERVICE_NAME,
        SESSION_SERVICE_NAME,
        reflection_stub,
    )
    from bee_code_interpreter_tpu.proto import reflection_pb2

    port = await grpc_server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            call = reflection_stub(channel)(
                iter(
                    [
                        reflection_pb2.ServerReflectionRequest(list_services=""),
                        reflection_pb2.ServerReflectionRequest(
                            file_containing_symbol=SERVICE_NAME
                        ),
                        reflection_pb2.ServerReflectionRequest(
                            file_containing_symbol="no.such.Symbol"
                        ),
                    ]
                )
            )
            responses = [r async for r in call]
            assert len(responses) == 3

            listed = {s.name for s in responses[0].list_services_response.service}
            assert listed == {
                SERVICE_NAME,
                SESSION_SERVICE_NAME,
                FLEET_SERVICE_NAME,
                OBSERVABILITY_SERVICE_NAME,
                HEALTH_SERVICE_NAME,
                REFLECTION_SERVICE_NAME,
            }

            files = responses[1].file_descriptor_response.file_descriptor_proto
            assert files  # at least the defining file
            # rebuild a client-side pool from the returned closure (what
            # grpcurl does) and find the Execute method in it
            pool = descriptor_pool.DescriptorPool()
            for raw in reversed(list(files)):  # deps before dependents
                pool.Add(descriptor_pb2.FileDescriptorProto.FromString(raw))
            method = pool.FindMethodByName(f"{SERVICE_NAME}.Execute")
            assert method.input_type.name == "ExecuteRequest"

            err = responses[2].error_response
            assert err.error_code == grpc.StatusCode.NOT_FOUND.value[0]
    finally:
        await grpc_server.stop(None)
