"""The sitecustomize shim end-to-end: executed user code gets the numpy→XLA
reroute and display patches without importing anything itself."""

from pathlib import Path

import pytest

from bee_code_interpreter_tpu.services.local_code_executor import LocalCodeExecutor

SHIM_DIR = (
    Path(__file__).resolve().parent.parent
    / "bee_code_interpreter_tpu" / "runtime" / "shim"
)


@pytest.fixture
def shimmed_executor(storage, tmp_path):
    return LocalCodeExecutor(
        storage=storage,
        workspace_root=tmp_path / "workspaces",
        disable_dep_install=True,
        execution_timeout_s=120.0,
        shim_dir=SHIM_DIR,
    )


async def test_numpy_reroute_active_in_sandbox(shimmed_executor):
    result = await shimmed_executor.execute(
        "import numpy as np\n"
        "x = np.random.rand(2_000_000)\n"
        "s = np.sum(np.square(x))\n"
        "print(type(s).__name__)\n"
        "print(abs(float(s) / len(x) - 1/3) < 0.01)\n",
        env={"JAX_PLATFORMS": "cpu"},
    )
    assert result.exit_code == 0, result.stderr
    assert result.stdout == "TpuArray\nTrue\n"


async def test_small_arrays_untouched_in_sandbox(shimmed_executor):
    result = await shimmed_executor.execute(
        "import numpy as np\n"
        "out = np.matmul(np.ones((3, 3)), np.ones((3, 3)))\n"
        "print(type(out).__name__)\n",
        env={"JAX_PLATFORMS": "cpu"},
    )
    assert result.exit_code == 0, result.stderr
    assert result.stdout == "ndarray\n"


async def test_profile_capture_rides_file_snapshot(shimmed_executor):
    # BCI_PROFILE_DIR → jax.profiler trace written under the workspace, so it
    # comes back through the ordinary changed-file map (SURVEY.md §5).
    result = await shimmed_executor.execute(
        "import jax\n"
        "jax.numpy.arange(16).sum().block_until_ready()\n",
        env={"JAX_PLATFORMS": "cpu", "BCI_PROFILE_DIR": "trace"},
    )
    assert result.exit_code == 0, result.stderr
    assert any(f.startswith("/workspace/trace/") for f in result.files), result.files


async def test_matplotlib_show_saves_plot(shimmed_executor):
    pytest.importorskip("matplotlib")
    result = await shimmed_executor.execute(
        "import matplotlib\n"
        "matplotlib.use('Agg')\n"
        "import matplotlib.pyplot as plt\n"
        "plt.plot([1, 2, 3])\n"
        "plt.show()\n",
    )
    assert result.exit_code == 0, result.stderr
    assert "/workspace/plot.png" in result.files
