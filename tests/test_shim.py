"""The sitecustomize shim end-to-end: executed user code gets the numpy→XLA
reroute and display patches without importing anything itself."""

from pathlib import Path

import pytest

from bee_code_interpreter_tpu.services.local_code_executor import LocalCodeExecutor

SHIM_DIR = (
    Path(__file__).resolve().parent.parent
    / "bee_code_interpreter_tpu" / "runtime" / "shim"
)


@pytest.fixture
def shimmed_executor(storage, tmp_path):
    return LocalCodeExecutor(
        storage=storage,
        workspace_root=tmp_path / "workspaces",
        disable_dep_install=True,
        execution_timeout_s=120.0,
        shim_dir=SHIM_DIR,
    )


async def test_numpy_reroute_active_in_sandbox(shimmed_executor):
    result = await shimmed_executor.execute(
        "import numpy as np\n"
        "x = np.random.rand(2_000_000)\n"
        "s = np.sum(np.square(x))\n"
        "print(type(s).__name__)\n"
        "print(abs(float(s) / len(x) - 1/3) < 0.01)\n",
        env={"JAX_PLATFORMS": "cpu"},
    )
    assert result.exit_code == 0, result.stderr
    assert result.stdout == "TpuArray\nTrue\n"


async def test_small_arrays_untouched_in_sandbox(shimmed_executor):
    result = await shimmed_executor.execute(
        "import numpy as np\n"
        "out = np.matmul(np.ones((3, 3)), np.ones((3, 3)))\n"
        "print(type(out).__name__)\n",
        env={"JAX_PLATFORMS": "cpu"},
    )
    assert result.exit_code == 0, result.stderr
    assert result.stdout == "ndarray\n"


async def test_profile_capture_rides_file_snapshot(shimmed_executor):
    # BCI_PROFILE_DIR → jax.profiler trace written under the workspace, so it
    # comes back through the ordinary changed-file map (SURVEY.md §5).
    result = await shimmed_executor.execute(
        "import jax\n"
        "jax.numpy.arange(16).sum().block_until_ready()\n",
        env={"JAX_PLATFORMS": "cpu", "BCI_PROFILE_DIR": "trace"},
    )
    assert result.exit_code == 0, result.stderr
    assert any(f.startswith("/workspace/trace/") for f in result.files), result.files


async def test_matplotlib_show_saves_plot(shimmed_executor):
    pytest.importorskip("matplotlib")
    result = await shimmed_executor.execute(
        "import matplotlib\n"
        "matplotlib.use('Agg')\n"
        "import matplotlib.pyplot as plt\n"
        "plt.plot([1, 2, 3])\n"
        "plt.show()\n",
    )
    assert result.exit_code == 0, result.stderr
    assert "/workspace/plot.png" in result.files


async def test_request_env_optout_disables_reroute(shimmed_executor):
    # BCI_XLA_REROUTE=0 in the request env is the documented opt-out
    # (executor_core._child_env); big arrays must stay plain ndarrays.
    result = await shimmed_executor.execute(
        "import numpy as np\n"
        "x = np.random.rand(2_000_000)\n"
        "print(type(x).__name__)\n"
        "print(type(np.sum(np.square(x))).__name__)\n",
        env={"JAX_PLATFORMS": "cpu", "BCI_XLA_REROUTE": "0"},
    )
    assert result.exit_code == 0, result.stderr
    assert result.stdout == "ndarray\nfloat64\n"


async def test_midscript_optout_takes_effect(shimmed_executor):
    # Round-1 weak #3: once numpy was imported (by anything — site hooks,
    # preload), an in-script env opt-out was a no-op because the proxies only
    # checked the flag at install time. Now they re-check per call.
    result = await shimmed_executor.execute(
        "import numpy as np\n"
        "before = type(np.random.rand(2_000_000)).__name__\n"
        "import os\n"
        "os.environ['BCI_XLA_REROUTE'] = '0'\n"
        "after = type(np.random.rand(2_000_000)).__name__\n"
        "print(before, after)\n",
        env={"JAX_PLATFORMS": "cpu"},
    )
    assert result.exit_code == 0, result.stderr
    assert result.stdout == "TpuArray ndarray\n"


async def test_chainloaded_sitecustomize_defers_patch(shimmed_executor, tmp_path):
    # Two deferral layers under test. (1) The chained (image) sitecustomize
    # itself no longer runs at interpreter start — it costs ~1 s of
    # accelerator-plugin import in real images, so it fires at the first
    # accelerator-adjacent import (here: a torch_xla attempt; even a failing
    # import must trigger it first). (2) Round-1 weak #1 root cause: imports
    # made WHILE the chained sitecustomize executes are platform
    # infrastructure and must not trigger patches; the first user-level
    # import still must.
    site_dir = tmp_path / "image-site"
    site_dir.mkdir()
    (site_dir / "sitecustomize.py").write_text(
        "import json\n"
        "import numpy as np\n"  # platform infrastructure importing numpy
        "with open('chainprobe.json', 'w') as f:\n"
        "    json.dump(\n"
        "        {'proxied_during_chain':\n"
        "         bool(getattr(np, '__bci_xla_rerouted__', False))}, f)\n"
    )
    result = await shimmed_executor.execute(
        "import json, os\n"
        "print(os.path.exists('chainprobe.json'))\n"  # chain still deferred
        "try:\n"
        "    import torch_xla\n"  # accelerator-adjacent: fires the chain
        "except ImportError:\n"
        "    pass\n"
        "probe = json.load(open('chainprobe.json'))\n"
        "import numpy as np\n"  # the *user* import: patch applies here
        "print(probe['proxied_during_chain'])\n"
        "print(bool(getattr(np, '__bci_xla_rerouted__', False)))\n",
        env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": str(site_dir)},
    )
    assert result.exit_code == 0, result.stderr
    assert result.stdout == "False\nFalse\nTrue\n"


async def test_chain_fires_via_importlib_too(shimmed_executor, tmp_path):
    # importlib.import_module bypasses builtins.__import__ entirely — the
    # chain tripwire is a meta-path finder precisely so plugin/entry-point
    # style loading still primes the image's site hooks first.
    site_dir = tmp_path / "image-site"
    site_dir.mkdir()
    (site_dir / "sitecustomize.py").write_text(
        "with open('chained.flag', 'w') as f:\n    f.write('yes')\n"
    )
    result = await shimmed_executor.execute(
        "import importlib, os\n"
        "print(os.path.exists('chained.flag'))\n"
        "try:\n"
        "    importlib.import_module('torch_xla')\n"
        "except ImportError:\n"
        "    pass\n"
        "print(os.path.exists('chained.flag'))\n",
        env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": str(site_dir)},
    )
    assert result.exit_code == 0, result.stderr
    assert result.stdout == "False\nTrue\n"
