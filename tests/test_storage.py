import hashlib

import pytest

from bee_code_interpreter_tpu.services.storage import Storage


async def test_roundtrip(storage: Storage):
    object_id = await storage.write(b"hello tpu")
    assert await storage.read(object_id) == b"hello tpu"
    assert await storage.exists(object_id)


async def test_content_addressed(storage: Storage):
    data = b"deterministic content"
    a = await storage.write(data)
    b = await storage.write(data)
    assert a == b == hashlib.sha256(data).hexdigest()


async def test_streaming_writer_reader(storage: Storage):
    async with storage.writer() as w:
        await w.write(b"part1-")
        await w.write(b"part2")
    chunks = []
    async with storage.reader(w.hash) as r:
        async for chunk in r:
            chunks.append(chunk)
    assert b"".join(chunks) == b"part1-part2"


async def test_missing_object(storage: Storage):
    assert not await storage.exists("0" * 64)
    with pytest.raises(FileNotFoundError):
        await storage.read("0" * 64)


async def test_aborted_write_leaves_no_object(storage: Storage, tmp_path):
    class Boom(Exception):
        pass

    with pytest.raises(Boom):
        async with storage.writer() as w:
            await w.write(b"partial")
            raise Boom()
    # no temp litter, no object
    assert list((tmp_path / "objects").iterdir()) == []
