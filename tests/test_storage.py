import hashlib

import pytest

from bee_code_interpreter_tpu.services.storage import Storage


async def test_roundtrip(storage: Storage):
    object_id = await storage.write(b"hello tpu")
    assert await storage.read(object_id) == b"hello tpu"
    assert await storage.exists(object_id)


async def test_content_addressed(storage: Storage):
    data = b"deterministic content"
    a = await storage.write(data)
    b = await storage.write(data)
    assert a == b == hashlib.sha256(data).hexdigest()


async def test_streaming_writer_reader(storage: Storage):
    async with storage.writer() as w:
        await w.write(b"part1-")
        await w.write(b"part2")
    chunks = []
    async with storage.reader(w.hash) as r:
        async for chunk in r:
            chunks.append(chunk)
    assert b"".join(chunks) == b"part1-part2"


async def test_missing_object(storage: Storage):
    assert not await storage.exists("0" * 64)
    with pytest.raises(FileNotFoundError):
        await storage.read("0" * 64)


async def test_aborted_write_leaves_no_object(storage: Storage, tmp_path):
    class Boom(Exception):
        pass

    with pytest.raises(Boom):
        async with storage.writer() as w:
            await w.write(b"partial")
            raise Boom()
    # no temp litter, no object
    assert list((tmp_path / "objects").iterdir()) == []


async def test_sweep_removes_only_expired(storage: Storage, tmp_path):
    import os
    import time

    old_id = await storage.write(b"ancient snapshot")
    fresh_id = await storage.write(b"current snapshot")
    # age the first object past the TTL
    past = time.time() - 1000
    os.utime(tmp_path / "objects" / old_id, (past, past))

    removed = await storage.sweep(max_age_s=500)
    assert removed == 1
    assert not await storage.exists(old_id)
    assert await storage.exists(fresh_id)
    # sweeping an empty/again is a no-op
    assert await storage.sweep(max_age_s=500) == 0


async def test_sweep_skips_inflight_writes(storage: Storage, tmp_path):
    import os
    import time

    root = tmp_path / "objects"
    async with storage.writer() as w:
        await w.write(b"long upload in progress")
        # even an "old" temp file survives (clock skew / slow streams)
        tmp_files = [p for p in root.iterdir() if p.name.startswith(".tmp-")]
        past = time.time() - 10_000
        for p in tmp_files:
            os.utime(p, (past, past))
        assert await storage.sweep(max_age_s=500) == 0
    assert await storage.exists(w.hash)


async def test_sweep_recovers_orphaned_guards(storage: Storage, tmp_path):
    # A sweep that crashed between rename-aside and resolution leaves
    # .tmp-sweep-<id> entries; the next sweep must restore fresh ones under
    # their public name and unlink expired ones (ADVICE r2: otherwise a
    # permanent disk leak every future sweep skips).
    import os
    import time

    root = tmp_path / "objects"
    live_id = await storage.write(b"live object a crashed sweep set aside")
    (root / live_id).rename(root / f".tmp-sweep-{live_id}")
    dead_id = await storage.write(b"expired object a crashed sweep set aside")
    dead_guard = root / f".tmp-sweep-{dead_id}"
    (root / dead_id).rename(dead_guard)
    past = time.time() - 10_000
    os.utime(dead_guard, (past, past))

    removed = await storage.sweep(max_age_s=500)
    assert removed == 1
    assert await storage.read(live_id) == b"live object a crashed sweep set aside"
    assert not await storage.exists(dead_id)
    assert [p for p in root.iterdir() if p.name.startswith(".tmp-sweep-")] == []


async def test_sweep_orphan_recovery_prefers_newer_public_write(
    storage: Storage, tmp_path
):
    # If an identical-content write recreated the public name after the crash,
    # the restore must not clobber it — the orphan is simply dropped.
    root = tmp_path / "objects"
    object_id = await storage.write(b"v1 content")
    (root / object_id).rename(root / f".tmp-sweep-{object_id}")
    # content-addressed: same bytes recreate the same public name
    assert await storage.write(b"v1 content") == object_id

    await storage.sweep(max_age_s=500)
    assert await storage.read(object_id) == b"v1 content"
    assert [p for p in root.iterdir() if p.name.startswith(".tmp-sweep-")] == []


async def test_read_refreshes_ttl(tmp_path):
    # A session that only restores a file (never rewrites it) must keep it
    # alive under the TTL sweep: reads mark use. Touch-on-read is opt-in —
    # enabled by ApplicationContext exactly when a TTL is configured.
    import os
    import time

    storage = Storage(tmp_path / "objects", touch_on_read=True)
    object_id = await storage.write(b"restored every run, never modified")
    past = time.time() - 1000
    os.utime(tmp_path / "objects" / object_id, (past, past))

    assert await storage.read(object_id)  # a restore happens...
    assert await storage.sweep(max_age_s=500) == 0  # ...so it survives
    assert await storage.exists(object_id)
