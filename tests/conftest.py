"""Test harness configuration.

- Forces JAX onto a *virtual 8-device CPU mesh* (SURVEY.md §4 "Implication for
  the TPU build") so DP/TP/SP paths run in CI without TPU hardware. Must happen
  before the first ``import jax`` anywhere in the test session.
- Runs ``async def`` tests directly (no pytest-asyncio in this environment):
  a minimal pytest_pyfunc_call hook executes coroutine tests via asyncio.run.
"""

import asyncio
import inspect
import os

# Force CPU regardless of ambient JAX_PLATFORMS (the dev box pre-sets a TPU
# platform and prepends it to jax_platforms even when the env var says cpu):
# tests must run on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
# grpc C-core INFO logs (GOAWAY notices on channel close) write straight to
# stderr and can interleave into pytest's progress-dot stream, corrupting
# dot-counting harnesses; only errors are worth hearing from the transport.
os.environ.setdefault("GRPC_VERBOSITY", "ERROR")
# Drop accelerator-tunnel plugin vars entirely: the dev box's TPU plugin hooks
# jax backend init whenever its pool vars are visible — even under
# JAX_PLATFORMS=cpu — and blocks on the (single-client) tunnel. Tests and
# every sandbox subprocess they spawn (which inherit via the executor's
# TPU_PASSTHROUGH_PREFIXES) must be hermetic CPU-only.
import sys  # noqa: E402
from pathlib import Path  # noqa: E402

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
from bee_code_interpreter_tpu.utils.envscrub import (  # noqa: E402
    scrub_tunnel_plugin_vars,
)

scrub_tunnel_plugin_vars()

# Sandbox subprocesses must import bee_code_interpreter_tpu the way the
# executor IMAGE guarantees (its Dockerfile installs the package). On the CPU
# test harness nothing installs it, and the ambient PYTHONPATH is the host's
# (this round it held only the tunnel plugin's site dir — examples importing
# the package failed with ModuleNotFoundError): mirror the image guarantee by
# putting the repo root on the PYTHONPATH every _child_env inherits.
_repo_root = str(Path(__file__).resolve().parent.parent)
_pp = os.environ.get("PYTHONPATH", "")
if _repo_root not in _pp.split(os.pathsep):
    os.environ["PYTHONPATH"] = _pp + (os.pathsep if _pp else "") + _repo_root

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {name: pyfuncitem.funcargs[name] for name in pyfuncitem._fixtureinfo.argnames}
        asyncio.run(fn(**kwargs))
        return True
    return None


@pytest.fixture(scope="session")
def native_binary():
    """Build the C++ executor server once per session; None without a toolchain.

    Shared by the native-executor unit tests and the e2e native backend so the
    `make -C executor` invocation happens exactly once per pytest run.
    """
    import shutil
    import subprocess
    from pathlib import Path

    executor_dir = Path(__file__).resolve().parent.parent / "executor"
    binary = executor_dir / "build" / "executor-server"
    if shutil.which("make") is None or shutil.which("g++") is None:
        return None
    result = subprocess.run(
        ["make", "-C", str(executor_dir)], capture_output=True, text=True
    )
    return binary if result.returncode == 0 and binary.exists() else None


@pytest.fixture
def storage(tmp_path):
    from bee_code_interpreter_tpu.services.storage import Storage

    return Storage(tmp_path / "objects")


@pytest.fixture
def local_executor_factory(storage, tmp_path):
    """One construction site for the test LocalCodeExecutor; tests that
    need a different execution timeout call the factory instead of
    re-building the executor (keeping constructor changes in one place)."""
    from bee_code_interpreter_tpu.services.local_code_executor import LocalCodeExecutor

    def make(execution_timeout_s: float = 30.0):
        return LocalCodeExecutor(
            storage=storage,
            workspace_root=tmp_path / "workspaces",
            disable_dep_install=True,
            execution_timeout_s=execution_timeout_s,
        )

    return make


@pytest.fixture
def local_executor(local_executor_factory):
    return local_executor_factory()


@pytest.fixture
def http_app(local_executor):
    """The aiohttp app over the local executor — the in-process service
    surface example/baseline-config tests drive payloads through."""
    from bee_code_interpreter_tpu.api.http_server import create_http_server
    from bee_code_interpreter_tpu.services.custom_tool_executor import (
        CustomToolExecutor,
    )

    return create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
    )



# ---------------------------------------------------------------- fast lane
# The model/serving/parallelism suites jit-compile dozens of programs and the
# e2e suites boot real services — together they dominate the ~35 min full
# run. `pytest -m "not slow"` is the inner loop: service + executor contract
# tests in a few minutes. The full suite is unchanged (markers only).
SLOW_TEST_MODULES = {
    "test_baseline_configs", "test_beam", "test_bench", "test_bench_mfu",
    "test_checkpoint", "test_chunked_prefill", "test_engine",
    "test_example_payloads", "test_flash_attention", "test_hf_loader",
    "test_interleaved_admission",
    "test_kv_cache", "test_local_code_executor", "test_lora", "test_models",
    "test_moe", "test_multihost_distributed", "test_multilora_serving",
    "test_paged_attention", "test_paged_kv_cache", "test_parallel",
    "test_pipeline", "test_pipeline_transformer", "test_prefix_cache",
    "test_replicated", "test_serving", "test_serving_fuzz",
    "test_serving_mesh", "test_serving_stops",
    "test_sliding_window",
    "test_speculative", "test_speculative_sampling", "test_text_engine",
    "test_ulysses", "test_vision", "test_vit", "test_weight_quant",
    "test_xla_reroute",
}


@pytest.fixture(autouse=True, scope="module")
def _bound_xla_jit_accumulation():
    """Clear jax's compilation caches after every test module.

    A full-suite run compiles thousands of programs into ONE process; at
    this round's suite size the XLA CPU backend started segfaulting inside
    backend_compile late in the run (reproducibly around the ~620th test,
    never in any subset), which points at accumulated JIT code/state
    rather than any single test. Per-module clearing bounds the
    accumulation; modules recompile their own programs anyway, so the
    cost is only the cross-module shared primitives."""
    yield
    jax.clear_caches()


def pytest_collection_modifyitems(config, items):
    for item in items:
        module = item.nodeid.split("::", 1)[0]
        name = Path(module).stem
        if name in SLOW_TEST_MODULES or "/e2e/" in module:
            item.add_marker(pytest.mark.slow)
