"""Data-parallel serving replicas (models/replicated.py): routing, dp × tp
placement over the virtual device mesh, prefix affinity, and the same
solo-equality bar as every other serving layer."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models.replicated import ReplicatedEngine

CFG = dataclasses.replace(
    T.TransformerConfig.tiny(), dtype=jnp.float32, n_kv_heads=2
)
PARAMS = T.init_params(CFG, jax.random.PRNGKey(0))


def solo(prompt, n):
    out = T.Transformer(CFG).generate_cached(
        PARAMS, jnp.asarray(prompt, dtype=jnp.int32)[None, :],
        max_new_tokens=n,
    )
    return np.asarray(out[0, len(prompt):]).tolist()


def build(n_replicas=2, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("n_pages", 24)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_pages_per_seq", 6)
    return ReplicatedEngine.build(PARAMS, CFG, n_replicas, **kw)


def test_replicas_spread_load_and_stay_solo_equal():
    eng = build(2)
    prompts = [
        [int(x) for x in np.random.default_rng(i).integers(0, 200, 4 + i)]
        for i in range(6)
    ]
    tickets = [eng.submit(p, 4) for p in prompts]
    # least-outstanding routing with 2-row replicas must use both
    assert {eng.replica_of(t) for t in tickets} == {0, 1}
    eng.run_to_completion()
    for t, p in zip(tickets, prompts):
        assert eng.result(t) == solo(p, 4)
        assert eng.finish_reason(t) == "length"
    st = eng.stats
    assert st["replicas"] == 2 and st["active_rows"] == 0


def test_replicas_live_on_distinct_devices():
    eng = build(2)
    devs = [
        next(iter(e.batcher.cache["k"].sharding.device_set))
        for e in eng.engines
    ]
    assert devs[0] != devs[1]


def test_dp_times_tp_replicas():
    # 2 replicas × tp=2 over 4 distinct virtual devices — the standard
    # serving topology, entirely in-process
    devices = jax.devices()
    meshes = [
        Mesh(np.array(devices[0:2]), ("tp",)),
        Mesh(np.array(devices[2:4]), ("tp",)),
    ]
    eng = build(2, meshes=meshes)
    p1, p2 = [5, 3, 7, 2, 9, 4, 1, 8], [1, 2, 3]
    t1, t2 = eng.submit(p1, 5), eng.submit(p2, 5)
    eng.run_to_completion()
    assert eng.result(t1) == solo(p1, 5)
    assert eng.result(t2) == solo(p2, 5)
    used = set()
    for e in eng.engines:
        shard_devs = e.batcher.cache["k"].sharding.device_set
        assert len(shard_devs) == 2  # tp really sharded within the replica
        used |= shard_devs
    assert len(used) == 4  # replicas on disjoint device pairs


def test_prefix_affinity_routes_repeats_to_same_replica():
    eng = build(2, prefix_affinity=True, prefix_cache=True)
    prompt = [7] * 9  # > 2 pages: a cacheable full-page prefix
    t1 = eng.submit(prompt, 3)
    eng.run_to_completion()
    t2 = eng.submit(prompt, 3)
    eng.run_to_completion()
    assert eng.replica_of(t1) == eng.replica_of(t2)
    hits = eng.engines[eng.replica_of(t2)].batcher.prefix_stats["hits"]
    assert hits >= 1  # the repeat actually reused pages
    assert eng.result(t1) == eng.result(t2) == solo(prompt, 3)


def test_affinity_yields_to_load():
    eng = build(2, prefix_affinity=True, affinity_slack=0, prefix_cache=True)
    prompt = [7] * 9
    preferred = eng._route(np.asarray(prompt, dtype=np.int32))
    # saturate the preferred replica's queue beyond the slack
    for _ in range(4):
        eng.engines[preferred].submit([1, 2, 3], 3)
    routed = eng._route(np.asarray(prompt, dtype=np.int32))
    assert routed != preferred
    eng.run_to_completion()


def test_streaming_and_cancel_pass_through():
    eng = build(2)
    t = eng.submit([5, 3, 7, 2], 6)
    seen: list[int] = []
    for _ in range(60):
        eng.step()
        seen += eng.new_tokens(t)
        if eng.is_done(t):
            break
    seen += eng.new_tokens(t)
    assert seen == eng.result(t) == solo([5, 3, 7, 2], 6)
    t2 = eng.submit([1, 2, 3], 15)
    eng.step()
    eng.cancel(t2)
    eng.run_to_completion()
    assert eng.finish_reason(t2) == "cancelled"
    eng.release(t2)
    with pytest.raises(KeyError):
        eng.result(t2)


def test_build_validates_replica_count():
    with pytest.raises(ValueError, match="devices"):
        ReplicatedEngine.build(PARAMS, CFG, 99)
    with pytest.raises(ValueError, match="at least one"):
        ReplicatedEngine([])


def test_full_queue_falls_back_to_other_replica():
    # max_queue bounds the pre-admission queue (admission happens in step,
    # not submit): with max_queue=1, each replica takes ONE ticket before
    # any step. The router must spill the second onto the other replica
    # rather than reject, and only reject when every replica is full.
    eng = build(2, max_queue=1)
    t1 = eng.submit([1, 2, 3], 3)
    t2 = eng.submit([1, 2, 3], 3)  # first replica full: falls back
    assert eng.replica_of(t1) != eng.replica_of(t2)
    with pytest.raises(RuntimeError, match="every replica"):
        eng.submit([1, 2, 3], 3)  # now genuinely everyone is full
    eng.run_to_completion()
    assert eng.result(t1) == eng.result(t2) == solo([1, 2, 3], 3)


def test_stats_distinguish_monotonic_from_live():
    eng = build(2)
    t1 = eng.submit([1, 2, 3], 3)
    t2 = eng.submit([4, 5, 6], 3)
    eng.run_to_completion()
    eng.release(t1)
    st = eng.stats
    assert st["requests_submitted"] == 2  # monotonic
    assert st["live_tickets"] == 1  # t2 still held
    assert eng.result(t2) == solo([4, 5, 6], 3)


def test_text_engine_composes_over_replicas():
    """TextEngine consumes the Engine surface only — a ReplicatedEngine
    drops in unchanged, giving text-level serving over dp replicas."""
    from bee_code_interpreter_tpu.models.text import TextEngine

    class CharTokenizer:
        def encode(self, text):
            return [ord(ch) % CFG.vocab_size for ch in text]

        def decode(self, tokens):
            return "".join(chr(32 + (t % 94)) for t in tokens)

    te = TextEngine(build(2), CharTokenizer())
    t1 = te.submit("hello world", 6)
    t2 = te.submit("other prompt", 6)
    te.run_to_completion()
    tok = CharTokenizer()
    want1 = tok.decode(solo(tok.encode("hello world"), 6))
    assert te.text(t1) == want1
    assert te.finish_reason(t1) == te.finish_reason(t2) == "length"
