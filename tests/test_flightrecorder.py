"""Flight recorder (ISSUE 8): wide-event ring/rotation/filtering units, the
tracer-sink emission path, the `/v1/events` API + SSE tail on the real HTTP
edge, the gRPC mirror, OTLP logs export with exact drop accounting (the
tier-1 half of chaos scenario 11), and session lifecycle emission."""

import asyncio
import json

import grpc.aio
import pytest

from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_tpu.api.grpc_server import (
    GrpcServer,
    observability_stubs,
    service_stubs,
)
from bee_code_interpreter_tpu.api.http_server import create_http_server
from bee_code_interpreter_tpu.observability import (
    FlightRecorder,
    TelemetryExporter,
    Tracer,
    span,
    wide_event_from_trace,
)
from bee_code_interpreter_tpu.proto import code_interpreter_pb2 as pb
from bee_code_interpreter_tpu.resilience import RetryPolicy
from bee_code_interpreter_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_tpu.utils.metrics import Registry
from tests.fakes import FakeCollector


# ------------------------------------------------------------------ ring/query


def test_ring_bounded_filters_and_seq():
    recorder = FlightRecorder(max_events=4, metrics=Registry())
    for i in range(6):
        recorder.record(
            {
                "kind": "request",
                "outcome": "ok" if i % 2 == 0 else "error",
                "session": f"sess-{i % 3}",
                "duration_ms": float(i * 100),
                "ts": 1000.0 + i,
            }
        )
    assert len(recorder) == 4  # ring evicted the oldest two
    events = recorder.events()
    assert [e["seq"] for e in events] == [6, 5, 4, 3]  # newest first
    assert [e["seq"] for e in recorder.events(outcome="error")] == [6, 4]
    assert [e["seq"] for e in recorder.events(session="sess-2")] == [6, 3]
    assert [e["seq"] for e in recorder.events(min_duration_ms=400.0)] == [6, 5]
    assert [e["seq"] for e in recorder.events(since=1003.5)] == [6, 5]
    assert [e["seq"] for e in recorder.events(limit=1)] == [6]
    assert recorder.events(limit=0) == []  # a zero backlog replays nothing
    assert recorder.events(kind="session") == []


def test_min_duration_filter_skips_durationless_events():
    recorder = FlightRecorder()
    recorder.record({"kind": "session", "outcome": "created"})  # no duration
    recorder.record({"kind": "request", "duration_ms": 50.0})
    assert [e["kind"] for e in recorder.events(min_duration_ms=1.0)] == [
        "request"
    ]


# ------------------------------------------------------------------- rotation


def test_segment_rotation_bounds_disk(tmp_path):
    recorder = FlightRecorder(
        dir=tmp_path / "events",
        segment_bytes=500,
        max_segments=2,
        metrics=Registry(),
    )
    for batch in range(6):
        for i in range(5):
            recorder.record({"kind": "request", "n": batch * 5 + i, "pad": "x" * 40})
        assert recorder.flush_to_disk() == 5
    segments = recorder.segment_paths()
    assert 1 <= len(segments) <= 2, segments  # rotation deleted the oldest
    # every line in every surviving segment is valid ndjson with a seq
    lines = [
        json.loads(line)
        for p in segments
        for line in p.read_text().splitlines()
    ]
    assert lines and all("seq" in e for e in lines)
    # the newest event survived in the newest segment
    assert lines[-1]["n"] == 29
    assert recorder.snapshot()["segments"] == [p.name for p in segments]


def test_write_queue_bounded_and_accounted(tmp_path):
    metrics = Registry()
    recorder = FlightRecorder(
        dir=tmp_path / "events", write_queue_max=3, metrics=metrics
    )
    for i in range(5):
        recorder.record({"n": i})
    assert len(recorder._pending) == 3
    dropped = metrics.metrics["bci_events_dropped_total"]._values
    assert dropped.get((("reason", "write_queue_full"),)) == 2


# ----------------------------------------------------------- trace -> event


def test_wide_event_from_trace_lifts_annotations():
    tracer = Tracer(metrics=Registry())
    with tracer.trace("/v1/execute", request_id="req-1") as trace:
        with span("execute"):
            pass
        with span("analysis") as s:
            s.attributes["analysis.predicted_deps"] = "numpy"
        trace.root.attributes.update(
            {
                "outcome": "ok",
                "sli": "good",
                "session": "sess-abc",
                "usage.cpu_user_s": "0.25",
                "stream.chunks": "3",
                "stream.ttfb_ms": "17.5",
                "replays": "1",
                "hedge": "primary_won",
                "custom": "kept",
            }
        )
    event = wide_event_from_trace(trace)
    assert event["kind"] == "request"
    assert event["name"] == "/v1/execute"
    assert event["trace_id"] == trace.trace_id
    assert event["request_id"] == "req-1"
    assert event["outcome"] == "ok" and event["sli"] == "good"
    assert event["session"] == "sess-abc"
    assert event["usage"] == {"cpu_user_s": 0.25}
    assert event["stream"] == {"chunks": 3.0, "ttfb_ms": 17.5}
    assert event["replays"] == 1 and event["hedge"] == "primary_won"
    assert event["analysis"] == {"predicted_deps": "numpy"}
    assert event["attributes"] == {"custom": "kept"}
    assert set(event["timings_ms"]) == {"execute", "analysis"}
    assert event["duration_ms"] == pytest.approx(trace.duration_s * 1000.0)


def test_error_trace_defaults_outcome_error():
    tracer = Tracer()
    with pytest.raises(RuntimeError):
        with tracer.trace("/v1/execute") as trace:
            raise RuntimeError("boom")
    assert wide_event_from_trace(trace)["outcome"] == "error"


# ------------------------------------------------------------- HTTP transport


async def with_client(app, fn):
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        return await fn(client)
    finally:
        await client.close()


def make_local_app(local_executor, metrics=None, tracer=None, recorder=None):
    metrics = metrics or Registry()
    return create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        metrics=metrics,
        tracer=tracer,
        recorder=recorder,
    )


async def test_http_wide_event_agrees_with_trace(local_executor):
    """Acceptance: one execution's wide event at /v1/events carries a
    trace_id resolvable at /v1/traces/{id}, and the two views agree on the
    stage breakdown (same sum — they are computed from the same spans)."""
    app = make_local_app(local_executor)

    async def go(client):
        resp = await client.post(
            "/v1/execute", json={"source_code": "print(6 * 7)"}
        )
        body = await resp.json()
        assert resp.status == 200 and body["stdout"] == "42\n"
        trace_id = body["trace_id"]

        events = (await (await client.get("/v1/events")).json())["events"]
        mine = [e for e in events if e.get("trace_id") == trace_id]
        assert len(mine) == 1, events
        event = mine[0]
        assert event["kind"] == "request"
        assert event["name"] == "/v1/execute"
        assert event["outcome"] == "ok" and event["sli"] == "good"
        assert event["duration_ms"] > 0

        detail = await (await client.get(f"/v1/traces/{trace_id}")).json()
        assert detail["trace_id"] == trace_id
        assert sum(event["timings_ms"].values()) == pytest.approx(
            sum(detail["stage_ms"].values())
        )
        # filters reach the same event
        filtered = (
            await (
                await client.get("/v1/events", params={"outcome": "ok"})
            ).json()
        )["events"]
        assert trace_id in {e.get("trace_id") for e in filtered}
        assert (
            await (
                await client.get("/v1/events", params={"outcome": "deadline"})
            ).json()
        )["events"] == []
        bad = await client.get("/v1/events", params={"limit": "nope"})
        assert bad.status == 400

    await with_client(app, go)


async def test_http_sse_follow_delivers_live(local_executor):
    app = make_local_app(local_executor)

    async def go(client):
        tail = await client.get(
            "/v1/events", params={"follow": "1"}, timeout=30
        )
        assert tail.status == 200
        assert tail.headers["Content-Type"].startswith("text/event-stream")

        resp = await client.post(
            "/v1/execute", json={"source_code": "print('live')"}
        )
        trace_id = (await resp.json())["trace_id"]

        async def read_event():
            data_lines = []
            while True:
                line = (await tail.content.readline()).decode()
                if line.startswith("data:"):
                    data_lines.append(line[len("data:"):].strip())
                elif line.strip() == "" and data_lines:
                    return json.loads("\n".join(data_lines))

        event = await asyncio.wait_for(read_event(), timeout=10)
        assert event["trace_id"] == trace_id
        tail.close()

    await with_client(app, go)


async def test_debug_bundle_carries_events_section(local_executor):
    app = make_local_app(local_executor)

    async def go(client):
        await client.post("/v1/execute", json={"source_code": "print(1)"})
        bundle = await (await client.get("/v1/debug/bundle")).json()
        assert bundle["events"]["retained"] >= 1
        assert bundle["events"]["recent"][0]["kind"] == "request"
        # loop/profile sections are always present (null when unwired)
        assert "loop" in bundle and "profile" in bundle
        assert bundle["loop"]["tasks"]["count"] >= 1

    await with_client(app, go)


# ------------------------------------------------------------- gRPC transport


async def test_grpc_wide_event_agrees_with_trace(local_executor):
    """The same acceptance on the other transport: Execute over gRPC emits
    a wide event (shared tracer sink) whose trace resolves in the shared
    store with an identical stage breakdown, served by
    ObservabilityService/GetEvents."""
    metrics = Registry()
    tracer = Tracer(metrics=metrics)
    recorder = FlightRecorder(metrics=metrics)
    tracer.add_sink(recorder.record_trace)
    server = GrpcServer(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        metrics=metrics,
        tracer=tracer,
        recorder=recorder,
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stubs = service_stubs(channel)
            resp = await stubs["Execute"](
                pb.ExecuteRequest(source_code="print('grpc wide')")
            )
            assert resp.stdout == "grpc wide\n"
            obs = observability_stubs(channel)
            body = json.loads(await obs["GetEvents"](b'{"outcome": "ok"}'))
            events = [
                e for e in body["events"] if e["name"] == "grpc:Execute"
            ]
            assert len(events) == 1
            event = events[0]
            trace = tracer.store.get(event["trace_id"])
            assert trace is not None  # resolvable at /v1/traces/{id}
            assert sum(event["timings_ms"].values()) == pytest.approx(
                sum(trace.stage_ms().values())
            )
            assert event["sli"] == "good"
            # malformed filter bodies are INVALID_ARGUMENT, never UNKNOWN
            with pytest.raises(grpc.aio.AioRpcError) as excinfo:
                await obs["GetEvents"](b"not json")
            assert excinfo.value.code() == grpc.StatusCode.INVALID_ARGUMENT
            # the task inventory serves real data over this transport too
            tasks = json.loads(await obs["GetTasks"](b""))
            assert tasks["count"] >= 1 and tasks["threads"]
    finally:
        await server.stop(None)


# ------------------------------------------- OTLP logs export (chaos 11 pair)


async def test_logs_export_exact_accounting_under_dead_collector():
    """Wide events flow through the exporter as OTLP logs; killing the
    collector and saturating the queue degrades to bounded, exactly
    accounted drops: emitted == exported + dropped{reason} + queued."""
    metrics = Registry()
    recorder = FlightRecorder(max_events=16, metrics=metrics)
    collector = await FakeCollector().start()
    exporter = TelemetryExporter(
        collector.endpoint,
        metrics,
        flush_interval_s=0.05,
        queue_max=8,
        batch_max=4,
        retry=RetryPolicy(attempts=2, wait_min_s=0.01, wait_max_s=0.02),
    )
    recorder.add_sink(exporter.enqueue_log)
    try:
        for i in range(3):
            recorder.record({"kind": "request", "outcome": "ok", "n": i})
        result = await exporter.flush_once()
        assert result["logs_exported"] == 3
        records = collector.log_records()
        assert len(records) == 3
        # the record body IS the wide event, JSON-encoded, trace-correlatable
        body = json.loads(records[0]["body"]["stringValue"])
        assert body["kind"] == "request" and body["seq"] == 1
        assert {"key": "event.kind", "value": {"stringValue": "request"}} in (
            records[0]["attributes"]
        )

        await collector.stop()  # chaos: collector dies mid-run
        # saturate: 20 more events against a queue of 8
        for i in range(20):
            recorder.record({"kind": "request", "outcome": "ok", "n": 100 + i})
        await exporter.flush_once()  # fails, drops one batch, stops draining
        await exporter.stop()  # accounts the rest as shutdown

        emitted = recorder.snapshot()["emitted"]
        assert emitted == 23
        exported = metrics.metrics["bci_telemetry_exported_total"]._values.get(
            (("signal", "logs"),), 0
        )
        dropped_by_reason = {
            dict(k)["reason"]: v
            for k, v in metrics.metrics[
                "bci_telemetry_dropped_total"
            ]._values.items()
            if dict(k)["signal"] == "logs"
        }
        assert exported == 3
        assert dropped_by_reason.get("queue_full", 0) == 12  # 20 - queue of 8
        # the queued 8: one batch dropped at send, the rest at shutdown
        assert (
            dropped_by_reason.get("send_failed", 0)
            + dropped_by_reason.get("shutdown", 0)
            == 8
        )
        assert exported + sum(dropped_by_reason.values()) == emitted
        assert exporter.logs_queue_depth == 0
    finally:
        await exporter.stop()
        await collector.stop()


# --------------------------------------------------- streaming metrics (sat.)


async def test_streaming_metrics_on_both_edges(local_executor):
    """Satellite: the bench-only streaming numbers are production metrics
    now — an SSE stream records bci_stream_ttfb_seconds +
    bci_stream_chunks_total{transport="http"} and its wide event carries
    stream.chunks / stream.ttfb_ms; gRPC ExecuteStream records the same
    under transport="grpc"."""
    metrics = Registry()
    app = make_local_app(local_executor, metrics=metrics)

    async def go(client):
        resp = await client.post(
            "/v1/execute",
            params={"stream": "1"},
            json={"source_code": "print('c1', flush=True)\nprint('c2')"},
        )
        assert resp.status == 200
        await resp.read()  # drain the SSE body to completion
        text = (await (await client.get("/metrics")).text())
        assert 'bci_stream_ttfb_seconds_count{transport="http"} 1' in text
        assert 'bci_stream_chunks_total{transport="http"}' in text
        events = (await (await client.get("/v1/events")).json())["events"]
        streamed = [e for e in events if e.get("stream")]
        assert streamed, events
        assert streamed[0]["stream"]["chunks"] >= 1
        assert streamed[0]["stream"]["ttfb_ms"] > 0

    await with_client(app, go)

    from bee_code_interpreter_tpu.api.grpc_server import execute_stream_stub

    server = GrpcServer(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        metrics=metrics,
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            call = execute_stream_stub(channel)(
                json.dumps({"source_code": "print('g1', flush=True)"}).encode()
            )
            events = [json.loads(raw) async for raw in call]
            assert events[-1]["event"] == "result"
        text = metrics.expose()
        assert 'bci_stream_ttfb_seconds_count{transport="grpc"} 1' in text
        assert 'bci_stream_chunks_total{transport="grpc"}' in text
    finally:
        await server.stop(None)


# --------------------------------------------------------- session lifecycle


async def test_session_lifecycle_ops_emit_wide_events(local_executor, storage):
    from bee_code_interpreter_tpu.sessions import SessionManager

    metrics = Registry()
    recorder = FlightRecorder(metrics=metrics)
    manager = SessionManager(
        local_executor, storage, metrics=metrics, recorder=recorder, ttl_s=0.2
    )
    session = await manager.create()
    sid = session.session_id
    created = recorder.events(kind="session", session=sid)
    assert [e["name"] for e in created] == ["session.created"]
    await asyncio.sleep(0.25)
    assert await manager.sweep_once() == 1
    events = recorder.events(kind="session", session=sid)
    assert [e["name"] for e in events] == ["session.ended", "session.created"]
    assert events[0]["outcome"] == "ttl"
    assert events[0]["sandbox"] == session.lease.name
