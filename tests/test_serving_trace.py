"""Serving-engine deep observability (ISSUE 9): ServingMonitor lifecycle
units, the engine/batcher hook integration on a real tiny CPU model, the
`GET /v1/serving` + `/v1/serving/requests` HTTP endpoints and their gRPC
mirrors, the saturation-accounting twin of chaos scenario 12, and the
acceptance e2e — one serving request's wide event, its `/v1/traces` trace,
and its `bci_serving_ttft_seconds` exemplar all share one trace_id."""

import dataclasses
import json
import re
import time

import grpc.aio
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_tpu.api.grpc_server import (
    GrpcServer,
    observability_stubs,
)
from bee_code_interpreter_tpu.api.http_server import create_http_server
from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models.engine import Engine
from bee_code_interpreter_tpu.models.serving import (
    CapacityError,
    ContinuousBatcher,
)
from bee_code_interpreter_tpu.observability import (
    FlightRecorder,
    ServingMonitor,
    ServingProfiler,
    TraceStore,
    Tracer,
)
from bee_code_interpreter_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_tpu.utils.metrics import Registry

CFG = dataclasses.replace(
    T.TransformerConfig.tiny(), dtype=jnp.float32, n_kv_heads=2
)
PARAMS = T.init_params(CFG, jax.random.PRNGKey(0))
LONG = [int(x) for x in np.random.default_rng(7).integers(0, 200, 21)]
SHORT = [5, 3, 7, 2]


def monitored_stack(
    *,
    max_batch=2,
    n_pages=32,
    max_queue=None,
    max_steps=512,
    max_requests=256,
    **batcher_kw,
):
    """Registry + tracer-shared store + recorder + monitor over a tiny
    engine/batcher — the production wiring in miniature (the geometry
    matches test_interleaved_admission so jit programs are shared)."""
    metrics = Registry()
    store = TraceStore()
    recorder = FlightRecorder(metrics=metrics)
    monitor = ServingMonitor(
        metrics=metrics,
        store=store,
        recorder=recorder,
        max_steps=max_steps,
        max_requests=max_requests,
    )
    batcher_kw.setdefault("page_size", 4)
    batcher_kw.setdefault("max_pages_per_seq", 8)
    batcher = ContinuousBatcher(
        PARAMS, CFG, max_batch=max_batch, n_pages=n_pages,
        metrics=metrics, **batcher_kw,
    )
    engine = Engine(batcher, max_queue=max_queue, metrics=metrics)
    monitor.attach(engine)
    return engine, monitor, metrics, store, recorder


def counter_value(metrics: Registry, needle: str) -> float:
    """One sample's value out of the classic exposition text."""
    for line in metrics.expose().splitlines():
        if line.startswith(needle + " ") or (
            line.startswith(needle + "{")
        ):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


# --------------------------------------------------------------- unit level


def test_monitor_lifecycle_trace_event_and_metrics():
    """Hand-driven hook sequence: the trace lands in the shared store with
    the span tree (queued → prefill → decode), the wide event is
    kind="serving" with the SAME trace_id, and the counters/gauges see the
    request."""
    metrics = Registry()
    store = TraceStore()
    recorder = FlightRecorder(metrics=metrics)
    mon = ServingMonitor(metrics=metrics, store=store, recorder=recorder)

    mon.on_ticket_queued(1)
    time.sleep(0.02)  # a real queue wait TTFT must not hide
    mon.on_ticket_admitting(1)
    mon.on_submit(
        7, prompt_tokens=5, max_new_tokens=4, pages=2, prefix_pages=1,
        adapter=None, speculative=True, interleaved=False,
    )
    mon.on_first_token(7)
    mon.on_commit(7, accepted=2, rejected=1)
    mon.on_done(7, "length", tokens=4)

    traces = store.traces()
    assert len(traces) == 1
    trace = traces[0]
    spans = {s.name: s for s in trace.spans}
    assert {"serving.request", "queued", "prefill", "decode"} <= set(spans)
    assert all(s.duration_s is not None for s in trace.spans)
    # the queued span precedes the prefill it waited for, and the root's
    # clock starts at engine intake
    queued_span, prefill_span = spans["queued"], spans["prefill"]
    assert (
        queued_span.start_mono + queued_span.duration_s
        <= prefill_span.start_mono + 1e-9
    )
    assert trace.root.start_mono == pytest.approx(queued_span.start_mono)

    events = recorder.events(kind="serving")
    assert len(events) == 1
    event = events[0]
    assert event["trace_id"] == trace.trace_id
    assert event["outcome"] == "ok"
    assert event["serving"]["finish"] == "length"
    assert event["serving"]["output_tokens"] == 4
    assert event["serving"]["prefix_hit_pages"] == 1
    assert event["serving"]["spec_accepted"] == 2
    assert event["timings_ms"].keys() >= {"queued", "prefill", "decode"}

    rows = mon.requests()
    assert len(rows) == 1 and rows[0]["active"] is False
    # TTFT is user-perceived: it INCLUDES the queue wait (the blocking
    # admission path fixes TTFT inside submit, so this pins the backdate)
    assert rows[0]["queued_ms"] >= 20.0
    assert rows[0]["ttft_ms"] >= rows[0]["queued_ms"]
    assert rows[0]["trace_id"] == trace.trace_id
    assert mon.spec_accept_ratio() == pytest.approx(2 / 3)
    assert counter_value(metrics, 'bci_serving_requests_total{outcome="length"}') == 1
    snap = mon.snapshot()
    assert snap["totals"]["finished"] == 1
    assert snap["totals"]["spec_accepted"] == 2
    assert snap["attached"] is False  # no engine bound in this unit test


def test_monitor_reject_requeue_preempt_accounting():
    metrics = Registry()
    recorder = FlightRecorder(metrics=metrics)
    mon = ServingMonitor(metrics=metrics, recorder=recorder)

    mon.on_ticket_rejected("queue_full")
    mon.on_ticket_rejected("queue_full")
    mon.on_ticket_queued(3)
    mon.on_ticket_requeued(3)
    mon.on_submit(
        9, prompt_tokens=3, max_new_tokens=2, pages=1, prefix_pages=0,
        adapter=None, speculative=False, interleaved=True,
    )
    mon.on_preempt(9)

    snap = mon.snapshot()
    assert snap["totals"]["rejected"] == 2
    assert snap["totals"]["requeued"] == 1
    assert snap["totals"]["preempted"] == 1
    kinds = [
        (e["name"], e["outcome"]) for e in recorder.events(kind="serving")
    ]
    assert kinds.count(("serving.reject", "rejected")) == 2
    assert kinds.count(("serving.requeue", "requeued")) == 1
    assert ("serving.request", "preempted") in kinds
    assert counter_value(metrics, "bci_serving_preemptions_total") == 1
    # the preempted request is a finished record with its own outcome
    assert mon.requests(outcome="preempted")[0]["finish"] == "preempted"


def test_step_ring_bounded_and_seq_monotonic():
    mon = ServingMonitor(max_steps=4)
    for i in range(10):
        mon.on_step({"duration_ms": float(i)})
    snap = mon.snapshot()
    assert snap["steps"]["recorded"] == 10
    assert snap["steps"]["retained"] == 4
    seqs = [s["seq"] for s in snap["steps"]["last"]]
    assert seqs == [7, 8, 9, 10]
    assert all("ts" in s for s in snap["steps"]["last"])
    # the query bound trims from the retained tail
    assert len(mon.snapshot(steps=2)["steps"]["last"]) == 2
    assert mon.snapshot(steps=0)["steps"]["last"] == []


def test_request_record_ring_bounded_and_filters():
    mon = ServingMonitor(max_requests=3)
    for req in range(5):
        mon.on_submit(
            req, prompt_tokens=2, max_new_tokens=1, pages=1, prefix_pages=0,
            adapter=req % 2, speculative=False, interleaved=False,
        )
        mon.on_first_token(req)
        mon.on_done(req, "length" if req % 2 else "stop", tokens=1)
    rows = mon.requests()
    assert len(rows) == 3  # ring keeps the newest finished records
    assert [r["request_id"] for r in rows] == [4, 3, 2]
    assert [r["request_id"] for r in mon.requests(limit=1)] == [4]
    assert mon.requests(limit=0) == []  # FlightRecorder.events semantics
    assert all(r["adapter"] == 1 for r in mon.requests(adapter=1))
    assert all(r["finish"] == "length" for r in mon.requests(finish="length"))
    assert mon.requests(active=True) == []


# ------------------------------------------------- engine/batcher integration


def test_engine_run_records_requests_steps_and_kv_telemetry():
    engine, mon, metrics, store, recorder = monitored_stack()
    tickets = [engine.submit(SHORT, 4), engine.submit(LONG, 4)]
    engine.run_to_completion()
    for t in tickets:
        assert len(engine.result(t)) == 4
        engine.release(t)

    rows = mon.requests()
    assert len(rows) == 2
    for row in rows:
        assert row["active"] is False
        assert row["outcome"] == "ok" and row["finish"] == "length"
        assert row["output_tokens"] == 4
        assert row["ttft_ms"] is not None and row["ttft_ms"] > 0
        assert row["queued_ms"] is not None
        assert row["duration_ms"] >= row["ttft_ms"]
        assert store.get(row["trace_id"]) is not None

    # the wide events carry the same ids, and the store's span trees agree
    events = recorder.events(kind="serving")
    assert {e["trace_id"] for e in events} == {r["trace_id"] for r in rows}
    for event in events:
        trace = store.get(event["trace_id"])
        assert sum(event["timings_ms"].values()) == pytest.approx(
            sum(trace.stage_ms().values())
        )

    snap = mon.snapshot()
    assert snap["attached"] is True
    assert snap["totals"]["finished"] == 2
    assert snap["queue_depth"] == 0
    assert snap["batcher"]["active_rows"] == 0
    assert snap["steps"]["recorded"] > 0
    steps = snap["steps"]["last"]
    assert sum(s["decode_tokens"] for s in steps) > 0
    assert all(s["max_batch"] == 2 for s in steps)
    assert all(s["duration_ms"] > 0 for s in steps)

    kv = snap["kv_cache"]
    assert kv["pages_total"] == 31  # n_pages minus the scratch page
    # every page is free, parked (prefix-cache), or held — and with all
    # requests retired and released, none is held
    assert kv["pages_free"] + kv["pages_parked"] + kv["pages_held"] == 31
    assert kv["pages_held"] == 0
    assert 0.0 <= kv["fragmentation"] <= 1.0
    assert kv["pages_allocated_total"] >= kv["pages_released_total"]
    assert kv["prefix"]["lookups"] == kv["prefix"]["hits"] + kv["prefix"]["misses"]
    assert 0.0 <= kv["prefix"]["hit_ratio"] <= 1.0
    # integer-math churn agrees with the pool scan: allocated - released
    # is the held count
    assert (
        kv["pages_allocated_total"] - kv["pages_released_total"]
        == kv["pages_held"]
    )


def test_page_churn_counters_survive_prefix_reuse():
    """Regression: reviving a parked prefix page (ref 0 → 1) must count as
    an allocation, or every reuse cycle drifts the alloc/release counters
    negative against the pool scan (held_pages went to -2 after one
    cycle)."""
    engine, mon, *_ = monitored_stack(prefix_cache=True)
    batcher = engine.batcher
    for _ in range(2):  # second pass revives the first pass's parked pages
        ticket = engine.submit(LONG, 3)
        engine.run_to_completion()
        assert len(engine.result(ticket)) == 3
        engine.release(ticket)
    kv = batcher.kv_telemetry()
    assert kv["prefix"]["hits"] >= 1, "second pass must hit the prefix cache"
    assert kv["pages_held"] == 0
    assert (
        kv["pages_allocated_total"] - kv["pages_released_total"]
        == kv["pages_held"]
    )
    assert kv["pages_free"] + kv["pages_parked"] + kv["pages_held"] == (
        kv["pages_total"]
    )


def test_saturation_rejections_and_requeues_account_exactly():
    """Tier-1 twin of chaos scenario 12: drive the engine past queue
    capacity and through an admission capacity race; every bounce is
    accounted once in the monitor totals, the wide-event journal, and the
    bci_serving_* counters — no double counting, no losses."""
    engine, mon, metrics, store, recorder = monitored_stack(max_queue=2)

    # capacity race: queue-level admission believes pages are available
    # (over-reported prefix credit) but the batcher's own arithmetic says
    # no — the CapacityError requeues the ticket instead of failing it
    queued = [engine.submit(LONG, 3)]
    real_credit = engine.batcher.prefix_credit
    free_backup = engine.batcher.free_pages
    engine.batcher.prefix_credit = lambda prompt, adapter: 10_000
    engine.batcher.free_pages = []
    engine._admit_ready()
    engine.batcher.prefix_credit = real_credit
    engine.batcher.free_pages = free_backup

    queued.append(engine.submit(SHORT, 3))
    rejected = 0
    for _ in range(3):  # queue is full (2): every further submit bounces
        with pytest.raises(RuntimeError, match="queue full"):
            engine.submit(SHORT, 3)
        rejected += 1

    engine.run_to_completion()
    for t in queued:
        assert len(engine.result(t)) == 3

    snap = mon.snapshot()
    assert snap["totals"]["rejected"] == rejected == 3
    assert snap["totals"]["requeued"] == 1
    assert snap["totals"]["finished"] == 2
    events = recorder.events(kind="serving", limit=100)
    assert (
        len([e for e in events if e["name"] == "serving.reject"]) == rejected
    )
    assert len([e for e in events if e["name"] == "serving.requeue"]) == 1
    assert (
        len([e for e in events if e["name"] == "serving.request"]) == 2
    )
    assert counter_value(metrics, "bci_serving_queue_rejected_total") == 3
    assert counter_value(metrics, "bci_serving_requeues_total") == 1
    # a requeued ticket's record carries its bounce count
    requeued_rows = [r for r in mon.requests() if r["requeues"]]
    assert len(requeued_rows) == 1 and requeued_rows[0]["requeues"] == 1


def test_preempt_interleaved_prefill_requeues_and_stays_exact():
    # reference: the same prompt decoded with nothing else going on
    engine0, *_ = monitored_stack(max_batch=1)
    t0 = engine0.submit(LONG, 4)
    engine0.run_to_completion()
    want = engine0.result(t0)

    engine, mon, metrics, store, recorder = monitored_stack()
    decoding = engine.submit(SHORT, 8)
    ticket = engine.submit(LONG, 4, interleave_admission=4)
    engine.step()  # admits both; LONG starts its windowed prefill
    assert engine.partial_result(ticket) == []

    # a decoding ticket is NOT preemptable (cancel is the tool for those);
    # an unknown ticket is the caller's bug, same contract as cancel()
    assert engine.preempt(decoding) is False
    with pytest.raises(KeyError, match="unknown ticket"):
        engine.preempt(10_000)
    assert engine.preempt(ticket) is True
    assert engine.preempt(ticket) is False  # back in the queue now

    engine.run_to_completion()
    assert engine.result(ticket) == want  # recompute preemption is exact
    assert len(engine.result(decoding)) == 8

    assert counter_value(metrics, "bci_serving_preemptions_total") == 1
    preempted = mon.requests(outcome="preempted")
    assert len(preempted) == 1 and preempted[0]["output_tokens"] == 0
    # the re-admitted run finished ok as a NEW serving request record
    finished = mon.requests(outcome="ok")
    assert len(finished) == 2
    events = [
        e for e in recorder.events(kind="serving")
        if e["name"] == "serving.request"
    ]
    assert {e["outcome"] for e in events} == {"ok", "preempted"}


def test_speculative_commit_accounting():
    engine, mon, metrics, *_ = monitored_stack(
        draft_params=PARAMS, draft_config=CFG, gamma=2,
    )
    ticket = engine.submit(SHORT, 6)
    engine.run_to_completion()
    assert len(engine.result(ticket)) == 6

    row = mon.requests()[0]
    proposed = row["spec_accepted"] + row["spec_rejected"]
    assert proposed > 0
    assert row["speculative"] is True
    # a perfect draft (draft == target) accepts nearly everything
    assert mon.spec_accept_ratio() == pytest.approx(
        row["spec_accepted"] / proposed
    )
    accepted = counter_value(
        metrics, 'bci_serving_spec_tokens_total{result="accepted"}'
    )
    assert accepted == row["spec_accepted"]
    snap = mon.snapshot()
    assert snap["totals"]["spec_accepted"] == row["spec_accepted"]
    steps = snap["steps"]["last"]
    assert sum(s["spec_accepted"] for s in steps) == row["spec_accepted"]


# ----------------------------------------------------------- bench trajectory


def test_serving_bench_phase_fields_and_overhead_bound():
    """The bench serving phase's arithmetic (models/serving_bench.py), on
    parameters tiny enough for the tier-1 CPU lane: every BENCH-artifact
    field is present, the latency numbers come from real lifecycle records,
    and the A/B overhead bound is COMPUTED (overhead_ok mirrors
    overhead_pct vs the budget) rather than asserted true — tiny-model CPU
    steps are a far harsher overhead denominator than any real serving
    config, so tier-1 must not flake on a noisy box."""
    import time

    from bee_code_interpreter_tpu.models.serving_bench import (
        run_serving_bench,
    )

    t0 = time.monotonic()
    out = run_serving_bench(
        n_requests=3, max_new_tokens=6, repeats=2, max_batch=2, inner=1
    )
    wall = time.monotonic() - t0
    assert wall < 120.0, f"tiny serving bench took {wall:.0f}s"

    for field in (
        "tokens_per_s", "uninstrumented_tokens_per_s", "overhead_pct",
        "overhead_budget_pct", "overhead_ok", "ttft_p50_ms", "ttft_p95_ms",
        "inter_token_p50_ms", "requests", "max_new_tokens", "repeats",
        "config",
    ):
        assert field in out, field
    assert out["tokens_per_s"] > 0
    assert out["uninstrumented_tokens_per_s"] > 0
    assert out["overhead_pct"] >= 0.0
    assert out["overhead_ok"] == (
        out["overhead_pct"] < out["overhead_budget_pct"]
    )
    # three requests finished ok through the instrumented arm, so the
    # latency percentiles exist and are ordered
    assert out["ttft_p50_ms"] is not None
    assert out["ttft_p95_ms"] >= out["ttft_p50_ms"]
    assert out["inter_token_p50_ms"] is not None and (
        out["inter_token_p50_ms"] > 0
    )
    assert out["requests"] == 3


# ------------------------------------------------------------- HTTP transport


def make_serving_app(local_executor, *, attach_engine=True):
    metrics = Registry()
    store = TraceStore()
    tracer = Tracer(store=store, metrics=metrics)
    recorder = FlightRecorder(metrics=metrics)
    tracer.add_sink(recorder.record_trace)
    monitor = ServingMonitor(
        metrics=metrics, store=store, recorder=recorder
    )
    if attach_engine:
        batcher = ContinuousBatcher(
            PARAMS, CFG, max_batch=2, n_pages=32, page_size=4,
            max_pages_per_seq=8, metrics=metrics,
        )
        monitor.attach(Engine(batcher, metrics=metrics))
    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        metrics=metrics,
        tracer=tracer,
        recorder=recorder,
        serving=monitor,
        profiler=ServingProfiler(monitor),
    )
    return app, monitor, metrics, store, recorder


async def with_client(app, fn):
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        await fn(client)
    finally:
        await client.close()


async def test_http_serving_endpoints_and_trace_id_agreement(local_executor):
    """The acceptance e2e: one serving request's wide event (/v1/events),
    its /v1/traces/{id} trace, and the bci_serving_ttft_seconds exemplar on
    the OpenMetrics exposition all share one trace_id."""
    app, monitor, metrics, store, recorder = make_serving_app(local_executor)
    engine = monitor._engine
    ticket = engine.submit(SHORT, 4)
    engine.run_to_completion()
    assert len(engine.result(ticket)) == 4
    trace_id = monitor.requests()[0]["trace_id"]

    async def go(client):
        snap = await (await client.get("/v1/serving")).json()
        assert snap["attached"] is True
        assert snap["totals"]["finished"] == 1
        assert snap["batcher"]["max_batch"] == 2
        assert snap["kv_cache"]["pages_total"] == 31
        assert snap["steps"]["last"], "no step records served"
        assert (
            await (await client.get("/v1/serving", params={"steps": "0"}))
            .json()
        )["steps"]["last"] == []

        rows = (
            await (
                await client.get(
                    "/v1/serving/requests", params={"outcome": "ok"}
                )
            ).json()
        )["requests"]
        assert len(rows) == 1 and rows[0]["trace_id"] == trace_id
        assert (
            await (
                await client.get(
                    "/v1/serving/requests", params={"outcome": "error"}
                )
            ).json()
        )["requests"] == []
        assert (
            await (
                await client.get("/v1/serving/requests", params={"limit": "0"})
            ).json()
        )["requests"] == []
        for bad_params in (
            {"steps": "nope"}, {"steps": "-1"},
        ):
            assert (
                await client.get("/v1/serving", params=bad_params)
            ).status == 400
        for bad_params in (
            {"limit": "nope"}, {"limit": "-1"}, {"min_duration_ms": "x"},
        ):
            assert (
                await client.get("/v1/serving/requests", params=bad_params)
            ).status == 400

        # wide event ↔ trace ↔ exemplar: one trace_id
        events = (
            await (
                await client.get("/v1/events", params={"kind": "serving"})
            ).json()
        )["events"]
        assert len(events) == 1 and events[0]["trace_id"] == trace_id
        detail = await (await client.get(f"/v1/traces/{trace_id}")).json()
        assert detail["trace_id"] == trace_id
        assert {"queued", "prefill", "decode"} <= set(detail["stage_ms"])

        exposition = await (
            await client.get(
                "/metrics",
                headers={"Accept": "application/openmetrics-text"},
            )
        ).text()
        pattern = re.compile(
            r'^bci_serving_ttft_seconds_bucket{[^}]*} \d+ '
            r'# {trace_id="([0-9a-f]{32})"',
            re.M,
        )
        exemplar_ids = set(pattern.findall(exposition))
        assert exemplar_ids == {trace_id}

        # the one-call incident bundle carries the serving section
        bundle = await (await client.get("/v1/debug/bundle")).json()
        assert bundle["serving"]["attached"] is True
        assert bundle["serving"]["totals"]["finished"] == 1

    await with_client(app, go)


async def test_http_profile_target_serving_captures_real_steps(
    local_executor, tmp_path
):
    """POST /v1/profile target=serving steps real batcher steps through the
    attached engine (501 only when nothing is attached — the other test)."""
    app, monitor, metrics, store, recorder = make_serving_app(local_executor)
    engine = monitor._engine
    # queue work so the profiled steps actually run the model
    tickets = [engine.submit(SHORT, 6), engine.submit(LONG, 6)]

    async def go(client):
        resp = await client.post(
            "/v1/profile", json={"target": "serving", "steps": 3}
        )
        body = await resp.json()
        assert resp.status == 200, body
        assert body["steps"] == 3 and body["duration_ms"] > 0
        assert body["files"], "no profiler artifacts captured"

    await with_client(app, go)
    engine.run_to_completion()
    for t in tickets:
        assert len(engine.result(t)) == 6


async def test_http_serving_unwired_and_unattached(local_executor):
    # no monitor at all: both endpoints answer 501
    bare = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        metrics=Registry(),
    )

    async def bare_go(client):
        assert (await client.get("/v1/serving")).status == 501
        assert (await client.get("/v1/serving/requests")).status == 501

    await with_client(bare, bare_go)

    # monitor wired but no engine attached: the snapshot answers honestly
    # and target=serving profiling is 501 (nothing can step)
    app, monitor, *_ = make_serving_app(local_executor, attach_engine=False)

    async def go(client):
        snap = await (await client.get("/v1/serving")).json()
        assert snap["attached"] is False
        assert "batcher" not in snap
        resp = await client.post(
            "/v1/profile", json={"target": "serving", "steps": 2}
        )
        assert resp.status == 501

    await with_client(app, go)


# ------------------------------------------------------------- gRPC transport


async def test_grpc_serving_snapshot_and_requests(local_executor):
    metrics = Registry()
    store = TraceStore()
    tracer = Tracer(store=store, metrics=metrics)
    recorder = FlightRecorder(metrics=metrics)
    tracer.add_sink(recorder.record_trace)
    monitor = ServingMonitor(metrics=metrics, store=store, recorder=recorder)
    batcher = ContinuousBatcher(
        PARAMS, CFG, max_batch=2, n_pages=32, page_size=4,
        max_pages_per_seq=8, metrics=metrics,
    )
    monitor.attach(Engine(batcher, metrics=metrics))
    engine = monitor._engine
    ticket = engine.submit(SHORT, 3)
    engine.run_to_completion()
    assert len(engine.result(ticket)) == 3

    server = GrpcServer(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        metrics=metrics,
        tracer=tracer,
        recorder=recorder,
        serving=monitor,
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            obs = observability_stubs(channel)
            snap = json.loads(await obs["GetServing"](b""))
            assert snap["attached"] is True
            assert snap["totals"]["finished"] == 1
            assert snap["kv_cache"]["pages_total"] == 31
            trimmed = json.loads(await obs["GetServing"](b'{"steps": 0}'))
            assert trimmed["steps"]["last"] == []

            rows = json.loads(
                await obs["GetServingRequests"](b'{"outcome": "ok"}')
            )["requests"]
            assert len(rows) == 1 and rows[0]["output_tokens"] == 3
            none = json.loads(
                await obs["GetServingRequests"](b'{"finish": "stop"}')
            )["requests"]
            assert none == []
            # the HTTP edge's ?active=1/0 string forms mean the same thing
            # here (bool("0") would invert them): "0" selects FINISHED rows
            done_rows = json.loads(
                await obs["GetServingRequests"](b'{"active": "0"}')
            )["requests"]
            assert len(done_rows) == 1 and done_rows[0]["active"] is False
            assert json.loads(
                await obs["GetServingRequests"](b'{"active": true}')
            )["requests"] == []

            for method, payload in (
                ("GetServing", b"not json"),
                ("GetServing", b'{"steps": -1}'),
                ("GetServingRequests", b'{"limit": "x"}'),
                ("GetServingRequests", b'{"limit": -5}'),
            ):
                with pytest.raises(grpc.aio.AioRpcError) as excinfo:
                    await obs[method](payload)
                assert (
                    excinfo.value.code() == grpc.StatusCode.INVALID_ARGUMENT
                )
    finally:
        await server.stop(None)


async def test_grpc_serving_unimplemented_without_monitor(local_executor):
    server = GrpcServer(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        metrics=Registry(),
    )
    port = await server.start("127.0.0.1:0")
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            obs = observability_stubs(channel)
            for method in ("GetServing", "GetServingRequests"):
                with pytest.raises(grpc.aio.AioRpcError) as excinfo:
                    await obs[method](b"")
                assert (
                    excinfo.value.code() == grpc.StatusCode.UNIMPLEMENTED
                )
    finally:
        await server.stop(None)
