"""Tracing subsystem: span context propagation, traceparent wire format,
trace retention, and log correlation (request_id/trace_id must survive await
boundaries and never cross-contaminate between interleaved requests)."""

import asyncio
import json
import logging

import pytest

from bee_code_interpreter_tpu.observability import (
    JsonLogFormatter,
    Tracer,
    TraceStore,
    current_ids,
    current_trace,
    format_traceparent,
    outbound_headers,
    parse_traceparent,
    span,
)
from bee_code_interpreter_tpu.utils.request_id import (
    RequestIdLoggingFilter,
    new_request_id,
    request_id_context_var,
)

# ------------------------------------------------------------- wire format


def test_traceparent_roundtrip():
    header = format_traceparent("ab" * 16, "cd" * 8)
    assert header == f"00-{'ab' * 16}-{'cd' * 8}-01"
    assert parse_traceparent(header) == ("ab" * 16, "cd" * 8)


@pytest.mark.parametrize(
    "bad",
    [
        None,
        "",
        "garbage",
        "00-short-cdcdcdcdcdcdcdcd-01",
        f"00-{'zz' * 16}-{'cd' * 8}-01",  # non-hex
        f"00-{'00' * 16}-{'cd' * 8}-01",  # all-zero trace id
        f"00-{'ab' * 16}-{'00' * 8}-01",  # all-zero span id
        f"ff-{'ab' * 16}-{'cd' * 8}-01",  # forbidden version
    ],
)
def test_traceparent_malformed_degrades_to_none(bad):
    assert parse_traceparent(bad) is None


# ----------------------------------------------------------------- spans


def test_span_is_noop_without_active_trace():
    with span("upload") as s:
        assert s is None
    assert current_trace() is None
    assert current_ids() == ("-", "-")


def test_trace_nests_spans_and_lands_in_store():
    tracer = Tracer()
    with tracer.trace("/v1/execute", request_id="req-1") as t:
        with span("spawn"):
            pass
        with span("execute") as s:
            assert s.parent_id == t.root.span_id
        # two spans of the same name sum in the stage breakdown
        with span("upload"):
            pass
        with span("upload"):
            pass
    stored = tracer.store.get(t.trace_id)
    assert stored is t
    assert {s.name for s in stored.spans} == {
        "/v1/execute", "spawn", "execute", "upload",
    }
    assert len(stored.spans) == 5
    stages = stored.stage_ms()
    assert set(stages) == {"spawn", "execute", "upload"}
    assert stored.root.duration_s is not None
    assert stored.summary()["request_id"] == "req-1"


def test_trace_continues_inbound_context():
    tracer = Tracer()
    with tracer.trace(
        "executor:/execute", trace_id="ab" * 16, parent_span_id="cd" * 8
    ) as t:
        assert t.trace_id == "ab" * 16
        assert t.root.parent_id == "cd" * 8


def test_error_span_marked_and_trace_retained():
    tracer = Tracer()
    with pytest.raises(RuntimeError):
        with tracer.trace("/v1/execute") as t:
            with span("execute"):
                raise RuntimeError("boom")
    stored = tracer.store.get(t.trace_id)
    assert stored.root.status == "error"
    execute = next(s for s in stored.spans if s.name == "execute")
    assert execute.status == "error"
    assert "boom" in execute.attributes["error"]


def test_outbound_headers_carry_trace_and_request_id():
    tracer = Tracer()
    rid = new_request_id()
    with tracer.trace("/v1/execute", request_id=rid) as t:
        with span("execute") as s:
            headers = outbound_headers()
    assert headers["X-Request-Id"] == rid
    assert parse_traceparent(headers["traceparent"]) == (t.trace_id, s.span_id)


def test_outbound_headers_request_id_only_without_trace():
    rid = new_request_id()
    headers = outbound_headers()
    assert headers == {"X-Request-Id": rid}
    request_id_context_var.set("-")
    assert outbound_headers() == {}


def test_stage_spans_feed_metrics_histogram():
    from bee_code_interpreter_tpu.utils.metrics import Registry

    reg = Registry()
    tracer = Tracer(metrics=reg)
    with tracer.trace("/v1/execute"):
        with span("spawn"):
            pass
        with span("execute"):
            pass
    text = reg.expose()
    assert 'bci_stage_seconds_count{stage="spawn"} 1' in text
    assert 'bci_stage_seconds_count{stage="execute"} 1' in text
    # the root span is the request, not a stage
    assert 'stage="/v1/execute"' not in text


# ----------------------------------------------------------------- store


def test_store_bounded_and_reserves_slowest():
    store = TraceStore(max_traces=8, slowest_keep=2)
    builder = Tracer()  # traces built detached, added with pinned durations
    slow_ids = []
    for i in range(40):
        with builder.trace(f"r{i}") as t:
            pass
        if i in (3, 5):  # make two early traces the slowest ever seen
            t.root.duration_s = 10.0 + i
            slow_ids.append(t.trace_id)
        else:
            t.root.duration_s = 0.001
        store.add(t)
    retained = {t.trace_id for t in store.traces()}
    assert len(retained) <= 8
    # the slowest requests survive 30+ subsequent evictions
    for trace_id in slow_ids:
        assert trace_id in retained
        assert store.get(trace_id) is not None
    assert store.get("not-a-trace") is None


def test_store_add_after_duration_mutation_ordering():
    # slowest ranking is computed at add() time from the trace duration
    store = TraceStore(max_traces=4, slowest_keep=1)
    t_slow = Tracer()  # build traces detached, add manually
    with t_slow.trace("slow") as slow:
        pass
    slow.root.duration_s = 99.0
    store.add(slow)
    for i in range(10):
        with t_slow.trace(f"fast{i}") as fast:
            pass
        store.add(fast)
    assert store.get(slow.trace_id) is not None


# ------------------------------------------------- async context isolation


async def test_ids_survive_await_boundaries():
    tracer = Tracer()
    rid = new_request_id()
    with tracer.trace("/v1/execute", request_id=rid) as t:
        with span("execute"):
            before = (request_id_context_var.get(), *current_ids())
            await asyncio.sleep(0.01)
            after = (request_id_context_var.get(), *current_ids())
    assert before == after
    assert before[0] == rid
    assert before[1] == t.trace_id


async def test_concurrent_requests_do_not_cross_contaminate():
    """Two in-flight 'requests' interleaving on one event loop: each task's
    ambient ids must stay its own across every await."""
    tracer = Tracer()
    observed: dict[str, set] = {"a": set(), "b": set()}

    async def request(name: str):
        rid = new_request_id()
        with tracer.trace(f"/v1/{name}", request_id=rid) as t:
            for _ in range(5):
                with span("execute"):
                    await asyncio.sleep(0)
                    observed[name].add(
                        (request_id_context_var.get(), current_ids()[0])
                    )
        return rid, t.trace_id

    (rid_a, tid_a), (rid_b, tid_b) = await asyncio.gather(
        request("a"), request("b")
    )
    assert rid_a != rid_b and tid_a != tid_b
    assert observed["a"] == {(rid_a, tid_a)}
    assert observed["b"] == {(rid_b, tid_b)}


async def test_gather_fanout_children_share_parent_trace():
    # asyncio.gather children copy the context: spans started inside each
    # child attach to the same trace without explicit plumbing (the SPMD
    # upload/execute fan-out in the kubernetes executor relies on this)
    tracer = Tracer()
    with tracer.trace("/v1/execute") as t:

        async def upload(i):
            with span("upload", worker=str(i)):
                await asyncio.sleep(0.001)

        await asyncio.gather(*(upload(i) for i in range(3)))
    assert sum(1 for s in t.spans if s.name == "upload") == 3
    assert all(
        s.trace_id == t.trace_id for s in t.spans
    )


# --------------------------------------------------------- log correlation


def _make_record(logger_name="test", exc=None):
    try:
        if exc is not None:
            raise exc
        record = logging.LogRecord(
            logger_name, logging.INFO, __file__, 1, "hello %s", ("world",),
            None,
        )
    except Exception:
        import sys

        record = logging.LogRecord(
            logger_name, logging.ERROR, __file__, 1, "kaboom", (),
            sys.exc_info(),
        )
    RequestIdLoggingFilter().filter(record)
    return record


def test_filter_attaches_all_three_ids():
    tracer = Tracer()
    rid = new_request_id()
    with tracer.trace("/v1/execute", request_id=rid) as t:
        with span("execute") as s:
            record = _make_record()
    assert record.request_id == rid
    assert record.trace_id == t.trace_id
    assert record.span_id == s.span_id


def test_json_formatter_emits_one_line_valid_json():
    tracer = Tracer()
    rid = new_request_id()
    with tracer.trace("/v1/execute", request_id=rid) as t:
        record = _make_record()
    line = JsonLogFormatter().format(record)
    assert "\n" not in line
    payload = json.loads(line)
    assert payload["message"] == "hello world"
    assert payload["request_id"] == rid
    assert payload["trace_id"] == t.trace_id
    assert payload["level"] == "INFO"


def test_json_formatter_one_line_under_exception_logging():
    record = _make_record(exc=ValueError("structured logs must not shear"))
    line = JsonLogFormatter().format(record)
    assert "\n" not in line  # stack trace folded into the one JSON line
    payload = json.loads(line)
    assert payload["level"] == "ERROR"
    assert "ValueError" in payload["exc_info"]
    assert "Traceback" in payload["exc_info"]


def test_json_formatter_outside_any_request():
    line = JsonLogFormatter().format(
        logging.LogRecord("boot", logging.INFO, __file__, 1, "starting", (), None)
    )
    payload = json.loads(line)
    # no filter ran, no request active: ids degrade to "-" not a crash
    assert payload["request_id"] == "-"
    assert payload["trace_id"] == "-"
