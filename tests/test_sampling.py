"""Sampled decoding: temperature / top-k / top-p on the cached generator."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models.transformer import sample_logits


def rand_logits(B=4, V=32, key=0):
    return jax.random.normal(jax.random.PRNGKey(key), (B, V)) * 3.0


def test_temperature_zero_is_argmax():
    logits = rand_logits()
    out = sample_logits(logits, jax.random.PRNGKey(1), temperature=0.0)
    np.testing.assert_array_equal(
        np.asarray(out[:, 0]), np.asarray(jnp.argmax(logits, -1))
    )


def test_top_k_support_containment():
    # Every sampled token must be among the k largest logits.
    logits = rand_logits(B=8, V=64, key=2)
    topk = np.asarray(jnp.argsort(-logits, axis=-1)[:, :5])
    for i in range(20):
        out = np.asarray(
            sample_logits(
                logits, jax.random.PRNGKey(i), temperature=1.0, top_k=5
            )
        )
        for b in range(8):
            assert out[b, 0] in topk[b], (b, out[b, 0])


def test_top_k_one_is_greedy_at_any_temperature():
    logits = rand_logits(key=3)
    out = sample_logits(logits, jax.random.PRNGKey(9), temperature=5.0, top_k=1)
    np.testing.assert_array_equal(
        np.asarray(out[:, 0]), np.asarray(jnp.argmax(logits, -1))
    )


def test_top_p_nucleus_containment():
    # Sampled tokens must lie in the smallest prefix (by descending prob)
    # whose mass reaches p — and the top token is always eligible.
    logits = rand_logits(B=8, V=64, key=4)
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    order = np.argsort(-probs, axis=-1)
    for i in range(20):
        out = np.asarray(
            sample_logits(
                logits, jax.random.PRNGKey(100 + i), temperature=1.0, top_p=0.5
            )
        )
        for b in range(8):
            sorted_p = probs[b][order[b]]
            keep_n = int(np.searchsorted(np.cumsum(sorted_p), 0.5) + 1)
            nucleus = set(order[b][:keep_n].tolist())
            assert out[b, 0] in nucleus, (b, out[b, 0], keep_n)


def test_sharp_distribution_top_p_forces_top_token():
    logits = jnp.array([[10.0, 0.0, -1.0, -2.0]])
    for i in range(10):
        out = sample_logits(
            logits, jax.random.PRNGKey(i), temperature=1.0, top_p=0.9
        )
        assert int(out[0, 0]) == 0


def test_generate_cached_sampling_deterministic_and_default_greedy():
    config = dataclasses.replace(T.TransformerConfig.tiny(), dtype=jnp.float32)
    model = T.Transformer(config)
    params = model.init(jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, config.vocab_size)

    greedy = model.generate_cached(params, prompt, max_new_tokens=5)
    uncached = model.generate(params, prompt, max_new_tokens=5)
    assert (greedy == uncached).all()  # default stays pinned to greedy

    k = jax.random.PRNGKey(7)
    a = model.generate_cached(
        params, prompt, max_new_tokens=5, temperature=1.0, top_k=8, key=k
    )
    b = model.generate_cached(
        params, prompt, max_new_tokens=5, temperature=1.0, top_k=8, key=k
    )
    assert (a == b).all()  # fixed key → fully deterministic
    assert a.shape == greedy.shape
    # prompt region untouched
    np.testing.assert_array_equal(np.asarray(a[:, :5]), np.asarray(prompt))


def test_top_p_degenerate_keeps_top_token():
    # top_p=0.0 must still sample the top token, never an all-masked vocab
    # collapsing to token id 0.
    logits = jnp.array([[0.0, 5.0, 1.0]])  # top token is id 1, not 0
    out = sample_logits(
        logits, jax.random.PRNGKey(0), temperature=1.0, top_p=0.0
    )
    assert int(out[0, 0]) == 1


def test_top_k_zero_rejected():
    import pytest

    with pytest.raises(ValueError, match="top_k must be >= 1"):
        sample_logits(
            rand_logits(), jax.random.PRNGKey(0), temperature=1.0, top_k=0
        )


def test_top_k_zero_rejected_even_greedy():
    import pytest

    with pytest.raises(ValueError, match="top_k must be >= 1"):
        sample_logits(
            rand_logits(), jax.random.PRNGKey(0), temperature=0.0, top_k=0
        )
