"""Tensor-parallel serving: the continuous batcher over a tp mesh.

``ContinuousBatcher(mesh=...)`` shards params under the Megatron specs and
the K/V page pool's head axis over ``tp``; GSPMD compiles the same decode/
prefill/window programs with the tp collectives inserted. The host
scheduling loop is untouched, so every serving feature rides along — these
tests pin the ones with distinct device-side layouts (bf16/f32 pool, int8
pool + scale planes, speculative draft+verify, prefix-cache suffix
admission) against the UNSHARDED solo decode, token-for-token, on the
virtual device mesh (tests/conftest.py)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from bee_code_interpreter_tpu.models import transformer as T
from bee_code_interpreter_tpu.models.serving import ContinuousBatcher

PROMPT = [5, 3, 7, 2, 9, 4, 1, 8]


def cfg(**kw):
    return dataclasses.replace(
        T.TransformerConfig.tiny(), dtype=jnp.float32, n_kv_heads=2, **kw
    )


def tp_mesh(n=2):
    return Mesh(np.array(jax.devices()[:n]), ("tp",))


def solo(params, config, prompt, n):
    out = T.Transformer(config).generate_cached(
        params, jnp.asarray(prompt)[None, :], max_new_tokens=n
    )
    return np.asarray(out[0, len(prompt):]).tolist()


def test_tp_batcher_matches_unsharded_solo_decode():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    want1 = solo(params, config, PROMPT, 6)
    want2 = solo(params, config, [1, 2, 3], 6)
    b = ContinuousBatcher(
        params, config, max_batch=2, n_pages=16, page_size=4,
        max_pages_per_seq=4, mesh=tp_mesh(),
    )
    r1 = b.submit(PROMPT, 6)
    r2 = b.submit([1, 2, 3], 6)
    b.run_to_completion()
    assert b.result(r1) == want1
    assert b.result(r2) == want2
    # params and pool really are distributed (not replicated onto one chip)
    wq = b.params["layers"]["wq"]
    assert len(wq.sharding.device_set) == 2
    assert len(b.cache["k"].sharding.device_set) == 2


def test_tp_int8_pool_matches_unsharded_solo():
    config = cfg(kv_cache_dtype="int8")
    params = T.init_params(config, jax.random.PRNGKey(0))
    want = solo(params, config, PROMPT, 5)
    b = ContinuousBatcher(
        params, config, max_batch=2, n_pages=16, page_size=4,
        max_pages_per_seq=4, mesh=tp_mesh(),
    )
    r = b.submit(PROMPT, 5)
    b.run_to_completion()
    assert b.result(r) == want
    assert len(b.cache["k_s"].sharding.device_set) == 2  # scale planes too


def test_tp_speculative_matches_unsharded_solo():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    draft_config = cfg(n_layers=1)
    draft_params = T.init_params(draft_config, jax.random.PRNGKey(1))
    want = solo(params, config, PROMPT, 6)
    b = ContinuousBatcher(
        params, config, max_batch=2, n_pages=32, page_size=4,
        max_pages_per_seq=6, mesh=tp_mesh(),
        draft_params=draft_params, draft_config=draft_config, gamma=3,
    )
    r = b.submit(PROMPT, 6)
    b.run_to_completion()
    assert b.result(r) == want


def test_tp_prefix_cache_matches_unsharded_solo():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    p1 = PROMPT + [1, 2]
    p2 = PROMPT + [3]
    want1 = solo(params, config, p1, 4)
    want2 = solo(params, config, p2, 4)
    b = ContinuousBatcher(
        params, config, max_batch=2, n_pages=32, page_size=4,
        max_pages_per_seq=8, mesh=tp_mesh(), prefix_cache=True,
    )
    r1 = b.submit(p1, 4)
    b.run_to_completion()
    r2 = b.submit(p2, 4)  # admits through the suffix window on shared pages
    b.run_to_completion()
    assert b.prefix_stats["hits"] >= 1
    assert b.result(r1) == want1
    assert b.result(r2) == want2


def test_tp_requires_divisible_kv_heads():
    config = dataclasses.replace(
        T.TransformerConfig.tiny(), dtype=jnp.float32, n_kv_heads=1
    )  # 1 % 2 != 0
    params = T.init_params(config, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="kv_heads"):
        ContinuousBatcher(
            params, config, max_batch=2, n_pages=16, page_size=4,
            max_pages_per_seq=4, mesh=tp_mesh(),
        )


def test_snapshot_restores_across_topologies():
    """Preemption recovery composes with resharding: a snapshot taken on a
    single-device batcher resumes on a tp=2 batcher (the pool is resharded
    on load) — the serving analogue of utils/checkpoint.py's
    cross-topology restore."""
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    want = solo(params, config, PROMPT, 6)

    a = ContinuousBatcher(
        params, config, max_batch=2, n_pages=16, page_size=4,
        max_pages_per_seq=4,
    )
    r = a.submit(PROMPT, 6)
    for _ in range(2):
        a.step()
    snap = a.state_dict()

    b = ContinuousBatcher(
        params, config, max_batch=2, n_pages=16, page_size=4,
        max_pages_per_seq=4, mesh=tp_mesh(),
    )
    b.load_state_dict(snap)
    b.run_to_completion()
    assert b.result(r) == want
    assert len(b.cache["k"].sharding.device_set) == 2  # resharded on load


def test_sp_ring_admission_matches_unsharded_solo():
    """Long-context admission: with an sp axis in the mesh, the one-shot
    prefill rings the attention across devices (forward's sequence
    parallelism) and the K/V reshards into the page pool — outputs must
    still equal unsharded solo decode."""
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    long_prompt = [int(x) for x in
                   np.random.default_rng(0).integers(0, 200, 21)]
    want = solo(params, config, long_prompt, 5)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("sp", "tp"))
    b = ContinuousBatcher(
        params, config, max_batch=2, n_pages=32, page_size=4,
        max_pages_per_seq=8, mesh=mesh,
    )
    r = b.submit(long_prompt, 5)
    b.run_to_completion()
    assert b.result(r) == want


def test_sp_requires_divisible_page_size():
    config = cfg()
    params = T.init_params(config, jax.random.PRNGKey(0))
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1), ("sp", "tp"))
    with pytest.raises(ValueError, match="page_size"):
        ContinuousBatcher(
            params, config, max_batch=2, n_pages=16, page_size=3,
            max_pages_per_seq=4, mesh=mesh,
        )


def test_ulysses_sp_admission_validated_and_matches_solo():
    """sp admission under Ulysses: head divisibility refuses at
    construction (not at the first submit's trace), and a valid config
    still matches unsharded solo decode."""
    bad = cfg(sp_attention="ulysses")  # kv_heads=2, sp=4 below: refuses
    params_bad = T.init_params(bad, jax.random.PRNGKey(0))
    mesh4 = Mesh(np.array(jax.devices()[:4]).reshape(4, 1), ("sp", "tp"))
    with pytest.raises(ValueError, match="ulysses"):
        ContinuousBatcher(
            params_bad, bad, max_batch=2, n_pages=32, page_size=4,
            max_pages_per_seq=8, mesh=mesh4,
        )
    good = cfg(sp_attention="ulysses")  # sp=2 divides both head counts
    params = T.init_params(good, jax.random.PRNGKey(0))
    long_prompt = [int(x) for x in
                   np.random.default_rng(3).integers(0, 200, 13)]
    want = solo(params, good, long_prompt, 4)
    mesh2 = Mesh(np.array(jax.devices()[:2]).reshape(2, 1), ("sp", "tp"))
    b = ContinuousBatcher(
        params, good, max_batch=2, n_pages=32, page_size=4,
        max_pages_per_seq=8, mesh=mesh2,
    )
    r = b.submit(long_prompt, 4)
    b.run_to_completion()
    assert b.result(r) == want
