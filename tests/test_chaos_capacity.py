"""Chaos scenario 18 (ISSUE 18, docs/capacity.md): flash crowd + replica
kill + abusive tenant, SIMULTANEOUSLY, through 3 replicas behind 2 peered
router edges — driven by the real open-loop generator, judged by the real
federated capacity surface.

What must hold, all at once:

- the SLO *page* (fast-burn, user-perceived, both edges) stays silent
  through the whole storm;
- every abuser shed is accounted: client-observed 429s ≡ the replicas'
  demand ledgers ≡ the federated capacity report's shed ledger (minus
  exactly the killed replica, which the report NAMES as failed);
- ``GET /v1/autoscale`` on a router edge recommends MORE replicas while
  the crowd burns and converges back to the floor after it passes;
- the converged recommendation (< live replicas) is ACTUATED through the
  PR 11 drain/lease-handoff machinery, with zero lease-scoped 5xx — the
  first scale-in this repo has ever exercised under load."""

import asyncio
import time

import httpx
import pytest
from aiohttp import web

from bee_code_interpreter_tpu.fleet import FleetRouter, create_router_app
from bee_code_interpreter_tpu.loadgen import (
    FlashCrowd,
    OpenLoopGenerator,
    Steady,
    TrafficMix,
)
from bee_code_interpreter_tpu.tenancy import (
    TENANT_HEADER,
    TenantRegistry,
    parse_tenants,
)
from tests.fakes import ReplicaStack, free_port

pytestmark = pytest.mark.chaos

SPEC = "abuser:weight=1:rps=2:burst=2,victim:weight=4"


async def test_chaos18_flash_crowd_replica_kill_abusive_tenant(tmp_path):
    shared_root = tmp_path / "shared-objects"
    port_a, port_b = free_port(), free_port()
    url_a = f"http://127.0.0.1:{port_a}"
    url_b = f"http://127.0.0.1:{port_b}"
    # Short demand windows so the recommendation can converge back within
    # test-scale seconds (the production default is 120s).
    stacks = [
        await ReplicaStack(
            f"r{i}",
            tmp_path,
            shared_root,
            tenants=SPEC,
            autoscale_window_s=4.0,
        ).start()
        for i in range(3)
    ]

    def make_router(rid, peer_name, peer_url):
        return FleetRouter(
            [(s.name, s.base_url) for s in stacks],
            refresh_interval_s=0.2,
            dead_after_s=1.0,
            tenancy=TenantRegistry(parse_tenants(SPEC)),
            peers=[(peer_name, peer_url)],
            router_id=rid,
        )

    router_a = make_router("A", "b", url_b)
    router_b = make_router("B", "a", url_a)
    runners = []
    for router, port in ((router_a, port_a), (router_b, port_b)):
        runner = web.AppRunner(create_router_app(router))
        await runner.setup()
        await web.TCPSite(runner, "127.0.0.1", port).start()
        await router.refresh_once()
        router.start()
        runners.append(runner)
    client = httpx.AsyncClient(timeout=30.0)
    session_statuses: list[int] = []
    try:
        # --- quiet fleet: the federated document already recommends the
        # floor, and knows its own size
        body = (await client.get(f"{url_a}/v1/autoscale")).json()
        assert body["replica_states"]["healthy"] == 3
        assert body["recommendation"]["target_replicas"] == 1
        assert body["recommendation"]["reason"] == "idle"
        assert body["replicas_reporting"] == ["r0", "r1", "r2"]

        # --- one live session through edge A, state written
        response = await client.post(f"{url_a}/v1/sessions", json={})
        assert response.status_code == 200, response.text
        session_id = response.json()["session_id"]
        response = await client.post(
            f"{url_a}/v1/sessions/{session_id}/execute",
            json={"source_code": "open('state.txt', 'w').write('eighteen')"},
        )
        assert response.status_code == 200, response.text

        async def session_turn() -> None:
            resp = await client.post(
                f"{url_a}/v1/sessions/{session_id}/execute",
                json={"source_code": "print(open('state.txt').read())"},
            )
            session_statuses.append(resp.status_code)

        # --- the storm: a 10x flash crowd open-loop through BOTH edges,
        # an abuser flood through edge B, a session trickle, and a hard
        # replica kill in the middle of it all
        crowd_shape = FlashCrowd(
            base_rps=3.0,
            duration_s=5.0,
            crowd_start_s=1.0,
            crowd_s=2.0,
            multiplier=10.0,
        )
        crowd_mix = TrafficMix(
            kinds=(("execute", 9.0), ("stream", 1.0)), seed=18
        )
        crowd_a = OpenLoopGenerator(client, url_a, mix=crowd_mix)
        crowd_b = OpenLoopGenerator(client, url_b, mix=crowd_mix)
        abuse_gen = OpenLoopGenerator(
            client,
            url_b,
            mix=TrafficMix(
                kinds=(("execute", 1.0),),
                tenants=[("abuser", 1.0)],
                seed=18,
            ),
        )

        async def storm_side_effects() -> None:
            # Mid-crowd (t≈2s): hard-kill a replica that does NOT hold the
            # session pin — the router must absorb it invisibly.
            await asyncio.sleep(2.0)
            pin = router_a.sessions[session_id].replica
            victim = next(s for s in stacks if s.name != pin)
            await victim.stop(hard=True)
            storm_side_effects.killed = victim.name
            await session_turn()
            # Scrape the federated recommendation WHILE the crowd burns
            # (the demand windows are seconds-short by design; a scrape
            # deferred to after the generators drain can see the peak
            # already decayed on a slow box).
            await asyncio.sleep(1.5)  # past dead_after_s: the view ages
            storm_side_effects.mid_storm = (
                await client.get(f"{url_a}/v1/autoscale")
            ).json()

        crowd_task_a = asyncio.create_task(
            crowd_a.run(crowd_shape, label="crowd-a", seed=1)
        )
        crowd_task_b = asyncio.create_task(
            crowd_b.run(crowd_shape, label="crowd-b", seed=2)
        )
        abuse_task = asyncio.create_task(
            abuse_gen.run(Steady(rps=18.0, duration_s=2.0), label="abuse")
        )
        kill_task = asyncio.create_task(storm_side_effects())
        await session_turn()
        result_a, result_b, abuse, _ = await asyncio.gather(
            crowd_task_a, crowd_task_b, abuse_task, kill_task
        )
        killed = storm_side_effects.killed

        # --- crowd verdict: open-loop offered everything on schedule; the
        # kill cost retries, not user-visible failures (the error allowance
        # absorbs CPU-starved in-flight casualties of the kill itself)
        for result in (result_a, result_b):
            assert result.sent == result.offered
            assert result.errors <= max(2, result.sent // 25), (
                result.to_dict()
            )
        assert result_a.lag_quantile_s(0.95) < 1.0

        # --- recommendation DURING the storm: the federated edge wants a
        # bigger fleet than it has left
        body = storm_side_effects.mid_storm
        rec = body["recommendation"]
        assert killed in body["replicas_failed"]
        healthy_now = body["replica_states"]["healthy"]
        assert healthy_now == 2
        assert rec["current_replicas"] == healthy_now
        assert rec["target_replicas"] > healthy_now, rec
        assert rec["reason"] == "forecast"

        # --- SLO page silent at BOTH edges, and fleet-wide
        for edge_url in (url_a, url_b):
            slo = (await client.get(f"{edge_url}/v1/slo")).json()
            assert slo["fast_burn_alerting"] is False
            assert slo["fleet_fast_burn"] is False

        # --- every abuser shed accounted, exactly once, fleet-wide:
        # client-observed 429s == the demand ledgers (the killed replica's
        # in-process ledger included), and the federated capacity report
        # carries the surviving share while NAMING the gap
        client_429 = abuse.shed_ledger().get("abuser", 0)
        assert client_429 > 0
        ledger_total = sum(
            s.demand.sheds_by_tenant.get("abuser", 0) for s in stacks
        )
        assert client_429 == ledger_total
        surviving = sum(
            s.demand.sheds_by_tenant.get("abuser", 0)
            for s in stacks
            if s.name != killed
        )
        # Fresh post-storm scrape: the per-tenant shed counters are
        # CUMULATIVE, so this accounting does not race the window decay.
        body = (await client.get(f"{url_a}/v1/autoscale")).json()
        assert killed in body["replicas_failed"]
        reported = (
            body["demand"]["by_tenant"].get("abuser", {}).get("sheds", 0)
        )
        assert reported == surviving
        # The abuser never touched the victim's session lane: zero
        # lease-scoped 5xx (a 429 under the crowd is the admission gate
        # doing its job on a saturated replica — the lease survives it).
        assert all(status < 500 for status in session_statuses), (
            session_statuses
        )

        # --- the crowd passes: the recommendation converges back to the
        # floor once the demand windows drain
        deadline = time.monotonic() + 15.0
        rec = None
        while time.monotonic() < deadline:
            body = (await client.get(f"{url_a}/v1/autoscale")).json()
            rec = body["recommendation"]
            if rec["target_replicas"] == 1:
                break
            await asyncio.sleep(0.3)
        assert rec is not None and rec["target_replicas"] == 1, rec
        # "idle" once every window drained; "forecast" while a trickle of
        # residual demand still needs (exactly) the floor — converged
        # either way.
        assert rec["reason"] in ("idle", "forecast"), rec

        # --- ACTUATE the scale-in the document asks for (target 1 < 2
        # healthy), through drain/lease-handoff: drain the replica holding
        # the session pin — its lease must hand off with zero 5xx
        assert rec["target_replicas"] < body["replica_states"]["healthy"]
        pin = router_a.sessions[session_id].replica
        response = await client.post(
            f"{url_a}/v1/fleet/replicas/{pin}/drain"
        )
        assert response.status_code == 200, response.text
        tally = response.json()
        assert tally["migrated"] == 1 and tally["failed"] == 0
        assert router_a.sessions[session_id].replica != pin
        await session_turn()
        # The drained replica retires; the fleet is now the recommended
        # size and the session (same public id, state intact) still serves.
        drained = next(s for s in stacks if s.name == pin)
        await drained.stop()
        await asyncio.sleep(1.2)  # let refresh age it past dead_after_s
        response = await client.post(
            f"{url_a}/v1/sessions/{session_id}/execute",
            json={"source_code": "print(open('state.txt').read())"},
        )
        session_statuses.append(response.status_code)
        assert response.status_code == 200, response.text
        assert "eighteen" in response.json()["stdout"]
        assert all(status < 500 for status in session_statuses), (
            session_statuses
        )
        assert len(session_statuses) >= 4

        body = (await client.get(f"{url_a}/v1/autoscale")).json()
        assert body["replica_states"]["healthy"] == 1
        assert body["recommendation"]["target_replicas"] == 1
        assert (
            body["recommendation"]["target_replicas"]
            == body["replica_states"]["healthy"]
        )

        # --- abusive-tenant sheds were tenant-scoped, never re-walked
        retries = router_b.metrics.metrics[
            "bci_router_retries_total"
        ]._values
        assert retries.get((("reason", "shed"),), 0) == 0
    finally:
        await client.aclose()
        for runner in runners:
            await runner.cleanup()
        await router_a.stop()
        await router_b.stop()
        for stack in stacks:
            await stack.stop()
