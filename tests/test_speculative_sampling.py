"""Speculative decoding with SAMPLING in the continuous batcher
(rejection sampling, models/serving._step_speculative_sampled).

The headline claim is distributional: the committed stream of a sampled
speculative request is distributed exactly as plain sampled decoding from
the target — rejection sampling's guarantee. That cannot be pinned
token-for-token (the rng is consumed differently), and end-to-end token
marginals mix too many first-token conditionals for statistical power at
test-sized n, so this file pins:

1. the token LAW of the rejection kernel itself, with 20k synthetic
   trials against adversarially different p/q and a skew control that
   proves the tolerance bites (the algorithm-level guarantee);
2. same-seed determinism end to end;
3. greedy rows batched WITH sampled rows keep the exact draft-verify
   token stream (batch-mate isolation), and top_k=1 sampling reduces to
   it exactly;
4. stops/logprobs/finish reasons compose; pages are conserved.
"""

import dataclasses
from collections import Counter

import numpy as np
import pytest

import jax

from bee_code_interpreter_tpu.models.serving import (
    ContinuousBatcher,
    SamplingParams,
)
from bee_code_interpreter_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)

CFG = dataclasses.replace(TransformerConfig.tiny(), n_kv_heads=2)
PARAMS = init_params(CFG, jax.random.PRNGKey(0))
DRAFT_CFG = dataclasses.replace(CFG, n_layers=1)
DRAFT = init_params(DRAFT_CFG, jax.random.PRNGKey(2))
PROMPT = [5, 3, 7, 2, 9, 4, 1, 8]


def make_batcher(speculative=True, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("n_pages", 32)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_pages_per_seq", 8)
    if speculative:
        kw.update(draft_params=DRAFT, draft_config=DRAFT_CFG, gamma=3)
    return ContinuousBatcher(PARAMS, CFG, **kw)


def run_one(b, n, sampling):
    r = b.submit(PROMPT, n, sampling=sampling)
    b.run_to_completion()
    return b.result(r)


def _norm(v):
    return v / v.sum()


def test_rejection_kernel_token_law():
    """The distributional guarantee, pinned at the algorithm level where
    statistical power is cheap (end-to-end token marginals mix too many
    first-token conditionals to distinguish anything at test-sized n):
    across 20k trials with ADVERSARIALLY different synthetic p/q, the
    first committed token's law equals p0 (TV < 0.02, sampling noise at
    this n/support is ~0.01), the second committed token given an accept
    equals p1, and a skew control shows the tolerance bites."""
    from bee_code_interpreter_tpu.models.serving import (
        rejection_sample_commit,
    )

    V, gamma, n_trials = 12, 3, 20_000
    master = np.random.default_rng(0)
    p_dists = [_norm(master.random(V) + 0.05) for _ in range(gamma + 1)]
    q_dists = [_norm(master.random(V) ** 2 + 0.01) for _ in range(gamma)]

    first = Counter()
    second = Counter()
    accepted_any = 0
    for i in range(n_trials):
        rng = np.random.default_rng(1000 + i)
        proposals = [int(rng.choice(V, p=q)) for q in q_dists]
        commit, n = rejection_sample_commit(
            proposals, q_dists, lambda g: p_dists[g], rng
        )
        first[commit[0]] += 1
        if n >= 1:
            accepted_any += 1
            second[commit[1]] += 1

    emp0 = np.array([first[t] for t in range(V)]) / n_trials
    tv0 = 0.5 * np.abs(emp0 - p_dists[0]).sum()
    assert tv0 < 0.02, tv0
    emp1 = np.array([second[t] for t in range(V)]) / max(accepted_any, 1)
    tv1 = 0.5 * np.abs(emp1 - p_dists[1]).sum()
    assert tv1 < 0.03, tv1
    # control: the same tolerance rejects the DRAFT's law — the kernel is
    # provably not just passing proposals through
    tv_q = 0.5 * np.abs(emp0 - q_dists[0]).sum()
    assert tv_q > 0.05, tv_q
    # and acceptance actually happens (the speedup exists)
    assert 0.2 < accepted_any / n_trials < 0.98


def test_rejection_kernel_identical_dists_always_accepts():
    from bee_code_interpreter_tpu.models.serving import (
        rejection_sample_commit,
    )

    V, gamma = 8, 4
    master = np.random.default_rng(3)
    dists = [_norm(master.random(V) + 0.1) for _ in range(gamma + 1)]
    for i in range(200):
        rng = np.random.default_rng(i)
        proposals = [int(rng.choice(V, p=q)) for q in dists[:gamma]]
        commit, n = rejection_sample_commit(
            proposals, dists[:gamma], lambda g: dists[g], rng
        )
        # p == q: min(1, p/q) == 1 at every proposed token
        assert n == gamma
        assert commit[:gamma] == proposals
        assert len(commit) == gamma + 1


def test_same_seed_is_deterministic():
    sp = SamplingParams(temperature=0.9, top_k=20, seed=42)
    out1 = run_one(make_batcher(), 8, sp)
    out2 = run_one(make_batcher(), 8, sp)
    assert out1 == out2
    assert len(out1) == 8


def test_greedy_batchmate_keeps_exact_draft_verify():
    want = run_one(make_batcher(), 6, SamplingParams())  # all-greedy path
    b = make_batcher()
    r_greedy = b.submit(PROMPT, 6)
    r_sampled = b.submit([3, 1, 4, 1, 5], 6,
                         sampling=SamplingParams(temperature=1.0, seed=3))
    b.run_to_completion()
    assert b.result(r_greedy) == want  # sampled batch-mate changes nothing
    assert len(b.result(r_sampled)) == 6


def test_top_k_filter_applies_to_both_sides():
    """top_k=1 sampling is greedy-by-filter: accepted proposals and
    resamples can only ever pick the target argmax, so the output equals
    the greedy stream exactly."""
    want = run_one(make_batcher(), 6, SamplingParams())
    got = run_one(make_batcher(), 6,
                  SamplingParams(temperature=0.8, top_k=1, seed=11))
    assert got == want


def test_stops_logprobs_and_reasons_compose():
    sp = SamplingParams(temperature=1.0, seed=5, logprobs=True)
    b = make_batcher()
    r = b.submit(PROMPT, 8, sampling=sp)
    b.run_to_completion()
    out = b.result(r)
    lps = b.result_logprobs(r)
    assert len(lps) == len(out) == 8
    assert all(np.isfinite(lps))
    assert b.finish_reason(r) == "length"
    # stop sequence on the deterministic (seeded) sampled stream
    stop = (out[3], out[4])
    b2 = make_batcher()
    r2 = b2.submit(PROMPT, 8, sampling=dataclasses.replace(
        sp, stop_sequences=(stop,)))
    b2.run_to_completion()
    assert b2.result(r2) == out[:3]
    assert b2.finish_reason(r2) == "stop"
    assert len(b2.result_logprobs(r2)) == 3


def test_pages_accounted_after_sampled_speculative():
    b = make_batcher()
    free0 = len(b.free_pages)
    for seed in range(4):
        run_one(b, 5, SamplingParams(temperature=1.1, seed=seed))
    assert len(b.free_pages) == free0
    assert not b.active.any()
