"""End-to-end observability through the fake-Kubernetes path (ISSUE 2
acceptance): one Execute yields ONE trace — admission→spawn→upload→execute→
download under a single trace_id — retrievable at /v1/traces/{trace_id},
with the same id in the pod-side (fake executor) log records and in the
response's timing breakdown, and stage durations consistent with the
end-to-end Prometheus histogram."""

import asyncio
import logging
import re

from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_tpu.api.http_server import create_http_server
from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.observability import Tracer, format_traceparent
from bee_code_interpreter_tpu.resilience import AdmissionController
from bee_code_interpreter_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_tpu.services.kubernetes_code_executor import (
    KubernetesCodeExecutor,
)
from bee_code_interpreter_tpu.utils.metrics import (
    PROMETHEUS_CONTENT_TYPE,
    Registry,
)
from bee_code_interpreter_tpu.utils.request_id import RequestIdLoggingFilter
from tests.fakes import FakeExecutorPods, FakeKubectl

POD_LOGGER = "bee_code_interpreter_tpu.runtime.executor_server"
EDGE_LOGGER = "bee_code_interpreter_tpu.api.http_server"


def make_app(pods, storage, metrics, tracer):
    config = Config(
        executor_backend="kubernetes",
        executor_port=pods.port,
        executor_pod_queue_target_length=0,  # every request spawns on demand
        pod_ready_timeout_s=5,
    )
    executor = KubernetesCodeExecutor(
        kubectl=FakeKubectl(pods),
        storage=storage,
        config=config,
        metrics=metrics,
        ip_poll_interval_s=0.02,
    )
    return create_http_server(
        code_executor=executor,
        custom_tool_executor=CustomToolExecutor(code_executor=executor),
        metrics=metrics,
        admission=AdmissionController(metrics=metrics),
        tracer=tracer,
    )


async def with_client(app, fn):
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        return await fn(client)
    finally:
        await client.close()


def _histogram_sum(text: str, name: str, route: str) -> float:
    pattern = re.compile(
        rf'^{name}_sum{{route="{re.escape(route)}"}} ([0-9.e+-]+)$', re.M
    )
    m = pattern.search(text)
    return float(m.group(1)) if m else 0.0


async def test_single_execute_yields_one_complete_trace(
    tmp_path, storage, caplog
):
    pods = FakeExecutorPods(tmp_path / "pods")
    metrics = Registry()
    tracer = Tracer(metrics=metrics)
    app = make_app(pods, storage, metrics, tracer)
    pod_logger = logging.getLogger(POD_LOGGER)
    log_filter = RequestIdLoggingFilter()
    pod_logger.addFilter(log_filter)

    async def go(client: TestClient):
        # request 1 creates a file so request 2 exercises BOTH upload (files
        # in) and download (changed files out)
        r1 = await (
            await client.post(
                "/v1/execute",
                json={"source_code": "open('state.txt', 'w').write('x')"},
            )
        ).json()
        assert set(r1["files"]) == {"/workspace/state.txt"}

        before = _histogram_sum(
            await (await client.get("/metrics")).text(),
            "bci_http_request_seconds",
            "/v1/execute",
        )
        caplog.clear()
        with caplog.at_level(logging.INFO, logger=POD_LOGGER):
            resp = await client.post(
                "/v1/execute",
                json={
                    # sleep makes the execute stage dominate, so the
                    # stage-sum-vs-histogram bound below is not noise-bound
                    "source_code": (
                        "import time; time.sleep(0.2)\n"
                        "print(open('state.txt').read())\n"
                        "open('out.txt', 'w').write('y')"
                    ),
                    "files": r1["files"],
                },
            )
        body = await resp.json()
        assert resp.status == 200
        assert body["stdout"] == "x\n"

        # --- response carries the trace id + per-stage breakdown ---
        trace_id = body["trace_id"]
        assert trace_id and len(trace_id) == 32
        timings = body["timings_ms"]
        assert {"admission", "spawn", "upload", "execute", "download"} <= set(
            timings
        )
        assert timings["execute"] >= 200.0  # the sleep is visible

        # --- the same trace is retrievable from the inspection API ---
        listed = await (await client.get("/v1/traces")).json()
        assert trace_id in {t["trace_id"] for t in listed["traces"]}
        detail = await (await client.get(f"/v1/traces/{trace_id}")).json()
        assert detail["trace_id"] == trace_id
        assert detail["name"] == "/v1/execute"
        names = {s["name"] for s in detail["spans"]}
        assert {
            "/v1/execute", "admission", "spawn", "upload", "execute",
            "download",
        } <= names
        # one trace: every span under the single trace_id
        assert {s["trace_id"] for s in detail["spans"]} == {trace_id}
        missing = await client.get("/v1/traces/" + "deadbeef" * 4)
        assert missing.status == 404

        # --- stage durations agree with the end-to-end histogram ---
        after = _histogram_sum(
            await (await client.get("/metrics")).text(),
            "bci_http_request_seconds",
            "/v1/execute",
        )
        end_to_end_ms = (after - before) * 1000.0
        stage_sum_ms = sum(
            timings[k]
            for k in ("admission", "spawn", "upload", "execute", "download")
        )
        assert stage_sum_ms <= end_to_end_ms * 1.001
        assert stage_sum_ms >= end_to_end_ms * 0.9

        # --- the pod-side executor logs carry the SAME correlation ids ---
        rid = resp.headers["X-Request-Id"]
        pod_records = [
            r for r in caplog.records if r.name == POD_LOGGER
        ]
        assert pod_records, "fake executor produced no log records"
        executing = [
            r for r in pod_records if "Executing sandboxed code" in r.message
        ]
        assert executing
        for r in executing:
            assert r.request_id == rid
            assert r.trace_id == trace_id

        # spans also fed the shared stage histogram (Prometheus and traces
        # agree on what stages exist)
        text = await (await client.get("/metrics")).text()
        for stage in ("admission", "spawn", "upload", "execute", "download"):
            assert f'bci_stage_seconds_count{{stage="{stage}"}}' in text

    try:
        await with_client(app, go)
    finally:
        pod_logger.removeFilter(log_filter)
        await pods.close()


async def test_inbound_traceparent_continues_the_trace(tmp_path, storage):
    pods = FakeExecutorPods(tmp_path / "pods")
    metrics = Registry()
    tracer = Tracer(metrics=metrics)
    app = make_app(pods, storage, metrics, tracer)

    async def go(client: TestClient):
        upstream_trace = "ab" * 16
        upstream_span = "cd" * 8
        resp = await client.post(
            "/v1/execute",
            json={"source_code": "print(1)"},
            headers={
                "traceparent": format_traceparent(upstream_trace, upstream_span)
            },
        )
        body = await resp.json()
        assert body["trace_id"] == upstream_trace
        detail = await (
            await client.get(f"/v1/traces/{upstream_trace}")
        ).json()
        root = next(s for s in detail["spans"] if s["name"] == "/v1/execute")
        assert root["parent_id"] == upstream_span

    try:
        await with_client(app, go)
    finally:
        await pods.close()


async def test_concurrent_executes_do_not_cross_contaminate_ids(
    tmp_path, storage, caplog
):
    """Two in-flight executes interleaving on the loop: each one's edge log
    records must carry its own request/trace ids (satellite: log-correlation
    coverage at the service level, not just the contextvar level)."""
    pods = FakeExecutorPods(tmp_path / "pods")
    metrics = Registry()
    tracer = Tracer(metrics=metrics)
    app = make_app(pods, storage, metrics, tracer)
    edge_logger = logging.getLogger(EDGE_LOGGER)
    log_filter = RequestIdLoggingFilter()
    edge_logger.addFilter(log_filter)

    async def go(client: TestClient):
        async def run(tag: str):
            resp = await client.post(
                "/v1/execute",
                json={
                    "source_code": (
                        f"import time; time.sleep(0.05); print('{tag}')"
                    )
                },
            )
            return tag, await resp.json()

        with caplog.at_level(logging.INFO, logger=EDGE_LOGGER):
            results = dict(
                await asyncio.gather(run("alpha"), run("bravo"))
            )
        assert results["alpha"]["stdout"] == "alpha\n"
        assert results["bravo"]["stdout"] == "bravo\n"
        assert results["alpha"]["trace_id"] != results["bravo"]["trace_id"]

        # every edge record mentioning a tag must carry that request's ids
        by_tag = {}
        for r in caplog.records:
            if r.name != EDGE_LOGGER:
                continue
            for tag in ("alpha", "bravo"):
                if tag in r.message:
                    by_tag.setdefault(tag, set()).add(r.trace_id)
        for tag in ("alpha", "bravo"):
            assert by_tag[tag] == {results[tag]["trace_id"]}, (
                f"log records for {tag} leaked another request's trace id"
            )

    try:
        await with_client(app, go)
    finally:
        edge_logger.removeFilter(log_filter)
        await pods.close()


async def test_metrics_content_type_negotiates_exposition_format(
    local_executor,
):
    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
    )

    async def go(client: TestClient):
        resp = await client.get("/metrics")
        assert resp.status == 200
        assert resp.headers["Content-Type"] == PROMETHEUS_CONTENT_TYPE

    await with_client(app, go)
