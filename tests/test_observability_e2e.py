"""End-to-end observability through the fake-Kubernetes path (ISSUE 2
acceptance): one Execute yields ONE trace — admission→spawn→upload→execute→
download under a single trace_id — retrievable at /v1/traces/{trace_id},
with the same id in the pod-side (fake executor) log records and in the
response's timing breakdown, and stage durations consistent with the
end-to-end Prometheus histogram."""

import asyncio
import logging
import re

from aiohttp.test_utils import TestClient, TestServer

from bee_code_interpreter_tpu.api.http_server import create_http_server
from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.observability import Tracer, format_traceparent
from bee_code_interpreter_tpu.resilience import AdmissionController
from bee_code_interpreter_tpu.services.custom_tool_executor import (
    CustomToolExecutor,
)
from bee_code_interpreter_tpu.services.kubernetes_code_executor import (
    KubernetesCodeExecutor,
)
from bee_code_interpreter_tpu.utils.metrics import (
    PROMETHEUS_CONTENT_TYPE,
    Registry,
)
from bee_code_interpreter_tpu.utils.request_id import RequestIdLoggingFilter
from tests.fakes import FakeExecutorPods, FakeKubectl

POD_LOGGER = "bee_code_interpreter_tpu.runtime.executor_server"
EDGE_LOGGER = "bee_code_interpreter_tpu.api.http_server"


def make_stack(pods, storage, metrics, tracer):
    """(app, executor): the aiohttp edge over the REAL KubernetesCodeExecutor
    against the fake cluster — the executor is returned so tests can reach
    its fleet journal / pool directly."""
    config = Config(
        executor_backend="kubernetes",
        executor_port=pods.port,
        executor_pod_queue_target_length=0,  # every request spawns on demand
        pod_ready_timeout_s=5,
    )
    executor = KubernetesCodeExecutor(
        kubectl=FakeKubectl(pods),
        storage=storage,
        config=config,
        metrics=metrics,
        ip_poll_interval_s=0.02,
    )
    app = create_http_server(
        code_executor=executor,
        custom_tool_executor=CustomToolExecutor(code_executor=executor),
        metrics=metrics,
        admission=AdmissionController(metrics=metrics),
        tracer=tracer,
    )
    return app, executor


def make_app(pods, storage, metrics, tracer):
    return make_stack(pods, storage, metrics, tracer)[0]


async def with_client(app, fn):
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        return await fn(client)
    finally:
        await client.close()


def _histogram_sum(text: str, name: str, route: str) -> float:
    pattern = re.compile(
        rf'^{name}_sum{{route="{re.escape(route)}"}} ([0-9.e+-]+)$', re.M
    )
    m = pattern.search(text)
    return float(m.group(1)) if m else 0.0


async def test_single_execute_yields_one_complete_trace(
    tmp_path, storage, caplog
):
    pods = FakeExecutorPods(tmp_path / "pods")
    metrics = Registry()
    tracer = Tracer(metrics=metrics)
    app = make_app(pods, storage, metrics, tracer)
    pod_logger = logging.getLogger(POD_LOGGER)
    log_filter = RequestIdLoggingFilter()
    pod_logger.addFilter(log_filter)

    async def go(client: TestClient):
        # request 1 creates a file so request 2 exercises BOTH upload (files
        # in) and download (changed files out)
        r1 = await (
            await client.post(
                "/v1/execute",
                json={"source_code": "open('state.txt', 'w').write('x')"},
            )
        ).json()
        assert set(r1["files"]) == {"/workspace/state.txt"}

        before = _histogram_sum(
            await (await client.get("/metrics")).text(),
            "bci_http_request_seconds",
            "/v1/execute",
        )
        caplog.clear()
        with caplog.at_level(logging.INFO, logger=POD_LOGGER):
            resp = await client.post(
                "/v1/execute",
                json={
                    # sleep makes the execute stage dominate, so the
                    # stage-sum-vs-histogram bound below is not noise-bound
                    "source_code": (
                        "import time; time.sleep(0.2)\n"
                        "print(open('state.txt').read())\n"
                        "open('out.txt', 'w').write('y')"
                    ),
                    "files": r1["files"],
                },
            )
        body = await resp.json()
        assert resp.status == 200
        assert body["stdout"] == "x\n"

        # --- response carries the trace id + per-stage breakdown ---
        trace_id = body["trace_id"]
        assert trace_id and len(trace_id) == 32
        timings = body["timings_ms"]
        assert {"admission", "spawn", "upload", "execute", "download"} <= set(
            timings
        )
        assert timings["execute"] >= 200.0  # the sleep is visible

        # --- the same trace is retrievable from the inspection API ---
        listed = await (await client.get("/v1/traces")).json()
        assert trace_id in {t["trace_id"] for t in listed["traces"]}
        detail = await (await client.get(f"/v1/traces/{trace_id}")).json()
        assert detail["trace_id"] == trace_id
        assert detail["name"] == "/v1/execute"
        names = {s["name"] for s in detail["spans"]}
        assert {
            "/v1/execute", "admission", "spawn", "upload", "execute",
            "download",
        } <= names
        # one trace: every span under the single trace_id
        assert {s["trace_id"] for s in detail["spans"]} == {trace_id}
        missing = await client.get("/v1/traces/" + "deadbeef" * 4)
        assert missing.status == 404

        # --- stage durations agree with the end-to-end histogram ---
        after = _histogram_sum(
            await (await client.get("/metrics")).text(),
            "bci_http_request_seconds",
            "/v1/execute",
        )
        end_to_end_ms = (after - before) * 1000.0
        stage_sum_ms = sum(
            timings[k]
            for k in ("admission", "spawn", "upload", "execute", "download")
        )
        assert stage_sum_ms <= end_to_end_ms * 1.001
        assert stage_sum_ms >= end_to_end_ms * 0.9

        # --- the pod-side executor logs carry the SAME correlation ids ---
        rid = resp.headers["X-Request-Id"]
        pod_records = [
            r for r in caplog.records if r.name == POD_LOGGER
        ]
        assert pod_records, "fake executor produced no log records"
        executing = [
            r for r in pod_records if "Executing sandboxed code" in r.message
        ]
        assert executing
        for r in executing:
            assert r.request_id == rid
            assert r.trace_id == trace_id

        # spans also fed the shared stage histogram (Prometheus and traces
        # agree on what stages exist)
        text = await (await client.get("/metrics")).text()
        for stage in ("admission", "spawn", "upload", "execute", "download"):
            assert f'bci_stage_seconds_count{{stage="{stage}"}}' in text

    try:
        await with_client(app, go)
    finally:
        pod_logger.removeFilter(log_filter)
        await pods.close()


async def test_inbound_traceparent_continues_the_trace(tmp_path, storage):
    pods = FakeExecutorPods(tmp_path / "pods")
    metrics = Registry()
    tracer = Tracer(metrics=metrics)
    app = make_app(pods, storage, metrics, tracer)

    async def go(client: TestClient):
        upstream_trace = "ab" * 16
        upstream_span = "cd" * 8
        resp = await client.post(
            "/v1/execute",
            json={"source_code": "print(1)"},
            headers={
                "traceparent": format_traceparent(upstream_trace, upstream_span)
            },
        )
        body = await resp.json()
        assert body["trace_id"] == upstream_trace
        detail = await (
            await client.get(f"/v1/traces/{upstream_trace}")
        ).json()
        root = next(s for s in detail["spans"] if s["name"] == "/v1/execute")
        assert root["parent_id"] == upstream_span

    try:
        await with_client(app, go)
    finally:
        await pods.close()


async def test_concurrent_executes_do_not_cross_contaminate_ids(
    tmp_path, storage, caplog
):
    """Two in-flight executes interleaving on the loop: each one's edge log
    records must carry its own request/trace ids (satellite: log-correlation
    coverage at the service level, not just the contextvar level)."""
    pods = FakeExecutorPods(tmp_path / "pods")
    metrics = Registry()
    tracer = Tracer(metrics=metrics)
    app = make_app(pods, storage, metrics, tracer)
    edge_logger = logging.getLogger(EDGE_LOGGER)
    log_filter = RequestIdLoggingFilter()
    edge_logger.addFilter(log_filter)

    async def go(client: TestClient):
        async def run(tag: str):
            resp = await client.post(
                "/v1/execute",
                json={
                    "source_code": (
                        f"import time; time.sleep(0.05); print('{tag}')"
                    )
                },
            )
            return tag, await resp.json()

        with caplog.at_level(logging.INFO, logger=EDGE_LOGGER):
            results = dict(
                await asyncio.gather(run("alpha"), run("bravo"))
            )
        assert results["alpha"]["stdout"] == "alpha\n"
        assert results["bravo"]["stdout"] == "bravo\n"
        assert results["alpha"]["trace_id"] != results["bravo"]["trace_id"]

        # every edge record mentioning a tag must carry that request's ids
        by_tag = {}
        for r in caplog.records:
            if r.name != EDGE_LOGGER:
                continue
            for tag in ("alpha", "bravo"):
                if tag in r.message:
                    by_tag.setdefault(tag, set()).add(r.trace_id)
        for tag in ("alpha", "bravo"):
            assert by_tag[tag] == {results[tag]["trace_id"]}, (
                f"log records for {tag} leaked another request's trace id"
            )

    try:
        await with_client(app, go)
    finally:
        edge_logger.removeFilter(log_filter)
        await pods.close()


async def test_fleet_usage_and_metrics_tell_one_requests_full_story(
    tmp_path, storage
):
    """ISSUE 3 acceptance: after one request through the fake-k8s path,
    /v1/fleet/events shows the serving pod's spawn→assigned→executing→
    released transitions, ExecuteResponse.usage reports nonzero cpu/wall/
    byte figures that match the trace span's usage.* attributes, and
    /metrics exposes the new pool + execution histograms."""
    pods = FakeExecutorPods(tmp_path / "pods")
    metrics = Registry()
    tracer = Tracer(metrics=metrics)
    app, _executor = make_stack(pods, storage, metrics, tracer)

    async def go(client: TestClient):
        seed = await (
            await client.post(
                "/v1/execute",
                json={"source_code": "open('in.txt', 'w').write('z' * 64)"},
            )
        ).json()
        resp = await client.post(
            "/v1/execute",
            json={
                "source_code": (
                    "print(open('in.txt').read()[:1])\n"
                    "open('out.txt', 'w').write('y' * 128)"
                ),
                "files": seed["files"],
            },
        )
        body = await resp.json()
        assert resp.status == 200

        # --- usage: nonzero cpu/wall/byte figures in the response ---
        usage = body["usage"]
        assert usage["cpu_user_s"] > 0
        assert usage["wall_s"] > 0
        assert usage["max_rss_bytes"] > 0
        assert usage["uploaded_bytes"] == 64
        assert usage["downloaded_bytes"] == 128
        assert usage["workspace_bytes_written"] >= 128

        # --- ...matching the trace root span's usage.* attributes ---
        detail = await (
            await client.get(f"/v1/traces/{body['trace_id']}")
        ).json()
        root = next(s for s in detail["spans"] if s["parent_id"] is None)
        for key in (
            "cpu_user_s", "wall_s", "max_rss_bytes",
            "uploaded_bytes", "downloaded_bytes",
        ):
            assert root["attributes"][f"usage.{key}"] == str(usage[key])

        # --- fleet journal: the serving pod's full story ---
        events = (
            await (await client.get("/v1/fleet/events?limit=50")).json()
        )["events"]
        pod_names = {e["pod"] for e in events}
        assert len(pod_names) == 2  # one pod per request
        by_pod = {}
        for e in reversed(events):  # chronological
            by_pod.setdefault(e["pod"], []).append(e["state"])
        for states in by_pod.values():
            assert states == [
                "spawning", "ready", "assigned", "executing", "released",
            ]
        snap = await (await client.get("/v1/fleet")).json()
        assert snap["live"] == 0  # single-use: nothing outlives its request
        assert snap["executions_total"] == 2
        assert snap["lifetime"]["released"] == 2

        # --- the new pool + execution metrics are exposed ---
        text = await (await client.get("/metrics")).text()
        assert "bci_pool_spawn_seconds_count 2" in text
        assert "bci_pool_utilization 0" in text
        assert "bci_execution_cpu_seconds_count 2" in text
        assert "bci_execution_peak_rss_bytes_count 2" in text

    try:
        await with_client(app, go)
    finally:
        await pods.close()


async def test_traces_endpoint_supports_limit_and_min_duration(
    tmp_path, storage
):
    pods = FakeExecutorPods(tmp_path / "pods")
    metrics = Registry()
    tracer = Tracer(metrics=metrics)
    app = make_app(pods, storage, metrics, tracer)

    async def go(client: TestClient):
        fast = await (
            await client.post("/v1/execute", json={"source_code": "pass"})
        ).json()
        slow = await (
            await client.post(
                "/v1/execute",
                json={"source_code": "import time; time.sleep(0.3)"},
            )
        ).json()

        listed = await (await client.get("/v1/traces")).json()
        assert len(listed["traces"]) == 2

        limited = await (await client.get("/v1/traces?limit=1")).json()
        assert len(limited["traces"]) == 1
        # newest first: the slow request came second
        assert limited["traces"][0]["trace_id"] == slow["trace_id"]

        slow_only = await (
            await client.get("/v1/traces?min_duration_ms=250")
        ).json()
        assert {t["trace_id"] for t in slow_only["traces"]} == {
            slow["trace_id"]
        }
        assert fast["trace_id"] not in {
            t["trace_id"] for t in slow_only["traces"]
        }

        both = await (
            await client.get("/v1/traces?limit=5&min_duration_ms=0")
        ).json()
        assert len(both["traces"]) == 2

        for bad in (
            "/v1/traces?limit=banana",
            "/v1/traces?min_duration_ms=soup",
            "/v1/traces?limit=-1",
        ):
            assert (await client.get(bad)).status == 400

    try:
        await with_client(app, go)
    finally:
        await pods.close()


async def test_healthz_verbose_reports_pool_breakers_and_fleet(
    tmp_path, storage
):
    pods = FakeExecutorPods(tmp_path / "pods")
    metrics = Registry()
    tracer = Tracer(metrics=metrics)
    app = make_app(pods, storage, metrics, tracer)

    async def go(client: TestClient):
        plain = await (await client.get("/healthz")).json()
        assert plain == {"status": "ok"}  # terse view unchanged
        explicit_off = await (await client.get("/healthz?verbose=0")).json()
        assert explicit_off == {"status": "ok"}  # =0 is not truthy

        await client.post("/v1/execute", json={"source_code": "print(1)"})
        verbose = await (await client.get("/healthz?verbose=1")).json()
        assert verbose["status"] == "ok"
        # `target` is the live refill target (docs/autoscaling.md): the
        # static config length until an act-mode autoscaler overrides it.
        assert verbose["pool"] == {"ready": 0, "spawning": 0, "target": 0}
        assert verbose["breakers"] == {
            "k8s-spawn": "closed", "k8s-http": "closed",
        }
        assert verbose["fleet"]["executions_total"] == 1
        assert verbose["fleet"]["live"] == 0

    try:
        await with_client(app, go)
    finally:
        await pods.close()


async def test_profile_sandbox_injects_trace_dir_and_reports_artifacts(
    local_executor,
):
    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
    )

    async def go(client: TestClient):
        resp = await client.post(
            "/v1/profile",
            json={
                "source_code": (
                    "import os\n"
                    "d = os.environ['BCI_PROFILE_DIR']\n"
                    "print(d)\n"
                    "os.makedirs(os.path.basename(d), exist_ok=True)\n"
                    "open(os.path.join(os.path.basename(d), 'trace.pb'),"
                    " 'w').write('fake-trace')"
                ),
            },
        )
        body = await resp.json()
        assert resp.status == 200
        # the shim's env trigger was injected...
        assert body["stdout"] == "/workspace/.bci-profile\n"
        assert body["profile_dir"] == "/workspace/.bci-profile"
        # ...and artifacts written under it ride the changed-file map
        assert body["profile_files"] == [
            "/workspace/.bci-profile/trace.pb"
        ]
        assert set(body["files"]) == {"/workspace/.bci-profile/trace.pb"}
        assert body["usage"]["cpu_user_s"] > 0

        # missing source_code for sandbox target is a validation error
        resp = await client.post("/v1/profile", json={"target": "sandbox"})
        assert resp.status == 422
        # serving target without an attached engine is explicit
        resp = await client.post("/v1/profile", json={"target": "serving"})
        assert resp.status == 501

    await with_client(app, go)


async def test_profile_serving_captures_engine_steps(tmp_path, local_executor):
    from bee_code_interpreter_tpu.observability import ServingProfiler

    class Stepper:
        steps = 0

        def step(self):
            Stepper.steps += 1

    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
        profiler=ServingProfiler(Stepper(), trace_root=tmp_path),
    )

    async def go(client: TestClient):
        resp = await client.post(
            "/v1/profile", json={"target": "serving", "steps": 4}
        )
        body = await resp.json()
        assert resp.status == 200
        assert body["target"] == "serving"
        assert body["steps"] == 4
        assert Stepper.steps == 4
        assert body["trace_dir"].startswith(str(tmp_path))

    await with_client(app, go)


async def test_grpc_fleet_service_serves_snapshot_and_events(
    tmp_path, storage
):
    """The gRPC spelling of /v1/fleet: JSON-bytes FleetService methods
    backed by the same journal the executor records into."""
    import grpc.aio

    from bee_code_interpreter_tpu.api.grpc_server import GrpcServer, fleet_stubs

    pods = FakeExecutorPods(tmp_path / "pods")
    config = Config(
        executor_backend="kubernetes",
        executor_port=pods.port,
        executor_pod_queue_target_length=0,
        pod_ready_timeout_s=5,
    )
    executor = KubernetesCodeExecutor(
        kubectl=FakeKubectl(pods),
        storage=storage,
        config=config,
        ip_poll_interval_s=0.02,
    )
    server = GrpcServer(
        code_executor=executor,
        custom_tool_executor=CustomToolExecutor(code_executor=executor),
    )
    port = await server.start("127.0.0.1:0")
    try:
        await executor.execute("print('hi')")
        import json as _json

        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stubs = fleet_stubs(channel)
            snap = _json.loads(await stubs["GetFleet"](b""))
            assert snap["executions_total"] == 1
            events = _json.loads(
                await stubs["GetFleetEvents"](_json.dumps({"limit": 2}).encode())
            )["events"]
            assert len(events) == 2
            assert events[0]["state"] == "released"
    finally:
        await server.stop(grace=0.1)
        await pods.close()


async def test_metrics_content_type_negotiates_exposition_format(
    local_executor,
):
    """Regression for BOTH negotiation paths: the classic Prometheus text
    format stays the default; ``Accept: application/openmetrics-text`` gets
    OpenMetrics 1.0 with the ``# EOF`` terminator."""
    from bee_code_interpreter_tpu.utils.metrics import (
        OPENMETRICS_CONTENT_TYPE,
    )

    app = create_http_server(
        code_executor=local_executor,
        custom_tool_executor=CustomToolExecutor(code_executor=local_executor),
    )

    async def go(client: TestClient):
        # default (no Accept preference): classic Prometheus text format
        resp = await client.get("/metrics")
        assert resp.status == 200
        assert resp.headers["Content-Type"] == PROMETHEUS_CONTENT_TYPE
        body = await resp.text()
        assert "# EOF" not in body

        # a Prometheus-style Accept chain asking for OpenMetrics first
        resp = await client.get(
            "/metrics",
            headers={
                "Accept": (
                    "application/openmetrics-text; version=1.0.0, "
                    "text/plain;version=0.0.4;q=0.5"
                )
            },
        )
        assert resp.status == 200
        assert resp.headers["Content-Type"] == OPENMETRICS_CONTENT_TYPE
        body = await resp.text()
        assert body.rstrip().endswith("# EOF")

        # an explicit text/plain Accept keeps the classic format
        resp = await client.get(
            "/metrics", headers={"Accept": "text/plain; version=0.0.4"}
        )
        assert resp.headers["Content-Type"] == PROMETHEUS_CONTENT_TYPE

        # q=0 means "not acceptable" (RFC 9110): a client explicitly
        # REFUSING OpenMetrics must get the classic format
        resp = await client.get(
            "/metrics",
            headers={
                "Accept": "application/openmetrics-text;q=0, text/plain"
            },
        )
        assert resp.headers["Content-Type"] == PROMETHEUS_CONTENT_TYPE

    await with_client(app, go)


async def test_export_traces_and_exemplars_tell_one_story(tmp_path, storage):
    """ISSUE 5 acceptance: one executed request produces an OTLP/JSON span
    batch whose trace_id matches both /v1/traces/{id} and the exemplar on
    the bci_stage_seconds OpenMetrics exposition — collector, inspection
    API, and Prometheus all point at the same trace."""
    import json as _json

    from bee_code_interpreter_tpu.observability import TelemetryExporter
    from bee_code_interpreter_tpu.resilience import RetryPolicy

    pods = FakeExecutorPods(tmp_path / "pods")
    metrics = Registry()
    tracer = Tracer(metrics=metrics)
    sent: list[tuple[str, dict]] = []

    async def transport(path, body):
        sent.append((path, _json.loads(body)))

    exporter = TelemetryExporter(
        "http://collector.invalid:4318",
        metrics,
        transport=transport,
        flush_interval_s=60.0,  # the test flushes explicitly
        retry=RetryPolicy(attempts=1, wait_min_s=0.001, wait_max_s=0.002),
    )
    tracer.add_sink(exporter.enqueue_trace)
    app = make_app(pods, storage, metrics, tracer)

    async def go(client: TestClient):
        body = await (
            await client.post(
                "/v1/execute", json={"source_code": "print('exported')"}
            )
        ).json()
        trace_id = body["trace_id"]

        # --- the exported OTLP batch carries the SAME trace ---
        await exporter.flush_once()
        trace_posts = [p for p in sent if p[0] == "/v1/traces"]
        assert len(trace_posts) == 1
        spans = trace_posts[0][1]["resourceSpans"][0]["scopeSpans"][0]["spans"]
        assert {s["traceId"] for s in spans} == {trace_id}
        exported_names = {s["name"] for s in spans}
        # (no files in/out on this request, so no upload/download stages)
        assert {"/v1/execute", "spawn", "execute"} <= exported_names

        # --- which is retrievable from the inspection API ---
        detail = await (await client.get(f"/v1/traces/{trace_id}")).json()
        assert {s["name"] for s in detail["spans"]} == exported_names

        # --- and is the exemplar on the stage histogram ---
        om = await (
            await client.get(
                "/metrics",
                headers={"Accept": "application/openmetrics-text"},
            )
        ).text()
        execute_exemplars = re.findall(
            r'^bci_stage_seconds_bucket\{le="[^"]+",stage="execute"\} \d+ '
            r'# \{trace_id="([0-9a-f]{32})"',
            om,
            re.M,
        )
        assert execute_exemplars == [trace_id]

        # drop accounting stayed clean on the happy path
        assert "bci_telemetry_dropped_total" not in re.sub(
            r"# (HELP|TYPE)[^\n]*", "", om
        )

    try:
        await with_client(app, go)
    finally:
        await pods.close()


async def test_debug_bundle_is_one_complete_document(tmp_path, storage):
    """ISSUE 5 acceptance: GET /v1/debug/bundle returns traces, fleet
    events, SLO state, service health, and the metrics dump in ONE JSON
    document."""
    from bee_code_interpreter_tpu.observability import (
        SloEngine,
        parse_objectives,
    )

    pods = FakeExecutorPods(tmp_path / "pods")
    metrics = Registry()
    tracer = Tracer(metrics=metrics)
    slo = SloEngine(parse_objectives(99.5, "2000:99"), metrics=metrics)
    pods_app, executor = make_stack(pods, storage, metrics, tracer)
    app = create_http_server(
        code_executor=executor,
        custom_tool_executor=CustomToolExecutor(code_executor=executor),
        metrics=metrics,
        tracer=tracer,
        slo=slo,
    )

    async def go(client: TestClient):
        body = await (
            await client.post("/v1/execute", json={"source_code": "print(1)"})
        ).json()

        resp = await client.get("/v1/debug/bundle")
        assert resp.status == 200
        bundle = await resp.json()
        assert bundle["generated_unix"] > 0

        # traces: the request is in the recent summaries and (being the
        # only one) in the slowest full dumps
        recent_ids = {t["trace_id"] for t in bundle["traces"]["recent"]}
        assert body["trace_id"] in recent_ids
        assert bundle["traces"]["slowest"][0]["spans"]

        # fleet: the serving pod's lifecycle is in the same document
        states = {e["state"] for e in bundle["fleet"]["events"]}
        assert {"spawning", "ready", "executing", "released"} <= states
        assert bundle["fleet"]["snapshot"]["executions_total"] == 1

        # slo: the request was sampled
        availability = next(
            o
            for o in bundle["slo"]["objectives"]
            if o["name"] == "availability"
        )
        assert availability["windows"]["5m"]["total"] == 1

        # service health + full metrics dump round out the snapshot
        assert bundle["service"]["breakers"] == {
            "k8s-spawn": "closed", "k8s-http": "closed",
        }
        assert "bci_stage_seconds" in bundle["metrics"]

    try:
        await with_client(app, go)
    finally:
        await pods.close()
