"""Mesh + ring attention on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from bee_code_interpreter_tpu.parallel import auto_mesh, make_mesh, ring_attention
from bee_code_interpreter_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention_sharded,
)


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_make_mesh_shapes():
    mesh = make_mesh({"dp": 2, "tp": 4})
    assert mesh.axis_names == ("dp", "tp")
    assert mesh.devices.shape == (2, 4)


def test_make_mesh_too_big():
    with pytest.raises(ValueError):
        make_mesh({"dp": 16, "tp": 4})


def test_auto_mesh():
    mesh = auto_mesh(8)
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("dp", "sp", "tp")
    mesh2 = auto_mesh(8, sp=2)
    assert dict(zip(mesh2.axis_names, mesh2.devices.shape))["sp"] == 2


def rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype=dtype)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(causal):
    mesh = make_mesh({"sp": 4})
    B, H, L, D = 2, 2, 64, 16
    q, k, v = (rand((B, H, L, D), i) for i in range(3))
    out = ring_attention_sharded(mesh, q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_attention_grad_flows():
    mesh = make_mesh({"sp": 2})

    def loss(q, k, v):
        return ring_attention_sharded(mesh, q, k, v).sum()

    B, H, L, D = 1, 1, 16, 8
    q, k, v = (rand((B, H, L, D), i) for i in range(3))
    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    def ref_loss(q, k, v):
        return reference_attention(q, k, v).sum()

    ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, rg in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg), atol=1e-4, rtol=1e-4)


def test_ring_attention_bf16():
    mesh = make_mesh({"sp": 4})
    B, H, L, D = 1, 2, 32, 8
    q, k, v = (rand((B, H, L, D), i, jnp.bfloat16) for i in range(3))
    out = ring_attention_sharded(mesh, q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref, dtype=np.float32),
        atol=3e-2, rtol=3e-2,
    )


def test_ring_attention_inside_jit_compiles_once():
    mesh = make_mesh({"sp": 2})
    B, H, L, D = 1, 1, 16, 8
    q, k, v = (rand((B, H, L, D), i) for i in range(3))

    @jax.jit
    def fn(q, k, v):
        return ring_attention_sharded(mesh, q, k, v)

    out = fn(q, k, v)
    assert out.shape == (B, H, L, D)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_gqa_compact_kv(causal):
    # Grouped-query: the ring rotates the compact [B, KVH, L/sp, D] K/V
    # blocks (KVH/H of the ppermute bytes) and must still equal the
    # broadcast reference.
    mesh = make_mesh({"sp": 4})
    B, H, KVH, L, D = 1, 4, 2, 64, 16
    q = rand((B, H, L, D), 0)
    k = rand((B, KVH, L, D), 1)
    v = rand((B, KVH, L, D), 2)
    out = ring_attention_sharded(mesh, q, k, v, causal=causal)
    rep = H // KVH
    ref = reference_attention(
        q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1), causal=causal
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window", [5, 16, 33, 64, 200])
def test_ring_attention_window_matches_reference(window):
    # Sliding window in global positions across the ring: sp=4 over L=128
    # puts L_local=32, so these widths cover sub-block, exactly-one-block,
    # boundary-straddling, multi-block, and wider-than-sequence windows —
    # the skip predicate, the own-block mask, and the straddle mask all bite.
    mesh = make_mesh({"sp": 4})
    B, H, L, D = 1, 2, 128, 16
    q, k, v = (rand((B, H, L, D), i + 40) for i in range(3))
    out = ring_attention_sharded(mesh, q, k, v, causal=True, window=window)
    ref = reference_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_attention_window_gqa_compact_kv():
    mesh = make_mesh({"sp": 4})
    B, H, KVH, L, D = 1, 4, 2, 128, 16
    q = rand((B, H, L, D), 50)
    k = rand((B, KVH, L, D), 51)
    v = rand((B, KVH, L, D), 52)
    out = ring_attention_sharded(mesh, q, k, v, causal=True, window=40)
    ref = reference_attention(q, k, v, causal=True, window=40)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window", [16, 48, 100])
def test_ring_attention_flash_hops_window_matches_reference(window):
    # The flash-hop ring with a window: own block via the kernel's window
    # mask, full hops via the plain kernel, straddling hops via the
    # jax-level masked block — all merged on lse (interpreter mode here;
    # scripts/validate-shardmap-pallas.py proves the Mosaic lowering).
    import functools

    mesh = make_mesh({"sp": 4})
    B, H, L, D = 1, 2, 128, 32
    q, k, v = (rand((B, H, L, D), i + 60) for i in range(3))
    spec = jax.sharding.PartitionSpec(None, None, "sp", None)
    fn = jax.shard_map(
        functools.partial(
            ring_attention, axis_name="sp", causal=True, use_flash=True,
            window=window,
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    out = fn(q, k, v)
    ref = reference_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4)


def test_ring_attention_window_grads():
    # Gradients through the windowed ring — including the boundary-straddle
    # block (jax-level math inside lax.cond) and the window-skip predicate.
    mesh = make_mesh({"sp": 4})
    B, H, L, D = 1, 2, 64, 16
    q, k, v = (rand((B, H, L, D), i + 70) for i in range(3))
    window = 24  # straddles: L_local=16, so hop delta=16 is partial

    def loss(q, k, v):
        return (
            ring_attention_sharded(
                mesh, q, k, v, causal=True, window=window
            ) ** 2
        ).sum()

    def ref_loss(q, k, v):
        return (reference_attention(q, k, v, causal=True, window=window) ** 2).sum()

    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=1e-3, rtol=1e-3, err_msg=name
        )


def test_ring_window_requires_causal():
    mesh = make_mesh({"sp": 2})
    q, k, v = (rand((1, 2, 32, 16), i) for i in range(3))
    with pytest.raises(ValueError, match="window requires causal"):
        ring_attention_sharded(mesh, q, k, v, causal=False, window=8)


def test_ring_window_must_be_positive():
    # window=0 would mask every row of the own block: the einsum path used
    # to emit silent NaNs where the flash kernel raised — both now raise.
    mesh = make_mesh({"sp": 2})
    q, k, v = (rand((1, 2, 32, 16), i) for i in range(3))
    for use_flash in (False, True):
        with pytest.raises(ValueError, match="window must be >= 1"):
            ring_attention_sharded(
                mesh, q, k, v, causal=True, window=0, use_flash=use_flash
            )


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_flash_hops_match_reference(causal):
    # The Pallas-kernel-per-hop ring (TPU default) vs the dense reference —
    # exercised here in interpreter mode inside shard_map. Merging hops on
    # their log-sum-exp must be exact.
    import functools

    from bee_code_interpreter_tpu.parallel.ring_attention import ring_attention

    mesh = make_mesh({"sp": 4})
    B, H, L, D = 1, 2, 128, 32
    q, k, v = (rand((B, H, L, D), i + 20) for i in range(3))
    spec = jax.sharding.PartitionSpec(None, None, "sp", None)
    # check_vma=False: interpreter-mode pallas under vma checking hits a
    # jax-internal limitation (its own dynamic_slice loses the vma set); the
    # Mosaic path on real TPU does not use this interpreter.
    fn = jax.shard_map(
        functools.partial(
            ring_attention, axis_name="sp", causal=causal, use_flash=True
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    out = fn(q, k, v)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4)


def test_ring_attention_flash_hops_grads():
    # Training through the flash ring: gradients flow through the hop
    # merging (real lse cotangents) and the kernel VJPs.
    import functools

    from bee_code_interpreter_tpu.parallel.ring_attention import ring_attention

    mesh = make_mesh({"sp": 2})
    B, H, KVH, L, D = 1, 4, 2, 64, 16
    q = rand((B, H, L, D), 30)
    k = rand((B, KVH, L, D), 31)
    v = rand((B, KVH, L, D), 32)
    spec = jax.sharding.PartitionSpec(None, None, "sp", None)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name="sp", use_flash=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )

    def loss(q, k, v):
        return (fn(q, k, v) ** 2).sum()

    def ref_loss(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=1e-3, rtol=1e-3, err_msg=name
        )
