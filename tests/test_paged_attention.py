"""Pallas paged-attention decode kernel (ops/paged_attention.py) — pinned
against the grouped-einsum oracle (the exact math the gather path
computes), and wired end-to-end through the batcher behind
``TransformerConfig(paged_attention_kernel=True)``.

CPU runs the kernel in Pallas interpreter mode; the Mosaic lowering and
the in-place-read HBM win are hardware-battery territory
(scripts/bench-decode.py)."""

import dataclasses
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bee_code_interpreter_tpu.models.serving import ContinuousBatcher
from bee_code_interpreter_tpu.models.transformer import (
    TransformerConfig,
    init_params,
)
from bee_code_interpreter_tpu.ops.paged_attention import (
    paged_decode_attention,
)


def oracle(q, k_pages, v_pages, bt, lengths):
    """The gather path's math: contiguous view + grouped einsums + causal
    length mask, f32 statistics."""
    B, nh, dh = q.shape
    kvh, ps = k_pages.shape[1], k_pages.shape[2]
    P = bt.shape[1]
    rep = nh // kvh

    def view(pages):
        g = pages[bt]  # [B, P, kvh, ps, dh]
        return g.transpose(0, 2, 1, 3, 4).reshape(B, kvh, P * ps, dh)

    kf = view(k_pages).astype(jnp.float32)
    vf = view(v_pages).astype(jnp.float32)
    qg = q.reshape(B, kvh, rep, dh).astype(jnp.float32)
    s = jnp.einsum("bgrd,bgsd->bgrs", qg, kf) / math.sqrt(dh)
    visible = jnp.arange(P * ps)[None, :] < lengths[:, None]  # [B, S]
    s = jnp.where(visible[:, None, None, :], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bgrs,bgsd->bgrd", w, vf)
    return out.reshape(B, nh, dh)


def make_case(key, B, nh, kvh, ps, P, n_pages, dtype=jnp.float32):
    ks = jax.random.split(key, 5)
    q = jax.random.normal(ks[0], (B, nh, 128), dtype)
    k_pages = jax.random.normal(ks[1], (n_pages, kvh, ps, 128), dtype)
    v_pages = jax.random.normal(ks[2], (n_pages, kvh, ps, 128), dtype)
    # permuted, non-trivial page placement per row
    bt = jax.random.permutation(ks[3], n_pages)[: B * P].reshape(B, P)
    lengths = jax.random.randint(ks[4], (B,), 1, P * ps + 1)
    return q, k_pages, v_pages, bt.astype(jnp.int32), lengths


@pytest.mark.parametrize("nh,kvh", [(8, 2), (4, 4), (16, 2), (24, 2)])
def test_matches_oracle_gqa_shapes(nh, kvh):
    q, kp, vp, bt, lengths = make_case(
        jax.random.PRNGKey(0), B=3, nh=nh, kvh=kvh, ps=16, P=4, n_pages=20
    )
    got = paged_decode_attention(q, kp, vp, bt, lengths)
    want = oracle(q, kp, vp, bt, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_bf16_pool_close_to_f32_oracle():
    q, kp, vp, bt, lengths = make_case(
        jax.random.PRNGKey(1), B=2, nh=8, kvh=2, ps=8, P=3, n_pages=12,
        dtype=jnp.bfloat16,
    )
    got = paged_decode_attention(q, kp, vp, bt, lengths)
    want = oracle(q, kp, vp, bt, lengths)
    np.testing.assert_allclose(
        np.asarray(got, dtype=np.float32), np.asarray(want),
        atol=3e-2, rtol=3e-2,
    )


def test_masked_slots_cannot_influence_output():
    q, kp, vp, bt, lengths = make_case(
        jax.random.PRNGKey(2), B=2, nh=4, kvh=2, ps=8, P=4, n_pages=16
    )
    lengths = jnp.asarray([5, 19], dtype=jnp.int32)
    base = paged_decode_attention(q, kp, vp, bt, lengths)
    # poison every slot at/after each row's length (per its own pages)
    kp2, vp2 = np.asarray(kp).copy(), np.asarray(vp).copy()
    bt_np = np.asarray(bt)
    for b in range(2):
        for logical in range(int(lengths[b]), 4 * 8):
            page, slot = bt_np[b, logical // 8], logical % 8
            kp2[page, :, slot] = 1e4
            vp2[page, :, slot] = -1e4
    poisoned = paged_decode_attention(
        q, jnp.asarray(kp2), jnp.asarray(vp2), bt, lengths
    )
    np.testing.assert_allclose(np.asarray(base), np.asarray(poisoned),
                               atol=1e-5, rtol=1e-5)


def test_batcher_kernel_flag_matches_einsum_path():
    """End to end: the batcher with paged_attention_kernel=True produces
    the exact token streams of the einsum path (f32 config — the kernel
    keeps f32 statistics where the einsum path rounds weights to the
    compute dtype, so bf16 near-ties could differ; determinism at bf16 is
    pinned separately below)."""
    cfg = dataclasses.replace(
        TransformerConfig.tiny(), n_kv_heads=2, dtype=jnp.float32,
        paged_attention_kernel=True,
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompts = [[5, 3, 7, 2, 9, 4, 1, 8], [3, 1, 4, 1, 5]]

    def run(flag):
        c = dataclasses.replace(cfg, paged_attention_kernel=flag)
        b = ContinuousBatcher(params, c, max_batch=2,
                              n_pages=24, page_size=4, max_pages_per_seq=8)
        reqs = [b.submit(p, 6) for p in prompts]
        b.run_to_completion()
        return [b.result(r) for r in reqs]

    assert run(True) == run(False)


def test_bf16_batcher_kernel_is_deterministic():
    cfg = dataclasses.replace(
        TransformerConfig.tiny(), n_kv_heads=2, paged_attention_kernel=True,
    )
    params = init_params(cfg, jax.random.PRNGKey(0))

    def run():
        b = ContinuousBatcher(params, cfg, max_batch=2, n_pages=24,
                              page_size=4, max_pages_per_seq=8)
        r = b.submit([5, 3, 7, 2, 9, 4, 1, 8], 6)
        b.run_to_completion()
        return b.result(r)

    assert run() == run()
    assert len(run()) == 6


def test_int8_pool_and_windows_keep_the_einsum_path():
    """The kernel gate: int8 pools and sliding windows fall back (the
    flag is safe to leave on globally)."""
    for extra in ({"kv_cache_dtype": "int8"}, {"sliding_window": 6}):
        cfg = dataclasses.replace(
            TransformerConfig.tiny(), n_kv_heads=2,
            paged_attention_kernel=True, **extra,
        )
        params = init_params(cfg, jax.random.PRNGKey(0))
        b = ContinuousBatcher(params, cfg, max_batch=1, n_pages=16,
                              page_size=4, max_pages_per_seq=8)
        r = b.submit([5, 3, 7, 2], 4)
        b.run_to_completion()
        base_cfg = dataclasses.replace(cfg, paged_attention_kernel=False)
        b2 = ContinuousBatcher(params, base_cfg, max_batch=1, n_pages=16,
                               page_size=4, max_pages_per_seq=8)
        r2 = b2.submit([5, 3, 7, 2], 4)
        b2.run_to_completion()
        assert b.result(r) == b2.result(r2)


def test_validation():
    with pytest.raises(ValueError, match="multiple"):
        paged_decode_attention(
            jnp.zeros((1, 3, 128)), jnp.zeros((4, 2, 8, 128)),
            jnp.zeros((4, 2, 8, 128)), jnp.zeros((1, 2), jnp.int32),
            jnp.ones((1,), jnp.int32),
        )


def test_sentinel_block_table_entries_are_harmless():
    """-1 is a common block-table convention for 'no page'. Entries at or
    beyond a row's visible length have their compute predicated off, but
    the DMA still issues — the kernel clamps the index so a sentinel
    reads in-bounds (identical output, no OOB in the Mosaic path)."""
    q, kp, vp, bt, lengths = make_case(
        jax.random.PRNGKey(7), B=2, nh=4, kvh=2, ps=8, P=4, n_pages=16
    )
    lengths = jnp.asarray([5, 9], dtype=jnp.int32)  # rows use 1 / 2 pages
    base = paged_decode_attention(q, kp, vp, bt, lengths)
    bt_sent = np.asarray(bt).copy()
    bt_sent[0, 1:] = -1  # pages past the visible length
    bt_sent[1, 2:] = -1
    out = paged_decode_attention(
        q, kp, vp, jnp.asarray(bt_sent, dtype=jnp.int32), lengths
    )
    np.testing.assert_allclose(np.asarray(base), np.asarray(out),
                               atol=1e-6, rtol=1e-6)
