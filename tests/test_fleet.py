"""Fleet observability (ISSUE 3): the sandbox lifecycle journal, the
per-execution usage accounting, and the profiling plumbing — at the unit
level and driven through the real executors against the fake cluster with
scripted chaos (tests/chaos.py)."""

import asyncio

import pytest

from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.observability import (
    FleetJournal,
    ProfilerUnavailable,
    ServingProfiler,
    collect_transfer,
    inject_profile_env,
    merge_worker_usage,
    record_transfer,
)
from bee_code_interpreter_tpu.services.kubernetes_code_executor import (
    KubernetesCodeExecutor,
)
from bee_code_interpreter_tpu.utils.metrics import Registry
from tests.chaos import ChaosKubectl, Fail, FaultPlan
from tests.fakes import FakeExecutorPods, FakeKubectl


# ------------------------------------------------------------ journal units


def test_journal_records_full_lifecycle_and_snapshot():
    journal = FleetJournal()
    journal.record("pod-a", "spawning", workers=2)
    journal.record("pod-a", "ready")
    journal.record("pod-a", "assigned", reason="warm_pop")
    journal.record("pod-a", "executing")
    snap = journal.snapshot()
    assert snap["live"] == 1
    assert snap["by_state"] == {"executing": 1}
    assert snap["utilization"] == 1.0
    assert snap["executions_total"] == 1
    (pod,) = snap["pods"]
    assert pod["workers"] == 2
    assert pod["executions"] == 1
    assert pod["spawn_s"] is not None

    journal.record("pod-a", "released", reason="single_use")
    snap = journal.snapshot()
    assert snap["live"] == 0
    assert snap["utilization"] == 0.0  # empty pool reads 0, never NaN
    # terminal events carry the pod's served-execution count and age
    released = journal.events(limit=1)[0]
    assert released["state"] == "released"
    assert released["executions"] == 1
    assert released["age_s"] >= 0

    states = [e["state"] for e in reversed(journal.events())]
    assert states == ["spawning", "ready", "assigned", "executing", "released"]


def test_journal_event_ring_is_bounded_and_limit_filters():
    journal = FleetJournal(max_events=8)
    for i in range(20):
        journal.record(f"pod-{i}", "spawning")
    assert len(journal) == 8
    events = journal.events(limit=3)
    assert len(events) == 3
    assert events[0]["pod"] == "pod-19"  # newest first
    # lifetime counters survive ring eviction
    assert journal.counts["spawning"] == 20


def test_journal_rejects_unknown_states():
    with pytest.raises(ValueError, match="unknown fleet state"):
        FleetJournal().record("pod-a", "meditating")


def test_journal_feeds_pool_metrics():
    metrics = Registry()
    journal = FleetJournal(metrics=metrics)
    journal.record("pod-a", "spawning")
    journal.record("pod-a", "ready")
    journal.record("pod-a", "assigned")
    journal.record("pod-b", "spawning")
    journal.record("pod-b", "failed", reason="spawn_failed", detail="apiserver down")
    journal.record("pod-c", "spawning")
    journal.record("pod-c", "ready")
    journal.record("pod-c", "reaped", reason="unhealthy")
    text = metrics.expose()
    assert "bci_pool_spawn_seconds_count 2" in text
    # the label stays CATEGORICAL (free text would mint unbounded series);
    # the free text lives on the journal event as `detail`
    assert 'bci_pod_reaped_total{reason="spawn_failed"} 1' in text
    assert 'bci_pod_reaped_total{reason="unhealthy"} 1' in text
    failed = next(e for e in journal.events() if e["state"] == "failed")
    assert failed["detail"] == "apiserver down"
    # one live pod (assigned), so utilization is 1.0
    assert "bci_pool_utilization 1" in text


# ------------------------------------------------------- accounting units


def test_merge_worker_usage_sums_cpu_maxes_rss():
    merged = merge_worker_usage(
        [
            {"cpu_user_s": 1.0, "cpu_system_s": 0.5, "max_rss_bytes": 100,
             "wall_s": 2.0, "workspace_bytes_written": 10,
             "files_changed": 1, "deps_installed": ["numpy"]},
            None,  # worker with an old server: no block
            {"cpu_user_s": 2.0, "cpu_system_s": 0.5, "max_rss_bytes": 300,
             "wall_s": 1.5, "workspace_bytes_written": 5,
             "files_changed": 2, "deps_installed": ["numpy", "pandas"]},
        ]
    )
    assert merged["cpu_user_s"] == 3.0
    assert merged["cpu_system_s"] == 1.0
    assert merged["max_rss_bytes"] == 300
    assert merged["wall_s"] == 2.0
    assert merged["workspace_bytes_written"] == 15
    assert merged["files_changed"] == 3
    assert merged["deps_installed"] == ["numpy", "pandas"]


def test_transfer_accounting_is_task_scoped():
    async def go():
        async def one(n):
            with collect_transfer() as acct:
                await asyncio.sleep(0.01)
                record_transfer("upload", n)
                await asyncio.sleep(0.01)
                record_transfer("download", n * 2)
            return acct

        a, b = await asyncio.gather(one(100), one(7))
        assert (a.uploaded_bytes, a.downloaded_bytes) == (100, 200)
        assert (b.uploaded_bytes, b.downloaded_bytes) == (7, 14)
        assert a.uploaded_files == a.downloaded_files == 1

    asyncio.run(go())
    # outside any scope, reporting is a no-op
    record_transfer("upload", 123)


# ------------------------------------ executors against the fake cluster


def make_executor(pods, storage, kubectl, metrics=None, **overrides):
    config = Config(
        executor_backend="kubernetes",
        executor_port=pods.port,
        executor_pod_queue_target_length=0,
        pod_ready_timeout_s=5,
        executor_retry_attempts=1,
        executor_retry_wait_min_s=0.01,
        executor_retry_wait_max_s=0.05,
        **overrides,
    )
    return KubernetesCodeExecutor(
        kubectl=kubectl,
        storage=storage,
        config=config,
        metrics=metrics,
        ip_poll_interval_s=0.02,
    )


async def test_usage_flows_through_fake_driver(tmp_path, storage):
    pods = FakeExecutorPods(tmp_path / "pods")
    executor = make_executor(pods, storage, FakeKubectl(pods))
    try:
        object_id = await storage.write(b"x" * 1000)
        result = await executor.execute(
            "print(open('in.txt').read()[:1])\n"
            "open('out.txt', 'w').write('y' * 500)",
            files={"/workspace/in.txt": object_id},
        )
        assert result.exit_code == 0
        usage = result.usage
        assert usage["cpu_user_s"] > 0
        assert usage["wall_s"] > 0
        assert usage["max_rss_bytes"] > 0
        assert usage["workspace_bytes_written"] >= 500
        assert usage["files_changed"] == 1
        # the driver's byte accounting saw both directions
        assert usage["uploaded_bytes"] == 1000
        assert usage["uploaded_files"] == 1
        assert usage["downloaded_bytes"] == 500
        assert usage["downloaded_files"] == 1
    finally:
        await pods.close()


async def test_spawn_failures_land_in_journal_under_chaos(tmp_path, storage):
    faults = FaultPlan()
    pods = FakeExecutorPods(tmp_path / "pods", faults=faults)
    faults.script("pod_create", Fail("apiserver down"))
    executor = make_executor(
        pods, storage, ChaosKubectl(pods, faults)
    )
    try:
        with pytest.raises(RuntimeError):
            await executor.spawn_pod_group()
        events = executor.journal.events()
        failed = [e for e in events if e["state"] == "failed"]
        assert len(failed) == 1
        assert failed[0]["reason"] == "spawn_failed"
        assert "apiserver down" in failed[0]["detail"]
        # then a healthy spawn journals spawning -> ready with latency
        group = await executor.spawn_pod_group()
        ready = [e for e in executor.journal.events() if e["state"] == "ready"]
        assert ready and ready[0]["pod"] == group.name
        assert ready[0]["spawn_s"] >= 0
    finally:
        await pods.close()


async def test_unhealthy_warm_group_is_journaled_as_reaped(tmp_path, storage):
    pods = FakeExecutorPods(tmp_path / "pods")
    metrics = Registry()
    executor = make_executor(
        pods, storage, FakeKubectl(pods), metrics=metrics
    )
    try:
        group = await executor.spawn_pod_group()
        executor._queue.append(group)
        # preempt the pod out from under the warm queue
        for ip in group.pod_ips:
            await pods.stop_pod(ip)
        result = await executor.execute("print('ok')")
        assert result.stdout == "ok\n"
        events = executor.journal.events()
        reaped = [e for e in events if e["state"] == "reaped"]
        assert [e["pod"] for e in reaped] == [group.name]
        assert reaped[0]["reason"] == "unhealthy"
        assert 'bci_pod_reaped_total{reason="unhealthy"} 1' in metrics.expose()
        # the replacement pod's full story is in the journal too
        served = [
            e
            for e in events
            if e["state"] == "released" and e["executions"] == 1
        ]
        assert served
    finally:
        await pods.close()


async def test_native_spawn_failure_closes_journal_record(tmp_path, storage):
    """Regression: a native spawn that dies anywhere (here: the 'binary'
    exits immediately, so readiness fails) must record 'failed' — never
    leave a phantom 'spawning' pod live in the journal forever."""
    from bee_code_interpreter_tpu.services.native_process_code_executor import (
        NativeProcessCodeExecutor,
    )

    config = Config(
        executor_backend="local",
        local_workspace_root=str(tmp_path / "ws"),
        pod_ready_timeout_s=0.5,
        executor_retry_attempts=1,
        disable_dep_install=True,
    )
    executor = NativeProcessCodeExecutor(
        storage=storage, config=config, binary="/bin/true"
    )
    try:
        with pytest.raises(RuntimeError, match="exited at startup"):
            await executor.spawn_sandbox()
        events = executor.journal.events()
        assert [e["state"] for e in reversed(events)] == ["spawning", "failed"]
        assert events[0]["reason"] == "spawn_failed"
        assert "exited at startup" in events[0]["detail"]
        assert executor.journal.snapshot()["live"] == 0  # no phantom pod
    finally:
        executor.shutdown()


def test_application_context_wires_one_shared_journal(tmp_path):
    """Regression: an EMPTY FleetJournal is falsy (len()==0); `journal or
    FleetJournal()` in an executor would silently record into a twin and
    leave /v1/fleet permanently empty. The context's journal must be the
    very object the executor records into."""
    from bee_code_interpreter_tpu.application_context import ApplicationContext

    ctx = ApplicationContext(
        Config(
            executor_backend="kubernetes",
            file_storage_path=str(tmp_path / "objects"),
            local_workspace_root=str(tmp_path / "ws"),
            disable_dep_install=True,
        )
    )
    assert ctx.code_executor.primary.journal is ctx.fleet


# ------------------------------------------------------------- profiling


def test_inject_profile_env_defaults_and_respects_caller():
    env = inject_profile_env({"FOO": "1"})
    assert env["BCI_PROFILE_DIR"] == "/workspace/.bci-profile"
    assert env["FOO"] == "1"
    env = inject_profile_env({"BCI_PROFILE_DIR": "/workspace/custom"})
    assert env["BCI_PROFILE_DIR"] == "/workspace/custom"


def test_serving_profiler_captures_steps(tmp_path):
    class Stepper:
        def __init__(self):
            self.steps = 0

        def step(self):
            import jax.numpy as jnp

            self.steps += 1
            jnp.zeros(4).block_until_ready()

    stepper = Stepper()
    profiler = ServingProfiler(stepper, trace_root=tmp_path)
    result = profiler.capture(3)
    assert stepper.steps == 3
    assert result["steps"] == 3
    assert result["duration_ms"] >= 0
    # jax's profiler wrote trace artifacts under the returned directory
    assert result["trace_dir"].startswith(str(tmp_path))
    assert result["files"], "no profiler artifacts captured"
    assert not profiler.capturing

    with pytest.raises(ValueError):
        profiler.capture(0)


def test_serving_profiler_rejects_overlapping_captures(tmp_path):
    """jax.profiler is process-global; a second capture arriving (on another
    thread — the HTTP handler runs captures via asyncio.to_thread) while one
    is in flight must be refused, not corrupt the first."""
    import threading
    import time

    started = threading.Event()

    class SlowStepper:
        def step(self):
            started.set()
            time.sleep(0.3)

    profiler = ServingProfiler(SlowStepper(), trace_root=tmp_path)
    background_errors = []

    def bg():
        try:
            profiler.capture(1)
        except Exception as e:  # pragma: no cover - would fail the assert
            background_errors.append(e)

    t = threading.Thread(target=bg)
    t.start()
    assert started.wait(5.0)
    with pytest.raises(ProfilerUnavailable, match="already in progress"):
        profiler.capture(1)
    t.join()
    assert not background_errors
    assert not profiler.capturing
