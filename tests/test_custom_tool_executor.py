"""Custom-tool parse/execute behavior, pinned to the reference e2e contract
(reference test/e2e/test_http.py:100-302), exercised as unit tests against the
in-process executor backend."""

import json

import pytest

from bee_code_interpreter_tpu.services.custom_tool_executor import (
    CustomToolExecuteError,
    CustomToolExecutor,
    CustomToolParseError,
)


@pytest.fixture
def tool_executor(local_executor):
    return CustomToolExecutor(code_executor=local_executor)


GNARLY_TOOL = '''
import typing
import typing as banana
from typing import Optional
from typing import Union as Onion

def my_tool(a: int, b: typing.Tuple[Optional[str], str] = ("hello", "world"), *, c: Onion[list[str], dict[str, banana.Optional[float]]]) -> int:
    """
    This tool is really really cool.
    Very toolish experience:
    - Toolable.
    - Toolastic.
    - Toolicious.
    :param a: something cool
    (very cool indeed)
    :param b: something nice
    :return: something great
    :param c: something awful
    """
    return 1 + 1
'''


def test_parse_gnarly_typing(tool_executor):
    tool = tool_executor.parse(GNARLY_TOOL)
    assert tool.name == "my_tool"
    assert tool.description == (
        "This tool is really really cool.\nVery toolish experience:\n- Toolable.\n"
        "- Toolastic.\n- Toolicious.\n\nReturns: int -- something great"
    )
    assert tool.input_schema == {
        "$schema": "http://json-schema.org/draft-07/schema#",
        "type": "object",
        "title": "my_tool",
        "properties": {
            "a": {"type": "integer", "description": "something cool\n(very cool indeed)"},
            "b": {
                "type": "array",
                "minItems": 2,
                "items": [
                    {"anyOf": [{"type": "string"}, {"type": "null"}]},
                    {"type": "string"},
                ],
                "additionalItems": False,
                "description": "something nice",
            },
            "c": {
                "anyOf": [
                    {"type": "array", "items": {"type": "string"}},
                    {
                        "type": "object",
                        "additionalProperties": {
                            "anyOf": [{"type": "number"}, {"type": "null"}]
                        },
                    },
                ],
                "description": "something awful",
            },
        },
        "required": ["a", "c"],
        "additionalProperties": False,
    }


def test_parse_no_return_annotation(tool_executor):
    tool = tool_executor.parse(
        '''
import typing
import requests

def current_weather(lat: float, lon: float):
    """
    Get the current weather at a location.

    :param lat: A latitude.
    :param lon: A longitude.
    :return: A dictionary with the current weather.
    """
    url = "https://fake-api.com/weather?lat=" + str(lat) + "&lon=" + str(lon)
    response = requests.get(url)
    response.raise_for_status()
    return response.json()'''
    )
    assert tool.name == "current_weather"
    assert tool.description == (
        "Get the current weather at a location.\n\nReturns: A dictionary with the current weather."
    )
    assert tool.input_schema["properties"] == {
        "lat": {"type": "number", "description": "A latitude."},
        "lon": {"type": "number", "description": "A longitude."},
    }
    assert tool.input_schema["required"] == ["lat", "lon"]


def test_parse_error_messages(tool_executor):
    with pytest.raises(CustomToolParseError) as e:
        tool_executor.parse("def my_tool(a, /, b, *args, **kwargs) -> int:\n  return 1 + 1")
    assert set(e.value.error_messages) == {
        "The tool function must not have positional-only arguments",
        "The tool function must not have *args",
        "The tool function must not have **kwargs",
        "The tool function arguments must have type annotations",
    }


def test_parse_rejects_non_function_statements(tool_executor):
    with pytest.raises(CustomToolParseError):
        tool_executor.parse("x = 1\ndef f(a: int) -> int:\n  return a")


def test_parse_rejects_unsafe_annotation(tool_executor):
    with pytest.raises(CustomToolParseError):
        tool_executor.parse("def f(a: __import__('os').system) -> int:\n  return 1")


def test_parse_syntax_error(tool_executor):
    with pytest.raises(CustomToolParseError):
        tool_executor.parse("def broken(:")


async def test_execute_simple(tool_executor):
    out = await tool_executor.execute(
        "def adding_tool(a: int, b: int) -> int:\n  return a + b",
        '{"a": 1, "b": 2}',
    )
    assert out == 3


async def test_execute_datetime_coercion(tool_executor):
    out = await tool_executor.execute(
        """
import datetime

def date_tool(a: datetime.datetime) -> str:
    return f"The year is {a.year}"
""",
        '{"a": "2000-01-01T00:00:00"}',
    )
    assert out == "The year is 2000"


async def test_execute_runtime_error_surfaces_stderr(tool_executor):
    with pytest.raises(CustomToolExecuteError) as e:
        await tool_executor.execute(
            "def division_tool(a: int, b: int) -> int:\n  return a / b",
            '{"a": 0, "b": 0}',
        )
    assert "division by zero" in e.value.stderr


async def test_execute_with_env(tool_executor):
    out = await tool_executor.execute(
        "import os\ndef greet() -> str:\n  return 'Hello ' + os.environ['MY_NAME']",
        "{}",
        env={"MY_NAME": "John Doe"},
    )
    assert out == "Hello John Doe"


async def test_tool_body_stdout_suppressed(tool_executor):
    out = await tool_executor.execute(
        "def noisy(a: int) -> int:\n  print('SIDE CHANNEL')\n  return a",
        '{"a": 7}',
    )
    assert out == 7


def test_json_roundtrip_of_output_encoding(tool_executor):
    # exact JSON encodings pinned by reference test_grpc.py:254,271
    assert json.dumps(3) == "3"
    assert json.dumps("The year is 2000") == '"The year is 2000"'


async def test_async_tool_supported(tool_executor):
    out = await tool_executor.execute(
        "import asyncio\nasync def slow_add(a: int, b: int) -> int:\n"
        "  await asyncio.sleep(0)\n  return a + b",
        '{"a": 2, "b": 3}',
    )
    assert out == 5


def test_parse_indented_tool_source(tool_executor):
    # A uniformly indented tool (an agent lifting a function out of a larger
    # file) parses on the reference via textwrap.dedent
    # (its custom_tool_executor.py:59) and must parse here too.
    tool = tool_executor.parse(
        "    def shifted(a: int) -> int:\n"
        '        """Doubles.\n\n        :param a: value\n        :return: doubled\n        """\n'
        "        return a * 2\n"
    )
    assert tool.name == "shifted"
    assert tool.input_schema["properties"]["a"]["type"] == "integer"


async def test_execute_indented_tool_source(tool_executor):
    out = await tool_executor.execute(
        "    import math\n"
        "    def hypot_tool(a: float, b: float) -> float:\n"
        "        return math.hypot(a, b)\n",
        '{"a": 3, "b": 4}',
    )
    assert out == 5.0
