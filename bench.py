#!/usr/bin/env python
"""Headline benchmark: dense-matmul GFLOPS/chip driven through /v1/execute.

Measures the BASELINE.json north-star metric — the benchmark-numpy dense
matmul payload submitted through the service's real execution path (the
sandbox executor with the TPU runtime shim), reported as GFLOPS on the
attached chip. ``vs_baseline`` compares against the same payload on the host
CPU path (the reference's only execution substrate; BASELINE.md "the
reference's CPU path is the comparison baseline").

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "GFLOPS", "vs_baseline": N}

Extra detail lines go to stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent
SHIM_DIR = REPO / "bee_code_interpreter_tpu" / "runtime" / "shim"

N = 32768
ITERS = 16

# The measured payload: a bf16 matmul chain under jit, the shape of work the
# MXU exists for. Chained with a data dependency (no loop hoisting), one
# device->host readback at the end. Written the way a sandbox user writes JAX.
# n=32768 keeps each matmul MXU-bound long enough to amortize loop/dispatch
# overhead (measured 186 TFLOPS = 94% of v5e bf16 peak vs 147 at n=8192); the
# one-time 1/128 pre-scale keeps the chain's magnitudes roughly stable without
# paying a per-iteration epilogue.
TPU_PAYLOAD = f"""
import time
import jax, jax.numpy as jnp
from jax import lax

n, iters = {N}, {ITERS}
if jax.devices()[0].platform == "cpu":
    n, iters = 1024, 4  # no accelerator: validate mechanics only
a = jax.random.normal(jax.random.PRNGKey(0), (n, n), dtype=jnp.bfloat16)

@jax.jit
def chain(a):
    a = a * jnp.bfloat16(1 / 128)
    def body(i, x):
        return a @ x
    return lax.fori_loop(0, iters, body, a).sum()

float(chain(a))  # compile + warm
best = float("inf")
for _ in range(3):
    t0 = time.time()
    float(chain(a))
    best = min(best, time.time() - t0)
print(f"RESULT_GFLOPS {{2 * n**3 * iters / best / 1e9:.1f}}")
"""

# Host-CPU baseline: the same kernel as the TPU chain — one-time 1/128
# pre-scale, then a pure data-dependent matmul chain with a single readback —
# through plain numpy (f32; numpy has no bf16), sized down (self-timed wall
# clock, as the reference's own benchmark payload does).
CPU_PAYLOAD = """
import os
os.environ["BCI_XLA_REROUTE"] = "0"
import time
import numpy as np

n, iters = 4096, 4
a = np.random.rand(n, n).astype(np.float32) * np.float32(1 / 128)
x = a
t0 = time.time()
for _ in range(iters):
    x = a @ x
s = float(x.sum())
dt = time.time() - t0
print(f"RESULT_GFLOPS {2 * n**3 * iters / dt / 1e9:.1f}")
"""


async def run_payload(source: str, env: dict[str, str]) -> float:
    from bee_code_interpreter_tpu.services.local_code_executor import (
        LocalCodeExecutor,
    )
    from bee_code_interpreter_tpu.services.storage import Storage

    tmp = tempfile.mkdtemp(prefix="bench-")
    executor = LocalCodeExecutor(
        storage=Storage(Path(tmp) / "objects"),
        workspace_root=Path(tmp) / "ws",
        disable_dep_install=True,
        execution_timeout_s=300.0,
        shim_dir=SHIM_DIR,
    )
    result = await executor.execute(source, env=env)
    if result.exit_code != 0:
        print(result.stderr, file=sys.stderr)
        raise RuntimeError(f"payload failed (exit {result.exit_code})")
    for line in result.stdout.splitlines():
        if line.startswith("RESULT_GFLOPS"):
            return float(line.split()[1])
    raise RuntimeError(f"no result in stdout: {result.stdout!r}")


def main() -> None:
    # the TPU payload must see the real chip, not the test-forced CPU
    # TPU/XLA/accelerator env flows through the executor's passthrough list +
    # the process environment; PYTHONPATH must NOT be overridden here or the
    # shim prepend (and the image's own site hooks) would be lost.
    tpu_env = {
        k: v for k, v in os.environ.items()
        if k.startswith(("TPU", "JAX", "XLA", "PALLAS"))
    }
    cpu_gflops = asyncio.run(run_payload(CPU_PAYLOAD, {"JAX_PLATFORMS": "cpu"}))
    print(f"cpu baseline: {cpu_gflops:.1f} GFLOPS", file=sys.stderr)

    try:
        tpu_gflops = asyncio.run(run_payload(TPU_PAYLOAD, tpu_env))
        print(f"tpu: {tpu_gflops:.1f} GFLOPS", file=sys.stderr)
        result = {
            "metric": "dense matmul GFLOPS/chip via /v1/execute (bf16 32768^3 jit chain)",
            "value": round(tpu_gflops, 1),
            "unit": "GFLOPS",
            "vs_baseline": round(tpu_gflops / cpu_gflops, 2),
        }
    except Exception as e:  # no chip reachable: report the CPU path honestly
        print(f"tpu payload failed ({e}); reporting CPU-path result", file=sys.stderr)
        result = {
            "metric": "dense matmul GFLOPS via /v1/execute (CPU fallback - no TPU reachable)",
            "value": round(cpu_gflops, 1),
            "unit": "GFLOPS",
            "vs_baseline": 1.0,
        }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
